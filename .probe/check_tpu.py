"""Fast TPU availability probe for the chip-up playbook.

Tries jax.default_backend() in a daemon thread with a short timeout.
Exit 0 iff a real accelerator backend ("tpu"/"axon") came up within the
window; exit 1 on raise (UNAVAILABLE outage) or block (wedged lease —
the claim thread is left running and dies with the process; we never
signal it, per the lease-wedging gotcha in CLAUDE.md).

Usage: python .probe/check_tpu.py [timeout_seconds]
"""

import sys
import threading

TIMEOUT = float(sys.argv[1]) if len(sys.argv) > 1 else 120.0

box: dict = {}


def _init() -> None:
    try:
        import jax

        box["backend"] = jax.default_backend()
        # A claim that returns a CPU backend means the accelerator plugin
        # is absent, not that the chip is up.
        if box["backend"] in ("tpu", "axon"):
            import jax.numpy as jnp

            # One tiny dispatch proves the runtime executes, not just inits.
            box["ok"] = float(jnp.ones((4,)).sum())
    except Exception as e:  # noqa: BLE001 — any failure = chip down
        box["error"] = e


t = threading.Thread(target=_init, daemon=True)
t.start()
t.join(TIMEOUT)

if "ok" in box:
    print(f"UP backend={box['backend']}")
    sys.exit(0)
if "error" in box:
    print(f"DOWN error={type(box['error']).__name__}: {box['error']}"[:300])
elif "backend" in box:
    print(f"DOWN backend={box['backend']} (no accelerator)")
else:
    print(f"DOWN blocked>{TIMEOUT:.0f}s (claim loop still waiting)")
sys.exit(1)
