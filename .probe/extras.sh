#!/usr/bin/env bash
# Chip-up extras: the hardware measurements beyond the driver sweep that
# docs/performance.md cites. Each mirrors ONE JSON line into tracked
# artifacts/ and commits. Run only after a full bench sweep succeeded
# (monitor.sh calls this; safe to re-run by hand).
set -u
cd "$(dirname "$0")/.." || exit 1
LOG=.probe/monitor.log
log() { echo "[$(date -u +%FT%TZ)] extras: $*" >>"$LOG"; }

run_metric() {
    local name="$1" out="$2"; shift 2
    log "running $name → $out"
    if env "$@" python bench.py >"$out.tmp" 2>>.probe/extras_$name.log \
        && ! grep -q chip_unavailable "$out.tmp"; then
        mv "$out.tmp" "$out"
        for _ in 1 2 3 4 5; do
            git add "$out" 2>>"$LOG" && git commit -m "Hardware measurement: $name" -- "$out" >>"$LOG" 2>&1 && break
            sleep 15
        done
        log "$name done: $(head -c 200 "$out")"
    else
        log "$name FAILED (see .probe/extras_$name.log)"
        rm -f "$out.tmp"
    fi
}

run_metric mine_1m artifacts/mine_1m.json \
    KAKVEDA_BENCH_METRIC=mine KAKVEDA_BENCH_MINE_N=1000000
run_metric warn_realemb artifacts/warn_realemb.json \
    KAKVEDA_BENCH_METRIC=warn KAKVEDA_BENCH_REAL_EMB=1
run_metric decode_curve artifacts/decode_curve.json \
    KAKVEDA_BENCH_METRIC=decode
run_metric serve artifacts/serve_http.json \
    KAKVEDA_BENCH_METRIC=serve
log "extras pass complete"
