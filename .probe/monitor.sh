#!/usr/bin/env bash
# Chip-up monitor: probe the tunneled TPU forever; the moment it breathes,
# run the full bench sweep (teed to a log), mirror the JSON into tracked
# artifacts/, run the extras playbook, and commit. Designed to be started
# detached at round start via .probe/probe.sh.
#
# Invariants honored (CLAUDE.md):
#  - never SIGKILL/SIGTERM a process that may hold the TPU lease — every
#    attempt is left to finish on its own (bench.py has internal watchdogs
#    that bound a blocked init to ~33 min and exit cleanly);
#  - bench resume-from-partial is on by default, so a sweep that wedges
#    mid-way re-measures only what's missing on the next window.
set -u
cd "$(dirname "$0")/.." || exit 1
PROBE_DIR=.probe
LOG="$PROBE_DIR/monitor.log"
STATUS="$PROBE_DIR/status"
PROBE_TIMEOUT="${KAKVEDA_PROBE_TIMEOUT:-150}"
SLEEP_DOWN="${KAKVEDA_PROBE_SLEEP:-180}"

log() { echo "[$(date -u +%FT%TZ)] $*" >>"$LOG"; }
set_status() { echo "$*" >"$STATUS"; }

commit_paths() {
    # Commit specific paths with retry (the interactive session may hold
    # the index lock); never fail the loop on a commit race.
    local msg="$1"; shift
    for _ in 1 2 3 4 5; do
        if git add "$@" 2>>"$LOG" && git commit -m "$msg" -- "$@" >>"$LOG" 2>&1; then
            log "committed: $msg"
            return 0
        fi
        sleep 15
    done
    log "commit FAILED after retries: $msg"
    return 1
}

log "monitor started (pid $$, probe timeout ${PROBE_TIMEOUT}s, down-sleep ${SLEEP_DOWN}s)"
set_status "probing"

attempt=0
while true; do
    # Kill-switch: `touch .probe/stop` disarms the loop without signaling
    # any process (the driver's own end-of-round bench must never find the
    # chip held by a monitor attempt).
    if [ -f "$PROBE_DIR/stop" ]; then
        log "stop file present — monitor exiting"
        set_status "STOPPED by .probe/stop at $(date -u +%FT%TZ)"
        exit 0
    fi
    attempt=$((attempt + 1))
    if python "$PROBE_DIR/check_tpu.py" "$PROBE_TIMEOUT" >>"$LOG" 2>&1; then
        log "probe #$attempt: chip UP — starting full bench sweep"
        set_status "bench-running since $(date -u +%FT%TZ)"
        ts=$(date -u +%Y%m%dT%H%M%SZ)
        BLOG="$PROBE_DIR/bench_$ts.log"
        # No external timeout: bench.py bounds itself and must never be
        # killed while holding the chip.
        python bench.py >"$PROBE_DIR/bench_$ts.json" 2>"$BLOG"
        rc=$?
        out=$(cat "$PROBE_DIR/bench_$ts.json")
        log "bench rc=$rc out=${out:0:200}"
        if [ $rc -eq 0 ] && [ -n "$out" ] && ! grep -q chip_unavailable "$PROBE_DIR/bench_$ts.json"; then
            cp "$PROBE_DIR/bench_$ts.json" artifacts/bench_tpu_sweep.json
            commit_paths "Hardware bench sweep captured by chip-up monitor" artifacts/bench_tpu_sweep.json
            set_status "extras-running since $(date -u +%FT%TZ)"
            bash "$PROBE_DIR/extras.sh" >>"$LOG" 2>&1
            set_status "DONE sweep+extras at $(date -u +%FT%TZ) (monitor exited)"
            log "sweep + extras complete; monitor exiting (chip free for the driver)"
            exit 0
        else
            set_status "probing (last attempt: bench wedged/outage at $(date -u +%FT%TZ))"
            log "bench did not complete (outage mid-run?); partial preserved, will retry"
            # Preserve whatever the wedged sweep DID measure as a tracked
            # artifact (the outage JSON also carries it, but this survives
            # even if the process died before printing).
            if [ -s .bench_partial.json ]; then
                cp .bench_partial.json artifacts/bench_partial_last.json
                commit_paths "Partial hardware sweep captured before mid-run outage" \
                    artifacts/bench_partial_last.json
            fi
            sleep 60
        fi
    else
        set_status "probing (chip down, attempt $attempt, $(date -u +%FT%TZ))"
        sleep "$SLEEP_DOWN"
    fi
done
