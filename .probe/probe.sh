#!/usr/bin/env bash
# Entry point: start the detached chip-up monitor (idempotent — refuses to
# double-start). Status: cat .probe/status ; log: tail .probe/monitor.log
set -u
cd "$(dirname "$0")/.." || exit 1
PIDFILE=.probe/monitor.pid
if [ -f "$PIDFILE" ] && kill -0 "$(cat "$PIDFILE")" 2>/dev/null; then
    echo "monitor already running (pid $(cat "$PIDFILE")): $(cat .probe/status 2>/dev/null)"
    exit 0
fi
nohup bash .probe/monitor.sh >/dev/null 2>&1 &
echo $! >"$PIDFILE"
disown
echo "monitor started (pid $(cat "$PIDFILE")); status → .probe/status, log → .probe/monitor.log"
