# kakveda-tpu: single-image deployment.
#
# The reference ships 9 service containers wired over HTTP
# (reference: docker-compose.yml:1-170); this framework collapses the
# pipeline into one device-owning process, so one image serves the platform
# API (8100, all reference REST contracts) and the dashboard (8110).
#
# Build arg BASE selects the runtime:
#   - TPU hosts:  a jax[tpu] image (the default expects libtpu present on
#     the host via the TPU VM runtime)
#   - CPU/dev:    python:3.12-slim works; jax falls back to CPU.
ARG BASE=python:3.12-slim
FROM ${BASE}

WORKDIR /app

# Native toolchain for the in-tree C++ host tier (kakveda_tpu/native).
RUN apt-get update \
    && apt-get install -y --no-install-recommends g++ make \
    && rm -rf /var/lib/apt/lists/*

COPY pyproject.toml README.md ./
COPY kakveda_tpu ./kakveda_tpu
COPY config ./config
COPY scripts ./scripts

RUN pip install --no-cache-dir ".[postgres]" \
    && make -C kakveda_tpu/native

ENV KAKVEDA_DATA_DIR=/app/data \
    KAKVEDA_CONFIG_PATH=/app/config/config.yaml
VOLUME /app/data

EXPOSE 8100 8110
HEALTHCHECK --interval=30s --timeout=5s \
    CMD python -c "import urllib.request;urllib.request.urlopen('http://127.0.0.1:8100/healthz', timeout=3)"

CMD ["python", "-m", "kakveda_tpu.service", "--host", "0.0.0.0"]
