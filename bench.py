"""Benchmarks: warn p50 @1M GFKB, ingest throughput, serving + mining.

One `python bench.py` run measures warn, ingest, decode MFU (+curve,
+int8), speculative decode, continuous batching, warn-under-ingest,
warn-under-decode and pattern mining, and prints ONE JSON line —
headline = the warn north star, with the rest under ``extra_metrics`` so
the driver's BENCH_r{N}.json carries every number.
``KAKVEDA_BENCH_METRIC=warn|ingest|decode|spec|continuous|mixed|
mixed-decode|mine|serve|overload|tiered|recovery|fleet|storm|elastic``
runs a single metric instead (``overload`` floods the HTTP tier past its
admission bounds and proves shedding keeps warn p95 bounded; ``tiered``
A/Bs the IVF-routed tiered GFKB against the exact oracle at 1M rows plus
a 10M host/disk arm — docs/robustness.md, docs/performance.md § tiered;
``recovery`` certifies the GFKB durability lifecycle — ≥5× restart
replay after compaction, recall@1 parity, aging resident-bytes bound,
crash-point sweep with zero corrupt recoveries — docs/robustness.md
§ failure-memory lifecycle;
``storm`` replays the seeded hot-key-skew + failure-storm scenario with
its chaos timeline through the traffic harness and self-certifies the
SLO gates — kakveda_tpu/traffic/, docs/robustness.md § traffic harness;
``elastic`` runs the flash-crowd autoscaling drill — scale 2→4→2 with a
SIGKILLed owner replaced, zero lost warns, ≤1 flap — and self-certifies
the elastic contract, docs/scale-out.md § elastic fleet).

== warn: pre-flight warning p50 latency at a 1M-entry GFKB.

The north-star metric (BASELINE.md): the reference answers a pre-flight
match by reading the whole failures.jsonl, pydantic-validating every row,
re-fitting a TF-IDF vectorizer on (query + corpus) and scoring with sklearn
— O(N) work per request (reference: services/gfkb/app.py:79-102,
services/shared/similarity.py:14-20). Here the same request is: hash-embed
the query (host), one warm compiled matmul + sharded top-k on device, map
slots to records (host).

``vs_baseline`` is the measured speedup over the reference's algorithm on
this same host: sklearn TF-IDF refit+score timed at a small corpus size and
scaled linearly to the benchmark index size (its cost is O(N) in corpus
rows; linear extrapolation is *generous* to the reference since refit
memory effects get worse, and waiting for real 1M-row refits would take
minutes per query).

Measured as the per-request cost of the μ-batched serving pipeline (batch
i's device match overlaps batch i-1's result fetch) — the configuration the
warn service actually runs; single-request wall latency is printed to
stderr (on this tunneled-TPU environment it is floored by a fixed ~70 ms
device→host wire RTT that locally-attached chips don't pay).

Prints exactly one JSON line:
  {"metric": "preflight_warn_p50_ms_at_<N>_gfkb", "value": <p50 ms/request>,
   "unit": "ms", "vs_baseline": <reference_p50_ms / our_p50_ms>}

Env knobs: KAKVEDA_BENCH_N (index entries; default 1M on TPU, 100k
elsewhere), KAKVEDA_BENCH_DIM (default 2048), KAKVEDA_BENCH_QUERIES,
KAKVEDA_BENCH_BATCH (warn μ-batch, default 64), KAKVEDA_BENCH_TRACES /
KAKVEDA_BENCH_INGEST_BATCH (ingest), KAKVEDA_BENCH_DECODE_PRESET (1b|tiny)
/ KAKVEDA_BENCH_DECODE_BATCH / KAKVEDA_BENCH_DECODE_STEPS (decode MFU).
"""

from __future__ import annotations

import json
import os
import sys
import time

import numpy as np


def _measure_ours(n: int, dim: int, n_queries: int) -> float:
    import jax

    from kakveda_tpu.core.fingerprint import signature_text
    from kakveda_tpu.ops.featurizer import HashedNGramFeaturizer
    from kakveda_tpu.ops.knn import ShardedKnn
    from kakveda_tpu.parallel.mesh import create_mesh

    import jax.numpy as jnp

    mesh = create_mesh("data:-1")
    knn = ShardedKnn(mesh, capacity=n, dim=dim, k=5)
    emb, valid = knn.alloc()

    if os.environ.get("KAKVEDA_BENCH_REAL_EMB", "0") == "1":
        # Honest variant: embed n GENERATED signature texts with the
        # production featurizer (chunked, off-clock) instead of random unit
        # vectors — hashed n-gram rows are sparse and clustered, so this
        # rules out surprises from tie-handling on near-duplicate scores.
        # Setup costs minutes at 1M (host featurize + sparse upload).
        t0 = time.time()
        feat_fill = HashedNGramFeaturizer(dim=dim)
        verbs = ["Summarize", "Explain", "Describe", "Review", "Audit", "Outline"]
        tails = [
            "and include citations even if not provided",
            "adding references for every claim",
            "with sources listed",
            "without making up sources",
        ]
        chunk = 1 << 14
        types = None
        for start in range(0, n, chunk):
            m = min(chunk, n - start)
            sigs_fill = [
                signature_text(
                    f"{verbs[(start + i) % len(verbs)]} document {start + i} "
                    f"{tails[(start + i) % len(tails)]}",
                    [],
                    {"os": "linux"},
                )
                for i in range(m)
            ]
            sp_i, sp_v = feat_fill.encode_batch_sparse(sigs_fill)
            if types is None:
                types = knn.alloc_i32()
            emb, valid, types = knn.insert_sparse(
                emb, valid, types, sp_i, sp_v,
                np.arange(start, start + m, dtype=np.int32),
                np.zeros(m, np.int32),
            )
        jax.block_until_ready(emb)
        print(f"bench: real-embedding fill of {n:,} rows took {time.time() - t0:.0f}s", file=sys.stderr)
    else:
        # Default: random unit vectors generated *on device* (embedding 1M
        # signature texts on one host — or shipping 8 GB of vectors over
        # the wire — would dominate setup; the device-side match cost, the
        # thing being measured, is identical — verified by the
        # KAKVEDA_BENCH_REAL_EMB=1 variant, docs/performance.md).
        chunk = 1 << 16

        @jax.jit
        def _fill(emb_buf, valid_buf, key, start):
            v = jax.random.normal(key, (chunk, dim), jnp.float32)
            v = v / jnp.linalg.norm(v, axis=1, keepdims=True)
            emb_buf = jax.lax.dynamic_update_slice(emb_buf, v.astype(emb_buf.dtype), (start, 0))
            valid_buf = jax.lax.dynamic_update_slice(
                valid_buf, jnp.ones((chunk,), jnp.bool_), (start,)
            )
            return emb_buf, valid_buf

        key = jax.random.PRNGKey(0)
        for start in range(0, n - chunk + 1, chunk):
            key, sub = jax.random.split(key)
            emb, valid = _fill(emb, valid, sub, start)
        jax.block_until_ready(emb)
    # Lightweight metadata side-table (what GFKB.match consults after top-k).
    meta = [{"failure_id": f"F-{i:07d}", "failure_type": "HALLUCINATION_CITATION"} for i in range(n)]

    feat = HashedNGramFeaturizer(dim=dim)
    B = int(os.environ.get("KAKVEDA_BENCH_BATCH", 64))  # μ-batch of concurrent pre-flights
    depth = int(os.environ.get("KAKVEDA_BENCH_PIPELINE", 4))
    # Need enough batches to fill the pipeline and still record ≥8 periods.
    n_batches = max(depth + 8, n_queries // B)
    sig_batches = [
        [
            signature_text(
                f"Summarize document {b}-{i} and include citations even if not provided.",
                [],
                {"os": "linux"},
            )
            for i in range(B)
        ]
        for b in range(n_batches)
    ]

    def finish(packed):
        # Sparse dispatch buckets ragged batches; rows past B are pad rows
        # (all-zero queries — they score 0.0 against real rows, so slice,
        # don't threshold).
        scores, slots = knn.topk_result(packed)
        return [
            [{**meta[int(s)], "score": float(v)} for v, s in zip(sr, tr) if v > -1.0 and int(s) < n]
            for sr, tr in zip(scores[:B], slots[:B])
        ]

    # Warm both stages. From here the measured loop reuses the one bucketed
    # batch shape — the ledger window (when armed) must see ZERO compiles
    # past this line, the runtime twin of the static retrace-hazard rule.
    warm = knn.topk_async_sparse(emb, valid, *feat.encode_batch_sparse(sig_batches[0]))
    finish(warm)
    _ledger_mark_warm()

    # Pipelined serving loop with a depth-D in-flight window: batch i's
    # device match + host copy overlap the fetches of batches i-1..i-D, the
    # way the warn service drains its μ-batch queue. Per-request cost is the
    # steady-state pipeline period / B.
    from collections import deque

    periods = []
    inflight: deque = deque()
    t_prev = time.perf_counter()
    for sigs in sig_batches:
        q_idx, q_val = feat.encode_batch_sparse(sigs)
        inflight.append(knn.topk_async_sparse(emb, valid, q_idx, q_val))
        if len(inflight) > depth:
            res = finish(inflight.popleft())
            assert len(res) == B
            now = time.perf_counter()
            periods.append((now - t_prev) * 1000.0)
            t_prev = now
    while inflight:
        finish(inflight.popleft())

    # Single-request wall latency (same compiled batch shape, padded): this
    # includes the fixed D2H wire RTT — on a tunneled/remote TPU that floor
    # is ~70 ms and is an environment artifact; locally-attached chips
    # fetch in microseconds.
    t0 = time.perf_counter()
    finish(knn.topk_async_sparse(emb, valid, *feat.encode_batch_sparse(sig_batches[0])))
    single_ms = (time.perf_counter() - t0) * 1000.0
    print(f"bench: single-batch wall latency {single_ms:.1f} ms (incl. wire RTT)", file=sys.stderr)

    return float(np.percentile(periods, 50)) / B


def _measure_ingest(n_traces: int, batch: int) -> tuple[float, float, float]:
    """Streaming-ingest throughput: traces/sec through the full pipeline
    (fingerprint + rule classify + hash-embed + batched device insert +
    failure.detected fan-out to pattern/health reactors).

    Returns (ours_tps, sequential_tps) where sequential is the same
    pipeline driven one trace at a time with per-append flush — the
    reference's processing model (per-trace HTTP event → classify → JSONL
    append, services/failure_classifier/app.py:30-91) minus its 5
    container-boundary HTTP hops, so the comparison is generous to it.
    """
    import asyncio
    import tempfile
    from datetime import datetime, timezone
    from pathlib import Path

    from kakveda_tpu.core.schemas import TracePayload
    from kakveda_tpu.platform import Platform

    def mk_traces(m: int, tag: str):
        ts = datetime.now(timezone.utc)
        return [
            TracePayload(
                trace_id=f"t-{tag}-{i}",
                ts=ts,
                app_id=f"app-{i % 7}",
                agent_id="bench",
                prompt=f"Summarize report {i} with citations for every claim.",
                response=f"Done [{i}] (Smith 2021) as requested.",
                model="stub",
                tools=[],
                env={"os": "linux"},
            )
            for i in range(m)
        ]

    tmp = Path(tempfile.mkdtemp(prefix="kakveda-bench-"))
    plat = Platform(data_dir=tmp / "batched", capacity=1 << 20, dim=2048)

    async def run_batched() -> float:
        warm = mk_traces(batch, "warm")
        await plat.ingest_batch(warm)  # compile embed+insert for this shape
        traces = mk_traces(n_traces, "b")
        t0 = time.perf_counter()
        for i in range(0, n_traces - batch + 1, batch):
            await plat.ingest_batch(traces[i : i + batch])
        dt = time.perf_counter() - t0
        return (n_traces // batch) * batch / dt

    ours_tps = asyncio.run(run_batched())

    seq_n = min(n_traces, 512)  # sequential is slow; sample and report its rate
    plat_seq = Platform(data_dir=tmp / "seq", capacity=1 << 14, dim=2048)

    async def run_seq() -> float:
        await plat_seq.ingest_batch(mk_traces(1, "warmseq"))
        traces = mk_traces(seq_n, "s")
        t0 = time.perf_counter()
        for t in traces:
            await plat_seq.ingest(t)  # per-trace bus fan-out, like the reference
        dt = time.perf_counter() - t0
        return seq_n / dt

    seq_tps = asyncio.run(run_seq())

    # HTTP e2e variant: the same batched pipeline driven through the REAL
    # aiohttp server (POST /ingest/batch) by concurrent clients — shows
    # what request framing/validation costs against the in-process rate
    # (VERDICT r4 #4; the reference's surface is per-trace HTTP,
    # services/ingestion/app.py:15-21).
    async def run_http() -> float:
        from aiohttp.test_utils import TestClient, TestServer

        from kakveda_tpu.service.app import make_app

        plat_http = Platform(data_dir=tmp / "http", capacity=1 << 20, dim=2048)
        app = make_app(platform=plat_http)
        server = TestServer(app)
        await server.start_server()
        n_clients = int(os.environ.get("KAKVEDA_BENCH_INGEST_CLIENTS", 4))
        clients = [TestClient(server) for _ in range(n_clients)]
        for c in clients:
            await c.start_server()
        try:
            # Payloads serialized off-clock; warm the compiled embed+insert.
            warm = [t.model_dump(mode="json") for t in mk_traces(batch, "hw")]
            await clients[0].post("/ingest/batch", json={"traces": warm})
            payloads = [t.model_dump(mode="json") for t in mk_traces(n_traces, "h")]
            chunks = [
                payloads[i : i + batch] for i in range(0, n_traces - batch + 1, batch)
            ]

            async def worker(client, mine):
                for ch in mine:
                    r = await client.post("/ingest/batch", json={"traces": ch})
                    assert r.status == 200, await r.text()

            t0 = time.perf_counter()
            await asyncio.gather(
                *(worker(c, chunks[i::n_clients]) for i, c in enumerate(clients))
            )
            dt = time.perf_counter() - t0
            return len(chunks) * batch / dt
        finally:
            for c in clients:
                await c.close()

    http_tps = asyncio.run(run_http())
    return ours_tps, seq_tps, http_tps


def _preset_cfg(preset: str):
    """Model shapes for the serving benches: '1b' = TinyLlama-1.1B (the
    small-open-checkpoint serving class), else the tiny CPU smoke shape."""
    from kakveda_tpu.models.llama import LlamaConfig

    if preset == "1b":
        return LlamaConfig(
            vocab_size=32000, d_model=2048, n_layers=22, n_heads=32,
            n_kv_heads=4, d_ff=5632, max_seq_len=2048,
        )
    return LlamaConfig()


def _measure_decode(preset: str, bsz: int, steps: int) -> dict:
    """Serving bench: prefill + steady-state decode tokens/sec and MFU on
    the current chip, via the fused whole-generation-on-device decode
    (models/generate.py:generate_tokens_fused — one compiled program per
    generation, so the tunneled-TPU wire RTT is paid once, not per token).

    Weight VALUES don't affect speed, so the model is random-init at real
    shapes (no pretrained weights ship in this image); `vs_baseline` is the
    batched-vs-unbatched throughput ratio measured in the same run.
    """
    import jax
    import jax.numpy as jnp

    from kakveda_tpu.models.generate import _generate_fused_jit
    from kakveda_tpu.models.llama import init_cache, init_params

    cfg = _preset_cfg(preset)

    params = jax.tree.map(
        lambda x: x.astype(jnp.bfloat16), init_params(jax.random.PRNGKey(0), cfg)
    )
    n_params = sum(int(np.prod(x.shape)) for x in jax.tree.leaves(params))
    if os.environ.get("KAKVEDA_BENCH_QUANT") == "int8":
        from kakveda_tpu.models.quant import quantize_params_int8

        params = quantize_params_int8(params)
        print("bench[decode]: int8 weight-only quantization enabled", file=sys.stderr)
    # Matmul FLOPs/token: 2·(params excl. embedding gather) + attention
    # (QK^T and PV: 4·L·ctx·d_model at the mean decode context).
    n_mat = n_params - int(np.prod(params["embed"].shape))
    plen = 128
    mean_ctx = plen + steps / 2
    flops_per_tok = 2 * n_mat + 4 * cfg.n_layers * mean_ctx * cfg.d_model

    peak = {
        # bf16 peak TFLOP/s per chip, by device_kind substring.
        "v4": 275e12, "v5 lite": 197e12, "v5e": 197e12,
        "v5p": 459e12, "v6": 918e12, "v6e": 918e12,
    }
    peak_bw = {
        # HBM GB/s per chip — the decode roofline. MFU alone makes decode
        # look bad (it is bandwidth-bound); % of peak HBM says how close
        # to the real ceiling the run is.
        "v4": 1228e9, "v5 lite": 819e9, "v5e": 819e9,
        "v5p": 2765e9, "v6": 1640e9, "v6e": 1640e9,
    }
    kind = jax.devices()[0].device_kind.lower()
    peak_flops = next((v for k, v in peak.items() if k in kind), 197e12)
    peak_hbm = next((v for k, v in peak_bw.items() if k in kind), 819e9)

    rng = np.random.default_rng(0)

    def timed(prm, b: int, p: int, n_steps: int, reps: int = 3, cfg_=None) -> float:
        """Best-of-reps wall time of one fused generation (prefill p tokens
        + n_steps decode) at batch b. np.asarray syncs through the wire, so
        every timing carries the same fixed RTT — all derived numbers below
        are *slopes* between two timings, which cancels it."""
        c = cfg_ or cfg
        toks = jnp.asarray(rng.integers(3, c.vocab_size, size=(b, p)), jnp.int32)
        valid = jnp.ones((b, 512), bool)
        offs = jnp.zeros((b,), jnp.int32)
        key = jax.random.PRNGKey(0)
        temp = jnp.asarray(1e-6, jnp.float32)

        def gen():
            cache = init_cache(c, batch=b, max_len=512)
            out = _generate_fused_jit(
                prm, c, toks, cache, valid, offs, key, temp, n_steps, True
            )
            return np.asarray(out)

        gen()  # compile + warm
        best = float("inf")
        for _ in range(reps):
            t0 = time.perf_counter()
            gen()
            best = min(best, time.perf_counter() - t0)
        return best

    s_lo = max(1, steps // 4)

    def decode_rate(prm, b: int, cfg_=None) -> float:
        dt = timed(prm, b, plen, steps, cfg_=cfg_) - timed(prm, b, plen, s_lo, cfg_=cfg_)
        return b * (steps - s_lo) / max(dt, 1e-9)

    decode_tps = decode_rate(params, bsz)
    solo_tps = decode_rate(params, 1)
    # Batch-scaling curve: defaults to 4×/8× the configured batch so an
    # operator who shrank KAKVEDA_BENCH_DECODE_BATCH for a small device
    # never gets surprise-large allocations; KAKVEDA_BENCH_DECODE_CURVE
    # overrides (empty string disables).
    curve = {}
    curve_env = os.environ.get("KAKVEDA_BENCH_DECODE_CURVE", f"{bsz * 4},{bsz * 8}")
    for b in (int(x) for x in curve_env.split(",") if x):
        if b != bsz:
            curve[b] = decode_rate(params, b)
    curve[bsz] = decode_tps

    # int8 weight-only decode at the same batch: decode streams every dense
    # weight from HBM per step, so halving the weight bytes is the headline
    # serving lever (models/quant.py). Skipped when the main run is already
    # int8 (KAKVEDA_BENCH_QUANT) or KAKVEDA_BENCH_INT8=0.
    int8_tps = None
    int8_curve: dict = {}
    if (
        os.environ.get("KAKVEDA_BENCH_QUANT") != "int8"
        and os.environ.get("KAKVEDA_BENCH_INT8", "1") != "0"
    ):
        from kakveda_tpu.models.quant import quantize_params_int8

        qparams = quantize_params_int8(params)
        int8_tps = decode_rate(qparams, bsz)
        # int8 row of the SAME batch curve: halving the weight stream
        # matters most where weights dominate traffic (small batch) and
        # fades as the KV cache takes over (large batch) — the crossover
        # is visible only with both rows measured.
        int8_curve = {bsz: int8_tps}
        for b in curve:
            if b != bsz:
                int8_curve[b] = decode_rate(qparams, b)
        del qparams

    # int8 KV cache at the largest curve batch: past the crossover the
    # cache is the binding HBM stream, so this is where cache quant pays.
    kv8_tps = None
    if os.environ.get("KAKVEDA_BENCH_KV8", "1") != "0":
        import dataclasses as _dc

        cfg8 = _dc.replace(cfg, kv_quant="int8")
        b_big = max(curve)
        kv8_tps = {b_big: decode_rate(params, b_big, cfg8)}
        if bsz != b_big:
            kv8_tps[bsz] = decode_rate(params, bsz, cfg8)

    # Prefill slope between two prompt lengths at one decode step.
    p_hi = 384
    dt_p = timed(params, bsz, p_hi, 1) - timed(params, bsz, plen, 1)
    prefill_tps = bsz * (p_hi - plen) / max(dt_p, 1e-9)

    mfu = decode_tps * flops_per_tok / peak_flops
    prefill_mfu = prefill_tps * (2 * n_mat) / peak_flops

    # Decode roofline: achieved HBM traffic as a fraction of peak
    # bandwidth. Per step the chip streams every dense weight once
    # (2 bytes/param bf16) plus each sequence's K/V prefix
    # (2·L·KV·hd·mean_ctx·2 bytes); "good" decode = hbm_util near 1,
    # NOT mfu near 1 (decode is bandwidth-bound by construction).
    def hbm_util(tps: float, b: int, w_bytes_per_param: float, cache_itemsize: float) -> float:
        w_bytes = w_bytes_per_param * n_mat
        kv_bytes = 2 * cfg.n_layers * cfg.n_kv_heads * cfg.head_dim * mean_ctx * cache_itemsize
        return (tps / b) * (w_bytes + b * kv_bytes) / peak_hbm

    cache_b = 2 if cfg.dtype == jnp.bfloat16 else 4
    utils = {"bf16": hbm_util(decode_tps, bsz, 2.0, cache_b)}
    if int8_tps:
        utils["int8"] = hbm_util(int8_tps, bsz, 1.0, cache_b)
    if kv8_tps:
        b_big = max(kv8_tps)
        # int8 rows + one f32 scale per head_dim elements
        utils["kv8"] = hbm_util(kv8_tps[b_big], b_big, 2.0, 1.0 + 4.0 / cfg.head_dim)
    return {
        "decode_tps": decode_tps,
        "prefill_tps": prefill_tps,
        "solo_tps": solo_tps,
        "int8_tps": int8_tps,
        "int8_curve": int8_curve,
        "kv8_tps": kv8_tps,
        "hbm_util": utils,
        "mfu": mfu,
        "prefill_mfu": prefill_mfu,
        "curve": curve,
        "n_params": n_params,
        "batch": bsz,
        "device_kind": kind,
        "peak_tflops": peak_flops / 1e12,
        "peak_hbm_gbps": peak_hbm / 1e9,
    }


def _measure_spec(preset: str, steps: int, k: int) -> dict:
    """Draft-free speculative decoding vs plain fused decode, single
    sequence (the playground / LLM-judge path). Both are ONE compiled
    program per generation; timings are slopes between two generation
    lengths (cancels the remote-TPU dispatch RTT). tokens/round is the
    measured acceptance — each round costs one weight stream, so the
    speedup ceiling is tokens_per_round (weight-bandwidth-bound decode).
    Weight values DO affect this metric (acceptance depends on how
    repetitive the model's output is); random-init is the conservative
    case — real checkpoints on judge-style prompts repeat far more."""
    import jax
    import jax.numpy as jnp

    from kakveda_tpu.models.generate import generate_tokens_fused
    from kakveda_tpu.models.llama import init_params
    from kakveda_tpu.models.speculative import generate_tokens_speculative

    cfg = _preset_cfg(preset)

    params = jax.tree.map(
        lambda x: x.astype(jnp.bfloat16), init_params(jax.random.PRNGKey(0), cfg)
    )
    rng = np.random.default_rng(0)
    prompt = rng.integers(3, min(cfg.vocab_size, 250), size=128).tolist()

    s_lo = max(8, steps // 4)

    def timed(fn, n_steps, reps=3):
        fn(n_steps)  # compile + warm
        best = float("inf")
        for _ in range(reps):
            t0 = time.perf_counter()
            fn(n_steps)
            best = min(best, time.perf_counter() - t0)
        return best

    def plain(n_steps):
        generate_tokens_fused(params, cfg, [prompt], max_new_tokens=n_steps)

    stats_box = {}  # keyed by n_steps — report the HEADLINE run's acceptance

    def spec(n_steps):
        _, st = generate_tokens_speculative(
            params, cfg, prompt, max_new_tokens=n_steps, k=k, return_stats=True
        )
        stats_box[n_steps] = st

    plain_tps = (steps - s_lo) / max(timed(plain, steps) - timed(plain, s_lo), 1e-9)
    spec_tps = (steps - s_lo) / max(timed(spec, steps) - timed(spec, s_lo), 1e-9)
    return {
        "plain_tps": plain_tps,
        "spec_tps": spec_tps,
        "tokens_per_round": stats_box.get(steps, {}).get("tokens_per_round", 0.0),
        "k": k,
    }


def _measure_spec_judge(k: int) -> dict:
    """Acceptance on the PRODUCTION workload shape: the failure-judge
    template over near-duplicate traces. Acceptance depends on weights
    (a model must actually continue the repeated n-grams), so a tiny
    model is trained on judge-formatted traces in-bench — minutes, vs
    days for the 1B preset — and acceptance is measured speculating a
    held-out judge prompt. tokens/round is the number that transfers
    across scales (each round = one weight stream regardless of size);
    the tiny-scale tok/s here are NOT the 1B serving numbers."""
    import jax.numpy as jnp

    from kakveda_tpu.models.llama import LlamaConfig
    from kakveda_tpu.models.speculative import generate_tokens_speculative
    from kakveda_tpu.models.tokenizer import ByteTokenizer
    from kakveda_tpu.models.train import fit
    from kakveda_tpu.pipeline.classifier import _JUDGE_PROMPT

    cfg = LlamaConfig(
        vocab_size=264, d_model=64, n_layers=2, n_heads=4, n_kv_heads=2,
        d_ff=128, max_seq_len=512, dtype=jnp.float32,
    )
    rng = np.random.default_rng(0)
    apps = ["billing", "search", "support"]

    def trace(i: int) -> str:
        return _JUDGE_PROMPT.format(
            prompt=f"Summarize the {apps[i % 3]} report {i} and include citations "
            "even if not provided",
            response=f"Here is a summary with references. [1] Smith et al. (2020) "
            f"A Study on Things. [2] Doe (2021) Another Paper. item {i}",
        ) + (" YES\n" if i % 3 else " NO\n")

    corpus = "".join(trace(i) for i in range(40))
    steps_tr = int(os.environ.get("KAKVEDA_BENCH_SPEC_JUDGE_STEPS", 150))
    params, losses = fit(cfg, corpus, steps=steps_tr, batch=4, seq_len=128, lr=3e-3, log_every=0)

    held_out = _JUDGE_PROMPT.format(
        prompt="Summarize the billing report 999 and include citations even if not provided",
        response="Here is a summary with references. [1] Smith et al. (2020) "
        "A Study on Things. [2] Doe (2021) Another Paper. item 999",
    )
    # No truncation: the template header must sit in the lookup buffer or
    # the first generated copy of it has nothing to match against.
    ids = ByteTokenizer().encode(held_out)
    _, st = generate_tokens_speculative(
        params, cfg, ids, max_new_tokens=96, k=k, return_stats=True
    )

    # ENGINE-level speculative A/B on the same trained judge model
    # (KAKVEDA_SERVE_SPEC): a pool of held-out judge prompts drains through
    # the ContinuousBatcher with plain chunks vs verify chunks. f32 weights
    # → outputs must be token-identical; acceptance here is the measured
    # judge-workload number that transfers to serving scale.
    from kakveda_tpu.models.serving import ContinuousBatcher

    # Prompts truncated so the admission bucket (pow2 255→256) leaves real
    # decode room in the 512 window — a 384-token prompt buckets to 511
    # and the pool would emit ONE token per request (a degenerate A/B).
    pool_prompts = [
        ByteTokenizer().encode(
            _JUDGE_PROMPT.format(
                prompt=f"Summarize the {apps[i % 3]} report {900 + i} and include "
                "citations even if not provided",
                response="Here is a summary with references. [1] Smith et al. (2020) "
                f"A Study on Things. [2] Doe (2021) Another Paper. item {900 + i}",
            )
        )[-255:]
        for i in range(6)
    ]

    def drain_pipelined(cb):
        """ONE engine-shaped pipelined drain for BOTH arms (dispatch
        chunk i+1 before fetching chunk i; verify chunks thread their
        post-acceptance slot_pos on device and draft from copy cursors —
        the same ordering the ServingEngine loop runs). The arms differ
        only in what spec_ready() dispatches, so an auto-gated-off spec
        pool times the SAME code path as the plain arm by construction —
        the gate's "within 5% of plain" contract is structural, not
        luck. Reusable: a warm pass doubles as gate calibration."""
        pending = list(enumerate(pool_prompts))
        order, handle, spec_handle = {}, None, None
        t0 = time.perf_counter()
        while pending or cb.slots or handle is not None or spec_handle is not None:
            if pending and cb.free and spec_handle is not None:
                # Admission needs host-authoritative slot state.
                cb.process_spec_chunk(spec_handle)
                spec_handle = None
            while pending and cb.free:
                i, p = pending.pop(0)
                order[cb.admit(p, max_new_tokens=96)] = i
            if cb.spec_ready():
                cb.process_chunk(handle)
                handle = None
                if spec_handle is not None and cb.spec_pipeline_ready():
                    # Full-accept regime: overlap draft/accept with the
                    # next verify chunk's device time (cursor drafts).
                    nxt = cb.step_spec_async()
                    cb.process_spec_chunk(spec_handle)
                    spec_handle = nxt
                else:
                    # Acceptance-preserving sync order.
                    cb.process_spec_chunk(spec_handle)
                    spec_handle = None
                    if cb.slots and cb.spec_ready():
                        spec_handle = cb.step_spec_async()
            elif cb.slots:
                cb.process_spec_chunk(spec_handle)
                spec_handle = None
                nxt = cb.step_async()
                cb.process_chunk(handle)
                handle = nxt
            else:
                cb.process_chunk(handle)
                cb.process_spec_chunk(spec_handle)
                handle = spec_handle = None
        wall = time.perf_counter() - t0
        outs = [None] * len(pool_prompts)
        for rid, i in order.items():
            outs[i] = cb.results.pop(rid)
        return wall, outs

    # ONE batcher per arm, reused warm→measured: the spec batcher's warm
    # pass doubles as the auto-gate's calibration+warmup, so the measured
    # pass reports the gate's SETTLED verdict (spec chunks if they pay,
    # plain fallback if they don't) — a fresh batcher would re-pay warmup
    # spec chunks inside the timed window.
    cb_plain = ContinuousBatcher(params, cfg, batch_slots=3, max_len=512, chunk_steps=8)
    cb_spec = ContinuousBatcher(
        params, cfg, batch_slots=3, max_len=512, chunk_steps=8, spec_k=k
    )
    drain_pipelined(cb_plain)  # warm compiled paths off-clock
    drain_pipelined(cb_spec)  # warm + gate calibration
    # Best-of-3 per arm: the tiny-preset drains are ~100 ms, where one
    # scheduler hiccup would swamp the within-5% gate contract.
    wall_plain, outs_plain = drain_pipelined(cb_plain)
    wall_spec, outs_spec = drain_pipelined(cb_spec)
    for _ in range(2):
        wall_plain = min(wall_plain, drain_pipelined(cb_plain)[0])
        wall_spec = min(wall_spec, drain_pipelined(cb_spec)[0])
    # Parity is exact in math (tests/test_serving_spec.py, f32); tolerate
    # at most one request flipping on a bitwise logit tie (argmax order
    # differs across program shapes — the CLAUDE.md greedy-parity gotcha)
    # and fail loudly past that.
    n_mismatch = sum(a != b for a, b in zip(outs_plain, outs_spec))
    if n_mismatch > 1:
        raise RuntimeError(
            f"engine verify chunks diverged on {n_mismatch}/{len(outs_plain)} "
            "judge requests — beyond tie noise, a real parity bug"
        )

    # THE read API (CLAUDE.md): the lock-guarded deep-copy snapshot, never
    # the live dicts — single-threaded here, but the discipline is uniform.
    s = cb_spec.stats_snapshot()["spec"]
    engine_rate = s["emitted"] / s["slot_chunks"] if s["slot_chunks"] else 0.0
    return {
        "tokens_per_round": st["tokens_per_round"],
        "rounds": st["rounds"],
        "train_loss": float(losses[-1]),
        "train_steps": steps_tr,
        "engine_wall_plain_s": wall_plain,
        "engine_wall_spec_s": wall_spec,
        "engine_tokens_per_verify": engine_rate,
        "engine_parity_mismatches": n_mismatch,
        "engine_gate_state": s["gate_state"],
        "engine_break_even": s["break_even"],
        "engine_tokens_per_verify_recent": s["tokens_per_verify"],
        "engine_accept_rate": s["accepted"] / s["drafted"] if s["drafted"] else 0.0,
        "engine_k_trace": list(s["k_trace"])[-16:],
    }


def _bench_spec(backend: str) -> dict:
    preset = os.environ.get("KAKVEDA_BENCH_DECODE_PRESET", "1b" if _on_tpu(backend) else "tiny")
    steps = int(os.environ.get("KAKVEDA_BENCH_SPEC_STEPS", 256))
    k = int(os.environ.get("KAKVEDA_BENCH_SPEC_K", 8))
    print(f"bench[spec]: backend={backend} preset={preset} steps={steps} k={k}", file=sys.stderr)
    r = _measure_spec(preset, steps, k)
    print(
        f"bench[spec]: speculative {r['spec_tps']:,.0f} tok/s vs plain {r['plain_tps']:,.0f} "
        f"tok/s @batch 1 ({r['tokens_per_round']:.2f} tokens/round, k={k}, random-init "
        f"= conservative acceptance floor)",
        file=sys.stderr,
    )
    out = {
        "metric": f"speculative_decode_tokens_per_sec_{preset}_b1",
        "value": round(r["spec_tps"], 1),
        "unit": "tokens/sec",
        "vs_baseline": round(r["spec_tps"] / r["plain_tps"], 2) if r["plain_tps"] > 0 else 0.0,
        "plain_tps": round(r["plain_tps"], 1),
        "tokens_per_round": round(r["tokens_per_round"], 2),
    }
    if os.environ.get("KAKVEDA_BENCH_SPEC_JUDGE", "1") != "0":
        j = _measure_spec_judge(k)
        # Projection to the main preset: rounds are weight-stream-bound,
        # so tok/s scales with acceptance at ~the floor run's per-round
        # overhead. Clearly a projection, not a measurement.
        overhead = (
            r["plain_tps"] * r["tokens_per_round"] / r["spec_tps"]
            if r["spec_tps"] > 0 else 1.0
        )
        projected = r["plain_tps"] * j["tokens_per_round"] / max(overhead, 1e-9)
        print(
            f"bench[spec]: judge-workload acceptance {j['tokens_per_round']:.2f} "
            f"tokens/round (tiny model trained {j['train_steps']} steps on the "
            f"judge template, loss {j['train_loss']:.3f}) — projected "
            f"{projected:,.0f} tok/s at {preset} scale at that acceptance",
            file=sys.stderr,
        )
        out["judge_tokens_per_round"] = round(j["tokens_per_round"], 2)
        out["judge_projected_tps"] = round(projected, 1)
        print(
            f"bench[spec]: ENGINE verify chunks on the judge pool — "
            f"{j['engine_wall_plain_s']:.2f}s pipelined-plain vs {j['engine_wall_spec_s']:.2f}s spec "
            f"({j['engine_wall_plain_s'] / max(j['engine_wall_spec_s'], 1e-9):.2f}x, "
            f"{j['engine_tokens_per_verify']:.2f} tokens/verify, "
            f"accept {j['engine_accept_rate']:.2f}, gate {j['engine_gate_state']} "
            f"@break-even {j['engine_break_even']:.2f}, "
            f"k trace {j['engine_k_trace']}, "
            f"{j['engine_parity_mismatches']} tie-flips)",
            file=sys.stderr,
        )
        out["engine_spec_speedup"] = round(
            j["engine_wall_plain_s"] / max(j["engine_wall_spec_s"], 1e-9), 2
        )
        out["engine_tokens_per_verify"] = round(j["engine_tokens_per_verify"], 2)
        # The auto-gate's verdict: when verify chunks can't clear the
        # measured break-even the pool decodes plain — the spec arm then
        # matches the plain arm instead of shipping a configured slowdown.
        out["engine_gate_state"] = j["engine_gate_state"]
        out["engine_break_even"] = round(j["engine_break_even"], 2)
        out["engine_accept_rate"] = round(j["engine_accept_rate"], 3)
        out["engine_adaptive_k_trace"] = j["engine_k_trace"]
    return out


def _measure_mixed(n: int, dim: int) -> dict:
    """Warn latency under concurrent streaming ingest — the decoupling
    claim: match dispatches serialize only on microsecond-scale lock holds,
    never on ingest's host-side embedding or growth re-embeds. Reports
    warn p50 idle vs p50 with a background ingest_batch storm."""
    import asyncio
    import tempfile
    import threading
    from datetime import datetime, timezone
    from pathlib import Path

    from kakveda_tpu.core.schemas import TracePayload, WarningRequest
    from kakveda_tpu.platform import Platform

    tmp = Path(tempfile.mkdtemp(prefix="kakveda-mixed-"))
    plat = Platform(data_dir=tmp, capacity=max(n, 1 << 15), dim=dim)

    def mk_traces(m: int, tag: str):
        ts = datetime.now(timezone.utc)
        return [
            TracePayload(
                trace_id=f"t-{tag}-{i}", ts=ts, app_id=f"app-{i % 7}", agent_id="bench",
                prompt=f"Summarize report {tag}-{i} with citations for every claim.",
                response=f"Done [{i}] (Smith 2021).", tools=[], env={"os": "linux"},
            )
            for i in range(m)
        ]

    reqs = [
        WarningRequest(app_id="app-0", agent_id="bench",
                       prompt=f"Explain document {i} and include citations", tools=[], env={})
        for i in range(64)
    ]
    # Seed + warm both compiled paths.
    asyncio.run(plat.ingest_batch(mk_traces(512, "seed")))
    plat.warn_batch(reqs)

    def warn_p50(rounds: int) -> float:
        lat = []
        for _ in range(rounds):
            t0 = time.perf_counter()
            plat.warn_batch(reqs)
            lat.append((time.perf_counter() - t0) * 1000.0 / len(reqs))
        return float(np.percentile(lat, 50))

    idle_p50 = warn_p50(30)

    stop = threading.Event()

    def ingest_storm():
        i = 0
        while not stop.is_set():
            asyncio.run(plat.ingest_batch(mk_traces(512, f"s{i}")))
            i += 1

    t = threading.Thread(target=ingest_storm)
    t.start()
    try:
        loaded_p50 = warn_p50(30)
    finally:
        stop.set()
        t.join()
    return {"idle_p50_ms": idle_p50, "loaded_p50_ms": loaded_p50}


def _measure_mixed_decode(n: int, dim: int, preset: str, chunk_steps: int) -> dict:
    """Warn latency while a continuous Llama generation storm shares the
    chip — SURVEY §7's 'interleaving generate steps with match batches'.

    The storm runs through DecodeSession (chunked dispatch): each chunk is a
    bounded device program, so a warn batch waits at most ~chunk_steps
    decode steps in the device queue instead of a whole generation (a
    single fused 128-step program at 1B scale blocks the chip for hundreds
    of ms). Reports warn p50/request idle vs loaded, plus the decode tok/s
    the storm sustains while sharing.

    HBM budget at the default TPU config (v5e 16 GB): 1M×2048 bf16 index
    4.0 GB + 1.1B bf16 params 2.2 GB + [16, KV4, 512, 64] caches 0.4 GB +
    transient scratch — comfortably co-resident.
    """
    import threading

    import jax
    import jax.numpy as jnp

    from kakveda_tpu.core.fingerprint import signature_text
    from kakveda_tpu.models.generate import DecodeSession
    from kakveda_tpu.models.llama import LlamaConfig, init_params
    from kakveda_tpu.ops.featurizer import HashedNGramFeaturizer
    from kakveda_tpu.ops.knn import ShardedKnn
    from kakveda_tpu.parallel.mesh import create_mesh

    # --- index (same synthetic fill as the warn bench) -------------------
    mesh = create_mesh("data:-1")
    knn = ShardedKnn(mesh, capacity=n, dim=dim, k=5)
    emb, valid = knn.alloc()
    chunk = 1 << 16

    @jax.jit
    def _fill(emb_buf, valid_buf, key, start):
        v = jax.random.normal(key, (chunk, dim), jnp.float32)
        v = v / jnp.linalg.norm(v, axis=1, keepdims=True)
        emb_buf = jax.lax.dynamic_update_slice(emb_buf, v.astype(emb_buf.dtype), (start, 0))
        valid_buf = jax.lax.dynamic_update_slice(valid_buf, jnp.ones((chunk,), jnp.bool_), (start,))
        return emb_buf, valid_buf

    key = jax.random.PRNGKey(0)
    for start in range(0, n - chunk + 1, chunk):
        key, sub = jax.random.split(key)
        emb, valid = _fill(emb, valid, sub, start)
    jax.block_until_ready(emb)

    feat = HashedNGramFeaturizer(dim=dim)
    B = 64
    sigs = [
        signature_text(f"Summarize document {i} and include citations.", [], {"os": "linux"})
        for i in range(B)
    ]
    q_idx, q_val = feat.encode_batch_sparse(sigs)
    knn.topk_result(knn.topk_async_sparse(emb, valid, q_idx, q_val))  # warm

    def warn_p50(rounds: int) -> float:
        lat = []
        for _ in range(rounds):
            t0 = time.perf_counter()
            knn.topk_result(knn.topk_async_sparse(emb, valid, q_idx, q_val))
            lat.append((time.perf_counter() - t0) * 1000.0 / B)
        return float(np.percentile(lat, 50))

    idle_p50 = warn_p50(30)

    # --- generation storm ------------------------------------------------
    cfg = _preset_cfg(preset)
    params = jax.tree.map(
        lambda x: x.astype(jnp.bfloat16), init_params(jax.random.PRNGKey(0), cfg)
    )
    rng = np.random.default_rng(0)
    prompts = [list(rng.integers(3, cfg.vocab_size, size=128)) for _ in range(16)]

    stop = threading.Event()
    tok_count = [0]

    def storm():
        while not stop.is_set():
            sess = DecodeSession(params, cfg, prompts, chunk_steps=chunk_steps, max_len=512)
            while not stop.is_set():
                c = sess.step_chunk()
                if c is None:
                    break
                tok_count[0] += c.size

    t = threading.Thread(target=storm)
    t.start()
    try:
        # Let the storm warm its compiled chunk program before measuring.
        deadline = time.time() + 60
        while tok_count[0] < 16 * chunk_steps * 2 and time.time() < deadline:
            time.sleep(0.5)
        c0, t0 = tok_count[0], time.perf_counter()
        loaded_p50 = warn_p50(30)
        storm_tps = (tok_count[0] - c0) / (time.perf_counter() - t0)
    finally:
        stop.set()
        t.join()
    return {
        "idle_p50_ms": idle_p50,
        "loaded_p50_ms": loaded_p50,
        "storm_decode_tps": storm_tps,
        "chunk_steps": chunk_steps,
    }


def _measure_mine(n: int, dim: int, n_templates: int) -> dict:
    """Batch pattern mining over ``n`` REAL hashed-ngram embeddings — the
    BASELINE 'batch clustering over full GFKB embeddings' config.

    Corpus: ``n_templates`` distinct failure shapes (prompt templates with
    per-row wording variation), embedded with the production featurizer.
    Sanity = cluster purity against the generating template: rows whose
    label's majority-template matches their own. The reference has no
    comparable capability (its pattern detector is a group-by on
    failure_type, services/pattern_detector/app.py:40-47); vs_baseline is
    purity, not a speedup."""
    import jax
    import jax.numpy as jnp

    from kakveda_tpu.core.fingerprint import signature_text
    from kakveda_tpu.ops.clustering import cluster_embeddings
    from kakveda_tpu.ops.featurizer import HashedNGramFeaturizer

    rng = np.random.default_rng(0)
    verbs = ["Summarize", "Explain", "Describe", "Review", "Outline"]
    objs = ["report", "paper", "contract", "dataset", "incident", "ticket"]
    tails = [
        "and include citations even if not provided",
        "and add references for every claim",
        "listing all sources used",
        "with a short bibliography",
    ]
    template_ids = rng.integers(0, n_templates, size=n)
    feat = HashedNGramFeaturizer(dim=dim)
    texts = []
    for i in range(n):
        t = int(template_ids[i])
        # Template fixes the stable wording; per-row noise varies the rest.
        text = (
            f"{verbs[t % len(verbs)]} the {objs[(t // len(verbs)) % len(objs)]} "
            f"variant {t} {tails[t % len(tails)]} item {rng.integers(0, 9)}"
        )
        texts.append(signature_text(text, [], {"os": "linux"}))
    # Embed + ship sparse (idx, val) pairs and densify ON DEVICE — the
    # dense [N, dim] form is ~98% zeros and shipping it over the tunneled
    # TPU's ~20 MB/s link took 4+ minutes at 1M rows (long enough to trip
    # backend timeouts); the sparse pairs are ~60× smaller. Untimed vs
    # mining: production embeddings already live in HBM.
    from functools import partial as _partial

    @_partial(jax.jit, donate_argnums=(0,))
    def _scatter_chunk(buf, idx, val, row0):
        b, k = idx.shape
        rows = jnp.broadcast_to(jnp.arange(b)[:, None], (b, k))
        chunk = jnp.zeros((b, dim + 1), jnp.float32).at[rows, idx].add(val)[:, :dim]
        return jax.lax.dynamic_update_slice(buf, chunk, (row0, 0))

    t0 = time.perf_counter()
    enc_chunk = 1 << 14
    n_pad = -(-n // enc_chunk) * enc_chunk  # buffer padded so the tail
    v_dev = jnp.zeros((n_pad, dim), jnp.float32)  # chunk never clamps
    t_embed = 0.0
    for s in range(0, n, enc_chunk):
        te = time.perf_counter()
        idx, val = feat.encode_batch_sparse(texts[s : s + enc_chunk])
        t_embed += time.perf_counter() - te
        if idx.shape[0] < enc_chunk:  # pad tail to the compiled shape
            pad = enc_chunk - idx.shape[0]
            idx = np.concatenate([idx, np.full((pad, idx.shape[1]), dim, np.int32)])
            val = np.concatenate([val, np.zeros((pad, val.shape[1]), np.float32)])
        v_dev = _scatter_chunk(v_dev, idx, val, jnp.asarray(s, jnp.int32))
    if n_pad != n:
        v_dev = v_dev[:n]
    jax.block_until_ready(v_dev)
    t_ship = time.perf_counter() - t0 - t_embed
    print(f"bench[mine]: embedded {n:,} texts in {t_embed:.1f}s", file=sys.stderr, flush=True)
    print(f"bench[mine]: sparse device upload took {t_ship:.1f}s", file=sys.stderr, flush=True)

    t0 = time.perf_counter()
    labels = cluster_embeddings(v_dev, threshold=0.6)
    t_mine = time.perf_counter() - t0

    def _purity(lab: np.ndarray, tmpl: np.ndarray) -> float:
        """Majority-template share per cluster label."""
        order = np.argsort(lab, kind="stable")
        sl, st = lab[order], tmpl[order]
        bounds = np.flatnonzero(np.r_[True, sl[1:] != sl[:-1], True])
        correct = 0
        for a, b in zip(bounds[:-1], bounds[1:]):
            _, counts = np.unique(st[a:b], return_counts=True)
            correct += int(counts.max())
        return correct / len(lab)

    purity = _purity(labels, template_ids)

    # --- incremental streaming arm --------------------------------------
    # The same corpus streamed through ingest-time attachment
    # (ops/incremental.py): per batch, ONE delta top-k against the rows
    # inserted so far + host union-find updates — O(ΔN·N) per batch,
    # amortized over the stream — then "refresh" = materialize labels
    # from the live state, which is what mine_patterns pays per call
    # instead of the full O(N²) sweep. Parity vs the full-mine oracle is
    # asserted EXACTLY (same packed-label convention), purity against the
    # generating templates like the full arm.
    from kakveda_tpu.ops.clustering import _KNN_K, _corpus_pad
    from kakveda_tpu.ops.incremental import ClusterState, delta_topk_dense, unpack_topk

    n_inc = min(n, int(os.environ.get("KAKVEDA_BENCH_MINE_INC_N", 20_000)))
    inc_bs = 1 << max(4, int(os.environ.get("KAKVEDA_BENCH_MINE_INC_BATCH", 512)).bit_length() - 1)
    thr = 0.6
    if n_inc == n:
        labels_sub, full_wall_sub = labels, t_mine
    else:
        t0 = time.perf_counter()
        labels_sub = cluster_embeddings(v_dev[:n_inc], threshold=thr)
        full_wall_sub = time.perf_counter() - t0
    P = _corpus_pad(n_inc)
    v_pad = (
        jnp.concatenate([v_dev[:n_inc], jnp.zeros((P - n_inc, dim), jnp.float32)])
        if P != n_inc
        else v_dev[:n_inc]
    )
    state = ClusterState(threshold=thr, k=_KNN_K)
    # warm the single compiled delta program off-clock
    jax.block_until_ready(delta_topk_dense(v_pad[:inc_bs], v_pad, inc_bs, _KNN_K + 1))
    t_stream = 0.0
    for s in range(0, n_inc, inc_bs):
        e = min(s + inc_bs, n_inc)
        t0 = time.perf_counter()
        packed = delta_topk_dense(v_pad[s : s + inc_bs], v_pad, e, _KNN_K + 1)
        sims, idx = unpack_topk(packed, e - s)
        for r in range(e - s):
            state.add_row(s + r)
        for r in range(e - s):
            state.attach(s + r, idx[r], sims[r])
        t_stream += time.perf_counter() - t0
    t0 = time.perf_counter()
    inc_labels = state.labels()
    t_refresh = time.perf_counter() - t0
    inc = {
        "n": n_inc,
        "stream_wall_s": t_stream,
        "amortized_ms_per_row": t_stream * 1000.0 / n_inc,
        "refresh_wall_s": t_refresh,
        "full_wall_s": full_wall_sub,
        "refresh_speedup": full_wall_sub / max(t_refresh, 1e-9),
        "parity": bool(np.array_equal(inc_labels, labels_sub)),
        "purity": _purity(inc_labels, template_ids[:n_inc]),
        "clusters": state.n_clusters,
        "batch": inc_bs,
    }

    return {
        "n": n,
        "wall_s": t_mine,
        "embed_s": t_embed,
        "clusters": int(len(np.unique(labels))),
        "purity": purity,
        "incremental": inc,
    }


def _measure_reference(dim_corpus: int, n_queries: int, target_n: int) -> float:
    """Reference algorithm (TF-IDF refit per query) on this host, timed at
    ``dim_corpus`` rows and linearly extrapolated to ``target_n`` rows."""
    try:
        from sklearn.feature_extraction.text import TfidfVectorizer
        from sklearn.metrics.pairwise import cosine_similarity
    except ImportError:
        return float("nan")

    from kakveda_tpu.core.fingerprint import signature_text

    corpus = [
        signature_text(f"Summarize report {i} and include citations please", [], {"os": "linux"})
        for i in range(dim_corpus)
    ]
    queries = [
        signature_text(f"Explain paper {i} and add references", [], {"os": "linux"})
        for i in range(n_queries)
    ]

    lat = []
    for q in queries:
        t0 = time.perf_counter()
        vec = TfidfVectorizer(ngram_range=(1, 2), min_df=1)
        X = vec.fit_transform([q] + corpus)
        sims = cosine_similarity(X[0:1], X[1:]).flatten()
        top = np.argsort(-sims)[:5]
        assert top.shape == (5,)
        lat.append((time.perf_counter() - t0) * 1000.0)
    p50_small = float(np.percentile(lat, 50))
    return p50_small * (target_n / dim_corpus)


def _on_tpu(backend: str) -> bool:
    """Real TPU hardware — the tunneled chip may report 'tpu' or 'axon'."""
    return backend in ("tpu", "axon")


def _bench_pallas(backend: str) -> dict:
    """Pallas-vs-XLA A/B on the SAME inputs: compiles (not interpret mode,
    on TPU) the fused kNN kernel (ops/pallas_knn.py) and the int8-streaming
    flash attention (models/attention.py:flash_gqa_cache), times each
    against its XLA fallback with the slope method (two run lengths, so the
    tunneled chip's fixed dispatch RTT cancels), and checks result parity.

    This is the hardware proof VERDICT r4 asked for: interpret-mode CPU
    tests verify kernel semantics, but only this run proves Mosaic
    compilation, VMEM fit at production tiles, and the actual speedup.
    ``compiled: true`` in the output means the kernels ran through Mosaic.
    """
    import jax
    import jax.numpy as jnp

    from kakveda_tpu.models.attention import _gqa_xla, _pick_block, flash_gqa_cache
    from kakveda_tpu.models.llama import _kv_dequant, _kv_quant_rows
    from kakveda_tpu.ops.knn import ShardedKnn
    from kakveda_tpu.parallel.mesh import create_mesh

    on_tpu = _on_tpu(backend)
    interpret = not on_tpu  # CPU smoke exercises kernel logic via interpreter

    def slope_ms(f, args, iters=(4, 12) if on_tpu else (1, 2)):
        """Steady-state ms/call: (t[iters1] - t[iters0]) / (i1 - i0)."""
        out = f(*args)
        jax.block_until_ready(out)  # compile + warm
        times = []
        for it in iters:
            t0 = time.perf_counter()
            for _ in range(it):
                out = f(*args)
            jax.block_until_ready(out)
            times.append(time.perf_counter() - t0)
        return (times[1] - times[0]) / (iters[1] - iters[0]) * 1000.0

    # --- fused top-k kNN vs matmul + lax.top_k --------------------------
    n = int(os.environ.get("KAKVEDA_BENCH_PALLAS_N", 1_000_000 if on_tpu else 16_384))
    dim = int(os.environ.get("KAKVEDA_BENCH_PALLAS_DIM", 2048 if on_tpu else 256))
    B = int(os.environ.get("KAKVEDA_BENCH_BATCH", 64))
    mesh = create_mesh("data:-1")
    knn = ShardedKnn(mesh, capacity=n, dim=dim, k=5, use_pallas=True)
    knn._pallas_interpret = interpret
    emb, valid = knn.alloc()
    chunk = min(1 << 16, knn.capacity)

    @jax.jit
    def _fill(emb_buf, valid_buf, key, start):
        v = jax.random.normal(key, (chunk, dim), jnp.float32)
        v = v / jnp.linalg.norm(v, axis=1, keepdims=True)
        emb_buf = jax.lax.dynamic_update_slice(emb_buf, v.astype(emb_buf.dtype), (start, 0))
        valid_buf = jax.lax.dynamic_update_slice(valid_buf, jnp.ones((chunk,), jnp.bool_), (start,))
        return emb_buf, valid_buf

    key = jax.random.PRNGKey(0)
    for start in range(0, knn.capacity - chunk + 1, chunk):
        key, sub = jax.random.split(key)
        emb, valid = _fill(emb, valid, sub, start)
    q = np.random.default_rng(0).standard_normal((B, dim), np.float32)
    q /= np.linalg.norm(q, axis=1, keepdims=True)
    qd = jnp.asarray(q)

    impl = knn._topk_single_impl if knn.single_device else knn._topk_impl
    knn.use_pallas = True
    f_pallas = jax.jit(impl)
    r_pallas = np.asarray(f_pallas(emb, valid, qd))
    knn.use_pallas = False
    f_xla = jax.jit(impl)
    r_xla = np.asarray(f_xla(emb, valid, qd))
    knn.use_pallas = True
    k = knn.k
    knn_parity = bool(
        np.array_equal(r_pallas[:, k:], r_xla[:, k:])  # same row ids
        and np.allclose(r_pallas[:, :k], r_xla[:, :k], atol=2e-2)
    )
    knn_pallas_ms = slope_ms(f_pallas, (emb, valid, qd))
    knn_xla_ms = slope_ms(f_xla, (emb, valid, qd))
    del emb, valid
    print(
        f"bench[pallas]: knn {knn.capacity}x{dim} B={B} — pallas {knn_pallas_ms:.2f} ms "
        f"vs XLA {knn_xla_ms:.2f} ms (parity={knn_parity}, compiled={not interpret})",
        file=sys.stderr,
    )

    # --- int8-KV flash attention vs XLA dequant-up-front ----------------
    if on_tpu:
        fb, fs, fh, fkv, fd, fl = 16, 1, 32, 4, 64, 2048
    else:
        fb, fs, fh, fkv, fd, fl = 2, 1, 8, 2, 64, 128
    key = jax.random.PRNGKey(1)
    kq, kk, kv_ = jax.random.split(key, 3)
    qa = jax.random.normal(kq, (fb, fs, fh, fd), jnp.bfloat16)
    k_f = jax.random.normal(kk, (fb, fkv, fl, fd), jnp.float32)
    v_f = jax.random.normal(kv_, (fb, fkv, fl, fd), jnp.float32)
    k_i8, k_sc = _kv_quant_rows(k_f)
    v_i8, v_sc = _kv_quant_rows(v_f)
    pos0 = jnp.asarray(fl - fs, jnp.int32)
    kv_valid = jnp.ones((fb, fl), jnp.bool_)
    sr = -(-(fs * (fh // fkv)) // 8) * 8
    q_blk = _pick_block(sr, 512, 8)
    l_blk = _pick_block(fl, 512, 128)

    @jax.jit
    def f_flash(qa, k_i8, k_sc, v_i8, v_sc):
        return flash_gqa_cache(
            qa, k_i8, v_i8, pos0, kv_valid,
            k_scale=k_sc, v_scale=v_sc, q_blk=q_blk, l_blk=l_blk,
            interpret=interpret,
        )

    @jax.jit
    def f_xla_attn(qa, k_i8, k_sc, v_i8, v_sc):
        kd = _kv_dequant(k_i8, k_sc, qa.dtype)
        vd = _kv_dequant(v_i8, v_sc, qa.dtype)
        return _gqa_xla(qa, kd, vd, pos0, kv_valid)

    args = (qa, k_i8, k_sc, v_i8, v_sc)
    o_flash = np.asarray(f_flash(*args), np.float32)
    o_xla = np.asarray(f_xla_attn(*args), np.float32)
    flash_diff = float(np.max(np.abs(o_flash - o_xla)))
    flash_ms = slope_ms(f_flash, args)
    xla_attn_ms = slope_ms(f_xla_attn, args)
    print(
        f"bench[pallas]: int8 flash [{fb},{fkv},{fl},{fd}] — flash {flash_ms:.3f} ms "
        f"vs XLA {xla_attn_ms:.3f} ms (max|Δ|={flash_diff:.1e})",
        file=sys.stderr,
    )

    knn_speedup = knn_xla_ms / knn_pallas_ms if knn_pallas_ms > 0 else 0.0
    return {
        "metric": "pallas_knn_speedup_vs_xla",
        "value": round(knn_speedup, 2),
        "unit": "x",
        "vs_baseline": round(knn_speedup, 2),
        "compiled": not interpret,
        "knn": {
            "rows": knn.capacity, "dim": dim, "batch": B,
            "pallas_ms": round(knn_pallas_ms, 3), "xla_ms": round(knn_xla_ms, 3),
            "parity": knn_parity,
        },
        "flash_attn_int8": {
            "shape_bkld": [fb, fkv, fl, fd],
            "flash_ms": round(flash_ms, 4), "xla_ms": round(xla_attn_ms, 4),
            "speedup": round(xla_attn_ms / flash_ms, 2) if flash_ms > 0 else 0.0,
            "max_abs_diff": flash_diff,
        },
    }


def _bench_warn(backend: str) -> dict:
    default_n = 1_000_000 if _on_tpu(backend) else 100_000
    n = int(os.environ.get("KAKVEDA_BENCH_N", default_n))
    dim = int(os.environ.get("KAKVEDA_BENCH_DIM", 2048))
    n_queries = int(os.environ.get("KAKVEDA_BENCH_QUERIES", 64))

    print(f"bench[warn]: backend={backend} n={n} dim={dim} queries={n_queries}", file=sys.stderr)
    _ledger_reset()
    t0 = time.time()
    ours_p50 = _measure_ours(n, dim, n_queries)
    print(f"bench[warn]: ours p50={ours_p50:.3f} ms (setup+run {time.time() - t0:.0f}s)", file=sys.stderr)
    # Self-certifying (KAKVEDA_LEDGER=1): the measured loop ran entirely on
    # warm compiled programs — a post-warmup compile fails the metric.
    ledger_plane = _ledger_certify("bench[warn]")

    ref_p50 = _measure_reference(2000, min(10, n_queries), n)
    print(f"bench[warn]: reference (extrapolated) p50={ref_p50:.1f} ms", file=sys.stderr)

    vs = ref_p50 / ours_p50 if ours_p50 > 0 and np.isfinite(ref_p50) else 0.0
    out = {
        "metric": f"preflight_warn_p50_ms_at_{n}_gfkb",
        "value": round(ours_p50, 3),
        "unit": "ms",
        "vs_baseline": round(vs, 1),
    }
    if ledger_plane:
        out["ledger"] = ledger_plane
    return out


def _bench_ingest(backend: str) -> dict:
    n_traces = int(os.environ.get("KAKVEDA_BENCH_TRACES", 20_000))
    batch = int(os.environ.get("KAKVEDA_BENCH_INGEST_BATCH", 512))
    print(f"bench[ingest]: backend={backend} traces={n_traces} batch={batch}", file=sys.stderr)
    ours_tps, seq_tps, http_tps = _measure_ingest(n_traces, batch)
    print(
        f"bench[ingest]: batched {ours_tps:,.0f} traces/s | over HTTP "
        f"(POST /ingest/batch, real server) {http_tps:,.0f} traces/s | per-trace "
        f"(reference model, no HTTP hops) {seq_tps:,.0f} traces/s",
        file=sys.stderr,
    )
    return {
        "metric": "ingest_throughput_traces_per_sec",
        "value": round(ours_tps, 1),
        "unit": "traces/sec",
        "vs_baseline": round(ours_tps / seq_tps, 1) if seq_tps > 0 else 0.0,
        "http_tps": round(http_tps, 1),
    }


def _bench_decode(backend: str) -> dict:
    preset = os.environ.get("KAKVEDA_BENCH_DECODE_PRESET", "1b" if _on_tpu(backend) else "tiny")
    bsz = int(os.environ.get("KAKVEDA_BENCH_DECODE_BATCH", 16))
    steps = int(os.environ.get("KAKVEDA_BENCH_DECODE_STEPS", 128))
    print(f"bench[decode]: backend={backend} preset={preset} batch={bsz} steps={steps}", file=sys.stderr)
    r = _measure_decode(preset, bsz, steps)
    curve_s = " ".join(f"b{b}={v:,.0f}" for b, v in sorted(r["curve"].items()))
    int8_s = (
        " | int8 " + " ".join(f"b{b}={v:,.0f}" for b, v in sorted(r["int8_curve"].items()))
        if r["int8_curve"] else ""
    )
    kv8_s = (
        " | kv8 " + " ".join(f"b{b}={v:,.0f}" for b, v in sorted(r["kv8_tps"].items()))
        if r["kv8_tps"] else ""
    )
    util_s = " ".join(f"{k}={v*100:.0f}%" for k, v in r["hbm_util"].items())
    print(
        f"bench[decode]: {r['n_params']/1e9:.2f}B params on {r['device_kind']} "
        f"(peak {r['peak_tflops']:.0f} bf16 TFLOP/s, {r['peak_hbm_gbps']:.0f} GB/s HBM assumed) — "
        f"decode {r['decode_tps']:,.0f} tok/s @batch {r['batch']} (MFU {r['mfu']*100:.1f}%), "
        f"prefill {r['prefill_tps']:,.0f} tok/s (MFU {r['prefill_mfu']*100:.1f}%), "
        f"unbatched {r['solo_tps']:,.0f} tok/s, curve {curve_s}{int8_s}{kv8_s} "
        f"| HBM roofline {util_s}",
        file=sys.stderr,
    )
    out = {
        "metric": f"decode_tokens_per_sec_{preset}_b{bsz}",
        "value": round(r["decode_tps"], 1),
        "unit": "tokens/sec",
        "vs_baseline": round(r["decode_tps"] / r["solo_tps"], 1) if r["solo_tps"] > 0 else 0.0,
        "mfu": round(r["mfu"], 4),
        "hbm_util": {k: round(v, 3) for k, v in r["hbm_util"].items()},
        "prefill_tokens_per_sec": round(r["prefill_tps"], 1),
        "prefill_mfu": round(r["prefill_mfu"], 4),
        "decode_tps_curve": {str(b): round(v, 1) for b, v in sorted(r["curve"].items())},
    }
    if r["int8_curve"]:
        out["int8_decode_tps"] = round(r["int8_tps"], 1)
        out["int8_decode_tps_curve"] = {str(b): round(v, 1) for b, v in sorted(r["int8_curve"].items())}
    if r["kv8_tps"]:
        out["kv8_decode_tps_curve"] = {str(b): round(v, 1) for b, v in sorted(r["kv8_tps"].items())}
    return out


def _bench_mixed(backend: str) -> dict:
    n = int(os.environ.get("KAKVEDA_BENCH_MIXED_N", 1 << 15))
    dim = int(os.environ.get("KAKVEDA_BENCH_DIM", 2048))
    print(f"bench[mixed]: backend={backend} n={n} dim={dim}", file=sys.stderr)
    r = _measure_mixed(n, dim)
    print(
        f"bench[mixed]: warn p50 idle {r['idle_p50_ms']:.3f} ms vs under-ingest "
        f"{r['loaded_p50_ms']:.3f} ms",
        file=sys.stderr,
    )
    return {
        "metric": "warn_p50_ms_under_concurrent_ingest",
        "value": round(r["loaded_p50_ms"], 3),
        "unit": "ms",
        "vs_baseline": round(r["idle_p50_ms"] / r["loaded_p50_ms"], 2)
        if r["loaded_p50_ms"] > 0
        else 0.0,
        "idle_p50_ms": round(r["idle_p50_ms"], 3),
    }


def _bench_mixed_decode(backend: str) -> dict:
    n = int(os.environ.get("KAKVEDA_BENCH_MIXED_N", 1 << 20 if _on_tpu(backend) else 1 << 14))
    dim = int(os.environ.get("KAKVEDA_BENCH_DIM", 2048))
    preset = os.environ.get("KAKVEDA_BENCH_DECODE_PRESET", "1b" if _on_tpu(backend) else "tiny")
    chunk_steps = int(os.environ.get("KAKVEDA_BENCH_CHUNK_STEPS", 8))
    print(
        f"bench[mixed-decode]: backend={backend} n={n} dim={dim} preset={preset} chunk={chunk_steps}",
        file=sys.stderr,
    )
    r = _measure_mixed_decode(n, dim, preset, chunk_steps)
    print(
        f"bench[mixed-decode]: warn p50 idle {r['idle_p50_ms']:.3f} ms vs under-decode "
        f"{r['loaded_p50_ms']:.3f} ms (storm {r['storm_decode_tps']:,.0f} tok/s, "
        f"chunks of {r['chunk_steps']} steps)",
        file=sys.stderr,
    )
    return {
        "metric": f"warn_p50_ms_under_decode_at_{n}_gfkb",
        "value": round(r["loaded_p50_ms"], 3),
        "unit": "ms",
        "vs_baseline": round(r["idle_p50_ms"] / r["loaded_p50_ms"], 2)
        if r["loaded_p50_ms"] > 0
        else 0.0,
        "idle_p50_ms": round(r["idle_p50_ms"], 3),
        "storm_decode_tps": round(r["storm_decode_tps"], 1),
    }


def _bus_dlq_count() -> int:
    """Process-cumulative dead-lettered event count off the metrics plane
    (kakveda_bus_dlq_total) — folded into the serve metric so a chaos'd
    bench line carries its own DLQ evidence."""
    from kakveda_tpu.core import metrics as _metrics

    fam = _metrics.get_registry().snapshot().get("kakveda_bus_dlq_total", {})
    return int(sum(v for v in fam.get("series", {}).values() if isinstance(v, (int, float))))


def _bench_serve(backend: str) -> dict:
    """Concurrent-HTTP serving SLOs: N separate logged-in clients drive
    playground generation through a REAL aiohttp dashboard server (all
    decodes share one ServingEngine, continuous batching) while a warn
    stream hits the service API — the mixed workload a deployment actually
    sees. Reports request p50/p95, aggregate decode tok/s, and warn p95
    under load. The reference can't exercise this: its playground and eval
    loops are strictly sequential HTTP calls to Ollama
    (services/dashboard/app.py:3127-3299, 2315-2393).

    vs_baseline = concurrency speedup: sum of request latencies (what a
    sequential server would take) / measured wall."""
    import asyncio
    import tempfile
    from pathlib import Path

    from aiohttp.test_utils import TestClient, TestServer

    from kakveda_tpu.dashboard.app import make_dashboard_app
    from kakveda_tpu.models.generate import LlamaRuntime
    from kakveda_tpu.platform import Platform
    from kakveda_tpu.service.app import make_app as make_service_app

    preset = os.environ.get("KAKVEDA_BENCH_DECODE_PRESET", "1b" if _on_tpu(backend) else "tiny")
    n_clients = int(os.environ.get("KAKVEDA_BENCH_SERVE_CLIENTS", 16))
    reqs_per = int(os.environ.get("KAKVEDA_BENCH_SERVE_REQS", 2))
    import jax
    import jax.numpy as jnp

    from kakveda_tpu.models.llama import init_params

    cfg = _preset_cfg(preset)
    # bf16 weights, like the decode bench: serving streams weights every
    # step, and f32 random-init params would double that stream.
    params = jax.tree.map(
        lambda x: x.astype(jnp.bfloat16), init_params(jax.random.PRNGKey(0), cfg)
    )

    rng = np.random.default_rng(0)
    prompts = [
        "Review failure report %d: %s" % (i, " ".join(
            str(w) for w in rng.integers(0, 999, size=12)
        ))
        for i in range(n_clients)
    ]

    def run_workload(pipeline: str) -> dict:
        """One full concurrent-HTTP round at the given pipelining setting
        (fresh runtime + apps, so the engine thread reads the env)."""
        os.environ["KAKVEDA_SERVE_PIPELINE"] = pipeline
        # The login limiter is process-global and keyed by peer IP: inside
        # the full sweep, this metric's 2×n_clients logins (all 127.0.0.1)
        # cross the 20/60s window and every later login bounces — which
        # zeroed the metric with a bare AssertionError. Fresh window per
        # workload, exactly like tests/test_dashboard.py's fixture.
        from kakveda_tpu.dashboard.core import RATE_LIMITER

        RATE_LIMITER._hits.clear()
        ledger_live = _ledger_reset()
        rt = LlamaRuntime(cfg=cfg, params=params, seed=0)
        tmp = Path(tempfile.mkdtemp(prefix="kakveda-bench-serve-"))
        plat = Platform(data_dir=tmp / "data", capacity=1 << 14, dim=2048)
        dash = make_dashboard_app(platform=plat, db_path=tmp / "dash.db", model=rt)
        svc = make_service_app(platform=plat)
        lat_play: list = []
        lat_warn: list = []
        lat_ttft: list = []
        stop = asyncio.Event()

        async def go():
            server = TestServer(dash)
            await server.start_server()
            svc_server = TestServer(svc)
            await svc_server.start_server()
            clients = [TestClient(server) for _ in range(n_clients)]
            svc_client = TestClient(svc_server)
            t_wall = 0.0
            try:
                for c in clients:
                    await c.start_server()
                    r = await c.post(
                        "/login",
                        data={"email": "admin@local", "password": "admin123", "next": "/"},
                        allow_redirects=False,
                    )
                    assert r.status == 302
                await svc_client.start_server()
                # Warm both compiled paths off-clock (engine decode + warn match).
                await clients[0].post(
                    "/playground/run", data={"prompt": "warm up", "target": "model"}
                )
                await svc_client.post("/warn", json={"app_id": "warm", "prompt": "warm"})
                if ledger_live:
                    # Ledger window: run every benchmark prompt once
                    # off-clock so ALL admit buckets / prefill widths are
                    # compiled, then draw the warm line — the measured
                    # workload below must compile NOTHING (certified after
                    # the run; a violation fails the metric).
                    for c, p in zip(clients, prompts):
                        await c.post(
                            "/playground/run", data={"prompt": p, "target": "model"}
                        )
                    _ledger_mark_warm()

                async def play_worker(client, prompt):
                    for _ in range(reqs_per):
                        t0 = time.perf_counter()
                        r = await client.post(
                            "/playground/run", data={"prompt": prompt, "target": "model"}
                        )
                        await r.text()
                        lat_play.append(time.perf_counter() - t0)
                        assert r.status == 200

                async def warn_worker():
                    i = 0
                    while not stop.is_set():
                        t0 = time.perf_counter()
                        r = await svc_client.post(
                            "/warn",
                            json={"app_id": "bench", "prompt": f"Cite sources for claim {i}."},
                        )
                        await r.json()
                        lat_warn.append(time.perf_counter() - t0)
                        assert r.status == 200
                        i += 1
                        await asyncio.sleep(0.02)

                wt = asyncio.create_task(warn_worker())
                t0 = time.perf_counter()
                await asyncio.gather(*(play_worker(c, p) for c, p in zip(clients, prompts)))
                t_wall = time.perf_counter() - t0
                stop.set()
                await wt
                # TTFT via the SSE endpoint: time from POST to the first
                # delta event (streaming makes this a real SLO — the
                # blocking path's first byte IS the last byte).
                for p in prompts[:4]:
                    ts = time.perf_counter()
                    r = await clients[0].post(
                        "/playground/stream", data={"prompt": p, "target": "model"}
                    )
                    async for _chunk in r.content.iter_any():
                        lat_ttft.append(time.perf_counter() - ts)
                        break
                    await r.release()
            finally:
                for c in clients:
                    await c.close()
                await svc_client.close()
            return t_wall

        wall = asyncio.run(go())
        # Self-certifying (KAKVEDA_LEDGER=1): the measured workload ran on
        # warm compiled programs only — zero post-warmup compiles.
        ledger_plane = _ledger_certify(f"bench[serve] pipeline={pipeline}")
        completed = restarts = 0
        if rt._engine is not None:
            est = rt._engine.stats()
            completed = est["completed"]
            restarts = est.get("restarts", 0)
            rt._engine.close()
        p50, p95 = (float(x) for x in np.percentile(lat_play, [50, 95]))
        return {
            "wall": wall,
            "p50": p50,
            "p95": p95,
            "p95_warn": float(np.percentile(lat_warn, 95)) if lat_warn else 0.0,
            "n_warns": len(lat_warn),
            "n_reqs": len(lat_play),
            "seq_est": float(np.sum(lat_play)),
            "completed": completed,
            "restarts": restarts,
            "ttft_p50": float(np.percentile(lat_ttft, 50)) if lat_ttft else 0.0,
            "ledger": ledger_plane,
        }

    prev_env = os.environ.get("KAKVEDA_SERVE_PIPELINE")
    prev_spec = os.environ.get("KAKVEDA_SERVE_SPEC")
    spec_arm = None
    try:
        # A/B the chunk-pipelining lever (dispatch chunk i+1 before fetching
        # chunk i — hides the per-chunk fetch RTT, the dominant per-chunk
        # cost on remote-attached chips). Unpipelined first so the
        # pipelined run (the headline) runs on the warmer process.
        base = run_workload("0")
        piped = run_workload("1")
        if _on_tpu(backend):
            # Third arm, hardware only: speculative verify chunks over the
            # same HTTP workload. Decode is weight-bound on TPU, so the
            # k+1-wide verify is where acceptance becomes throughput; on
            # CPU the arm would just burn sweep minutes re-measuring
            # compute-bound behavior the spec metric already reports.
            os.environ["KAKVEDA_SERVE_SPEC"] = "8"
            spec_arm = run_workload("1")
    finally:
        if prev_env is None:
            os.environ.pop("KAKVEDA_SERVE_PIPELINE", None)
        else:
            os.environ["KAKVEDA_SERVE_PIPELINE"] = prev_env
        if prev_spec is None:
            os.environ.pop("KAKVEDA_SERVE_SPEC", None)
        else:
            os.environ["KAKVEDA_SERVE_SPEC"] = prev_spec

    r = piped
    tok_s = r["n_reqs"] * 64 / r["wall"] if r["wall"] > 0 else 0.0  # generate() default max_tokens
    print(
        f"bench[serve]: {n_clients} clients × {reqs_per} reqs ({preset}) — "
        f"p50 {r['p50']*1000:.0f} ms, p95 {r['p95']*1000:.0f} ms, {tok_s:,.0f} tok/s agg, "
        f"warn p95 under load {r['p95_warn']*1000:.1f} ms ({r['n_warns']} warns), "
        f"concurrency speedup {r['seq_est']/r['wall']:.1f}x | unpipelined p95 "
        f"{base['p95']*1000:.0f} ms (pipeline gain {base['p95']/max(r['p95'],1e-9):.2f}x)",
        file=sys.stderr,
    )
    return {
        "metric": "serve_http_p95_ms_concurrent",
        "value": round(r["p95"] * 1000, 1),
        "unit": "ms",
        "vs_baseline": round(r["seq_est"] / r["wall"], 2) if r["wall"] > 0 else 0.0,
        "clients": n_clients,
        "requests": r["n_reqs"],
        "p50_ms": round(r["p50"] * 1000, 1),
        "agg_tokens_per_sec": round(tok_s, 1),
        "warn_p95_ms_under_load": round(r["p95_warn"] * 1000, 2),
        "engine_completed": r["completed"],
        # Robustness plane: zero in a healthy run — nonzero restarts or
        # dead-lettered events mean the workload survived real failures
        # (or a KAKVEDA_FAULTS chaos arm was active for this sweep).
        "engine_restarts": base["restarts"] + r["restarts"],
        "dlq_events": _bus_dlq_count(),
        # Overload plane (process-cumulative, like dlq_events): zero in a
        # healthy un-flooded run; nonzero means admission shed requests /
        # the brownout ladder moved during this process.
        "shed_total": _admission_shed_count(),
        "brownout_transitions": _brownout_transition_count(),
        "preset": preset,
        "unpipelined_p95_ms": round(base["p95"] * 1000, 1),
        "pipeline_p95_gain": round(base["p95"] / max(r["p95"], 1e-9), 2),
        "stream_ttft_p50_ms": round(r["ttft_p50"] * 1000, 1),
        # Certified by _ledger_certify inside run_workload: the headline
        # (pipelined) workload saw zero post-warmup XLA compiles.
        **({"ledger": r["ledger"]} if r.get("ledger") else {}),
        **(
            {
                "spec_p95_ms": round(spec_arm["p95"] * 1000, 1),
                "spec_p95_gain": round(r["p95"] / max(spec_arm["p95"], 1e-9), 2),
            }
            if spec_arm is not None
            else {}
        ),
    }


def _admission_shed_count() -> int:
    """Process-cumulative shed/429 count off the metrics plane
    (kakveda_admission_shed_total) — folded into the serve row so a bench
    line carries its own overload evidence, like dlq_events."""
    from kakveda_tpu.core import metrics as _metrics

    fam = _metrics.get_registry().snapshot().get("kakveda_admission_shed_total", {})
    return int(sum(v for v in fam.get("series", {}).values() if isinstance(v, (int, float))))


def _brownout_transition_count() -> int:
    from kakveda_tpu.core import metrics as _metrics

    fam = _metrics.get_registry().snapshot().get(
        "kakveda_brownout_transitions_total", {}
    )
    return int(sum(v for v in fam.get("series", {}).values() if isinstance(v, (int, float))))


def _bench_overload(backend: str) -> dict:
    """Overload-protection SLO: drive the service HTTP tier PAST capacity
    and prove that shedding — not queueing — absorbs the excess. Two
    phases against one live aiohttp server with deliberately small
    admission bounds: (1) unloaded warn p95 baseline; (2) saturation —
    ingest floods pinned past their class bound plus a warn storm wider
    than the warn bound — measuring admitted-warn p95 WHILE saturated,
    the shed/429 counts per class, and the brownout ladder's time-in-state
    occupancy. The acceptance bar: saturated warn p95 ≤ 2× unloaded (the
    queue never grows past what drains) with shed counters > 0 (the
    excess went to cheap 429s, not to timeouts). The reference has no
    admission control anywhere — overload just times out every caller."""
    import asyncio
    import tempfile
    from pathlib import Path

    from aiohttp.test_utils import TestClient, TestServer

    from kakveda_tpu.core import admission as _adm
    from kakveda_tpu.platform import Platform
    from kakveda_tpu.service.app import make_app as make_service_app

    n_warn_clients = int(os.environ.get("KAKVEDA_BENCH_OVERLOAD_CLIENTS", 8))
    n_ingest_clients = 4
    duration = float(os.environ.get("KAKVEDA_BENCH_OVERLOAD_DUR", 8.0))

    # Private controller (the global one must stay clean for the serve
    # metric): small bounds so a laptop-sized flood genuinely saturates,
    # fast brownout dwell so the ladder is observable within the window.
    # ingest=1: the admitted ingest stream still burns real embed+insert
    # compute (sharing the GIL and the GFKB data lock with warn matches),
    # so the bound is what keeps warn's latency bounded — everything past
    # it is the excess that must shed.
    brown = _adm.BrownoutController(
        enabled=True, enter=0.85, exit=0.5, dwell_s=0.25,
    )
    adm = _adm.AdmissionController(
        limits={"warn": 16, "ingest": 1, "interactive": 8, "background": 1},
        enabled=True, brownout=brown,
    )

    tmp = Path(tempfile.mkdtemp(prefix="kakveda-bench-overload-"))
    plat = Platform(data_dir=tmp / "data", capacity=1 << 12, dim=1024)
    svc = make_service_app(platform=plat, admission=adm)

    def _trace(i: int) -> dict:
        return {
            "trace_id": f"ov-{i}",
            "ts": time.time(),
            "app_id": f"app-{i % 4}",
            "prompt": "Cite sources for claim %d even if unavailable." % i,
            "response": "According to [Smith 2020] (fabricated).",
            "tools": [],
            "env": {"os": "linux"},
        }

    # Pre-serialized flood payloads: the load generator shares ONE event
    # loop (and GIL) with the server under test, so per-attempt payload
    # construction would pollute the latency being measured. 64 distinct
    # batches cycle so ingest still sees fresh signatures.
    _hdr = {"Content-Type": "application/json"}
    ingest_bodies = [
        json.dumps(
            {"traces": [_trace(b * 10_000 + k) for k in range(32)]}
        ).encode()
        for b in range(64)
    ]
    warn_bodies = [
        json.dumps(
            {"app_id": f"w{i % 8}", "prompt": f"Cite sources for claim {i}."}
        ).encode()
        for i in range(256)
    ]

    lat_solo: list = []
    lat_unloaded: list = []
    lat_saturated: list = []
    status_counts = {"warn_200": 0, "warn_429": 0, "ingest_200": 0, "ingest_429": 0}

    async def go():
        server = TestServer(svc)
        await server.start_server()
        client = TestClient(server)
        await client.start_server()
        try:
            # Warm the compiled match path off-clock.
            for i in range(4):
                await client.post("/warn", json={"app_id": "warm", "prompt": f"warm {i}"})
            # Solo reference: one sequential client, no concurrency at all
            # (context for the report; the ratio uses the like-for-like
            # storm baseline below).
            for i in range(50):
                t0 = time.perf_counter()
                r = await client.post(
                    "/warn", json={"app_id": "base", "prompt": f"Cite sources for claim {i}."}
                )
                await r.json()
                assert r.status == 200
                lat_solo.append(time.perf_counter() - t0)

            stop = asyncio.Event()

            async def ingest_flooder(wid: int):
                i = wid
                while not stop.is_set():
                    r = await client.post(
                        "/ingest/batch",
                        data=ingest_bodies[i % len(ingest_bodies)], headers=_hdr,
                    )
                    await r.read()
                    status_counts["ingest_200" if r.status == 200 else "ingest_429"] += 1
                    if r.status == 429:
                        # Back off a token 50 ms on a shed — far below the
                        # Retry-After hint (so the class stays saturated
                        # the whole window) but not a zero-delay hammer:
                        # the load generator shares this host's core(s)
                        # with the server, and a spin-flood would measure
                        # raw HTTP parse cost, not admission control.
                        await asyncio.sleep(0.05)
                    i += 1

            async def warn_flooder(wid: int, sink: list):
                i = wid
                while not stop.is_set():
                    t0 = time.perf_counter()
                    r = await client.post(
                        "/warn",
                        data=warn_bodies[i % len(warn_bodies)], headers=_hdr,
                    )
                    await r.read()
                    if r.status == 200:
                        status_counts["warn_200"] += 1
                        sink.append(time.perf_counter() - t0)
                    else:
                        status_counts["warn_429"] += 1
                        await asyncio.sleep(0.001)
                    i += 1

            async def ingest_steady():
                # ONE polite client — exactly the admitted ingest
                # concurrency. Present in BOTH phases: the admitted
                # stream is the platform's steady state, not overload.
                i = 0
                while not stop.is_set():
                    r = await client.post(
                        "/ingest/batch",
                        data=ingest_bodies[i % len(ingest_bodies)], headers=_hdr,
                    )
                    await r.read()
                    status_counts["ingest_200" if r.status == 200 else "ingest_429"] += 1
                    i += 1

            # Phase 1 — the AT-CAPACITY workload: the full warn storm plus
            # the one admitted ingest stream, nothing shed. Its p95 is the
            # like-for-like baseline the overloaded phase is held to
            # (≤ 2×): what the flood may NOT do is degrade the work the
            # platform already admitted.
            tasks = [
                asyncio.create_task(warn_flooder(w, lat_unloaded))
                for w in range(n_warn_clients)
            ] + [asyncio.create_task(ingest_steady())]
            await asyncio.sleep(duration / 2)
            stop.set()
            await asyncio.gather(*tasks)

            # Phase 2 — same storm PLUS ingest floods driven past the
            # ingest class bound: the excess must shed as 429s while the
            # admitted warn stream stays within 2× of phase 1.
            stop.clear()
            tasks = [
                asyncio.create_task(ingest_flooder(w)) for w in range(n_ingest_clients)
            ] + [
                asyncio.create_task(warn_flooder(w, lat_saturated))
                for w in range(n_warn_clients)
            ]
            await asyncio.sleep(duration)
            stop.set()
            await asyncio.gather(*tasks)
        finally:
            await client.close()
    asyncio.run(go())

    p95_solo = float(np.percentile(lat_solo, 95))
    p95_base = float(np.percentile(lat_unloaded, 95)) if lat_unloaded else 0.0
    p95_sat = float(np.percentile(lat_saturated, 95)) if lat_saturated else 0.0
    ratio = p95_sat / p95_base if p95_base > 0 else 0.0
    sheds = adm.shed_counts()
    shed_total = int(sum(sheds.values()))
    occ = brown.occupancy()
    occ_pct = {
        s: round(100.0 * v / max(1e-9, sum(occ.values())), 1) for s, v in occ.items()
    }
    print(
        f"bench[overload]: warn p95 {p95_base*1000:.1f} ms at-capacity -> "
        f"{p95_sat*1000:.1f} ms saturated ({ratio:.2f}x; solo ref "
        f"{p95_solo*1000:.1f} ms) over {duration:.0f}s; "
        f"{status_counts['warn_200']} warns served, "
        f"{shed_total} shed ({status_counts['warn_429']} warn 429s, "
        f"{status_counts['ingest_429']} ingest 429s); brownout occupancy "
        f"{ {k: v for k, v in occ_pct.items() if v > 0} }",
        file=sys.stderr,
    )
    # Self-certifying, like the mine metric: bounded-latency-while-shedding
    # IS the result. A saturated p95 that blew past 2× unloaded means the
    # queue absorbed the excess (the failure mode this layer removes), and
    # zero sheds means the server was never actually saturated.
    max_ratio = float(os.environ.get("KAKVEDA_BENCH_OVERLOAD_MAX_RATIO", 2.0))
    if shed_total == 0:
        raise AssertionError(
            "overload bench never shed a request — the flood did not "
            "saturate the admission bounds; latency bound not demonstrated"
        )
    if ratio > max_ratio:
        raise AssertionError(
            f"warn p95 under overload is {ratio:.2f}x its unloaded value "
            f"(bound {max_ratio}x) — queueing, not shedding, absorbed the excess"
        )
    return {
        "metric": "overload_warn_p95_ms_saturated",
        "value": round(p95_sat * 1000, 2),
        "unit": "ms",
        # Ratio vs unloaded: the acceptance bound is <= 2.0 (bounded
        # latency while saturated), enforced above.
        "vs_baseline": round(ratio, 2),
        "warn_p95_ms_unloaded": round(p95_base * 1000, 2),
        "warn_p95_ms_solo": round(p95_solo * 1000, 2),
        "warns_served_saturated": status_counts["warn_200"],
        "warn_429": status_counts["warn_429"],
        "ingest_429": status_counts["ingest_429"],
        "shed_total": shed_total,
        "shed_by_class": {k: int(v) for k, v in sheds.items()},
        "brownout_occupancy_pct": occ_pct,
        "brownout_transitions": _brownout_transition_count(),
        "duration_s": duration,
    }


def _bench_fleet(backend: str) -> dict:
    """Replica-fleet scale-out A/B (docs/scale-out.md): aggregate warn
    throughput through the front router at 1 vs N replicas, plus router
    added-latency vs hitting a replica directly, shard balance and
    hot-key skew.

    The replicas' per-process bottleneck is pinned to the DISPATCH RTT,
    not CPU: every replica runs with ``KAKVEDA_WARN_RTT_EMU_MS`` (default
    160 ms — one dispatch + one fetch at the ~80 ms wire RTT of the
    tunneled TPU this platform actually serves from, CLAUDE.md) so each
    micro-batched device call blocks one round trip exactly like a remote
    dispatch/fetch does, releasing the GIL/CPU while it waits. That is the regime horizontal scale-out
    exists for — per-replica throughput is capped at
    max_batch/RTT regardless of host cores — and the only regime a
    1-core bench host can honestly demonstrate scaling in (N CPU-bound
    replicas on one core aggregate to 1x by construction). The emulation
    is declared in the JSON row (``rtt_emulated_ms``); on real hardware
    the wire provides it and the knob stays 0.

    Self-certifying: aggregate throughput at N replicas must reach
    ``KAKVEDA_BENCH_FLEET_MIN_RATIO`` (default 2.5x) of the single-replica
    arm with ZERO failed warns in either arm, or the bench raises."""
    import asyncio
    import tempfile
    from pathlib import Path

    import yaml
    from aiohttp.test_utils import TestClient, TestServer

    from kakveda_tpu.core import metrics as _metrics
    from kakveda_tpu.fleet.router import make_router_app
    from kakveda_tpu.fleet.supervisor import FleetSupervisor, pick_port_base

    n_replicas = int(os.environ.get("KAKVEDA_BENCH_FLEET_REPLICAS", 4))
    rtt_ms = float(os.environ.get("KAKVEDA_BENCH_FLEET_RTT_MS", 160))
    n_clients = int(os.environ.get("KAKVEDA_BENCH_FLEET_CLIENTS", 48))
    duration = float(os.environ.get("KAKVEDA_BENCH_FLEET_DUR", 8.0))
    min_ratio = float(os.environ.get("KAKVEDA_BENCH_FLEET_MIN_RATIO", 2.5))

    tmp = Path(tempfile.mkdtemp(prefix="kakveda-bench-fleet-"))
    cfg = tmp / "config.yaml"
    cfg.write_text(yaml.safe_dump({
        "failure_matching": {
            "similarity_threshold": 0.8, "embedding_dim": 512, "top_k": 5,
        },
    }))
    replica_env = {
        "JAX_PLATFORMS": "cpu" if not _on_tpu(backend) else "",
        "KAKVEDA_CONFIG_PATH": str(cfg),
        "KAKVEDA_INDEX_CAPACITY": "2048",
        "KAKVEDA_WARN_RTT_EMU_MS": str(rtt_ms),
        # Small per-call batches keep each replica RTT-bound (the regime
        # under test); per-request INFO logging is CPU the shared-core
        # load generator needs.
        "KAKVEDA_WARN_MAX_BATCH": "4",
        "KAKVEDA_LOG_LEVEL": "WARNING",
        "KAKVEDA_GC_TUNE": "0",
    }
    replica_env = {k: v for k, v in replica_env.items() if v != ""}

    def _shard_series() -> dict:
        fam = _metrics.get_registry().snapshot().get(
            "kakveda_fleet_shard_load_total", {}
        )
        return dict(fam.get("series", {}))

    def run_arm(n: int) -> dict:
        import httpx

        root = tmp / f"arm-{n}"
        sup = FleetSupervisor(
            root, port_base=pick_port_base(n), replicas=n, env=replica_env,
        )
        sup.start_all()
        lat_direct: list = []
        lat_routed: list = []
        counts = {"ok": 0, "shed": 0, "failed": 0}
        shard_before = _shard_series()

        async def go():
            router_app = make_router_app(
                sup.backend_map(), probe_interval_s=1.0, eject_fails=3,
                retries=min(2, n - 1) if n > 1 else 0, timeout_s=20.0,
            )
            rc = TestClient(TestServer(router_app))
            await rc.start_server()
            try:
                # Seed the corpus through the router; replication converges
                # every replica before any measurement.
                traces = [
                    {
                        "trace_id": f"fl-{i}",
                        "ts": time.time(),
                        "app_id": f"app-{i % 8}",
                        "prompt": f"Cite sources for claim {i} even if unavailable.",
                        "response": "See [1].\n\nReferences:\n[1] Smith (2020).",
                        "tools": [], "env": {"os": "linux"},
                    }
                    for i in range(32)
                ]
                r = await rc.post("/ingest/batch", json={"traces": traces})
                assert r.status == 200, await r.text()
                loop = asyncio.get_running_loop()
                for u in sup.urls():
                    for _ in range(80):
                        body = await loop.run_in_executor(
                            None, lambda u=u: httpx.get(u + "/readyz", timeout=5).json()
                        )
                        if body["gfkb_count"] > 0:
                            break
                        await asyncio.sleep(0.25)

                async def warm_and_time(post, sink, reps):
                    for i in range(reps):
                        t0 = time.perf_counter()
                        rr = await post(i)
                        await rr.read() if hasattr(rr, "read") else None
                        sink.append(time.perf_counter() - t0)

                # Router added latency vs direct: sequential probes of the
                # same replica, unloaded.
                async with httpx.AsyncClient() as hc:
                    async def direct(i):
                        return await hc.post(
                            sup.url(0) + "/warn",
                            json={"app_id": "lat", "prompt": f"Cite sources for claim {i}."},
                            timeout=20.0,
                        )

                    await direct(0)  # warm the compiled match path
                    for i in range(20):
                        t0 = time.perf_counter()
                        await direct(i)
                        lat_direct.append(time.perf_counter() - t0)
                for i in range(20):
                    t0 = time.perf_counter()
                    r = await rc.post(
                        "/warn",
                        json={"app_id": "lat", "prompt": f"Cite sources for claim {i}."},
                    )
                    await r.read()
                    lat_routed.append(time.perf_counter() - t0)

                # Aggregate throughput: closed-loop clients, one app key
                # each (the production shape — a client IS an app), so
                # every shard's batch pipeline stays saturated instead of
                # every client stalling on the momentarily-slowest shard.
                stop = asyncio.Event()

                async def client_loop(wid: int):
                    i = 0
                    while not stop.is_set():
                        r = await rc.post("/warn", json={
                            "app_id": f"app-{wid}",
                            "prompt": f"Cite sources for claim {wid}-{i}.",
                        })
                        await r.read()
                        if r.status == 200:
                            counts["ok"] += 1
                        elif r.status == 429:
                            counts["shed"] += 1
                        else:
                            counts["failed"] += 1
                        i += 1

                tasks = [asyncio.create_task(client_loop(w)) for w in range(n_clients)]
                t0 = time.perf_counter()
                await asyncio.sleep(duration)
                stop.set()
                await asyncio.gather(*tasks)
                return time.perf_counter() - t0
            finally:
                await rc.close()

        try:
            sup.wait_ready(timeout_s=300.0)
            wall = asyncio.run(go())
        finally:
            sup.stop_all()
        shard_after = _shard_series()
        shards = {}
        for label, v in shard_after.items():
            delta = v - shard_before.get(label, 0)
            if delta > 0:
                shards[label] = int(delta)
        return {
            "replicas": n,
            "rate": counts["ok"] / wall,
            "counts": dict(counts),
            "wall_s": wall,
            "warn_p50_direct_ms": float(np.percentile(lat_direct, 50)) * 1e3,
            "warn_p50_routed_ms": float(np.percentile(lat_routed, 50)) * 1e3,
            "warn_p95_direct_ms": float(np.percentile(lat_direct, 95)) * 1e3,
            "warn_p95_routed_ms": float(np.percentile(lat_routed, 95)) * 1e3,
            "shard_load": shards,
        }

    one = run_arm(1)
    many = run_arm(n_replicas)
    ratio = many["rate"] / one["rate"] if one["rate"] > 0 else 0.0
    loads = list(many["shard_load"].values())
    balance = (min(loads) / max(loads)) if loads and max(loads) > 0 else 0.0
    hot_fam = _metrics.get_registry().snapshot().get(
        "kakveda_fleet_hot_key_share", {}
    )
    hot_share = max(
        (v for v in hot_fam.get("series", {}).values() if isinstance(v, (int, float))),
        default=0.0,
    )
    added_p50 = many["warn_p50_routed_ms"] - many["warn_p50_direct_ms"]
    added_p95 = many["warn_p95_routed_ms"] - many["warn_p95_direct_ms"]
    print(
        f"bench[fleet]: aggregate warn {one['rate']:.0f}/s @1 -> "
        f"{many['rate']:.0f}/s @{n_replicas} ({ratio:.2f}x; bound {min_ratio}x); "
        f"router added p50 {added_p50:+.1f} ms p95 {added_p95:+.1f} ms; "
        f"shard balance min/max {balance:.2f} {many['shard_load']}; "
        f"rtt emulated {rtt_ms:.0f} ms",
        file=sys.stderr,
    )
    for arm in (one, many):
        if arm["counts"]["failed"]:
            raise AssertionError(
                f"fleet bench lost {arm['counts']['failed']} warns at "
                f"{arm['replicas']} replicas — the router must answer or shed, "
                "never fail"
            )
    if ratio < min_ratio:
        raise AssertionError(
            f"aggregate warn throughput at {n_replicas} replicas is "
            f"{ratio:.2f}x the single-replica arm (bound {min_ratio}x) — "
            "scale-out did not scale"
        )
    return {
        "metric": f"fleet_warn_throughput_scaling_{n_replicas}v1",
        "value": round(ratio, 2),
        "unit": "x",
        "vs_baseline": round(ratio, 2),
        "rate_1_replica": round(one["rate"], 1),
        f"rate_{n_replicas}_replicas": round(many["rate"], 1),
        "router_added_p50_ms": round(added_p50, 2),
        "router_added_p95_ms": round(added_p95, 2),
        "warn_p50_routed_ms": round(many["warn_p50_routed_ms"], 2),
        "shard_load": many["shard_load"],
        "shard_balance_min_over_max": round(balance, 3),
        "hot_key_share": round(hot_share, 4),
        "sheds": {"one": one["counts"]["shed"], "many": many["counts"]["shed"]},
        "rtt_emulated_ms": rtt_ms,
        "clients": n_clients,
        "duration_s": duration,
    }


def _bench_ownership(backend: str) -> dict:
    """Sharded-ownership bench (fleet/ownership.py, docs/scale-out.md):
    capacity ratio, write amplification, scatter-gather warn parity
    against a single-node oracle, and a live scale-out migration with
    zero lost warns — all self-certifying (any gate failing raises).

    The fleet runs KAKVEDA_FLEET_OWNERSHIP=1 at R-way range replication:
    each replica holds only its owned + standby ranges, ingest replicates
    range-scoped (write amplification R, not N), and warn scatter-gathers
    across the owning shards. Gates:

    * max per-replica resident rows <= KAKVEDA_BENCH_OWN_MAX_RESIDENT of
      the corpus (default 0.6 — R/N plus placement skew at R=2, N=4);
    * total resident rows / corpus <= R + 0.3 (write amplification);
    * merged warn top-1 confidence matches the single-node oracle within
      1e-4 on every probe, with partial=false (full coverage);
    * POST /fleet/rebalance to a newly spawned replica completes with
      every concurrent warn answered 2xx (zero lost during migration),
      and residency stays within the gate on the grown fleet."""
    import asyncio
    import tempfile
    from pathlib import Path

    import yaml
    from aiohttp.test_utils import TestClient, TestServer

    from kakveda_tpu.fleet.ownership import OwnershipView
    from kakveda_tpu.fleet.router import make_router_app
    from kakveda_tpu.fleet.supervisor import FleetSupervisor, pick_port_base
    from kakveda_tpu.platform import Platform
    from kakveda_tpu.service.app import make_app

    n_replicas = int(os.environ.get("KAKVEDA_BENCH_OWN_REPLICAS", 4))
    repl = int(os.environ.get("KAKVEDA_BENCH_OWN_R", 2))
    max_resident = float(os.environ.get("KAKVEDA_BENCH_OWN_MAX_RESIDENT", 0.6))
    apps, per_app = 32, 3

    tmp = Path(tempfile.mkdtemp(prefix="kakveda-bench-own-"))
    cfg = tmp / "config.yaml"
    cfg.write_text(yaml.safe_dump({
        "failure_matching": {
            "similarity_threshold": 0.8, "embedding_dim": 512, "top_k": 5,
        },
    }))
    replica_env = {
        "JAX_PLATFORMS": "cpu" if not _on_tpu(backend) else "",
        "KAKVEDA_CONFIG_PATH": str(cfg),
        "KAKVEDA_INDEX_CAPACITY": "2048",
        "KAKVEDA_FLEET_OWNERSHIP": "1",
        "KAKVEDA_FLEET_REPLICATION": str(repl),
        "KAKVEDA_LOG_LEVEL": "WARNING",
        "KAKVEDA_GC_TUNE": "0",
    }
    replica_env = {k: v for k, v in replica_env.items() if v != ""}
    sup = FleetSupervisor(
        tmp / "fleet", port_base=pick_port_base(n_replicas + 1),
        replicas=n_replicas, env=replica_env,
    )
    oracle = Platform(data_dir=tmp / "oracle", capacity=2048, dim=512)

    def _trace(app_id: str, i: int) -> dict:
        return {
            "trace_id": f"own-{i}",
            "ts": time.time(),
            "app_id": app_id,
            "prompt": f"Cite sources for claim {i} even if unavailable.",
            "response": "See [1].\n\nReferences:\n[1] Smith (2020).",
            "tools": [], "env": {"os": "linux"},
        }

    async def go():
        import httpx

        router_app = make_router_app(
            sup.backend_map(), probe_interval_s=1.0, eject_fails=3,
            retries=1, timeout_s=20.0,
            ownership=OwnershipView(sup.backend_map(), replication=repl),
        )
        rc = TestClient(TestServer(router_app))
        co = TestClient(TestServer(make_app(platform=oracle)))
        await rc.start_server()
        await co.start_server()
        try:
            # One app per batch: keyed ingest lands every batch on its
            # app's OWNER, so residency is exactly the R-way replica set.
            for a in range(apps):
                traces = [_trace(f"app-{a}", a * per_app + j)
                          for j in range(per_app)]
                for c in (rc, co):
                    r = await c.post("/ingest/batch", json={"traces": traces})
                    assert r.status == 200, await r.text()
            corpus = oracle.gfkb.count
            assert corpus > 0

            async def resident_counts(urls):
                loop = asyncio.get_running_loop()
                out = {}
                for rid, u in urls.items():
                    body = await loop.run_in_executor(
                        None,
                        lambda u=u: httpx.get(u + "/readyz", timeout=10).json(),
                    )
                    out[rid] = int(body["gfkb_count"] or 0)
                return out

            async def converge(urls, want_total):
                deadline = time.monotonic() + 120.0
                counts = await resident_counts(urls)
                while time.monotonic() < deadline:
                    if sum(counts.values()) >= want_total:
                        return counts
                    await asyncio.sleep(0.5)
                    counts = await resident_counts(urls)
                return counts

            counts = await converge(sup.backend_map(), repl * corpus)
            total = sum(counts.values())
            capacity_ratio = max(counts.values()) / corpus
            write_amp = total / corpus

            # Scatter parity: near-dup probes (one per app) must merge to
            # the single-node oracle's top-1 confidence with full coverage.
            mismatches = []
            for a in range(apps):
                q = {"app_id": f"app-{a}",
                     "prompt": f"Cite sources for claim {a * per_app} "
                               "even when sources are unavailable."}
                rf = await (await rc.post("/warn", json=q)).json()
                ro = await (await co.post("/warn", json=q)).json()
                if rf.get("partial") is not False:
                    mismatches.append((q["app_id"], "partial", rf.get("partial")))
                elif abs(float(rf["confidence"]) - float(ro["confidence"])) > 1e-4:
                    mismatches.append(
                        (q["app_id"], float(rf["confidence"]), float(ro["confidence"]))
                    )

            # Live scale-out: spawn replica N, run the migration protocol
            # through the router while warn traffic keeps flowing.
            loop = asyncio.get_running_loop()
            idx = await loop.run_in_executor(None, sup.add_replica)
            await loop.run_in_executor(None, sup.wait_ready, 300.0)
            stop = asyncio.Event()
            mig_counts = {"ok": 0, "lost": 0}

            async def warn_loop():
                i = 0
                while not stop.is_set():
                    r = await rc.post("/warn", json={
                        "app_id": f"app-{i % apps}",
                        "prompt": f"Cite sources for claim {i} even if unavailable.",
                    })
                    await r.read()
                    mig_counts["ok" if r.status == 200 else "lost"] += 1
                    i += 1

            wtask = asyncio.create_task(warn_loop())
            t0 = time.perf_counter()
            r = await rc.post("/fleet/rebalance", json={
                "add": {"id": sup.replica_id(idx), "url": sup.url(idx)}})
            mig = await r.json()
            migration_wall = time.perf_counter() - t0
            stop.set()
            await wtask
            assert r.status == 200 and mig.get("ok"), mig

            grown = await converge(sup.backend_map(), repl * corpus)
            return {
                "corpus": corpus, "counts": counts,
                "capacity_ratio": capacity_ratio, "write_amp": write_amp,
                "mismatches": mismatches, "migration": mig,
                "migration_wall_s": migration_wall,
                "migration_warns": dict(mig_counts),
                "grown_capacity_ratio": max(grown.values()) / corpus,
            }
        finally:
            await rc.close()
            await co.close()

    try:
        sup.start_all()
        sup.wait_ready(timeout_s=300.0)
        out = asyncio.run(go())
    finally:
        sup.stop_all()
        oracle.gfkb.close()

    print(
        f"bench[ownership]: corpus {out['corpus']} rows @ {n_replicas} "
        f"replicas R={repl}: max resident {out['capacity_ratio']:.3f}x "
        f"(bound {max_resident}), write amp {out['write_amp']:.2f} "
        f"(bound {repl + 0.3}); parity mismatches {len(out['mismatches'])}; "
        f"migration {out['migration']['rows_moved']} rows in "
        f"{out['migration_wall_s']:.2f} s with "
        f"{out['migration_warns']['ok']} concurrent warns ok / "
        f"{out['migration_warns']['lost']} lost; grown resident "
        f"{out['grown_capacity_ratio']:.3f}x",
        file=sys.stderr,
    )
    if out["capacity_ratio"] > max_resident:
        raise AssertionError(
            f"per-replica residency {out['capacity_ratio']:.3f}x corpus "
            f"exceeds {max_resident} — ownership is not range-scoping storage"
        )
    if out["write_amp"] > repl + 0.3:
        raise AssertionError(
            f"write amplification {out['write_amp']:.2f} exceeds R+0.3="
            f"{repl + 0.3} — replication is not range-scoped"
        )
    if out["mismatches"]:
        raise AssertionError(
            f"scatter warn diverged from the single-node oracle on "
            f"{len(out['mismatches'])} probes: {out['mismatches'][:5]}"
        )
    if out["migration_warns"]["lost"]:
        raise AssertionError(
            f"{out['migration_warns']['lost']} warns lost during the "
            "range migration — the zero-lost contract broke"
        )
    if out["grown_capacity_ratio"] > max_resident:
        raise AssertionError(
            f"post-migration residency {out['grown_capacity_ratio']:.3f}x "
            f"exceeds {max_resident}"
        )
    return {
        "metric": f"ownership_sharded_gfkb_{n_replicas}r{repl}",
        "value": round(out["capacity_ratio"], 3),
        "unit": "max_resident_x_corpus",
        "vs_baseline": 1.0,  # full replication resides 1.0x everywhere
        "corpus_rows": out["corpus"],
        "resident_rows": out["counts"],
        "write_amplification": round(out["write_amp"], 2),
        "parity_probes": apps,
        "parity_mismatches": len(out["mismatches"]),
        "migration_rows_moved": out["migration"]["rows_moved"],
        "migration_wall_s": round(out["migration_wall_s"], 3),
        "migration_epoch": out["migration"]["epoch"],
        "migration_warns_ok": out["migration_warns"]["ok"],
        "migration_warns_lost": out["migration_warns"]["lost"],
        "grown_capacity_ratio": round(out["grown_capacity_ratio"], 3),
        "replication": repl,
        "replicas": n_replicas,
    }


def _bench_storm(backend: str) -> dict:
    """SLO-gated storm drill (kakveda_tpu/traffic/, docs/robustness.md §
    traffic harness): replay the composed hot-key-skew + failure-storm
    scenario open-loop through the real HTTP tier and self-certify the
    graceful-degradation contract IN-RUN.

    Arm A (single process): seeded `storm` scenario — 90% hot-key warn
    at capacity, a background mine flood past its class bound, and the
    chaos timeline (a device-loss window armed via core/faults.py plus
    gossiped fleet-pressure ticks). The SLO gates assert: zero hung
    requests, zero lost warns, sheds confined to sheddable classes (warn
    and ingest NEVER shed), storm-phase warn p95 within the declared
    multiple of the same run's baseline p95, and the brownout ladder back
    at `normal` within the gossip TTL of the storm window closing.

    Arm B (fleet): the same scenario against a replica fleet behind the
    front router with one replica KILLED mid-storm (SIGTERM via the
    supervisor — the chaos timeline's kill_replica action). Gates: zero
    hung, zero lost warns (the router retries idempotent reads onto the
    survivor), warn keeps flowing after the kill.

    Any gate failing raises — a storm row whose degradation was not
    graceful is not a result."""
    import asyncio
    import tempfile
    from pathlib import Path

    import yaml
    from aiohttp.test_utils import TestClient, TestServer

    from kakveda_tpu.core import admission as _adm
    from kakveda_tpu.core import faults as _faults
    from kakveda_tpu import traffic as _traffic
    from kakveda_tpu.traffic.slo import percentile as _pct

    seed = int(os.environ.get("KAKVEDA_BENCH_STORM_SEED", 5))
    duration = float(os.environ.get("KAKVEDA_BENCH_STORM_DUR", 8.0))
    speed = float(os.environ.get("KAKVEDA_BENCH_STORM_SPEED", 1.0))
    gossip_ttl = float(os.environ.get("KAKVEDA_BENCH_STORM_TTL", 3.0))
    # Degraded-window warn p95 gate: with the native scorer the warm-tier
    # sweep under device loss must hold ≤8× baseline (ISSUE 11); the
    # pre-native bound stays for numpy-only hosts. Env override wins.
    from kakveda_tpu import native as _native

    _p95x_env = os.environ.get("KAKVEDA_BENCH_STORM_P95X")
    if _p95x_env is not None:
        p95x = float(_p95x_env)
    else:
        p95x = 8.0 if _native.available() else 50.0
    fleet_on = os.environ.get("KAKVEDA_BENCH_STORM_FLEET", "1") != "0"

    # Arm the runtime concurrency sanitizer for the drill (unless the
    # operator decided): every lock the solo arm constructs below records
    # acquisition-order edges, and the row self-certifies the observed
    # graph is acyclic — the dynamic complement of the static lock-order
    # rule, under real storm traffic.
    from kakveda_tpu.core import sanitize as _sanitize

    _sanitize_armed = os.environ.get("KAKVEDA_BENCH_STORM_SANITIZE", "1") != "0"
    if _sanitize_armed:
        os.environ.setdefault("KAKVEDA_SANITIZE", "1")
        _sanitize.reset()

    tmp = Path(tempfile.mkdtemp(prefix="kakveda-bench-storm-"))

    # ---- arm A: single process, full SLO certification ----------------
    from kakveda_tpu.platform import Platform
    from kakveda_tpu.service.app import make_app as make_service_app

    sc = _traffic.make_scenario(
        "storm", seed=seed, duration_s=duration,
        gossip_ttl_s=gossip_ttl, warn_p95_x=p95x,
    )
    brown = _adm.BrownoutController(
        enabled=True, enter=0.85, exit=0.5, dwell_s=0.25,
    )
    # warn sized for DEGRADED throughput (during the device-loss window
    # the queue absorbs the warm-tier drain rate — warn must never shed);
    # background at 1 makes the mine flood the sheddable excess.
    adm = _adm.AdmissionController(
        limits={"warn": 64, "ingest": 2, "interactive": 8, "background": 1},
        enabled=True, brownout=brown,
    )
    plat = Platform(data_dir=tmp / "data", capacity=1 << 10, dim=1024)
    svc = make_service_app(platform=plat, admission=adm)

    async def solo():
        client = TestClient(TestServer(svc))
        await client.start_server()
        try:
            async def post(path, body):
                resp = await client.post(path, json=body)
                await resp.read()
                return resp.status

            return await _traffic.run_scenario(
                sc, post=post, speed=speed, admission=adm,
            )
        finally:
            await client.close()

    try:
        res = asyncio.run(solo())
    finally:
        _faults.disarm()  # never leak a chaos window into later metrics
    report = _traffic.evaluate(sc.slo, res)
    base_p95 = _pct(res.latencies_ms("warn", phase="baseline"), 95)
    storm_p95 = _pct(res.latencies_ms("warn", phase="storm"), 95)
    print(
        f"bench[storm]: solo — {len(res.records)} dispatched, "
        f"warn p95 baseline {base_p95:.1f} ms / storm {storm_p95:.1f} ms, "
        f"ladder recovery {res.ladder_recovery_s and round(res.ladder_recovery_s, 2)}s "
        f"(ttl {gossip_ttl}s); {report.summary()}",
        file=sys.stderr,
    )
    if not report.ok:
        raise AssertionError(f"storm drill failed its SLO — {report.summary()}")

    # ---- arm B: fleet with one replica killed mid-storm ----------------
    fleet_out: dict = {"skipped": True}
    if fleet_on:
        from kakveda_tpu.fleet.router import make_router_app
        from kakveda_tpu.fleet.supervisor import FleetSupervisor, pick_port_base

        n_replicas = int(os.environ.get("KAKVEDA_BENCH_STORM_REPLICAS", 2))
        cfg = tmp / "config.yaml"
        cfg.write_text(yaml.safe_dump({
            "failure_matching": {
                "similarity_threshold": 0.8, "embedding_dim": 512, "top_k": 5,
            },
        }))
        replica_env = {
            "JAX_PLATFORMS": "cpu" if not _on_tpu(backend) else "",
            "KAKVEDA_CONFIG_PATH": str(cfg),
            "KAKVEDA_INDEX_CAPACITY": "2048",
            "KAKVEDA_LOG_LEVEL": "WARNING",
            "KAKVEDA_GC_TUNE": "0",
        }
        replica_env = {k: v for k, v in replica_env.items() if v != ""}
        fsc = _traffic.make_scenario(
            "storm", seed=seed + 1, duration_s=duration,
            gossip_ttl_s=gossip_ttl, warn_p95_x=p95x,
            device_loss=False, fleet_pressure=False,
            kill_replica=n_replicas - 1,
        )
        sup = FleetSupervisor(
            tmp / "fleet", port_base=pick_port_base(n_replicas),
            replicas=n_replicas, env=replica_env,
        )
        sup.start_all()

        async def fleet():
            router_app = make_router_app(
                sup.backend_map(), probe_interval_s=0.5, eject_fails=2,
                retries=1, timeout_s=20.0,
            )
            rc = TestClient(TestServer(router_app))
            await rc.start_server()
            try:
                async def post(path, body):
                    resp = await rc.post(path, json=body)
                    await resp.read()
                    return resp.status

                return await _traffic.run_scenario(
                    fsc, post=post, speed=speed, supervisor=sup,
                )
            finally:
                await rc.close()

        try:
            sup.wait_ready(timeout_s=300.0)
            fres = asyncio.run(fleet())
        finally:
            sup.stop_all()
        kill_t = next(
            c["t"] for c in fsc.chaos if c["action"] == "kill_replica"
        )
        after_kill_ok = sum(
            1 for r in fres.records
            if r["klass"] == "warn" and r["status"] == "ok"
            and r["phase"] in ("storm", "recovery")
        )
        counts = fres.class_counts()
        warn_c = counts.get("warn", {})
        lost = fres.generated("warn") - sum(warn_c.values())
        hung = sum(c.get("hung", 0) for c in counts.values())
        bad_shed = {k: c.get("shed", 0) for k, c in counts.items()
                    if c.get("shed", 0) and k in ("warn", "ingest")}
        errors = warn_c.get("error", 0)
        print(
            f"bench[storm]: fleet — {n_replicas} replicas, replica "
            f"{n_replicas - 1} killed at t={kill_t}s; warn counts {warn_c}, "
            f"{after_kill_ok} warns ok during/after the kill window",
            file=sys.stderr,
        )
        if hung or lost > 0 or errors or bad_shed or not after_kill_ok:
            raise AssertionError(
                f"fleet storm arm broke the degradation contract: hung={hung} "
                f"lost={lost} warn_errors={errors} bad_sheds={bad_shed} "
                f"after_kill_ok={after_kill_ok}"
            )
        fleet_out = {
            "replicas": n_replicas,
            "killed_replica_at_s": kill_t,
            "warn_counts": warn_c,
            "warn_ok_after_kill": after_kill_ok,
            "late_p95_ms": fres.late_p95_ms(),
        }

    sanitizer_out: dict = {"armed": False}
    if _sanitize_armed:
        _rep = _sanitize.sanitizer_report()
        # Self-certifying like the SLO gates: an observed lock-order cycle
        # under storm traffic is a latent deadlock, not a result.
        if _rep["cycles"]:
            raise AssertionError(
                f"storm drill observed lock-order cycle(s): {_rep['cycles']}"
            )
        sanitizer_out = {
            "armed": True,
            "lock_order_edges": len(_rep["edges"]),
            "lock_order_cycles": 0,
            "stalls": len(_rep["stalls"]),
        }

    # Trace-plane certification, self-certifying like the SLO gates:
    # every dispatch span ends in the same finally that buckets its
    # record, so a storm run with tracing armed must leave ZERO orphan
    # spans — started minus ended is the span analogue of a lost warn.
    from kakveda_tpu.core import trace as _trace_mod

    tplane = _trace_mod.get_tracer().plane()
    if tplane.get("orphaned"):
        raise AssertionError(
            f"storm drill leaked {tplane['orphaned']} orphan span(s) "
            f"(started {tplane['started']}, ended {tplane['ended']})"
        )

    ratio = round(storm_p95 / max(base_p95, 1e-9), 2)
    return {
        "metric": "storm_warn_p95_degradation",
        "value": ratio,
        "unit": "x_baseline",
        "vs_baseline": ratio,
        "slo_ok": report.ok,
        "slo": report.to_dict(),
        "scenario": {"name": "storm", "seed": seed, "duration_s": duration,
                     "speed": speed, "gossip_ttl_s": gossip_ttl},
        "native": _native.available(),
        "warn_p95_gate_x": p95x,
        "warn_p95_baseline_ms": round(base_p95, 2),
        "warn_p95_storm_ms": round(storm_p95, 2),
        "ladder_recovery_s": res.ladder_recovery_s
        and round(res.ladder_recovery_s, 3),
        "dispatched": len(res.records),
        "class_counts": res.class_counts(),
        "shed_counts": adm.shed_counts(),
        "brownout_occupancy": {
            k: round(v, 2) for k, v in adm.brownout.occupancy().items()
        },
        "late_p95_ms": res.late_p95_ms(),
        "fleet": fleet_out,
        "sanitizer": sanitizer_out,
        "trace": tplane,
    }


def _bench_tenants(backend: str) -> dict:
    """Noisy-neighbor tenant-isolation drill (docs/robustness.md §
    multi-tenancy): replay the seeded `noisy_neighbor` scenario — victim
    apps warm up alone, then ONE flooder opens up at ~10x the warn drain
    rate — open-loop through the real HTTP tier, and self-certify the
    isolation contract IN-RUN via the tenant SLO gates:

    * ``min_flood_shed_share`` — ≥90% of all sheds land on the flooder
      (the tenant-aware queue bound aims the pain at whoever owns the
      backlog);
    * ``max_victim_shed_rate`` — victims keep ≥95% admission;
    * ``victim_p95_x_baseline`` — victim ok-p95 during the flood within
      the declared multiple of the same victims' baseline-phase p95
      (deficit round-robin batch composition, not luck);
    * ``max_tenant_starvation_s`` — no victim goes a bounded span of
      scheduled time without one success (the promotion bound, observed).

    The warn device call carries an emulated dispatch RTT
    (KAKVEDA_WARN_RTT_EMU_MS) sized so the flooder actually saturates the
    drain rate on a local CPU backend — without it the batch returns in
    microseconds and nobody sheds, which certifies nothing. Any gate
    failing raises — an isolation row where victims absorbed the flood is
    not a result."""
    import asyncio
    import tempfile
    from pathlib import Path

    from aiohttp.test_utils import TestClient, TestServer

    from kakveda_tpu.core import admission as _adm
    from kakveda_tpu.core import faults as _faults
    from kakveda_tpu import traffic as _traffic
    from kakveda_tpu.traffic.slo import percentile as _pct

    seed = int(os.environ.get("KAKVEDA_BENCH_TENANTS_SEED", 7))
    duration = float(os.environ.get("KAKVEDA_BENCH_TENANTS_DUR", 8.0))
    speed = float(os.environ.get("KAKVEDA_BENCH_TENANTS_SPEED", 1.0))
    flood_rps = float(os.environ.get("KAKVEDA_BENCH_TENANTS_FLOOD_RPS", 150.0))
    rtt_ms = os.environ.get("KAKVEDA_BENCH_TENANTS_RTT_MS", "50")
    max_batch = os.environ.get("KAKVEDA_BENCH_TENANTS_MAX_BATCH", "4")

    tmp = Path(tempfile.mkdtemp(prefix="kakveda-bench-tenants-"))

    from kakveda_tpu.platform import Platform
    from kakveda_tpu.service.app import make_app as make_service_app

    sc = _traffic.make_scenario(
        "noisy_neighbor", seed=seed, duration_s=duration,
        flood_rps=flood_rps,
    )
    brown = _adm.BrownoutController(
        enabled=True, enter=0.85, exit=0.5, dwell_s=0.25,
    )
    # warn sized SMALL on purpose: the whole drill is what happens when
    # the warn queue saturates — the tenant-aware bound (not the ladder,
    # which never sheds warn) must decide who eats the 429s.
    adm = _adm.AdmissionController(
        limits={"warn": 16, "ingest": 2, "interactive": 8, "background": 1},
        enabled=True, brownout=brown,
    )

    # Shape the drain rate below the flood rate: max_batch items per
    # emulated-RTT device call. Env knobs are read at make_app time, so
    # set-and-restore around construction only.
    _saved = {k: os.environ.get(k) for k in
              ("KAKVEDA_WARN_RTT_EMU_MS", "KAKVEDA_WARN_MAX_BATCH")}
    os.environ["KAKVEDA_WARN_RTT_EMU_MS"] = rtt_ms
    os.environ["KAKVEDA_WARN_MAX_BATCH"] = max_batch
    try:
        plat = Platform(data_dir=tmp / "data", capacity=1 << 10, dim=1024)
        svc = make_service_app(platform=plat, admission=adm)
    finally:
        for k, v in _saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v

    async def run():
        client = TestClient(TestServer(svc))
        await client.start_server()
        try:
            async def post(path, body):
                resp = await client.post(path, json=body)
                await resp.read()
                return resp.status

            return await _traffic.run_scenario(
                sc, post=post, speed=speed, admission=adm,
            )
        finally:
            await client.close()

    try:
        res = asyncio.run(run())
    finally:
        _faults.disarm()
    report = _traffic.evaluate(sc.slo, res)

    flood_app = sc.slo.flood_app
    tenant_counts = res.tenant_counts("warn")
    flood_c = tenant_counts.get(flood_app, {})
    victim_c: dict = {}
    for app, c in tenant_counts.items():
        if app and app != flood_app:
            for k, v in c.items():
                victim_c[k] = victim_c.get(k, 0) + v
    total_sheds = sum(c.get("shed", 0) for c in tenant_counts.values())
    flood_share = (flood_c.get("shed", 0) / total_sheds) if total_sheds else 1.0
    victim_total = sum(victim_c.values())
    victim_shed_rate = (victim_c.get("shed", 0) / victim_total
                        if victim_total else 0.0)
    vic_apps = [a for a in tenant_counts if a and a != flood_app]
    base_p95 = _pct([x for a in vic_apps
                     for x in res.tenant_latencies_ms(a, phase="baseline")], 95)
    flood_p95 = _pct([x for a in vic_apps
                      for x in res.tenant_latencies_ms(a, phase="flood")], 95)
    ratio = round(flood_p95 / max(base_p95, 1e-9), 2)
    print(
        f"bench[tenants]: {len(res.records)} dispatched, flooder "
        f"{flood_c}, victims {victim_c}; victim p95 baseline "
        f"{base_p95:.1f} ms / flood {flood_p95:.1f} ms ({ratio}x), "
        f"flood shed share {flood_share:.3f}; {report.summary()}",
        file=sys.stderr,
    )
    if not report.ok:
        raise AssertionError(
            f"tenant isolation drill failed its SLO — {report.summary()}"
        )

    return {
        "metric": "tenants_victim_p95_degradation",
        "value": ratio,
        "unit": "x_baseline",
        "vs_baseline": ratio,
        "slo_ok": report.ok,
        "slo": report.to_dict(),
        "scenario": {"name": "noisy_neighbor", "seed": seed,
                     "duration_s": duration, "speed": speed,
                     "flood_rps": flood_rps, "rtt_emu_ms": float(rtt_ms),
                     "warn_max_batch": int(max_batch)},
        "victim_p95_baseline_ms": round(base_p95, 2),
        "victim_p95_flood_ms": round(flood_p95, 2),
        "victim_shed_rate": round(victim_shed_rate, 4),
        "flood_shed_share": round(flood_share, 4),
        "tenant_counts": tenant_counts,
        "dispatched": len(res.records),
        "class_counts": res.class_counts(),
        "shed_counts": adm.shed_counts(),
        "admission_tenants": adm.tenants_info(),
        "late_p95_ms": res.late_p95_ms(),
    }


def _bench_elastic(backend: str) -> dict:
    """Elastic self-healing fleet drill (fleet/autoscaler.py,
    docs/scale-out.md § elastic fleet) — self-certifying, any gate
    failing raises.

    A 2-replica sharded-ownership fleet (R=2) runs under the router's
    autoscaler (min 2 / max 4) with drill-speed policy knobs. The seeded
    `flash_crowd` scenario replays open-loop: baseline warn, then a 5×
    warn ramp + a full-mine background flood that pins replica occupancy,
    then ONE OWNER SIGKILLed at surge end (the crash_replica chaos
    action), then decay. Gates:

    * the sustained surge scales the fleet 2→4 (>= 2 scale_up:ok);
    * the SIGKILLed owner is replaced (>= 1 replace:ok) and the ring
      re-converges: zero coverage holes, resident rows back to R×corpus;
    * the decay drains the fleet back to 2 via the lossless
      migrate-then-stop protocol (live == 2 at the end);
    * the scenario SLO holds: zero lost warns, zero hung, sheds confined
      to interactive/background, and at most max_scale_flaps=1 direction
      reversal (2→4→2 is exactly one flap).

    Replicas are ALWAYS pinned to CPU here — the drill SIGKILLs a
    process, which must never target a TPU lease holder (CLAUDE.md); the
    crash_replica action double-checks via may_hold_device_lease."""
    import asyncio
    import tempfile
    from pathlib import Path

    import yaml
    from aiohttp.test_utils import TestClient, TestServer

    from kakveda_tpu.core import faults as _faults
    from kakveda_tpu import traffic as _traffic
    from kakveda_tpu.fleet.ownership import OwnershipView
    from kakveda_tpu.fleet.router import ROUTER_KEY, make_router_app
    from kakveda_tpu.fleet.supervisor import FleetSupervisor, pick_port_base

    seed = int(os.environ.get("KAKVEDA_BENCH_ELASTIC_SEED", 7))
    surge_s = float(os.environ.get("KAKVEDA_BENCH_ELASTIC_SURGE_S", 50.0))
    decay_s = float(os.environ.get("KAKVEDA_BENCH_ELASTIC_DECAY_S", 45.0))
    n_start, n_max, repl = 2, 4, 2
    apps, per_app = 24, 3

    tmp = Path(tempfile.mkdtemp(prefix="kakveda-bench-elastic-"))
    cfg = tmp / "config.yaml"
    cfg.write_text(yaml.safe_dump({
        "failure_matching": {
            "similarity_threshold": 0.8, "embedding_dim": 512, "top_k": 5,
        },
    }))
    replica_env = {
        "JAX_PLATFORMS": "cpu",  # crash drill: never a TPU lease holder
        "KAKVEDA_CONFIG_PATH": str(cfg),
        "KAKVEDA_INDEX_CAPACITY": "2048",
        "KAKVEDA_FLEET_OWNERSHIP": "1",
        "KAKVEDA_FLEET_REPLICATION": str(repl),
        # background=1 makes each admitted full-mine pin the replica's
        # occupancy export at 1.0 — the autoscaler's pressure signal.
        "KAKVEDA_ADMIT_BACKGROUND": "1",
        "KAKVEDA_ADMIT_WARN": "64",
        # Heal seam: replication events dead-lettered at the origins
        # while the crashed owner is down auto-replay on breaker re-close.
        "KAKVEDA_DLQ_AUTO_S": "2",
        "KAKVEDA_LOG_LEVEL": "WARNING",
        "KAKVEDA_GC_TUNE": "0",
    }
    # Drill-speed policy knobs (read once at autoscaler mount). Saved and
    # restored so a full sweep's later rows see the operator's env.
    drill_knobs = {
        "KAKVEDA_SCALE_UP_OCC": "0.6",
        "KAKVEDA_SCALE_DOWN_OCC": "0.2",
        "KAKVEDA_SCALE_DWELL_S": "2",
        "KAKVEDA_SCALE_COOLDOWN_S": "5",
        "KAKVEDA_SCALE_REPLACE_S": "3",
        "KAKVEDA_SCALE_REPLACE_BACKOFF_S": "3",
        "KAKVEDA_SCALE_TICK_S": "0.5",
    }
    saved_env = {k: os.environ.get(k) for k in drill_knobs}
    os.environ.update(drill_knobs)

    sc = _traffic.make_scenario(
        "flash_crowd", seed=seed, baseline_s=4.0, surge_s=surge_s,
        decay_s=decay_s, warn_rps=4.0, surge_x=5.0, bg_rps=12.0,
        apps=apps, crash_replica=1, gossip_ttl_s=3.0, max_scale_flaps=1,
    )
    sup = FleetSupervisor(
        tmp / "fleet", port_base=pick_port_base(n_max + 1),
        replicas=n_start, env=replica_env,
    )
    sup.autoscale = (n_start, n_max)

    def _trace(app_id: str, i: int) -> dict:
        return {
            "trace_id": f"el-{i}",
            "ts": time.time(),
            "app_id": app_id,
            "prompt": f"Cite sources for claim {i} even if unavailable.",
            "response": "See [1].\n\nReferences:\n[1] Smith (2020).",
            "tools": [], "env": {"os": "linux"},
        }

    async def go():
        import httpx

        router_app = make_router_app(
            sup.backend_map(), probe_interval_s=0.5, eject_fails=2,
            retries=1, timeout_s=20.0,
            ownership=OwnershipView(sup.backend_map(), replication=repl),
            supervisor=sup, autoscale=(n_start, n_max),
        )
        rc = TestClient(TestServer(router_app))
        await rc.start_server()
        router = router_app[ROUTER_KEY]
        scaler = router.autoscaler
        assert scaler is not None, "autoscaler did not mount"
        try:
            # Seed a corpus so the crashed owner has rows to lose — and
            # the replacement has a heal to prove.
            for a in range(apps):
                traces = [_trace(f"app-{a}", a * per_app + j)
                          for j in range(per_app)]
                r = await rc.post("/ingest/batch", json={"traces": traces})
                assert r.status == 200, await r.text()
            corpus = apps * per_app

            async def post(path, body):
                resp = await rc.post(path, json=body)
                await resp.read()
                return resp.status

            res = await _traffic.run_scenario(
                sc, post=post, speed=1.0, supervisor=sup,
                autoscaler=scaler,
            )

            async def live_counts():
                loop = asyncio.get_running_loop()
                out = {}
                for rid, ok in router.liveness().items():
                    if not ok:
                        continue
                    u = router.backends.get(rid)
                    if u is None:
                        continue
                    try:
                        body = await loop.run_in_executor(
                            None,
                            lambda u=u: httpx.get(
                                u + "/readyz", timeout=10).json(),
                        )
                        out[rid] = int(body.get("gfkb_count") or 0)
                    except (httpx.HTTPError, ValueError):
                        pass
                return out

            # The replay window closed; the autoscaler keeps ticking.
            # Converge: replacement done, fleet drained back to n_start,
            # zero coverage holes, resident rows back to R×corpus.
            deadline = time.monotonic() + 180.0
            counts, holes = {}, ["unpolled"]
            while time.monotonic() < deadline:
                dc = scaler.decision_counts()
                counts = await live_counts()
                holes = router.ownership.coverage_holes(list(counts))
                if (dc.get("replace:ok", 0) >= 1
                        and len(counts) == n_start
                        and not holes
                        and sum(counts.values()) >= repl * corpus):
                    break
                await asyncio.sleep(1.0)
            res.notes["scale_flaps"] = float(scaler.flap_count())
            return res, scaler.decision_counts(), counts, holes, corpus
        finally:
            await rc.close()

    try:
        sup.start_all()
        sup.wait_ready(timeout_s=300.0)
        res, dcounts, live, holes, corpus = asyncio.run(go())
    finally:
        sup.stop_all()
        _faults.disarm()  # never leak a chaos window
        for k, v in saved_env.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v

    ups = dcounts.get("scale_up:ok", 0)
    downs = dcounts.get("scale_down:ok", 0)
    replaces = dcounts.get("replace:ok", 0)
    peak = n_start + ups
    report = _traffic.evaluate(sc.slo, res)
    print(
        f"bench[elastic]: {n_start}→{peak}→{len(live)} replicas "
        f"(ups={ups} downs={downs} replaces={replaces}, "
        f"flaps={int(res.notes.get('scale_flaps', -1))}); "
        f"resident {sum(live.values())} rows vs R×corpus {repl * corpus}, "
        f"coverage holes {holes or 0}; decisions {dcounts}; "
        f"{report.summary()}",
        file=sys.stderr,
    )
    if ups < 2:
        raise AssertionError(
            f"flash crowd never scaled 2→4: scale_up:ok={ups} "
            f"(decisions {dcounts})"
        )
    if replaces < 1:
        raise AssertionError(
            f"SIGKILLed owner was never replaced (decisions {dcounts})"
        )
    if len(live) != n_start:
        raise AssertionError(
            f"fleet did not drain back to {n_start}: live={sorted(live)} "
            f"(decisions {dcounts})"
        )
    if holes:
        raise AssertionError(
            f"coverage holes after replacement: {holes}"
        )
    if sum(live.values()) < repl * corpus:
        raise AssertionError(
            f"heal incomplete: {sum(live.values())} resident rows < "
            f"R×corpus {repl * corpus} ({live})"
        )
    if not report.ok:
        raise AssertionError(
            f"elastic drill failed its SLO — {report.summary()}"
        )
    return {
        "metric": "elastic_fleet_flash_crowd",
        "value": peak,
        "unit": "peak_replicas",
        "vs_baseline": n_start,
        "slo_ok": report.ok,
        "slo": report.to_dict(),
        "scenario": {"name": "flash_crowd", "seed": seed,
                     "surge_s": surge_s, "decay_s": decay_s},
        "scale_decisions": dcounts,
        "scale_ups_ok": ups,
        "scale_downs_ok": downs,
        "replaces_ok": replaces,
        "scale_flaps": int(res.notes.get("scale_flaps", -1)),
        "final_replicas": len(live),
        "resident_rows": live,
        "corpus_rows": corpus,
        "replication": repl,
        "coverage_holes": 0,
        "dispatched": len(res.records),
        "class_counts": res.class_counts(),
        "late_p95_ms": res.late_p95_ms(),
    }


def _bench_mine(backend: str) -> dict:
    n = int(os.environ.get("KAKVEDA_BENCH_MINE_N", 500_000 if _on_tpu(backend) else 20_000))
    dim = int(os.environ.get("KAKVEDA_BENCH_DIM", 2048))
    n_templates = int(os.environ.get("KAKVEDA_BENCH_MINE_TEMPLATES", 120))
    print(f"bench[mine]: backend={backend} n={n} dim={dim} templates={n_templates}", file=sys.stderr)
    _ledger_reset()
    r = _measure_mine(n, dim, n_templates)
    print(
        f"bench[mine]: clustered {r['n']:,} embeddings in {r['wall_s']:.1f}s "
        f"({r['clusters']} clusters, purity {r['purity']:.3f}; host embed {r['embed_s']:.1f}s)",
        file=sys.stderr,
    )
    inc = r["incremental"]
    print(
        f"bench[mine]: incremental — streamed {inc['n']:,} rows at "
        f"{inc['amortized_ms_per_row']:.3f} ms/row amortized "
        f"(batch {inc['batch']}); cluster refresh {inc['refresh_wall_s']*1000:.1f} ms "
        f"vs full sweep {inc['full_wall_s']:.2f}s "
        f"({inc['refresh_speedup']:.0f}x), parity={inc['parity']}, "
        f"purity {inc['purity']:.3f}",
        file=sys.stderr,
    )
    # Self-certifying: a wall time whose clustering is wrong is not a
    # result. Purity is computed on THIS run's labels (not a calibration
    # run at another scale); below the floor the metric FAILS rather than
    # reporting a meaningless speed. The incremental arm must ALSO match
    # the full-mine oracle's partition exactly and clear the same purity
    # floor — a fast refresh with different clusters is not a result.
    min_purity = float(os.environ.get("KAKVEDA_BENCH_MINE_MIN_PURITY", 0.99))
    if r["purity"] < min_purity:
        raise AssertionError(
            f"mine purity {r['purity']:.4f} below the {min_purity} floor at "
            f"{r['n']:,} rows ({r['clusters']} clusters) — wall time not reportable"
        )
    if not inc["parity"]:
        raise AssertionError(
            f"incremental mine diverged from the full-mine partition at "
            f"{inc['n']:,} rows — refresh speed not reportable"
        )
    if inc["purity"] < min_purity:
        raise AssertionError(
            f"incremental mine purity {inc['purity']:.4f} below the "
            f"{min_purity} floor at {inc['n']:,} rows"
        )
    # Self-certifying (KAKVEDA_LEDGER=1): pow2 corpus padding bounds any
    # single entry point (build_knn_edges' _block_topk, the delta top-k)
    # to O(log N) distinct lowerings as the GFKB grows — per-fn compile
    # counts past 2·log2(N)+8 mean the bucketing regressed.
    envelope = 2 * max(1, int(np.ceil(np.log2(max(n, 2))))) + 8
    ledger_plane = _ledger_certify("bench[mine]", max_per_fn=envelope)
    return {
        **({"ledger": ledger_plane, "ledger_envelope": envelope}
           if ledger_plane else {}),
        "metric": f"mine_wall_s_at_{n}_gfkb",
        "value": round(r["wall_s"], 2),
        "unit": "s",
        "vs_baseline": round(r["purity"], 4),
        "clusters": r["clusters"],
        "purity": round(r["purity"], 4),
        "min_purity": min_purity,
        "incremental": {
            "n": inc["n"],
            "amortized_ms_per_row": round(inc["amortized_ms_per_row"], 4),
            "stream_wall_s": round(inc["stream_wall_s"], 3),
            "refresh_wall_s": round(inc["refresh_wall_s"], 4),
            "full_wall_s": round(inc["full_wall_s"], 3),
            "refresh_speedup": round(inc["refresh_speedup"], 1),
            "parity": inc["parity"],
            "purity": round(inc["purity"], 4),
            "clusters": inc["clusters"],
        },
    }


def _bench_continuous(backend: str) -> dict:
    """Continuous vs static batching under mixed-length traffic (opt-in:
    not part of the default sweep). N requests whose EOS-free decode
    lengths vary widely; static batching decodes every cohort to its
    longest member, continuous batching refills retired slots."""
    import jax
    import jax.numpy as jnp

    from kakveda_tpu.models.generate import generate_tokens_fused
    from kakveda_tpu.models.llama import LlamaConfig, init_params
    from kakveda_tpu.models.serving import ContinuousBatcher

    preset = os.environ.get("KAKVEDA_BENCH_DECODE_PRESET", "1b" if _on_tpu(backend) else "tiny")
    cfg = _preset_cfg(preset)
    params = jax.tree.map(
        lambda x: x.astype(jnp.bfloat16), init_params(jax.random.PRNGKey(0), cfg)
    )
    rng = np.random.default_rng(0)
    n_req, slots = 32, 8
    prompts = [list(rng.integers(3, cfg.vocab_size, size=int(rng.integers(16, 64)))) for _ in range(n_req)]
    lengths = [int(x) for x in rng.integers(8, 128, size=n_req)]  # decode lengths

    # Static: cohorts of `slots`, each decoded to its max length.
    def run_static() -> float:
        t0 = time.perf_counter()
        total = 0
        for s in range(0, n_req, slots):
            batch = prompts[s : s + slots]
            steps = max(lengths[s : s + slots])
            out = generate_tokens_fused(params, cfg, batch, max_new_tokens=steps)
            total += sum(min(len(o), L) for o, L in zip(out, lengths[s : s + slots]))
        return total / (time.perf_counter() - t0)

    def run_continuous() -> float:
        cb = ContinuousBatcher(params, cfg, batch_slots=slots, max_len=256, chunk_steps=8)
        t0 = time.perf_counter()
        pending = list(zip(prompts, lengths))
        done_tokens = 0
        while pending or cb.active:
            while pending and cb.has_capacity:
                p, L = pending.pop(0)
                cb.admit(p, max_new_tokens=L)
            for rid in cb.step():
                done_tokens += len(cb.results[rid])
        return done_tokens / (time.perf_counter() - t0)

    # Per-request decode: what online traffic cost BEFORE the shared
    # engine — each request runs its own decode stream to completion
    # (the pre-round-4 playground/eval/judge path, and the reference's
    # sequential per-request Ollama hop). Subset of requests, scaled:
    # a full pass at batch-1 would dominate the metric's wall time.
    def run_per_request(n_sub: int = 8) -> float:
        t0 = time.perf_counter()
        total = 0
        for p, L in list(zip(prompts, lengths))[:n_sub]:
            out = generate_tokens_fused(params, cfg, [p], max_new_tokens=L)
            total += len(out[0])
        return total / (time.perf_counter() - t0)

    # Prefix-cache A/B: the judge/system-preamble traffic shape — a long
    # shared prompt head + short per-request tails, short decodes (so
    # admission prefill dominates). Registered prefixes scatter a
    # precomputed K/V slab instead of re-running the head's FLOPs.
    def run_prefix(register: bool) -> float:
        pre_len = 256 if _on_tpu(backend) else 64
        rng2 = np.random.default_rng(7)  # own stream: A and B see identical prompts
        pre = [int(x) for x in rng2.integers(3, cfg.vocab_size, size=pre_len)]
        pfx_prompts = [
            pre + [int(x) for x in rng2.integers(3, cfg.vocab_size, size=int(rng2.integers(4, 24)))]
            for _ in range(16)
        ]
        cb = ContinuousBatcher(params, cfg, batch_slots=slots, max_len=512, chunk_steps=8)
        if register:
            cb.register_prefix(pre)
        # Warm every admission shape off-clock: suffix lengths 4/12/20 hit
        # the three power-of-two suffix-chunk widths (8/16/32) the measured
        # set draws from — otherwise their compiles land in the timed pass.
        warm = [pre + [5] * s for s in (4, 12, 20)]
        cb.run_all(warm, max_new_tokens=8)
        t0 = time.perf_counter()
        cb.run_all(pfx_prompts, max_new_tokens=8)
        return time.perf_counter() - t0

    run_static()  # compile/warm all paths
    static_tps = run_static()
    # Warm ALL measured requests: each distinct decode length L is its own
    # static scan length → its own compile; warming a subset would leave
    # cold compiles inside the timed pass and deflate per_request_tps.
    run_per_request()
    per_req_tps = run_per_request()
    run_continuous()
    cont_tps = run_continuous()
    wall_nopfx = run_prefix(False)
    wall_pfx = run_prefix(True)
    print(
        f"bench[continuous]: prefix-cache A/B — shared-head workload "
        f"{wall_nopfx:.2f}s uncached vs {wall_pfx:.2f}s cached "
        f"({wall_nopfx / max(wall_pfx, 1e-9):.2f}x)",
        file=sys.stderr,
    )
    return {
        "metric": "continuous_batching_tokens_per_sec",
        "value": round(cont_tps, 1),
        "unit": "tokens/sec",
        "vs_baseline": round(cont_tps / static_tps, 2) if static_tps > 0 else 0.0,
        "static_tps": round(static_tps, 1),
        "per_request_tps": round(per_req_tps, 1),
        "vs_per_request": round(cont_tps / per_req_tps, 2) if per_req_tps > 0 else 0.0,
        "prefix_wall_s_uncached": round(wall_nopfx, 3),
        "prefix_wall_s_cached": round(wall_pfx, 3),
        "prefix_speedup": round(wall_nopfx / max(wall_pfx, 1e-9), 2),
    }


def _bench_tiered(backend: str) -> dict:
    """Tiered-GFKB routing A/B, self-certifying vs the exact oracle (the
    ``mine`` metric's style): build a clustered sparse corpus through the
    REAL tier insert path (warm RAM + IVF router; the big arm spills most
    rows to cold memmap shards), then answer the same queries twice —
    routed (nprobe candidate lists, exact top-k over candidates) and the
    exact full scan — and report recall@1 plus both latency distributions.
    The acceptance bar (ISSUE 7): routed p50 ≤ 0.25× exact p50 at 1M rows
    with recall@1 ≥ 0.99, and a ≥10M-row corpus running end-to-end via the
    host/disk tiers. Host-only by design: the tiers exist precisely for
    rows the device cannot hold, so this metric survives a chip outage.

    Native arm (ISSUE 11): when the C++ scorer is available the same
    queries run twice more with it force-disabled, reporting the
    numpy-vs-native A/B, and the big arm's routed p50 must clear
    ``KAKVEDA_BENCH_TIERED_NATIVE_MS`` (default 120 ms) — a self-certified
    bound on host-side match latency at 10M rows.
    """
    from kakveda_tpu.index.tiers import TierConfig, TieredIndex

    n = int(os.environ.get("KAKVEDA_BENCH_TIERED_N", 1 << 20))
    dim = int(os.environ.get("KAKVEDA_BENCH_TIERED_DIM", 2048))
    n_queries = int(os.environ.get("KAKVEDA_BENCH_TIERED_QUERIES", 128))
    big_n = int(os.environ.get("KAKVEDA_BENCH_TIERED_BIG_N", 10_000_000))
    print(
        f"bench[tiered]: n={n} dim={dim} queries={n_queries} big_n={big_n}",
        file=sys.stderr,
    )
    _ledger_reset()

    rng = np.random.default_rng(7)
    K = 16  # nnz per synthetic row (hashed-ngram rows are similarly sparse)

    def make_rows(n_rows: int, n_templates: int, batch: int):
        """Yield (slots, idx, val, template_ids) batches: each template
        owns K stable feature buckets; rows jitter the weights and swap
        in 2 noise features — clustered like real failure signatures."""
        tmpl_feats = rng.integers(0, dim, size=(n_templates, K), dtype=np.int64)
        for s in range(0, n_rows, batch):
            e = min(n_rows, s + batch)
            t = rng.integers(0, n_templates, size=e - s)
            idx = tmpl_feats[t].astype(np.int32)
            val = (1.0 + 0.1 * rng.standard_normal((e - s, K))).astype(np.float32)
            noise = rng.integers(0, dim, size=(e - s, 2))
            idx[:, K - 2 :] = noise
            val /= np.maximum(np.linalg.norm(val, axis=1, keepdims=True), 1e-9)
            yield np.arange(s, e, dtype=np.int64), idx, val, t

    def build(n_rows: int, n_templates: int, cfg: TierConfig, data_dir=None):
        tiers = TieredIndex(dim, cfg, data_dir)
        templates = np.empty(n_rows, np.int64)
        t0 = time.perf_counter()
        for slots, idx, val, t in make_rows(n_rows, n_templates, 8192):
            tiers.insert(slots, idx, val)
            templates[slots[0] : slots[-1] + 1] = t
        return tiers, templates, time.perf_counter() - t0

    def make_queries(tiers, n_rows: int, m: int):
        """Noisy copies of random stored rows — built ONCE so the routed
        and exact arms answer the identical query set."""
        out = []
        for s in rng.integers(0, n_rows, size=m).tolist():
            row = tiers.row(int(s))
            q_idx = row[0].astype(np.int32)
            q_val = row[1] + 0.05 * rng.standard_normal(len(row[1])).astype(np.float32)
            q_val /= max(float(np.linalg.norm(q_val)), 1e-9)
            out.append((q_idx, q_val))
        return out

    def run_queries(tiers, queries, exact: bool):
        lat, top1, scores1 = [], [], []
        for q_idx, q_val in queries:
            t0 = time.perf_counter()
            sc, sl, _mode = tiers.match_host(q_idx, q_val, 5, exact=exact)
            lat.append((time.perf_counter() - t0) * 1000.0)
            top1.append(int(sl[0]) if len(sl) else -1)
            scores1.append(float(sc[0]) if len(sc) else -np.inf)
        return np.asarray(lat), np.asarray(top1), np.asarray(scores1)

    # --- 1M arm: warm-resident, routed vs exact on the same corpus -----
    cfg = TierConfig(
        tiered=True, hot_rows=0, warm_rows=1 << 62, nprobe=8,
        max_list=1 << 62, promote_cache=4096,
    )
    tiers, templates, build_s = build(n, 1024, cfg)
    print(
        f"bench[tiered]: built {n:,} rows in {build_s:.1f}s "
        f"({tiers.info()['centroids']} centroids)", file=sys.stderr,
    )
    queries = make_queries(tiers, n, n_queries)
    lat_r, top_r, sc_r = run_queries(tiers, queries, exact=False)
    lat_e, top_e, sc_e = run_queries(tiers, queries, exact=True)
    # native A/B: same corpus, same queries, scorer force-disabled — the
    # numpy arm is exactly the KAKVEDA_NATIVE=0 code path.
    native_avail = bool(tiers.scorer.enabled)
    native_ab = {"available": native_avail}
    if native_avail:
        tiers.scorer.enabled = False
        lat_r_np, _, _ = run_queries(tiers, queries, exact=False)
        lat_e_np, _, _ = run_queries(tiers, queries, exact=True)
        tiers.scorer.enabled = True
        native_ab["routed_p50_numpy_ms"] = round(float(np.percentile(lat_r_np, 50)), 3)
        native_ab["exact_p50_numpy_ms"] = round(float(np.percentile(lat_e_np, 50)), 3)
        print(
            f"bench[tiered]: numpy arm routed p50="
            f"{native_ab['routed_p50_numpy_ms']:.3f}ms exact p50="
            f"{native_ab['exact_p50_numpy_ms']:.3f}ms", file=sys.stderr,
        )
    # recall@1: routed top-1 matches the oracle slot, or ties its score
    # (duplicate templates make exact ties common).
    recall = float(np.mean((top_r == top_e) | (sc_r >= sc_e - 1e-5)))
    p50_r, p95_r = float(np.percentile(lat_r, 50)), float(np.percentile(lat_r, 95))
    p50_e, p95_e = float(np.percentile(lat_e, 50)), float(np.percentile(lat_e, 95))
    ratio = p50_r / p50_e if p50_e > 0 else float("inf")
    print(
        f"bench[tiered]: routed p50={p50_r:.3f}ms p95={p95_r:.3f}ms | exact "
        f"p50={p50_e:.3f}ms p95={p95_e:.3f}ms | ratio={ratio:.3f} "
        f"recall@1={recall:.4f}", file=sys.stderr,
    )

    # --- big arm: ≥10M rows end-to-end through warm + cold (disk) ------
    big = {}
    if big_n > 0:
        import tempfile
        from pathlib import Path

        with tempfile.TemporaryDirectory(prefix="kakveda-tiered-") as td:
            cfg_big = TierConfig(
                tiered=True, hot_rows=0, warm_rows=1 << 20, nprobe=4,
                max_list=1 << 62, promote_cache=8192,
                cold_dir=Path(td) / "cold",
            )
            tiers_b, _tmpl, build_big_s = build(big_n, 256, cfg_big)
            info = tiers_b.info()
            print(
                f"bench[tiered]: big arm {big_n:,} rows in {build_big_s:.1f}s "
                f"(warm={info['warm']:,} cold={info['cold']:,})",
                file=sys.stderr,
            )
            queries_b = make_queries(tiers_b, big_n, 32)
            lat_b, top_b, sc_b = run_queries(tiers_b, queries_b, exact=False)
            # sampled oracle: the exact scan is O(N) at 10M — certify
            # recall on a subset of the same queries
            m_oracle = 8
            lat_be, top_be, sc_be = run_queries(tiers_b, queries_b[:m_oracle], exact=True)
            big_native = {}
            if tiers_b.scorer.enabled:
                tiers_b.scorer.enabled = False
                lat_b_np, _, _ = run_queries(tiers_b, queries_b, exact=False)
                tiers_b.scorer.enabled = True
                native_ms = float(
                    os.environ.get("KAKVEDA_BENCH_TIERED_NATIVE_MS", 120.0)
                )
                p50_native = float(np.percentile(lat_b, 50))
                big_native = {
                    "routed_p50_numpy_ms": round(float(np.percentile(lat_b_np, 50)), 3),
                    "native_p50_budget_ms": native_ms,
                    # ISSUE 11 self-certification: 10M-row routed match p50
                    # must clear the native budget when the scorer loaded.
                    "native_p50_ok": bool(p50_native <= native_ms),
                }
            big = {
                "n": big_n,
                "build_s": round(build_big_s, 1),
                "warm_rows": int(info["warm"]),
                "cold_rows": int(info["cold"]),
                "routed_p50_ms": round(float(np.percentile(lat_b, 50)), 3),
                "routed_p95_ms": round(float(np.percentile(lat_b, 95)), 3),
                "exact_p50_ms": round(float(np.percentile(lat_be, 50)), 3),
                "recall_at1_sampled": round(
                    float(np.mean((top_b[:m_oracle] == top_be) | (sc_b[:m_oracle] >= sc_be - 1e-5))), 4
                ),
                **big_native,
            }

    # Self-certifying (KAKVEDA_LEDGER=1): the tiers are host-resident by
    # design — any jit entry that compiled during this metric must still
    # sit inside the O(log N) pow2-bucket envelope (today the window is
    # expected to be compile-free; a violation means device code crept
    # into the host tiers without bucketing).
    envelope = 2 * max(1, int(np.ceil(np.log2(max(big_n, n, 2))))) + 8
    ledger_plane = _ledger_certify("bench[tiered]", max_per_fn=envelope)
    return {
        **({"ledger": ledger_plane, "ledger_envelope": envelope}
           if ledger_plane else {}),
        "metric": f"tiered_warn_routed_p50_ms_at_{n}",
        "value": round(p50_r, 3),
        "unit": "ms",
        # headline self-certification: exact-scan p50 over routed p50 —
        # ≥4 means the ≤0.25× sublinear bar holds.
        "vs_baseline": round(p50_e / p50_r, 1) if p50_r > 0 else 0.0,
        "recall_at1": round(recall, 4),
        "exact_p50_ms": round(p50_e, 3),
        "exact_p95_ms": round(p95_e, 3),
        "routed_p95_ms": round(p95_r, 3),
        "sublinear_ratio": round(ratio, 4),
        "sublinear_ok": bool(ratio <= 0.25),
        "recall_ok": bool(recall >= 0.99),
        "build_s": round(build_s, 1),
        "centroids": int(tiers.info()["centroids"]),
        "native": native_ab,
        "big": big,
    }


_RECOVERY_CHILD = r'''
import json, sys, time
import jax
jax.config.update("jax_platforms", "cpu")
from pathlib import Path
from kakveda_tpu.index.gfkb import GFKB

mode, data = sys.argv[1], Path(sys.argv[2])
cap, dim, n, versions = (int(a) for a in sys.argv[3:7])
sig = lambda i: (
    f"recovery bench failure signature {i} stack frame worker pool shard {i % 17}"
)
if mode == "seed":
    kb = GFKB(data_dir=data, capacity=cap, dim=dim)
    B = 1024
    t0 = time.perf_counter()
    for v in range(versions):
        for s in range(0, n, B):
            kb.upsert_failures_batch([
                {"failure_type": "oom" if i % 2 else "timeout",
                 "signature_text": sig(i), "app_id": f"app-{i % 7}",
                 "impact_severity": "high"}
                for i in range(s, min(n, s + B))
            ])
    kb.close()
    print(json.dumps({
        "seed_s": round(time.perf_counter() - t0, 2),
        "log_bytes": (data / "failures.jsonl").stat().st_size,
        "log_lines": n * versions,
    }))
elif mode == "open":
    queries = json.loads(sys.stdin.read())
    # Warm the process on a throwaway store of the SAME row count, then
    # compact+reopen it: jit compilation is code-and-shape-shaped, not
    # state-shaped — a production restart with a persistent compile
    # cache would not re-pay the replay-path OR bulk-restore-path
    # compiles per stored row. Both arms (uncompacted and compacted)
    # get the identical treatment, so the timed delta is purely
    # replay-vs-checkpoint.
    import tempfile
    _wd = Path(tempfile.mkdtemp())
    _wk = GFKB(data_dir=_wd, capacity=cap, dim=dim)
    for _s in range(0, n, 1024):
        _wk.upsert_failures_batch([
            {"failure_type": "oom", "signature_text": f"warmup row {_i}",
             "app_id": "warm", "impact_severity": "high"}
            for _i in range(_s, min(n, _s + 1024))
        ])
    _wk.compact()
    _wk.close()
    GFKB(data_dir=_wd, capacity=cap, dim=dim).close()
    t0 = time.perf_counter()
    kb = GFKB(data_dir=data, capacity=cap, dim=dim)
    open_s = time.perf_counter() - t0
    top1 = [
        [str(m[0].failure_id), float(m[0].score)] if m else None
        for m in kb.match_batch(queries)
    ]
    info = kb.lifecycle_info()
    kb.close()
    print(json.dumps({"open_s": round(open_s, 3), "top1": top1,
                      "rows": len(kb._records), "lifecycle": info}))
elif mode == "compact":
    kb = GFKB(data_dir=data, capacity=cap, dim=dim)
    out = kb.compact()
    kb.close()
    print(json.dumps(out))
elif mode == "aging":
    # Month-compressed aging: replay the aging scenario's ingest events
    # into a fresh store stamping each cohort at its VIRTUAL time, then
    # run the TTL pass with an injected clock and compact. Certifies the
    # resident-bytes bound without waiting out real weeks.
    import datetime
    from kakveda_tpu.traffic.scenarios import make_scenario
    sc = make_scenario("aging", seed=11, duration_s=8.0)
    kb = GFKB(data_dir=data, capacity=cap, dim=dim)
    comp = sc.notes["compression"]
    now0 = time.time()
    for e in sc.events:
        if e["klass"] != "ingest":
            continue
        res = kb.upsert_failures_batch([
            {"failure_type": "hallucinated_citation",
             "signature_text": t["prompt"],
             "app_id": e["app_id"], "impact_severity": "high"}
            for t in e["body"]["traces"]
        ])
        # Stamp the touched records at the event's VIRTUAL timestamp —
        # upsert returns the stored objects, so age_rows sees cohort k as
        # k virtual weeks old even though the whole replay took seconds.
        vts = datetime.datetime.fromtimestamp(
            now0 + e["t"] * comp, tz=datetime.timezone.utc
        )
        with kb._lock:
            for rec, _created in res:
                rec.updated_at = vts
    bytes_before = (data / "failures.jsonl").stat().st_size
    rows_before = len(kb._records)
    now_virtual = now0 + sc.duration_s * comp
    aged = kb.age_rows(ttl_s=sc.notes["age_ttl_virtual_s"], now=now_virtual)
    out = kb.compact()
    kb.close()
    print(json.dumps({
        "rows": rows_before,
        "aged": aged["tombstoned"],
        "bytes_before": bytes_before,
        "bytes_after": (data / "failures.jsonl").stat().st_size
        + (data / "tombstones.jsonl").stat().st_size,
        "compact": out,
    }))
else:
    raise SystemExit(f"unknown mode {mode}")
'''


def _bench_recovery(backend: str) -> dict:
    """GFKB durability-lifecycle certification, self-certifying end to end.

    Four sub-certifications, each of which RAISES on failure (ISSUE 18):
    (1) restart-replay wall at ``KAKVEDA_BENCH_RECOVERY_N × _VERSIONS``
    log lines (default 10k signatures × 30 occurrence bumps = 300k —
    the months-of-recurrences shape the lifecycle exists for: a
    signature recurring daily for a month appends 30 update lines the
    checkpoint folds into one) must improve ≥
    ``KAKVEDA_BENCH_RECOVERY_IMPROVE``× (default 5×) after checkpoint+
    delta compaction; (2) recall@1 parity on a held-out warn set vs the
    uncompacted oracle (top-1 id equal, or score tie within 1e-5); (3)
    the month-compressed aging scenario tombstones its expired cohorts
    and ends with failures-log+tombstone bytes strictly below the
    uncompacted log (resident-bytes bound); (4) the crash-point sweep
    over every lifecycle kill offset reports ``corrupt_recoveries == 0``.

    Host-durability by design: every store open/seed/compact runs in a
    CPU-pinned child process (the sitecustomize TPU pin is overridden
    in-child), so this metric survives a chip outage and never holds —
    or wedges — the device lease.
    """
    import shutil
    import subprocess
    import tempfile
    from pathlib import Path

    n = int(os.environ.get("KAKVEDA_BENCH_RECOVERY_N", 10_000))
    versions = int(os.environ.get("KAKVEDA_BENCH_RECOVERY_VERSIONS", 30))
    n_queries = int(os.environ.get("KAKVEDA_BENCH_RECOVERY_QUERIES", 64))
    improve_min = float(os.environ.get("KAKVEDA_BENCH_RECOVERY_IMPROVE", 5.0))
    cap = int(os.environ.get("KAKVEDA_BENCH_RECOVERY_CAP", 2048))
    dim = 256
    print(
        f"bench[recovery]: n={n} versions={versions} queries={n_queries} "
        f"improve_min={improve_min}x",
        file=sys.stderr,
    )

    env = {k: v for k, v in os.environ.items() if not k.startswith("KAKVEDA_")}
    # Tiered serving shape: rows past the hot cap live in the host warm
    # tier, which is the realistic ≥100k-row production profile AND what
    # the restore path is optimized for (device scatter for hot rows
    # only, numpy install for warm).
    env["KAKVEDA_GFKB_HOT_ROWS"] = str(cap)

    def child(mode: str, data: Path, stdin: str = "") -> dict:
        proc = subprocess.run(
            [sys.executable, "-c", _RECOVERY_CHILD, mode, str(data),
             str(cap), str(dim), str(n), str(versions)],
            input=stdin, capture_output=True, text=True, env=env,
            timeout=3600,
        )
        if proc.returncode != 0:
            raise RuntimeError(
                f"bench[recovery] {mode} child failed rc={proc.returncode}:\n"
                f"{proc.stderr[-2000:]}"
            )
        return json.loads(proc.stdout.strip().splitlines()[-1])

    root = Path(tempfile.mkdtemp(prefix="kakveda-recovery-"))
    try:
        store = root / "store"
        store.mkdir()
        seeded = child("seed", store)
        print(
            f"bench[recovery]: seeded {seeded['log_lines']:,} log lines "
            f"({seeded['log_bytes']:,}B) in {seeded['seed_s']}s",
            file=sys.stderr,
        )
        rng = np.random.default_rng(23)
        queries = [
            f"recovery bench failure signature {i} stack frame worker pool "
            f"shard {i % 17}"
            for i in rng.integers(0, n, size=n_queries).tolist()
        ]
        qjson = json.dumps(queries)

        # Uncompacted oracle: replay the full version-append history.
        pre = child("open", store, stdin=qjson)
        # Compact, then reopen: checkpoint + (empty) delta.
        child("compact", store)
        post = child("open", store, stdin=qjson)
        improve = pre["open_s"] / max(post["open_s"], 1e-9)
        parity = [
            a is None and b is None
            or (a is not None and b is not None
                and (a[0] == b[0] or b[1] >= a[1] - 1e-5))
            for a, b in zip(pre["top1"], post["top1"])
        ]
        recall = float(np.mean(parity))
        print(
            f"bench[recovery]: replay {pre['open_s']}s -> {post['open_s']}s "
            f"({improve:.1f}x) recall@1={recall:.4f}",
            file=sys.stderr,
        )
        if improve < improve_min:
            raise RuntimeError(
                f"bench[recovery]: compaction replay speedup {improve:.2f}x "
                f"< required {improve_min}x"
            )
        if recall < 1.0:
            raise RuntimeError(
                f"bench[recovery]: recall@1 parity {recall:.4f} < 1.0 vs "
                f"uncompacted oracle"
            )

        # Month-compressed aging scenario: resident-bytes bound.
        aging_dir = root / "aging"
        aging_dir.mkdir()
        aging = child("aging", aging_dir)
        print(
            f"bench[recovery]: aging scenario rows={aging['rows']} "
            f"aged={aging['aged']} bytes {aging['bytes_before']:,} -> "
            f"{aging['bytes_after']:,}",
            file=sys.stderr,
        )
        if aging["aged"] <= 0:
            raise RuntimeError(
                "bench[recovery]: aging scenario tombstoned no rows"
            )
        if aging["bytes_after"] >= aging["bytes_before"]:
            raise RuntimeError(
                f"bench[recovery]: resident bytes not bound after aging "
                f"({aging['bytes_before']} -> {aging['bytes_after']})"
            )

        # Crash-point sweep: every lifecycle kill offset must recover.
        from kakveda_tpu.index.crashsweep import run_sweep

        sweep = run_sweep(rows=8, aged=4)
        print(
            f"bench[recovery]: crash sweep kill_points="
            f"{sweep['kill_points']} corrupt={sweep['corrupt_recoveries']}",
            file=sys.stderr,
        )
        if sweep["corrupt_recoveries"] != 0:
            raise RuntimeError(
                f"bench[recovery]: crash sweep found "
                f"{sweep['corrupt_recoveries']} corrupt recoveries: "
                f"{sweep['failures'][:3]}"
            )

        return {
            "metric": f"recovery_replay_speedup_at_{n * versions}_lines",
            "value": round(improve, 2),
            "unit": "x",
            "vs_baseline": round(improve, 1),
            "replay_uncompacted_s": pre["open_s"],
            "replay_compacted_s": post["open_s"],
            "log_bytes": seeded["log_bytes"],
            "log_lines": seeded["log_lines"],
            "recall_at1": round(recall, 4),
            "recall_ok": bool(recall >= 1.0),
            "speedup_ok": bool(improve >= improve_min),
            "aging": {
                "rows": aging["rows"],
                "aged": aging["aged"],
                "bytes_before": aging["bytes_before"],
                "bytes_after": aging["bytes_after"],
                "bytes_bound_ok": True,
            },
            "crash_sweep": {
                "kill_points": sweep["kill_points"],
                "corrupt_recoveries": sweep["corrupt_recoveries"],
                "sites": sweep["sites"],
            },
        }
    finally:
        shutil.rmtree(root, ignore_errors=True)


def _metrics_plane() -> dict:
    """Compact snapshot of the process-global metrics registry, folded
    into every emitted bench JSON line: BENCH_*.json then carries the
    acceptance/gate/prefix-hit trajectories the metrics the run generated
    — not just the headline walls. Zero-valued series are dropped."""
    try:
        from kakveda_tpu.core.metrics import get_registry

        return get_registry().snapshot(compact=True)
    except Exception:  # noqa: BLE001 — telemetry must never sink a bench line
        return {}


def _trace_plane() -> dict:
    """Counters of the process-global causal tracer (core/trace.py),
    folded into every bench JSON line next to metrics_plane: spans
    started/ended/recorded/dropped plus the orphan count (started minus
    ended — a nonzero value means some span never terminated, the trace
    analogue of a lost warn)."""
    try:
        from kakveda_tpu.core.trace import get_tracer

        return get_tracer().plane()
    except Exception:  # noqa: BLE001 — telemetry must never sink a bench line
        return {}


def _lint_findings() -> int:
    """Invariant-lint finding count over this tree (the AST rules of
    scripts/lint_invariants.py, docs/static-analysis.md), folded into the
    bench JSON line so every BENCH_r{N}.json records whether the design
    contracts held at measurement time. 0 = clean; -1 = the linter itself
    failed (never sink a bench line over telemetry)."""
    try:
        from pathlib import Path

        from kakveda_tpu.analysis.framework import run_lint

        return len(run_lint(Path(__file__).resolve().parent).findings)
    except Exception:  # noqa: BLE001 — lint telemetry must never sink a bench line
        return -1


_CONCURRENCY_RULES = ("lockset-race", "lock-order", "event-loop-blocking",
                      "unjoined-thread")


def _concurrency_findings() -> int:
    """Finding count of the static concurrency pass alone (lockset races,
    lock-order cycles, event-loop blockers, unjoined threads) — split out
    from lint_findings so a regression in thread discipline is visible as
    its own number. 0 = clean; -1 = linter failure."""
    try:
        from pathlib import Path

        from kakveda_tpu.analysis.framework import run_lint

        res = run_lint(Path(__file__).resolve().parent,
                       rule_ids=_CONCURRENCY_RULES)
        return len(res.findings)
    except Exception:  # noqa: BLE001 — lint telemetry must never sink a bench line
        return -1


_DEVICE_RULES = ("constant-capture", "donation-after-use",
                 "dynamic-slice-by-trace", "host-sync", "retrace-hazard")


def _device_findings() -> int:
    """Finding count of the static device-plane pass alone (retrace
    hazards, donation-after-use, constant capture, traced-size slices,
    host syncs) — split out from lint_findings so a regression in
    device-plane hygiene is visible as its own number. 0 = clean;
    -1 = linter failure."""
    try:
        from pathlib import Path

        from kakveda_tpu.analysis.framework import run_lint

        res = run_lint(Path(__file__).resolve().parent,
                       rule_ids=_DEVICE_RULES)
        return len(res.findings)
    except Exception:  # noqa: BLE001 — lint telemetry must never sink a bench line
        return -1


def _ledger_plane() -> dict:
    """Compile-and-transfer ledger evidence for the bench line, when armed
    (KAKVEDA_LEDGER=1): total XLA backend compiles attributed so far,
    compiles seen after the bench marked itself warm (the runtime twin of
    the static retrace-hazard rule — nonzero means something retraced on
    the measured path), and host<->device bytes by direction. Empty dict
    when the ledger is not installed."""
    try:
        from kakveda_tpu.core import ledger

        if not ledger.installed():
            return {}
        rep = ledger.ledger_report()
        return {
            "compile_total": rep["compile_total"],
            "post_warmup_compiles": rep["post_warmup_compiles"],
            "transfer_bytes": rep["transfer_bytes"],
        }
    except Exception:  # noqa: BLE001 — telemetry must never sink a bench line
        return {}


def _ledger_reset() -> bool:
    """Arm a per-metric ledger window: reset the tables (the warm flag
    included) and report whether the ledger is live. Each self-certifying
    bench calls this up front so its assertions see only its own window."""
    try:
        from kakveda_tpu.core import ledger

        if not ledger.installed():
            return False
        ledger.reset()
        return True
    except Exception:  # noqa: BLE001 — telemetry must never sink a bench line
        return False


def _ledger_mark_warm() -> None:
    try:
        from kakveda_tpu.core import ledger

        if ledger.installed():
            ledger.mark_warm()
    except Exception:  # noqa: BLE001 — telemetry must never sink a bench line
        pass


def _ledger_certify(metric: str, max_per_fn: "int | None" = None) -> dict:
    """Close a per-metric ledger window: return the plane for the bench
    row and RAISE (self-certifying, like the mine purity floor) when the
    window saw post-warmup compiles, or — with ``max_per_fn`` — when any
    single entry point compiled more than the O(log N) pow2-bucket
    envelope allows. No-op ({}) when the ledger is not installed."""
    try:
        from kakveda_tpu.core import ledger

        if not ledger.installed():
            return {}
        rep = ledger.ledger_report()
    except Exception:  # noqa: BLE001 — telemetry must never sink a bench line
        return {}
    if rep["warm"] and rep["post_warmup_compiles"]:
        raise AssertionError(
            f"{metric}: {rep['post_warmup_compiles']} post-warmup XLA "
            f"compile(s) on the measured path — something retraced: "
            f"{rep['post_warmup']}"
        )
    if max_per_fn is not None and rep["compiles"]:
        worst = max(rep["compiles"], key=rep["compiles"].get)
        if rep["compiles"][worst] > max_per_fn:
            raise AssertionError(
                f"{metric}: entry {worst!r} compiled {rep['compiles'][worst]} "
                f"times, past the O(log N) envelope of {max_per_fn} — "
                f"shapes are not bucketing: {rep['compiles']}"
            )
    return {
        "compile_total": rep["compile_total"],
        "compiles": rep["compiles"],
        "post_warmup_compiles": rep["post_warmup_compiles"],
        "transfer_bytes": rep["transfer_bytes"],
    }


def _sanitizer_plane() -> dict:
    """Runtime-sanitizer evidence for the bench line, when armed
    (KAKVEDA_SANITIZE=1): loop stalls seen, distinct lock-order edges
    observed, and any cycles among them. Empty dict when disarmed."""
    try:
        from kakveda_tpu.core import sanitize

        rep = sanitize.sanitizer_report()
        if not rep["enabled"] and not rep["edges"] and not rep["stalls"]:
            return {}
        return {
            "sanitizer_stalls": len(rep["stalls"]),
            "lock_order_edges": len(rep["edges"]),
            "lock_order_cycles": len(rep["cycles"]),
        }
    except Exception:  # noqa: BLE001 — telemetry must never sink a bench line
        return {}


def load_resumable_partial(partial_path: str, backend: str) -> dict:
    """Load already-measured metrics from a prior wedged sweep.

    Resume is ON by default: after a mid-sweep wedge, re-running measures
    only what's missing. Stale partials can't masquerade as fresh runs:
    the file is deleted after a fully successful sweep, and resume refuses
    partials older than KAKVEDA_BENCH_RESUME_MAX_AGE (default 6h) or from
    a different backend. KAKVEDA_BENCH_RESUME=0 disables resume entirely.
    """
    if not partial_path or os.environ.get("KAKVEDA_BENCH_RESUME", "1") != "1":
        return {}
    resume_max_age = float(os.environ.get("KAKVEDA_BENCH_RESUME_MAX_AGE", 6 * 3600))
    try:
        with open(partial_path) as f:
            prior = json.load(f)
    except FileNotFoundError:
        return {}
    except (OSError, ValueError) as e:
        print(f"bench: resume load failed ({e}); fresh run", file=sys.stderr)
        return {}
    if prior.get("complete"):
        print(
            "bench: partial file records a finished sweep; re-measuring fresh "
            "(complete partials are outage evidence, not resume state)",
            file=sys.stderr,
        )
        return {}
    age = time.time() - float(prior.get("ts", 0))
    if prior.get("backend") != backend:
        print(
            f"bench: partial file is from backend {prior.get('backend')!r}, "
            f"not {backend!r}; ignoring it",
            file=sys.stderr,
        )
        return {}
    if age > resume_max_age:
        print(
            f"bench: partial file is {age / 3600:.1f}h old "
            f"(max {resume_max_age / 3600:.1f}h); fresh run",
            file=sys.stderr,
        )
        return {}
    done = dict(prior.get("done", {}))
    print(f"bench: resuming — {sorted(done)} already measured", file=sys.stderr)
    return done


def main() -> int:
    import threading

    import jax

    # Honor JAX_PLATFORMS=cpu explicitly: this image's sitecustomize pins
    # jax to the remote accelerator via jax.config, which the env var alone
    # does not override — without this a "CPU" bench run would still claim
    # (or block on) the device lease.
    # (Honoring any value — not just "cpu" — also gives tests a fast
    # outage simulation: JAX_PLATFORMS=nonexistent raises immediately
    # instead of blocking in the remote claim loop.)
    env_platforms = os.environ.get("JAX_PLATFORMS", "")
    if env_platforms:
        try:
            jax.config.update("jax_platforms", env_platforms.lower())
        except Exception:
            pass

    # Arm the compile-and-transfer ledger (no-op unless KAKVEDA_LEDGER=1)
    # BEFORE any kakveda model/ops module imports: jits created after
    # install self-label with their function names, so compile counts
    # attribute to real entry points instead of "unattributed".
    try:
        from kakveda_tpu.core import ledger as _ledger_mod

        _ledger_mod.maybe_install()
    except Exception:  # noqa: BLE001 — telemetry must never sink a bench run
        pass

    # Backend-init watchdog with retry/backoff: a wedged accelerator lease
    # (e.g. a killed process still holding the remote chip) blocks
    # jax.default_backend() in an indefinite claim loop, and a transient
    # outage raises UNAVAILABLE. Neither should zero a whole bench round:
    #  - while the claim thread is merely *blocked*, keep waiting in rounds
    #    (the in-process claim loop keeps trying; killing it would wedge the
    #    remote lease for hours — never SIGTERM a claim in progress);
    #  - if init *raises*, clear the cached backend error, back off, retry.
    # KAKVEDA_BENCH_INIT_TIMEOUT: seconds per wait round (default 600).
    # KAKVEDA_BENCH_INIT_RETRIES: extra rounds after the first (default 2).
    # KAKVEDA_BENCH_INIT_BACKOFF: sleep before re-init after a raise (default 60).
    init_timeout = float(os.environ.get("KAKVEDA_BENCH_INIT_TIMEOUT", 600))
    init_retries = int(os.environ.get("KAKVEDA_BENCH_INIT_RETRIES", 2))
    init_backoff = float(os.environ.get("KAKVEDA_BENCH_INIT_BACKOFF", 60))
    backend = None
    box: dict = {}
    thread: threading.Thread | None = None
    for attempt in range(init_retries + 1):
        if thread is None or not thread.is_alive():
            if "error" in box:
                # Previous attempt raised: reset jax's cached init failure
                # and back off before claiming again.
                box.clear()
                try:
                    import jax.extend.backend as _jeb

                    _jeb.clear_backends()
                except Exception:  # noqa: BLE001 — best effort; retry anyway
                    pass
                time.sleep(init_backoff)

            def _init():
                try:
                    box["backend"] = jax.default_backend()
                except Exception as e:  # noqa: BLE001
                    box["error"] = e

            thread = threading.Thread(target=_init, daemon=True)
            thread.start()
        thread.join(init_timeout)
        if "backend" in box:
            backend = box["backend"]
            break
        if "error" in box:
            err = box["error"]
            print(
                f"bench: backend init failed (attempt {attempt + 1}/"
                f"{init_retries + 1}): {type(err).__name__}: {err}",
                file=sys.stderr,
            )
        else:
            print(
                f"bench: accelerator backend still blocked after round "
                f"{attempt + 1}/{init_retries + 1} "
                f"({init_timeout:.0f}s each; wedged device lease?) — claim "
                "thread left running",
                file=sys.stderr,
            )
    if backend is None:
        # Chip outage: still emit ONE machine-readable JSON line so the
        # driver's `parsed` field records the outage plus any metrics a
        # prior attempt already measured (from the partial-flush file),
        # instead of a bare traceback with parsed=null (see BENCH_r04).
        err = box.get("error")
        if err is not None:
            import traceback

            traceback.print_exception(err, file=sys.stderr)
            reason = f"{type(err).__name__}: {err}"
        else:
            reason = (
                f"backend init still blocked after "
                f"{(init_retries + 1) * init_timeout:.0f}s (wedged device lease?)"
            )
            print(f"bench: {reason}; aborting", file=sys.stderr)
        partial: dict = {}
        ppath = os.environ.get("KAKVEDA_BENCH_PARTIAL", ".bench_partial.json")
        try:
            with open(ppath) as f:
                partial = json.load(f)
        except (OSError, ValueError):
            pass
        print(
            json.dumps(
                {
                    "metric": "chip_unavailable",
                    "value": 1,
                    "unit": "flag",
                    "vs_baseline": 0.0,
                    "chip_unavailable": True,
                    "error": reason[:500],
                    "partial": partial,
                }
            )
        )
        # Default rc 0: the run met its contract (one parseable status
        # line); callers that treat nonzero stdout as garbage would
        # otherwise drop the outage record. KAKVEDA_BENCH_OUTAGE_RC=1
        # restores fail-loud behavior for CI-style callers.
        return int(os.environ.get("KAKVEDA_BENCH_OUTAGE_RC", "0"))
    which = os.environ.get("KAKVEDA_BENCH_METRIC", "all")

    fns = {
        "warn": _bench_warn,
        "ingest": _bench_ingest,
        "decode": _bench_decode,
        "mixed": _bench_mixed,
        "mixed-decode": _bench_mixed_decode,
        "mine": _bench_mine,
        "continuous": _bench_continuous,
        "spec": _bench_spec,
        "pallas": _bench_pallas,
        "serve": _bench_serve,
        "overload": _bench_overload,
        "tiered": _bench_tiered,
        "recovery": _bench_recovery,
        "fleet": _bench_fleet,
        "ownership": _bench_ownership,
        "storm": _bench_storm,
        "tenants": _bench_tenants,
        "elastic": _bench_elastic,
    }
    if which in fns:
        out = fns[which](backend)
        out["metrics_plane"] = _metrics_plane()
        out["trace_plane"] = _trace_plane()
        out["lint_findings"] = _lint_findings()
        out["concurrency_findings"] = _concurrency_findings()
        out["device_findings"] = _device_findings()
        out.update(_sanitizer_plane())
        out.update(_ledger_plane())
        print(json.dumps(out))
        return 0

    # Default: every metric in one run, one JSON line — the driver records
    # the whole object, so warn + ingest + decode all land in BENCH_r{N}.json.
    # Each completed metric is also flushed to KAKVEDA_BENCH_PARTIAL
    # (default .bench_partial.json) so a later metric wedging — or the
    # driver timing the run out — cannot erase numbers already measured.
    # KAKVEDA_BENCH_RESUME=1 preloads that file and skips metrics it
    # already holds: re-running after a mid-sweep wedge re-measures only
    # what's missing instead of burning another hour on a flaky lease.
    partial_path = os.environ.get("KAKVEDA_BENCH_PARTIAL", ".bench_partial.json")
    done = load_resumable_partial(partial_path, backend)

    def _flush_partial():
        if not partial_path:
            return
        try:
            tmp = partial_path + ".tmp"
            with open(tmp, "w") as f:
                json.dump({"backend": backend, "ts": time.time(), "done": done}, f)
            os.replace(tmp, partial_path)
        except OSError as e:
            print(f"bench: partial flush failed: {e}", file=sys.stderr)

    order = (
        _bench_warn,
        _bench_pallas,
        _bench_ingest,
        _bench_decode,
        _bench_spec,
        _bench_continuous,
        _bench_serve,
        _bench_overload,
        _bench_mixed,
        _bench_mixed_decode,
        _bench_mine,
        _bench_tiered,
        _bench_recovery,
        _bench_fleet,
        _bench_ownership,
        _bench_storm,
        _bench_tenants,
        _bench_elastic,
    )
    for fn in order:
        if fn.__name__ in done:
            continue
        t_metric = time.perf_counter()
        try:
            done[fn.__name__] = fn(backend)
            print(
                f"bench: {fn.__name__} done in {time.perf_counter() - t_metric:.1f}s",
                file=sys.stderr,
            )
        except Exception as e:  # noqa: BLE001 — one failed metric must not hide the others
            print(f"bench: {fn.__name__} failed: {type(e).__name__}: {e}", file=sys.stderr)
        _flush_partial()
    results = [done[fn.__name__] for fn in order if fn.__name__ in done]
    if not results:
        return 1
    if partial_path and all(fn.__name__ in done for fn in order):
        # Fully successful sweep: mark the partial complete instead of
        # deleting it. A complete partial is never resumed from (so a later
        # run with a live chip re-measures everything fresh), but if that
        # later run hits an outage, its chip_unavailable line still carries
        # these numbers as evidence of the last full sweep.
        try:
            tmp = partial_path + ".tmp"
            with open(tmp, "w") as f:
                json.dump(
                    {"backend": backend, "ts": time.time(), "done": done, "complete": True}, f
                )
            os.replace(tmp, partial_path)
        except OSError:
            pass
    headline = results[0]
    headline["extra_metrics"] = results[1:]
    headline["metrics_plane"] = _metrics_plane()
    headline["trace_plane"] = _trace_plane()
    headline["lint_findings"] = _lint_findings()
    headline["concurrency_findings"] = _concurrency_findings()
    headline["device_findings"] = _device_findings()
    headline.update(_sanitizer_plane())
    headline.update(_ledger_plane())
    print(json.dumps(headline))
    return 0


if __name__ == "__main__":
    sys.exit(main())
