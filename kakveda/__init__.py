"""Import-path compatibility alias: ``kakveda.*`` → ``kakveda_tpu.*``.

Capability parity with the reference's root-level alias package
(reference: shared/__init__.py:1-6, which re-exports services.shared.* as
shared.* so deployment images and test paths resolve either way), done
properly for a whole package tree: a meta-path finder resolves any
``kakveda.X.Y`` import to the *same module object* as ``kakveda_tpu.X.Y``,
so classes, singletons, and module state are never duplicated between the
two spellings.
"""

from __future__ import annotations

import importlib
import importlib.abc
import importlib.util
import sys
import types

_TARGET = "kakveda_tpu"

_pkg = importlib.import_module(_TARGET)
__version__ = getattr(_pkg, "__version__", "0")


class _AliasLoader(importlib.abc.Loader):
    """Hands the already-imported real module back to the import machinery."""

    _KEEP = ("__name__", "__spec__", "__loader__", "__package__")

    def __init__(self, module: types.ModuleType):
        self._module = module
        self._saved = {k: getattr(module, k, None) for k in self._KEEP}

    def create_module(self, spec):
        return self._module

    def exec_module(self, module):
        # The machinery re-stamps __name__/__spec__/… with the alias spec in
        # module_from_spec; restore the real identity so tooling that reads
        # module metadata (pickling, repr, importlib.reload) is unaffected.
        for key, value in self._saved.items():
            if value is not None:
                setattr(module, key, value)


class _AliasFinder(importlib.abc.MetaPathFinder):
    def find_spec(self, fullname, path=None, target=None):
        if not fullname.startswith(__name__ + "."):
            return None
        real_name = _TARGET + fullname[len(__name__):]
        try:
            module = importlib.import_module(real_name)
        except ModuleNotFoundError:
            return None
        return importlib.util.spec_from_loader(fullname, _AliasLoader(module))


# Idempotent under re-import of this package.
if not any(isinstance(f, _AliasFinder) for f in sys.meta_path):
    sys.meta_path.insert(0, _AliasFinder())


_MISSING = object()


def __getattr__(name: str):
    value = getattr(_pkg, name, _MISSING)
    if value is not _MISSING:  # None-valued attributes are real (optional deps)
        return value
    try:
        return importlib.import_module(f"{__name__}.{name}")
    except ModuleNotFoundError:
        # hasattr()/getattr-with-default probes must see AttributeError.
        raise AttributeError(f"module {__name__!r} has no attribute {name!r}") from None
