"""kakveda-tpu: a TPU-native LLM failure-intelligence platform.

A ground-up JAX/XLA re-design of the capabilities of
``prateekdevisingh/kakveda`` (see SURVEY.md): traces are ingested and
classified into failures, failures are canonicalized into a Global Failure
Knowledge Base (GFKB), recurring failures become patterns, new executions get
pre-flight "this failed before" warnings via similarity matching, and per-app
health is scored over time.

Where the reference runs nine FastAPI containers talking JSON-over-HTTP with
a per-query TF-IDF refit over a JSONL file, this framework keeps one
device-resident intelligence core: hashed n-gram failure embeddings, a
sharded HBM-resident GFKB index answering cosine-kNN pre-flight matches, batch
clustering for pattern mining, and an in-tree JAX Llama replacing the Ollama
HTTP model calls — all sharded with ``jax.sharding`` over a TPU mesh. A thin
host service layer (aiohttp) keeps the reference's external REST/event
contracts.
"""

__version__ = "0.3.0"
