"""Static analysis: machine-enforced design contracts.

CLAUDE.md's invariants ("gate transitions go through ``_set_gate_state``
ONLY", "every forward path must honor a new ``LlamaConfig`` flag", "batcher
stat mutations hold ``cb.stats_lock``") were prose until this package: a
dependency-free AST lint framework (:mod:`framework`) plus the
project-specific rules (:mod:`rules`) that encode them, run via
``python scripts/lint_invariants.py`` and enforced in tier-1 by
``tests/test_lint_invariants.py``. ``scripts/check_knobs.py``'s knob/
fault-site parity checks live here too (:mod:`knobs`) so both entry points
share one source-tree discovery helper (:mod:`discovery`).

Deliberately imports NOTHING heavy — no jax, no numpy — so the lint runs
in well under a second and tier-1 can gate on it without a backend.
Rule catalog: docs/static-analysis.md.
"""
