"""Concurrency rules: lockset races, lock-order cycles, event-loop
blocking, thread lifecycle.

The static half of the concurrency sanitizer (runtime half:
:mod:`kakveda_tpu.core.sanitize`). Four rules in the PR-6 framework, same
pragma/baseline/exit-code semantics:

* **lockset-race** — Eraser-style, adapted to this tree's thread-entry
  seams. Per class, discover the contexts code runs in (``threading.
  Thread(target=…)``/``Timer`` targets, ``run_in_executor``/``to_thread``
  callees, ``async def`` = the event-loop plane, everything else = the
  caller's thread) and the locks the class owns. Flag a ``self._*``
  attribute that is (a) accessed under a lock somewhere but MUTATED
  without it elsewhere, or (b) mutated from ≥2 distinct contexts with no
  common lexical guard. Single-writer-by-design fields document their
  discipline with ``# kakveda: owned-by[<context>]`` on the mutation or
  the ``__init__`` declaration — an annotation, not a silent suppression.
* **lock-order** — build the global lock-acquisition graph (lexical
  ``with`` nesting, plus calls that transitively acquire: same-class
  ``self.m()`` and ``self.attr.m()`` where ``__init__`` pins ``attr`` to
  a known class) and flag cycles. Node ids (``ClassName._attr``) match
  :func:`kakveda_tpu.core.sanitize.named_lock` names so the runtime edge
  set cross-checks against this graph.
* **event-loop-blocking** — sync blocking calls (``time.sleep``,
  ``.result()``, sync httpx/requests, file I/O, ``lock.acquire()``,
  device sync, subprocess) lexically inside ``async def`` bodies on the
  HTTP planes. Code inside a nested ``def``/``lambda`` is exempt — that
  is exactly the ``run_in_executor``/``to_thread`` thunk idiom. Also
  flags ``with <lock>`` in an async body when the same file acquires
  that lock from a spawned worker thread (a loop blocked behind a
  worker's critical section).
* **unjoined-thread** — every spawned ``Thread``/``Timer`` must be
  daemonized (``daemon=True`` kwarg or ``.daemon = True`` before start)
  or joined/cancelled somewhere on a close path.

Shared idiom notes: methods named ``*_locked`` and methods whose
docstring says "caller holds …" are treated as running under every lock
their class owns (the tree's caller-holds convention — single-writer
helpers like ``_set_brownout_state`` rely on it).
"""

from __future__ import annotations

import ast
from pathlib import Path
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

from kakveda_tpu.analysis.framework import (
    FileContext,
    Finding,
    Rule,
    TreeContext,
    register,
)
from kakveda_tpu.analysis.rules import _const_str, _parent_map, _self_attr
from kakveda_tpu.core.sanitize import find_cycles

# Container-mutating method names that count as writes in the lockset
# analysis. Thread-safe primitives' verbs (Event.set, Queue.put) are
# deliberately absent — they synchronize internally.
_MUTATORS = frozenset({
    "append", "extend", "insert", "add", "discard", "remove", "update",
    "setdefault", "pop", "popitem", "clear", "appendleft", "popleft",
})

_INIT_METHODS = frozenset({"__init__", "__new__", "__post_init__", "__del__"})


def _docstring(node: ast.AST) -> str:
    try:
        return ast.get_docstring(node) or ""
    except TypeError:
        return ""


def _caller_holds(meth: ast.AST) -> bool:
    """The tree's caller-holds-the-lock convention: ``*_locked`` names or
    a docstring saying so."""
    name = getattr(meth, "name", "")
    if name.endswith("_locked"):
        return True
    doc = _docstring(meth).lower()
    return "caller holds" in doc or "callers hold" in doc


# ---------------------------------------------------------------------------
# per-file class models (shared by all four rules; cached on the FileContext)
# ---------------------------------------------------------------------------


class _ClassModel:
    def __init__(self, name: str, node: ast.ClassDef):
        self.name = name
        self.node = node
        # method name -> def node (class-level only; nested defs excluded)
        self.methods: Dict[str, ast.AST] = {}
        # lock-holding attr -> stable lock node id. Conditions built over a
        # class lock alias to the SAME id (``with self._cv`` holds _lock).
        self.locks: Dict[str, str] = {}
        # self.attr -> class name candidates from __init__ construction
        self.attr_types: Dict[str, str] = {}
        # method -> context labels ("loop"/"thread"/"executor"/"caller")
        self.labels: Dict[str, Set[str]] = {}
        # methods directly spawned (Thread/Timer target, executor callee)
        self.spawn_entries: Set[str] = set()


class _FileModel:
    def __init__(self, fc: FileContext):
        self.fc = fc
        self.stem = Path(fc.rel).stem
        self.classes: Dict[str, _ClassModel] = {}
        self.module_locks: Dict[str, str] = {}  # module var -> lock id
        if fc.tree is None:
            return
        for node in fc.tree.body:  # type: ignore[union-attr]
            if isinstance(node, ast.ClassDef):
                self.classes[node.name] = self._build_class(node)
            elif isinstance(node, ast.Assign) and len(node.targets) == 1:
                tgt = node.targets[0]
                if isinstance(tgt, ast.Name):
                    lid = _lock_ctor_id(node.value, owner=self.stem,
                                        attr=tgt.id)
                    if lid is not None:
                        self.module_locks[tgt.id] = lid

    def _build_class(self, cnode: ast.ClassDef) -> _ClassModel:
        cm = _ClassModel(cnode.name, cnode)
        for item in cnode.body:
            if isinstance(item, (ast.FunctionDef, ast.AsyncFunctionDef)):
                cm.methods[item.name] = item
        # Lock attrs + attr types: every `self.X = …` assignment anywhere
        # in the class (locks are occasionally built outside __init__).
        for meth in cm.methods.values():
            for node in ast.walk(meth):
                if not (isinstance(node, ast.Assign) and len(node.targets) == 1):
                    continue
                attr = _self_attr(node.targets[0])
                if attr is None:
                    continue
                lid = _lock_ctor_id(node.value, owner=cm.name, attr=attr)
                if lid is not None:
                    cm.locks[attr] = lid
                    continue
                alias = _condition_over(node.value)
                if alias is not None and alias in cm.locks:
                    cm.locks[attr] = cm.locks[alias]
                    continue
                ctor = _constructed_class(node.value)
                if ctor is not None:
                    cm.attr_types[attr] = ctor
        _label_contexts(cm)
        return cm


def _lock_ctor_id(value: ast.AST, owner: str, attr: str) -> Optional[str]:
    """If ``value`` constructs a lock, its stable node id: the
    ``named_lock("…")`` literal when present (the runtime sanitizer uses
    the same string), else ``Owner.attr``."""
    if not isinstance(value, ast.Call):
        return None
    fn = value.func
    fname = fn.attr if isinstance(fn, ast.Attribute) else (
        fn.id if isinstance(fn, ast.Name) else None)
    if fname == "named_lock" and value.args:
        lit = _const_str(value.args[0])
        if lit:
            return lit
    if fname in ("Lock", "RLock"):
        return f"{owner}.{attr}"
    return None


def _condition_over(value: ast.AST) -> Optional[str]:
    """``threading.Condition(self.X)`` -> ``X`` (holding the condition IS
    holding the underlying lock)."""
    if isinstance(value, ast.Call):
        fn = value.func
        fname = fn.attr if isinstance(fn, ast.Attribute) else (
            fn.id if isinstance(fn, ast.Name) else None)
        if fname == "Condition" and value.args:
            return _self_attr(value.args[0])
    return None


def _constructed_class(value: ast.AST) -> Optional[str]:
    """The single CapWords class constructed anywhere in ``value`` (for
    ``self.brownout = brownout or BrownoutController(…)``), else None."""
    names = {
        n.func.id for n in ast.walk(value)
        if isinstance(n, ast.Call) and isinstance(n.func, ast.Name)
        and n.func.id[:1].isupper()
    }
    return names.pop() if len(names) == 1 else None


def _self_method_of(call_arg: ast.AST) -> Optional[str]:
    return _self_attr(call_arg)


def _label_contexts(cm: _ClassModel) -> None:
    """Assign each method the thread contexts it may run in, propagated
    through the class's ``self.m()`` call graph."""
    labels: Dict[str, Set[str]] = {m: set() for m in cm.methods}
    calls: Dict[str, Set[str]] = {m: set() for m in cm.methods}
    for mname, meth in cm.methods.items():
        if isinstance(meth, ast.AsyncFunctionDef):
            labels[mname].add("loop")
        for node in ast.walk(meth):
            if not isinstance(node, ast.Call):
                continue
            fn = node.func
            fname = fn.attr if isinstance(fn, ast.Attribute) else (
                fn.id if isinstance(fn, ast.Name) else None)
            if fname in ("Thread", "Timer"):
                tgt = None
                for kw in node.keywords:
                    if kw.arg == "target":
                        tgt = _self_method_of(kw.value)
                if fname == "Timer" and tgt is None and len(node.args) >= 2:
                    tgt = _self_method_of(node.args[1])
                if tgt in labels:
                    labels[tgt].add("thread")
                    cm.spawn_entries.add(tgt)
            elif fname == "run_in_executor" and len(node.args) >= 2:
                tgt = _self_method_of(node.args[1])
                if tgt in labels:
                    labels[tgt].add("executor")
                    cm.spawn_entries.add(tgt)
            elif fname == "to_thread" and node.args:
                tgt = _self_method_of(node.args[0])
                if tgt in labels:
                    labels[tgt].add("executor")
                    cm.spawn_entries.add(tgt)
            elif isinstance(fn, ast.Attribute):
                callee = _self_attr(fn)
                if callee in calls:
                    calls[mname].add(callee)
    # Propagate: a callee runs in every context its callers do.
    changed = True
    while changed:
        changed = False
        for caller, callees in calls.items():
            for callee in callees:
                before = len(labels[callee])
                labels[callee] |= labels[caller]
                changed = changed or len(labels[callee]) != before
    for m in labels:
        if not labels[m]:
            labels[m] = {"caller"}
    cm.labels = labels


def _file_model(fc: FileContext) -> _FileModel:
    fm = getattr(fc, "_concurrency_model", None)
    if fm is None:
        fm = _FileModel(fc)
        fc._concurrency_model = fm  # type: ignore[attr-defined]
    return fm


def _global_maps(ctx: TreeContext):
    """Tree-wide class map and unique-owner lock-attr map, cached on ctx."""
    cached = getattr(ctx, "_concurrency_global", None)
    if cached is not None:
        return cached
    class_map: Dict[str, _ClassModel] = {}
    dropped: Set[str] = set()
    for fc in ctx.files:
        for name, cm in _file_model(fc).classes.items():
            if name in class_map or name in dropped:
                class_map.pop(name, None)  # ambiguous: two defs share a name
                dropped.add(name)
            else:
                class_map[name] = cm
    attr_owner: Dict[str, Set[str]] = {}
    for cm in class_map.values():
        for attr, lid in cm.locks.items():
            attr_owner.setdefault(attr, set()).add(lid)
    unique_owner = {a: next(iter(s)) for a, s in attr_owner.items() if len(s) == 1}
    ctx._concurrency_global = (class_map, unique_owner)  # type: ignore[attr-defined]
    return ctx._concurrency_global  # type: ignore[attr-defined]


def _resolve_lock(expr: ast.AST, cm: Optional[_ClassModel], fm: _FileModel,
                  unique_owner: Dict[str, str],
                  class_map: Dict[str, _ClassModel]) -> Optional[str]:
    """Lock node id for a ``with``-item context expression, else None."""
    attr = _self_attr(expr)
    if attr is not None:
        if cm is not None and attr in cm.locks:
            return cm.locks[attr]
        if "lock" in attr.lower() or attr.endswith("_cv"):
            owner = cm.name if cm is not None else fm.stem
            return f"{owner}.{attr}"
        return None
    if isinstance(expr, ast.Name):
        if expr.id in fm.module_locks:
            return fm.module_locks[expr.id]
        if "lock" in expr.id.lower():
            return f"{fm.stem}.{expr.id}"
        return None
    if isinstance(expr, ast.Attribute):
        # self.a.lockattr / obj.lockattr — resolve via __init__-pinned
        # types first, then the unique global owner of the attr name.
        base = _self_attr(expr.value)
        if base is not None and cm is not None:
            tname = cm.attr_types.get(base)
            tcm = class_map.get(tname) if tname else None
            if tcm is not None and expr.attr in tcm.locks:
                return tcm.locks[expr.attr]
        if expr.attr in unique_owner:
            return unique_owner[expr.attr]
    return None


# ---------------------------------------------------------------------------
# held-stack scanner
# ---------------------------------------------------------------------------


def _scan_held(node: ast.AST, held: List[str], resolve, visit) -> None:
    """Depth-first walk tracking the lexically-held lock stack. ``visit``
    is called for every node (with the current stack); nested function
    bodies are skipped — they run in their own context (and a nested
    ``def`` is exactly the executor-thunk idiom)."""
    if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
        return
    if isinstance(node, (ast.With, ast.AsyncWith)):
        acquired: List[str] = []
        for item in node.items:
            _scan_held(item.context_expr, held, resolve, visit)
            lid = resolve(item.context_expr)
            if lid is not None:
                visit("acquire", item.context_expr, lid, held)
                acquired.append(lid)
        held.extend(acquired)
        for child in node.body:
            _scan_held(child, held, resolve, visit)
        if acquired:
            del held[-len(acquired):]
        return
    visit("node", node, None, held)
    for child in ast.iter_child_nodes(node):
        _scan_held(child, held, resolve, visit)


def _scan_function(fn: ast.AST, initial_held: List[str], resolve, visit) -> None:
    for stmt in fn.body:  # type: ignore[union-attr]
        _scan_held(stmt, initial_held, resolve, visit)


# ---------------------------------------------------------------------------
# rule: lockset-race
# ---------------------------------------------------------------------------


@register
class LocksetRace(Rule):
    id = "lockset-race"
    invariant = (
        "a self._attr shared across thread contexts is mutated only under "
        "its lock (or carries an owned-by[<context>] annotation)"
    )
    scope = ("kakveda_tpu",)

    def visit_file(self, fc: FileContext, ctx: TreeContext) -> List[Finding]:
        fm = _file_model(fc)
        class_map, unique_owner = _global_maps(ctx)
        out: List[Finding] = []
        for cm in fm.classes.values():
            out.extend(self._check_class(fc, fm, cm, class_map, unique_owner))
        return out

    def _check_class(self, fc, fm, cm, class_map, unique_owner) -> List[Finding]:
        if not cm.locks:
            return []  # no lock discipline to check against
        # Entry points whose callers are outside the class: public (incl.
        # dunder) methods, async defs, and direct Thread/executor targets.
        # Everything else (private helpers) inherits its guards from its
        # call sites — ``reload()`` holding the lock around ``_replay()``
        # guards _replay's body even though the ``with`` is in the caller.
        entries: Set[str] = set(cm.spawn_entries)
        for mname, meth in cm.methods.items():
            if mname in _INIT_METHODS:
                continue
            if not mname.startswith("_") or (
                    mname.startswith("__") and mname.endswith("__")):
                entries.add(mname)
            if isinstance(meth, ast.AsyncFunctionDef):
                entries.add(mname)

        # per-method raw accesses: (attr, is_mutation, lexical guards, line)
        raw: Dict[str, List[Tuple[str, bool, frozenset, int]]] = {}
        # class-internal call sites: (caller, callee, lexical held)
        sites: List[Tuple[str, str, frozenset]] = []
        decl_lines: Dict[str, List[int]] = {}

        def resolve(expr):
            return _resolve_lock(expr, cm, fm, unique_owner, class_map)

        for mname, meth in cm.methods.items():
            is_init = mname in _INIT_METHODS
            if is_init:
                for node in ast.walk(meth):
                    if isinstance(node, ast.Assign):
                        targets = node.targets
                    elif isinstance(node, ast.AnnAssign):
                        targets = [node.target]
                    else:
                        continue
                    for tgt in targets:
                        attr = _self_attr(tgt)
                        if attr is not None:
                            decl_lines.setdefault(attr, []).append(node.lineno)
            base_held = sorted(set(cm.locks.values())) if _caller_holds(meth) else []
            acc_list = raw.setdefault(mname, [])

            def visit(kind, node, lid, held, _m=mname, _init=is_init,
                      _accs=acc_list):
                if kind != "node":
                    return
                if isinstance(node, ast.Call) and isinstance(
                        node.func, ast.Attribute):
                    callee = _self_attr(node.func)
                    if callee in cm.methods and not _init:
                        sites.append((_m, callee, frozenset(held)))
                if _init:
                    return  # pre-publication: no shared-state hazard yet
                guards = frozenset(held)
                attr = None
                mutation = False
                if isinstance(node, ast.Attribute):
                    attr = _self_attr(node)
                    mutation = isinstance(node.ctx, (ast.Store, ast.Del))
                elif isinstance(node, ast.Subscript) and isinstance(
                        node.ctx, (ast.Store, ast.Del)):
                    attr = _self_attr(node.value)
                    mutation = True
                elif isinstance(node, ast.Call) and isinstance(
                        node.func, ast.Attribute) and node.func.attr in _MUTATORS:
                    attr = _self_attr(node.func.value)
                    mutation = attr is not None
                if attr is None or not attr.startswith("_"):
                    return
                if attr in cm.locks:
                    return  # the locks themselves
                _accs.append((attr, mutation, guards, node.lineno))

            _scan_function(meth, list(base_held), resolve, visit)

        # Effective caller-held guards per method, to a fixed point.
        # None = not yet constrained (⊤); entries start at their own base.
        eff: Dict[str, Optional[frozenset]] = {}
        for mname, meth in cm.methods.items():
            if mname in _INIT_METHODS:
                continue
            if mname in entries:
                eff[mname] = frozenset(cm.locks.values()) if \
                    _caller_holds(meth) else frozenset()
            else:
                eff[mname] = None
        for _ in range(len(cm.methods) + 1):
            changed = False
            for mname in eff:
                if mname in entries:
                    continue
                contribs = []
                unknown = False
                for caller, callee, held in sites:
                    if callee != mname or caller in _INIT_METHODS:
                        continue
                    ceff = eff.get(caller, frozenset())
                    if ceff is None:
                        unknown = True
                        continue
                    contribs.append(held | ceff)
                if not contribs:
                    continue  # init-only (or unreached) — resolved below
                new = contribs[0]
                for c in contribs[1:]:
                    new = new & c
                if unknown and eff[mname] is None:
                    continue  # wait for callers to settle
                if eff[mname] is None or new != eff[mname]:
                    eff[mname] = new
                    changed = True
            if not changed:
                break

        # attr -> list of (is_mutation, effective guards, labels, lineno)
        accesses: Dict[str, List[Tuple[bool, frozenset, frozenset, int]]] = {}
        for mname, acc_list in raw.items():
            if mname in _INIT_METHODS:
                continue
            m_eff = eff.get(mname)
            if m_eff is None:
                continue  # reachable only from __init__: construction state
            labels = frozenset(cm.labels.get(mname, {"caller"}))
            for attr, mutation, guards, line in acc_list:
                accesses.setdefault(attr, []).append(
                    (mutation, guards | m_eff, labels, line))

        out: List[Finding] = []
        for attr, accs in sorted(accesses.items()):
            if self._owned(fc, accs, decl_lines.get(attr, ())):
                continue
            muts = [a for a in accs if a[0]]
            if not muts:
                continue
            guarded = [a for a in accs if a[1]]
            unguarded_muts = [a for a in muts if not a[1]]
            if guarded and unguarded_muts:
                lock = sorted(guarded[0][1])[0]
                out.append(Finding(
                    self.id, fc.rel, unguarded_muts[0][3],
                    f"{cm.name}.{attr} is guarded by {lock} elsewhere but "
                    f"mutated without it — racy write (guard it or annotate "
                    f"owned-by[…])",
                ))
                continue
            mut_labels = set()
            for a in muts:
                mut_labels |= a[2]
            common = None
            for a in muts:
                common = a[1] if common is None else (common & a[1])
            if len(mut_labels) >= 2 and not common:
                out.append(Finding(
                    self.id, fc.rel, unguarded_muts[0][3] if unguarded_muts
                    else muts[0][3],
                    f"{cm.name}.{attr} is mutated from multiple contexts "
                    f"({', '.join(sorted(mut_labels))}) with no common lock "
                    f"guard — annotate owned-by[…] if single-writer by design",
                ))
        return out

    @staticmethod
    def _owned(fc: FileContext, accs, decl_lines) -> bool:
        lines = {a[3] for a in accs} | set(decl_lines)
        for ln in lines:
            if ln in fc.owned or (ln - 1) in fc.owned:
                return True
        return False


# ---------------------------------------------------------------------------
# rule: lock-order
# ---------------------------------------------------------------------------


def _build_lock_graph(ctx: TreeContext) -> Dict[Tuple[str, str], Tuple[str, int]]:
    """Global (outer, inner) acquisition edges -> first observed site.
    Lexical ``with`` nesting plus transitive acquisition through resolved
    method calls (same-class ``self.m()``, ``__init__``-typed
    ``self.attr.m()``, same-file ``f()``)."""
    class_map, unique_owner = _global_maps(ctx)
    # callable key -> (lexical acquisitions, call edges, held-at events)
    own_acq: Dict[tuple, Set[str]] = {}
    call_edges: Dict[tuple, Set[tuple]] = {}
    held_acqs: List[Tuple[Tuple[str, ...], str, str, int]] = []
    held_calls: List[Tuple[Tuple[str, ...], tuple, str, int]] = []

    for fc in ctx.files:
        if fc.tree is None:
            continue
        fm = _file_model(fc)
        module_funcs = {
            n.name for n in fc.tree.body
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
        }

        def scan(fn_node, key, cm):
            own_acq.setdefault(key, set())
            call_edges.setdefault(key, set())

            def resolve(expr):
                return _resolve_lock(expr, cm, fm, unique_owner, class_map)

            def visit(kind, node, lid, held):
                if kind == "acquire":
                    own_acq[key].add(lid)
                    if held:
                        held_acqs.append((tuple(held), lid, fc.rel, node.lineno))
                    return
                if not isinstance(node, ast.Call):
                    return
                callee = None
                f = node.func
                if isinstance(f, ast.Attribute):
                    attr = _self_attr(f)
                    if attr is not None and cm is not None and attr in cm.methods:
                        callee = ("m", cm.name, attr)
                    else:
                        base = _self_attr(f.value)
                        if base is not None and cm is not None:
                            tname = cm.attr_types.get(base)
                            if tname and tname in class_map and \
                                    f.attr in class_map[tname].methods:
                                callee = ("m", tname, f.attr)
                elif isinstance(f, ast.Name) and f.id in module_funcs:
                    callee = ("f", fc.rel, f.id)
                if callee is None:
                    return
                call_edges[key].add(callee)
                if held:
                    held_calls.append((tuple(held), callee, fc.rel, node.lineno))

            base = []
            if cm is not None and _caller_holds(fn_node):
                base = sorted(set(cm.locks.values()))
            _scan_function(fn_node, base, resolve, visit)

        for cname, cm in fm.classes.items():
            for mname, meth in cm.methods.items():
                scan(meth, ("m", cname, mname), cm)
        for n in fc.tree.body:
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef)):
                scan(n, ("f", fc.rel, n.name), None)

    # Transitive closure of "may acquire" over the call graph.
    acq = {k: set(v) for k, v in own_acq.items()}
    changed = True
    while changed:
        changed = False
        for key, callees in call_edges.items():
            for callee in callees:
                extra = acq.get(callee, set()) - acq[key]
                if extra:
                    acq[key] |= extra
                    changed = True

    edges: Dict[Tuple[str, str], Tuple[str, int]] = {}
    for held, lid, rel, line in held_acqs:
        for outer in held:
            if outer != lid:
                edges.setdefault((outer, lid), (rel, line))
    for held, callee, rel, line in held_calls:
        for inner in acq.get(callee, ()):
            for outer in held:
                if outer != inner:
                    edges.setdefault((outer, inner), (rel, line))
    return edges


def static_lock_graph(root) -> List[Tuple[str, str]]:
    """The tree's static lock-order edges, sorted — the cross-check target
    for :func:`kakveda_tpu.core.sanitize.lock_order_edges`."""
    return sorted(_build_lock_graph(TreeContext(Path(root))))


@register
class LockOrder(Rule):
    id = "lock-order"
    invariant = "the global lock-acquisition graph stays acyclic"
    scope = None  # whole-tree

    def check_tree(self, ctx: TreeContext) -> List[Finding]:
        edges = _build_lock_graph(ctx)
        out: List[Finding] = []
        for cycle in find_cycles(edges.keys()):
            # Normalize rotation so the message (the baseline key) is
            # stable whatever DFS order found it.
            body = cycle[:-1]
            i = body.index(min(body))
            norm = body[i:] + body[:i] + [body[i]]
            rel, line = "", 1
            for a, b in zip(norm, norm[1:]):
                if (a, b) in edges:
                    rel, line = edges[(a, b)]
                    break
            out.append(Finding(
                self.id, rel or "kakveda_tpu", line,
                "lock-order cycle: " + " -> ".join(norm) +
                " — a thread holding one while another holds the next "
                "deadlocks; invert one nesting",
            ))
        return out


# ---------------------------------------------------------------------------
# rule: event-loop-blocking
# ---------------------------------------------------------------------------

_HTTP_VERBS = frozenset({"get", "post", "put", "patch", "delete", "head", "request"})
_FILE_IO = frozenset({"read_text", "write_text", "read_bytes", "write_bytes"})
_SUBPROC = frozenset({"run", "call", "check_call", "check_output"})


def _blocking_label(node: ast.Call) -> Optional[str]:
    f = node.func
    if isinstance(f, ast.Attribute):
        recv = f.value
        rname = recv.id if isinstance(recv, ast.Name) else (
            recv.attr if isinstance(recv, ast.Attribute) else "")
        if f.attr == "sleep" and rname == "time":
            return "time.sleep() blocks the event loop — await asyncio.sleep"
        if f.attr == "result":
            return ".result() blocks the loop on a future — await it instead"
        if f.attr in _HTTP_VERBS and rname in ("httpx", "requests"):
            return f"sync {rname}.{f.attr}() on the loop — use an async client"
        if f.attr in _FILE_IO:
            return f".{f.attr}() does file I/O on the loop — run_in_executor"
        if f.attr in _SUBPROC and rname == "subprocess":
            return f"subprocess.{f.attr}() blocks the loop"
        if f.attr == "acquire" and "lock" in (rname or "").lower():
            return "lock.acquire() can block the loop behind a worker thread"
        if f.attr == "block_until_ready" or (
                f.attr == "device_get" and rname == "jax"):
            return f".{f.attr}() synchronizes on device work — run_in_executor"
        if f.attr == "join" and any(
                k in (rname or "").lower() for k in ("thread", "timer", "proc")):
            return ".join() blocks the loop on a worker thread"
    elif isinstance(f, ast.Name) and f.id == "open":
        return "open() does file I/O on the loop — run_in_executor"
    return None


@register
class EventLoopBlocking(Rule):
    id = "event-loop-blocking"
    invariant = (
        "async def bodies on the HTTP planes never call sync blocking "
        "primitives — blocking work goes through run_in_executor/to_thread"
    )
    scope = (
        "kakveda_tpu/service", "kakveda_tpu/dashboard", "kakveda_tpu/fleet",
        "kakveda_tpu/events", "kakveda_tpu/traffic", "kakveda_tpu/platform.py",
    )

    def visit_file(self, fc: FileContext, ctx: TreeContext) -> List[Finding]:
        if fc.tree is None:
            return []
        fm = _file_model(fc)
        class_map, unique_owner = _global_maps(ctx)
        # Locks this file acquires from spawned worker threads: a `with`
        # on one of these inside an async body parks the loop behind
        # whatever that worker does under the lock.
        worker_locks: Set[str] = set()
        for cm in fm.classes.values():
            def resolve(expr, _cm=cm):
                return _resolve_lock(expr, _cm, fm, unique_owner, class_map)
            for mname, meth in cm.methods.items():
                if not (cm.labels.get(mname, set()) & {"thread", "executor"}):
                    continue

                def visit(kind, node, lid, held):
                    if kind == "acquire":
                        worker_locks.add(lid)

                _scan_function(meth, [], resolve, visit)

        out: List[Finding] = []
        parents = _parent_map(fc.tree)
        for fn in ast.walk(fc.tree):
            if not isinstance(fn, ast.AsyncFunctionDef):
                continue
            cm = None
            p = parents.get(fn)
            while p is not None and not isinstance(p, ast.ClassDef):
                p = parents.get(p)
            if isinstance(p, ast.ClassDef):
                cm = fm.classes.get(p.name)

            def resolve(expr, _cm=cm):
                return _resolve_lock(expr, _cm, fm, unique_owner, class_map)

            def visit(kind, node, lid, held, _fn=fn):
                if kind == "acquire":
                    if lid in worker_locks:
                        out.append(Finding(
                            self.id, fc.rel, node.lineno,
                            f"async {_fn.name}() acquires {lid}, also held "
                            f"by a worker thread in this file — the loop "
                            f"stalls behind the worker's critical section",
                        ))
                    return
                if isinstance(node, ast.Call):
                    label = _blocking_label(node)
                    if label is not None:
                        out.append(Finding(
                            self.id, fc.rel, node.lineno,
                            f"async {_fn.name}(): {label}",
                        ))

            # Nested async defs are walked on their own; skip them here by
            # scanning only this function's direct body (the scanner
            # already refuses to descend into any nested def).
            _scan_function(fn, [], resolve, visit)
        return out


# ---------------------------------------------------------------------------
# rule: unjoined-thread
# ---------------------------------------------------------------------------


@register
class UnjoinedThread(Rule):
    id = "unjoined-thread"
    invariant = (
        "every spawned Thread/Timer is daemonized or joined/cancelled on "
        "a close path"
    )
    scope = ("kakveda_tpu", "scripts", "bench.py", "__graft_entry__.py")

    def visit_file(self, fc: FileContext, ctx: TreeContext) -> List[Finding]:
        if fc.tree is None:
            return []
        parents = _parent_map(fc.tree)
        out: List[Finding] = []
        for node in ast.walk(fc.tree):
            if not isinstance(node, ast.Call):
                continue
            f = node.func
            fname = f.attr if isinstance(f, ast.Attribute) else (
                f.id if isinstance(f, ast.Name) else None)
            if fname not in ("Thread", "Timer"):
                continue
            if isinstance(f, ast.Attribute) and not (
                    isinstance(f.value, ast.Name) and f.value.id == "threading"):
                continue
            if any(kw.arg == "daemon" and isinstance(kw.value, ast.Constant)
                   and kw.value.value is True for kw in node.keywords):
                continue
            if self._retired_later(node, parents):
                continue
            out.append(Finding(
                self.id, fc.rel, node.lineno,
                f"threading.{fname} spawned without daemon=True and never "
                f"joined/cancelled — leaks past close/shutdown",
            ))
        return out

    @staticmethod
    def _retired_later(call: ast.Call, parents) -> bool:
        """Is the constructed thread/timer bound to a name that later gets
        ``.daemon = True`` or a ``.join()``/``.cancel()`` call?"""
        parent = parents.get(call)
        target: Optional[ast.AST] = None
        if isinstance(parent, ast.Assign) and len(parent.targets) == 1:
            target = parent.targets[0]
        if target is None:
            return False
        # Search space: the enclosing class for self.X bindings (close
        # paths live on other methods), else the enclosing function/module.
        scope: Optional[ast.AST] = parents.get(call)
        want_cls = _self_attr(target) is not None
        while scope is not None:
            if want_cls and isinstance(scope, ast.ClassDef):
                break
            if not want_cls and isinstance(
                    scope, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Module)):
                break
            scope = parents.get(scope)
        if scope is None:
            return False

        def same(a: ast.AST) -> bool:
            if isinstance(target, ast.Name):
                return isinstance(a, ast.Name) and a.id == target.id
            return _self_attr(a) is not None and _self_attr(a) == _self_attr(target)

        for n in ast.walk(scope):
            if isinstance(n, ast.Assign):
                for t in n.targets:
                    if (isinstance(t, ast.Attribute) and t.attr == "daemon"
                            and same(t.value)
                            and isinstance(n.value, ast.Constant)
                            and n.value.value is True):
                        return True
            elif (isinstance(n, ast.Call) and isinstance(n.func, ast.Attribute)
                  and n.func.attr in ("join", "cancel") and same(n.func.value)):
                return True
        return False
