"""Device-plane hygiene rules: retrace, donation, capture and slice checks.

The tunneled TPU pays ~70-90 ms wire RTT per dispatch, and one silent
retrace costs more than the kernel it wraps — so the device-plane
discipline CLAUDE.md states as prose (pow2 bucketing before every jit
dispatch, donation-safe buffer handoff, no host constants closed over by
traced bodies, static shapes in jit/scan bodies) is machine-enforced
here, on the PR-6 AST framework. Four new rules plus the relocated
``host-sync`` rule share ONE jit-discovery index per file
(:class:`JitIndex`): decorated ``@jax.jit`` functions, ``jax.jit(fn)``
wrappers (including ``self._impl`` methods and inline lambdas) and
``lax.scan`` bodies, with their ``donate_argnums`` / ``static_argnums`` /
``static_argnames`` metadata.

The runtime half is :mod:`kakveda_tpu.core.ledger` (``KAKVEDA_LEDGER=1``):
the compile-and-transfer ledger counts what these rules predict — a tree
that lints clean must show O(log N) distinct lowerings per entry point
and zero post-warmup compiles on the serve path, and the bench rows
assert it. Static and runtime halves cross-check exactly like the
concurrency sanitizer pair (analysis/concurrency.py + core/sanitize.py).

False-positive policy is the framework's: a deliberate exception gets an
inline ``# kakveda: allow[rule-id]`` pragma with a comment saying why.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterator, List, Optional, Sequence, Set, Tuple

from kakveda_tpu.analysis.framework import (
    FileContext,
    Finding,
    Rule,
    TreeContext,
    register,
)
from kakveda_tpu.analysis.rules import _parent_map, _self_attr

# The device plane: compiled programs and the modules that dispatch them.
_DEVICE_SCOPE = ("kakveda_tpu/models/", "kakveda_tpu/ops/", "kakveda_tpu/index/")

# THE blessed bucket seam (ops/knn.pow2_bucket) and its thin wrappers —
# rounding a data-dependent size through any of these kills the taint.
_BLESSED_BUCKETS = frozenset({
    "pow2_bucket", "batch_bucket", "_bucket_len", "bucket_for", "_bucket",
    "_corpus_pad", "_prefill_width",
})

_NP_NAMES = frozenset({"np", "onp", "numpy"})
_JNP_NAMES = frozenset({"jnp"})
# Shape-taking constructors: a tainted name in the shape argument makes the
# result a retrace-hazard array. *_like ctors mirror an existing array's
# shape and are exempt by construction.
_SHAPE_CTORS = frozenset({"zeros", "ones", "empty", "full", "arange"})


# ---------------------------------------------------------------------------
# shared jit discovery
# ---------------------------------------------------------------------------


def _is_jit_ref(node: ast.AST) -> bool:
    return (isinstance(node, ast.Name) and node.id == "jit") or (
        isinstance(node, ast.Attribute) and node.attr == "jit"
    )


def _is_jit_decorator(dec: ast.AST) -> bool:
    if _is_jit_ref(dec):
        return True
    if isinstance(dec, ast.Call):
        if _is_jit_ref(dec.func):
            return True
        # @partial(jax.jit, static_argnames=…)
        if (
            isinstance(dec.func, ast.Name) and dec.func.id == "partial"
        ) or (
            isinstance(dec.func, ast.Attribute) and dec.func.attr == "partial"
        ):
            return any(_is_jit_ref(a) for a in dec.args)
    return False


def _int_tuple(node: Optional[ast.AST]) -> Tuple[int, ...]:
    """``donate_argnums=(0, 1)`` / ``=2`` → (0, 1) / (2,)."""
    if node is None:
        return ()
    elts = node.elts if isinstance(node, (ast.Tuple, ast.List)) else [node]
    out = []
    for e in elts:
        if isinstance(e, ast.Constant) and isinstance(e.value, int):
            out.append(e.value)
    return tuple(out)


def _str_tuple(node: Optional[ast.AST]) -> Tuple[str, ...]:
    if node is None:
        return ()
    elts = node.elts if isinstance(node, (ast.Tuple, ast.List)) else [node]
    return tuple(
        e.value for e in elts
        if isinstance(e, ast.Constant) and isinstance(e.value, str)
    )


def _kw(call: ast.Call, name: str) -> Optional[ast.AST]:
    for k in call.keywords:
        if k.arg == name:
            return k.value
    return None


def _fn_params(node: ast.AST) -> List[str]:
    args = node.args
    return [a.arg for a in args.posonlyargs + args.args + args.kwonlyargs]


class JitBody:
    """One traced body: a function/lambda whose code runs under trace."""

    __slots__ = ("label", "node", "static_names")

    def __init__(self, label: str, node: ast.AST, static_names: Set[str]):
        self.label = label
        self.node = node
        self.static_names = static_names


class JitEntry:
    """One *callable* jit entry point: the name host code calls."""

    __slots__ = ("name", "donate", "line")

    def __init__(self, name: str, donate: Tuple[int, ...], line: int):
        self.name = name
        self.donate = donate
        self.line = line


class JitIndex:
    """Per-file index of traced bodies and callable jit entry points.

    Shared by every device rule (and the relocated host-sync rule) so the
    family blesses/flags ONE consistent notion of "inside jit" and "a call
    into jit": ``@jax.jit``/``@partial(jax.jit, …)`` decorations,
    ``x = jax.jit(fn)`` / ``self._x = jax.jit(self._impl)`` wrappers
    (entry = the assignment target; lambdas traced inline), and
    ``jax.lax.scan(body, …)`` bodies.
    """

    def __init__(self, tree: ast.AST, parents: Dict[ast.AST, ast.AST]):
        self.bodies: List[JitBody] = []
        self.entries: Dict[str, JitEntry] = {}
        self._body_nodes: Set[int] = set()

        func_defs: Dict[str, ast.AST] = {}
        for n in ast.walk(tree):
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef)):
                func_defs.setdefault(n.name, n)

        for n in ast.walk(tree):
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef)):
                for dec in n.decorator_list:
                    if _is_jit_decorator(dec):
                        donate, statics = self._jit_opts(dec, n)
                        self._add_body(n.name, n, statics)
                        self._add_entry(n.name, donate, n.lineno)
                        break
            elif isinstance(n, ast.Call) and _is_jit_ref(n.func) and n.args:
                donate_nums = _int_tuple(_kw(n, "donate_argnums"))
                target = self._assign_target(n, parents)
                a = n.args[0]
                body: Optional[ast.AST] = None
                label = target or "<jit>"
                if isinstance(a, ast.Lambda):
                    body = a
                elif isinstance(a, ast.Name):
                    body = func_defs.get(a.id)
                    label = a.id
                elif isinstance(a, ast.Attribute):
                    body = func_defs.get(a.attr)
                    label = a.attr
                if body is not None:
                    statics = self._static_names(n, body)
                    self._add_body(label, body, statics)
                if target is not None:
                    self._add_entry(target, donate_nums, n.lineno)
            elif (
                isinstance(n, ast.Call)
                and isinstance(n.func, ast.Attribute)
                and n.func.attr == "scan"
                and n.args
                and isinstance(n.args[0], ast.Name)
            ):
                body = func_defs.get(n.args[0].id)
                if body is not None:
                    self._add_body(n.args[0].id, body, set())

    def _add_body(self, label: str, node: ast.AST, statics: Set[str]) -> None:
        if id(node) in self._body_nodes:
            return
        self._body_nodes.add(id(node))
        self.bodies.append(JitBody(label, node, statics))

    def _add_entry(self, name: str, donate: Tuple[int, ...], line: int) -> None:
        self.entries.setdefault(name, JitEntry(name, donate, line))

    def is_body(self, node: ast.AST) -> bool:
        return id(node) in self._body_nodes

    @staticmethod
    def _assign_target(
        call: ast.Call, parents: Dict[ast.AST, ast.AST]
    ) -> Optional[str]:
        """``x = jax.jit(f)`` / ``self._x = jax.jit(…)`` → the entry name."""
        parent = parents.get(call)
        if isinstance(parent, ast.Assign) and len(parent.targets) == 1:
            t = parent.targets[0]
            if isinstance(t, ast.Name):
                return t.id
            attr = _self_attr(t)
            if attr is not None:
                return attr
        return None

    @staticmethod
    def _static_names(call: ast.Call, body: ast.AST) -> Set[str]:
        names = set(_str_tuple(_kw(call, "static_argnames")))
        params = _fn_params(body)
        for i in _int_tuple(_kw(call, "static_argnums")):
            if 0 <= i < len(params):
                names.add(params[i])
        return names

    @classmethod
    def _jit_opts(
        cls, dec: ast.AST, fn: ast.AST
    ) -> Tuple[Tuple[int, ...], Set[str]]:
        if isinstance(dec, ast.Call):
            return (
                _int_tuple(_kw(dec, "donate_argnums")),
                cls._static_names(dec, fn),
            )
        return (), set()


def _jit_index(fc: FileContext) -> Tuple[JitIndex, Dict[ast.AST, ast.AST]]:
    """Build (and memoize on the FileContext) the file's jit index."""
    cached = getattr(fc, "_device_jit_index", None)
    if cached is not None:
        return cached
    parents = _parent_map(fc.tree)
    idx = JitIndex(fc.tree, parents)
    fc._device_jit_index = (idx, parents)  # type: ignore[attr-defined]
    return idx, parents


def _name_loads(node: ast.AST) -> Iterator[ast.Name]:
    """Name loads in ``node``, excluding names used only as the base of an
    attribute access (``cfg.max_seq_len`` reads a static config field, not
    the per-request value ``cfg`` itself)."""
    attr_bases = {
        id(n.value) for n in ast.walk(node) if isinstance(n, ast.Attribute)
    }
    for n in ast.walk(node):
        if (
            isinstance(n, ast.Name)
            and isinstance(n.ctx, ast.Load)
            and id(n) not in attr_bases
        ):
            yield n


def _contains_blessed_call(node: ast.AST) -> bool:
    for n in ast.walk(node):
        if isinstance(n, ast.Call):
            f = n.func
            name = (
                f.id if isinstance(f, ast.Name)
                else f.attr if isinstance(f, ast.Attribute) else None
            )
            if name in _BLESSED_BUCKETS:
                return True
    return False


def _contains_taint_source(node: ast.AST) -> bool:
    """``len(…)`` calls or ``.shape`` reads anywhere in the expression."""
    for n in ast.walk(node):
        if (
            isinstance(n, ast.Call)
            and isinstance(n.func, ast.Name)
            and n.func.id == "len"
        ):
            return True
        if isinstance(n, ast.Attribute) and n.attr == "shape":
            return True
    return False


def _shape_ctor(call: ast.Call, modules: frozenset) -> Optional[ast.AST]:
    """``np.zeros(shape, …)``-style constructor → its shape expression."""
    f = call.func
    if (
        isinstance(f, ast.Attribute)
        and f.attr in _SHAPE_CTORS
        and isinstance(f.value, ast.Name)
        and f.value.id in modules
    ):
        shape = _kw(call, "shape")
        if shape is None and call.args:
            shape = call.args[0]
        return shape
    return None


def _assign_name_targets(stmt: ast.AST) -> List[ast.Name]:
    targets = stmt.targets if isinstance(stmt, ast.Assign) else [stmt.target]
    out: List[ast.Name] = []
    for t in targets:
        elts = t.elts if isinstance(t, (ast.Tuple, ast.List)) else [t]
        out.extend(e for e in elts if isinstance(e, ast.Name))
    return out


# ---------------------------------------------------------------------------
# retrace-hazard
# ---------------------------------------------------------------------------


@register
class RetraceHazard(Rule):
    id = "retrace-hazard"
    invariant = (
        "an array whose shape derives from a data-dependent Python value "
        "(len(), .shape[i]) must round through the blessed bucket seam "
        "(ops/knn.pow2_bucket or its wrappers) before being passed to a "
        "jit entry point — exact-fit shapes retrace per distinct size"
    )
    scope = _DEVICE_SCOPE

    def visit_file(self, fc: FileContext, ctx: TreeContext) -> List[Finding]:
        idx, _parents = _jit_index(fc)
        if not idx.entries:
            return []
        out: List[Finding] = []
        for func in ast.walk(fc.tree):
            if not isinstance(func, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            if idx.is_body(func):
                continue  # inside a trace, shapes are static per trace
            out.extend(self._check_function(fc, idx, func))
        return out

    def _check_function(self, fc, idx: JitIndex, func) -> List[Finding]:
        out: List[Finding] = []
        tainted: Set[str] = set()   # data-dependent Python sizes
        hazard: Dict[str, str] = {}  # array name -> the size name that sized it

        events: List[Tuple[int, int, str, ast.AST]] = []
        for n in ast.walk(func):
            if n is not func and isinstance(
                n, (ast.FunctionDef, ast.AsyncFunctionDef)
            ):
                continue  # nested defs analyzed on their own walk
            if isinstance(n, (ast.Assign, ast.AugAssign, ast.AnnAssign)):
                if getattr(n, "value", None) is not None:
                    events.append((n.lineno, n.col_offset, "assign", n))
            elif isinstance(n, ast.Call):
                name = self._call_name(n)
                if name in idx.entries:
                    events.append((n.lineno, n.col_offset, "call", n))
        events.sort(key=lambda e: (e[0], e[1]))

        for _ln, _col, kind, node in events:
            if kind == "assign":
                self._apply_assign(node, tainted, hazard)
                continue
            entry = self._call_name(node)
            for arg in list(node.args) + [k.value for k in node.keywords]:
                flagged = self._hazard_in(arg, tainted, hazard)
                if flagged is not None:
                    array, size = flagged
                    out.append(Finding(
                        self.id, fc.rel, node.lineno,
                        f"array `{array}` (sized by data-dependent "
                        f"`{size}`) is passed to jit entry `{entry}` in "
                        f"{func.name}() — every distinct size is a fresh "
                        "trace+compile; round the size through the blessed "
                        "bucket seam (ops/knn.pow2_bucket or its wrappers)",
                    ))
        return out

    @staticmethod
    def _call_name(call: ast.Call) -> Optional[str]:
        f = call.func
        if isinstance(f, ast.Name):
            return f.id
        if isinstance(f, ast.Attribute):
            return f.attr
        return None

    def _apply_assign(self, stmt, tainted: Set[str], hazard: Dict[str, str]):
        value = stmt.value
        targets = _assign_name_targets(stmt)
        if not targets:
            return
        if _contains_blessed_call(value):
            # Rounded through the seam: the result is bucket-clean.
            for t in targets:
                tainted.discard(t.id)
                hazard.pop(t.id, None)
            return
        # Hazard-array creation: shape-taking ctor with a tainted dim.
        sized_by = self._ctor_tainted_dim(value, tainted)
        if sized_by is not None:
            for t in targets:
                hazard[t.id] = sized_by
                tainted.discard(t.id)
            return
        # Hazard propagation through plain rebinds (idx, val = pad_i, pad_v).
        src_names = [n.id for n in _name_loads(value)]
        carried = [n for n in src_names if n in hazard]
        if carried and isinstance(value, (ast.Name, ast.Tuple, ast.List)):
            srcs = (
                value.elts if isinstance(value, (ast.Tuple, ast.List))
                else [value]
            )
            for t, s in zip(targets, srcs):
                if isinstance(s, ast.Name) and s.id in hazard:
                    hazard[t.id] = hazard[s.id]
                    tainted.discard(t.id)
            return
        # Size-taint creation/propagation.
        if _contains_taint_source(value) or any(n in tainted for n in src_names):
            for t in targets:
                tainted.add(t.id)
                hazard.pop(t.id, None)
            return
        for t in targets:  # clean reassignment kills prior state
            tainted.discard(t.id)
            hazard.pop(t.id, None)

    def _ctor_tainted_dim(self, value, tainted: Set[str]) -> Optional[str]:
        for n in ast.walk(value):
            if isinstance(n, ast.Call):
                shape = _shape_ctor(n, _NP_NAMES | _JNP_NAMES)
                if shape is not None:
                    for name in _name_loads(shape):
                        if name.id in tainted:
                            return name.id
        return None

    def _hazard_in(
        self, arg: ast.AST, tainted: Set[str], hazard: Dict[str, str]
    ) -> Optional[Tuple[str, str]]:
        for name in _name_loads(arg):
            if name.id in hazard:
                return name.id, hazard[name.id]
        # Inline ctor in the call args: self._jit(np.zeros((b, d))).
        sized_by = self._ctor_tainted_dim(arg, tainted)
        if sized_by is not None:
            return "<inline array>", sized_by
        return None


# ---------------------------------------------------------------------------
# donation-after-use
# ---------------------------------------------------------------------------


@register
class DonationAfterUse(Rule):
    id = "donation-after-use"
    invariant = (
        "an array passed at a donate_argnums position is dead after the "
        "call — its buffer was handed to the output; the sanctioned shape "
        "rebinds the result over the donated name in the same statement "
        "(self.cache, … = _step_jit(…, self.cache, …))"
    )
    scope = _DEVICE_SCOPE

    def visit_file(self, fc: FileContext, ctx: TreeContext) -> List[Finding]:
        idx, parents = _jit_index(fc)
        donating = {n: e for n, e in idx.entries.items() if e.donate}
        if not donating:
            return []
        out: List[Finding] = []
        for call in ast.walk(fc.tree):
            if not isinstance(call, ast.Call):
                continue
            name = RetraceHazard._call_name(call)
            entry = donating.get(name)
            if entry is None:
                continue
            func = self._enclosing(call, parents)
            if func is None:
                continue
            stmt = self._enclosing_stmt(call, parents)
            for pos in entry.donate:
                if pos >= len(call.args):
                    continue
                key = self._var_key(call.args[pos])
                if key is None:
                    continue
                if stmt is not None and self._stmt_rebinds(stmt, key):
                    continue  # the sanctioned same-statement rebind
                read = self._first_read_after(func, stmt or call, key)
                if read is not None:
                    out.append(Finding(
                        self.id, fc.rel, read,
                        f"`{self._human(key)}` is donated to `{name}` "
                        f"(donate_argnums position {pos}) at line "
                        f"{call.lineno} but read afterwards — the donated "
                        "buffer is dead after the call; rebind the result "
                        "over it in the same statement before any use",
                    ))
        return out

    @staticmethod
    def _enclosing(node, parents):
        cur = parents.get(node)
        while cur is not None:
            if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
                return cur
            cur = parents.get(cur)
        return None

    @staticmethod
    def _enclosing_stmt(node, parents):
        cur = node
        while cur is not None:
            parent = parents.get(cur)
            if isinstance(parent, (ast.FunctionDef, ast.AsyncFunctionDef,
                                   ast.Module, ast.ClassDef)):
                return cur if isinstance(cur, ast.stmt) else None
            cur = parent
        return None

    @staticmethod
    def _var_key(node: ast.AST) -> Optional[Tuple[str, str]]:
        if isinstance(node, ast.Name):
            return ("name", node.id)
        attr = _self_attr(node)
        if attr is not None:
            return ("self", attr)
        return None

    @staticmethod
    def _human(key: Tuple[str, str]) -> str:
        return key[1] if key[0] == "name" else f"self.{key[1]}"

    @classmethod
    def _matches(cls, node: ast.AST, key: Tuple[str, str]) -> bool:
        return cls._var_key(node) == key

    @classmethod
    def _stmt_rebinds(cls, stmt: ast.AST, key: Tuple[str, str]) -> bool:
        if isinstance(stmt, (ast.Assign, ast.AugAssign, ast.AnnAssign)):
            targets = (
                stmt.targets if isinstance(stmt, ast.Assign) else [stmt.target]
            )
            for t in targets:
                elts = t.elts if isinstance(t, (ast.Tuple, ast.List)) else [t]
                if any(cls._matches(e, key) for e in elts):
                    return True
        return False

    @classmethod
    def _first_read_after(cls, func, stmt, key) -> Optional[int]:
        """Line of the first Load of ``key`` lexically after ``stmt`` in
        ``func``, unless a Store/del kills it first. Lexical order is the
        approximation that matches this tree's straight-line dispatch code."""
        after = getattr(stmt, "end_lineno", stmt.lineno)
        events: List[Tuple[int, int, str]] = []
        for n in ast.walk(func):
            if cls._var_key(n) != key:
                continue
            if n.lineno <= after:
                continue
            if isinstance(n.ctx, ast.Load):
                events.append((n.lineno, n.col_offset, "load"))
            elif isinstance(n.ctx, (ast.Store, ast.Del)):
                events.append((n.lineno, n.col_offset, "store"))
        for ln, _col, kind in sorted(events):
            if kind == "store":
                return None
            return ln
        return None


# ---------------------------------------------------------------------------
# constant-capture
# ---------------------------------------------------------------------------


@register
class ConstantCapture(Rule):
    id = "constant-capture"
    invariant = (
        "jit bodies must not close over module/instance numpy arrays — a "
        "closed-over host array is re-hashed (and on remote backends "
        "re-uploaded) on every trace; pass it as an argument or upload it "
        "once at construction"
    )
    scope = _DEVICE_SCOPE

    def visit_file(self, fc: FileContext, ctx: TreeContext) -> List[Finding]:
        idx, _parents = _jit_index(fc)
        if not idx.bodies:
            return []
        np_globals, np_attrs = self._numpy_names(fc.tree)
        if not np_globals and not np_attrs:
            return []
        out: List[Finding] = []
        for body in idx.bodies:
            params = set(_fn_params(body.node))
            locals_: Set[str] = {
                t.id
                for n in ast.walk(body.node)
                if isinstance(n, (ast.Assign, ast.AugAssign, ast.AnnAssign))
                for t in _assign_name_targets(n)
            }
            for n in ast.walk(body.node):
                ref = None
                if (
                    isinstance(n, ast.Name)
                    and isinstance(n.ctx, ast.Load)
                    and n.id in np_globals
                    and n.id not in params
                    and n.id not in locals_
                ):
                    ref = n.id
                else:
                    attr = _self_attr(n)
                    if (
                        attr is not None
                        and attr in np_attrs
                        and isinstance(n.ctx, ast.Load)
                        and "self" not in params
                    ):
                        ref = f"self.{attr}"
                if ref is not None:
                    out.append(Finding(
                        self.id, fc.rel, n.lineno,
                        f"jit body `{body.label}` closes over host numpy "
                        f"array `{ref}` — re-hashed per trace and "
                        "re-uploaded per compile on remote backends; pass "
                        "it as an argument or pre-upload it once",
                    ))
        return out

    @staticmethod
    def _numpy_names(tree: ast.AST) -> Tuple[Set[str], Set[str]]:
        """(module-level names, self attributes) known to hold numpy
        arrays: assigned from an np.* call or carrying the tree's ``_np``
        host-mirror suffix."""

        def is_np_value(v: ast.AST) -> bool:
            return (
                isinstance(v, ast.Call)
                and isinstance(v.func, ast.Attribute)
                and isinstance(v.func.value, ast.Name)
                and v.func.value.id in _NP_NAMES
            )

        np_globals: Set[str] = set()
        for stmt in getattr(tree, "body", []):
            if isinstance(stmt, ast.Assign) and is_np_value(stmt.value):
                np_globals.update(
                    t.id for t in stmt.targets if isinstance(t, ast.Name)
                )
        np_attrs: Set[str] = set()
        for n in ast.walk(tree):
            if isinstance(n, ast.Assign):
                for t in n.targets:
                    attr = _self_attr(t)
                    if attr is not None and (
                        attr.endswith("_np") or is_np_value(n.value)
                    ):
                        np_attrs.add(attr)
        return np_globals, np_attrs


# ---------------------------------------------------------------------------
# dynamic-slice-by-trace
# ---------------------------------------------------------------------------


@register
class DynamicSliceByTrace(Rule):
    id = "dynamic-slice-by-trace"
    invariant = (
        "no x[n:] / lax.dynamic_slice sized by a traced value inside "
        "jit/scan bodies — output shapes must be static under trace "
        "(the prefix-slab contract); traced starts are fine, traced "
        "SIZES are the bug"
    )
    scope = ("kakveda_tpu/models/", "kakveda_tpu/ops/")

    _DSLICE = frozenset({"dynamic_slice", "dynamic_slice_in_dim"})

    def visit_file(self, fc: FileContext, ctx: TreeContext) -> List[Finding]:
        idx, _parents = _jit_index(fc)
        out: List[Finding] = []
        for body in idx.bodies:
            traced = {
                p for p in _fn_params(body.node)
                if p not in body.static_names and p != "self"
            }
            traced |= self._derived(body.node, traced)
            for n in ast.walk(body.node):
                if isinstance(n, ast.Subscript):
                    for sl in self._slices(n.slice):
                        name = self._traced_in(
                            [sl.lower, sl.upper, sl.step], traced
                        )
                        if name is not None:
                            out.append(Finding(
                                self.id, fc.rel, n.lineno,
                                f"slice bound `{name}` inside jit body "
                                f"`{body.label}` is traced/per-request — "
                                "the result shape changes per value; use a "
                                "static width + masking (or lax.dynamic_"
                                "slice with a STATIC size)",
                            ))
                elif isinstance(n, ast.Call):
                    fname = RetraceHazard._call_name(n)
                    if fname in self._DSLICE:
                        size_args = self._size_args(n, fname)
                        name = self._traced_in(size_args, traced)
                        if name is not None:
                            out.append(Finding(
                                self.id, fc.rel, n.lineno,
                                f"`{fname}` size `{name}` inside jit body "
                                f"`{body.label}` is traced/per-request — "
                                "dynamic_slice sizes must be static; only "
                                "the start indices may be traced",
                            ))
        return out

    @staticmethod
    def _slices(node: ast.AST) -> List[ast.Slice]:
        if isinstance(node, ast.Slice):
            return [node]
        if isinstance(node, ast.Tuple):
            return [e for e in node.elts if isinstance(e, ast.Slice)]
        return []

    @staticmethod
    def _size_args(call: ast.Call, fname: str) -> List[Optional[ast.AST]]:
        if fname == "dynamic_slice":  # (operand, starts, slice_sizes)
            out = [call.args[2] if len(call.args) > 2 else None]
            out.append(_kw(call, "slice_sizes"))
            return out
        # dynamic_slice_in_dim(operand, start, size, axis)
        return [call.args[2] if len(call.args) > 2 else None,
                _kw(call, "slice_size"), _kw(call, "size")]

    @staticmethod
    def _derived(body: ast.AST, traced: Set[str]) -> Set[str]:
        """Locals assigned from expressions over traced names."""
        derived: Set[str] = set()
        changed = True
        while changed:
            changed = False
            for n in ast.walk(body):
                if isinstance(n, (ast.Assign, ast.AugAssign, ast.AnnAssign)):
                    value = getattr(n, "value", None)
                    if value is None:
                        continue
                    if any(
                        nm.id in traced or nm.id in derived
                        for nm in _name_loads(value)
                    ):
                        for t in _assign_name_targets(n):
                            if t.id not in derived and t.id not in traced:
                                derived.add(t.id)
                                changed = True
        return derived

    @staticmethod
    def _traced_in(
        nodes: Sequence[Optional[ast.AST]], traced: Set[str]
    ) -> Optional[str]:
        for node in nodes:
            if node is None:
                continue
            for name in _name_loads(node):
                if name.id in traced:
                    return name.id
        return None


# ---------------------------------------------------------------------------
# host-sync (relocated from analysis/rules.py — same id, same messages)
# ---------------------------------------------------------------------------


@register
class HostSyncHazards(Rule):
    id = "host-sync"
    invariant = (
        "no host synchronization (.item()/.tolist()/np.asarray/float(arg)) "
        "inside jit-compiled bodies in models/ and ops/, and no "
        "jnp.asarray(self.<mirror>_np) upload without .copy() — the CPU "
        "backend aliases numpy buffers zero-copy"
    )
    scope = ("kakveda_tpu/models/", "kakveda_tpu/ops/")

    def visit_file(self, fc: FileContext, ctx: TreeContext) -> List[Finding]:
        idx, _parents = _jit_index(fc)
        out: List[Finding] = []
        for body in idx.bodies:
            func = body.node
            params = set(_fn_params(func))
            for n in ast.walk(func):
                if not isinstance(n, ast.Call):
                    continue
                msg = None
                if isinstance(n.func, ast.Attribute):
                    if n.func.attr in ("item", "tolist"):
                        msg = f".{n.func.attr}() forces a device→host sync"
                    elif (
                        n.func.attr in ("asarray", "array")
                        and isinstance(n.func.value, ast.Name)
                        and n.func.value.id in _NP_NAMES
                    ):
                        msg = (
                            f"{n.func.value.id}.{n.func.attr}() on a traced "
                            "value forces a device→host sync"
                        )
                    elif (
                        n.func.attr == "device_get"
                        and isinstance(n.func.value, ast.Name)
                        and n.func.value.id == "jax"
                    ):
                        msg = "jax.device_get() forces a device→host sync"
                elif (
                    isinstance(n.func, ast.Name)
                    and n.func.id in ("float", "int", "bool")
                    and len(n.args) == 1
                    and isinstance(n.args[0], ast.Name)
                    and n.args[0].id in params
                ):
                    msg = (
                        f"{n.func.id}() on traced argument "
                        f"`{n.args[0].id}` forces a device→host sync"
                    )
                if msg is not None:
                    out.append(Finding(
                        self.id, fc.rel, n.lineno,
                        f"inside jit-compiled `{body.label}`: {msg} "
                        "(~70-90 ms wire RTT per dispatch on tunneled TPUs)",
                    ))

        # Mutable-mirror aliasing: jnp.asarray(self.<x>_np) without .copy().
        for n in ast.walk(fc.tree):
            if (
                isinstance(n, ast.Call)
                and isinstance(n.func, ast.Attribute)
                and n.func.attr == "asarray"
                and isinstance(n.func.value, ast.Name)
                and n.func.value.id == "jnp"
                and n.args
                and isinstance(n.args[0], ast.Attribute)
                and n.args[0].attr.endswith("_np")
            ):
                out.append(Finding(
                    self.id, fc.rel, n.lineno,
                    f"jnp.asarray(…{n.args[0].attr}) without .copy(): on the "
                    "CPU backend the upload aliases the mutating numpy "
                    "mirror zero-copy (flaky garbage logits)",
                ))
        return out
