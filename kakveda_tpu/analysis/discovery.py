"""Source-tree discovery shared by every static check.

ONE definition of "the code tree" and "the docs corpus" — previously
``scripts/check_knobs.py`` had its own walker and any new checker would
have grown another, and the two would drift (one skipping ``.probe/``,
the other not, each with its own idea of what counts as code). Both the
invariant linter (:mod:`kakveda_tpu.analysis.framework`) and the knob
checker (:mod:`kakveda_tpu.analysis.knobs`) walk through here.

Scope decisions, inherited from check_knobs and now load-bearing for the
lint rules too:

* ``tests/`` is NOT code: test fixtures deliberately contain rule
  violations and ``KAKVEDA_TEST_*`` levers that are not operator surface.
* ``kakveda/`` (the retrieved reference tree), ``.probe/`` (the detached
  probe loop's scratch) and ``__pycache__`` are never scanned.
"""

from __future__ import annotations

from pathlib import Path
from typing import Iterator

# Code that can introduce operator-facing knobs or violate design
# invariants. Tests are deliberately excluded (see module docstring).
CODE_PATHS = ("kakveda_tpu", "scripts", "bench.py", "__graft_entry__.py")

# The docs corpus a knob/fault-site must be discoverable from.
DOC_PATHS = ("CLAUDE.md", "README.md", "TROUBLESHOOTING.md", "BASELINE.md", "docs")

# Never descend into these directory names anywhere in the tree.
SKIP_DIRS = frozenset({"__pycache__", ".probe", "kakveda", ".git", ".pytest_cache"})


def _skipped(root: Path, p: Path) -> bool:
    return any(part in SKIP_DIRS for part in p.relative_to(root).parts)


def code_files(root: Path) -> Iterator[Path]:
    """Every Python source file in the scanned code tree, sorted."""
    root = Path(root)
    for rel in CODE_PATHS:
        p = root / rel
        if p.is_file():
            yield p
        elif p.is_dir():
            for f in sorted(p.rglob("*.py")):
                if not _skipped(root, f):
                    yield f


def md_files(root: Path) -> Iterator[Path]:
    """Every markdown file in the docs corpus, sorted."""
    root = Path(root)
    for rel in DOC_PATHS:
        p = root / rel
        if p.is_file():
            yield p
        elif p.is_dir():
            for f in sorted(p.rglob("*.md")):
                if not _skipped(root, f):
                    yield f
