"""AST lint framework: rule registry, pragmas, baseline, stable outputs.

The machinery under ``scripts/lint_invariants.py`` — rules themselves live
in :mod:`kakveda_tpu.analysis.rules`. Design mirrors what made
``check_knobs.py`` stick:

* **Pure stdlib.** Parsing is ``ast`` only; no file is ever imported or
  executed, so linting ``models/serving.py`` needs no jax, no backend, no
  mesh — the whole-tree run is budgeted under 10 s in tier-1 and actually
  takes well under one.
* **Rules are registered, not hardcoded.** A rule declares an ``id`` (the
  stable name docs, pragmas and the baseline refer to), an ``invariant``
  one-liner (surfaced by ``--list-rules`` and docs/static-analysis.md) and
  either a per-file visitor (``scope`` + ``visit_file``) or a whole-tree
  check (``check_tree``). The runner parses each file once and dispatches.
* **Suppressions are inline and named**: ``# kakveda: allow[rule-id]`` on
  the offending line or the line above. A suppression without a rule id
  does not exist — greps for the id find every grandfathered site.
* **Baseline**: ``kakveda_tpu/analysis/baseline.json`` holds finding keys
  (rule:file:message — line numbers excluded so unrelated edits don't
  churn it) that are reported but don't fail. Shipped EMPTY: the PR that
  introduced the linter fixed what it found. Keep it that way.
* **Stable exit codes** (enforced by tests): 0 = clean (suppressed/
  baselined findings allowed), 1 = live findings, 2 = usage/internal
  error. Output is human lines by default, ``--json`` for machines —
  bench.py folds ``len(findings)`` into its JSON line as
  ``lint_findings``.
"""

from __future__ import annotations

import ast
import json
import re
from dataclasses import dataclass
from pathlib import Path
from typing import Dict, Iterable, List, Optional, Sequence

from kakveda_tpu.analysis import discovery

PRAGMA_RE = re.compile(r"#\s*kakveda:\s*allow\[([A-Za-z0-9_,\- ]+)\]")

# Ownership annotation for the concurrency pass: a field mutated without a
# lock because exactly one context writes it BY DESIGN documents that
# discipline with ``# kakveda: owned-by[<context>]`` on the mutation (or
# its __init__ declaration). Same line-or-line-above placement as allow[].
OWNED_RE = re.compile(r"#\s*kakveda:\s*owned-by\[([A-Za-z0-9_:.,\- ]+)\]")

# Default baseline location, repo-relative (committed; grandfathered keys).
BASELINE_REL = "kakveda_tpu/analysis/baseline.json"


@dataclass(frozen=True)
class Finding:
    """One rule violation at one location."""

    rule: str
    file: str  # repo-relative posix path
    line: int
    message: str

    @property
    def baseline_key(self) -> str:
        # Deliberately line-free: a baselined finding must survive the file
        # shifting around it, and die the moment the offending code changes
        # enough to reword the message.
        return f"{self.rule}:{self.file}:{self.message}"

    def human(self) -> str:
        return f"{self.file}:{self.line}: [{self.rule}] {self.message}"

    def as_dict(self) -> dict:
        return {
            "rule": self.rule,
            "file": self.file,
            "line": self.line,
            "message": self.message,
        }


class FileContext:
    """One parsed source file: AST, raw lines, and suppression pragmas."""

    def __init__(self, root: Path, path: Path):
        self.path = path
        self.rel = path.relative_to(root).as_posix()
        self.source = path.read_text(errors="replace")
        self.lines = self.source.splitlines()
        self.parse_error: Optional[SyntaxError] = None
        try:
            self.tree: Optional[ast.AST] = ast.parse(self.source)
        except SyntaxError as e:
            self.tree = None
            self.parse_error = e
        # lineno -> rule ids allowed on that line (or the line below it).
        self.allows: Dict[int, set] = {}
        # lineno -> owned-by[<context>] annotation (concurrency pass).
        self.owned: Dict[int, str] = {}
        for i, ln in enumerate(self.lines, 1):
            m = PRAGMA_RE.search(ln)
            if m:
                self.allows[i] = {r.strip() for r in m.group(1).split(",") if r.strip()}
            m = OWNED_RE.search(ln)
            if m:
                self.owned[i] = m.group(1).strip()

    def find_line(self, needle: str) -> int:
        """First 1-based line containing ``needle`` (1 when absent) — for
        tree rules whose evidence is textual (knob/site strings)."""
        for i, ln in enumerate(self.lines, 1):
            if needle in ln:
                return i
        return 1


class TreeContext:
    """The whole scanned tree, parsed once and shared by every rule.

    ``files`` restricts the scan to an explicit path list (the
    ``--changed`` pre-commit mode) — tree rules that need the full corpus
    are skipped by the runner in that mode, never fed a partial tree."""

    def __init__(self, root: Path, files: Optional[Sequence[Path]] = None):
        self.root = Path(root)
        if files is None:
            paths = list(discovery.code_files(self.root))
        else:
            paths = [Path(p) for p in files if Path(p).is_file()]
        self.files: List[FileContext] = [
            FileContext(self.root, p) for p in paths
        ]
        self.by_rel: Dict[str, FileContext] = {fc.rel: fc for fc in self.files}
        self.partial = files is not None


class Rule:
    """Base rule. Subclasses set ``id``/``invariant`` and implement either
    ``visit_file`` (with ``scope`` = tuple of repo-relative path prefixes)
    or ``check_tree`` (``scope`` = None)."""

    id: str = ""
    invariant: str = ""
    scope: Optional[Sequence[str]] = None  # None => whole-tree rule

    def interested(self, rel: str) -> bool:
        return self.scope is not None and any(
            rel == s or rel.startswith(s) for s in self.scope
        )

    def visit_file(self, fc: FileContext, ctx: TreeContext) -> List[Finding]:
        return []

    def check_tree(self, ctx: TreeContext) -> List[Finding]:
        return []


_REGISTRY: Dict[str, Rule] = {}


def register(cls):
    """Class decorator: instantiate and register a rule by its id."""
    if not cls.id:
        raise ValueError(f"rule {cls.__name__} has no id")
    if cls.id in _REGISTRY:
        raise ValueError(f"duplicate rule id {cls.id!r}")
    _REGISTRY[cls.id] = cls()
    return cls


def all_rules() -> Dict[str, Rule]:
    """The registry, loading the project rules on first use."""
    from kakveda_tpu.analysis import concurrency as _concurrency  # noqa: F401
    from kakveda_tpu.analysis import device as _device  # noqa: F401
    from kakveda_tpu.analysis import rules as _rules  # noqa: F401  (registers)

    return dict(sorted(_REGISTRY.items()))


@dataclass
class LintResult:
    findings: List[Finding]      # live: fail the run
    suppressed: List[Finding]    # silenced by an inline pragma
    baselined: List[Finding]     # grandfathered by baseline.json
    rules_run: List[str]


def _suppressed(ctx: TreeContext, f: Finding) -> bool:
    fc = ctx.by_rel.get(f.file)
    if fc is None:
        return False
    for ln in (f.line, f.line - 1):
        ids = fc.allows.get(ln)
        if ids and (f.rule in ids or "*" in ids):
            return True
    return False


def load_baseline(root: Path, baseline_path: Optional[Path] = None) -> set:
    p = baseline_path or (Path(root) / BASELINE_REL)
    try:
        data = json.loads(p.read_text())
    except (OSError, ValueError):
        return set()
    return {str(k) for k in data} if isinstance(data, list) else set()


def run_lint(
    root,
    rule_ids: Optional[Iterable[str]] = None,
    baseline_path: Optional[Path] = None,
    files: Optional[Sequence[Path]] = None,
) -> LintResult:
    """Run the (selected) rules over ``root``; partition findings into
    live / suppressed / baselined. Raises KeyError on an unknown rule id.
    With ``files``, scan only those paths and run only per-file rules —
    whole-tree rules would misfire on a partial corpus (dead-knob checks
    would see every knob as dead); the full-tree run stays the gate."""
    registry = all_rules()
    if rule_ids:
        rules = [registry[r] for r in rule_ids]  # KeyError = caller's usage error
    else:
        rules = list(registry.values())
    ctx = TreeContext(Path(root), files=files)
    if ctx.partial:
        rules = [r for r in rules if r.scope is not None]

    raw: List[Finding] = []
    for fc in ctx.files:
        if fc.parse_error is not None:
            # A file the linter cannot parse is a file whose invariants
            # nobody can verify — always a finding, whatever rules ran.
            raw.append(Finding(
                "syntax", fc.rel, fc.parse_error.lineno or 1,
                f"unparseable source: {fc.parse_error.msg}",
            ))
            continue
        for rule in rules:
            if rule.interested(fc.rel):
                raw.extend(rule.visit_file(fc, ctx))
    for rule in rules:
        if rule.scope is None:
            raw.extend(rule.check_tree(ctx))

    raw = sorted(set(raw), key=lambda f: (f.file, f.line, f.rule, f.message))
    baseline = load_baseline(ctx.root, baseline_path)
    findings: List[Finding] = []
    suppressed: List[Finding] = []
    baselined: List[Finding] = []
    for f in raw:
        if _suppressed(ctx, f):
            suppressed.append(f)
        elif f.baseline_key in baseline:
            baselined.append(f)
        else:
            findings.append(f)
    return LintResult(findings, suppressed, baselined, [r.id for r in rules])
