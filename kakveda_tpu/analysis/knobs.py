"""Knob-documentation and fault-site-catalog parity — the checks
``scripts/check_knobs.py`` pioneered, now shared with the invariant
linter's ``knob-docs`` and ``fault-site-catalog`` rules so both entry
points enforce ONE contract over ONE tree walk
(:mod:`kakveda_tpu.analysis.discovery`).

Contract (unchanged from the original script): every ``KAKVEDA_*`` env
knob the code reads must be documented in the docs corpus, every
documented knob must still be read by code (dead-knob drift), and every
``faults.site("…")`` registered in code must appear in
docs/robustness.md's catalog — the only surface an operator can discover
``KAKVEDA_FAULTS`` arms from.
"""

from __future__ import annotations

import re
from pathlib import Path

from kakveda_tpu.analysis.discovery import code_files, md_files

KNOB_RE = re.compile(r"KAKVEDA_[A-Z0-9_]+")
# A fault-site registration in code: faults.site("engine.dispatch") /
# _faults.site("gfkb.append"). Dotted lowercase names only — the call in
# core/faults.py's own site() definition has no literal and never matches.
SITE_RE = re.compile(r"""\bsite\(\s*["']([a-z0-9_]+(?:\.[a-z0-9_]+)+)["']\s*\)""")

# Internal/cross-process plumbing set by our own launchers, not operators.
ALLOWLIST = frozenset({
    "KAKVEDA_PROCESS_ID",  # set per-process by the multihost launcher
    "KAKVEDA_TEST_PLATFORM",  # test-suite lever (tests/conftest.py), named here
    "KAKVEDA_CRASHSWEEP_CHILD",  # marker set per-child by the crash sweep
})

# Knobs the docs legitimately mention without the scanned code tree reading
# them — test-surface levers (tests/ is excluded from the code walk on
# purpose) and docs-about-the-docs. Anything else documented-but-unread is
# dead-knob drift and fails.
DOC_ONLY_ALLOWLIST = frozenset({
    "KAKVEDA_TEST_PLATFORM",  # tests/conftest.py: run the suite on real TPU
    # tests/test_hf_integration.py: prompt/expectation for the real-weight
    # integration test (tests/ is outside the code scan)
    "KAKVEDA_HF_PROMPT",
    "KAKVEDA_HF_EXPECT",
})


def referenced_knobs(root: Path) -> dict:
    """knob -> sorted list of repo-relative files referencing it."""
    refs: dict = {}
    for f in code_files(Path(root)):
        try:
            text = f.read_text(errors="replace")
        except OSError:
            continue
        for m in set(KNOB_RE.findall(text)):
            if m.rstrip("_") != m or m == "KAKVEDA_":
                continue
            refs.setdefault(m, []).append(str(f.relative_to(root)))
    for files in refs.values():
        files.sort()
    return refs


def documented_knobs(root: Path) -> set:
    docs: set = set()
    for f in md_files(Path(root)):
        try:
            docs.update(KNOB_RE.findall(f.read_text(errors="replace")))
        except OSError:
            continue
    return docs


def undocumented_knobs(root: Path) -> dict:
    """knob -> referencing files, for every knob the docs never mention."""
    refs = referenced_knobs(root)
    docs = documented_knobs(root)
    return {
        k: v for k, v in sorted(refs.items())
        if k not in docs and k not in ALLOWLIST
    }


def registered_fault_sites(root: Path) -> dict:
    """site name -> sorted list of repo-relative files registering it."""
    refs: dict = {}
    for f in code_files(Path(root)):
        try:
            text = f.read_text(errors="replace")
        except OSError:
            continue
        for m in set(SITE_RE.findall(text)):
            refs.setdefault(m, []).append(str(f.relative_to(root)))
    for files in refs.values():
        files.sort()
    return refs


def undocumented_fault_sites(root: Path) -> dict:
    """Registered sites docs/robustness.md never mentions — the catalog is
    the only surface an operator can discover KAKVEDA_FAULTS arms from."""
    doc = Path(root) / "docs" / "robustness.md"
    try:
        text = doc.read_text(errors="replace")
    except OSError:
        text = ""
    return {k: v for k, v in sorted(registered_fault_sites(root).items())
            if k not in text}


def dead_knobs(root: Path) -> list:
    """Documented knobs the code no longer references — dead-knob drift."""
    refs = referenced_knobs(root)
    docs = documented_knobs(root)
    return sorted(
        k for k in docs
        if k not in refs
        and k not in DOC_ONLY_ALLOWLIST
        and k.rstrip("_") == k and k != "KAKVEDA_"
    )
