"""The project rules: CLAUDE.md's design contracts as AST checks.

Each rule encodes ONE prose invariant (catalog with rationale and
suppression policy: docs/static-analysis.md). Rules are intentionally
narrow — they match the specific idioms this codebase uses (``cfg.<flag>``
reads, ``self.<dict>["key"]`` stores, ``with …stats_lock`` blocks,
``_faults.site("…")`` registrations) rather than trying to be a general
linter; a pattern the rule can't see is a pattern the codebase shouldn't
use for that invariant in the first place.

False-positive policy: a deliberate exception gets an inline
``# kakveda: allow[rule-id]`` pragma WITH a comment explaining why —
never widen a rule's blind spot to hide one site.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from kakveda_tpu.analysis import discovery as _discovery
from kakveda_tpu.analysis import knobs as _knobs
from kakveda_tpu.analysis.framework import (
    FileContext,
    Finding,
    Rule,
    TreeContext,
    register,
)

# ---------------------------------------------------------------------------
# shared AST helpers
# ---------------------------------------------------------------------------


def _parent_map(node: ast.AST) -> Dict[ast.AST, ast.AST]:
    parents: Dict[ast.AST, ast.AST] = {}
    for parent in ast.walk(node):
        for child in ast.iter_child_nodes(parent):
            parents[child] = parent
    return parents


def _enclosing_function(
    node: ast.AST, parents: Dict[ast.AST, ast.AST]
) -> Optional[ast.AST]:
    cur = parents.get(node)
    while cur is not None:
        if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef)):
            return cur
        cur = parents.get(cur)
    return None


def _self_attr(node: ast.AST) -> Optional[str]:
    """``self.X`` -> ``X`` (else None)."""
    if (
        isinstance(node, ast.Attribute)
        and isinstance(node.value, ast.Name)
        and node.value.id == "self"
    ):
        return node.attr
    return None


def _call_chain_base_attr(call: ast.Call) -> Optional[str]:
    """For ``self.G.labels(...).set(...)`` / ``self.G.set(...)`` /
    ``self.C.labels(...).inc()`` return ``G``/``C`` — the self attribute at
    the base of a method-call chain (else None)."""
    cur: ast.AST = call.func
    while True:
        if isinstance(cur, ast.Attribute):
            cur = cur.value
        elif isinstance(cur, ast.Call):
            cur = cur.func
        else:
            return None  # chain bottoms out at a bare name/subscript
        attr = _self_attr(cur)
        if attr is not None:
            return attr


def _const_str(node: ast.AST) -> Optional[str]:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    return None


# ---------------------------------------------------------------------------
# forward-flag-parity
# ---------------------------------------------------------------------------

_PARITY_FILES = (
    "kakveda_tpu/models/llama.py",
    "kakveda_tpu/models/attention.py",
    "kakveda_tpu/models/moe.py",
    "kakveda_tpu/models/serving.py",
    "kakveda_tpu/models/pipeline.py",
)
_PARITY_ROOTS = ("forward", "decode_step", "_forward_wide", "pp_forward")
# Shape/arch parameters every path reads incidentally — not family flags,
# excluded so the contract stays about behavior-bearing flags.
_PARITY_IGNORE = frozenset({
    "vocab_size", "d_model", "n_layers", "n_heads", "n_kv_heads", "d_ff",
    "max_seq_len", "norm_eps", "dtype", "head_dim_opt",
})
# Params-presence flags: family deltas keyed on layer-dict membership
# ("post_attn_norm" in layer) rather than a cfg read — tracked with the
# same parity contract.
_PARITY_LAYER_KEYS = frozenset({
    "bq", "bk", "bv", "q_norm", "k_norm",
    "post_attn_norm", "post_ffw_norm", "router",
})
# (root, flag) pairs exempt BY DESIGN — documented in docs/static-analysis.md:
# kv_quant shapes the KV cache, which the full-sequence paths don't have;
# effective_vocab masking happens at the sampler for the offline paths
# (generate._last_logits / _admit_jit) but in-program for _forward_wide.
_PARITY_EXEMPT: Set[Tuple[str, str]] = {
    ("forward", "kv_quant"),
    ("pp_forward", "kv_quant"),
    ("forward", "effective_vocab"),
    ("decode_step", "effective_vocab"),
    ("pp_forward", "effective_vocab"),
}


class _FuncInfo:
    __slots__ = ("reads", "keys", "calls", "rel", "line")

    def __init__(self, rel: str, line: int):
        self.reads: Set[str] = set()
        self.keys: Set[str] = set()
        self.calls: Set[str] = set()
        self.rel = rel
        self.line = line


def _scan_parity_function(node, rel: str, receivers: Set[str]) -> _FuncInfo:
    info = _FuncInfo(rel, node.lineno)
    for n in ast.walk(node):
        if isinstance(n, ast.Attribute) and isinstance(n.value, ast.Name):
            if n.value.id in receivers:
                info.reads.add(n.attr)
        elif isinstance(n, ast.Call):
            if isinstance(n.func, ast.Name):
                info.calls.add(n.func.id)
            elif isinstance(n.func, ast.Attribute):
                v = n.func.value
                if isinstance(v, ast.Name) and v.id in receivers:
                    info.calls.add(n.func.attr)  # cfg.layer_window(li)
        elif isinstance(n, ast.Compare) and len(n.ops) == 1:
            if isinstance(n.ops[0], (ast.In, ast.NotIn)):
                k = _const_str(n.left)
                if (
                    k in _PARITY_LAYER_KEYS
                    and isinstance(n.comparators[0], ast.Name)
                    and n.comparators[0].id == "layer"
                ):
                    info.keys.add(k)
        elif isinstance(n, ast.Subscript):
            if isinstance(n.value, ast.Name) and n.value.id == "layer":
                k = _const_str(n.slice)
                if k in _PARITY_LAYER_KEYS:
                    info.keys.add(k)
    return info


@register
class ForwardFlagParity(Rule):
    id = "forward-flag-parity"
    invariant = (
        "every LlamaConfig feature flag read by llama.forward must also be "
        "read (transitively) by decode_step, serving._forward_wide and "
        "pipeline.pp_forward — the 'grep all four before adding a flag' "
        "rule, automated"
    )
    scope = None  # tree rule: spans models/llama|serving|pipeline

    def check_tree(self, ctx: TreeContext) -> List[Finding]:
        funcs: Dict[str, _FuncInfo] = {}
        fields: Optional[Set[str]] = None
        for rel in _PARITY_FILES:
            fc = ctx.by_rel.get(rel)
            if fc is None or fc.tree is None:
                continue
            for node in fc.tree.body:
                if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    funcs.setdefault(
                        node.name, _scan_parity_function(node, rel, {"cfg"})
                    )
                elif isinstance(node, ast.ClassDef) and node.name == "LlamaConfig":
                    fields = {
                        stmt.target.id
                        for stmt in node.body
                        if isinstance(stmt, ast.AnnAssign)
                        and isinstance(stmt.target, ast.Name)
                    }
                    for m in node.body:
                        if isinstance(m, (ast.FunctionDef, ast.AsyncFunctionDef)):
                            # Config methods/properties (layer_window) read
                            # flags through ``self``.
                            funcs.setdefault(
                                m.name,
                                _scan_parity_function(m, rel, {"cfg", "self"}),
                            )

        roots = [r for r in _PARITY_ROOTS if r in funcs]
        if len(roots) < 2:
            return []  # nothing to compare (partial fixture tree)

        def closure(root: str) -> Tuple[Set[str], Set[str]]:
            reads: Set[str] = set()
            keys: Set[str] = set()
            seen: Set[str] = set()
            stack = [root]
            while stack:
                name = stack.pop()
                if name in seen or name not in funcs:
                    continue
                seen.add(name)
                info = funcs[name]
                reads |= info.reads
                keys |= info.keys
                stack.extend(info.calls)
            if fields is not None:
                reads &= fields
            return reads - _PARITY_IGNORE, keys

        per_root = {r: closure(r) for r in roots}
        union_flags = set().union(*(f for f, _ in per_root.values()))
        union_keys = set().union(*(k for _, k in per_root.values()))

        out: List[Finding] = []
        for root in roots:
            flags, keys = per_root[root]
            for flag in sorted(union_flags - flags):
                if (root, flag) in _PARITY_EXEMPT:
                    continue
                others = sorted(r for r in roots if flag in per_root[r][0])
                out.append(Finding(
                    self.id, funcs[root].rel, funcs[root].line,
                    f"forward path `{root}` never reads `cfg.{flag}` "
                    f"(read by {', '.join(others)}); every forward path "
                    "must honor every model-family flag",
                ))
            for key in sorted(union_keys - keys):
                others = sorted(r for r in roots if key in per_root[r][1])
                out.append(Finding(
                    self.id, funcs[root].rel, funcs[root].line,
                    f"forward path `{root}` never checks layer key "
                    f"{key!r} (checked by {', '.join(others)}); every "
                    "forward path must honor every params-keyed family flag",
                ))
        return out


# ---------------------------------------------------------------------------
# single-writer
# ---------------------------------------------------------------------------

_SINGLE_WRITER = {
    "kakveda_tpu/models/serving.py": ("_set_gate_state",),
    "kakveda_tpu/core/admission.py": ("_set_brownout_state", "_set_tenant_state"),
    "kakveda_tpu/fleet/autoscaler.py": ("_set_scale_state",),
}
_ANY_KEY = object()


@register
class SingleWriterTransitions(Rule):
    id = "single-writer"
    invariant = (
        "the fields moved by _set_gate_state/_set_brownout_state/"
        "_set_scale_state (state key, gauge vector, transition counter) "
        "are assigned nowhere else in their class except __init__"
    )
    scope = tuple(_SINGLE_WRITER)

    def visit_file(self, fc: FileContext, ctx: TreeContext) -> List[Finding]:
        out: List[Finding] = []
        for cls in ast.walk(fc.tree):
            if not isinstance(cls, ast.ClassDef):
                continue
            methods = {
                m.name: m
                for m in cls.body
                if isinstance(m, (ast.FunctionDef, ast.AsyncFunctionDef))
            }
            for helper_name in _SINGLE_WRITER[fc.rel]:
                helper = methods.get(helper_name)
                if helper is None:
                    continue
                attrs, subs, metrics = self._protected(helper)
                for name, m in methods.items():
                    if name in (helper_name, "__init__"):
                        continue
                    out.extend(
                        self._violations(fc, m, helper_name, attrs, subs, metrics)
                    )
        return out

    @staticmethod
    def _protected(helper) -> Tuple[Set[str], Dict[str, set], Set[str]]:
        """Derive the protected write-set from the helper's own body."""
        attrs: Set[str] = set()          # self.X = …
        subs: Dict[str, set] = {}        # self.X[key] = … (key set or ANY)
        metrics: Set[str] = set()        # self.G.labels(...).set()/.inc()
        for n in ast.walk(helper):
            if isinstance(n, (ast.Assign, ast.AugAssign)):
                targets = n.targets if isinstance(n, ast.Assign) else [n.target]
                for t in targets:
                    a = _self_attr(t)
                    if a is not None:
                        attrs.add(a)
                    elif isinstance(t, ast.Subscript):
                        base = _self_attr(t.value)
                        if base is not None:
                            key = _const_str(t.slice)
                            subs.setdefault(base, set()).add(
                                key if key is not None else _ANY_KEY
                            )
            elif isinstance(n, ast.Call) and isinstance(n.func, ast.Attribute):
                if n.func.attr in ("set", "inc", "dec"):
                    base = _call_chain_base_attr(n)
                    if base is not None:
                        metrics.add(base)
        return attrs, subs, metrics

    def _violations(
        self, fc, method, helper_name, attrs, subs, metrics
    ) -> List[Finding]:
        out: List[Finding] = []
        # Local aliases of protected dict attrs (x = self.spec_stats).
        aliases: Dict[str, str] = {}
        for n in ast.walk(method):
            if isinstance(n, ast.Assign) and len(n.targets) == 1:
                t = n.targets[0]
                base = _self_attr(n.value)
                if (
                    isinstance(t, ast.Name)
                    and base is not None
                    and (base in subs or base in attrs)
                ):
                    aliases[t.id] = base

        def sub_base(node: ast.Subscript) -> Optional[str]:
            b = _self_attr(node.value)
            if b is not None:
                return b
            if isinstance(node.value, ast.Name):
                return aliases.get(node.value.id)
            return None

        for n in ast.walk(method):
            if isinstance(n, (ast.Assign, ast.AugAssign)):
                targets = n.targets if isinstance(n, ast.Assign) else [n.target]
                for t in targets:
                    a = _self_attr(t)
                    if a is not None and (a in attrs or a in subs):
                        out.append(Finding(
                            self.id, fc.rel, t.lineno,
                            f"`self.{a}` is moved by {helper_name}() only; "
                            f"direct assignment in {method.name}() bypasses "
                            "the single-writer transition helper",
                        ))
                    elif isinstance(t, ast.Subscript):
                        base = sub_base(t)
                        if base in subs:
                            key = _const_str(t.slice)
                            protected = subs[base]
                            if _ANY_KEY in protected or key in protected:
                                out.append(Finding(
                                    self.id, fc.rel, t.lineno,
                                    f"`self.{base}[{key!r}]` is moved by "
                                    f"{helper_name}() only; direct store in "
                                    f"{method.name}() bypasses the "
                                    "single-writer transition helper",
                                ))
            elif isinstance(n, ast.Call) and isinstance(n.func, ast.Attribute):
                if n.func.attr in ("set", "inc", "dec"):
                    base = _call_chain_base_attr(n)
                    if base in metrics:
                        out.append(Finding(
                            self.id, fc.rel, n.lineno,
                            f"metric `self.{base}` is moved by "
                            f"{helper_name}() only; direct "
                            f".{n.func.attr}() in {method.name}() bypasses "
                            "the single-writer transition helper",
                        ))
        return out


# ---------------------------------------------------------------------------
# stats-lock
# ---------------------------------------------------------------------------

_STATS_ATTRS = frozenset({"spec_stats", "prefix_stats", "_stats"})
_READ_GUARDED = ("spec_stats", "prefix_stats")
_MUTATORS = frozenset({
    "update", "setdefault", "pop", "popitem", "clear",
    "append", "extend", "insert", "remove",
})
_SERVING_REL = "kakveda_tpu/models/serving.py"


def _attr_anywhere(node: ast.AST, attr: str) -> bool:
    return any(
        isinstance(n, ast.Attribute) and n.attr == attr for n in ast.walk(node)
    )


@register
class StatsLockDiscipline(Rule):
    id = "stats-lock"
    invariant = (
        "mutations of the batcher/engine stats dicts (spec_stats, "
        "prefix_stats, _stats) happen lexically inside `with …stats_lock`; "
        "outside models/serving.py the spec/prefix stats are read only "
        "through stats()/stats_snapshot()"
    )
    scope = None  # needs the whole tree for the external-read half

    def check_tree(self, ctx: TreeContext) -> List[Finding]:
        out: List[Finding] = []
        for fc in ctx.files:
            if fc.tree is None:
                continue
            if fc.rel == _SERVING_REL or fc.rel.startswith("tests/"):
                continue
            if fc.rel.startswith("kakveda_tpu/analysis/"):
                continue  # the linter names the dicts without touching them
            for n in ast.walk(fc.tree):
                if isinstance(n, ast.Attribute) and n.attr in _READ_GUARDED:
                    out.append(Finding(
                        self.id, fc.rel, n.lineno,
                        f"direct `{n.attr}` access outside the serving "
                        "module — the loop thread mutates the live dicts; "
                        "read through ServingEngine.stats() / "
                        "ContinuousBatcher.stats_snapshot()",
                    ))
        fc = ctx.by_rel.get(_SERVING_REL)
        if fc is not None and fc.tree is not None:
            out.extend(self._check_serving(fc))
        return out

    def _check_serving(self, fc: FileContext) -> List[Finding]:
        out: List[Finding] = []
        parents = _parent_map(fc.tree)

        def in_locked_with(node: ast.AST) -> bool:
            cur = parents.get(node)
            while cur is not None and not isinstance(
                cur, (ast.FunctionDef, ast.AsyncFunctionDef)
            ):
                if isinstance(cur, ast.With):
                    for item in cur.items:
                        if _attr_anywhere(item.context_expr, "stats_lock"):
                            return True
                cur = parents.get(cur)
            return False

        for func in ast.walk(fc.tree):
            if not isinstance(func, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            if func.name == "__init__":
                continue  # construction publishes the dicts before any reader
            # Aliases: s = self.spec_stats; kt = s["k_trace"] — anything
            # reached from a stats dict counts as the stats dict.
            aliases: Set[str] = set()
            changed = True
            while changed:
                changed = False
                for n in ast.walk(func):
                    if isinstance(n, ast.Assign) and len(n.targets) == 1:
                        t = n.targets[0]
                        if isinstance(t, ast.Name) and t.id not in aliases:
                            if self._is_stats_expr(n.value, aliases):
                                aliases.add(t.id)
                                changed = True

            for n in ast.walk(func):
                target = None
                if isinstance(n, (ast.Assign, ast.AugAssign)):
                    targets = n.targets if isinstance(n, ast.Assign) else [n.target]
                    for t in targets:
                        if isinstance(t, ast.Subscript) and self._is_stats_expr(
                            t.value, aliases
                        ):
                            target = t
                elif isinstance(n, ast.Call) and isinstance(n.func, ast.Attribute):
                    if n.func.attr in _MUTATORS and self._is_stats_expr(
                        n.func.value, aliases
                    ):
                        target = n
                elif isinstance(n, ast.Delete):
                    for t in n.targets:
                        if isinstance(t, ast.Subscript) and self._is_stats_expr(
                            t.value, aliases
                        ):
                            target = t
                if target is not None and not in_locked_with(target):
                    out.append(Finding(
                        self.id, fc.rel, target.lineno,
                        f"stats mutation in {func.name}() outside a "
                        "`with …stats_lock` block — the loop thread and "
                        "readers race on these dicts",
                    ))
        return out

    @staticmethod
    def _is_stats_expr(node: ast.AST, aliases: Set[str]) -> bool:
        if isinstance(node, ast.Attribute) and node.attr in _STATS_ATTRS:
            return True
        if isinstance(node, ast.Name) and node.id in aliases:
            return True
        if isinstance(node, ast.Subscript):
            return StatsLockDiscipline._is_stats_expr(node.value, aliases)
        return False


# ---------------------------------------------------------------------------
# host-sync — RELOCATED to kakveda_tpu/analysis/device.py: the jit-body
# checks now share the device family's JitIndex discovery (same rule id,
# same messages). The device-plane rules (retrace-hazard,
# donation-after-use, constant-capture, dynamic-slice-by-trace) live there.
# ---------------------------------------------------------------------------

_NP_NAMES = frozenset({"np", "onp", "numpy"})


# ---------------------------------------------------------------------------
# typed-errors
# ---------------------------------------------------------------------------

_TYPED_ERRORS = frozenset({
    "OverloadError", "DeviceUnavailableError", "EngineDeadError",
    "EngineRetryableError", "DeadlineExceededError",
})
_BROAD = frozenset({"Exception", "BaseException"})
# Calls whose raise surface includes the typed errors above.
_TYPED_SOURCES = frozenset({
    "submit", "generate_ids", "register_prefix", "try_admit", "admit",
    "shed", "slot", "check",
})
_PROPAGATORS = frozenset({"set_exception", "_fail", "fail", "note_failure"})


@register
class TypedErrorDiscipline(Rule):
    id = "typed-errors"
    invariant = (
        "no broad `except Exception` that swallows "
        "OverloadError/DeviceUnavailableError/EngineDeadError around "
        "admission/engine calls on service paths — shed work must surface "
        "as 429, never take the solo-decode fallback"
    )
    scope = (
        "kakveda_tpu/service/",
        "kakveda_tpu/cli/",
        "kakveda_tpu/core/admission.py",
        "kakveda_tpu/models/serving.py",
        "kakveda_tpu/models/generate.py",
        "kakveda_tpu/models/runtime.py",
    )

    def visit_file(self, fc: FileContext, ctx: TreeContext) -> List[Finding]:
        out: List[Finding] = []
        for n in ast.walk(fc.tree):
            if not isinstance(n, ast.Try):
                continue
            typed_handled = False
            for h in n.handlers:
                names = self._handler_names(h)
                if names & _TYPED_ERRORS:
                    typed_handled = True
                    continue
                broad = h.type is None or (names & _BROAD)
                if not broad or typed_handled:
                    continue
                if not self._body_calls_typed_source(n.body):
                    continue
                if self._handler_propagates(h):
                    continue
                out.append(Finding(
                    self.id, fc.rel, h.lineno,
                    "broad except around a typed-error source "
                    "(admission/engine call in this try) swallows "
                    "OverloadError/DeviceUnavailableError/EngineDeadError; "
                    "catch the typed errors first, re-raise, or propagate "
                    "the original exception",
                ))
        return out

    @staticmethod
    def _handler_names(h: ast.ExceptHandler) -> Set[str]:
        names: Set[str] = set()
        if h.type is None:
            return names
        nodes = h.type.elts if isinstance(h.type, ast.Tuple) else [h.type]
        for t in nodes:
            if isinstance(t, ast.Name):
                names.add(t.id)
            elif isinstance(t, ast.Attribute):
                names.add(t.attr)
        return names

    @staticmethod
    def _body_calls_typed_source(body) -> bool:
        for stmt in body:
            for n in ast.walk(stmt):
                if isinstance(n, ast.Call):
                    f = n.func
                    name = (
                        f.attr if isinstance(f, ast.Attribute)
                        else f.id if isinstance(f, ast.Name) else None
                    )
                    if name in _TYPED_SOURCES:
                        return True
        return False

    @staticmethod
    def _handler_propagates(h: ast.ExceptHandler) -> bool:
        for n in ast.walk(h):
            if isinstance(n, ast.Raise):
                if n.exc is None:
                    return True  # bare re-raise keeps the type
                if isinstance(n.exc, ast.Call):
                    f = n.exc.func
                    name = f.id if isinstance(f, ast.Name) else (
                        f.attr if isinstance(f, ast.Attribute) else None
                    )
                    if name in _TYPED_ERRORS:
                        return True
            elif isinstance(n, ast.Call) and h.name is not None:
                f = n.func
                name = (
                    f.attr if isinstance(f, ast.Attribute)
                    else f.id if isinstance(f, ast.Name) else None
                )
                if name in _PROPAGATORS and any(
                    isinstance(a, ast.Name) and a.id == h.name for a in n.args
                ):
                    return True
        return False


# ---------------------------------------------------------------------------
# fault-site-once
# ---------------------------------------------------------------------------


@register
class FaultSiteOnce(Rule):
    id = "fault-site-once"
    invariant = (
        "faults.site(\"…\") resolves ONCE at construction (module import "
        "or __init__) — the hot path calls .fire() on the kept reference, "
        "never re-resolves"
    )
    scope = ("kakveda_tpu/", "bench.py", "scripts/", "__graft_entry__.py")

    def visit_file(self, fc: FileContext, ctx: TreeContext) -> List[Finding]:
        if fc.rel == "kakveda_tpu/core/faults.py":
            return []  # the registry itself
        out: List[Finding] = []
        parents = None
        for n in ast.walk(fc.tree):
            if not (
                isinstance(n, ast.Call)
                and isinstance(n.func, (ast.Name, ast.Attribute))
                and (
                    n.func.id == "site"
                    if isinstance(n.func, ast.Name)
                    else n.func.attr == "site"
                )
                and n.args
            ):
                continue
            name = _const_str(n.args[0])
            if name is None or "." not in name:
                continue
            if parents is None:
                parents = _parent_map(fc.tree)
            func = _enclosing_function(n, parents)
            if func is None or func.name == "__init__":
                continue  # construction / import time: the contract
            out.append(Finding(
                self.id, fc.rel, n.lineno,
                f"fault site {name!r} resolved inside {func.name}() — "
                "resolve once at construction and keep the reference "
                "(unarmed fire() is a bare attribute check; site() takes "
                "a lock)",
            ))
        return out


# ---------------------------------------------------------------------------
# fault-site-catalog + knob-docs (check_knobs, as rules)
# ---------------------------------------------------------------------------


def _evidence(ctx: TreeContext, files: List[str], needle: str) -> Tuple[str, int]:
    """(file, line) of the first reference to ``needle`` among ``files``."""
    for rel in files:
        fc = ctx.by_rel.get(str(rel).replace("\\", "/"))
        if fc is not None:
            return fc.rel, fc.find_line(needle)
    return files[0] if files else "?", 1


@register
class FaultSiteCatalog(Rule):
    id = "fault-site-catalog"
    invariant = (
        "every fault site registered in code appears in the "
        "docs/robustness.md catalog — the only surface operators can "
        "discover KAKVEDA_FAULTS arms from"
    )
    scope = None

    def check_tree(self, ctx: TreeContext) -> List[Finding]:
        out: List[Finding] = []
        for site, files in _knobs.undocumented_fault_sites(ctx.root).items():
            rel, line = _evidence(ctx, files, site)
            out.append(Finding(
                self.id, rel, line,
                f"fault site {site!r} is registered here but missing from "
                "the docs/robustness.md catalog",
            ))
        return out


@register
class KnobDocsParity(Rule):
    id = "knob-docs"
    invariant = (
        "every KAKVEDA_* knob the code reads is documented, and every "
        "documented knob is still read (no dead-knob drift)"
    )
    scope = None

    def check_tree(self, ctx: TreeContext) -> List[Finding]:
        out: List[Finding] = []
        for knob, files in _knobs.undocumented_knobs(ctx.root).items():
            rel, line = _evidence(ctx, files, knob)
            out.append(Finding(
                self.id, rel, line,
                f"env knob {knob} is read here but documented nowhere "
                "(CLAUDE.md / docs/) — an undocumented knob is an outage "
                "waiting for an operator",
            ))
        for knob in _knobs.dead_knobs(ctx.root):
            rel, line = "docs", 1
            for md in _discovery.md_files(ctx.root):
                try:
                    text = md.read_text(errors="replace")
                except OSError:
                    continue
                if knob in text:
                    rel = md.relative_to(ctx.root).as_posix()
                    line = next(
                        (i for i, ln in enumerate(text.splitlines(), 1) if knob in ln),
                        1,
                    )
                    break
            out.append(Finding(
                self.id, rel, line,
                f"env knob {knob} is documented but no code reads it — "
                "dead-knob drift sends operators tuning a no-op",
            ))
        return out


# ---------------------------------------------------------------------------
# atomic-log-rewrite
# ---------------------------------------------------------------------------

# The replayed stores: every byte of these files is state (restart = replay),
# so an in-place "w"-mode rewrite that crashes mid-write IS data loss. The
# only legal rewrite is write-tmp -> fsync -> os.replace (the compaction
# idiom); expressions routed through .with_suffix() derive such a tmp/bak
# sibling and pass.
_REPLAYED_LOG_ATTRS = frozenset({
    "failures_path", "patterns_path", "applied_path", "tombstones_path",
})
_REPLAYED_LOG_NAMES = (
    "failures.jsonl", "patterns.jsonl", "applied_events.jsonl",
    "tombstones.jsonl",
)
_TRUNCATING_WRITERS = frozenset({"write_text", "write_bytes"})


def _replayed_log_ref(node: ast.AST) -> Optional[str]:
    """The replayed log this path expression refers to (else None).
    ``.with_suffix``-derived expressions name a tmp/bak sibling, not the
    log itself — they return None by design."""
    for n in ast.walk(node):
        if isinstance(n, ast.Attribute) and n.attr == "with_suffix":
            return None
    for n in ast.walk(node):
        if isinstance(n, ast.Attribute) and n.attr in _REPLAYED_LOG_ATTRS:
            return n.attr
        s = _const_str(n)
        if s is not None:
            for name in _REPLAYED_LOG_NAMES:
                if s == name or s.endswith("/" + name):
                    return name
    return None


def _truncating_write_target(call: ast.Call) -> Optional[ast.AST]:
    """The path expression a call truncates, if it is a truncating write:
    ``X.write_text(...)`` / ``X.write_bytes(...)`` / ``X.open("w"…)`` /
    ``open(X, "w"…)`` — else None. Append ("a") and read modes pass."""
    f = call.func
    if isinstance(f, ast.Attribute) and f.attr in _TRUNCATING_WRITERS:
        return f.value
    if isinstance(f, ast.Attribute) and f.attr == "open":
        mode = _const_str(call.args[0]) if call.args else None
        if mode is None:
            for kw in call.keywords:
                if kw.arg == "mode":
                    mode = _const_str(kw.value)
        if mode is not None and mode.startswith("w"):
            return f.value
    if isinstance(f, ast.Name) and f.id == "open" and len(call.args) >= 2:
        mode = _const_str(call.args[1])
        if mode is not None and mode.startswith("w"):
            return call.args[0]
    return None


@register
class AtomicLogRewrite(Rule):
    id = "atomic-log-rewrite"
    invariant = (
        "replayed logs (failures/patterns/applied_events/tombstones "
        ".jsonl) are never opened 'w' in place — rewrites go write-tmp + "
        "fsync + os.replace (crash at any byte leaves old or new log "
        "fully live); torn-FINAL-line truncation is the only in-place "
        "surgery and it goes through _truncate_pending"
    )
    scope = ("kakveda_tpu/", "bench.py", "scripts/", "__graft_entry__.py")

    def visit_file(self, fc: FileContext, ctx: TreeContext) -> List[Finding]:
        out: List[Finding] = []
        # Local helpers that "w"-rewrite one of their own parameters: a
        # call passing a replayed-log path into one is the same hazard one
        # hop away (the routes_admin _purge_jsonl shape).
        rewriting_helpers: Dict[str, Set[int]] = {}
        for n in ast.walk(fc.tree):
            if not isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            params = {a.arg for a in n.args.args if a.arg != "self"}
            if not params:
                continue
            hit = {
                i for i, a in enumerate(n.args.args)
                for c in ast.walk(n)
                if isinstance(c, ast.Call)
                and (t := _truncating_write_target(c)) is not None
                and isinstance(t, ast.Name) and t.id == a.arg
            }
            if hit:
                rewriting_helpers[n.name] = hit
        for n in ast.walk(fc.tree):
            if not isinstance(n, ast.Call):
                continue
            target = _truncating_write_target(n)
            if target is not None:
                ref = _replayed_log_ref(target)
                if ref is not None:
                    out.append(Finding(
                        self.id, fc.rel, n.lineno,
                        f"in-place 'w'-mode rewrite of replayed log "
                        f"{ref!r} — a crash mid-write loses committed "
                        "state; write a .tmp sibling, fsync, then "
                        "os.replace (or append)",
                    ))
                continue
            if isinstance(n.func, ast.Name) and n.func.id in rewriting_helpers:
                for i, arg in enumerate(n.args):
                    if i not in rewriting_helpers[n.func.id]:
                        continue
                    ref = _replayed_log_ref(arg)
                    if ref is not None:
                        out.append(Finding(
                            self.id, fc.rel, n.lineno,
                            f"replayed log {ref!r} passed into "
                            f"{n.func.id}(), which rewrites its argument "
                            "in place with mode 'w' — a crash mid-write "
                            "loses committed state; rewrite via .tmp + "
                            "os.replace",
                        ))
        return out
