"""kakveda-tpu command line interface."""
