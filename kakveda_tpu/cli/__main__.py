"""`python -m kakveda_tpu.cli` — same entry as the `kakveda-tpu` script."""

import sys

from kakveda_tpu.cli.main import main

if __name__ == "__main__":
    sys.exit(main())
