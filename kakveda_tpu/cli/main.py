"""`kakveda-tpu` CLI: init | up | down | status | reset | logs | dlq | traffic | compact | doctor | version.

Verb parity with the reference CLI (reference: kakveda_cli/cli.py:46-409),
re-targeted at the single-process TPU platform: where the reference
orchestrates a 9-container docker-compose stack, `up` here starts the
in-process service layer (all reference REST contracts on one port) and
`doctor` checks the JAX/TPU environment instead of the Docker daemon.
"""

from __future__ import annotations

import argparse
import json
import os
import shutil
import sys
from pathlib import Path


def _cmd_version(args: argparse.Namespace) -> int:
    from kakveda_tpu import __version__

    print(f"kakveda-tpu {__version__}")
    return 0


def _cmd_init(args: argparse.Namespace) -> int:
    from kakveda_tpu.core.config import write_default_config

    root = Path(args.dir)
    cfg = root / "config" / "config.yaml"
    if cfg.exists() and not args.force:
        print(f"config already exists at {cfg} (use --force to overwrite)")
    else:
        write_default_config(cfg)
        print(f"wrote {cfg}")
    (root / "data").mkdir(parents=True, exist_ok=True)
    print(f"data dir ready at {root / 'data'}")
    if args.wizard or args.yes:
        from kakveda_tpu.cli.wizard import run_wizard

        run_wizard(root, assume_yes=args.yes)
    return 0


def _cmd_doctor(args: argparse.Namespace) -> int:
    checks = []

    def check(name, fn):
        try:
            detail = fn()
            checks.append((name, True, detail))
        except Exception as e:  # noqa: BLE001 — doctor reports, never crashes
            checks.append((name, False, f"{type(e).__name__}: {e}"))

    def _jax():
        import jax

        # Honor JAX_PLATFORMS before touching the backend: the image's
        # sitecustomize pins jax to the remote accelerator via jax.config,
        # and with the chip in an outage the claim loop BLOCKS (no
        # exception for the fallback below to catch) — doctor would hang.
        plat = os.environ.get("JAX_PLATFORMS", "")
        if plat:
            try:
                jax.config.update("jax_platforms", plat.lower())
            except Exception:  # noqa: BLE001 — best effort
                pass

        try:
            backend = jax.default_backend()
            note = ""
        except RuntimeError:
            # Accelerator plugin present but not initializable from this
            # environment — fall back so the rest of doctor still runs.
            jax.config.update("jax_platforms", "cpu")
            backend = jax.default_backend()
            note = " (accelerator unavailable here; fell back to cpu)"
        return f"{jax.__version__} backend={backend} devices={len(jax.devices())}{note}"

    def _mesh():
        from kakveda_tpu.parallel.mesh import create_mesh

        mesh = create_mesh(os.environ.get("KAKVEDA_MESH_SHAPE", "data:-1"))
        return f"axes={dict(mesh.shape)}"

    def _device_compute():
        import jax
        import jax.numpy as jnp

        x = jnp.ones((128, 128), jnp.float32)
        y = jax.jit(lambda a: (a @ a).sum())(x)
        return f"matmul ok (sum={float(y):.0f})"

    def _native():
        from kakveda_tpu import native

        return "C++ fast path loaded" if native.available() else "pure-python fallback (run make in kakveda_tpu/native)"

    def _config_parse():
        from kakveda_tpu.core.config import ConfigStore

        cs = ConfigStore()
        return f"threshold={cs.similarity_threshold()} top_k={cs.match_top_k()}"

    def _jwt_secret():
        from kakveda_tpu.core.runtime import get_runtime_config

        rc = get_runtime_config(service_name="doctor")
        if rc.env == "production" and rc.dashboard_jwt_secret == "dev-secret-change-me":
            raise RuntimeError("production with default JWT secret — set DASHBOARD_JWT_SECRET")
        return "set" if rc.dashboard_jwt_secret != "dev-secret-change-me" else "dev default (fine outside production)"

    def _serving_levers():
        """The env-tunable serving configuration in one line — what the
        engine will actually run with (models/serving.py knobs)."""
        e = os.environ.get
        parts = [
            f"continuous={'on' if e('KAKVEDA_SERVE_CONTINUOUS', '1') != '0' else 'OFF'}",
            f"slots={e('KAKVEDA_SERVE_SLOTS', '8')}",
            f"window={e('KAKVEDA_SERVE_WINDOW', 'auto')}",
            f"chunk={e('KAKVEDA_SERVE_CHUNK', '8')}",
            f"pipeline={'on' if e('KAKVEDA_SERVE_PIPELINE', '1') != '0' else 'OFF'}",
            f"prefix={'on' if e('KAKVEDA_SERVE_PREFIX', '1') != '0' else 'OFF'}",
            f"spec_k={e('KAKVEDA_SERVE_SPEC', '0')}",
            f"spec_gate=warmup{e('KAKVEDA_SERVE_SPEC_WARMUP', '8')}"
            f"/calib{e('KAKVEDA_SERVE_SPEC_CALIB', '2')}"
            f"/reprobe{e('KAKVEDA_SERVE_SPEC_REPROBE', '256')}",
            f"quant={e('KAKVEDA_QUANT', 'none')}",
            f"kv_quant={e('KAKVEDA_KV_QUANT', 'none')}",
        ]
        if e("KAKVEDA_HBM_BUDGET"):
            parts.append(f"hbm_budget={e('KAKVEDA_HBM_BUDGET')}")
        return " ".join(parts)

    def _redis():
        url = os.environ.get("KAKVEDA_REDIS_URL")
        if not url:
            return "not configured (in-memory revocation/rate-limit)"
        import redis  # type: ignore[import-not-found]

        redis.Redis.from_url(url, socket_timeout=1).ping()
        # Redact userinfo — the URL may carry a password, and doctor output
        # lands in terminals and CI logs.
        safe = url.split("@", 1)[-1] if "@" in url else url.split("//", 1)[-1]
        return f"reachable at {safe}"

    def _fleet():
        """Per-replica health + fleet admission mode, from the manifest
        `up --replicas` writes (fleet.json) — mirrors the router's
        /readyz report for operators without curl."""
        from kakveda_tpu.fleet.supervisor import read_manifest

        manifest = read_manifest(args.dir)
        if not manifest:
            return "single-process (no fleet.json)"
        import httpx

        parts = []
        worst = "normal"
        live_ids = []
        epochs = {}
        for rep in manifest.get("replicas", []):
            rid = rep.get("id", "?")
            pidp = Path(rep.get("pid_file", ""))
            alive = False
            try:
                alive = _pid_alive(int(pidp.read_text().strip()))
            except (OSError, ValueError):
                pass
            mode = "down"
            if alive:
                try:
                    r = httpx.get(rep["url"] + "/readyz", timeout=2.0)
                    r.raise_for_status()
                    body = r.json()
                    adm = body.get("admission", {})
                    mode = adm.get("brownout", "?")
                    steps = ("normal", "no_spec", "clamped",
                             "shed_background", "shed_interactive")
                    if mode in steps and steps.index(mode) > steps.index(worst):
                        worst = mode
                    live_ids.append(rid)
                    own = body.get("ownership") or {}
                    if own.get("enabled"):
                        epochs[rid] = int(own.get("epoch", 0))
                except (httpx.HTTPError, ValueError):
                    mode = "unreachable"
            parts.append(f"{rid}={'up' if alive else 'DOWN'}/{mode}")
        if any("DOWN" in p or "unreachable" in p for p in parts):
            raise RuntimeError(" ".join(parts))
        own_note = ""
        if epochs:
            # Sharded ownership (fleet/ownership.py): every reachable
            # replica must agree on the epoch, and every key range needs
            # at least one live holder — either failing is a doctor
            # ERROR, not a warning (stale views mis-fence replication;
            # a coverage hole silently un-answers a key range).
            if len(set(epochs.values())) > 1:
                raise RuntimeError(
                    f"{' '.join(parts)} ownership epochs DISAGREE: {epochs}"
                )
            from kakveda_tpu.fleet.ownership import OwnershipView

            top = max(epochs, key=epochs.get)
            url = next(r["url"] for r in manifest["replicas"]
                       if r.get("id") == top)
            try:
                view = OwnershipView.from_dict(
                    httpx.get(url + "/fleet/ownership", timeout=2.0).json()
                )
            except (httpx.HTTPError, ValueError, KeyError) as e:
                raise RuntimeError(f"ownership view unreadable: {e}") from e
            holes = view.coverage_holes(live_ids)
            if holes:
                raise RuntimeError(
                    f"{' '.join(parts)} COVERAGE HOLES: {holes} ranges "
                    f"have zero live holders (epoch {epochs[top]})"
                )
            own_note = (f" ownership=epoch:{epochs[top]}"
                        f"/R:{view.replication}/holes:0")
        scale_note = ""
        auto = manifest.get("autoscale")
        if auto:
            # Elastic fleet (fleet/autoscaler.py): surface the policy
            # bounds and the last ledgered decision so an operator sees a
            # crash-looping replacement or a stuck drain without curl.
            scale_note = f" autoscale={auto.get('min')}..{auto.get('max')}"
            last = None
            try:
                lines = Path(auto.get("scale_log", "")).read_text().splitlines()
                if lines:
                    last = json.loads(lines[-1])
            except (OSError, ValueError):
                pass
            if last:
                scale_note += (f" last={last.get('action')}:"
                               f"{last.get('outcome')}→{last.get('target')}")
        return f"{' '.join(parts)} fleet_mode={worst}{own_note}{scale_note}"

    def _tenant_plane():
        """Tenant fairness posture (docs/robustness.md § multi-tenancy):
        reads the live server's /readyz admission.tenants block. A tenant
        pinned at 100% shed — many sheds, ZERO admits — is a doctor
        ERROR: either a flooder that should be talked to, or (if it's a
        victim) an isolation bug. No live server is fine (the plane only
        exists in-process)."""
        pid = _read_pid(Path(args.dir))
        if not (pid and _pid_alive(pid)):
            return "no live server (probes /readyz when one is up)"
        import httpx

        body = httpx.get(args.url + "/readyz", timeout=2.0).json()
        tenants = (body.get("admission") or {}).get("tenants")
        if not tenants:
            return "admission reports no tenant block (older server?)"
        if not tenants.get("fair", False):
            return "KAKVEDA_TENANT_FAIR=0 — global FIFO, no isolation"
        pinned = [
            row for row in tenants.get("top_shed", [])
            if row.get("sheds", 0) >= 20 and row.get("admits", 0) == 0
        ]
        note = (
            f"fair=on table={tenants.get('table_size')}/"
            f"{tenants.get('table_max')} share_cap={tenants.get('max_share')} "
            f"promotions={tenants.get('promotions') or {}}"
        )
        top = tenants.get("top_shed", [])
        if top:
            worst = top[0]
            note += (f" top_shed={worst.get('tenant')}:"
                     f"{worst.get('sheds')}")
        if pinned:
            raise RuntimeError(
                f"{note} — tenant(s) pinned at 100% shed: "
                + ", ".join(f"{r['tenant']} ({r['sheds']} sheds, 0 admits)"
                            for r in pinned)
            )
        return note

    def _replay_budget():
        """Durability posture vs the operator's recovery-time budget:
        KAKVEDA_GFKB_REPLAY_BUDGET_S > 0 turns the replay estimate into a
        hard doctor check — a restart that would replay longer than the
        budget is an error to fix with `kakveda-tpu compact`, not a
        surprise during the next incident."""
        data = Path(args.dir) / "data"
        if not data.exists():
            return "no data dir yet"
        post = _durability_posture(data)
        budget = float(os.environ.get("KAKVEDA_GFKB_REPLAY_BUDGET_S", "0"))
        est = post["replay_estimate_s"]
        note = (
            f"replay≈{est}s ({post['replayable_bytes']}B replayable, "
            f"generation {post['compact_generation']}, "
            f"{post['tombstoned_rows']} tombstoned)"
        )
        if budget > 0 and est > budget:
            raise RuntimeError(
                f"{note} exceeds KAKVEDA_GFKB_REPLAY_BUDGET_S={budget} — "
                f"run `kakveda-tpu compact`"
            )
        return note

    check("python", lambda: sys.version.split()[0])
    check("replay budget", _replay_budget)
    check("tenant plane", _tenant_plane)
    check("fleet", _fleet)
    check("jax", _jax)
    check("device mesh", _mesh)
    check("device compute", _device_compute)
    check("native extension", _native)
    check("config", lambda: str(Path(os.environ.get("KAKVEDA_CONFIG_PATH", "config/config.yaml")).resolve()))
    check("serving levers", _serving_levers)
    check("config parse", _config_parse)
    check("jwt secret", _jwt_secret)
    check("redis", _redis)
    check("data dir writable", lambda: _writable(os.environ.get("KAKVEDA_DATA_DIR", "data")))

    ok = all(c[1] for c in checks)
    for name, good, detail in checks:
        print(f"[{'ok' if good else 'FAIL'}] {name}: {detail}")
    return 0 if ok else 1


def _writable(d: str) -> str:
    p = Path(d)
    p.mkdir(parents=True, exist_ok=True)
    probe = p / ".probe"
    probe.write_text("ok")
    probe.unlink()
    return str(p.resolve())


def _cmd_reset(args: argparse.Namespace) -> int:
    root = Path(args.dir)
    data = root / "data"
    if not data.exists():
        print(f"nothing to reset at {data}")
        return 0
    if not args.yes:
        print(f"would delete {data} — re-run with --yes to confirm")
        return 1
    shutil.rmtree(data)
    print(f"deleted {data}")
    return 0


def _pid_path(root: Path) -> Path:
    return root / "server.pid"


def _log_path(root: Path) -> Path:
    return root / "server.log"


def _read_pid(root: Path) -> int | None:
    p = _pid_path(root)
    try:
        return int(p.read_text().strip())
    except (OSError, ValueError):
        return None


def _pid_alive(pid: int) -> bool:
    try:
        os.kill(pid, 0)
        return True
    except ProcessLookupError:
        return False
    except PermissionError:
        return True


def _durability_posture(data: Path) -> dict:
    """Per-store durability posture from the files alone — no jax, no
    GFKB construction, safe against a live server holding the store.

    Replay time is estimated as replayable-bytes / KAKVEDA_GFKB_REPLAY_RATE
    (bytes/s, default 4 MiB/s — conservative for the pydantic JSONL parse
    path); replayable bytes start at the snapshot manifest's log_offset,
    so a compaction directly shrinks the estimate the operator sees."""
    rate = float(os.environ.get("KAKVEDA_GFKB_REPLAY_RATE", str(4 << 20)))
    stores = {}
    replayable = 0
    for name in ("failures", "patterns", "applied_events", "tombstones"):
        f = data / f"{name}.jsonl"
        try:
            size = f.stat().st_size
        except OSError:
            size = 0
        stores[name] = {"bytes": size}
        replayable += size
    manifest = {}
    try:
        manifest = json.loads((data / "snapshot" / "manifest.json").read_text())
    except (OSError, ValueError):
        pass
    offset = int(manifest.get("log_offset", 0) or 0)
    # The snapshot replaces log replay up to log_offset.
    stores["failures"]["replayable_bytes"] = max(
        0, stores["failures"]["bytes"] - offset
    )
    replayable -= min(offset, stores["failures"]["bytes"])
    tomb = 0
    f = data / "tombstones.jsonl"
    if f.exists():
        net = {}
        try:
            for ln in f.read_text().splitlines():
                ln = ln.strip()
                if not ln:
                    continue
                try:
                    rec = json.loads(ln)
                except ValueError:
                    continue  # torn tail — the store's replay handles it
                if rec.get("op") == "tomb":
                    net[rec.get("id")] = rec.get("reason")
                else:
                    net.pop(rec.get("id"), None)
            tomb = len(net)
        except OSError:
            pass
    compact = manifest.get("compact") or {}
    return {
        "stores": stores,
        "snapshot_rows": int(manifest.get("n", 0) or 0),
        "compact_generation": int(compact.get("generation", 0) or 0),
        "last_compact_ts": float(compact.get("ts", 0.0) or 0.0),
        "tombstoned_rows": tomb,
        "replayable_bytes": max(0, replayable),
        "replay_estimate_s": round(max(0, replayable) / rate, 3),
    }


def _cmd_status(args: argparse.Namespace) -> int:
    root = Path(args.dir)
    data = root / "data"
    status = {"data_dir": str(data), "exists": data.exists()}
    for name in ("failures", "patterns", "health"):
        f = data / f"{name}.jsonl"
        status[name] = sum(1 for ln in f.read_text().splitlines() if ln.strip()) if f.exists() else 0
    if data.exists():
        status["durability"] = _durability_posture(data)
    pid = _read_pid(root)
    status["server"] = (
        {"pid": pid, "running": _pid_alive(pid)} if pid else {"pid": None, "running": False}
    )
    if status["server"]["running"]:
        # Tenant plane (docs/robustness.md § multi-tenancy): quota table
        # occupancy + top shed tenants + promotion counts, straight from
        # the live server's /readyz admission block. Best effort — an
        # unreachable server just omits the block.
        try:
            import httpx

            body = httpx.get(args.url + "/readyz", timeout=2.0).json()
            tenants = (body.get("admission") or {}).get("tenants")
            if tenants:
                status["tenants"] = tenants
        except Exception:  # noqa: BLE001 — status reports, never crashes
            pass
    replicas = {}
    for pidp in sorted(root.glob("replica-*.pid")):
        try:
            rpid = int(pidp.read_text().strip())
        except (OSError, ValueError):
            continue
        replicas[pidp.stem] = {"pid": rpid, "running": _pid_alive(rpid)}
    if replicas:
        status["replicas"] = replicas
        # Sharded ownership: per-replica owned/standby ranges + resident
        # row split and the acknowledged epoch, straight from /readyz.
        from kakveda_tpu.fleet.supervisor import read_manifest

        manifest = read_manifest(root) or {}
        if (manifest.get("ownership") or {}).get("enabled"):
            import httpx

            ownership = {}
            for rep in manifest.get("replicas", []):
                rid = rep.get("id", "?")
                try:
                    body = httpx.get(rep["url"] + "/readyz", timeout=2.0).json()
                    own = body.get("ownership") or {}
                    ownership[rid] = {
                        "epoch": own.get("epoch"),
                        "owned_arcs": own.get("owned_arcs"),
                        "standby_arcs": own.get("standby_arcs"),
                        "rows": own.get("rows"),
                        "gfkb_count": body.get("gfkb_count"),
                    }
                except (httpx.HTTPError, ValueError):
                    ownership[rid] = {"unreachable": True}
            status["ownership"] = ownership
        auto = (manifest or {}).get("autoscale")
        if auto:
            # Elastic fleet: policy bounds + the tail of the decision
            # ledger (data/scale_log.jsonl — one typed record per
            # autoscaler decision, docs/scale-out.md § elastic fleet).
            block = {"min": auto.get("min"), "max": auto.get("max")}
            try:
                lines = Path(auto.get("scale_log", "")).read_text().splitlines()
                block["decisions"] = len(lines)
                block["last_decisions"] = [
                    json.loads(ln) for ln in lines[-5:] if ln.strip()
                ]
            except (OSError, ValueError):
                block["decisions"] = 0
            status["autoscale"] = block
    print(json.dumps(status, indent=2))
    return 0


def _cmd_compact(args: argparse.Namespace) -> int:
    """Offline failures-log compaction: open the GFKB against the data
    dir, checkpoint + rewrite, print the posture delta. Refuses while a
    recorded server owns the store — the GFKB is single-writer, and a
    live process would keep appending into the pre-swap inode."""
    root = Path(args.dir)
    data = root / "data"
    pid = _read_pid(root)
    if pid and _pid_alive(pid) and not args.force:
        print(
            f"server pid {pid} is running against {data} — stop it first "
            f"(or --force if you know the pid file is stale)",
            file=sys.stderr,
        )
        return 1
    if not (data / "failures.jsonl").exists():
        print(f"nothing to compact: no failures log under {data}")
        return 0
    before = _durability_posture(data)
    import jax

    # In-process override beats the image's TPU-pinning sitecustomize; a
    # maintenance rewrite must never touch (or wedge) the device lease.
    jax.config.update("jax_platforms", "cpu")
    from kakveda_tpu.core.config import ConfigStore
    from kakveda_tpu.index.gfkb import GFKB

    dim = args.dim or ConfigStore().embedding_dim()
    kb = GFKB(data_dir=data, capacity=args.capacity, dim=dim)
    try:
        if args.age_ttl > 0:
            aged = kb.age_rows(ttl_s=args.age_ttl)
            print(f"aged out {aged['tombstoned']} rows (ttl {args.age_ttl}s)")
        if args.collapse > 1:
            col = kb.collapse_duplicates(min_cluster=args.collapse)
            print(
                f"collapsed {col['collapsed']} rows across "
                f"{col['clusters']} clusters"
            )
        out = kb.compact()
    finally:
        kb.close()
    after = _durability_posture(data)
    print(
        json.dumps(
            {
                "compact": out,
                "replay_estimate_s": {
                    "before": before["replay_estimate_s"],
                    "after": after["replay_estimate_s"],
                },
                "durability": after,
            },
            indent=2,
        )
    )
    return 0


def _cmd_up(args: argparse.Namespace) -> int:
    root = Path(args.dir)

    if getattr(args, "replica_index", None) is not None:
        # We ARE a fleet replica (spawned by the supervisor): a plain
        # single-process server with its own data dir and pid file beside
        # server.pid (replica-<i>.pid / data/replica-<i>/). Fleet identity
        # (KAKVEDA_REPLICA_ID / _FLEET_SELF / _FLEET_PEERS) arrived in env.
        i = int(args.replica_index)
        try:
            from kakveda_tpu.service.main import run_server
        except ImportError:
            print("the HTTP service layer is not available in this build", file=sys.stderr)
            return 1
        pidp = root / f"replica-{i}.pid"
        root.mkdir(parents=True, exist_ok=True)
        pidp.write_text(str(os.getpid()))
        try:
            return run_server(
                host=args.host,
                port=args.port,
                data_dir=str(root / "data" / f"replica-{i}"),
                dashboard_port=args.dashboard_port or None,
            )
        finally:
            try:
                if int(pidp.read_text().strip()) == os.getpid():
                    pidp.unlink()
            except (OSError, ValueError):
                pass

    pid = _read_pid(root)
    # pid == os.getpid(): we ARE the detached child (the parent recorded
    # our pid before exec'ing us) — not a conflict.
    if pid and pid != os.getpid() and _pid_alive(pid):
        print(f"server already running (pid {pid}); `kakveda-tpu down` first", file=sys.stderr)
        return 1

    if getattr(args, "detach", False):
        # Background mode, the reference's `up` semantics
        # (reference: kakveda_cli/cli.py:104-123 detaches via compose):
        # re-exec the foreground verb with stdout/err into server.log and
        # record the child pid for down/logs.
        import subprocess

        cmd = [
            sys.executable, "-m", "kakveda_tpu.cli", "up",
            "--dir", str(root), "--host", args.host, "--port", str(args.port),
            "--dashboard-port", str(args.dashboard_port),
        ]
        if getattr(args, "replicas", 0):
            cmd += ["--replicas", str(args.replicas),
                    "--port-base", str(args.port_base or 0)]
            if getattr(args, "autoscale", None):
                cmd += ["--autoscale", args.autoscale]
        root.mkdir(parents=True, exist_ok=True)  # fresh --dir: log lives inside
        logf = open(_log_path(root), "ab")
        proc = subprocess.Popen(
            cmd, stdout=logf, stderr=subprocess.STDOUT, start_new_session=True
        )
        _pid_path(root).write_text(str(proc.pid))
        print(f"server starting (pid {proc.pid}); logs: {_log_path(root)}")
        return 0

    if getattr(args, "replicas", 0):
        return _run_fleet(args, root)

    try:
        from kakveda_tpu.service.main import run_server
    except ImportError:
        print("the HTTP service layer is not available in this build", file=sys.stderr)
        return 1
    _pid_path(root).write_text(str(os.getpid()))
    try:
        return run_server(
            host=args.host,
            port=args.port,
            data_dir=str(root / "data"),
            dashboard_port=args.dashboard_port or None,
        )
    finally:
        try:
            if _read_pid(root) == os.getpid():
                _pid_path(root).unlink()
        except OSError:
            pass


def _run_fleet(args: argparse.Namespace, root: Path) -> int:
    """`up --replicas N [--port-base P] [--autoscale MIN:MAX]`: spawn N
    replica servers on P..P+N-1 (per-replica pid/log files, private data
    dirs), wait for readiness, then serve the front router
    (fleet/router.py) on --port. The router supervises: health probes +
    ejection always; process restarts within KAKVEDA_FLEET_RESTARTS — or,
    with --autoscale, the elastic policy loop (fleet/autoscaler.py):
    scale-up on sustained pressure, lossless drain on idle, dead-replica
    replacement (which subsumes the restart duty). Teardown (SIGTERM/exit
    or `kakveda-tpu down`) stops every replica."""
    from aiohttp import web

    from kakveda_tpu.fleet.router import make_router_app
    from kakveda_tpu.fleet.supervisor import FleetSupervisor

    autoscale = None
    if getattr(args, "autoscale", None):
        try:
            mn_s, mx_s = str(args.autoscale).split(":", 1)
            autoscale = (int(mn_s), int(mx_s))
        except ValueError:
            print(f"bad --autoscale {args.autoscale!r} (want MIN:MAX)",
                  file=sys.stderr)
            return 2
        if not (1 <= autoscale[0] <= autoscale[1]):
            print(f"bad --autoscale bounds {autoscale} (want 1 <= min <= max)",
                  file=sys.stderr)
            return 2

    port_base = args.port_base or (args.port + 1)
    sup = FleetSupervisor(
        root, host=args.host, port_base=port_base,
        replicas=args.replicas, router_port=args.port,
    )
    if autoscale is not None:
        sup.autoscale = autoscale  # manifest block for status/doctor
    _pid_path(root).write_text(str(os.getpid()))
    sup.start_all()
    print(
        f"fleet: {args.replicas} replicas starting on ports "
        f"{port_base}..{port_base + args.replicas - 1} "
        f"(replica-<i>.pid / replica-<i>.log under {root})"
        + (f" autoscale={autoscale[0]}..{autoscale[1]}" if autoscale else "")
    )
    try:
        sup.wait_ready(timeout_s=float(os.environ.get("KAKVEDA_FLEET_READY_S", "240")))
        app = make_router_app(sup.backend_map(), supervisor=sup,
                              autoscale=autoscale)
        print(f"fleet router on http://{args.host}:{args.port}")
        web.run_app(app, host=args.host, port=args.port, print=None)
        return 0
    finally:
        sup.stop_all()
        try:
            if _read_pid(root) == os.getpid():
                _pid_path(root).unlink()
        except OSError:
            pass


def _cmd_down(args: argparse.Namespace) -> int:
    """Stop the server recorded in server.pid (SIGTERM, bounded wait) —
    real process management, matching the operational intent of the
    reference's compose-backed `down` (reference: kakveda_cli/cli.py:124-136)."""
    import signal
    import time

    root = Path(args.dir)
    rc = 0
    pid = _read_pid(root)
    if pid is None:
        print("no server.pid — nothing to stop")
    elif not _pid_alive(pid):
        print(f"stale server.pid (pid {pid} not running); cleaning up")
        _pid_path(root).unlink(missing_ok=True)
    else:
        os.kill(pid, signal.SIGTERM)
        deadline = time.time() + args.timeout
        while _pid_alive(pid) and time.time() < deadline:
            time.sleep(0.2)
        if _pid_alive(pid):
            print(f"pid {pid} did not exit within {args.timeout}s (still running)",
                  file=sys.stderr)
            rc = 1
        else:
            _pid_path(root).unlink(missing_ok=True)
            print(f"stopped (pid {pid})")

    # Fleet sweep: a foreground fleet parent tears its replicas down on
    # exit, but a crashed parent (or a SIGKILL'd router) leaves
    # replica-<i>.pid files behind — stop whatever still runs.
    for pidp in sorted(root.glob("replica-*.pid")):
        try:
            rpid = int(pidp.read_text().strip())
        except (OSError, ValueError):
            pidp.unlink(missing_ok=True)
            continue
        if _pid_alive(rpid):
            os.kill(rpid, signal.SIGTERM)
            deadline = time.time() + args.timeout
            while _pid_alive(rpid) and time.time() < deadline:
                time.sleep(0.2)
            if _pid_alive(rpid):
                print(f"replica pid {rpid} did not exit within {args.timeout}s",
                      file=sys.stderr)
                rc = 1
                continue
            print(f"stopped replica (pid {rpid})")
        pidp.unlink(missing_ok=True)
    (root / "fleet.json").unlink(missing_ok=True)
    return rc


def _cmd_dlq(args: argparse.Namespace) -> int:
    """Inspect / replay the event bus dead-letter queue (data/dlq.jsonl —
    events whose HTTP delivery exhausted its retries or short-circuited on
    an open breaker; docs/robustness.md). ``list`` prints a summary,
    ``replay`` re-POSTs every event and rewrites the file with what still
    fails."""
    dlq = Path(args.dir) / "data" / "dlq.jsonl"
    if args.action == "replay":
        from kakveda_tpu.events.bus import replay_dlq_file

        out = replay_dlq_file(dlq, timeout=args.timeout)
        print(json.dumps(out, indent=2))
        return 0 if out["failed"] == 0 else 1
    # list: per-(topic, url) counts plus the newest error, no event bodies
    # (they can be large and may carry payload data).
    if not dlq.exists():
        print(json.dumps({"path": str(dlq), "events": 0, "entries": []}, indent=2))
        return 0
    groups: dict = {}
    total = 0
    for line in dlq.read_text(encoding="utf-8").splitlines():
        if not line.strip():
            continue
        total += 1
        try:
            rec = json.loads(line)
            key = (rec.get("topic"), rec.get("url"))
            g = groups.setdefault(key, {"count": 0, "last_error": None, "last_ts": 0})
            g["count"] += 1
            if rec.get("ts", 0) >= g["last_ts"]:
                g["last_ts"] = rec.get("ts", 0)
                g["last_error"] = rec.get("error")
        except ValueError:
            groups.setdefault(("<malformed>", None), {"count": 0})["count"] += 1
    print(json.dumps({
        "path": str(dlq),
        "events": total,
        "entries": [
            {"topic": t, "url": u, **g} for (t, u), g in sorted(groups.items(), key=str)
        ],
    }, indent=2))
    return 0


def _cmd_traffic(args: argparse.Namespace) -> int:
    """Record-replay traffic harness (kakveda_tpu/traffic/,
    docs/robustness.md § traffic harness):

    * ``record`` — pull GET /flightrecorder from a live server and convert
      its ``traffic`` ring into a replayable JSONL traffic log.
    * ``replay`` — drive a traffic log (or a named ``--scenario``)
      open-loop against a live server at ``--speed``; prints the replay
      accounting and the SLO report; rc 1 on SLO failure.
    * ``storm`` — hermetic in-process storm drill: private platform +
      admission controller, the composed hot-key-skew + failure-storm
      scenario WITH its chaos timeline (device-loss window, gossiped
      fleet pressure), SLO-gated. The same harness the `storm` bench row
      runs; this verb is the operator-sized version.

    Chaos ``faults`` actions arm `core/faults.py` IN THIS PROCESS — they
    reach a remote server only via its own ``KAKVEDA_FAULTS_TIMELINE``
    env; ``replay --url`` therefore replays traffic faithfully but leaves
    remote fault windows to the server's timeline.
    """
    import asyncio

    from kakveda_tpu import traffic as T

    if args.action == "record":
        import urllib.request

        with urllib.request.urlopen(args.url.rstrip("/") + "/flightrecorder",
                                    timeout=args.timeout) as r:
            payload = json.loads(r.read().decode("utf-8"))
        events = T.from_flightrecorder(payload, seed=args.seed)
        n = T.write_log(args.out, events,
                        meta={"source": args.url, "seed": args.seed})
        print(json.dumps({"out": str(args.out), "events": n}))
        return 0 if n else 1

    async def _replay_against_url(events, chaos=None, notes=None):
        import aiohttp

        base = args.url.rstrip("/")
        async with aiohttp.ClientSession() as sess:
            async def post(path, body):
                async with sess.post(base + path, json=body) as resp:
                    await resp.read()
                    return resp.status

            sc = T.Scenario(name="cli", seed=args.seed, duration_s=0.0,
                            events=events, chaos=chaos or [],
                            notes=notes or {})
            return await T.run_scenario(
                sc, post=post, speed=args.speed,
                max_concurrency=args.max_concurrency,
                timeout_s=args.timeout)

    if args.action == "replay":
        if args.scenario:
            sc = T.make_scenario(args.scenario, seed=args.seed,
                                 duration_s=args.duration)
            events, chaos, notes, slo = sc.events, sc.chaos, sc.notes, sc.slo
        else:
            if not args.log:
                print("replay needs --log or --scenario", file=sys.stderr)
                return 2
            meta, events = T.read_log(args.log)
            chaos, notes, slo = [], {}, T.SLO()
        res = asyncio.run(_replay_against_url(events, chaos, notes))
        import dataclasses

        rep = T.evaluate(dataclasses.replace(slo, recovery_s=None), res)
        print(json.dumps({"replay": res.to_dict(), "slo": rep.to_dict()},
                         indent=2))
        print(rep.summary(), file=sys.stderr)
        return 0 if rep.ok else 1

    # storm: hermetic in-process drill (TestServer — no port, no detach).
    import tempfile

    from aiohttp.test_utils import TestClient, TestServer

    from kakveda_tpu.core import admission as _adm
    from kakveda_tpu.platform import Platform
    from kakveda_tpu.service.app import make_app

    sc = T.make_scenario("storm", seed=args.seed, duration_s=args.duration,
                         gossip_ttl_s=args.gossip_ttl)
    brown = _adm.BrownoutController(enabled=True, enter=0.85, exit=0.5,
                                    dwell_s=0.25)
    # warn sized for DEGRADED throughput: during the device-loss window
    # the queue must absorb the warm-tier drain rate without shedding
    # (warn never sheds is a gate); background at 1 so the mine flood is
    # the sheddable excess.
    adm = _adm.AdmissionController(
        limits={"warn": 64, "ingest": 2, "interactive": 8, "background": 1},
        enabled=True, brownout=brown)
    tmp = Path(tempfile.mkdtemp(prefix="kakveda-traffic-storm-"))

    async def _storm():
        plat = Platform(data_dir=tmp / "data", capacity=1 << 10, dim=256)
        client = TestClient(TestServer(make_app(platform=plat, admission=adm)))
        await client.start_server()
        try:
            async def post(path, body):
                resp = await client.post(path, json=body)
                await resp.read()
                return resp.status

            return await T.run_scenario(
                sc, post=post, speed=args.speed,
                max_concurrency=args.max_concurrency,
                timeout_s=args.timeout, admission=adm)
        finally:
            await client.close()

    res = asyncio.run(_storm())
    rep = T.evaluate(sc.slo, res)
    print(json.dumps({"replay": res.to_dict(), "slo": rep.to_dict()},
                     indent=2))
    print(rep.summary(), file=sys.stderr)
    return 0 if rep.ok else 1


def _cmd_trace(args: argparse.Namespace) -> int:
    """Fetch one trace from a running server / router and render its tree.

    Against a router the id scatter-assembles across the fleet (GET
    /trace/{id} merges every replica's ring); against a single replica it
    is that process's ring only. Prints the ASCII tree plus per-source
    span counts when present."""
    import urllib.error
    import urllib.request

    url = args.url.rstrip("/") + "/trace/" + args.trace_id
    try:
        with urllib.request.urlopen(url, timeout=args.timeout) as r:
            body = json.loads(r.read().decode("utf-8"))
    except (urllib.error.URLError, OSError, ValueError) as e:
        print(f"trace fetch failed: {url}: {e}", file=sys.stderr)
        return 1
    spans = body.get("spans") or []
    if not spans:
        print(f"no spans for trace {args.trace_id} at {args.url}")
        return 1
    tree = body.get("tree")
    if not tree:
        from kakveda_tpu.core.trace import render_trace

        tree = render_trace(spans)
    print(tree)
    if body.get("sources"):
        print(json.dumps({"sources": body["sources"]}))
    return 0


def _cmd_logs(args: argparse.Namespace) -> int:
    """Tail server.log (written by `up --detach`), optionally following —
    the reference's `logs` verb over a file instead of docker-compose
    (reference: kakveda_cli/cli.py:167-181)."""
    import time

    root = Path(args.dir)
    logp = _log_path(root)
    if not logp.exists():
        print(f"no log file at {logp} (start with `kakveda-tpu up --detach`)", file=sys.stderr)
        return 1
    lines = logp.read_text(encoding="utf-8", errors="replace").splitlines()
    for ln in (lines[-args.tail :] if args.tail > 0 else []):
        print(ln)
    if not args.follow:
        return 0
    with logp.open("r", encoding="utf-8", errors="replace") as f:
        f.seek(0, os.SEEK_END)
        try:
            while True:
                ln = f.readline()
                if ln:
                    print(ln, end="")
                else:
                    time.sleep(0.5)
        except KeyboardInterrupt:
            pass
    return 0


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(prog="kakveda-tpu", description=__doc__.splitlines()[0])
    sub = p.add_subparsers(dest="cmd", required=True)

    sp = sub.add_parser("init", help="write default config + create data dir")
    sp.add_argument("--dir", default=".", help="project root")
    sp.add_argument("--force", action="store_true")
    sp.add_argument("--wizard", action="store_true", help="interactive .env setup")
    sp.add_argument("--yes", action="store_true", help="write .env with all defaults, no questions")
    sp.set_defaults(fn=_cmd_init)

    sp = sub.add_parser("up", help="start the platform server")
    sp.add_argument("--dir", default=".")
    sp.add_argument("--host", default="127.0.0.1")
    sp.add_argument("--port", type=int, default=8100)
    sp.add_argument("--dashboard-port", type=int, default=8110, help="0 disables the dashboard")
    sp.add_argument("-d", "--detach", action="store_true", help="run in the background (server.pid/server.log)")
    sp.add_argument("--replicas", type=int, default=0,
                    help="spawn N service replicas behind a front router on --port (docs/scale-out.md)")
    sp.add_argument("--port-base", type=int, default=0,
                    help="first replica port (default --port + 1)")
    sp.add_argument("--autoscale", default=None, metavar="MIN:MAX",
                    help="elastic fleet bounds: the router's autoscaler "
                         "scales replicas between MIN and MAX "
                         "(--replicas is the starting count; "
                         "docs/scale-out.md § elastic fleet)")
    # Internal: set by the fleet supervisor on the children it spawns.
    sp.add_argument("--replica-index", type=int, default=None, help=argparse.SUPPRESS)
    sp.set_defaults(fn=_cmd_up)

    sp = sub.add_parser("down", help="stop the server recorded in server.pid")
    sp.add_argument("--dir", default=".")
    sp.add_argument("--timeout", type=float, default=30.0)
    sp.set_defaults(fn=_cmd_down)

    sp = sub.add_parser("status", help="show data-store row counts")
    sp.add_argument("--dir", default=".")
    sp.add_argument("--url", default="http://127.0.0.1:8100",
                    help="live server base URL for the tenant-plane probe")
    sp.set_defaults(fn=_cmd_status)

    sp = sub.add_parser("reset", help="delete local data stores")
    sp.add_argument("--dir", default=".")
    sp.add_argument("--yes", action="store_true")
    sp.set_defaults(fn=_cmd_reset)

    sp = sub.add_parser("logs", help="tail server.log")
    sp.add_argument("--dir", default=".")
    sp.add_argument("-n", "--tail", type=int, default=50)
    sp.add_argument("-f", "--follow", action="store_true")
    sp.set_defaults(fn=_cmd_logs)

    sp = sub.add_parser("dlq", help="inspect / replay the bus dead-letter queue")
    sp.add_argument("action", nargs="?", choices=("list", "replay"), default="list")
    sp.add_argument("--dir", default=".")
    sp.add_argument("--timeout", type=float, default=5.0, help="per-POST replay timeout")
    sp.set_defaults(fn=_cmd_dlq)

    sp = sub.add_parser(
        "traffic",
        help="record / replay traffic logs, run SLO-gated storm drills",
    )
    sp.add_argument("action", choices=("record", "replay", "storm"))
    sp.add_argument("--url", default="http://127.0.0.1:8000",
                    help="server base URL (record/replay)")
    sp.add_argument("--out", default="traffic.jsonl",
                    help="record: output traffic log path")
    sp.add_argument("--log", default=None,
                    help="replay: traffic log to drive")
    sp.add_argument("--scenario", default=None,
                    help="replay: named scenario instead of a log "
                         "(diurnal|hot_key|failure_storm|near_dup|mixed|"
                         "storm|aging)")
    sp.add_argument("--seed", type=int, default=0)
    sp.add_argument("--duration", type=float, default=12.0,
                    help="scenario duration in seconds")
    sp.add_argument("--speed", type=float, default=1.0,
                    help="replay speed factor (2 = twice real time)")
    sp.add_argument("--max-concurrency", type=int, default=None,
                    help="bounded client concurrency "
                         "(default KAKVEDA_TRAFFIC_MAX_CONC)")
    sp.add_argument("--timeout", type=float, default=15.0,
                    help="per-request timeout seconds (hung past this)")
    sp.add_argument("--gossip-ttl", type=float, default=5.0,
                    help="storm: gossip TTL / ladder recovery bound")
    sp.set_defaults(fn=_cmd_traffic)

    sp = sub.add_parser(
        "trace", help="fetch + render one causal trace (router assembles fleet-wide)"
    )
    sp.add_argument("trace_id", help="32-hex trace id (x-request-id of the request)")
    sp.add_argument("--url", default="http://localhost:8000")
    sp.add_argument("--timeout", type=float, default=5.0)
    sp.set_defaults(fn=_cmd_trace)

    sp = sub.add_parser(
        "compact",
        help="offline GFKB lifecycle maintenance: optional aging/collapse, "
             "then checkpoint+delta log compaction (server must be down)",
    )
    sp.add_argument("--dir", default=".")
    sp.add_argument("--capacity", type=int, default=1 << 14,
                    help="GFKB device capacity (match the server's)")
    sp.add_argument("--dim", type=int, default=0,
                    help="embedding dim (0 = from config)")
    sp.add_argument("--age-ttl", type=float, default=0.0,
                    help="tombstone rows idle longer than this many seconds "
                         "before compacting (0 = skip aging)")
    sp.add_argument("--collapse", type=int, default=0,
                    help="collapse mining clusters with ≥ N near-duplicate "
                         "members to one exemplar (0 = skip)")
    sp.add_argument("--force", action="store_true",
                    help="compact even though server.pid looks alive")
    sp.set_defaults(fn=_cmd_compact)

    sp = sub.add_parser("doctor", help="check the runtime environment")
    sp.add_argument("--dir", default=".", help="project root (for .env)")
    sp.add_argument("--url", default="http://127.0.0.1:8100",
                    help="live server base URL for the tenant-plane probe")
    sp.set_defaults(fn=_cmd_doctor)

    sp = sub.add_parser("version", help="print version")
    sp.set_defaults(fn=_cmd_version)

    return p


def main(argv: list[str] | None = None) -> int:
    args = build_parser().parse_args(argv)
    # Apply the wizard-written .env (real environment wins) so the config
    # consumers see what docker compose would; `init` must not load it —
    # it may be about to (re)write the file.
    if args.cmd in ("up", "doctor", "status"):
        from kakveda_tpu.cli.wizard import load_dotenv

        load_dotenv(Path(getattr(args, "dir", ".")) / ".env")
    return args.fn(args)


if __name__ == "__main__":
    sys.exit(main())
