"""Shared host-side kernel: schemas, fingerprinting, config, runtime.

TPU-native counterpart of the reference's ``services/shared/`` package
(reference: services/shared/models.py, fingerprint.py, config.py,
runtime.py).
"""

from kakveda_tpu.core.schemas import (  # noqa: F401
    CanonicalFailureRecord,
    FailureMatch,
    FailureMatchRequest,
    FailureMatchResponse,
    FailureSignal,
    HealthPoint,
    IngestRequest,
    PatternEntity,
    Severity,
    TracePayload,
    WarningRequest,
    WarningResponse,
)
from kakveda_tpu.core.fingerprint import (  # noqa: F401
    CitationCheck,
    detect_citation_markers,
    fingerprint,
    normalize_prompt,
    prompt_intent_tags,
    signature_text,
)
from kakveda_tpu.core.config import ConfigStore  # noqa: F401
from kakveda_tpu.core.runtime import (  # noqa: F401
    RuntimeConfig,
    ensure_request_id,
    get_runtime_config,
    setup_logging,
)
