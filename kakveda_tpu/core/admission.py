"""Overload protection + graceful degradation — the layer that keeps the
platform ANSWERING when traffic or the chip stops cooperating.

Three cooperating controllers, one module (they share pressure signals and
the same observability discipline as the spec gate):

* :class:`AdmissionController` — bounded per-class admission ahead of every
  real queue (warn micro-batcher, ingest pipeline, serving-engine pool).
  Classes are priority-ordered (``warn`` pre-flight > ``ingest`` >
  ``interactive`` generation > ``background`` batch/mine); each has its own
  in-flight bound so a flood of one class can never starve a higher one.
  Over the bound a request is SHED immediately with a typed
  :class:`OverloadError` whose ``retry_after`` derives from the observed
  per-class drain rate — the HTTP tier surfaces it as 429 + ``Retry-After``
  (Dean & Barroso's tail-at-scale prescription: reject early and cheaply,
  never queue into a timeout). Deadline-aware shedding rejects a request
  whose deadline cannot be met given the live queue-wait history instead of
  letting it burn a slot and expire anyway.
* :class:`BrownoutController` — under sustained pressure, step DOWN
  capability instead of falling over: disable speculation → clamp decode
  token budgets → shed the background class → shed interactive generation.
  Thresholds carry hysteresis (enter high, exit low, minimum dwell) so the
  ladder doesn't flap; every transition goes through ONE
  :meth:`_set_brownout_state` helper that moves the state gauge vector, the
  transition counter and the flight recorder together (the spec gate's
  single-definition discipline).
* :class:`DeviceHealth` — the device-loss latch. A ``device.unavailable``
  fault site (chaos harness) or a REAL backend error observed on a device
  path latches DEGRADED: the warn path serves from the GFKB's host
  warm/cold tiers (``GFKB.match_batch_fallback``, the same storage
  hierarchy that absorbs overflow — index/tiers.py), generation fails
  fast with a typed
  retryable :class:`DeviceUnavailableError` + retry hint, and a background
  probe thread re-tests the backend (a tiny compiled op) until it answers,
  then un-latches. The probe never kills or restarts anything — a wedged
  remote TPU lease must be waited out, not shot (CLAUDE.md).

Everything is process-global by default (:func:`get_admission`,
:func:`get_device_health`) — the HTTP tier, the serving engine and the
warn pipeline must see ONE pressure picture. Tests build private instances
and/or call :func:`reset_for_tests`.

Per-tenant fairness (docs/robustness.md § multi-tenancy): admission is
also TENANT-aware — call sites that know the requesting app key pass it
as ``tenant=`` and the controller enforces a per-tenant share quota
INSIDE each class (``KAKVEDA_TENANT_MAX_SHARE`` of the class bound,
work-conserving: a lone tenant may still use the whole class). Tenant
state lives in ONE bounded LRU table (``KAKVEDA_TENANT_TABLE`` rows,
overflow folds into an ``other`` bucket that never quota-sheds — fail
open, never wrong-but-confident) and every mutation of it flows through
the single-writer :meth:`AdmissionController._set_tenant_state` helper
(table + size gauge + per-tenant shed counter + flight recorder move
together; machine-enforced by scripts/lint_invariants.py). A quota shed
raises the same typed :class:`OverloadError` with ``reason=
"tenant_quota"`` and tenant provenance; its Retry-After derives from
THAT tenant's own drain rate when one has been observed. The
``admission.tenant_quota`` fault site fails OPEN: an armed fault skips
quota bookkeeping and admits on class capacity alone (a bookkeeping
failure must degrade to coarser fairness, never become a shed storm).
``KAKVEDA_TENANT_FAIR=0`` disables the whole tenant plane bit-for-bit.

Knobs (docs/robustness.md): ``KAKVEDA_ADMIT`` (0 disables shedding),
``KAKVEDA_ADMIT_WARN/_INGEST/_INTERACTIVE/_BACKGROUND`` per-class bounds,
``KAKVEDA_BROWNOUT`` (0 disables the ladder), ``KAKVEDA_BROWNOUT_ENTER`` /
``KAKVEDA_BROWNOUT_EXIT`` / ``KAKVEDA_BROWNOUT_DWELL`` /
``KAKVEDA_BROWNOUT_TOKEN_CAP``, ``KAKVEDA_DEGRADED_PROBE``,
``KAKVEDA_TENANT_FAIR`` / ``KAKVEDA_TENANT_TABLE`` /
``KAKVEDA_TENANT_MAX_SHARE`` / ``KAKVEDA_TENANT_TOPK``.
"""

from __future__ import annotations

import logging
import os
import random
import threading
import time
from collections import OrderedDict, deque
from typing import Dict, Optional, Tuple

from kakveda_tpu.core import faults as _faults
from kakveda_tpu.core import metrics as _metrics
from kakveda_tpu.core import sanitize

log = logging.getLogger("kakveda.admission")

__all__ = [
    "OverloadError",
    "DeviceUnavailableError",
    "AdmissionController",
    "BrownoutController",
    "DeviceHealth",
    "get_admission",
    "get_device_health",
    "reset_for_tests",
    "tenant_fair_enabled",
    "note_tenant_promotion",
    "tenant_promotions",
    "CLASSES",
]

# Priority order, highest first. The warn pre-flight check is the product's
# whole point and must survive everything below it; background batch work
# (full mines, snapshots) is the first thing a brownout sheds.
CLASSES: Tuple[str, ...] = ("warn", "ingest", "interactive", "background")

# Brownout ladder, mild → severe. Each step KEEPS every restriction of the
# steps before it.
BROWNOUT_STATES: Tuple[str, ...] = (
    "normal",            # full capability
    "no_spec",           # speculation off (verify-width FLOPs back to decode)
    "clamped",           # + decode token budgets clamped (shorter answers)
    "shed_background",   # + background class rejected outright
    "shed_interactive",  # + interactive generation rejected (warn/ingest live)
)


class OverloadError(Exception):
    """A request was shed by admission control or the brownout ladder.

    Deliberately NOT a RuntimeError: the serving paths treat RuntimeError
    as 'engine closed, fall back to a solo decode' — a shed request must
    NOT silently take the fallback path (that would defeat the shed), it
    must surface to the caller as 429 + Retry-After.
    """

    def __init__(self, message: str, retry_after: float = 1.0,
                 klass: str = "", reason: str = "", tenant: str = ""):
        super().__init__(message)
        self.retry_after = max(0.1, float(retry_after))
        self.klass = klass
        self.reason = reason
        # Tenant provenance: the app key whose traffic was shed (empty for
        # tenant-blind call sites). The HTTP tier and the traffic harness's
        # per-tenant accounting both read it.
        self.tenant = tenant


class DeviceUnavailableError(Exception):
    """The accelerator backend is latched DEGRADED (device loss / wedged
    lease). Retryable — the probe will un-latch when the chip answers
    again; ``retry_after`` hints when to come back. NOT a RuntimeError for
    the same reason as :class:`OverloadError`: the solo-decode fallback
    would hit the same dead device and hang."""

    def __init__(self, message: str, retry_after: float = 5.0):
        super().__init__(message)
        self.retry_after = max(0.1, float(retry_after))


def _env_int(name: str, default: int) -> int:
    try:
        return int(os.environ.get(name, str(default)))
    except ValueError:
        return default


def _env_float(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, str(default)))
    except ValueError:
        return default


def tenant_fair_enabled() -> bool:
    """The ONE switch for the whole tenant plane (admission quotas, warn
    micro-batcher DRR, serving-slot weighted-fair). ``KAKVEDA_TENANT_FAIR=0``
    must keep every scheduler bit-for-bit FIFO — each consumer resolves
    this at construction, the same discipline as every other knob."""
    return os.environ.get("KAKVEDA_TENANT_FAIR", "1") != "0"


# Starvation-promotion accounting, shared across planes (the serving
# engine's max-wait promotion lives in models/serving.py but the tenant
# plane's observability surface — info()/readyz/cli status — is here).
_PROMOTIONS_LOCK = threading.Lock()
_PROMOTIONS: Dict[str, int] = {}
_PROMOTIONS_CHILDREN: Dict[str, object] = {}


def note_tenant_promotion(plane: str) -> None:
    """Count one starvation promotion (a waiting item force-admitted after
    sitting out the max fair-scheduling rounds). ``plane`` is a bounded
    enum ("serving", …), never a tenant id — cardinality stays O(planes)."""
    with _PROMOTIONS_LOCK:
        _PROMOTIONS[plane] = _PROMOTIONS.get(plane, 0) + 1
        child = _PROMOTIONS_CHILDREN.get(plane)
        if child is None:
            child = _metrics.get_registry().counter(
                "kakveda_tenant_promotions_total",
                "Starvation promotions by fair schedulers (a waiting item "
                "admitted out of deficit order after max fair rounds)",
                ("plane",),
            ).labels(plane=plane)
            _PROMOTIONS_CHILDREN[plane] = child
    child.inc()


def tenant_promotions() -> Dict[str, int]:
    with _PROMOTIONS_LOCK:
        return dict(_PROMOTIONS)


class BrownoutController:
    """The capability ladder. Pressure in, capability restrictions out.

    Pressure is the max over classes of in-flight/limit (fed by the
    admission controller on every admit/release) combined with the recent
    interactive queue-wait. Hysteresis: a step is entered when pressure
    ≥ ``enter`` and left only when pressure ≤ ``exit`` AND the state has
    dwelled ``dwell_s`` — so one burst can't flap the ladder per request.
    The ladder moves ONE step per evaluation in either direction; severe
    states are reached by sustained pressure, not a single spike.
    """

    def __init__(
        self,
        *,
        enabled: Optional[bool] = None,
        enter: Optional[float] = None,
        exit: Optional[float] = None,
        dwell_s: Optional[float] = None,
        token_cap: Optional[int] = None,
        recorder: Optional[_metrics.FlightRecorder] = None,
    ):
        self.enabled = (
            os.environ.get("KAKVEDA_BROWNOUT", "1") != "0"
            if enabled is None else enabled
        )
        self.enter = _env_float("KAKVEDA_BROWNOUT_ENTER", 0.85) if enter is None else enter
        self.exit = _env_float("KAKVEDA_BROWNOUT_EXIT", 0.5) if exit is None else exit
        self.dwell_s = _env_float("KAKVEDA_BROWNOUT_DWELL", 5.0) if dwell_s is None else dwell_s
        self._token_cap = (
            _env_int("KAKVEDA_BROWNOUT_TOKEN_CAP", 32)
            if token_cap is None else token_cap
        )
        self.recorder = recorder
        self._lock = sanitize.named_lock("BrownoutController._lock", kind="rlock")
        self._step = 0
        self._entered_at = time.monotonic()
        # Time-in-state accounting (bench occupancy + postmortems).
        self._occupancy: Dict[str, float] = {s: 0.0 for s in BROWNOUT_STATES}
        reg = _metrics.get_registry()
        self._gauge = reg.gauge(
            "kakveda_brownout_state",
            "1 on the brownout ladder's current step "
            "(normal|no_spec|clamped|shed_background|shed_interactive)",
            ("state",),
        )
        self._transitions = reg.counter(
            "kakveda_brownout_transitions_total",
            "Brownout ladder step transitions", ("from", "to"),
        )
        for s in BROWNOUT_STATES:
            self._gauge.labels(state=s).set(1.0 if s == "normal" else 0.0)

    # -- reads -----------------------------------------------------------

    @property
    def step(self) -> int:
        return self._step

    @property
    def state(self) -> str:
        return BROWNOUT_STATES[self._step]

    def spec_allowed(self) -> bool:
        """Speculative decoding permitted? False from step 1 up."""
        return self._step < 1

    def token_cap(self) -> Optional[int]:
        """max_new_tokens clamp, or None when unclamped (below step 2)."""
        return self._token_cap if self._step >= 2 else None

    def class_shed(self, klass: str) -> bool:
        """Is this admission class currently shed outright by the ladder?"""
        if self._step >= 4 and klass == "interactive":
            return True
        if self._step >= 3 and klass == "background":
            return True
        return False

    def occupancy(self) -> Dict[str, float]:
        """Seconds spent in each ladder state (current state up to now)."""
        with self._lock:
            occ = dict(self._occupancy)
            occ[self.state] += time.monotonic() - self._entered_at
            return occ

    # -- transitions -----------------------------------------------------

    def _set_brownout_state(self, new_step: int, pressure: float) -> None:
        """ONE definition of a ladder transition: step, the state gauge
        vector, the transition counter, occupancy accounting and the
        flight recorder move together. Caller holds ``_lock``."""
        old_step = self._step
        if new_step == old_step:
            return
        now = time.monotonic()
        old, new = BROWNOUT_STATES[old_step], BROWNOUT_STATES[new_step]
        self._occupancy[old] += now - self._entered_at
        self._entered_at = now
        self._step = new_step
        self._gauge.labels(state=old).set(0.0)
        self._gauge.labels(state=new).set(1.0)
        self._transitions.labels(**{"from": old, "to": new}).inc()
        if self.recorder is not None:
            self.recorder.record(
                "brownout", **{"from": old, "to": new,
                               "pressure": round(pressure, 3)}
            )
        log.warning(
            "brownout %s -> %s (pressure %.2f)", old, new, pressure
        )

    def note_pressure(self, pressure: float) -> None:
        """Feed one pressure sample (max class load fraction) and move the
        ladder at most one step. Cheap — a lock and two compares."""
        if not self.enabled:
            return
        with self._lock:
            if pressure >= self.enter and self._step < len(BROWNOUT_STATES) - 1:
                # Escalate one step only after dwelling at the current one
                # (the first step is immediate — shedding FLOPs is cheap
                # and reversible; later steps need sustained pressure).
                if self._step == 0 or (
                    time.monotonic() - self._entered_at >= self.dwell_s
                ):
                    self._set_brownout_state(self._step + 1, pressure)
            elif pressure <= self.exit and self._step > 0:
                if time.monotonic() - self._entered_at >= self.dwell_s:
                    self._set_brownout_state(self._step - 1, pressure)

    def reset(self) -> None:
        with self._lock:
            self._set_brownout_state(0, 0.0)
            # Deliberate direct writes AFTER the single-writer helper ran:
            # reset() re-zeroes the occupancy HISTORY (tests, bench phase
            # boundaries) — not a ladder transition, which the helper above
            # already performed with full gauge/counter/recorder movement.
            self._occupancy = {s: 0.0 for s in BROWNOUT_STATES}  # kakveda: allow[single-writer]
            self._entered_at = time.monotonic()  # kakveda: allow[single-writer]


class AdmissionController:
    """Bounded per-class admission with typed shedding.

    ``admit(klass)`` either returns (the caller runs, then calls
    ``release``) or raises :class:`OverloadError` immediately — a shed
    costs microseconds, never a slot. The bound covers in-flight work
    INCLUDING whatever downstream queue the class drains through (warn
    micro-batcher, engine pool): the controller doesn't queue anything
    itself, it keeps the real queues from growing past what they can
    drain before callers time out.
    """

    _WAIT_WINDOW = 64  # recent queue-wait samples per class

    def __init__(
        self,
        limits: Optional[Dict[str, int]] = None,
        *,
        enabled: Optional[bool] = None,
        brownout: Optional[BrownoutController] = None,
        recorder: Optional[_metrics.FlightRecorder] = None,
    ):
        self.enabled = (
            os.environ.get("KAKVEDA_ADMIT", "1") != "0"
            if enabled is None else enabled
        )
        self.limits: Dict[str, int] = {
            "warn": _env_int("KAKVEDA_ADMIT_WARN", 256),
            "ingest": _env_int("KAKVEDA_ADMIT_INGEST", 64),
            "interactive": _env_int("KAKVEDA_ADMIT_INTERACTIVE", 32),
            "background": _env_int("KAKVEDA_ADMIT_BACKGROUND", 4),
        }
        if limits:
            self.limits.update(limits)
        self.recorder = recorder or _metrics.FlightRecorder("admission")
        self.brownout = brownout if brownout is not None else BrownoutController(
            recorder=self.recorder
        )
        self._lock = sanitize.named_lock("AdmissionController._lock")
        self._inflight: Dict[str, int] = {k: 0 for k in CLASSES}
        # Fleet pressure floor (gossip input, fleet/gossip.py): the max
        # live PEER occupancy with an expiry — while fresh, pressure() is
        # max(local, fleet) so the brownout ladder degrades fleet-wide.
        # This is an INPUT feed only: transitions still happen solely in
        # BrownoutController._set_brownout_state.
        self._fleet_pressure: Tuple[float, float] = (0.0, 0.0)  # (value, expires)
        # Per-class drain-rate estimate: (completions, window start) over a
        # sliding ~5 s window, plus recent observed queue waits — the two
        # inputs Retry-After and deadline shedding derive from.
        self._done_count: Dict[str, int] = {k: 0 for k in CLASSES}
        self._done_t0: Dict[str, float] = {k: time.monotonic() for k in CLASSES}
        self._drain_rate: Dict[str, float] = {k: 0.0 for k in CLASSES}
        # Bounded multiplicative Retry-After jitter fraction (see
        # retry_after): 0 disables, clamped to [0, 1).
        self._ra_jitter = min(0.99, max(
            0.0, _env_float("KAKVEDA_ADMIT_RA_JITTER", 0.25)
        ))
        self._waits: Dict[str, deque] = {k: deque(maxlen=self._WAIT_WINDOW) for k in CLASSES}
        # Peak-hold window for the EXPORTED local pressure (gossip/probe):
        # a flood of short-lived requests through a small class bound
        # (one 100 ms mine at a time through background=1) is real
        # sustained load, but point-in-time in-flight samples flicker
        # 1.0/0.0 and an autoscaler's dwell clock resets on every dip.
        # (ts, local) peaks recorded at admit time; local_pressure() is
        # the max over the window. 0 = instantaneous export.
        self._occ_window_s = max(
            0.0, _env_float("KAKVEDA_ADMIT_OCC_WINDOW_S", 3.0))
        self._occ_peaks: deque = deque(maxlen=1024)
        # --- tenant plane (docs/robustness.md § multi-tenancy) ----------
        # One bounded LRU table of per-tenant records; EVERY mutation goes
        # through _set_tenant_state (single-writer, lint-enforced). A
        # record: per-class in-flight, admit/shed counts, and the same
        # drain-rate window the class keeps — the input to per-tenant
        # Retry-After. Overflow past the bound evicts the stalest idle
        # tenant, else folds into the aggregate "other" bucket, which
        # NEVER quota-sheds (no per-tenant resolution → fail open).
        self._tenant_fair = tenant_fair_enabled()
        self._tenant_table_max = max(2, _env_int("KAKVEDA_TENANT_TABLE", 512))
        self._tenant_share = min(1.0, max(
            0.01, _env_float("KAKVEDA_TENANT_MAX_SHARE", 0.5)))
        self._tenant_topk = max(1, _env_int("KAKVEDA_TENANT_TOPK", 16))
        self._tenants: "OrderedDict[str, dict]" = OrderedDict()
        # Fail-OPEN chaos site: armed → quota bookkeeping is skipped and
        # the request admits on class capacity alone (degraded fairness,
        # never a shed storm). Resolved once, like every site.
        self._fault_tenant = _faults.site("admission.tenant_quota")
        reg = _metrics.get_registry()
        self._g_tenant_table = reg.gauge(
            "kakveda_tenant_table_size",
            "Live per-tenant state-table rows per plane (bounded by "
            "KAKVEDA_TENANT_TABLE / KAKVEDA_RATELIMIT_MAX_KEYS)",
            ("plane",),
        ).labels(plane="admission")
        c_tenant_shed = reg.counter(
            "kakveda_admission_tenant_shed_total",
            "Requests shed per tenant label (top-K first-seen shed tenants; "
            "the rest aggregate under tenant=\"other\" — "
            "docs/observability.md cardinality policy)",
            ("tenant",),
        )
        self._c_tenant_shed = c_tenant_shed
        self._tenant_shed_children: Dict[str, object] = {}
        self._c_tenant_degraded = reg.counter(
            "kakveda_admission_tenant_quota_degraded_total",
            "Admissions where tenant-quota bookkeeping failed open "
            "(admission.tenant_quota fault site)",
        )
        g_inflight = reg.gauge(
            "kakveda_admission_inflight",
            "In-flight (admitted, not yet released) requests per admission "
            "class", ("klass",),
        )
        c_admitted = reg.counter(
            "kakveda_admission_admitted_total",
            "Requests admitted per admission class", ("klass",),
        )
        self._c_shed = reg.counter(
            "kakveda_admission_shed_total",
            "Requests shed by admission control, by class and reason "
            "(queue_full|brownout|deadline|degraded|ratelimit)",
            ("klass", "reason"),
        )
        h_wait = reg.histogram(
            "kakveda_admission_wait_seconds",
            "Observed downstream queue wait per admission class (feeds "
            "deadline-aware shedding)", ("klass",),
        )
        self._m_inflight = {k: g_inflight.labels(klass=k) for k in CLASSES}
        self._m_admitted = {k: c_admitted.labels(klass=k) for k in CLASSES}
        self._m_wait = {k: h_wait.labels(klass=k) for k in CLASSES}
        # Per-INSTANCE shed accounting (the metric family above is
        # process-global and shared by every controller): what
        # shed_counts() reports, so a private bench/test controller sees
        # only its own rejections.
        self._sheds: Dict[str, float] = {}

    # -- pressure --------------------------------------------------------

    def _local_locked(self) -> float:
        return max(
            self._inflight[k] / self.limits[k] if self.limits[k] > 0 else 0.0
            for k in CLASSES
        )

    def _pressure_locked(self) -> float:
        local = self._local_locked()
        fp, expires = self._fleet_pressure
        if fp > local and time.monotonic() < expires:
            return fp
        return local

    def pressure(self) -> float:
        with self._lock:
            return self._pressure_locked()

    def _note_peak_locked(self, now: float) -> None:
        if self._occ_window_s > 0.0:
            self._occ_peaks.append((now, self._local_locked()))

    def local_pressure(self) -> float:
        """Peak-held max class load from THIS replica's own in-flight
        work — the gossip/probe EXPORT. Two deliberate properties:

        * excludes the TTL'd fleet floor: publishing the combined
          ``pressure()`` echoes a peer's number back as this replica's
          own state, and two idle replicas then refresh each other's
          floor forever — a latched pressure rumor no real load backs,
          which pins the brownout ladder AND the autoscaler's scale-down
          signal. The floor stays an INPUT (``pressure()``), never an
          output.
        * holds admit-time peaks for ``KAKVEDA_ADMIT_OCC_WINDOW_S`` (3 s;
          0 = instantaneous): a flood of short requests through a small
          class bound is real sustained load, but point samples flicker
          1.0/0.0 between them and a dwell clock resets on every dip."""
        with self._lock:
            cur = self._local_locked()
            if self._occ_window_s <= 0.0:
                return cur
            horizon = time.monotonic() - self._occ_window_s
            while self._occ_peaks and self._occ_peaks[0][0] < horizon:
                self._occ_peaks.popleft()
            return max([cur] + [v for _, v in self._occ_peaks])

    def note_fleet_pressure(self, pressure: float, ttl_s: float = 5.0) -> None:
        """Gossip input (fleet/gossip.py): fold the fleet's worst live
        occupancy in as a pressure floor with an expiry — a silent peer
        stops contributing after ``ttl_s``, so a dead replica can't pin
        the whole fleet browned-out. Also re-evaluates the ladder, which
        is how an IDLE replica follows the fleet down (and back up)."""
        p = max(0.0, min(float(pressure), 2.0))
        with self._lock:
            self._fleet_pressure = (p, time.monotonic() + max(0.1, ttl_s))
            combined = self._pressure_locked()
        self.brownout.note_pressure(combined)

    def fleet_pressure(self) -> float:
        """The live (unexpired) fleet pressure floor, 0.0 when none."""
        with self._lock:
            fp, expires = self._fleet_pressure
            return fp if time.monotonic() < expires else 0.0

    # -- drain rate / retry-after ---------------------------------------

    def _note_done_locked(self, klass: str) -> None:
        now = time.monotonic()
        self._done_count[klass] += 1
        dt = now - self._done_t0[klass]
        if dt >= 5.0:
            # Fold the window into the EWMA-ish estimate and restart it.
            rate = self._done_count[klass] / dt
            prev = self._drain_rate[klass]
            self._drain_rate[klass] = rate if prev == 0.0 else 0.5 * prev + 0.5 * rate
            self._done_count[klass] = 0
            self._done_t0[klass] = now

    def retry_after(self, klass: str, tenant: str = "") -> float:
        """Seconds until the class's backlog plausibly drains: in-flight /
        observed drain rate, clamped to [0.5, 30], then spread by a bounded
        multiplicative jitter (±``KAKVEDA_ADMIT_RA_JITTER``, default 0.25).

        With a ``tenant`` whose drain rate has been observed, the estimate
        is THAT tenant's own backlog over its own rate instead — a
        quota-shed flooder is told when ITS slots free up, not when the
        class (which other tenants keep busy) does.

        The jitter is load-bearing, not cosmetic: without it every client
        shed in the same saturation window gets the SAME drain-derived
        hint, and the ones that honor it re-arrive in lockstep — a
        metastable retry storm that re-saturates the gate exactly one
        Retry-After later. Spreading the hint de-phases the retry wave.
        With no rate measured yet the base is a 1 s default — honest
        enough for a fresh process, and jittered for the same reason."""
        with self._lock:
            rate = self._drain_rate[klass]
            if rate <= 0.0:
                # Live window estimate before the first fold.
                dt = time.monotonic() - self._done_t0[klass]
                if self._done_count[klass] and dt > 0.05:
                    rate = self._done_count[klass] / dt
            backlog = self._inflight[klass]
            if self._tenant_fair and tenant:
                rec = self._tenants.get(tenant)
                if rec is not None:
                    trate = rec["rate"]
                    if trate <= 0.0:
                        dt = time.monotonic() - rec["t0"]
                        if rec["done"] and dt > 0.05:
                            trate = rec["done"] / dt
                    if trate > 0.0:
                        rate = trate
                        backlog = rec["inflight"].get(klass, 0)
        if rate <= 0.0:
            base = 1.0
        else:
            base = min(30.0, max(0.5, backlog / rate))
        if self._ra_jitter <= 0.0:
            return base
        # Uniform in [1-j, 1+j]: bounded (a client never waits more than
        # (1+j)x the honest estimate) and multiplicative (the spread scales
        # with the backlog it is de-phasing). Floor at the OverloadError
        # minimum so the typed 429 shape is unchanged.
        return max(0.1, base * (1.0 + self._ra_jitter * (2.0 * random.random() - 1.0)))

    def note_wait(self, klass: str, wait_s: float) -> None:
        """Feed one observed downstream queue wait (engine admission,
        micro-batcher drain) — the live histogram deadline shedding reads.
        Also re-evaluates the brownout ladder: warn traffic flows through
        the micro-batcher's own bounded queue, never try_admit/release, so
        without this a warn-only recovery tail produced ZERO pressure
        samples and the ladder froze at its storm step (caught by the
        traffic harness's ladder-recovery SLO gate)."""
        self._m_wait[klass].observe(wait_s)
        with self._lock:
            self._waits[klass].append(wait_s)
            pressure = self._pressure_locked()
        self.brownout.note_pressure(pressure)

    def predicted_wait(self, klass: str) -> float:
        """Pessimistic queue-wait estimate for a NEW request of ``klass``:
        ~p95 of recent observed waits, scaled by how full the class is.
        Zero until waits have been observed (never shed on no data)."""
        with self._lock:
            waits = sorted(self._waits[klass])
            if not waits:
                return 0.0
            p95 = waits[min(len(waits) - 1, int(0.95 * len(waits)))]
            load = self._inflight[klass] / max(1, self.limits[klass])
        return p95 * (1.0 + load)

    # -- tenant plane ----------------------------------------------------

    def _tenant_cap(self, klass: str) -> int:
        return max(1, int(self.limits[klass] * self._tenant_share))

    def _set_tenant_state(
        self,
        tenant: Optional[str],
        klass: Optional[str] = None,
        *,
        inflight_delta: int = 0,
        shed: bool = False,
        done: bool = False,
        retry_after: float = 0.0,
        clear: bool = False,
    ) -> Optional[dict]:
        """ONE definition of a tenant-table mutation: the bounded LRU table
        (touch / create / evict / overflow-fold), per-class in-flight and
        admit/shed/drain accounting, the table-size gauge, the capped
        per-tenant shed counter and the flight recorder all move together.
        Caller holds ``_lock``. Returns the (possibly "other") record."""
        if clear:
            self._tenants.clear()
            self._g_tenant_table.set(0.0)
            return None
        assert tenant
        now = time.monotonic()
        rec = self._tenants.get(tenant)
        if rec is None:
            if len(self._tenants) >= self._tenant_table_max:
                # Evict the stalest tenant with nothing in flight; if every
                # row is live (pathological), fold THIS tenant into the
                # aggregate bucket instead of growing.
                victim = None
                for k, r in self._tenants.items():  # LRU order, oldest first
                    if k != "other" and not any(r["inflight"].values()):
                        victim = k
                        break
                if victim is not None:
                    del self._tenants[victim]
                else:
                    tenant = "other"
                    rec = self._tenants.get("other")
            if rec is None:
                rec = {
                    "key": tenant,
                    "inflight": {},
                    "admits": 0,
                    "sheds": 0,
                    "done": 0,
                    "t0": now,
                    "rate": 0.0,
                }
                self._tenants[tenant] = rec
        self._tenants.move_to_end(tenant)
        self._g_tenant_table.set(float(len(self._tenants)))
        if inflight_delta:
            held = rec["inflight"].get(klass, 0) + inflight_delta
            rec["inflight"][klass] = max(0, held)
            if inflight_delta > 0:
                rec["admits"] += 1
        if done:
            # Same fold-at-5s drain-rate window the class keeps — the
            # per-tenant Retry-After input.
            rec["done"] += 1
            dt = now - rec["t0"]
            if dt >= 5.0:
                rate = rec["done"] / dt
                prev = rec["rate"]
                rec["rate"] = rate if prev == 0.0 else 0.5 * prev + 0.5 * rate
                rec["done"] = 0
                rec["t0"] = now
        if shed:
            rec["sheds"] += 1
            label = tenant if (
                tenant in self._tenant_shed_children
                or len(self._tenant_shed_children) < self._tenant_topk
            ) else "other"
            child = self._tenant_shed_children.get(label)
            if child is None:
                child = self._c_tenant_shed.labels(tenant=label)
                self._tenant_shed_children[label] = child
            child.inc()
            if self.recorder is not None:
                self.recorder.record(
                    "tenant_shed", tenant=tenant, klass=klass or "",
                    retry_after=round(retry_after, 2),
                )
        return rec

    def _tenant_quota_locked(self, klass: str, tenant: str) -> Optional[Tuple[int, int]]:
        """None → admit; (held, cap) → quota shed. Caller holds ``_lock``
        and has already established class capacity. The quota is
        WORK-CONSERVING: it only binds while OTHER tenants hold in-flight
        work in the class — a lone tenant may use the whole bound. The
        ``admission.tenant_quota`` site fails OPEN (skip quota, admit)."""
        if not (self._tenant_fair and tenant):
            return None
        try:
            self._fault_tenant.fire()
        except _faults.FaultInjected:
            self._c_tenant_degraded.inc()
            return None
        rec = self._set_tenant_state(tenant)
        if rec is None or rec["key"] == "other":
            return None
        held = rec["inflight"].get(klass, 0)
        cap = self._tenant_cap(klass)
        if held >= cap and held < self._inflight[klass]:
            return held, cap
        return None

    def tenants_info(self) -> dict:
        """The tenant-plane report for info()/readyz → cli status/doctor:
        top shed tenants (with shed rate for the pinned-at-100% doctor
        check), live quota occupancy, table bound, promotions."""
        with self._lock:
            fair = self._tenant_fair
            size = len(self._tenants)
            rows = [
                {
                    "tenant": k,
                    "sheds": r["sheds"],
                    "admits": r["admits"],
                    "shed_rate": round(
                        r["sheds"] / max(1, r["sheds"] + r["admits"]), 4),
                    "inflight": {c: n for c, n in r["inflight"].items() if n},
                }
                for k, r in self._tenants.items()
            ]
        rows.sort(key=lambda r: (-r["sheds"], r["tenant"]))
        return {
            "fair": fair,
            "table_size": size,
            "table_max": self._tenant_table_max,
            "max_share": self._tenant_share,
            "top_shed": rows[:8],
            "promotions": tenant_promotions(),
        }

    # -- admit / release -------------------------------------------------

    def try_admit(self, klass: str, deadline_s: Optional[float] = None,
                  tenant: str = "") -> None:
        """Admit or raise :class:`OverloadError`. Callers MUST pair a
        successful return with :meth:`release` (use :meth:`slot`)."""
        if klass not in self._inflight:
            raise ValueError(f"unknown admission class {klass!r}")
        if not self.enabled:
            with self._lock:
                self._inflight[klass] += 1
                self._note_peak_locked(time.monotonic())
            self._m_inflight[klass].set(self._inflight[klass])
            self._m_admitted[klass].inc()
            return
        if self.brownout.class_shed(klass):
            self.shed(klass, "brownout", tenant=tenant)
        with self._lock:
            busy = self._inflight[klass] > 0
        if deadline_s is not None and busy:
            # Only with LIVE in-flight work: an idle class's wait history
            # describes a past storm, not this request's fate.
            predicted = self.predicted_wait(klass)
            if predicted > deadline_s:
                self.shed(
                    klass, "deadline",
                    detail=f"predicted queue wait {predicted:.2f}s exceeds "
                           f"deadline {deadline_s:.2f}s",
                    tenant=tenant,
                )
        quota: Optional[Tuple[int, int]] = None
        with self._lock:
            if self._inflight[klass] >= self.limits[klass]:
                # Shed-at-limit is peak load too: between two short-lived
                # admits the instantaneous in-flight reads 0, but demand
                # past the bound is exactly what the autoscaler must see.
                self._note_peak_locked(time.monotonic())
                pressure = self._pressure_locked()
            else:
                quota = self._tenant_quota_locked(klass, tenant)
                if quota is None:
                    self._inflight[klass] += 1
                    if self._tenant_fair and tenant:
                        self._set_tenant_state(tenant, klass, inflight_delta=1)
                    self._note_peak_locked(time.monotonic())
                    self._m_inflight[klass].set(self._inflight[klass])
                    self._m_admitted[klass].inc()
                    pressure = self._pressure_locked()
                    self.brownout.note_pressure(pressure)
                    return
                # Quota shed is tenant-local demand, not class pressure —
                # record the peak (real arriving load) but shed below.
                self._note_peak_locked(time.monotonic())
                pressure = self._pressure_locked()
        self.brownout.note_pressure(pressure)
        if quota is not None:
            held, cap = quota
            self.shed(
                klass, "tenant_quota",
                detail=f"tenant {tenant!r} holds {held}/{cap} {klass} slots "
                       "while other tenants wait",
                tenant=tenant,
            )
        self.shed(klass, "queue_full", tenant=tenant)

    def note_shed(self, klass: str, reason: str, retry_after: float = 1.0,
                  tenant: str = "") -> None:
        """Record a shed decided OUTSIDE the controller (token bucket,
        micro-batcher bound) so every rejection lands on one counter."""
        self._c_shed.labels(klass=klass, reason=reason).inc()
        key = f"{klass}/{reason}"
        with self._lock:
            self._sheds[key] = self._sheds.get(key, 0) + 1
            if self._tenant_fair and tenant:
                self._set_tenant_state(
                    tenant, klass, shed=True, retry_after=retry_after)
        if self.recorder is not None:
            self.recorder.record(
                "shed", klass=klass, reason=reason,
                retry_after=round(retry_after, 2),
                **({"tenant": tenant} if tenant else {}),
            )

    def shed(self, klass: str, reason: str, detail: str = "",
             tenant: str = "") -> None:
        """Record + raise: THE rejection path (429 + Retry-After at the
        HTTP tier)."""
        ra = self.retry_after(klass, tenant=tenant)
        self.note_shed(klass, reason, retry_after=ra, tenant=tenant)
        msg = f"{klass} request shed ({reason})"
        if detail:
            msg += f": {detail}"
        raise OverloadError(msg, retry_after=ra, klass=klass, reason=reason,
                            tenant=tenant)

    def release(self, klass: str, wait_s: Optional[float] = None,
                tenant: str = "") -> None:
        with self._lock:
            self._inflight[klass] = max(0, self._inflight[klass] - 1)
            self._note_done_locked(klass)
            if self._tenant_fair and tenant:
                self._set_tenant_state(tenant, klass, inflight_delta=-1,
                                       done=True)
            pressure = self._pressure_locked()
        self._m_inflight[klass].set(self._inflight[klass])
        if wait_s is not None:
            self.note_wait(klass, wait_s)
        self.brownout.note_pressure(pressure)

    def slot(self, klass: str, deadline_s: Optional[float] = None,
             tenant: str = "") -> "_Slot":
        """Context-manager admission: sheds on entry, releases on exit."""
        return _Slot(self, klass, deadline_s, tenant)

    def shed_counts(self) -> Dict[str, float]:
        """{"klass/reason": count} for THIS controller instance — bench +
        readyz surface (the metric family is process-global and would mix
        controllers)."""
        with self._lock:
            return dict(self._sheds)

    def info(self) -> dict:
        """Mode report for /readyz: per-class occupancy + ladder state."""
        occupancy = self.local_pressure()
        with self._lock:
            inflight = dict(self._inflight)
        return {
            "enabled": self.enabled,
            "classes": {
                k: {"inflight": inflight[k], "limit": self.limits[k]}
                for k in CLASSES
            },
            "brownout": self.brownout.state,
            "brownout_step": self.brownout.step,
            # LOCAL load only (local_pressure): the probe gossips this
            # into the autoscaler's view, and exporting the folded floor
            # instead would echo a peer's pressure back as this replica's
            # own state — a rumor latch. The floor is reported separately.
            "occupancy": round(occupancy, 4),
            "fleet_pressure": round(self.fleet_pressure(), 4),
            "tenants": self.tenants_info(),
        }

    def reset(self) -> None:
        """Zero the live occupancy/wait state (tests, bench phases).
        Counters are cumulative and stay."""
        with self._lock:
            self._sheds.clear()
            self._fleet_pressure = (0.0, 0.0)
            self._occ_peaks.clear()
            self._set_tenant_state(None, clear=True)
            for k in CLASSES:
                self._inflight[k] = 0
                self._waits[k].clear()
                self._done_count[k] = 0
                self._done_t0[k] = time.monotonic()
                self._drain_rate[k] = 0.0
        for k in CLASSES:
            self._m_inflight[k].set(0)
        self.brownout.reset()


class _Slot:
    __slots__ = ("_adm", "_klass", "_deadline", "_tenant", "_t0")

    def __init__(self, adm: AdmissionController, klass: str, deadline_s,
                 tenant: str = ""):
        self._adm, self._klass, self._deadline = adm, klass, deadline_s
        self._tenant = tenant

    def __enter__(self):
        self._adm.try_admit(self._klass, self._deadline, tenant=self._tenant)
        self._t0 = time.monotonic()
        return self

    def __exit__(self, *exc):
        self._adm.release(self._klass, tenant=self._tenant)
        return False


class DeviceHealth:
    """The device-loss latch + recovery probe.

    ``degraded`` flips on when (a) the ``device.unavailable`` chaos site is
    armed and fires on a device path, or (b) a REAL backend error
    (jaxlib/XLA runtime failures, connection loss to a remote chip) is
    reported via :meth:`note_failure`. While latched:

    * hot paths that would touch the device call :meth:`check` first and
      fail FAST with :class:`DeviceUnavailableError` (< 1 s, never a hang
      into a wedged dispatch);
    * the warn path serves from the GFKB's host warm/cold tiers (degraded
      but alive);
    * one daemon probe thread retries a tiny device op every
      ``KAKVEDA_DEGRADED_PROBE`` seconds. Success un-latches. The probe
      NEVER kills the wedged process or backend — a remote TPU lease that
      is shot wedges for hours (CLAUDE.md); it just keeps asking.
    """

    # Substrings that identify an accelerator-backend failure in exception
    # text — deliberately conservative: a random ValueError must NOT latch
    # the whole platform into degraded mode.
    _BACKEND_MARKERS = (
        "unavailable", "deadline_exceeded", "failed to connect",
        "socket closed", "device or resource busy", "tpu", "pjrt",
    )

    def __init__(self, probe_interval: Optional[float] = None, probe_fn=None):
        self.probe_interval = (
            _env_float("KAKVEDA_DEGRADED_PROBE", 5.0)
            if probe_interval is None else probe_interval
        )
        self._probe_fn = probe_fn or self._default_probe
        self._degraded = threading.Event()
        self._lock = sanitize.named_lock("DeviceHealth._lock")
        self._probe_thread: Optional[threading.Thread] = None
        self._since: Optional[float] = None
        self._reason = ""
        # The chaos site, resolved once and SHARED with every device path
        # that threads it (GFKB match dispatch, the probe itself): while
        # armed the probe keeps failing, so disarming is what lets the
        # platform recover — exactly how a real outage ends.
        self._fault = _faults.site("device.unavailable")
        reg = _metrics.get_registry()
        self._g_degraded = reg.gauge(
            "kakveda_device_degraded",
            "1 while the accelerator backend is latched DEGRADED "
            "(device-loss mode: host-fallback warn, fail-fast generation)",
        )
        self._c_transitions = reg.counter(
            "kakveda_device_degraded_transitions_total",
            "Degraded-mode latch transitions", ("to",),
        )
        self._c_probe = reg.counter(
            "kakveda_device_probe_total",
            "Backend recovery-probe attempts by result", ("result",),
        )
        self._g_degraded.set(0.0)
        self.recorder = _metrics.FlightRecorder("device-health")

    # -- classification --------------------------------------------------

    @classmethod
    def is_backend_error(cls, exc: BaseException) -> bool:
        """Does this exception look like the accelerator going away (vs a
        plain software bug)? Injected ``device.unavailable`` faults count
        by construction; real errors match on the jaxlib/XLA types or the
        conservative marker list."""
        if isinstance(exc, _faults.FaultInjected):
            return exc.site == "device.unavailable"
        tname = type(exc).__name__
        mod = type(exc).__module__ or ""
        if "XlaRuntimeError" in tname or mod.startswith(("jaxlib", "jax._src.lib")):
            return True
        text = str(exc).lower()
        return any(m in text for m in cls._BACKEND_MARKERS)

    # -- latch -----------------------------------------------------------

    @property
    def degraded(self) -> bool:
        return self._degraded.is_set()

    def check(self) -> None:
        """Fail fast while latched — the shed-never-hang rule for device
        paths (a dispatch into a wedged backend blocks forever)."""
        if self._degraded.is_set():
            raise DeviceUnavailableError(
                f"accelerator backend degraded ({self._reason}); "
                "host-fallback paths only",
                retry_after=self.probe_interval,
            )

    def note_failure(self, exc: BaseException, where: str = "") -> bool:
        """Classify + maybe latch. Returns True when the platform is (now)
        degraded — the caller's cue to take its host fallback."""
        if self._degraded.is_set():
            return True
        if not self.is_backend_error(exc):
            return False
        with self._lock:
            if not self._degraded.is_set():
                self._reason = f"{type(exc).__name__} at {where or 'device path'}"
                self._since = time.time()
                self._degraded.set()
                self._g_degraded.set(1.0)
                self._c_transitions.labels(to="degraded").inc()
                self.recorder.record("degraded", where=where,
                                     error=f"{type(exc).__name__}: {exc}")
                log.error(
                    "accelerator backend latched DEGRADED (%s); warn serves "
                    "from the host fallback, generation fails fast; probing "
                    "every %.1fs", self._reason, self.probe_interval,
                )
                self._start_probe_locked()
        return True

    def _start_probe_locked(self) -> None:
        if self._probe_thread is not None and self._probe_thread.is_alive():
            return
        t = threading.Thread(
            target=self._probe_loop, daemon=True, name="device-health-probe"
        )
        self._probe_thread = t
        t.start()

    def _default_probe(self) -> None:
        """One tiny compiled device op. Raises when the backend is gone;
        the armed chaos site fires first so injected outages gate the
        probe exactly like real ones."""
        self._fault.fire()
        import jax
        import jax.numpy as jnp

        jax.block_until_ready(jnp.zeros((8,), jnp.float32) + 1.0)

    def _probe_loop(self) -> None:
        while self._degraded.is_set():
            time.sleep(self.probe_interval)
            if not self._degraded.is_set():
                return
            try:
                self._probe_fn()
            except Exception as e:  # noqa: BLE001 — any failure = still down
                self._c_probe.labels(result="fail").inc()
                log.warning("backend probe failed (%s: %s); still degraded",
                            type(e).__name__, e)
                continue
            self._c_probe.labels(result="ok").inc()
            self.unlatch("probe succeeded")
            return

    def unlatch(self, why: str = "") -> None:
        with self._lock:
            if not self._degraded.is_set():
                return
            down_s = time.time() - (self._since or time.time())
            self._degraded.clear()
            self._g_degraded.set(0.0)
            self._c_transitions.labels(to="healthy").inc()
            self.recorder.record("recovered", why=why,
                                 down_s=round(down_s, 3))
            log.warning(
                "accelerator backend recovered (%s) after %.1fs degraded",
                why or "manual", down_s,
            )

    def info(self) -> dict:
        return {
            "degraded": self.degraded,
            "reason": self._reason if self.degraded else None,
            "since": self._since if self.degraded else None,
            "probe_interval_s": self.probe_interval,
        }


# --- process-global instances ----------------------------------------------

_GLOBAL_LOCK = sanitize.named_lock("admission._GLOBAL_LOCK")
_ADMISSION: Optional[AdmissionController] = None
_DEVICE_HEALTH: Optional[DeviceHealth] = None


def get_admission() -> AdmissionController:
    """The process-global admission/brownout controller — one pressure
    picture shared by the HTTP tier, the serving engine and the batcher."""
    global _ADMISSION
    if _ADMISSION is None:
        with _GLOBAL_LOCK:
            if _ADMISSION is None:
                _ADMISSION = AdmissionController()
    return _ADMISSION


def get_device_health() -> DeviceHealth:
    global _DEVICE_HEALTH
    if _DEVICE_HEALTH is None:
        with _GLOBAL_LOCK:
            if _DEVICE_HEALTH is None:
                _DEVICE_HEALTH = DeviceHealth()
    return _DEVICE_HEALTH


def reset_for_tests() -> None:
    """Drop the global controllers so the next accessor call rebuilds them
    from the current env. Tests that latch degraded mode or drive the
    brownout ladder MUST call this in teardown — tier-1 runs everything in
    one process and a leaked latch would poison unrelated tests."""
    global _ADMISSION, _DEVICE_HEALTH
    with _GLOBAL_LOCK:
        if _DEVICE_HEALTH is not None:
            _DEVICE_HEALTH.unlatch("reset_for_tests")
        if _ADMISSION is not None:
            _ADMISSION.reset()
        _ADMISSION = None
        _DEVICE_HEALTH = None
    with _PROMOTIONS_LOCK:
        _PROMOTIONS.clear()
