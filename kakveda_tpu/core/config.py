"""File-backed config with polling hot reload.

Capability parity with the reference's ConfigStore
(reference: services/shared/config.py:18-58): YAML file, mtime-change or
poll-interval triggered reload, per-service instances with no shared mutable
state. Adds typed accessors for the knobs every subsystem reads
(reference: config/config.yaml:1-20).
"""

from __future__ import annotations

import os
from dataclasses import dataclass
from pathlib import Path
from typing import Any, Dict, Mapping, Optional

import yaml

DEFAULT_CONFIG: Dict[str, Any] = {
    "failure_matching": {
        "similarity_threshold": 0.8,
        "mode": "semantic_plus_rule",
        "embedding_dim": 2048,
        "top_k": 5,
    },
    "warning_policy": {"default_action": "warn"},
    "health_score": {
        "severity_weights": {"low": 1, "medium": 3, "high": 7},
        "window_size": 10,
        "base_score": 100,
    },
    "sampling": {"enabled": False},
    "hot_reload": {"enabled": True, "poll_seconds": 2},
}


@dataclass(frozen=True)
class HotReloadConfig:
    enabled: bool
    poll_seconds: int


class ConfigStore:
    """YAML config with mtime + poll-based hot reload.

    ``get()`` is cheap enough to call on every request; it stats the file and
    re-reads only when the mtime changed or the poll interval elapsed.
    """

    def __init__(self, config_path: Optional[str | Path] = None):
        default = os.environ.get("KAKVEDA_CONFIG_PATH", "config/config.yaml")
        self._path = Path(config_path or default)
        self._last_mtime: Optional[float] = None
        self._cache: Dict[str, Any] = {}
        self._loaded = False

    @property
    def path(self) -> Path:
        return self._path

    def _read(self) -> Dict[str, Any]:
        if not self._path.exists():
            return {}
        with self._path.open("r", encoding="utf-8") as f:
            return yaml.safe_load(f) or {}

    def get(self) -> Dict[str, Any]:
        """Current config; re-parses only on first use or mtime change.

        Hot reload works by statting the file per call (cheap) — the mtime
        check is what detects edits, so there is no parse-every-poll churn.
        """
        try:
            mtime = self._path.stat().st_mtime if self._path.exists() else None
        except OSError:
            mtime = None

        if not self._loaded or (self.hot_reload().enabled and mtime != self._last_mtime):
            self._cache = self._read()
            self._last_mtime = mtime
            self._loaded = True
        return self._cache

    def hot_reload(self) -> HotReloadConfig:
        data = self._cache if self._loaded else (self._read() or {})
        hr = data.get("hot_reload") or {}
        return HotReloadConfig(
            enabled=bool(hr.get("enabled", True)),
            poll_seconds=int(hr.get("poll_seconds", 2)),
        )

    # --- typed accessors -------------------------------------------------

    def _section(self, name: str) -> Mapping[str, Any]:
        return self.get().get(name) or DEFAULT_CONFIG.get(name) or {}

    def similarity_threshold(self) -> float:
        sect = self._section("failure_matching")
        return float(sect.get("similarity_threshold", 0.8))

    def match_top_k(self) -> int:
        sect = self._section("failure_matching")
        return int(sect.get("top_k", 5))

    def embedding_dim(self) -> int:
        sect = self._section("failure_matching")
        return int(sect.get("embedding_dim", 2048))

    def default_action(self) -> str:
        sect = self._section("warning_policy")
        return str(sect.get("default_action", "warn"))

    def severity_weights(self) -> Dict[str, float]:
        sect = self._section("health_score")
        w = sect.get("severity_weights") or {"low": 1, "medium": 3, "high": 7}
        return {k: float(v) for k, v in w.items()}

    def base_score(self) -> float:
        sect = self._section("health_score")
        return float(sect.get("base_score", 100))


def write_default_config(path: str | Path) -> Path:
    """Materialize the default config file (used by `kakveda-tpu init`)."""
    p = Path(path)
    p.parent.mkdir(parents=True, exist_ok=True)
    p.write_text(yaml.safe_dump(DEFAULT_CONFIG, sort_keys=False), encoding="utf-8")
    return p
