"""Deterministic fault injection — the chaos harness the platform eats its
own dogfood with.

kakveda's premise is failure intelligence, so its own failure handling must
be provable, not aspirational: the serving-engine supervisor, the bus's
retry/breaker/DLQ path and the crash-safe log replay (docs/robustness.md)
all need a way to *cause* the failures they claim to survive, on demand and
reproducibly. This module is that switch.

Design (mirrors the metrics plane's resolve-once pattern):

* A **fault site** is a named point in the code (``engine.dispatch``,
  ``bus.deliver``, ``gfkb.append``, …). Components resolve their sites ONCE
  at construction/import via :func:`site` and keep the object; the hot-path
  call is ``site.fire()`` — a single ``self.armed`` attribute check when
  nothing is armed, so compiled-in sites cost nothing in production.
* Arming is an env spec — ``KAKVEDA_FAULTS=site:prob:count,…`` (``prob``
  defaults to 1.0, ``count`` to 1; ``count`` ``-1`` = unlimited) — parsed at
  import, or programmatic via :func:`arm` (tests). Arming mutates the
  existing site objects in place, so components constructed before
  ``arm()`` still inject.
* The RNG is seeded (``KAKVEDA_FAULTS_SEED``, default 0) so a probabilistic
  chaos run replays the same injection sequence.
* **Timed arming** (:func:`schedule` / ``KAKVEDA_FAULTS_TIMELINE``): a
  chaos *timeline* applies full arm specs at scheduled offsets — the
  traffic replayer (kakveda_tpu/traffic) opens and closes outage windows
  mid-storm with it, and the env form gives subprocess fleet replicas the
  same capability without any admin API.
* An injection raises :class:`FaultInjected` at the site and increments
  ``kakveda_faults_injected_total{site=…}`` — chaos runs are observable on
  the same /metrics plane as the recovery they exercise.
* **Crash points** (:func:`arm_crash` / ``KAKVEDA_FAULTS_CRASH=site:nth,…``)
  are the power-cut mode: the n-th pass through the site hard-kills the
  process with ``os._exit(137)`` — no exception, no ``finally``, no atexit,
  no buffered-write flush. The crash-point recovery sweep
  (index/crashsweep.py) arms these in a child process at every durable
  write seam of a compaction/aging cycle and certifies the recovered store.
  Crash arming composes with (and is cleared by) :func:`arm`/:func:`disarm`
  like any other arming, so the standard test teardown can never leave a
  process-killing trap behind.

The fault-site catalog lives in docs/robustness.md; adding a site means
adding it there — scripts/check_knobs.py (tier-1) fails when a ``site("…")``
registered in code is missing from that catalog. ``device.unavailable`` is
the one deliberately *shared* site: GFKB match dispatch and the
device-health recovery probe resolve the same object, so arming it
simulates a whole-chip outage (warn falls back to the host index,
generation fails fast) and DISARMING it is what lets the probe un-latch —
the same shape as a real outage ending (core/admission.py).
"""

from __future__ import annotations

import logging
import os
import random
import threading
import time
from typing import Dict, Optional
from kakveda_tpu.core import sanitize

log = logging.getLogger("kakveda.faults")

__all__ = [
    "FaultInjected",
    "FaultSite",
    "FaultSchedule",
    "site",
    "arm",
    "arm_crash",
    "disarm",
    "armed_sites",
    "schedule",
]


class FaultInjected(RuntimeError):
    """An armed fault site fired. Deliberately a RuntimeError subclass so
    injected failures travel the exact error paths real device/IO failures
    travel — the harness must not need special-cased handling."""

    def __init__(self, site_name: str):
        super().__init__(f"injected fault at {site_name} (KAKVEDA_FAULTS)")
        self.site = site_name


class FaultSite:
    """One named injection point. ``fire()`` is the hot-path call: a bare
    attribute check when unarmed, a lock + seeded draw when armed."""

    __slots__ = ("name", "armed", "prob", "remaining", "fired", "crash_at", "passes")

    def __init__(self, name: str):
        self.name = name
        self.armed = False
        self.prob = 0.0
        self.remaining = 0  # -1 = unlimited
        self.fired = 0
        self.crash_at = 0  # CrashPoint mode: kill process at the n-th pass
        self.passes = 0

    def fire(self) -> None:
        if not self.armed:
            return
        _fire(self)

    def __repr__(self) -> str:  # pragma: no cover - debugging aid
        return (
            f"FaultSite({self.name!r}, armed={self.armed}, prob={self.prob}, "
            f"remaining={self.remaining}, fired={self.fired})"
        )


_lock = sanitize.named_lock("faults._lock")
_sites: Dict[str, FaultSite] = {}
_rng = random.Random(0)
_m_injected = None  # resolved lazily: metrics must stay import-cycle-free


def site(name: str) -> FaultSite:
    """Get-or-create the site object for ``name`` — call once per component
    (construction/import), keep the reference, ``fire()`` on the hot path."""
    with _lock:
        s = _sites.get(name)
        if s is None:
            s = _sites[name] = FaultSite(name)
        return s


def _fire(s: FaultSite) -> None:
    with _lock:
        if not s.armed:  # lost the race with disarm()
            return
        if s.crash_at:
            # CrashPoint mode: the n-th pass through the site is a power
            # cut — os._exit skips exception handlers, finally blocks,
            # atexit and buffered flushes, which is exactly the point.
            s.passes += 1
            if s.passes >= s.crash_at:
                try:
                    os.write(2, f"kakveda crash point: {s.name} pass {s.passes}\n".encode())
                except OSError:  # pragma: no cover - stderr gone
                    pass
                os._exit(137)
            return  # passes below n fall through silently
        if s.prob < 1.0 and _rng.random() >= s.prob:
            return
        s.fired += 1
        if s.remaining > 0:
            s.remaining -= 1
            if s.remaining == 0:
                s.armed = False
    global _m_injected
    if _m_injected is None:
        from kakveda_tpu.core import metrics as _metrics

        _m_injected = _metrics.get_registry().counter(
            "kakveda_faults_injected_total",
            "Injected faults by site (KAKVEDA_FAULTS chaos harness)", ("site",),
        )
    _m_injected.labels(site=s.name).inc()
    log.warning("fault injected at %s (fired=%d)", s.name, s.fired)
    raise FaultInjected(s.name)


def arm(spec: str, seed: Optional[int] = None) -> None:
    """Arm sites from a ``site:prob:count,…`` spec (prob defaults to 1.0,
    count to 1, count -1 = unlimited). Replaces the previous arming —
    unlisted sites disarm. ``seed`` reseeds the shared RNG (default: keep)."""
    parsed = []
    for part in (spec or "").split(","):
        part = part.strip()
        if not part:
            continue
        fields = part.split(":")
        name = fields[0]
        try:
            prob = float(fields[1]) if len(fields) > 1 and fields[1] else 1.0
            count = int(fields[2]) if len(fields) > 2 and fields[2] else 1
        except ValueError as e:
            raise ValueError(f"bad KAKVEDA_FAULTS entry {part!r}: {e}") from e
        parsed.append((name, prob, count))
    with _lock:
        if seed is not None:
            _rng.seed(seed)
        for s in _sites.values():
            s.armed = False
            s.prob = 0.0
            s.remaining = 0
            s.crash_at = 0
            s.passes = 0
        for name, prob, count in parsed:
            s = _sites.get(name)
            if s is None:
                s = _sites[name] = FaultSite(name)
            s.prob = prob
            s.remaining = count
            s.armed = count != 0
            s.fired = 0  # each arming is a fresh experiment
    if parsed:
        log.warning("fault sites armed: %s", ", ".join(p[0] for p in parsed))


def arm_crash(spec: str) -> None:
    """Arm crash points from a ``site:nth,…`` spec (``nth`` defaults to 1):
    the n-th ``fire()`` at the site calls ``os._exit(137)``. Additive over
    probabilistic arming on OTHER sites, but replaces any previous crash
    arming; :func:`arm`/:func:`disarm` clear crash state like everything
    else, so the standard teardown path can't leak a live trap."""
    parsed = []
    for part in (spec or "").split(","):
        part = part.strip()
        if not part:
            continue
        fields = part.split(":")
        name = fields[0]
        try:
            nth = int(fields[1]) if len(fields) > 1 and fields[1] else 1
        except ValueError as e:
            raise ValueError(f"bad KAKVEDA_FAULTS_CRASH entry {part!r}: {e}") from e
        parsed.append((name, max(1, nth)))
    with _lock:
        for s in _sites.values():
            s.crash_at = 0
            s.passes = 0
        for name, nth in parsed:
            s = _sites.get(name)
            if s is None:
                s = _sites[name] = FaultSite(name)
            s.crash_at = nth
            s.passes = 0
            s.armed = True
    if parsed:
        log.warning(
            "crash points armed: %s",
            ", ".join(f"{name}@{nth}" for name, nth in parsed),
        )


def disarm() -> None:
    """Disarm every site (counters survive for inspection)."""
    arm("")


def armed_sites() -> Dict[str, FaultSite]:
    with _lock:
        return {n: s for n, s in _sites.items() if s.armed}


class FaultSchedule:
    """Timed arming — a chaos *timeline*: apply full :func:`arm` specs at
    scheduled offsets from ``start()``.

    Entries are ``{"t": offset_s, "spec": "site:prob:count,…"}`` dicts (or
    ``(t, spec)`` pairs), applied in offset order by a daemon thread. Each
    entry carries a COMPLETE arming state — :func:`arm` replaces, so an
    entry with ``spec=""`` is how an outage window closes (the same
    disarm-ends-the-outage shape as a manual chaos run). ``speed`` divides
    the offsets, matching the traffic replayer's speed factor
    (kakveda_tpu/traffic): a 2x replay runs its chaos timeline at 2x too.

    ``cancel()`` stops FUTURE entries only; it deliberately does not
    disarm — the caller owns terminal cleanup (tests use the standard
    ``faults.disarm()`` teardown)."""

    def __init__(self, entries, *, speed: float = 1.0, seed: Optional[int] = None):
        norm = []
        for e in entries:
            if isinstance(e, dict):
                t, spec = float(e["t"]), str(e.get("spec", ""))
            else:
                t, spec = float(e[0]), str(e[1])
            # Parse eagerly so a bad timeline fails at construction, not
            # mid-run inside a daemon thread nobody is watching.
            arm_spec_check(spec)
            norm.append((t, spec))
        self.entries = sorted(norm, key=lambda p: p[0])
        self.speed = max(1e-6, float(speed))
        self.seed = seed
        self.applied = 0
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def start(self) -> "FaultSchedule":
        if self._thread is not None:
            return self
        self._thread = threading.Thread(
            target=self._run, name="kakveda-fault-schedule", daemon=True
        )
        self._thread.start()
        return self

    def _run(self) -> None:
        t0 = time.monotonic()
        if self.seed is not None:
            with _lock:
                _rng.seed(self.seed)
        for t, spec in self.entries:
            delay = t0 + t / self.speed - time.monotonic()
            if delay > 0 and self._stop.wait(delay):
                return
            if self._stop.is_set():
                return
            arm(spec)
            self.applied += 1

    def cancel(self) -> None:
        self._stop.set()

    def join(self, timeout: Optional[float] = None) -> None:
        if self._thread is not None:
            self._thread.join(timeout)

    @property
    def done(self) -> bool:
        return self._thread is not None and not self._thread.is_alive()


def arm_spec_check(spec: str) -> None:
    """Validate a ``site:prob:count,…`` spec without touching site state
    (schedule construction, timeline env parse)."""
    for part in (spec or "").split(","):
        part = part.strip()
        if not part:
            continue
        fields = part.split(":")
        try:
            float(fields[1]) if len(fields) > 1 and fields[1] else 1.0
            int(fields[2]) if len(fields) > 2 and fields[2] else 1
        except ValueError as e:
            raise ValueError(f"bad fault spec entry {part!r}: {e}") from e


def schedule(entries, *, speed: float = 1.0, seed: Optional[int] = None,
             start: bool = True) -> FaultSchedule:
    """Build (and by default start) a :class:`FaultSchedule`."""
    sched = FaultSchedule(entries, speed=speed, seed=seed)
    return sched.start() if start else sched


# Env arming at import: components resolving sites later still see it, and
# a process started with KAKVEDA_FAULTS set injects from its first event.
_env_spec = os.environ.get("KAKVEDA_FAULTS", "")
if _env_spec:
    arm(_env_spec, seed=int(os.environ.get("KAKVEDA_FAULTS_SEED", "0")))

# Env chaos timeline: KAKVEDA_FAULTS_TIMELINE is a JSON array of
# {"t": offset_s, "spec": "site:prob:count,…"} entries, offsets relative to
# import. This is how a SUBPROCESS (fleet replica under the storm bench /
# traffic replayer) gets a mid-run outage window without an admin API: the
# parent sets the env, the child arms and disarms itself on schedule.
# Env crash points: KAKVEDA_FAULTS_CRASH=site:nth,… — the subprocess half
# of the crash-point recovery sweep (index/crashsweep.py): the parent sets
# the env, the child dies mid-write at the n-th pass, the parent certifies
# the recovered store.
_env_crash = os.environ.get("KAKVEDA_FAULTS_CRASH", "")
if _env_crash:
    arm_crash(_env_crash)

_env_timeline = os.environ.get("KAKVEDA_FAULTS_TIMELINE", "")
if _env_timeline:
    import json as _json

    schedule(
        _json.loads(_env_timeline),
        seed=int(os.environ.get("KAKVEDA_FAULTS_SEED", "0")),
    )
