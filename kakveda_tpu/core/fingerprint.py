"""Deterministic failure fingerprinting.

Produces an app-agnostic ``signature_text`` for every execution — the string
that gets embedded into the GFKB index and matched against at pre-flight
time — plus a short sha256 fingerprint and a citation-marker detector used by
the rule classifier.

Semantics are behaviour-compatible with the reference
(reference: services/shared/fingerprint.py:16-87): identical intent-tag
vocabulary, identical signature layout, identical hash derivation. This
determinism is load-bearing — the e2e scenario tests and the pre-flight
similarity calibration depend on stable tags being the dominant signal.
"""

from __future__ import annotations

import hashlib
import re
from dataclasses import dataclass
from functools import lru_cache
from typing import Any, Dict, Iterable, List

_WS_RE = re.compile(r"\s+")

# Markers that indicate the response *contains* citations: bracketed numeric
# refs, author-year parentheticals, DOIs, or a References/Bibliography section
# (reference: services/shared/fingerprint.py:9-13,79-87).
_CITATION_MARKER_RES = [
    re.compile(r"\[[0-9]+\]"),
    re.compile(r"\([A-Za-z]+,\s*\d{4}\)"),
    re.compile(r"doi:\s*\S+"),
]

_CITATION_KEYWORDS = (
    "citation",
    "citations",
    "reference",
    "references",
    "sources",
    "bibliography",
)

_SUMMARIZATION_KEYWORDS = ("summarize", "summary", "tl;dr")
_EXPLANATION_KEYWORDS = ("explain", "explanation", "describe")


def normalize_prompt(prompt: str) -> str:
    """Lowercase and collapse whitespace."""
    return _WS_RE.sub(" ", prompt.strip().lower())


def prompt_intent_tags(prompt: str) -> List[str]:
    """Coarse, app-agnostic prompt "shape" tags.

    Prompts that carry the same failure risk share tags even when the wording
    differs, which keeps similarity matching deterministic across apps.
    Tag vocabulary matches the reference exactly
    (reference: services/shared/fingerprint.py:22-48).
    """
    # Cache only prompts of bounded size: the entry count is capped but the
    # keys are untrusted strings, and 64k × multi-KB prompts would pin
    # gigabytes for the process lifetime.
    if len(prompt) > _TAG_CACHE_MAX_PROMPT_LEN:
        return list(_intent_tags_compute(prompt))
    return list(_intent_tags_cached(prompt))


_TAG_CACHE_MAX_PROMPT_LEN = 2048


# The streaming path tags every prompt twice (classifier + signature_text);
# the cache collapses that, and repeated prompts in production hit it too.
def _intent_tags_compute(prompt: str) -> tuple:
    p = normalize_prompt(prompt)
    tags: List[str] = []

    wants_citations = any(k in p for k in _CITATION_KEYWORDS)
    if wants_citations:
        tags.append("intent:citations_required")

    if any(k in p for k in _SUMMARIZATION_KEYWORDS):
        tags.append("task:summarization")
    if any(k in p for k in _EXPLANATION_KEYWORDS):
        tags.append("task:explanation")

    if "even if not provided" in p or "even if none" in p:
        tags.append("constraint:no_sources_provided")
    if "include" in p and wants_citations:
        tags.append("instruction:include_references")

    return tuple(sorted(set(tags)))


_intent_tags_cached = lru_cache(maxsize=65536)(_intent_tags_compute)


def signature_text(prompt: str, tools: Iterable[str], env: Dict[str, Any]) -> str:
    """Build the canonical match string for an execution.

    Deliberately app-agnostic (no app_id / trace_id). Intent tags lead so
    they dominate the embedding; the raw prompt contributes only an 80-char
    hint (reference: services/shared/fingerprint.py:51-66).
    """
    tags = prompt_intent_tags(prompt)
    pshort = normalize_prompt(prompt)[:80]
    parts = [
        f"intent_tags:{','.join(tags)}",
        f"prompt_hint:{pshort}",
        f"tools:{','.join(sorted(set(tools)))}",
        f"env_keys:{','.join(sorted(env.keys()))}",
    ]
    return " | ".join(parts)


def fingerprint(prompt: str, tools: Iterable[str], env: Dict[str, Any]) -> str:
    """16-hex-char stable id of the signature text."""
    sig = signature_text(prompt, tools, env)
    return hashlib.sha256(sig.encode("utf-8")).hexdigest()[:16]


@dataclass(frozen=True)
class CitationCheck:
    has_citation_markers: bool


def detect_citation_markers(text: str) -> CitationCheck:
    """Does the text *look like* it contains citations?

    Regex markers first, then the crude "References"/"Bibliography" section
    heuristic (reference: services/shared/fingerprint.py:79-87).
    """
    t = text or ""
    if any(rx.search(t) for rx in _CITATION_MARKER_RES):
        return CitationCheck(True)
    low = t.lower()
    if "references" in low or "bibliography" in low:
        return CitationCheck(True)
    return CitationCheck(False)
