"""Runtime compile-and-transfer ledger (``KAKVEDA_LEDGER=1``).

The static half of the device-plane pass (:mod:`kakveda_tpu.analysis.
device`) reasons about retrace hazards and donation misuse from the AST;
this module is the dynamic half, the same cross-check shape as the
concurrency sanitizer (static lock-order graph ↔ runtime lock
instrumentation). The static rules say "this call site CANNOT retrace";
the ledger proves at runtime that it DIDN'T: every XLA backend compile is
counted against the jit entry point that triggered it, and every
host↔device transfer seam reports its bytes against the request phase it
served.

Off by default the module is inert: :func:`note_transfer` is one module
attribute check, nothing patches jax, nothing registers listeners. With
``KAKVEDA_LEDGER=1`` and :func:`maybe_install`:

* ``jax.jit`` is wrapped so every jitted callable created AFTER install
  carries its function name; calling it pushes that label onto a
  thread-local stack. A ``jax.monitoring`` duration listener on the
  backend-compile event attributes each actual XLA compile to the label
  on top of the stack (``unattributed`` when the compile came from a jit
  created before install — wrap those regions in :func:`entry`).
* Transfer seams (``ShardedKnn._replicate`` h2d, ``topk_result`` d2h,
  the serving engine's mirror upload / token fetch) call
  :func:`note_transfer`; bytes accumulate per (direction, phase), the
  phase being whatever :func:`phase` context is active on that thread.
* :func:`mark_warm` draws the warmup line: compiles after it are the
  bug the static retrace-hazard rule exists to prevent, so each one is
  recorded as a ``post_warmup_compile`` flight-recorder event (served at
  ``GET /flightrecorder``) and counted in :func:`ledger_report` —
  bench.py's serve/warn rows assert that count is ZERO, and the
  tiered/mine rows assert the per-entry compile counts stay inside the
  O(log N) pow2-bucket envelope.

Metrics: ``kakveda_compile_total{fn}`` and
``kakveda_transfer_bytes{direction,phase}`` (``core/metrics.py``
registry; catalog in docs/observability.md).

Dependency-free at import (stdlib only; jax, the metrics registry and
the flight recorder are imported lazily at install/use) so the analysis
pass and its tests can import this module without a backend.
"""

from __future__ import annotations

import contextlib
import functools
import os
import threading
import time
from typing import Dict, List, Optional

_TRUTHY = frozenset({"1", "true", "yes", "on"})

#: jax.monitoring event suffix that fires exactly once per actual XLA
#: backend compile (NOT per trace, NOT per cache hit).
_COMPILE_EVENT_SUFFIX = "backend_compile_duration"


def enabled() -> bool:
    """Is the ledger armed? Read at :func:`maybe_install` time, not per
    call — benches set ``KAKVEDA_LEDGER=1`` before building the objects
    under test."""
    return os.environ.get("KAKVEDA_LEDGER", "").strip().lower() in _TRUTHY


# ---------------------------------------------------------------------------
# process-global ledger state
# ---------------------------------------------------------------------------

# Guards the tables below. A raw lock ON PURPOSE (mirrors sanitize.py):
# the ledger must never show up in its own instrumentation.
_STATE_LOCK = threading.Lock()
# entry label -> number of XLA backend compiles attributed to it.
_COMPILES: Dict[str, int] = {}
# Compiles observed after mark_warm(): [{"fn", "t", "duration_ms"}].
_POST_WARMUP: List[dict] = []
# direction ("h2d"|"d2h") -> phase -> bytes.
_TRANSFERS: Dict[str, Dict[str, int]] = {}
_WARM = False

_INSTALLED = False
_ORIG_JIT = None  # jax.jit before the labeling wrapper replaced it
# jax.monitoring has no unregister: the listener is registered ONCE per
# process and deafened via _INSTALLED; install/uninstall cycles (tests)
# must not stack duplicate registrations.
_LISTENER_REGISTERED = False

_TLS = threading.local()

_RECORDER = None  # lazy FlightRecorder("ledger")


def _recorder():
    global _RECORDER
    if _RECORDER is None:
        from kakveda_tpu.core import metrics as _metrics

        _RECORDER = _metrics.FlightRecorder("ledger")
    return _RECORDER


def _metric(name: str, help: str, labels):
    """Label-family get-or-create, lazy and failure-proof: the ledger
    records into its own tables regardless; the Prometheus mirror is
    best-effort (shapes are pre-declared in metrics._CORE_FAMILIES)."""
    try:
        from kakveda_tpu.core import metrics as _metrics

        return _metrics.get_registry().counter(name, help, labels)
    except Exception:
        return None


def _entry_stack() -> List[str]:
    st = getattr(_TLS, "entries", None)
    if st is None:
        st = _TLS.entries = []
    return st


def _phase_stack() -> List[str]:
    st = getattr(_TLS, "phases", None)
    if st is None:
        st = _TLS.phases = []
    return st


@contextlib.contextmanager
def entry(name: str):
    """Attribute any compile triggered inside the block to ``name``.
    Needed only for jits created BEFORE install (module-level jits in
    already-imported modules); jits created after install self-label."""
    st = _entry_stack()
    st.append(name)
    try:
        yield
    finally:
        st.pop()


@contextlib.contextmanager
def phase(name: str):
    """Attribute transfer bytes inside the block to request phase
    ``name`` (``warn``/``ingest``/``admit``/``decode``/…)."""
    st = _phase_stack()
    st.append(name)
    try:
        yield
    finally:
        st.pop()


# ---------------------------------------------------------------------------
# compile attribution
# ---------------------------------------------------------------------------


class _LabeledJit:
    """A jitted callable that pushes its label while running, so the
    monitoring listener can attribute the backend compile the first call
    (per shape signature) triggers. Pure delegation otherwise — lower/
    eval_shape/clear_cache etc. pass through untouched."""

    __slots__ = ("_jitted", "_label")

    def __init__(self, jitted, label: str):
        self._jitted = jitted
        self._label = label

    def __call__(self, *args, **kwargs):
        st = _entry_stack()
        st.append(self._label)
        try:
            return self._jitted(*args, **kwargs)
        finally:
            st.pop()

    def __get__(self, obj, objtype=None):  # decorated methods keep binding
        if obj is None:
            return self
        return functools.partial(self.__call__, obj)

    def __getattr__(self, item):
        return getattr(self._jitted, item)

    def __repr__(self):
        return f"<ledger-labeled jit {self._label!r}>"


def _patched_jit(fun=None, **kwargs):
    """Drop-in ``jax.jit``: same semantics, but the returned callable is
    wrapped with its function name for compile attribution. Handles both
    ``jax.jit(f, ...)`` and the kwargs-only decorator-factory form."""
    if fun is None:
        return functools.partial(_patched_jit, **kwargs)
    jitted = _ORIG_JIT(fun, **kwargs)
    label = getattr(fun, "__name__", None)
    if not label or label == "<lambda>":
        # A lambda has no useful name; leave it unwrapped so its compiles
        # attribute to the enclosing entry() (or the self-labeled caller).
        return jitted
    return _LabeledJit(jitted, label)


def _on_duration_event(event: str, duration: float, **kw) -> None:
    """jax.monitoring listener: count backend compiles by current entry."""
    if not _INSTALLED or not event.endswith(_COMPILE_EVENT_SUFFIX):
        return
    st = _entry_stack()
    label = st[-1] if st else "unattributed"
    with _STATE_LOCK:
        _COMPILES[label] = _COMPILES.get(label, 0) + 1
        warm = _WARM
        if warm:
            evt = {
                "fn": label,
                "t": round(time.time(), 6),
                "duration_ms": round(duration * 1000.0, 3),
            }
            _POST_WARMUP.append(evt)
    fam = _metric(
        "kakveda_compile_total",
        "XLA backend compiles attributed per jit entry point "
        "(KAKVEDA_LEDGER=1)", ("fn",),
    )
    if fam is not None:
        fam.labels(fn=label).inc()
    if warm:
        _recorder().record(
            "post_warmup_compile", fn=label,
            duration_ms=round(duration * 1000.0, 3),
        )


def maybe_install() -> bool:
    """Install the ledger if ``KAKVEDA_LEDGER=1`` and not yet installed.
    Idempotent; returns whether the ledger is installed after the call.
    Importing jax happens here, never at module import."""
    global _INSTALLED, _ORIG_JIT, _LISTENER_REGISTERED
    if _INSTALLED:
        return True
    if not enabled():
        return False
    import jax
    from jax import monitoring as _monitoring

    with _STATE_LOCK:
        if _INSTALLED:
            return True
        if jax.jit is not _patched_jit:
            if _ORIG_JIT is None:
                _ORIG_JIT = jax.jit
            jax.jit = _patched_jit
        if not _LISTENER_REGISTERED:
            _monitoring.register_event_duration_secs_listener(_on_duration_event)
            _LISTENER_REGISTERED = True
        _INSTALLED = True
    return True


def uninstall() -> None:
    """Restore ``jax.jit`` and deafen the listener (it stays registered —
    jax.monitoring has no unregister — but no-ops while not installed).
    Jitted callables created while installed keep working; they just
    stop attributing. Test hygiene, not a production path."""
    global _INSTALLED
    with _STATE_LOCK:
        if _ORIG_JIT is not None:
            import jax

            jax.jit = _ORIG_JIT
            # _ORIG_JIT itself is kept: a partial(jax.jit, …) captured
            # while installed still routes through _patched_jit and must
            # keep resolving the real jit.
        _INSTALLED = False


def installed() -> bool:
    return _INSTALLED


# ---------------------------------------------------------------------------
# transfer accounting
# ---------------------------------------------------------------------------


def note_transfer(direction: str, nbytes: int) -> None:
    """Record ``nbytes`` moving ``h2d`` or ``d2h`` under the current
    phase. Callers invoke this unconditionally at the module seams; when
    the ledger is not installed it is one attribute check."""
    if not _INSTALLED or nbytes <= 0:
        return
    st = _phase_stack()
    ph = st[-1] if st else "unphased"
    with _STATE_LOCK:
        by_phase = _TRANSFERS.setdefault(direction, {})
        by_phase[ph] = by_phase.get(ph, 0) + int(nbytes)
    fam = _metric(
        "kakveda_transfer_bytes",
        "Host<->device transfer bytes by direction and request phase "
        "(KAKVEDA_LEDGER=1)", ("direction", "phase"),
    )
    if fam is not None:
        fam.labels(direction=direction, phase=ph).inc(int(nbytes))


def mark_warm() -> None:
    """Draw the warmup line: every compile from here on is recorded as a
    ``post_warmup_compile`` flight-recorder event and counted in the
    report (bench rows assert on that count)."""
    global _WARM
    with _STATE_LOCK:
        _WARM = True


def ledger_report() -> dict:
    """Snapshot of everything the ledger has seen (deep-copied)."""
    with _STATE_LOCK:
        compiles = dict(_COMPILES)
        post = [dict(e) for e in _POST_WARMUP]
        transfers = {d: dict(p) for d, p in _TRANSFERS.items()}
        warm = _WARM
    return {
        "enabled": enabled(),
        "installed": _INSTALLED,
        "warm": warm,
        "compiles": compiles,
        "compile_total": sum(compiles.values()),
        "post_warmup_compiles": len(post),
        "post_warmup": post,
        "transfer_bytes": {
            d: sum(p.values()) for d, p in transfers.items()
        },
        "transfer_by_phase": transfers,
    }


def reset() -> None:
    """Zero the tables and the warm flag (install state is kept)."""
    global _WARM
    with _STATE_LOCK:
        _COMPILES.clear()
        _POST_WARMUP.clear()
        _TRANSFERS.clear()
        _WARM = False
    global _RECORDER
    _RECORDER = None
