"""The in-process metrics plane: registry, Prometheus exposition, flight
recorders.

The platform is *failure intelligence*, so its own serving engine must not
be a black box: spec acceptance, gate transitions, prefix-cache hits and
queue waits were ad-hoc ``spec_stats`` dicts that bench.py sampled once and
threw away. This module is the shared substrate every subsystem reports
through — dependency-free (no prometheus_client; the container must not
grow a dependency for its own introspection) and cheap enough for the
decode hot path (one uncontended lock acquire + a float add per update;
bound label children are resolved ONCE at construction, never per event —
see ``models/serving.py``).

Three layers:

* **Registry** (:class:`MetricsRegistry`): counters, gauges, histograms
  with fixed log-spaced buckets, label support, thread-safe updates and a
  consistent :meth:`~MetricsRegistry.snapshot`. One process-global default
  (:func:`get_registry`); tests build private instances.
* **Exposition**: :meth:`MetricsRegistry.render` emits Prometheus text
  format (``# HELP``/``# TYPE``, escaped labels, cumulative ``_bucket``
  series ending in ``+Inf``). Served at ``GET /metrics`` by both the
  service and dashboard apps (kakveda_tpu/service/app.py).
* **Flight recorder** (:class:`FlightRecorder`): a bounded ring of recent
  request timelines and gate/k transitions per serving engine, dumpable as
  JSON via ``GET /flightrecorder`` and automatically on engine error —
  "stochastic 500 in the playground" postmortems become one fetch instead
  of log archaeology.

The well-known metric families (serving TTFT, tokens/s, gate state, …) are
pre-declared on the default registry so a scrape is self-describing —
HELP/TYPE lines appear before the first request ever decodes.

Knobs: ``KAKVEDA_METRICS_RECORDER`` — flight-recorder ring capacity per
engine (default 256; 0 disables recording but keeps the dump endpoints).
"""

from __future__ import annotations

import json
import os
import re
import threading
import time
import weakref
from bisect import bisect_left
from collections import OrderedDict
from typing import Dict, Iterable, List, Optional, Tuple
from kakveda_tpu.core import sanitize

__all__ = [
    "Counter",
    "Gauge",
    "Histogram",
    "MetricsRegistry",
    "FlightRecorder",
    "get_registry",
    "dump_recorders",
    "device_block",
    "TIME_BUCKETS",
    "RATE_BUCKETS",
    "PROMETHEUS_CONTENT_TYPE",
]

PROMETHEUS_CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"

# Fixed log-spaced buckets (1-2.5-5 per decade). TIME_BUCKETS spans 100 µs
# (a cheap host hop) to 100 s (a wedged remote dispatch); RATE_BUCKETS spans
# 1 tok/s (a struggling solo decode) to 100k tok/s (a saturated pool).
TIME_BUCKETS: Tuple[float, ...] = (
    0.0001, 0.00025, 0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05,
    0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 25.0, 50.0, 100.0,
)
RATE_BUCKETS: Tuple[float, ...] = (
    1.0, 2.5, 5.0, 10.0, 25.0, 50.0, 100.0, 250.0, 500.0,
    1000.0, 2500.0, 5000.0, 10000.0, 25000.0, 50000.0, 100000.0,
)


def _fmt(v: float) -> str:
    """Prometheus sample value: integers render bare, floats via repr."""
    if v == int(v) and abs(v) < 1e15:
        return str(int(v))
    return repr(float(v))


def _fmt_exemplar(ex: Optional[Tuple[str, float, float]]) -> str:
    """OpenMetrics exemplar suffix for a bucket line: a trace id linking
    the bucket to one recent observation ('' when the bucket has none)."""
    if not ex:
        return ""
    trace_id, v, ts = ex
    return f' # {{trace_id="{_escape_label(trace_id)}"}} {_fmt(v)} {ts:.3f}'


def _escape_label(v: str) -> str:
    return str(v).replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")


def _escape_help(v: str) -> str:
    return str(v).replace("\\", "\\\\").replace("\n", "\\n")


class _Family:
    """One named metric family: shared lock, labelnames, label children.

    Children are created on first :meth:`labels` call and cached — hot
    paths resolve their bound child once and keep it, so a per-event
    update is a lock + an add, never a dict lookup over label tuples.
    """

    kind = "untyped"

    def __init__(self, name: str, help: str, labelnames: Tuple[str, ...]):
        self.name = name
        self.help = help
        self.labelnames = tuple(labelnames)
        self._lock = threading.Lock()
        self._children: Dict[Tuple[str, ...], object] = {}

    def _child_cls(self):  # pragma: no cover - overridden
        raise NotImplementedError

    def labels(self, **kv):
        if set(kv) != set(self.labelnames):
            raise ValueError(
                f"{self.name}: got labels {sorted(kv)}, wants {sorted(self.labelnames)}"
            )
        key = tuple(str(kv[n]) for n in self.labelnames)
        with self._lock:
            child = self._children.get(key)
            if child is None:
                child = self._child_cls()(self)
                self._children[key] = child
        return child

    def _default(self):
        """The no-label child — lets `reg.counter(...).inc()` work for
        label-free families without an empty labels() call."""
        if self.labelnames:
            raise ValueError(f"{self.name} has labels {self.labelnames}; use .labels()")
        return self.labels()

    def _series(self) -> List[Tuple[Tuple[str, ...], object]]:
        with self._lock:
            return list(self._children.items())

    def _label_str(self, key: Tuple[str, ...], extra: str = "") -> str:
        parts = [
            f'{n}="{_escape_label(v)}"' for n, v in zip(self.labelnames, key)
        ]
        if extra:
            parts.append(extra)
        return "{" + ",".join(parts) + "}" if parts else ""


class _CounterChild:
    __slots__ = ("_lock", "value")

    def __init__(self, family: "_Family"):
        self._lock = family._lock
        self.value = 0.0

    def inc(self, v: float = 1.0) -> None:
        with self._lock:
            self.value += v


class Counter(_Family):
    kind = "counter"

    def _child_cls(self):
        return _CounterChild

    def inc(self, v: float = 1.0) -> None:
        self._default().inc(v)


class _GaugeChild:
    __slots__ = ("_lock", "value")

    def __init__(self, family: "_Family"):
        self._lock = family._lock
        self.value = 0.0

    def set(self, v: float) -> None:
        with self._lock:
            self.value = float(v)

    def inc(self, v: float = 1.0) -> None:
        with self._lock:
            self.value += v

    def dec(self, v: float = 1.0) -> None:
        self.inc(-v)


class Gauge(_Family):
    kind = "gauge"

    def _child_cls(self):
        return _GaugeChild

    def set(self, v: float) -> None:
        self._default().set(v)

    def inc(self, v: float = 1.0) -> None:
        self._default().inc(v)

    def dec(self, v: float = 1.0) -> None:
        self._default().dec(v)


class _HistogramChild:
    __slots__ = ("_lock", "_bounds", "counts", "sum", "count", "exemplars")

    def __init__(self, family: "Histogram"):
        self._lock = family._lock
        self._bounds = family.buckets
        self.counts = [0] * (len(self._bounds) + 1)  # last = overflow (+Inf only)
        self.sum = 0.0
        self.count = 0
        # Bucket idx → (trace_id, value, ts): one exemplar per bucket,
        # last-write-wins — bounded by the bucket count, so "warn p95" is
        # one click from its worst recent trace without growing the child.
        self.exemplars: Dict[int, Tuple[str, float, float]] = {}

    def observe(self, v: float, exemplar: Optional[str] = None) -> None:
        v = float(v)
        idx = bisect_left(self._bounds, v)
        with self._lock:
            self.counts[idx] += 1
            self.sum += v
            self.count += 1
            if exemplar:
                self.exemplars[idx] = (str(exemplar), v, time.time())


class Histogram(_Family):
    kind = "histogram"

    def __init__(
        self,
        name: str,
        help: str,
        labelnames: Tuple[str, ...],
        buckets: Iterable[float] = TIME_BUCKETS,
    ):
        super().__init__(name, help, labelnames)
        bs = tuple(sorted(float(b) for b in buckets))
        if not bs:
            raise ValueError(f"{name}: histogram needs at least one bucket")
        self.buckets = bs

    def _child_cls(self):
        return _HistogramChild

    def observe(self, v: float, exemplar: Optional[str] = None) -> None:
        self._default().observe(v, exemplar=exemplar)


class MetricsRegistry:
    """Name → family store with get-or-create semantics: every subsystem
    calls ``counter/gauge/histogram`` with the same (name, labelnames) and
    gets the same family back — re-registration with a different shape is
    a programming error and raises."""

    def __init__(self, preregister: bool = True):
        self._lock = threading.Lock()
        self._families: Dict[str, _Family] = {}
        if preregister:
            for kind, name, help, labels, buckets in _CORE_FAMILIES:
                if kind == "counter":
                    self.counter(name, help, labels)
                elif kind == "gauge":
                    self.gauge(name, help, labels)
                else:
                    self.histogram(name, help, labels, buckets=buckets or TIME_BUCKETS)

    def _get_or_create(self, cls, name, help, labelnames, **kw) -> _Family:
        labelnames = tuple(labelnames)
        with self._lock:
            fam = self._families.get(name)
            if fam is not None:
                if not isinstance(fam, cls) or fam.labelnames != labelnames:
                    raise ValueError(
                        f"metric {name!r} re-registered as {cls.kind} {labelnames} "
                        f"but exists as {fam.kind} {fam.labelnames}"
                    )
                return fam
            fam = cls(name, help, labelnames, **kw)
            self._families[name] = fam
            return fam

    def counter(self, name: str, help: str, labelnames: Iterable[str] = ()) -> Counter:
        return self._get_or_create(Counter, name, help, labelnames)

    def gauge(self, name: str, help: str, labelnames: Iterable[str] = ()) -> Gauge:
        return self._get_or_create(Gauge, name, help, labelnames)

    def histogram(
        self,
        name: str,
        help: str,
        labelnames: Iterable[str] = (),
        buckets: Iterable[float] = TIME_BUCKETS,
    ) -> Histogram:
        return self._get_or_create(Histogram, name, help, labelnames, buckets=buckets)

    # --- exposition -----------------------------------------------------

    def render(self) -> str:
        """Prometheus text format. Families render in registration order;
        a family with no children still emits HELP/TYPE (the scrape is
        self-describing before the first event)."""
        with self._lock:
            fams = list(self._families.values())
        out: List[str] = []
        for fam in fams:
            out.append(f"# HELP {fam.name} {_escape_help(fam.help)}")
            out.append(f"# TYPE {fam.name} {fam.kind}")
            for key, child in fam._series():
                if isinstance(child, _HistogramChild):
                    # Read a consistent view under the family lock; the
                    # cumulative sums are computed from that snapshot, so a
                    # concurrent observe can never break monotonicity.
                    with fam._lock:
                        counts = list(child.counts)
                        s, c = child.sum, child.count
                        exemplars = dict(child.exemplars)
                    acc = 0
                    for i, (bound, n) in enumerate(zip(fam.buckets, counts)):
                        acc += n
                        le = 'le="%s"' % _fmt(bound)
                        out.append(f"{fam.name}_bucket{fam._label_str(key, le)} {acc}"
                                   + _fmt_exemplar(exemplars.get(i)))
                    inf = 'le="+Inf"'
                    out.append(f"{fam.name}_bucket{fam._label_str(key, inf)} {c}"
                               + _fmt_exemplar(exemplars.get(len(fam.buckets))))
                    out.append(f"{fam.name}_sum{fam._label_str(key)} {_fmt(s)}")
                    out.append(f"{fam.name}_count{fam._label_str(key)} {c}")
                else:
                    out.append(f"{fam.name}{fam._label_str(key)} {_fmt(child.value)}")
        return "\n".join(out) + "\n"

    def snapshot(self, compact: bool = False) -> dict:
        """Plain-dict view for JSON embedding (bench lines, tests). With
        ``compact=True`` zero-valued series and empty families are dropped
        — the shape BENCH_*.json carries per round."""
        with self._lock:
            fams = list(self._families.values())
        snap: dict = {}
        for fam in fams:
            series: dict = {}
            for key, child in fam._series():
                label = ",".join(f"{n}={v}" for n, v in zip(fam.labelnames, key)) or ""
                if isinstance(child, _HistogramChild):
                    with fam._lock:
                        c, s = child.count, child.sum
                        exemplars = dict(child.exemplars)
                    if compact and c == 0:
                        continue
                    series[label] = {"count": c, "sum": round(s, 6)}
                    if exemplars:
                        # Latest exemplar only — the bench line wants "one
                        # click to the worst trace", not the full set.
                        tid, v, _ts = max(exemplars.values(), key=lambda e: e[2])
                        series[label]["exemplar"] = {
                            "trace_id": tid, "value": round(v, 6),
                        }
                else:
                    v = child.value
                    if compact and v == 0:
                        continue
                    series[label] = round(v, 6) if isinstance(v, float) else v
            if series or not compact:
                snap[fam.name] = {"type": fam.kind, "series": series}
        return snap


# --- the default registry + the pre-declared catalog -----------------------

# (kind, name, help, labelnames, buckets-or-None). Declared up front so a
# bare-process scrape already names the serving TTFT / tokens-per-second /
# gate-state families — and so there is ONE place the shapes live; the
# instrumentation sites get-or-create against these.
_CORE_FAMILIES = (
    ("histogram", "kakveda_serving_queue_wait_seconds",
     "Submit-to-admission wait in the serving engine queue", ("engine",), None),
    ("histogram", "kakveda_serving_prefill_seconds",
     "Admission prefill dispatch wall per request", ("engine",), None),
    ("histogram", "kakveda_serving_ttft_seconds",
     "Submit-to-first-token latency per request", ("engine",), None),
    ("histogram", "kakveda_serving_request_seconds",
     "Submit-to-completion wall per request", ("engine",), None),
    ("histogram", "kakveda_serving_tokens_per_second",
     "Per-request decode rate (tokens / request wall)", ("engine",), RATE_BUCKETS),
    ("histogram", "kakveda_serving_chunk_seconds",
     "Effective decode-chunk wall (dispatch to process, overlapped under "
     "pipelining)", ("engine", "flavor"), None),
    ("counter", "kakveda_serving_requests_total",
     "Serving requests by outcome", ("engine", "outcome"), None),
    ("counter", "kakveda_serving_tokens_total",
     "Decode tokens emitted to callers", ("engine",), None),
    ("counter", "kakveda_serving_spec_drafted_total",
     "Speculative draft tokens sent to verify chunks", ("engine",), None),
    ("counter", "kakveda_serving_spec_accepted_total",
     "Speculative draft tokens accepted by verify chunks", ("engine",), None),
    ("gauge", "kakveda_serving_spec_gate_state",
     "1 for the pool's current speculation gate state "
     "(disabled|warmup|on|off)", ("engine", "state"), None),
    ("counter", "kakveda_serving_gate_transitions_total",
     "Speculation auto-gate state transitions", ("engine", "from", "to"), None),
    ("gauge", "kakveda_serving_spec_k",
     "Pool verify width of the most recent speculative chunk", ("engine",), None),
    ("counter", "kakveda_serving_prefix_requests_total",
     "Admissions by prefix-cache result", ("engine", "result"), None),
    ("gauge", "kakveda_serving_active_slots",
     "Occupied slots in the continuous-batching pool", ("engine",), None),
    ("gauge", "kakveda_serving_slots",
     "Total slots in the continuous-batching pool", ("engine",), None),
    ("counter", "kakveda_serving_engine_errors_total",
     "Serving-engine loop deaths (flight recorder dumped on each)",
     ("engine",), None),
    ("counter", "kakveda_serving_engine_restarts_total",
     "Supervisor restarts of a serving-engine loop after a crash (bounded "
     "by KAKVEDA_SERVE_RESTARTS)", ("engine",), None),
    ("counter", "kakveda_ingest_traces_total",
     "Traces classified by the intelligence pipeline", (), None),
    ("counter", "kakveda_ingest_failures_total",
     "Failure signals detected by the classifier tier", (), None),
    ("histogram", "kakveda_ingest_batch_seconds",
     "Classify+embed+insert wall per ingest batch", (), None),
    ("counter", "kakveda_warn_requests_total",
     "Pre-flight warn verdicts by action", ("action",), None),
    ("histogram", "kakveda_mine_update_seconds",
     "Incremental cluster-state update wall per drained delta batch", (), None),
    ("gauge", "kakveda_mine_clusters",
     "Live clusters in the incremental mining state", (), None),
    ("counter", "kakveda_mine_attach_total",
     "Rows attached to the incremental cluster state by neighbor source",
     ("source",), None),
    ("counter", "kakveda_mine_merges_total",
     "Cluster merges performed by incremental attachment", (), None),
    ("counter", "kakveda_mine_sweeps_total",
     "Pattern-mining sweeps by mode", ("mode",), None),
    ("histogram", "kakveda_warn_batch_seconds",
     "Device kNN match wall per warn batch", (), None),
    ("counter", "kakveda_bus_events_published_total",
     "Events published on the in-process bus", ("topic",), None),
    ("counter", "kakveda_bus_deliveries_total",
     "Bus deliveries by result", ("result",), None),
    ("gauge", "kakveda_bus_inflight_deliveries",
     "Bus deliveries currently in flight", (), None),
    ("counter", "kakveda_bus_delivery_attempts_total",
     "URL delivery attempts by result (ok|retry|failed|short_circuit)",
     ("result",), None),
    ("counter", "kakveda_bus_breaker_transitions_total",
     "Bus circuit-breaker state transitions", ("to",), None),
    ("gauge", "kakveda_bus_breaker_open",
     "URL subscribers whose circuit breaker is currently open", (), None),
    ("counter", "kakveda_bus_dlq_total",
     "Events dead-lettered after retries were exhausted or the breaker "
     "short-circuited", (), None),
    ("counter", "kakveda_faults_injected_total",
     "Injected faults by site (KAKVEDA_FAULTS chaos harness)", ("site",), None),
    ("gauge", "kakveda_admission_inflight",
     "In-flight (admitted, not yet released) requests per admission class",
     ("klass",), None),
    ("counter", "kakveda_admission_admitted_total",
     "Requests admitted per admission class", ("klass",), None),
    ("counter", "kakveda_admission_shed_total",
     "Requests shed by admission control, by class and reason "
     "(queue_full|brownout|deadline|degraded|ratelimit)",
     ("klass", "reason"), None),
    ("histogram", "kakveda_admission_wait_seconds",
     "Observed downstream queue wait per admission class (feeds "
     "deadline-aware shedding)", ("klass",), None),
    ("gauge", "kakveda_brownout_state",
     "1 on the brownout ladder's current step "
     "(normal|no_spec|clamped|shed_background|shed_interactive)",
     ("state",), None),
    ("counter", "kakveda_brownout_transitions_total",
     "Brownout ladder step transitions", ("from", "to"), None),
    ("gauge", "kakveda_device_degraded",
     "1 while the accelerator backend is latched DEGRADED (device-loss "
     "mode: host-fallback warn, fail-fast generation)", (), None),
    ("counter", "kakveda_device_degraded_transitions_total",
     "Degraded-mode latch transitions", ("to",), None),
    ("counter", "kakveda_device_probe_total",
     "Backend recovery-probe attempts by result", ("result",), None),
    ("counter", "kakveda_warn_fallback_total",
     "Warn verdicts served by the host-side fallback index while the "
     "backend is degraded", (), None),
    ("gauge", "kakveda_microbatch_queue_depth",
     "Requests waiting in a micro-batcher queue", ("batcher",), None),
    ("histogram", "kakveda_microbatch_batch_size",
     "Coalesced batch size per micro-batcher drain", ("batcher",),
     (1.0, 2.0, 4.0, 8.0, 16.0, 32.0, 64.0, 128.0)),
    ("gauge", "kakveda_hbm_budget_bytes",
     "Configured HBM weight+KV budget (0 = unbudgeted)", (), None),
    ("gauge", "kakveda_hbm_loaded_bytes",
     "Resident weight+KV bytes accounted by the model router", (), None),
    ("histogram", "kakveda_device_block_seconds",
     "Host wall of profiling.annotate()-labeled device blocks, keyed by "
     "annotation name", ("name",), None),
    ("counter", "kakveda_compile_total",
     "XLA backend compiles attributed per jit entry point "
     "(KAKVEDA_LEDGER=1)", ("fn",), None),
    ("counter", "kakveda_transfer_bytes",
     "Host<->device transfer bytes by direction and request phase "
     "(KAKVEDA_LEDGER=1)", ("direction", "phase"), None),
)

_REGISTRY = MetricsRegistry()


def get_registry() -> MetricsRegistry:
    return _REGISTRY


_DEVICE_HIST: Optional[Histogram] = None


def device_block(name: str, seconds: float) -> None:
    """Observe one profiling.annotate block's host wall — the bridge that
    keys XPlane annotation names to metric label values, so the kNN device
    time an operator sees in a profile and the one on /metrics share a
    vocabulary."""
    global _DEVICE_HIST
    h = _DEVICE_HIST
    if h is None:
        h = _DEVICE_HIST = _REGISTRY.histogram(
            "kakveda_device_block_seconds",
            "Host wall of profiling.annotate()-labeled device blocks, keyed "
            "by annotation name",
            ("name",),
        )
    h.labels(name=name).observe(seconds)


# --- flight recorder --------------------------------------------------------

# Every live recorder registers here so the dump endpoints can enumerate
# them without the HTTP layer knowing which engines exist. WeakSet: a
# closed engine's recorder disappears with it, no unregister protocol.
_RECORDERS: "weakref.WeakSet[FlightRecorder]" = weakref.WeakSet()


class FlightRecorder:
    """Bounded ring of structured events (request timelines, gate/k
    transitions). Append is a lock + deque append; the ring survives any
    number of dumps and overwrites oldest-first at capacity
    (``KAKVEDA_METRICS_RECORDER``, default 256 events)."""

    def __init__(self, name: str, capacity: Optional[int] = None):
        if capacity is None:
            capacity = int(os.environ.get("KAKVEDA_METRICS_RECORDER", "256"))
        self.name = name
        self.capacity = max(0, capacity)
        self._lock = sanitize.named_lock("FlightRecorder._lock")
        self._events: List[dict] = []
        _RECORDERS.add(self)

    def record(self, kind: str, **fields) -> None:
        if self.capacity <= 0:
            return
        evt = {"kind": kind, "t": round(time.time(), 6), **fields}
        with self._lock:
            self._events.append(evt)
            if len(self._events) > self.capacity:
                del self._events[: len(self._events) - self.capacity]

    def dump(self) -> List[dict]:
        with self._lock:
            return [dict(e) for e in self._events]

    def dump_json(self) -> str:
        return json.dumps({"name": self.name, "events": self.dump()})


def dump_recorders() -> List[dict]:
    """Every live recorder's ring, oldest events first — the payload of
    ``GET /flightrecorder`` on both HTTP apps."""
    recs = sorted(_RECORDERS, key=lambda r: r.name)
    return [{"name": r.name, "events": r.dump()} for r in recs]


# --- fleet federation -------------------------------------------------------

_SAMPLE_RE = re.compile(
    r"^([a-zA-Z_:][a-zA-Z0-9_:]*)(\{[^}]*\})?\s+(\S+)(?:\s+#.*)?$"
)


def parse_prometheus_text(text: str) -> "OrderedDict[str, dict]":
    """Parse our own exposition format back into families — the inverse of
    :meth:`MetricsRegistry.render`, for router-side federation. Returns
    family name → ``{"type", "help", "samples": [(sample_name, labelstr,
    value)]}`` with labelstr the raw ``{…}`` part ('' when unlabeled).
    Exemplar suffixes are dropped (sums across replicas cannot keep a
    single trace id honest). Unparseable lines are skipped — a replica
    mid-restart must not take the fleet scrape down."""
    fams: "OrderedDict[str, dict]" = OrderedDict()

    def fam_for(sample_name: str) -> dict:
        base = sample_name
        for suffix in ("_bucket", "_sum", "_count"):
            if base.endswith(suffix) and base[: -len(suffix)] in fams:
                base = base[: -len(suffix)]
                break
        return fams.setdefault(
            base, {"type": "untyped", "help": "", "samples": []}
        )

    for line in text.splitlines():
        line = line.strip()
        if not line:
            continue
        if line.startswith("# HELP "):
            parts = line.split(" ", 3)
            if len(parts) == 4:
                fams.setdefault(
                    parts[2], {"type": "untyped", "help": "", "samples": []}
                )["help"] = parts[3]
            continue
        if line.startswith("# TYPE "):
            parts = line.split(" ", 3)
            if len(parts) == 4:
                fams.setdefault(
                    parts[2], {"type": "untyped", "help": "", "samples": []}
                )["type"] = parts[3]
            continue
        if line.startswith("#"):
            continue
        m = _SAMPLE_RE.match(line)
        if m is None:
            continue
        name, labels, raw = m.group(1), m.group(2) or "", m.group(3)
        try:
            value = float(raw)
        except ValueError:
            continue
        fam_for(name)["samples"].append((name, labels, value))
    return fams


def _with_replica_label(labels: str, replica: str) -> str:
    tag = f'replica="{_escape_label(replica)}"'
    if not labels:
        return "{%s}" % tag
    inner = labels[1:-1].strip()
    return "{%s}" % (f"{inner},{tag}" if inner else tag)


def federate_renders(per_replica: Dict[str, str]) -> str:
    """Merge N processes' ``/metrics`` texts into ONE exposition — the
    router's ``GET /metrics/fleet``. Counters and histogram series
    (``_bucket``/``_sum``/``_count``) SUM across replicas by (sample,
    labels) — every process runs the same code, so bucket bounds agree by
    construction. Gauges are NOT summable (an occupancy averaged over the
    fleet hides the hot replica), so each gauge sample instead gains a
    ``replica="<id>"`` label. Family order follows the first replica that
    exposes each family."""
    order: List[str] = []
    merged: Dict[str, dict] = {}
    for rid in sorted(per_replica):
        for name, fam in parse_prometheus_text(per_replica[rid]).items():
            tgt = merged.get(name)
            if tgt is None:
                tgt = merged[name] = {
                    "type": fam["type"], "help": fam["help"],
                    "sums": OrderedDict(), "gauges": [],
                }
                order.append(name)
            if fam["type"] != "untyped" and tgt["type"] == "untyped":
                tgt["type"] = fam["type"]
            if fam["help"] and not tgt["help"]:
                tgt["help"] = fam["help"]
            summable = tgt["type"] in ("counter", "histogram")
            for sample, labels, value in fam["samples"]:
                if summable:
                    key = (sample, labels)
                    tgt["sums"][key] = tgt["sums"].get(key, 0.0) + value
                else:
                    tgt["gauges"].append(
                        (sample, _with_replica_label(labels, rid), value)
                    )
    out: List[str] = []
    for name in order:
        fam = merged[name]
        if fam["help"]:
            out.append(f"# HELP {name} {fam['help']}")
        out.append(f"# TYPE {name} {fam['type'] if fam['type'] != 'untyped' else 'gauge'}")
        for (sample, labels), value in fam["sums"].items():
            out.append(f"{sample}{labels} {_fmt(value)}")
        for sample, labels, value in fam["gauges"]:
            out.append(f"{sample}{labels} {_fmt(value)}")
    return "\n".join(out) + "\n"
