"""Best-effort OpenTelemetry bootstrap.

Capability parity with the reference's otel module (reference:
services/shared/otel.py:6-59): an OTLP span exporter plus per-request
server spans, enabled only when ``KAKVEDA_OTEL_ENABLED`` is truthy and
degrading to a no-op when the SDK (or the exporter endpoint) is absent —
observability must never take the service down.

The reference instruments FastAPI; the server here is aiohttp, so
instrumentation is an explicit middleware (``otel_middleware``) that opens
one server span per request, records method/route/status, and marks 5xx as
errors. TPU-side kernel profiling is separate (``jax.profiler`` — see
kakveda_tpu/platform.py profiling hooks); OTel covers the host plane.
"""

from __future__ import annotations

import logging
from typing import Any, Optional

from kakveda_tpu.core.runtime import get_runtime_config

log = logging.getLogger("kakveda.otel")

_tracer: Optional[Any] = None
_setup_done = False


def setup_otel(service_name: str) -> bool:
    """Install a tracer provider with an OTLP exporter. Returns enabled?"""
    global _tracer, _setup_done
    if _setup_done:
        return _tracer is not None
    _setup_done = True
    cfg = get_runtime_config(service_name=service_name)
    if not cfg.otel_enabled:
        return False
    try:
        from opentelemetry import trace
        from opentelemetry.sdk.resources import Resource
        from opentelemetry.sdk.trace import TracerProvider
        from opentelemetry.sdk.trace.export import BatchSpanProcessor

        provider = TracerProvider(
            resource=Resource.create({"service.name": cfg.otel_service_name})
        )
        if cfg.otel_exporter_otlp_endpoint:
            try:
                from opentelemetry.exporter.otlp.proto.http.trace_exporter import (
                    OTLPSpanExporter,
                )

                provider.add_span_processor(
                    BatchSpanProcessor(
                        OTLPSpanExporter(endpoint=cfg.otel_exporter_otlp_endpoint)
                    )
                )
            except Exception as e:  # noqa: BLE001 — exporter is optional
                log.warning("otel exporter unavailable: %s", e)
        trace.set_tracer_provider(provider)
        _tracer = trace.get_tracer("kakveda-tpu")
        log.info("otel enabled (service=%s)", cfg.otel_service_name)
        return True
    except Exception as e:  # noqa: BLE001 — never block startup on otel
        log.warning("otel disabled: %s", e)
        return False


def get_tracer() -> Optional[Any]:
    return _tracer


def add_span_events(name: str, payload: Optional[dict]) -> None:
    """Attach a flat payload (e.g. the serving engine's request timeline)
    to the CURRENT server span as one event, so traces and /metrics
    correlate by request id. No-op without otel, a recording span, or a
    payload — observability never breaks the request path."""
    if _tracer is None or not payload:
        return
    try:
        from opentelemetry import trace

        span = trace.get_current_span()
        if span is None or not span.is_recording():
            return
        span.add_event(
            name,
            {
                k: v
                for k, v in payload.items()
                if isinstance(v, (str, bool, int, float))
            },
        )
    except Exception:  # noqa: BLE001 — telemetry must not take the request down
        pass


def export_native_span(span: dict) -> None:
    """Bridge ONE finished native span (core/trace.py ring dict) into the
    OTel SDK — called by the native tracer's record path only when
    ``KAKVEDA_OTEL_ENABLED`` stood setup up (``_tracer`` set), so the off
    path stays a single None check and zero import. The native trace id is
    attached as attributes (``kakveda.trace_id``/``span_id``/``parent_id``)
    — the shared 32-hex id is what parents the export under the server
    span in the backend; no new hard dependency, never raises."""
    if _tracer is None or not span:
        return
    try:
        start_ns = int(span.get("ts", 0.0) * 1e9)
        end_ns = start_ns + int(span.get("dur_ms", 0.0) * 1e6)
        ot = _tracer.start_span(span.get("name", "span"), start_time=start_ns)
        try:
            for k in ("trace_id", "span_id", "parent_id", "outcome", "service"):
                v = span.get(k)
                if v:
                    ot.set_attribute(f"kakveda.{k}", str(v))
            for k, v in (span.get("attrs") or {}).items():
                if isinstance(v, (str, bool, int, float)):
                    ot.set_attribute(str(k), v)
            if span.get("outcome") == "error":
                from opentelemetry.trace import Status, StatusCode

                ot.set_status(Status(StatusCode.ERROR))
        finally:
            ot.end(end_time=end_ns)
    except Exception:  # noqa: BLE001 — telemetry must not take the request down
        pass


def otel_middleware():
    """aiohttp middleware: one server span per request (no-op when off).

    The request id is resolved HERE (incoming header or fresh) and stashed
    on the request so the inner request-context middleware reuses it —
    span attribute ``request.id`` and the logged/echoed ``x-request-id``
    are the same value, the correlation key across traces, /metrics
    exemplars and the flight recorder."""
    from aiohttp import web

    from kakveda_tpu.core.runtime import ensure_request_id, get_runtime_config

    @web.middleware
    async def mw(request: web.Request, handler):
        tracer = _tracer
        if tracer is None:
            return await handler(request)
        from opentelemetry.trace import SpanKind, Status, StatusCode

        cfg = get_runtime_config(service_name="kakveda-tpu")
        rid = ensure_request_id(request.headers.get(cfg.request_id_header))
        request["request_id"] = rid
        with tracer.start_as_current_span(
            f"{request.method} {request.path}", kind=SpanKind.SERVER
        ) as span:
            span.set_attribute("http.request.method", request.method)
            span.set_attribute("url.path", request.path)
            span.set_attribute("request.id", rid)
            try:
                response = await handler(request)
            except web.HTTPException as exc:
                span.set_attribute("http.response.status_code", exc.status)
                if exc.status >= 500:
                    span.set_status(Status(StatusCode.ERROR))
                raise
            except Exception as exc:
                span.set_status(Status(StatusCode.ERROR, str(exc)))
                raise
            span.set_attribute("http.response.status_code", response.status)
            if response.status >= 500:
                span.set_status(Status(StatusCode.ERROR))
            return response

    return mw
