"""Device-side profiling hooks (jax.profiler / XPlane).

The reference's tracing is host-only: OTel spans plus per-step TraceSpan
rows rendered as a waterfall (reference: services/dashboard/db.py:320-334,
app.py:2927-2970). The TPU build keeps that span model for the host plane
(kakveda_tpu/core/otel.py, dashboard spans) and adds what the reference
has no equivalent for: XLA-level kernel traces.

- ``annotate(name)``: a TraceAnnotation context that labels enclosed device
  work in the XPlane timeline; used around the hot entry points (GFKB
  match/insert, Llama generate) so profiles read in product terms.
- ``profile(logdir)``: capture a TensorBoard-loadable trace of everything
  inside the block.
- ``KAKVEDA_PROFILE_DIR``: when set, the platform captures a trace of its
  first match + ingest batch at startup — zero-code profiling for
  operators.

All hooks degrade to no-ops off-device or if the profiler is unavailable.
"""

from __future__ import annotations

import contextlib
import logging
import os
from typing import Iterator, Optional

log = logging.getLogger("kakveda.profiling")


@contextlib.contextmanager
def annotate(name: str) -> Iterator[None]:
    """Label enclosed device work in the profiler timeline (no-op safe).

    The block's host wall also lands on the metrics plane
    (``kakveda_device_block_seconds{name=...}``) keyed by this SAME name —
    the annotation an operator sees in an XPlane profile and the series on
    /metrics share a vocabulary, so kNN/decode device time is monitorable
    without capturing a trace."""
    # Only the profiler setup is guarded — the yield must stay outside the
    # try/except, or an exception raised by the *enclosed work* would be
    # thrown into the generator, caught here, and surface as contextlib's
    # "generator didn't stop after throw()" RuntimeError with the real
    # error destroyed.
    annotation = None
    try:
        import jax.profiler

        annotation = jax.profiler.TraceAnnotation(name)
        annotation.__enter__()
    except Exception:  # noqa: BLE001 — profiling must never break the hot path
        annotation = None
    import time as _time

    t0 = _time.perf_counter()
    try:
        yield
    finally:
        if annotation is not None:
            try:
                annotation.__exit__(None, None, None)
            except Exception:  # noqa: BLE001
                pass
        try:
            from kakveda_tpu.core import metrics as _metrics

            _metrics.device_block(name, _time.perf_counter() - t0)
        except Exception:  # noqa: BLE001 — metrics must never break the hot path
            pass


@contextlib.contextmanager
def profile(logdir: str | os.PathLike) -> Iterator[None]:
    """Capture a device trace of the enclosed block into ``logdir``."""
    try:
        import jax.profiler

        jax.profiler.start_trace(str(logdir))
        started = True
    except Exception as e:  # noqa: BLE001
        log.warning("profiler unavailable: %s", e)
        started = False
    try:
        yield
    finally:
        if started:
            try:
                import jax.profiler

                jax.profiler.stop_trace()
                log.info("device trace written to %s", logdir)
            except Exception as e:  # noqa: BLE001
                log.warning("profiler stop failed: %s", e)


def startup_profile_dir() -> Optional[str]:
    return os.environ.get("KAKVEDA_PROFILE_DIR") or None
