"""Fixed-window rate limiter with a distributed (Redis) tier.

Capability parity with the reference's RateLimiter (reference:
services/shared/redis_helpers.py:62-84): INCR + EXPIRE on a per-window key
when ``KAKVEDA_REDIS_URL`` points at a reachable Redis, else an in-memory
fixed-window counter. The in-memory tier sweeps expired windows so keys
derived from client IPs on unauthenticated routes cannot grow unboundedly.

Async callers (aiohttp handlers) must use :meth:`allow_async`, which runs
the Redis round-trips in the executor — the sync client must never block
the event loop. Connection setup is lazy so constructing the limiter at
module import costs nothing.
"""

from __future__ import annotations

import asyncio
import os
import time
from collections import OrderedDict
from typing import Dict, Optional, Tuple

from kakveda_tpu.core import metrics as _metrics

_UNSET = object()


class RateLimiter:
    _SWEEP_EVERY = 1024

    def __init__(self, redis_url: object = _UNSET):
        self._hits: Dict[str, Tuple[float, int]] = {}
        self._calls = 0
        self._redis = None
        # Explicit redis_url=None opts out of Redis even when the env var is
        # set (tests and deliberately-local limiters need that).
        if redis_url is _UNSET:
            self._url: Optional[str] = os.environ.get("KAKVEDA_REDIS_URL")
        else:
            self._url = redis_url  # type: ignore[assignment]
        self._connect_attempted = False

    def _client(self):
        if self._connect_attempted:
            return self._redis
        self._connect_attempted = True
        if not self._url:
            return None
        try:
            import redis  # type: ignore[import-not-found]

            # Sub-second timeout: a slow Redis must cost milliseconds per
            # miss, not seconds.
            self._redis = redis.Redis.from_url(
                self._url, socket_timeout=0.25, socket_connect_timeout=0.25
            )
            self._redis.ping()
        except Exception:  # noqa: BLE001 — fall back to memory
            self._redis = None
        return self._redis

    def allow(self, key: str, limit: int, window_s: float = 60.0) -> bool:
        client = self._client()
        if client is not None:
            try:
                window = int(time.time() // window_s)
                rkey = f"kakveda:rl:{key}:{window}"
                count = client.incr(rkey)
                if count == 1:
                    client.expire(rkey, int(window_s) + 1)
                return int(count) <= limit
            except Exception:  # noqa: BLE001 — degrade to memory permanently:
                # a dead Redis must not tax every subsequent request with a
                # connect timeout for the life of the process.
                self._redis = None
        now = time.time()
        self._calls += 1
        if self._calls % self._SWEEP_EVERY == 0:
            self._hits = {k: v for k, v in self._hits.items() if now - v[0] < window_s}
        start, count = self._hits.get(key, (now, 0))
        if now - start >= window_s:
            start, count = now, 0
        count += 1
        self._hits[key] = (start, count)
        return count <= limit

    async def allow_async(self, key: str, limit: int, window_s: float = 60.0) -> bool:
        """Event-loop-safe entry: Redis round trips (including the lazy
        first connect) run in the executor; the pure in-memory tier is
        answered inline."""
        if self._url and not (self._connect_attempted and self._redis is None):
            loop = asyncio.get_running_loop()
            return await loop.run_in_executor(None, self.allow, key, limit, window_s)
        return self.allow(key, limit, window_s)


class TokenBucket:
    """Per-key token bucket — the smooth-rate tier the ingest and
    playground routes use (``KAKVEDA_RATELIMIT_RPS``).

    The fixed-window :class:`RateLimiter` above admits a full window's
    burst at the window edge; a token bucket refills continuously (``rps``
    tokens/second up to ``burst``), so a client that exceeds its rate is
    told exactly how long until the next token — the ``retry_after``
    second element of :meth:`allow`, which the HTTP tier echoes as a 429
    ``Retry-After`` header in the same shape the admission controller
    sheds with (docs/robustness.md). In-memory only by design: per-client
    smoothing is a node-local concern; cross-fleet quotas stay on the
    Redis fixed-window tier.

    The table is HARD-bounded: the refill sweep drops idle keys, but a
    key-churn flood (1M distinct app ids inside one burst window) would
    still grow it between sweeps, so past ``KAKVEDA_RATELIMIT_MAX_KEYS``
    the least-recently-seen bucket is evicted on insert. Eviction is
    semantics-preserving in the only direction that matters — an evicted
    key re-enters FULL, exactly what its bucket would have refilled to by
    the time a genuinely idle client returns; a churn attacker evicting
    hot keys only ever GRANTS tokens, never wrongly denies. Table size is
    exported on the ``kakveda_tenant_table_size{plane="ratelimit"}``
    gauge.
    """

    _SWEEP_EVERY = 1024

    def __init__(self, rps: float, burst: Optional[float] = None,
                 max_keys: Optional[int] = None):
        if rps <= 0:
            raise ValueError(f"rps must be positive, got {rps}")
        self.rps = float(rps)
        self.burst = float(burst) if burst is not None else max(1.0, 2.0 * rps)
        if max_keys is None:
            try:
                max_keys = int(os.environ.get("KAKVEDA_RATELIMIT_MAX_KEYS", "65536"))
            except ValueError:
                max_keys = 65536
        self.max_keys = max(1, max_keys)
        # key -> (tokens, last_ts), most-recently-seen last (LRU order).
        self._buckets: "OrderedDict[str, Tuple[float, float]]" = OrderedDict()
        self._calls = 0
        self._g_table = _metrics.get_registry().gauge(
            "kakveda_tenant_table_size",
            "Live per-tenant state-table rows per plane (bounded by "
            "KAKVEDA_TENANT_TABLE / KAKVEDA_RATELIMIT_MAX_KEYS)",
            ("plane",),
        ).labels(plane="ratelimit")

    def allow(self, key: str, now: Optional[float] = None) -> Tuple[bool, float]:
        """(admitted, retry_after_s). ``retry_after`` is 0 when admitted,
        else the time until one full token has refilled."""
        if now is None:
            now = time.monotonic()
        self._calls += 1
        if self._calls % self._SWEEP_EVERY == 0:
            # Drop keys whose bucket has fully refilled — idle clients
            # (IP-derived keys on unauthenticated routes) must not leak.
            full_age = self.burst / self.rps
            self._buckets = OrderedDict(
                (k, v) for k, v in self._buckets.items() if now - v[1] < full_age
            )
        entry = self._buckets.get(key)
        if entry is None:
            tokens, last = self.burst, now
            if len(self._buckets) >= self.max_keys:
                self._buckets.popitem(last=False)  # least-recently-seen
        else:
            tokens, last = entry
            self._buckets.move_to_end(key)
        tokens = min(self.burst, tokens + (now - last) * self.rps)
        if tokens >= 1.0:
            self._buckets[key] = (tokens - 1.0, now)
            self._g_table.set(float(len(self._buckets)))
            return True, 0.0
        self._buckets[key] = (tokens, now)
        self._g_table.set(float(len(self._buckets)))
        return False, (1.0 - tokens) / self.rps
