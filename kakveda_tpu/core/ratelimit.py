"""Fixed-window rate limiter with a distributed (Redis) tier.

Capability parity with the reference's RateLimiter (reference:
services/shared/redis_helpers.py:62-84): INCR + EXPIRE on a per-window key
when ``KAKVEDA_REDIS_URL`` points at a reachable Redis, else an in-memory
fixed-window counter. The in-memory tier sweeps expired windows so keys
derived from client IPs on unauthenticated routes cannot grow unboundedly.
"""

from __future__ import annotations

import os
import time
from typing import Dict, Optional, Tuple


class RateLimiter:
    _SWEEP_EVERY = 1024

    def __init__(self, redis_url: Optional[str] = None):
        self._hits: Dict[str, Tuple[float, int]] = {}
        self._calls = 0
        self._redis = None
        url = redis_url or os.environ.get("KAKVEDA_REDIS_URL")
        if url:
            try:
                import redis  # type: ignore[import-not-found]

                # Sub-second timeout: allow() runs synchronously on request
                # paths (including inside an event loop), so a slow Redis
                # must cost milliseconds, not seconds.
                self._redis = redis.Redis.from_url(
                    url, socket_timeout=0.25, socket_connect_timeout=0.25
                )
                self._redis.ping()
            except Exception:  # noqa: BLE001 — fall back to memory
                self._redis = None

    def allow(self, key: str, limit: int, window_s: float = 60.0) -> bool:
        if self._redis is not None:
            try:
                window = int(time.time() // window_s)
                rkey = f"kakveda:rl:{key}:{window}"
                count = self._redis.incr(rkey)
                if count == 1:
                    self._redis.expire(rkey, int(window_s) + 1)
                return int(count) <= limit
            except Exception:  # noqa: BLE001 — degrade to memory permanently:
                # a dead Redis must not tax every subsequent request with a
                # connect timeout for the life of the process.
                self._redis = None
        now = time.time()
        self._calls += 1
        if self._calls % self._SWEEP_EVERY == 0:
            self._hits = {k: v for k, v in self._hits.items() if now - v[0] < window_s}
        start, count = self._hits.get(key, (now, 0))
        if now - start >= window_s:
            start, count = now, 0
        count += 1
        self._hits[key] = (start, count)
        return count <= limit
