"""JWT revocation store — logout actually invalidates the token.

Capability parity with the reference's RevocationStore (reference:
services/shared/redis_helpers.py:26-59): revoked token ids (jti) are held
until their natural expiry, backed by Redis when ``KAKVEDA_REDIS_URL`` is
set and the client library is importable, else an in-memory TTL set (the
reference's fallback tier; fine for the single-process deployment, which
is the default topology here).
"""

from __future__ import annotations

import os
import threading
import time
from typing import Dict, Optional
from kakveda_tpu.core import sanitize


class RevocationStore:
    def __init__(self, redis_url: Optional[str] = None):
        self._mem: Dict[str, float] = {}  # jti -> expiry ts
        self._lock = sanitize.named_lock("RevocationStore._lock")
        self._redis = None
        url = redis_url or os.environ.get("KAKVEDA_REDIS_URL")
        if url:
            try:
                import redis  # type: ignore[import-not-found]

                self._redis = redis.Redis.from_url(url, socket_timeout=2)
                self._redis.ping()
            except Exception:  # noqa: BLE001 — fall back to memory
                self._redis = None

    def revoke(self, jti: str, expires_at: float) -> None:
        """Remember ``jti`` as revoked until ``expires_at`` (unix ts)."""
        ttl = max(1, int(expires_at - time.time()))
        if self._redis is not None:
            try:
                self._redis.setex(f"kakveda:revoked:{jti}", ttl, b"1")
                return
            except Exception:  # noqa: BLE001
                pass
        with self._lock:
            self._sweep_locked()
            self._mem[jti] = expires_at

    def is_revoked(self, jti: str) -> bool:
        if self._redis is not None:
            try:
                return bool(self._redis.exists(f"kakveda:revoked:{jti}"))
            except Exception:  # noqa: BLE001
                pass
        with self._lock:
            exp = self._mem.get(jti)
            if exp is None:
                return False
            if exp <= time.time():
                del self._mem[jti]
                return False
            return True

    def _sweep_locked(self) -> None:
        if len(self._mem) > 4096:
            now = time.time()
            self._mem = {k: v for k, v in self._mem.items() if v > now}
