"""Env-driven runtime configuration, structured logging, request ids.

Capability parity with the reference's runtime kernel
(reference: services/shared/runtime.py:39-142): one frozen RuntimeConfig per
service, JSON structured logs with service/request_id/duration fields, and a
request-id helper. Adds the TPU-runtime knobs (mesh shape, model runtime
selection, index capacity) that have no reference equivalent.
"""

from __future__ import annotations

import json
import logging
import os
import sys
import time
import uuid
from dataclasses import dataclass
from typing import Any, Mapping, Optional


def _env(name: str, default: Optional[str] = None) -> Optional[str]:
    v = os.environ.get(name)
    if v is None:
        return default
    v = str(v).strip()
    return v if v != "" else default


def _env_bool(name: str, default: bool = False) -> bool:
    v = _env(name)
    if v is None:
        return default
    return v.lower() in {"1", "true", "yes", "y", "on"}


def _env_int(name: str, default: int) -> int:
    v = _env(name)
    if v is None:
        return default
    try:
        return int(v)
    except ValueError:
        return default


@dataclass(frozen=True)
class RuntimeConfig:
    env: str
    log_level: str
    log_format: str
    request_id_header: str

    # Security / secrets
    dashboard_jwt_secret: str

    # Storage
    data_dir: str

    # TPU intelligence core
    model_runtime: str  # stub | tpu | ollama
    index_capacity: int
    mesh_shape: str  # e.g. "data:8" or "data:4,model:2"

    # Observability
    otel_enabled: bool
    otel_service_name: str
    otel_exporter_otlp_endpoint: Optional[str]


def get_runtime_config(*, service_name: str) -> RuntimeConfig:
    env = (_env("KAKVEDA_ENV", _env("ENV", "dev")) or "dev").lower()
    return RuntimeConfig(
        env=env,
        log_level=(_env("KAKVEDA_LOG_LEVEL", "INFO") or "INFO").upper(),
        log_format=(_env("KAKVEDA_LOG_FORMAT", "json") or "json").lower(),
        request_id_header=(_env("KAKVEDA_REQUEST_ID_HEADER", "x-request-id") or "x-request-id").lower(),
        dashboard_jwt_secret=_env("DASHBOARD_JWT_SECRET", "dev-secret-change-me") or "dev-secret-change-me",
        data_dir=_env("KAKVEDA_DATA_DIR", "data") or "data",
        model_runtime=(_env("KAKVEDA_MODEL_RUNTIME", "stub") or "stub").lower(),
        index_capacity=_env_int("KAKVEDA_INDEX_CAPACITY", 1 << 17),
        mesh_shape=_env("KAKVEDA_MESH_SHAPE", "data:-1") or "data:-1",
        otel_enabled=_env_bool("KAKVEDA_OTEL_ENABLED", default=False),
        otel_service_name=_env("OTEL_SERVICE_NAME", service_name) or service_name,
        otel_exporter_otlp_endpoint=_env("OTEL_EXPORTER_OTLP_ENDPOINT"),
    )


def _json_record(level: str, msg: str, extra: Optional[Mapping[str, Any]] = None) -> str:
    body: dict[str, Any] = {
        "ts": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
        "level": level,
        "msg": msg,
    }
    if extra:
        for k, v in extra.items():
            if v is not None:
                body[k] = v
    return json.dumps(body, ensure_ascii=False)


class _JsonFormatter(logging.Formatter):
    def __init__(self, service_name: str):
        super().__init__()
        self._service = service_name

    def format(self, record: logging.LogRecord) -> str:
        extra: dict[str, Any] = {"logger": record.name, "service": self._service}
        for key in ("request_id", "path", "method", "status_code", "duration_ms"):
            if hasattr(record, key):
                extra[key] = getattr(record, key)
        if record.exc_info and record.exc_info[0] is not None:
            # Server-side exceptions (aiohttp logs them with exc_info) must
            # reach the JSON stream — a 500 with no traceback in the logs
            # is undebuggable in production.
            extra["exc"] = self.formatException(record.exc_info)
        return _json_record(record.levelname, record.getMessage(), extra)


def setup_logging(*, service_name: str) -> None:
    cfg = get_runtime_config(service_name=service_name)
    root = logging.getLogger()
    root.setLevel(getattr(logging, cfg.log_level, logging.INFO))
    for h in list(root.handlers):
        root.removeHandler(h)
    handler = logging.StreamHandler(stream=sys.stdout)
    if cfg.log_format == "json":
        handler.setFormatter(_JsonFormatter(service_name))
    else:
        handler.setFormatter(logging.Formatter("%(asctime)s %(levelname)s %(name)s: %(message)s"))
    root.addHandler(handler)


def ensure_request_id(incoming: Optional[str] = None) -> str:
    v = (incoming or "").strip()
    if v:
        return v[:128]
    return uuid.uuid4().hex
