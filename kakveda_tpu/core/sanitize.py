"""Runtime concurrency sanitizer: named-lock instrumentation + loop-stall
watchdog (``KAKVEDA_SANITIZE=1``).

The static half of the concurrency pass (:mod:`kakveda_tpu.analysis.
concurrency`) reasons about lock-order from the AST; this module is the
dynamic half. Every long-lived lock in the tree is constructed through
:func:`named_lock` with a stable ``ClassName._attr`` name — the SAME node
id the static lock-order graph uses, so the two graphs cross-check
(``tests/test_sanitize.py`` merges them and asserts the union is acyclic
during a storm drill).

Off by default the factory returns a plain ``threading.Lock``/``RLock`` —
zero overhead, zero behavior change. With ``KAKVEDA_SANITIZE=1`` each
lock is wrapped to record, per process:

* **acquisition-order edges** — for every acquire while other sanitized
  locks are held by the same thread, an (outer, inner) edge with a count
  and the first observed site;
* **hold times and contention** — wait time per acquire (contended past
  1 ms), total/max hold per lock.

The loop-stall watchdog (:class:`LoopStallWatchdog`) is the event-loop
analogue: an asyncio heartbeat task plus a checker daemon thread; when
the heartbeat goes stale past ``KAKVEDA_SANITIZE_STALL_MS`` the loop
thread's current stack is dumped to the ``sanitizer`` flight recorder
(served at ``GET /flightrecorder``) and recorded in
:func:`sanitizer_report` — machine-evidence for "something blocked the
event loop", with the offending frames attached.

Dependency-free by design (stdlib only; the flight recorder import is
lazy) so ``core/faults.py`` and the analysis pass can both import it.
"""

from __future__ import annotations

import os
import sys
import threading
import time
import traceback
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

_TRUTHY = frozenset({"1", "true", "yes", "on"})

# Waits past this count as contention (blocking on a held lock), below it
# as an uncontended fast path that merely paid the wrapper.
_CONTENDED_S = 0.001


def enabled() -> bool:
    """Is the sanitizer armed? Read per lock CONSTRUCTION (not per
    acquire): chaos tests set ``KAKVEDA_SANITIZE=1`` before building the
    objects under test; locks built earlier stay plain."""
    return os.environ.get("KAKVEDA_SANITIZE", "").strip().lower() in _TRUTHY


# ---------------------------------------------------------------------------
# process-global sanitizer state
# ---------------------------------------------------------------------------

# Guards the tables below. A raw lock ON PURPOSE: the sanitizer must never
# instrument itself (acquiring a sanitized lock inside _note_acquire would
# recurse) and never appear in its own edge graph.
_STATE_LOCK = threading.Lock()
# (outer, inner) -> {"count": n, "site": "thread-name"}
_EDGES: Dict[Tuple[str, str], Dict[str, object]] = {}
# name -> {"acquisitions", "contended", "wait_ms_total", "hold_ms_total", "hold_ms_max"}
_LOCK_STATS: Dict[str, Dict[str, float]] = {}
# Loop-stall events appended by any live watchdog.
_STALLS: List[dict] = []

_TLS = threading.local()

_RECORDER = None  # lazy FlightRecorder("sanitizer")


def _recorder():
    global _RECORDER
    if _RECORDER is None:
        from kakveda_tpu.core import metrics as _metrics

        _RECORDER = _metrics.FlightRecorder("sanitizer")
    return _RECORDER


def _held() -> List[Tuple[str, Optional[float]]]:
    """This thread's stack of held sanitized locks: (name, t_acquired);
    ``t_acquired`` is None for reentrant re-acquisitions (no hold
    accounting, no self-edges)."""
    stack = getattr(_TLS, "held", None)
    if stack is None:
        stack = _TLS.held = []
    return stack


def _note_acquire(name: str, held_names: Iterable[str], wait_s: float) -> None:
    with _STATE_LOCK:
        st = _LOCK_STATS.setdefault(name, {
            "acquisitions": 0, "contended": 0, "wait_ms_total": 0.0,
            "hold_ms_total": 0.0, "hold_ms_max": 0.0,
        })
        st["acquisitions"] += 1
        st["wait_ms_total"] += wait_s * 1000.0
        if wait_s >= _CONTENDED_S:
            st["contended"] += 1
        for outer in held_names:
            if outer == name:
                continue
            e = _EDGES.setdefault((outer, name), {
                "count": 0, "site": threading.current_thread().name,
            })
            e["count"] += 1  # type: ignore[operator]


def _note_release(name: str, t_acquired: float) -> None:
    hold_ms = (time.monotonic() - t_acquired) * 1000.0
    with _STATE_LOCK:
        st = _LOCK_STATS.get(name)
        if st is not None:
            st["hold_ms_total"] += hold_ms
            if hold_ms > st["hold_ms_max"]:
                st["hold_ms_max"] = hold_ms


class SanitizedLock:
    """Lock wrapper recording order edges, waits and holds. Duck-types the
    ``threading.Lock``/``RLock`` surface the tree uses (``with``,
    ``acquire(blocking, timeout)``, ``release``, ``locked``) and stays
    ``threading.Condition``-compatible (Condition only needs
    acquire/release and probes ownership via ``acquire(False)``)."""

    __slots__ = ("name", "_inner")

    def __init__(self, name: str, inner):
        self.name = name
        self._inner = inner

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        held = _held()
        reentrant = any(n == self.name for n, _ in held)
        t0 = time.monotonic()
        got = self._inner.acquire(blocking, timeout)
        if not got:
            return False
        if reentrant:
            # RLock re-entry: no new edges, hold attributed to the
            # outermost acquire only.
            held.append((self.name, None))
        else:
            _note_acquire(self.name, [n for n, _ in held], time.monotonic() - t0)
            held.append((self.name, time.monotonic()))
        return True

    def release(self) -> None:
        self._inner.release()
        held = _held()
        for i in range(len(held) - 1, -1, -1):
            if held[i][0] == self.name:
                _, t_acq = held.pop(i)
                if t_acq is not None:
                    _note_release(self.name, t_acq)
                return

    def __enter__(self) -> "SanitizedLock":
        self.acquire()
        return self

    def __exit__(self, *exc) -> bool:
        self.release()
        return False

    def locked(self) -> bool:
        locked = getattr(self._inner, "locked", None)
        if locked is not None:
            return bool(locked())
        # RLock pre-3.12 has no locked(); probe like Condition does.
        if self._inner.acquire(False):
            self._inner.release()
            return False
        return True


def named_lock(name: str, kind: str = "lock"):
    """Construct one of the tree's long-lived locks under a stable name.

    ``name`` MUST match the static analyzer's node id for the same lock
    (``ClassName._attr`` for instance locks, ``module._name`` for
    module-level ones) — that equality is what lets the runtime edge set
    cross-check against the static lock-order graph. Returns a plain
    ``threading.Lock``/``RLock`` unless ``KAKVEDA_SANITIZE`` is armed."""
    inner = threading.RLock() if kind == "rlock" else threading.Lock()
    if not enabled():
        return inner
    return SanitizedLock(name, inner)


# ---------------------------------------------------------------------------
# lock-order graph over the recorded edges
# ---------------------------------------------------------------------------


def find_cycles(edges: Iterable[Tuple[str, str]]) -> List[List[str]]:
    """Cycles in a directed edge set, each as the node path closing on its
    first node (``[a, b, a]``). Deterministic order; shared by the static
    lock-order rule and :func:`sanitizer_report`."""
    adj: Dict[str, set] = {}
    for a, b in edges:
        adj.setdefault(a, set()).add(b)
        adj.setdefault(b, set())
    color: Dict[str, int] = {}  # 0/absent=unvisited, 1=on stack, 2=done
    cycles: List[List[str]] = []

    def dfs(n: str, path: List[str]) -> None:
        color[n] = 1
        path.append(n)
        for m in sorted(adj.get(n, ())):
            c = color.get(m, 0)
            if c == 1:
                cycles.append(path[path.index(m):] + [m])
            elif c == 0:
                dfs(m, path)
        path.pop()
        color[n] = 2

    for n in sorted(adj):
        if color.get(n, 0) == 0:
            dfs(n, [])
    return cycles


def lock_order_edges() -> List[Tuple[str, str]]:
    """The distinct (outer, inner) acquisition-order edges observed so
    far, sorted."""
    with _STATE_LOCK:
        return sorted(_EDGES)


def record_stall(stall_ms: float, stack: str, where: str = "loop") -> None:
    evt = {
        "t": round(time.time(), 6), "stall_ms": round(stall_ms, 3),
        "where": where, "stack": stack,
    }
    with _STATE_LOCK:
        _STALLS.append(evt)
        if len(_STALLS) > 256:
            del _STALLS[0]
    try:
        _recorder().record("loop_stall", stall_ms=evt["stall_ms"],
                           where=where, stack=stack)
    except Exception:  # noqa: BLE001 — telemetry must never break the app
        pass


def sanitizer_report() -> dict:
    """Everything the sanitizer observed: per-lock stats, the order-edge
    graph (+ any cycles in it), and loop stalls. Read by bench.py's JSON
    line and the chaos cross-check test."""
    with _STATE_LOCK:
        locks = {k: dict(v) for k, v in _LOCK_STATS.items()}
        edges = [[a, b, int(v["count"])] for (a, b), v in sorted(_EDGES.items())]
        stalls = [dict(s) for s in _STALLS]
    return {
        "enabled": enabled(),
        "locks": locks,
        "edges": edges,
        "cycles": find_cycles([(a, b) for a, b, _ in edges]),
        "stalls": stalls,
    }


def reset() -> None:
    """Drop all recorded state (tests; the tables are process-global)."""
    with _STATE_LOCK:
        _EDGES.clear()
        _LOCK_STATS.clear()
        del _STALLS[:]


# ---------------------------------------------------------------------------
# asyncio loop-stall watchdog
# ---------------------------------------------------------------------------


class LoopStallWatchdog:
    """Heartbeat task + checker thread: detect event-loop stalls and dump
    the offending stack.

    A coroutine stamps ``monotonic()`` every ``interval``; a daemon thread
    watches the stamp age. When it exceeds the threshold
    (``KAKVEDA_SANITIZE_STALL_MS``, default 250) the loop thread's current
    frame is captured via ``sys._current_frames()`` — that stack IS the
    code blocking the loop — and recorded once per stall episode."""

    def __init__(self, threshold_ms: Optional[float] = None):
        if threshold_ms is None:
            threshold_ms = float(os.environ.get("KAKVEDA_SANITIZE_STALL_MS", "250"))
        self.threshold_s = max(0.01, threshold_ms / 1000.0)
        self._interval = self.threshold_s / 4.0
        self._last = time.monotonic()
        self._loop_tid: Optional[int] = None
        self._stop = threading.Event()
        self._task = None
        self._thread: Optional[threading.Thread] = None
        self.stall_count = 0

    async def start(self) -> None:
        """Call on the loop under watch."""
        import asyncio

        self._loop_tid = threading.get_ident()
        self._last = time.monotonic()
        self._task = asyncio.get_running_loop().create_task(self._beat())
        self._thread = threading.Thread(
            target=self._watch, name="sanitize-stall-watchdog", daemon=True)
        self._thread.start()

    async def _beat(self) -> None:
        import asyncio

        while not self._stop.is_set():
            self._last = time.monotonic()
            await asyncio.sleep(self._interval)

    def _watch(self) -> None:
        in_stall = False
        while not self._stop.wait(self._interval):
            age = time.monotonic() - self._last
            if age > self.threshold_s and not in_stall:
                in_stall = True
                self.stall_count += 1
                frame = sys._current_frames().get(self._loop_tid)
                stack = "".join(traceback.format_stack(frame)[-8:]) if frame else "<no frame>"
                record_stall(age * 1000.0, stack)
            elif age <= self._interval * 2:
                in_stall = False

    async def stop(self) -> None:
        self._stop.set()
        if self._task is not None:
            self._task.cancel()
            try:
                await self._task
            except BaseException:  # noqa: BLE001 — CancelledError et al.
                pass
            self._task = None
        if self._thread is not None:
            self._thread.join(timeout=2.0)
            self._thread = None
