"""Pydantic data contracts for the failure-intelligence plane.

Capability parity with the reference's shared schemas
(reference: services/shared/models.py:10-120). These are the wire shapes for
traces, failures, patterns, pre-flight warnings and health points; every
subsystem (ingestion, classifier, GFKB, warning policy, health scoring,
dashboard) speaks these types.
"""

from __future__ import annotations

from datetime import datetime, timezone
from enum import Enum
from typing import Any, Dict, List, Optional

from pydantic import BaseModel, Field


def utcnow() -> datetime:
    return datetime.now(timezone.utc)


class Severity(str, Enum):
    low = "low"
    medium = "medium"
    high = "high"


class TracePayload(BaseModel):
    """One observed LLM execution: prompt in, response out, plus context."""

    trace_id: str
    ts: datetime
    app_id: str
    agent_id: Optional[str] = None

    prompt: str
    response: str

    model: Optional[str] = None
    temperature: Optional[float] = None

    tools: List[str] = Field(default_factory=list)
    env: Dict[str, Any] = Field(default_factory=dict)


class IngestRequest(BaseModel):
    trace: TracePayload


class IngestBatchRequest(BaseModel):
    """Batched ingest: the 10k-traces/sec HTTP surface. The reference only
    has per-trace POSTs (services/ingestion/app.py:15-21); per-trace HTTP
    framing caps throughput far below the device pipeline's rate."""

    traces: List[TracePayload]


class FailureSignal(BaseModel):
    """Classifier verdict for a single trace."""

    trace_id: str
    ts: datetime
    app_id: str

    failure_type: str
    severity: Severity

    root_cause: Optional[str] = None
    mitigation: Optional[str] = None

    context_signature: Dict[str, Any]


class CanonicalFailureRecord(BaseModel):
    """A canonical, versioned entry in the Global Failure Knowledge Base.

    Versioning is append-only: an update re-appends the record with
    ``version + 1`` (reference: services/gfkb/app.py:105-147). The device
    index keeps exactly one embedding row per canonical failure; the version
    history lives in the append log.
    """

    failure_id: str
    version: int
    created_at: datetime
    updated_at: datetime

    failure_type: str
    root_cause: Optional[str] = None
    context_signature: Dict[str, Any]

    impact_severity: Severity
    resolution: Optional[str] = None

    occurrences: int = 0
    affected_apps: List[str] = Field(default_factory=list)

    signature_text: str


class FailureMatchRequest(BaseModel):
    signature_text: str
    failure_type: Optional[str] = None
    top_k: int = 5


class FailureMatch(BaseModel):
    failure_id: str
    version: int
    score: float
    failure_type: str
    suggested_mitigation: Optional[str] = None


class FailureMatchResponse(BaseModel):
    matches: List[FailureMatch]


class PatternEntity(BaseModel):
    """A recurring failure shape spanning multiple apps."""

    pattern_id: str
    name: str
    created_at: datetime
    failure_ids: List[str]
    affected_apps: List[str]
    description: Optional[str] = None


class WarningRequest(BaseModel):
    """Pre-flight check: 'has something like this failed before?'"""

    app_id: str
    agent_id: Optional[str] = None
    prompt: str
    tools: List[str] = Field(default_factory=list)
    env: Dict[str, Any] = Field(default_factory=dict)


class WarningResponse(BaseModel):
    action: str  # block | warn | silent
    confidence: float
    pattern_id: Optional[str] = None
    references: List[FailureMatch] = Field(default_factory=list)
    message: str
    # True when the verdict was served by the host-side warm/cold tiers
    # because the accelerator backend is latched DEGRADED (device-loss
    # mode, docs/robustness.md) — still a real verdict, just slower.
    degraded: bool = False
    # Serving provenance from the tiered GFKB (index/tiers.py): which
    # storage tier answered ("hot" = exact device scan, "tiered" = device
    # + routed overflow, "warm"/"warm_routed" = host tiers while
    # degraded, "*_exact"/"*_fault" = routing degraded to the exact
    # scan), and the IVF nprobe used when the answer was routed.
    tier: Optional[str] = None
    nprobe: Optional[int] = None


class HealthPoint(BaseModel):
    ts: datetime
    app_id: str
    score: float
    failure_rate: float
    recurrent_penalty: float
    avg_recovery_time_sec: float
    notes: Dict[str, Any] = Field(default_factory=dict)
