"""The in-process causal-tracing spine: spans, W3C context, span rings.

The platform is *failure intelligence*, yet its own failure telemetry used
to stop at process edges: one warn traverses router → scatter-gather across
R replica processes → admission → GFKB tiers → merge, and an ingest fans
out over the bus into peer dedup logs and possibly the DLQ — N uncorrelated
flight recorders and logs, no way to answer "*where* did this p95 / shed /
lost-warn come from". This module is the shared causal substrate, built in
the style of the metrics registry (core/metrics.py): dependency-free (no
opentelemetry import — the optional bridge lives in core/otel.py), one
process-global tracer (:func:`get_tracer`; tests build private instances),
and cheap enough for the warn hot path (an unsampled span is one object
allocation + two counter bumps; ``KAKVEDA_TRACE_SAMPLE=0`` records nothing
unless the outcome is bad).

Three layers:

* **Context** — trace_id (32 hex) / span_id (16 hex) / parent span, carried
  across process boundaries as a W3C ``traceparent`` header
  (``00-<trace>-<span>-<flags>``; :func:`parse_traceparent` /
  :func:`format_traceparent`) and across ``await`` points via a
  contextvar (:func:`current_span`). The service middleware FOLDS the
  existing request id into the trace: ``ensure_request_id`` already mints
  32 lowercase hex, so an unheadered request's rid IS its trace id and
  replica logs join router logs by either key.
* **Sampling** — head-based and DETERMINISTIC in the trace id
  (``KAKVEDA_TRACE_SAMPLE`` ∈ [0,1]; the first 8 hex digits thresholded),
  so every process in the fleet makes the SAME keep/drop decision for one
  trace without coordination. Spans whose outcome is ``error``/``shed``/
  ``degraded`` are ALWAYS recorded — failure intelligence must not sample
  away its failures.
* **Ring** — a bounded per-process list of finished spans
  (``KAKVEDA_TRACE_N``, default 512), dumped at ``GET /trace`` and
  ``GET /trace/{id}`` and scatter-assembled into one cross-process tree by
  the router collector (fleet/router.py) / ``cli trace <id>``.

Contract (same as core/otel.py): tracing NEVER raises into the request
path. :meth:`Tracer.start_span` and every :class:`Span` method swallow
their own failures; the ``trace.record`` fault site (chaos-armable,
docs/robustness.md) proves it — an armed recorder drops the span, bumps
``dropped``, and the warn still answers. Orphan accounting is the harness
invariant: every started span must end in exactly ONE outcome bucket, so
``plane()["orphaned"]`` (= started − ended) is asserted ZERO by the storm
bench row, mirroring the replay accounting invariant.

Knobs: ``KAKVEDA_TRACE_N`` — span-ring capacity per process (default 512;
0 disables recording but keeps propagation and the dump endpoints).
``KAKVEDA_TRACE_SAMPLE`` — head sampling rate in [0,1] (default 1; bad
outcomes record regardless).
"""

from __future__ import annotations

import contextvars
import os
import time
import uuid
from typing import Any, Dict, Iterable, List, Optional, Tuple

from kakveda_tpu.core import faults as _faults
from kakveda_tpu.core import sanitize

__all__ = [
    "Span",
    "Tracer",
    "get_tracer",
    "current_span",
    "current_traceparent",
    "parse_traceparent",
    "format_traceparent",
    "assemble_tree",
    "render_trace",
    "TRACEPARENT_HEADER",
    "ALWAYS_RECORD_OUTCOMES",
]

TRACEPARENT_HEADER = "traceparent"

# Outcomes that bypass head sampling: a dropped failure trace is exactly
# the telemetry this platform exists to keep.
ALWAYS_RECORD_OUTCOMES = ("error", "shed", "degraded")

# Resolved ONCE at import (fault-site contract, core/faults.py): armed
# chaos makes record() drop the span — never raise into the request path.
_FAULT_RECORD = _faults.site("trace.record")

_HEX = frozenset("0123456789abcdef")
_ZERO_TRACE = "0" * 32
_ZERO_SPAN = "0" * 16

_CURRENT: contextvars.ContextVar[Optional["Span"]] = contextvars.ContextVar(
    "kakveda_trace_span", default=None
)


# ---------------------------------------------------------------------------
# W3C wire format


def parse_traceparent(value: Any) -> Optional[Tuple[str, str, bool]]:
    """``00-<32hex>-<16hex>-<2hex>`` → ``(trace_id, span_id, sampled)``,
    or None for anything malformed (never raises — wire input)."""
    if not isinstance(value, str):
        return None
    parts = value.strip().lower().split("-")
    if len(parts) < 4:
        return None
    ver, tid, sid, flags = parts[0], parts[1], parts[2], parts[3]
    # version "ff" is forbidden by the W3C spec; other unknown versions
    # parse forward-compatibly as long as the id fields fit.
    if len(ver) != 2 or set(ver) - _HEX or ver == "ff":
        return None
    if len(tid) != 32 or len(sid) != 16:
        return None
    if set(tid) - _HEX or set(sid) - _HEX:
        return None
    if tid == _ZERO_TRACE or sid == _ZERO_SPAN:
        return None
    try:
        sampled = bool(int(flags, 16) & 1)
    except ValueError:
        return None
    return tid, sid, sampled


def format_traceparent(trace_id: str, span_id: str, sampled: bool) -> str:
    return f"00-{trace_id}-{span_id}-{'01' if sampled else '00'}"


def _valid_trace_id(s: Any) -> bool:
    return (
        isinstance(s, str) and len(s) == 32
        and not set(s) - _HEX and s != _ZERO_TRACE
    )


def new_trace_id() -> str:
    return uuid.uuid4().hex


def new_span_id() -> str:
    return uuid.uuid4().hex[:16]


# ---------------------------------------------------------------------------
# spans


class Span:
    """One timed unit of work. Never raises from any method — tracing is
    telemetry, not control flow. Use as a context manager to both activate
    it (contextvar) and end it with an exception-aware outcome."""

    __slots__ = (
        "trace_id", "span_id", "parent_id", "name", "ts", "dur_ms",
        "outcome", "attrs", "sampled", "_tracer", "_t0", "_token", "_ended",
    )

    def __init__(
        self,
        tracer: "Tracer",
        name: str,
        trace_id: str,
        span_id: str,
        parent_id: Optional[str],
        sampled: bool,
        attrs: Dict[str, Any],
    ) -> None:
        self._tracer = tracer
        self.name = name
        self.trace_id = trace_id
        self.span_id = span_id
        self.parent_id = parent_id
        self.sampled = sampled
        self.attrs = attrs
        self.ts = time.time()
        self._t0 = time.perf_counter()
        self.dur_ms = 0.0
        self.outcome = "ok"
        self._token: Optional[contextvars.Token] = None
        self._ended = False

    # -- context propagation ----------------------------------------------

    def traceparent(self) -> str:
        """Wire form naming THIS span as the parent of the next hop."""
        return format_traceparent(self.trace_id, self.span_id, self.sampled)

    def activate(self) -> None:
        """Make this span the contextvar-current parent for child spans
        started in the same task/thread context."""
        try:
            self._token = _CURRENT.set(self)
        except Exception:  # noqa: BLE001 — never raise into the request path
            pass

    def deactivate(self) -> None:
        try:
            if self._token is not None:
                _CURRENT.reset(self._token)
                self._token = None
        except Exception:  # noqa: BLE001 — never raise into the request path
            pass

    # -- annotation / completion ------------------------------------------

    def set(self, **attrs: Any) -> "Span":
        try:
            self.attrs.update(attrs)
        except Exception:  # noqa: BLE001 — never raise into the request path
            pass
        return self

    def end(self, outcome: str = "ok", **attrs: Any) -> None:
        """Close the span into exactly ONE outcome bucket and hand it to
        the tracer ring. Idempotent: the first end() wins."""
        try:
            if self._ended:
                return
            self._ended = True
            self.dur_ms = round((time.perf_counter() - self._t0) * 1000, 3)
            if attrs:
                self.attrs.update(attrs)
            self.outcome = outcome
            self._tracer._finish(self)
        except Exception:  # noqa: BLE001 — never raise into the request path
            pass

    def __enter__(self) -> "Span":
        self.activate()
        return self

    def __exit__(self, exc_type, exc, _tb) -> bool:
        self.deactivate()
        if exc_type is not None and self.outcome == "ok":
            self.set(error=getattr(exc_type, "__name__", str(exc_type)))
            self.end("error")
        else:
            self.end(self.outcome)
        return False  # never swallow the caller's exception

    def to_dict(self) -> Dict[str, Any]:
        return {
            "trace_id": self.trace_id,
            "span_id": self.span_id,
            "parent_id": self.parent_id,
            "name": self.name,
            "ts": self.ts,
            "dur_ms": self.dur_ms,
            "outcome": self.outcome,
            "attrs": dict(self.attrs),
        }


class _NullSpan:
    """Inert stand-in returned when span creation itself fails — keeps the
    caller's code path identical (attrs/end/with all no-op, direct
    attribute writes like ``span.outcome = ...`` absorbed)."""

    __slots__ = ()
    trace_id = ""
    span_id = ""
    parent_id = None
    name = ""
    sampled = False
    outcome = "ok"
    attrs: Dict[str, Any] = {}

    def traceparent(self) -> str:
        return ""

    def activate(self) -> None:
        pass

    def deactivate(self) -> None:
        pass

    def set(self, **_attrs: Any) -> "_NullSpan":
        return self

    def __setattr__(self, _name: str, _value: Any) -> None:
        pass  # writes no-op: callers may assign .outcome directly

    def end(self, _outcome: str = "ok", **_attrs: Any) -> None:
        pass

    def __enter__(self) -> "_NullSpan":
        return self

    def __exit__(self, *_a) -> bool:
        return False


NULL_SPAN = _NullSpan()


# ---------------------------------------------------------------------------
# tracer


class Tracer:
    """Process-global span factory + bounded finished-span ring.

    Counter contract (``plane()``): ``started`` and ``ended`` count EVERY
    span (sampled or not) so ``orphaned = started - ended`` certifies that
    each span terminated in exactly one bucket; ``recorded`` counts ring
    appends; ``dropped`` counts ring evictions + chaos-injected record
    failures (``trace.record``)."""

    def __init__(
        self,
        capacity: Optional[int] = None,
        sample: Optional[float] = None,
    ) -> None:
        if capacity is None:
            capacity = int(os.environ.get("KAKVEDA_TRACE_N", "512") or 0)
        if sample is None:
            sample = float(os.environ.get("KAKVEDA_TRACE_SAMPLE", "1") or 0.0)
        self.capacity = max(0, int(capacity))
        self.sample = min(1.0, max(0.0, float(sample)))
        self.service = ""  # replica id; stamped by the service app
        self._lock = sanitize.named_lock("Tracer._lock")
        self._spans: List[Dict[str, Any]] = []
        self._started = 0
        self._ended = 0
        self._recorded = 0
        self._dropped = 0

    # -- sampling ----------------------------------------------------------

    def sample_decision(self, trace_id: str) -> bool:
        """Deterministic head decision: pure in (trace_id, rate) so every
        process agrees without coordination."""
        if self.sample >= 1.0:
            return True
        if self.sample <= 0.0:
            return False
        try:
            return int(trace_id[:8], 16) < self.sample * 0x100000000
        except (ValueError, TypeError):
            return False

    # -- span lifecycle ----------------------------------------------------

    def start_span(
        self,
        name: str,
        *,
        parent: Optional[Span] = None,
        traceparent: Optional[str] = None,
        trace_id: Optional[str] = None,
        **attrs: Any,
    ) -> Span:
        """Start a span, resolving its parent in precedence order: explicit
        ``parent`` span → ``traceparent`` wire header → contextvar-current
        span → new root. ``trace_id`` (e.g. the folded request id, 32 hex)
        seeds a NEW root's id only. Never raises: on any internal failure
        the caller gets :data:`NULL_SPAN` and proceeds untraced."""
        try:
            pid: Optional[str] = None
            sampled: Optional[bool] = None
            tid: Optional[str] = None
            if parent is not None and getattr(parent, "trace_id", ""):
                tid, pid, sampled = parent.trace_id, parent.span_id, parent.sampled
            elif traceparent:
                ctx = parse_traceparent(traceparent)
                if ctx is not None:
                    tid, pid, sampled = ctx
            if tid is None:
                cur = _CURRENT.get()
                if cur is not None and cur.trace_id:
                    tid, pid, sampled = cur.trace_id, cur.span_id, cur.sampled
            if tid is None:  # new root — fold the request id when it fits
                tid = trace_id if _valid_trace_id(trace_id) else new_trace_id()
                sampled = self.sample_decision(tid)
            if sampled is None:
                sampled = self.sample_decision(tid)
            span = Span(self, name, tid, new_span_id(), pid, sampled, dict(attrs))
            with self._lock:
                self._started += 1
            return span
        except Exception:  # noqa: BLE001 — never raise into the request path
            return NULL_SPAN  # type: ignore[return-value]

    def record_completed(
        self,
        name: str,
        *,
        traceparent: Optional[str] = None,
        ts: Optional[float] = None,
        dur_ms: float = 0.0,
        outcome: str = "ok",
        **attrs: Any,
    ) -> Optional[Dict[str, Any]]:
        """Record an already-finished timeline as one span — for work whose
        timing is assembled after the fact (serving-engine request
        timelines, autoscaler decision ledger lines). Returns the recorded
        dict, or None when unsampled/dropped. Never raises."""
        try:
            span = self.start_span(name, traceparent=traceparent, **attrs)
            if ts is not None:
                span.ts = ts
            span.dur_ms = round(float(dur_ms), 3)
            # end() would overwrite dur_ms from the wall clock; finish the
            # span through the ring path directly.
            span._ended = True
            span.outcome = outcome
            self._finish(span)
            return span.to_dict()
        except Exception:  # noqa: BLE001 — never raise into the request path
            return None

    def _finish(self, span: Span) -> None:
        """Ring-append a finished span when sampled or the outcome demands
        it. The ``trace.record`` chaos site proves the failure contract:
        an armed site drops the span (counted), the request path never
        sees an exception."""
        with self._lock:
            self._ended += 1
        if self.capacity <= 0:
            return
        if not span.sampled and span.outcome not in ALWAYS_RECORD_OUTCOMES:
            return
        try:
            _FAULT_RECORD.fire()
            d = span.to_dict()
            if self.service:
                d["service"] = self.service
            with self._lock:
                self._spans.append(d)
                self._recorded += 1
                over = len(self._spans) - self.capacity
                if over > 0:
                    del self._spans[:over]
                    self._dropped += over
            # OTel bridge (KAKVEDA_OTEL_ENABLED): recorded spans also
            # export through the best-effort SDK tracer — one None check
            # when off, never a new hard dependency.
            from kakveda_tpu.core import otel as _otel

            if _otel.get_tracer() is not None:
                _otel.export_native_span(d)
        except Exception:  # noqa: BLE001 — a failing recorder drops the span, nothing else
            with self._lock:
                self._dropped += 1

    # -- collection --------------------------------------------------------

    def dump(
        self, trace_id: Optional[str] = None, limit: Optional[int] = None
    ) -> List[Dict[str, Any]]:
        """Finished spans, oldest→newest; optionally one trace only."""
        with self._lock:
            spans = list(self._spans)
        if trace_id is not None:
            spans = [s for s in spans if s.get("trace_id") == trace_id]
        if limit is not None and limit >= 0:
            spans = spans[-limit:]
        return spans

    def plane(self) -> Dict[str, Any]:
        """The bench/storm counters: one dict, cheap, lock-consistent."""
        with self._lock:
            started, ended = self._started, self._ended
            recorded, dropped = self._recorded, self._dropped
            ring = len(self._spans)
        return {
            "started": started,
            "ended": ended,
            "orphaned": started - ended,
            "recorded": recorded,
            "dropped": dropped,
            "ring": ring,
            "capacity": self.capacity,
            "sample": self.sample,
        }

    def reset(self) -> None:
        """Zero the ring and counters (bench A/B runs, tests)."""
        with self._lock:
            del self._spans[:]
            self._started = self._ended = 0
            self._recorded = self._dropped = 0


# ---------------------------------------------------------------------------
# process-global default + context helpers

_TRACER = Tracer()


def get_tracer() -> Tracer:
    return _TRACER


def current_span() -> Optional[Span]:
    try:
        return _CURRENT.get()
    except Exception:  # noqa: BLE001 — never raise into the request path
        return None


def current_traceparent() -> str:
    """Wire form of the contextvar-current span ('' when untraced) — the
    one-liner boundary code uses to stamp outgoing envelopes/headers."""
    span = current_span()
    return span.traceparent() if span is not None else ""


# ---------------------------------------------------------------------------
# tree assembly / rendering (collector + CLI)


def assemble_tree(spans: Iterable[Dict[str, Any]]) -> List[Dict[str, Any]]:
    """Merge span dicts (possibly from several processes, possibly with
    duplicates from scatter-assembly) into root-first trees: each node is
    the span dict plus a ``children`` list sorted by start ts. Spans whose
    parent is missing from the set are roots (partial traces render rather
    than vanish)."""
    by_id: Dict[str, Dict[str, Any]] = {}
    for s in spans:
        sid = s.get("span_id")
        if not sid or sid in by_id:
            continue
        by_id[sid] = dict(s, children=[])
    roots: List[Dict[str, Any]] = []
    for node in by_id.values():
        parent = by_id.get(node.get("parent_id") or "")
        if parent is not None and parent is not node:
            parent["children"].append(node)
        else:
            roots.append(node)
    def _sort(nodes: List[Dict[str, Any]]) -> None:
        nodes.sort(key=lambda n: (n.get("ts") or 0.0, n.get("span_id") or ""))
        for n in nodes:
            _sort(n["children"])
    _sort(roots)
    return roots


def _render_node(node: Dict[str, Any], prefix: str, last: bool,
                 out: List[str]) -> None:
    branch = "└─ " if last else "├─ "
    svc = f" [{node['service']}]" if node.get("service") else ""
    attrs = node.get("attrs") or {}
    extras = " ".join(f"{k}={attrs[k]}" for k in sorted(attrs))
    out.append(
        f"{prefix}{branch}{node.get('name', '?')}{svc} "
        f"{node.get('dur_ms', 0.0):.1f}ms {node.get('outcome', '?')}"
        + (f"  {extras}" if extras else "")
    )
    children = node.get("children") or []
    child_prefix = prefix + ("   " if last else "│  ")
    for i, child in enumerate(children):
        _render_node(child, child_prefix, i == len(children) - 1, out)


def render_trace(spans: Iterable[Dict[str, Any]]) -> str:
    """ASCII tree for ``cli trace <id>`` — one line per span with service,
    duration, outcome, and sorted attrs."""
    spans = list(spans)
    if not spans:
        return "(no spans)"
    roots = assemble_tree(spans)
    tid = spans[0].get("trace_id", "?")
    out = [f"trace {tid} ({len({s.get('span_id') for s in spans})} spans)"]
    for i, root in enumerate(roots):
        _render_node(root, "", i == len(roots) - 1, out)
    return "\n".join(out)
