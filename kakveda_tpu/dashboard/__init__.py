"""Dashboard application layer.

Capability parity with the reference dashboard
(reference: services/dashboard/ — app.py, db.py, auth.py, rbac.py,
templates/): auth + RBAC, scenario runner, runs explorer with span
waterfalls, warnings analytics, per-app health, datasets/evaluations,
prompt library, experiments, playground, external-agent registry, projects
with API keys and budgets, admin. Built on aiohttp + stdlib sqlite3 +
jinja2 (this image has no FastAPI/SQLAlchemy/passlib; auth crypto is
stdlib hashlib/hmac).
"""
