"""Dashboard app factory.

Assembles the aiohttp application: DB init + demo-user bootstrap + prod
secret guardrail (reference: services/dashboard/app.py:1261-1329),
middlewares (user resolution, security headers, request logging), and all
route modules.
"""

from __future__ import annotations

from pathlib import Path
from typing import Optional

from aiohttp import web

from kakveda_tpu.core.runtime import get_runtime_config
from kakveda_tpu.dashboard.core import (
    CTX_KEY,
    DashboardContext,
    csrf_middleware,
    security_headers_middleware,
    user_middleware,
)
from kakveda_tpu.dashboard.db import make_database
from kakveda_tpu.models.runtime import ModelRuntime, get_runtime
from kakveda_tpu.platform import Platform
from kakveda_tpu.service.app import metrics_routes, request_context_middleware


def make_dashboard_app(
    platform: Optional[Platform] = None,
    db_path: str | Path | None = None,
    model: Optional[ModelRuntime] = None,
    demo_users: bool = True,
    **platform_kw,
) -> web.Application:
    cfg = get_runtime_config(service_name="dashboard")
    if cfg.env == "production" and cfg.dashboard_jwt_secret == "dev-secret-change-me":
        raise RuntimeError(
            "refusing to start in production with the default JWT secret "
            "(set DASHBOARD_JWT_SECRET)"
        )

    plat = platform or Platform(**platform_kw)
    db = make_database(db_path or (Path(cfg.data_dir) / "dashboard.db"))
    # Demo accounts carry published credentials and self-repair to them on
    # every start — never in production (KAKVEDA_DEMO_USERS=1 overrides for
    # an explicit opt-in).
    import os

    if cfg.env == "production" and os.environ.get("KAKVEDA_DEMO_USERS") != "1":
        demo_users = False
    db.bootstrap(demo_users=demo_users)

    ctx = DashboardContext(
        platform=plat,
        db=db,
        model=model or get_runtime(cfg.model_runtime),
        jwt_secret=cfg.dashboard_jwt_secret,
    )

    from kakveda_tpu.core import otel

    middlewares = [
        request_context_middleware,
        user_middleware,
        security_headers_middleware,
        csrf_middleware,
    ]
    if otel.setup_otel("dashboard"):
        middlewares.insert(0, otel.otel_middleware())
    app = web.Application(middlewares=middlewares)
    app[CTX_KEY] = ctx

    from kakveda_tpu.dashboard import routes_admin, routes_auth, routes_data, routes_main

    routes_auth.setup(app)
    routes_main.setup(app)
    routes_data.setup(app)
    routes_admin.setup(app)

    async def healthz(request):
        return web.json_response({"ok": True})

    async def readyz(request):
        try:
            db.one("SELECT 1 AS one")
            return web.json_response({"ok": True})
        except Exception as e:  # noqa: BLE001
            return web.json_response({"ok": False, "error": str(e)}, status=503)

    app.add_routes([web.get("/healthz", healthz), web.get("/readyz", readyz)])
    # The metrics plane (GET /metrics, GET /flightrecorder) — same routes
    # as the service app; the registry and recorders are process-global.
    app.add_routes(metrics_routes())

    # Bus subscriptions (reference: services/dashboard/app.py:1332-1431):
    # traces ingested through the platform API (not just scenario runs) land
    # in the runs explorer, and child-safety alerts from external agents
    # become WarningEvent rows. Raising on failure lets the bus's delivery
    # accounting see it (a swallowed insert error would silently lose e.g. a
    # high-severity safety alert).
    import logging as _logging
    import time as _time
    from datetime import datetime as _dt

    _log = _logging.getLogger("kakveda.dashboard.events")

    def _event_ts(event: dict) -> float:
        """Honor the trace's own timestamp (backfilled traces must not all
        land at 'now'); fall back to the wall clock."""
        raw = event.get("ts")
        if isinstance(raw, (int, float)):
            return float(raw)
        if isinstance(raw, str):
            try:
                return _dt.fromisoformat(raw.replace("Z", "+00:00")).timestamp()
            except ValueError:
                pass
        return _time.time()

    def _on_trace_ingested(event: dict) -> None:
        try:
            db.execute(
                "INSERT OR IGNORE INTO trace_runs (trace_id, ts, app_id, agent_id, prompt,"
                " response, provider, model, status, tags_json) VALUES (?,?,?,?,?,?,?,?,'ok','[]')",
                (
                    str(event.get("trace_id") or ""),
                    _event_ts(event),
                    str(event.get("app_id") or "unknown"),
                    event.get("agent_id"),
                    str(event.get("prompt") or ""),
                    str(event.get("response") or ""),
                    "event",
                    event.get("model"),
                ),
            )
        except Exception:
            _log.exception("trace.ingested persistence failed")
            raise

    def _on_child_safety(event: dict) -> None:
        sev = str(event.get("severity") or "medium").lower()
        confidence = {"low": 0.4, "medium": 0.7, "high": 0.95}.get(sev, 0.7)
        try:
            db.execute(
                "INSERT INTO warning_events (ts, app_id, action, confidence, failure_type,"
                " message, source) VALUES (?,?,?,?,?,?,'child_safety')",
                (
                    _event_ts(event),
                    str(event.get("app_id") or "unknown"),
                    "block" if sev == "high" else "warn",
                    confidence,
                    str(event.get("failure_type") or "CHILD_SAFETY"),
                    str(event.get("message") or event.get("reason") or "child safety alert"),
                ),
            )
        except Exception:
            _log.exception("child_safety_alert persistence failed")
            raise

    from kakveda_tpu.events.bus import TOPIC_CHILD_SAFETY, TOPIC_TRACE_INGESTED

    plat.bus.subscribe(TOPIC_TRACE_INGESTED, _on_trace_ingested)
    plat.bus.subscribe(TOPIC_CHILD_SAFETY, _on_child_safety)

    async def _unsubscribe(app_):
        # A second make_dashboard_app on the same Platform (tests, reload)
        # must not leave stale closures duplicating rows / pinning the DB.
        plat.bus.unsubscribe(TOPIC_TRACE_INGESTED, _on_trace_ingested)
        plat.bus.unsubscribe(TOPIC_CHILD_SAFETY, _on_child_safety)

    app.on_cleanup.append(_unsubscribe)
    return app
