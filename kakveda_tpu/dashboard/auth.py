"""Auth crypto on the stdlib: pbkdf2 password hashing + HS256 JWT.

Same guarantees as the reference's passlib/pyjwt stack
(reference: services/dashboard/auth.py:30-58): salted pbkdf2_sha256
password hashes, HS256 tokens with iss/jti/exp claims (default TTL 720
minutes), and single-use reset tokens — implemented with hashlib/hmac/
base64/json since those wheels aren't in this image.
"""

from __future__ import annotations

import base64
import hashlib
import hmac
import json
import os
import secrets
import time
from typing import Any, Dict, Optional

ISSUER = "kakveda-tpu"
TOKEN_TTL_MINUTES = int(os.environ.get("DASHBOARD_TOKEN_TTL_MINUTES", "720"))
_PBKDF2_ITERATIONS = 390_000


# --- passwords -------------------------------------------------------------


def hash_password(password: str) -> str:
    salt = secrets.token_bytes(16)
    dk = hashlib.pbkdf2_hmac("sha256", password.encode("utf-8"), salt, _PBKDF2_ITERATIONS)
    return f"pbkdf2_sha256${_PBKDF2_ITERATIONS}${salt.hex()}${dk.hex()}"


def verify_password(password: str, stored: str) -> bool:
    try:
        scheme, iters, salt_hex, dk_hex = stored.split("$")
        if scheme != "pbkdf2_sha256":
            return False
        dk = hashlib.pbkdf2_hmac(
            "sha256", password.encode("utf-8"), bytes.fromhex(salt_hex), int(iters)
        )
        return hmac.compare_digest(dk.hex(), dk_hex)
    except (ValueError, TypeError):
        return False


# --- JWT (HS256) -----------------------------------------------------------


def _b64url(data: bytes) -> str:
    return base64.urlsafe_b64encode(data).rstrip(b"=").decode("ascii")


def _b64url_decode(s: str) -> bytes:
    pad = "=" * (-len(s) % 4)
    return base64.urlsafe_b64decode(s + pad)


def create_access_token(
    *,
    email: str,
    roles: list[str],
    secret: str,
    ttl_minutes: int = TOKEN_TTL_MINUTES,
    extra: Optional[Dict[str, Any]] = None,
) -> str:
    now = int(time.time())
    payload: Dict[str, Any] = {
        "iss": ISSUER,
        "sub": email,
        "roles": roles,
        "jti": secrets.token_hex(16),
        "iat": now,
        "exp": now + ttl_minutes * 60,
    }
    if extra:
        payload.update(extra)
    header = {"alg": "HS256", "typ": "JWT"}
    signing_input = f"{_b64url(json.dumps(header, separators=(',', ':')).encode())}." \
                    f"{_b64url(json.dumps(payload, separators=(',', ':')).encode())}"
    sig = hmac.new(secret.encode(), signing_input.encode(), hashlib.sha256).digest()
    return f"{signing_input}.{_b64url(sig)}"


def decode_token(token: str, *, secret: str) -> Optional[Dict[str, Any]]:
    """Validated claims dict, or None for any invalid/expired/forged token."""
    try:
        h, p, s = token.split(".")
        signing_input = f"{h}.{p}"
        expected = hmac.new(secret.encode(), signing_input.encode(), hashlib.sha256).digest()
        if not hmac.compare_digest(expected, _b64url_decode(s)):
            return None
        header = json.loads(_b64url_decode(h))
        if header.get("alg") != "HS256":
            return None
        payload = json.loads(_b64url_decode(p))
        if payload.get("iss") != ISSUER:
            return None
        if int(payload.get("exp", 0)) < time.time():
            return None
        return payload
    except (ValueError, KeyError, json.JSONDecodeError):
        return None


# --- reset tokens ----------------------------------------------------------


def mint_reset_token() -> str:
    return secrets.token_urlsafe(32)
