"""Dashboard app context: jinja env, auth/session helpers, middleware."""

from __future__ import annotations

import logging
from dataclasses import dataclass, field
from pathlib import Path
from typing import Any, List, Optional

import secrets

import jinja2
from aiohttp import web

from kakveda_tpu.core.ratelimit import RateLimiter
from kakveda_tpu.core.revocation import RevocationStore
from kakveda_tpu.core.runtime import get_runtime_config
from kakveda_tpu.dashboard import auth as auth_lib
from kakveda_tpu.dashboard import rbac
from kakveda_tpu.dashboard.db import Database
from kakveda_tpu.models.runtime import ModelRuntime
from kakveda_tpu.platform import Platform

log = logging.getLogger("kakveda.dashboard")

COOKIE_NAME = "kakveda_token"
VIEW_AS_COOKIE = "kakveda_view_as"
PROJECT_COOKIE = "kakveda_project"

TEMPLATES_DIR = Path(__file__).parent / "templates"


@dataclass
class DashboardContext:
    platform: Platform
    db: Database
    model: ModelRuntime
    jwt_secret: str
    revocations: RevocationStore = field(default_factory=RevocationStore)
    jinja: jinja2.Environment = field(init=False)

    def __post_init__(self):
        self.jinja = jinja2.Environment(
            loader=jinja2.FileSystemLoader(str(TEMPLATES_DIR)),
            autoescape=True,
        )
        # Epoch-seconds → "YYYY-MM-DD HH:MM" UTC; DB rows store raw floats.
        import datetime as _dt

        self.jinja.filters["ts_utc"] = lambda ts: (
            _dt.datetime.fromtimestamp(float(ts), tz=_dt.timezone.utc).strftime("%Y-%m-%d %H:%M")
            if ts else "—"
        )

    def render(self, request: web.Request, template: str, **ctx: Any) -> web.Response:
        user = request.get("user")
        html = self.jinja.get_template(template).render(
            user=user, request=request, csp_nonce=request.get("csp_nonce", ""), **ctx
        )
        return web.Response(text=html, content_type="text/html")


CTX_KEY: web.AppKey[DashboardContext] = web.AppKey("dashboard_ctx", DashboardContext)


# --- user resolution -------------------------------------------------------


@dataclass
class CurrentUser:
    email: str
    display_name: str
    roles: List[str]
    user_id: int
    impersonated_by: Optional[str] = None

    @property
    def is_admin(self) -> bool:
        return rbac.has_role(self.roles, rbac.ADMIN)


def resolve_user(request: web.Request) -> Optional[CurrentUser]:
    """Cookie JWT → DB-truth user (roles come from the DB, not the token —
    reference: services/dashboard/app.py:681-720 — with admin 'view-as'
    impersonation via a second cookie)."""
    ctx = request.app[CTX_KEY]
    token = request.cookies.get(COOKIE_NAME)
    if not token:
        return None
    claims = auth_lib.decode_token(token, secret=ctx.jwt_secret)
    if not claims:
        return None
    if claims.get("jti") and ctx.revocations.is_revoked(claims["jti"]):
        return None
    row = ctx.db.user_by_email(claims.get("sub", ""))
    if row is None or not row["is_active"]:
        return None
    roles = ctx.db.user_roles(row["id"])
    user = CurrentUser(
        email=row["email"],
        display_name=row["display_name"] or row["email"],
        roles=roles,
        user_id=row["id"],
    )
    view_as = request.cookies.get(VIEW_AS_COOKIE)
    if view_as and user.is_admin:
        target = ctx.db.user_by_email(view_as)
        if target is not None:
            return CurrentUser(
                email=target["email"],
                display_name=target["display_name"] or target["email"],
                roles=ctx.db.user_roles(target["id"]),
                user_id=target["id"],
                impersonated_by=user.email,
            )
    return user


def require_login(handler):
    async def wrapped(request: web.Request):
        if request.get("user") is None:
            raise web.HTTPFound(f"/login?next={request.path}")
        return await handler(request)

    return wrapped


def require_roles(*allowed: str):
    def deco(handler):
        async def wrapped(request: web.Request):
            user: Optional[CurrentUser] = request.get("user")
            if user is None:
                raise web.HTTPFound(f"/login?next={request.path}")
            if not rbac.require_any(user.roles, allowed):
                raise web.HTTPForbidden(text="insufficient role")
            return await handler(request)

        return wrapped

    return deco


# --- middleware ------------------------------------------------------------


@web.middleware
async def user_middleware(request: web.Request, handler):
    request["user"] = resolve_user(request)
    return await handler(request)


def _stamp_security_headers(response, nonce: str = "") -> None:
    # Inline scripts (warnings charts, playground streaming) carry a
    # per-request nonce: script-src falls back to default-src 'self'
    # otherwise, and 'self' BLOCKS inline execution in real browsers —
    # a gap TestClient-based tests can't see (clients don't enforce CSP).
    script_src = f" 'nonce-{nonce}'" if nonce else ""
    response.headers.setdefault(
        "Content-Security-Policy",
        f"default-src 'self'; script-src 'self'{script_src}; "
        "style-src 'self' 'unsafe-inline'",
    )
    response.headers.setdefault("X-Frame-Options", "DENY")
    response.headers.setdefault("X-Content-Type-Options", "nosniff")
    if get_runtime_config(service_name="dashboard").env == "production":
        response.headers.setdefault(
            "Strict-Transport-Security", "max-age=31536000; includeSubDomains"
        )


@web.middleware
async def security_headers_middleware(request: web.Request, handler):
    """CSP/XFO/no-sniff on every response
    (reference: services/dashboard/app.py:615-626). Redirects and error
    pages are raised as HTTPException by most handlers, so the raised path
    must be stamped too."""
    request["csp_nonce"] = secrets.token_urlsafe(16)
    try:
        response = await handler(request)
    except web.HTTPException as exc:
        _stamp_security_headers(exc, request["csp_nonce"])
        _stamp_csrf_cookie(request, exc)
        raise
    _stamp_security_headers(response, request["csp_nonce"])
    _stamp_csrf_cookie(request, response)
    return response


CSRF_COOKIE = "csrf_token"


def _stamp_csrf_cookie(request: web.Request, response) -> None:
    """Issue the double-submit CSRF token cookie when absent — the
    reference sets it even with enforcement disabled
    (reference: services/dashboard/app.py:655-663), so clients are primed
    before enforcement is switched on."""
    if request.cookies.get(CSRF_COOKIE):
        return
    try:
        response.set_cookie(
            CSRF_COOKIE,
            secrets.token_urlsafe(32),
            httponly=False,  # double-submit: JS must read it back
            samesite="Lax",
            secure=get_runtime_config(service_name="dashboard").env == "production",
        )
    except (AttributeError, RuntimeError):  # prepared/streamed responses
        pass


@web.middleware
async def csrf_middleware(request: web.Request, handler):
    """Double-submit CSRF check on mutating methods, enforcement gated on
    ``KAKVEDA_CSRF_ENFORCE=1`` (the reference ships with enforcement
    disabled too; the cookie issuance above keeps clients ready)."""
    import os

    if request.method in ("POST", "PUT", "PATCH", "DELETE") and os.environ.get(
        "KAKVEDA_CSRF_ENFORCE", ""
    ).lower() in ("1", "true", "yes"):
        # /api/* authenticates by API key/bearer, not cookies — exempt.
        if not request.path.startswith("/api/"):
            cookie = request.cookies.get(CSRF_COOKIE, "")
            sent = request.headers.get("X-CSRF-Token", "")
            if not sent and request.content_type == "application/x-www-form-urlencoded":
                form = await request.post()
                sent = str(form.get("csrf_token", ""))
            if not cookie or sent != cookie:
                raise web.HTTPForbidden(text="CSRF token missing or mismatched")
    return await handler(request)


# --- shared rate limiter ---------------------------------------------------

RATE_LIMITER = RateLimiter()
