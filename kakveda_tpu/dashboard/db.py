"""Dashboard persistence: sqlite (default) or Postgres behind one DAO.

Table-for-table parity with the reference's 22 SQLAlchemy models + its
hand-rolled ALTER-based migrate_db (reference: services/dashboard/db.py:
25-362 models, 364-644 migrations). The default backend is stdlib sqlite3
with WAL journaling; setting ``KAKVEDA_DB_URL=postgresql://…`` routes the
SAME route-layer SQL through Postgres (the reference's prod compose runs
Postgres, docker-compose.prod.yml) — the thin dialect shim below rewrites
the three divergences (qmark params, AUTOINCREMENT, INSERT OR IGNORE)
instead of dragging in an ORM.

Connections are per-call (cheap for sqlite, and it avoids cross-thread
sharing issues under aiohttp's executor; Postgres callers who need more
than the dashboard's modest QPS can front it with pgbouncer).
"""

from __future__ import annotations

import json
import os
import re
import sqlite3
import time
import uuid
from pathlib import Path
from typing import Any, Dict, Iterable, List, Optional

_SCHEMA = """
CREATE TABLE IF NOT EXISTS users (
  id INTEGER PRIMARY KEY AUTOINCREMENT,
  email TEXT UNIQUE NOT NULL,
  password_hash TEXT NOT NULL,
  display_name TEXT,
  is_active INTEGER NOT NULL DEFAULT 1,
  created_at REAL NOT NULL
);
CREATE TABLE IF NOT EXISTS roles (
  id INTEGER PRIMARY KEY AUTOINCREMENT,
  name TEXT UNIQUE NOT NULL
);
CREATE TABLE IF NOT EXISTS user_roles (
  user_id INTEGER NOT NULL,
  role_id INTEGER NOT NULL,
  PRIMARY KEY (user_id, role_id)
);
CREATE TABLE IF NOT EXISTS password_reset_tokens (
  token TEXT PRIMARY KEY,
  user_id INTEGER NOT NULL,
  expires_at REAL NOT NULL,
  used INTEGER NOT NULL DEFAULT 0
);
CREATE TABLE IF NOT EXISTS audit_events (
  id INTEGER PRIMARY KEY AUTOINCREMENT,
  ts REAL NOT NULL,
  user_email TEXT,
  action TEXT NOT NULL,
  detail TEXT
);
CREATE TABLE IF NOT EXISTS projects (
  id INTEGER PRIMARY KEY AUTOINCREMENT,
  name TEXT UNIQUE NOT NULL,
  created_at REAL NOT NULL
);
CREATE TABLE IF NOT EXISTS project_members (
  project_id INTEGER NOT NULL,
  user_id INTEGER NOT NULL,
  role TEXT NOT NULL DEFAULT 'member',
  PRIMARY KEY (project_id, user_id)
);
CREATE TABLE IF NOT EXISTS project_api_keys (
  id INTEGER PRIMARY KEY AUTOINCREMENT,
  project_id INTEGER NOT NULL,
  key_hash TEXT UNIQUE NOT NULL,
  label TEXT,
  created_at REAL NOT NULL,
  revoked INTEGER NOT NULL DEFAULT 0
);
CREATE TABLE IF NOT EXISTS project_budgets (
  project_id INTEGER PRIMARY KEY,
  monthly_budget_micro_usd INTEGER NOT NULL DEFAULT 0,
  spent_micro_usd INTEGER NOT NULL DEFAULT 0
);
CREATE TABLE IF NOT EXISTS agent_registry (
  id INTEGER PRIMARY KEY AUTOINCREMENT,
  name TEXT UNIQUE NOT NULL,
  base_url TEXT NOT NULL,
  auth_kind TEXT,           -- none | bearer_env | api_key_env
  auth_secret_env TEXT,     -- env var name holding the secret (never the secret)
  enabled INTEGER NOT NULL DEFAULT 1,
  last_heartbeat REAL,
  created_at REAL NOT NULL
);
CREATE TABLE IF NOT EXISTS scenario_runs (
  id INTEGER PRIMARY KEY AUTOINCREMENT,
  ts REAL NOT NULL,
  user_email TEXT,
  app_id TEXT NOT NULL,
  prompt TEXT NOT NULL,
  response TEXT,
  warning_action TEXT,
  warning_confidence REAL,
  provider TEXT,
  model TEXT,
  latency_ms INTEGER
);
CREATE TABLE IF NOT EXISTS warning_events (
  id INTEGER PRIMARY KEY AUTOINCREMENT,
  ts REAL NOT NULL,
  app_id TEXT NOT NULL,
  action TEXT NOT NULL,
  confidence REAL NOT NULL,
  pattern_id TEXT,
  failure_id TEXT,
  failure_type TEXT,
  message TEXT,
  source TEXT NOT NULL DEFAULT 'scenario'
);
CREATE TABLE IF NOT EXISTS trace_runs (
  id INTEGER PRIMARY KEY AUTOINCREMENT,
  trace_id TEXT UNIQUE NOT NULL,
  ts REAL NOT NULL,
  app_id TEXT NOT NULL,
  agent_id TEXT,
  project_id INTEGER,
  prompt TEXT,
  response TEXT,
  provider TEXT,
  model TEXT,
  latency_ms INTEGER,
  tokens_in INTEGER,
  tokens_out INTEGER,
  cost_micro_usd INTEGER,
  status TEXT NOT NULL DEFAULT 'ok',
  error TEXT
);
CREATE TABLE IF NOT EXISTS trace_spans (
  id INTEGER PRIMARY KEY AUTOINCREMENT,
  trace_id TEXT NOT NULL,
  parent_id INTEGER,
  name TEXT NOT NULL,
  start_ts REAL NOT NULL,
  end_ts REAL NOT NULL,
  meta_json TEXT
);
CREATE TABLE IF NOT EXISTS run_feedback (
  id INTEGER PRIMARY KEY AUTOINCREMENT,
  trace_id TEXT NOT NULL,
  user_email TEXT,
  thumb TEXT,               -- up | down
  label TEXT,
  note TEXT,
  ts REAL NOT NULL,
  UNIQUE (trace_id, user_email, thumb)
);
CREATE TABLE IF NOT EXISTS prompt_library (
  id INTEGER PRIMARY KEY AUTOINCREMENT,
  name TEXT UNIQUE NOT NULL,
  description TEXT,
  created_at REAL NOT NULL
);
CREATE TABLE IF NOT EXISTS prompt_versions (
  id INTEGER PRIMARY KEY AUTOINCREMENT,
  prompt_id INTEGER NOT NULL,
  version INTEGER NOT NULL,
  text TEXT NOT NULL,
  created_at REAL NOT NULL,
  UNIQUE (prompt_id, version)
);
CREATE TABLE IF NOT EXISTS experiments (
  id INTEGER PRIMARY KEY AUTOINCREMENT,
  name TEXT UNIQUE NOT NULL,
  description TEXT,
  created_at REAL NOT NULL
);
CREATE TABLE IF NOT EXISTS experiment_runs (
  experiment_id INTEGER NOT NULL,
  trace_id TEXT NOT NULL,
  PRIMARY KEY (experiment_id, trace_id)
);
CREATE TABLE IF NOT EXISTS datasets (
  id INTEGER PRIMARY KEY AUTOINCREMENT,
  name TEXT UNIQUE NOT NULL,
  description TEXT,
  created_at REAL NOT NULL
);
CREATE TABLE IF NOT EXISTS dataset_examples (
  id INTEGER PRIMARY KEY AUTOINCREMENT,
  dataset_id INTEGER NOT NULL,
  app_id TEXT NOT NULL DEFAULT 'eval-app',
  prompt TEXT NOT NULL,
  expected TEXT
);
CREATE TABLE IF NOT EXISTS evaluation_runs (
  id INTEGER PRIMARY KEY AUTOINCREMENT,
  dataset_id INTEGER NOT NULL,
  ts REAL NOT NULL,
  user_email TEXT,
  total INTEGER NOT NULL DEFAULT 0,
  passed INTEGER NOT NULL DEFAULT 0,
  status TEXT NOT NULL DEFAULT 'done'
);
CREATE TABLE IF NOT EXISTS evaluation_results (
  id INTEGER PRIMARY KEY AUTOINCREMENT,
  eval_run_id INTEGER NOT NULL,
  example_id INTEGER NOT NULL,
  trace_id TEXT,
  passed INTEGER NOT NULL,
  detail TEXT,
  latency_ms INTEGER,
  provider TEXT
);
CREATE INDEX IF NOT EXISTS idx_trace_runs_ts ON trace_runs (ts);
CREATE INDEX IF NOT EXISTS idx_trace_runs_app ON trace_runs (app_id);
CREATE INDEX IF NOT EXISTS idx_warning_events_ts ON warning_events (ts);
CREATE INDEX IF NOT EXISTS idx_spans_trace ON trace_spans (trace_id);
CREATE INDEX IF NOT EXISTS idx_audit_ts ON audit_events (ts);
"""

# Columns added after initial release ship as idempotent ALTERs, mirroring
# the reference's migrate_db approach (reference: services/dashboard/db.py:368-644).
_MIGRATIONS: List[str] = [
    "ALTER TABLE trace_runs ADD COLUMN tags_json TEXT",
    "ALTER TABLE scenario_runs ADD COLUMN trace_id TEXT",
    "ALTER TABLE agent_registry ADD COLUMN capabilities_json TEXT",
]


class Database:
    def __init__(self, path: str | Path):
        self.path = str(path)
        if self.path != ":memory:":
            Path(self.path).parent.mkdir(parents=True, exist_ok=True)
        self._memory_conn: Optional[sqlite3.Connection] = None
        if self.path == ":memory:":
            self._memory_conn = self._open()
        self.init()

    def _open(self) -> sqlite3.Connection:
        conn = sqlite3.connect(self.path, timeout=10.0)
        conn.row_factory = sqlite3.Row
        conn.execute("PRAGMA journal_mode=WAL")
        conn.execute("PRAGMA foreign_keys=ON")
        return conn

    def connect(self) -> sqlite3.Connection:
        return self._memory_conn if self._memory_conn is not None else self._open()

    def _close(self, conn: sqlite3.Connection) -> None:
        if conn is not self._memory_conn:
            conn.close()

    def init(self) -> None:
        conn = self.connect()
        try:
            conn.executescript(_SCHEMA)
            for stmt in _MIGRATIONS:
                try:
                    conn.execute(stmt)
                except sqlite3.OperationalError:
                    pass  # column already exists — idempotent by design
            conn.commit()
        finally:
            self._close(conn)

    # --- tiny DAO helpers ------------------------------------------------

    def execute(self, sql: str, params: Iterable[Any] = ()) -> int:
        """Run a statement; returns the inserted rowid (INSERTs only —
        sqlite keeps ``lastrowid`` stale across non-INSERT statements on a
        shared connection, so use :meth:`execute_rowcount` when the caller
        needs matched-row semantics)."""
        conn = self.connect()
        try:
            cur = conn.execute(sql, tuple(params))
            conn.commit()
            return cur.lastrowid or 0
        finally:
            self._close(conn)

    def execute_rowcount(self, sql: str, params: Iterable[Any] = ()) -> int:
        """Run a statement; returns the number of matched/affected rows."""
        conn = self.connect()
        try:
            cur = conn.execute(sql, tuple(params))
            conn.commit()
            return cur.rowcount
        finally:
            self._close(conn)

    def query(self, sql: str, params: Iterable[Any] = ()) -> List[Dict[str, Any]]:
        conn = self.connect()
        try:
            rows = conn.execute(sql, tuple(params)).fetchall()
            return [dict(r) for r in rows]
        finally:
            self._close(conn)

    def one(self, sql: str, params: Iterable[Any] = ()) -> Optional[Dict[str, Any]]:
        rows = self.query(sql, params)
        return rows[0] if rows else None

    # --- bootstrap -------------------------------------------------------

    def bootstrap(self, *, demo_users: bool = True) -> None:
        """Roles + self-repairing demo users
        (reference: services/dashboard/app.py:1273-1329)."""
        from kakveda_tpu.dashboard.auth import hash_password

        for role in ("admin", "operator", "viewer"):
            self.execute("INSERT OR IGNORE INTO roles (name) VALUES (?)", (role,))
        if not demo_users:
            return
        demo = [
            ("admin@local", "admin123", "Admin", "admin"),
            ("operator@local", "operator123", "Operator", "operator"),
            ("viewer@local", "viewer123", "Viewer", "viewer"),
        ]
        for email, pw, name, role in demo:
            user = self.one("SELECT id FROM users WHERE email=?", (email,))
            if user is None:
                uid = self.execute(
                    "INSERT INTO users (email, password_hash, display_name, is_active, created_at)"
                    " VALUES (?,?,?,1,?)",
                    (email, hash_password(pw), name, time.time()),
                )
            else:
                uid = user["id"]
                # self-repair: demo accounts always reactivate with known creds
                self.execute(
                    "UPDATE users SET password_hash=?, is_active=1 WHERE id=?",
                    (hash_password(pw), uid),
                )
            rid = self.one("SELECT id FROM roles WHERE name=?", (role,))["id"]
            self.execute(
                "INSERT OR IGNORE INTO user_roles (user_id, role_id) VALUES (?,?)", (uid, rid)
            )

    # --- common lookups --------------------------------------------------

    def user_by_email(self, email: str) -> Optional[Dict[str, Any]]:
        return self.one("SELECT * FROM users WHERE email=?", (email,))

    def user_roles(self, user_id: int) -> List[str]:
        rows = self.query(
            "SELECT r.name FROM roles r JOIN user_roles ur ON ur.role_id=r.id WHERE ur.user_id=?",
            (user_id,),
        )
        return [r["name"] for r in rows]

    def audit(self, user_email: Optional[str], action: str, detail: Any = None) -> None:
        self.execute(
            "INSERT INTO audit_events (ts, user_email, action, detail) VALUES (?,?,?,?)",
            (time.time(), user_email, action, json.dumps(detail) if detail is not None else None),
        )

    def add_span(
        self,
        trace_id: str,
        name: str,
        start_ts: float,
        end_ts: float,
        parent_id: Optional[int] = None,
        meta: Optional[dict] = None,
    ) -> int:
        return self.execute(
            "INSERT INTO trace_spans (trace_id, parent_id, name, start_ts, end_ts, meta_json)"
            " VALUES (?,?,?,?,?,?)",
            (trace_id, parent_id, name, start_ts, end_ts, json.dumps(meta or {})),
        )


# --- Postgres backend ------------------------------------------------------

# Tables without a surrogate ``id`` column — INSERTs into these skip the
# RETURNING clause the Postgres path uses in place of sqlite's lastrowid.
_IDLESS_TABLES = frozenset(
    {"user_roles", "project_members", "experiment_runs", "project_budgets",
     "password_reset_tokens"}
)

_INSERT_RE = re.compile(r"^\s*INSERT\s+(OR\s+IGNORE\s+)?INTO\s+(\w+)", re.IGNORECASE)


def pg_translate(sql: str) -> str:
    """Route-layer (sqlite-flavored) SQL → Postgres. Only the constructs
    this codebase uses: qmark params and INSERT OR IGNORE."""
    m = _INSERT_RE.match(sql)
    ignore = bool(m and m.group(1))
    if ignore:
        sql = re.sub(
            r"INSERT\s+OR\s+IGNORE\s+INTO", "INSERT INTO", sql, count=1, flags=re.IGNORECASE
        )
    out = sql.replace("?", "%s")
    if ignore:
        out += " ON CONFLICT DO NOTHING"
    return out


def pg_schema(schema_sql: str) -> List[str]:
    """The shared DDL → Postgres statements (AUTOINCREMENT → BIGSERIAL)."""
    ddl = schema_sql.replace("INTEGER PRIMARY KEY AUTOINCREMENT", "BIGSERIAL PRIMARY KEY")
    return [s.strip() for s in ddl.split(";") if s.strip()]


class PgDatabase:
    """Same DAO surface as :class:`Database`, speaking Postgres.

    Gated on psycopg2 being importable — the driver is not vendored; the
    prod compose image installs it (docker-compose.prod.yml)."""

    def __init__(self, url: str):
        try:
            import psycopg2  # noqa: F401
            import psycopg2.extras  # noqa: F401
        except ImportError as e:  # pragma: no cover - driver present in prod image
            raise RuntimeError(
                "KAKVEDA_DB_URL points at Postgres but psycopg2 is not "
                "installed; pip install psycopg2-binary (the prod compose "
                "image does) or unset KAKVEDA_DB_URL for sqlite"
            ) from e
        self.url = url
        self.path = url  # parity with Database.path for logs/doctor
        self.init()

    def connect(self):
        import psycopg2
        import psycopg2.extras

        return psycopg2.connect(self.url, cursor_factory=psycopg2.extras.RealDictCursor)

    def init(self) -> None:
        conn = self.connect()
        try:
            with conn.cursor() as cur:
                for stmt in pg_schema(_SCHEMA):
                    cur.execute(stmt)
            conn.commit()
            for stmt in _MIGRATIONS:
                try:
                    with conn.cursor() as cur:
                        cur.execute(pg_translate(stmt))
                    conn.commit()
                except Exception:  # noqa: BLE001 — column exists: idempotent
                    conn.rollback()
        finally:
            conn.close()

    def execute(self, sql: str, params: Iterable[Any] = ()) -> int:
        tr = pg_translate(sql)
        m = _INSERT_RE.match(sql)
        want_id = bool(m) and m.group(2).lower() not in _IDLESS_TABLES and "RETURNING" not in tr.upper()
        if want_id:
            tr += " RETURNING id"
        conn = self.connect()
        try:
            with conn.cursor() as cur:
                cur.execute(tr, tuple(params))
                rid = 0
                if want_id:
                    row = cur.fetchone()
                    rid = int(row["id"]) if row else 0
            conn.commit()
            return rid
        finally:
            conn.close()

    def execute_rowcount(self, sql: str, params: Iterable[Any] = ()) -> int:
        conn = self.connect()
        try:
            with conn.cursor() as cur:
                cur.execute(pg_translate(sql), tuple(params))
                rc = cur.rowcount
            conn.commit()
            return rc
        finally:
            conn.close()

    def query(self, sql: str, params: Iterable[Any] = ()) -> List[Dict[str, Any]]:
        conn = self.connect()
        try:
            with conn.cursor() as cur:
                cur.execute(pg_translate(sql), tuple(params))
                return [dict(r) for r in cur.fetchall()]
        finally:
            conn.close()

    def one(self, sql: str, params: Iterable[Any] = ()) -> Optional[Dict[str, Any]]:
        rows = self.query(sql, params)
        return rows[0] if rows else None

    # Shared helpers are identical SQL-wise — reuse Database's implementations.
    bootstrap = Database.bootstrap
    user_by_email = Database.user_by_email
    user_roles = Database.user_roles
    audit = Database.audit
    add_span = Database.add_span


def make_database(path: str | Path):
    """sqlite at ``path`` unless KAKVEDA_DB_URL selects Postgres — one env
    var flips the whole dashboard, no route changes."""
    url = os.environ.get("KAKVEDA_DB_URL", "").strip()
    if url.startswith(("postgres://", "postgresql://")):
        return PgDatabase(url)
    return Database(path)


def new_trace_id() -> str:
    return str(uuid.uuid4())
