"""Best-effort SMTP email delivery (reference:
services/dashboard/app.py:67-92).

Configured entirely from env (SMTP_HOST/PORT/USER/PASS/FROM/TLS); returns
False rather than raising when unconfigured or the send fails, so callers
can fall back to demo-mode behavior (inline reset link outside production).
"""

from __future__ import annotations

import logging
import os
import smtplib
from email.message import EmailMessage

logger = logging.getLogger("kakveda.email")


def smtp_configured() -> bool:
    return bool(os.environ.get("SMTP_HOST") and os.environ.get("SMTP_USER"))


def send_email(to: str, subject: str, body: str) -> bool:
    host = os.environ.get("SMTP_HOST")
    user = os.environ.get("SMTP_USER")
    password = os.environ.get("SMTP_PASS", "")
    if not host or not user:
        return False
    port = int(os.environ.get("SMTP_PORT", "587"))
    sender = os.environ.get("SMTP_FROM", "noreply@localhost")
    use_tls = os.environ.get("SMTP_TLS", "true").lower() in ("1", "true", "yes")
    try:
        msg = EmailMessage()
        msg["From"] = sender
        msg["To"] = to
        msg["Subject"] = subject
        msg.set_content(body)
        with smtplib.SMTP(host, port, timeout=10) as s:
            if use_tls:
                s.starttls()
            s.login(user, password)
            s.send_message(msg)
        return True
    except Exception as exc:  # noqa: BLE001 — delivery is best-effort
        logger.error("SMTP send failed: %s", exc)
        return False
