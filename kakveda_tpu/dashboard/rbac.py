"""Role-based access control (reference: services/dashboard/rbac.py:6-18)."""

from __future__ import annotations

from typing import Iterable

ADMIN = "admin"
OPERATOR = "operator"
VIEWER = "viewer"

ALL_ROLES = (ADMIN, OPERATOR, VIEWER)


def has_role(user_roles: Iterable[str], role: str) -> bool:
    return role in set(user_roles)


def require_any(user_roles: Iterable[str], allowed: Iterable[str]) -> bool:
    return bool(set(user_roles) & set(allowed))
