"""Admin, agent registry, projects, public ingest API
(reference: services/dashboard/app.py:811-1179, 1436-1605, 2675-2763,
3651-3694)."""

from __future__ import annotations

import hashlib
import json
import secrets
import shutil
import time
from datetime import datetime, timezone
from typing import Optional

from aiohttp import web

from kakveda_tpu.core.schemas import TracePayload
from kakveda_tpu.dashboard.core import (
    CTX_KEY,
    PROJECT_COOKIE,
    VIEW_AS_COOKIE,
    require_login,
    require_roles,
)
from kakveda_tpu.dashboard.routes_main import estimate_cost_micro_usd, estimate_tokens


def _hash_api_key(key: str) -> str:
    return hashlib.sha256(key.encode("utf-8")).hexdigest()


def setup(app: web.Application) -> None:
    ctx = app[CTX_KEY]
    plat = ctx.platform

    # ------------------------------------------------------------------
    # admin: users, audit, impersonation, purge
    # ------------------------------------------------------------------

    @require_roles("admin")
    async def admin_users(request):
        users = ctx.db.query("SELECT * FROM users ORDER BY email")
        for u in users:
            u["roles"] = ctx.db.user_roles(u["id"])
        return ctx.render(request, "admin_users.html", users=users)

    @require_roles("admin")
    async def admin_set_role(request):
        form = await request.post()
        uid = int(form.get("user_id", 0))
        role = str(form.get("role") or "")
        rid_row = ctx.db.one("SELECT id FROM roles WHERE name=?", (role,))
        if rid_row is None:
            raise web.HTTPBadRequest(text="unknown role")
        ctx.db.execute("DELETE FROM user_roles WHERE user_id=?", (uid,))
        ctx.db.execute(
            "INSERT INTO user_roles (user_id, role_id) VALUES (?,?)", (uid, rid_row["id"])
        )
        ctx.db.audit(request["user"].email, "admin.set_role", {"user_id": uid, "role": role})
        raise web.HTTPFound("/admin/users")

    @require_roles("admin")
    async def admin_toggle_active(request):
        form = await request.post()
        uid = int(form.get("user_id", 0))
        ctx.db.execute("UPDATE users SET is_active = 1 - is_active WHERE id=?", (uid,))
        ctx.db.audit(request["user"].email, "admin.toggle_active", {"user_id": uid})
        raise web.HTTPFound("/admin/users")

    @require_roles("admin")
    async def admin_impersonate(request):
        """'View as' — second cookie, honored only for admins
        (reference: services/dashboard/app.py:2730-2763)."""
        form = await request.post()
        email = str(form.get("email") or "")
        resp = web.HTTPFound("/")
        if email:
            resp.set_cookie(VIEW_AS_COOKIE, email, httponly=True, samesite="Lax")
            ctx.db.audit(request["user"].email, "admin.impersonate", {"as": email})
        else:
            resp.del_cookie(VIEW_AS_COOKIE)
            ctx.db.audit(request["user"].email, "admin.impersonate.clear")
        raise resp

    @require_roles("admin")
    async def admin_audit(request):
        events = ctx.db.query("SELECT * FROM audit_events ORDER BY ts DESC LIMIT 200")
        return ctx.render(request, "admin_audit.html", events=events)

    _DEMO_APPS = {"app-A", "app-B"}

    def _demo_counts():
        """Per-store (demo rows, total rows) for the purge preview.
        Patterns count from the in-memory union — the delta-append log's
        raw lines don't carry full membership."""
        out = []
        data_dir = plat.gfkb.data_dir
        for name in ("failures.jsonl", "health.jsonl"):
            p = data_dir / name
            demo = total = 0
            if p.exists():
                for line in p.read_text(encoding="utf-8").splitlines():
                    if not line.strip():
                        continue
                    total += 1
                    try:
                        row = json.loads(line)
                    except json.JSONDecodeError:
                        continue
                    apps = set(row.get("affected_apps") or [])
                    if row.get("app_id") in _DEMO_APPS or (apps and apps <= _DEMO_APPS):
                        demo += 1
            out.append({"store": name, "demo": demo, "total": total})
        pats = plat.gfkb.list_patterns()
        demo_pats = sum(
            1 for p in pats if p.affected_apps and set(p.affected_apps) <= _DEMO_APPS
        )
        out.append({"store": "patterns", "demo": demo_pats, "total": len(pats)})
        for table in ("trace_runs", "warning_events", "scenario_runs"):
            demo = sum(
                ctx.db.one(f"SELECT COUNT(*) AS n FROM {table} WHERE app_id=?", (a,))["n"]
                for a in _DEMO_APPS
            )
            total = ctx.db.one(f"SELECT COUNT(*) AS n FROM {table}")["n"]
            out.append({"store": f"db:{table}", "demo": demo, "total": total})
        return out

    def _backups():
        data_dir = plat.gfkb.data_dir
        return sorted(
            (
                {"name": p.name, "size": p.stat().st_size}
                for p in data_dir.glob("*.bak-*")
            ),
            key=lambda b: b["name"],
            reverse=True,
        )

    @require_roles("admin")
    async def admin_purge_demo_page(request):
        """Preview + confirm flow before the destructive purge
        (reference: services/dashboard/app.py:811-830 + its
        admin_purge_demo.html): shows what will be removed and the existing
        timestamped backups; the POST requires an explicit confirmation."""
        return ctx.render(
            request,
            "admin_purge_demo.html",
            apps=sorted(_DEMO_APPS),
            counts=_demo_counts(),
            backups=_backups(),
            message=request.query.get("message") or "",
            error=request.query.get("error") or "",
        )

    @require_roles("admin")
    async def admin_purge_demo(request):
        """Backup then purge demo apps app-A/app-B from JSONL + DB
        (reference: services/dashboard/app.py:833-867)."""
        form = await request.post()
        if form.get("confirm") != "yes":
            raise web.HTTPFound("/admin/purge-demo?error=confirmation%20required")
        demo_apps = _DEMO_APPS
        stamp = datetime.now(timezone.utc).strftime("%Y%m%d-%H%M%S")
        data_dir = plat.gfkb.data_dir
        for name in ("failures.jsonl", "patterns.jsonl", "health.jsonl"):
            p = data_dir / name
            if p.exists():
                shutil.copy2(p, p.with_suffix(f".jsonl.bak-{stamp}"))
        # JSONL purge (reference: services/dashboard/app.py:330-375 purges
        # all three stores). failures/health filter line-by-line; a corrupt
        # line (crash mid-append) is skipped, not fatal — the preview
        # already tolerates it and a purge must not 500 after backing up.
        def _purge_jsonl(path):
            if not path.exists():
                return
            kept = []
            for line in path.read_text(encoding="utf-8").splitlines():
                if not line.strip():
                    continue
                try:
                    row = json.loads(line)
                except json.JSONDecodeError:
                    continue
                apps = set(row.get("affected_apps") or [])
                if row.get("app_id") in demo_apps or (apps and apps <= demo_apps):
                    continue
                kept.append(line)
            path.write_text("\n".join(kept) + ("\n" if kept else ""), encoding="utf-8")

        # Admin-only, confirmed purge: a timestamped .bak was copied above
        # and gfkb.reload() below replays the result — a crash mid-rewrite
        # loses at most this purge, recoverable from the backup.
        _purge_jsonl(plat.gfkb.failures_path)  # kakveda: allow[atomic-log-rewrite]
        _purge_jsonl(plat.health.health_path)
        # The patterns log is DELTA-append (each line carries only that
        # upsert's new members), so line filtering can't remove an app from
        # a pattern. Rewrite it CONSOLIDATED from the in-memory union:
        # full-membership lines minus demo apps; patterns spanning only
        # demo apps disappear. (Replay unions full lines identically.)
        kept_lines = []
        for pat in plat.gfkb.list_patterns():
            apps = [a for a in pat.affected_apps if a not in demo_apps]
            if not apps:
                continue
            cleaned = pat.model_copy(update={"affected_apps": apps})
            kept_lines.append(cleaned.model_dump_json())
        # Log rewrite + full GFKB replay are seconds of disk/CPU at scale —
        # off the event loop, or every dashboard request stalls behind the
        # purge (event-loop-blocking rule).
        import asyncio

        loop = asyncio.get_running_loop()
        rewritten = "\n".join(kept_lines) + ("\n" if kept_lines else "")
        await loop.run_in_executor(
            None,
            # Same admin-purge exception as _purge_jsonl: .bak taken above,
            # reload() replays below.
            lambda: plat.gfkb.patterns_path.write_text(rewritten, encoding="utf-8"),  # kakveda: allow[atomic-log-rewrite]
        )
        for app_id in demo_apps:
            ctx.db.execute("DELETE FROM trace_runs WHERE app_id=?", (app_id,))
            ctx.db.execute("DELETE FROM warning_events WHERE app_id=?", (app_id,))
            ctx.db.execute("DELETE FROM scenario_runs WHERE app_id=?", (app_id,))
        # The device index and host metadata were built from the pre-purge
        # log — replay the rewritten files so queries and id minting agree.
        await loop.run_in_executor(None, plat.gfkb.reload)
        ctx.db.audit(request["user"].email, "admin.purge_demo", {"apps": sorted(demo_apps)})
        from urllib.parse import quote

        msg = f"Purged demo apps {sorted(demo_apps)}; backups stamped {stamp}."
        raise web.HTTPFound(f"/admin/purge-demo?message={quote(msg)}")

    # ------------------------------------------------------------------
    # agent registry
    # ------------------------------------------------------------------

    @require_login
    async def agents_page(request):
        agents = ctx.db.query("SELECT * FROM agent_registry ORDER BY name")
        return ctx.render(request, "agents.html", agents=agents, test_result=None)

    @require_roles("admin")
    async def admin_agents_page(request):
        """Dedicated agent-management page (reference:
        services/dashboard/app.py:949-1087 + admin_agents.html): full
        register/update form, enable toggle, health test, removal."""
        agents = ctx.db.query("SELECT * FROM agent_registry ORDER BY name")
        return ctx.render(request, "admin_agents.html", agents=agents, test_result=None)

    @require_roles("admin")
    async def admin_serving_page(request):
        """Serving observability: which runtime backs generation, the
        shared engine's pool state (submitted/completed/max_active,
        slots/window), the serving-lever flags (weight + KV quant), and —
        under a multi-model router — the HBM budget accounting (resident
        models, bytes, headroom). No reference counterpart (its model
        tier is a stateless per-request Ollama hop)."""
        stats = ctx.model.serving_stats() if hasattr(ctx.model, "serving_stats") else {
            "runtime": getattr(ctx.model, "name", "unknown"), "engine": None,
        }
        return ctx.render(
            request, "admin_serving.html", stats=stats,
            prefix_result=request.query.get("prefix", ""),
            can_register_prefix=callable(getattr(ctx.model, "register_prefix", None)),
        )

    @require_roles("admin")
    async def admin_serving_prefix(request):
        """Register a shared prompt prefix (system preamble) on the serving
        engine from the ops panel — later requests starting with it
        prefill only their suffix (models/serving.py prefix cache)."""
        from kakveda_tpu.dashboard.routes_main import off_loop

        form = await request.post()
        text = str(form.get("prefix") or "").strip()
        reg = getattr(ctx.model, "register_prefix", None)
        if not text or not callable(reg):
            raise web.HTTPFound("/admin/serving?prefix=unsupported")
        ok = await off_loop(reg, text)
        ctx.db.audit(
            request["user"].email, "serving.prefix_register",
            {"chars": len(text), "accepted": bool(ok)},
        )
        raise web.HTTPFound(f"/admin/serving?prefix={'registered' if ok else 'refused'}")

    @require_roles("admin")
    async def admin_agent_delete(request):
        form = await request.post()
        name = str(form.get("name") or "")
        ctx.db.execute("DELETE FROM agent_registry WHERE name=?", (name,))
        ctx.db.audit(request["user"].email, "agent.delete", {"name": name})
        raise web.HTTPFound("/admin/agents")

    @require_roles("admin")
    async def admin_agent_test(request):
        """Health test rendered back into the admin page."""
        name = request.match_info["name"]
        agent = ctx.db.one("SELECT * FROM agent_registry WHERE name=?", (name,))
        if agent is None:
            raise web.HTTPNotFound(text="agent not found")
        import httpx

        from kakveda_tpu.dashboard.routes_main import off_loop

        try:
            r = await off_loop(httpx.get, f"{agent['base_url']}/health", timeout=5.0)
            result = {"status": r.status_code, "body": r.json()}
        except Exception as e:  # noqa: BLE001
            result = {"status": 0, "body": {"error": f"{type(e).__name__}: {e}"}}
        agents = ctx.db.query("SELECT * FROM agent_registry ORDER BY name")
        return ctx.render(
            request, "admin_agents.html", agents=agents, test_result={"name": name, **result}
        )

    @require_roles("admin")
    async def agent_register(request):
        form = await request.post()
        name = str(form.get("name") or "").strip()
        base_url = str(form.get("base_url") or "").strip()
        if not name or not base_url:
            raise web.HTTPBadRequest(text="name and base_url required")
        ctx.db.execute(
            "INSERT OR REPLACE INTO agent_registry (name, base_url, auth_kind, auth_secret_env,"
            " enabled, created_at) VALUES (?,?,?,?,1,?)",
            (
                name,
                base_url,
                str(form.get("auth_kind") or "none"),
                # env-var *name*, never the secret itself
                str(form.get("auth_secret_env") or "") or None,
                time.time(),
            ),
        )
        ctx.db.audit(request["user"].email, "agent.register", {"name": name})
        nxt = str(form.get("next") or "/agents")
        # Reject protocol-relative //host targets, not just absolute URLs.
        raise web.HTTPFound(nxt if nxt.startswith("/") and not nxt.startswith("//") else "/agents")

    @require_roles("admin")
    async def agent_toggle(request):
        form = await request.post()
        name = str(form.get("name") or "")
        ctx.db.execute("UPDATE agent_registry SET enabled = 1 - enabled WHERE name=?", (name,))
        nxt = str(form.get("next") or "/agents")
        raise web.HTTPFound(nxt if nxt.startswith("/") and not nxt.startswith("//") else "/agents")

    @require_login
    async def agent_test(request):
        """Health-check an agent (reference: app.py:874-946)."""
        name = request.match_info["name"]
        agent = ctx.db.one("SELECT * FROM agent_registry WHERE name=?", (name,))
        if agent is None:
            raise web.HTTPNotFound(text="agent not found")
        import httpx

        from kakveda_tpu.dashboard.routes_main import off_loop

        try:
            r = await off_loop(httpx.get, f"{agent['base_url']}/health", timeout=5.0)
            result = {"status": r.status_code, "body": r.json()}
        except Exception as e:  # noqa: BLE001
            result = {"status": 0, "body": {"error": f"{type(e).__name__}: {e}"}}
        agents = ctx.db.query("SELECT * FROM agent_registry ORDER BY name")
        return ctx.render(
            request, "agents.html", agents=agents, test_result={"name": name, **result}
        )

    async def agent_self_register(request):
        """External agents may self-register (reference: app.py:1105-1160)."""
        body = await request.json()
        name = str(body.get("name") or "").strip()
        base_url = str(body.get("base_url") or "").strip()
        if not name or not base_url:
            return web.json_response({"ok": False, "error": "name and base_url required"}, status=422)
        ctx.db.execute(
            "INSERT OR REPLACE INTO agent_registry (name, base_url, auth_kind, enabled,"
            " capabilities_json, created_at) VALUES (?,?,'none',1,?,?)",
            (name, base_url, json.dumps(body.get("capabilities", [])), time.time()),
        )
        return web.json_response({"ok": True, "name": name})

    async def agent_heartbeat(request):
        name = request.match_info["name"]
        n = ctx.db.execute_rowcount(
            "UPDATE agent_registry SET last_heartbeat=? WHERE name=?", (time.time(), name)
        )
        if not n:
            return web.json_response({"ok": False, "error": "unknown agent"}, status=404)
        return web.json_response({"ok": True})

    async def api_agents(request):
        agents = ctx.db.query("SELECT name, base_url, enabled, last_heartbeat FROM agent_registry")
        return web.json_response({"agents": agents})

    # ------------------------------------------------------------------
    # projects + API keys + budgets
    # ------------------------------------------------------------------

    @require_login
    async def projects_page(request):
        projects = ctx.db.query(
            "SELECT p.*, b.monthly_budget_micro_usd, b.spent_micro_usd FROM projects p"
            " LEFT JOIN project_budgets b ON b.project_id=p.id ORDER BY p.name"
        )
        return ctx.render(request, "projects.html", projects=projects, new_key=None)

    @require_roles("admin", "operator")
    async def project_create(request):
        form = await request.post()
        name = str(form.get("name") or "").strip()
        if not name:
            raise web.HTTPBadRequest(text="name required")
        ctx.db.execute(
            "INSERT OR IGNORE INTO projects (name, created_at) VALUES (?,?)", (name, time.time())
        )
        # Re-read the id: an ignored duplicate insert returns no usable
        # lastrowid, and re-submitting an existing project must still be
        # able to set its budget.
        pid = ctx.db.one("SELECT id FROM projects WHERE name=?", (name,))["id"]
        budget = int(form.get("monthly_budget_micro_usd") or 0)
        if pid and budget:
            ctx.db.execute(
                "INSERT OR REPLACE INTO project_budgets (project_id, monthly_budget_micro_usd,"
                " spent_micro_usd) VALUES (?,?,COALESCE((SELECT spent_micro_usd FROM"
                " project_budgets WHERE project_id=?),0))",
                (pid, budget, pid),
            )
        ctx.db.audit(request["user"].email, "project.create", {"name": name})
        raise web.HTTPFound("/projects")

    @require_login
    async def project_select(request):
        form = await request.post()
        pid = str(form.get("project_id") or "")
        resp = web.HTTPFound("/projects")
        if pid:
            resp.set_cookie(PROJECT_COOKIE, pid, httponly=True, samesite="Lax")
        else:
            resp.del_cookie(PROJECT_COOKIE)
        raise resp

    @require_login
    async def project_clear(request):
        """Drop the active-project cookie (reference: app.py:1436-1486)."""
        resp = web.HTTPFound("/projects")
        resp.del_cookie(PROJECT_COOKIE)
        raise resp

    @require_roles("admin", "operator")
    async def project_api_key(request):
        """Mint an API key: shown once, stored as sha256
        (reference: app.py:1489-1510)."""
        form = await request.post()
        pid = int(form.get("project_id", 0))
        key = f"kk-{secrets.token_urlsafe(24)}"
        ctx.db.execute(
            "INSERT INTO project_api_keys (project_id, key_hash, label, created_at)"
            " VALUES (?,?,?,?)",
            (pid, _hash_api_key(key), str(form.get("label") or ""), time.time()),
        )
        ctx.db.audit(request["user"].email, "project.api_key.create", {"project_id": pid})
        projects = ctx.db.query(
            "SELECT p.*, b.monthly_budget_micro_usd, b.spent_micro_usd FROM projects p"
            " LEFT JOIN project_budgets b ON b.project_id=p.id ORDER BY p.name"
        )
        return ctx.render(request, "projects.html", projects=projects, new_key=key)

    # ------------------------------------------------------------------
    # public ingest API (X-API-Key) with budget enforcement
    # ------------------------------------------------------------------

    async def api_ingest_run(request):
        """Programmatic run ingestion (reference: app.py:1512-1605)."""
        api_key = request.headers.get("X-API-Key", "")
        if not api_key:
            return web.json_response({"ok": False, "error": "X-API-Key required"}, status=401)
        row = ctx.db.one(
            "SELECT * FROM project_api_keys WHERE key_hash=? AND revoked=0",
            (_hash_api_key(api_key),),
        )
        if row is None:
            return web.json_response({"ok": False, "error": "invalid API key"}, status=403)
        project_id = row["project_id"]

        try:
            body = await request.json()
            prompt = str(body.get("prompt") or "")
            response_text = str(body.get("response") or "")
            app_id = str(body.get("app_id") or "api-app")
        except Exception:  # noqa: BLE001
            return web.json_response({"ok": False, "error": "bad json"}, status=422)

        tokens_in = estimate_tokens(prompt)
        tokens_out = estimate_tokens(response_text)
        cost = estimate_cost_micro_usd(tokens_in, tokens_out)

        status = "ok"
        error: Optional[str] = None
        budget = ctx.db.one("SELECT * FROM project_budgets WHERE project_id=?", (project_id,))
        if budget and budget["monthly_budget_micro_usd"] > 0:
            if budget["spent_micro_usd"] + cost > budget["monthly_budget_micro_usd"]:
                status, error = "error", "budget exceeded"

        from kakveda_tpu.dashboard.db import new_trace_id

        trace_id = str(body.get("trace_id") or new_trace_id())
        inserted = ctx.db.execute_rowcount(
            "INSERT OR IGNORE INTO trace_runs (trace_id, ts, app_id, agent_id, project_id, prompt,"
            " response, provider, model, latency_ms, tokens_in, tokens_out, cost_micro_usd,"
            " status, error, tags_json) VALUES (?,?,?,?,?,?,?,?,?,?,?,?,?,?,?,?)",
            (
                trace_id,
                time.time(),
                app_id,
                str(body.get("agent_id") or "api"),
                project_id,
                prompt,
                response_text,
                str(body.get("provider") or "api"),
                body.get("model"),
                body.get("latency_ms"),
                tokens_in,
                tokens_out,
                cost,
                status,
                error,
                json.dumps(body.get("tags", [])),
            ),
        )
        # A duplicate trace_id is a retry: acknowledge idempotently without
        # charging the budget or re-running the pipeline.
        if inserted == 0:
            return web.json_response(
                {"ok": True, "trace_id": trace_id, "cost_micro_usd": 0, "duplicate": True}
            )
        if status == "ok" and budget:
            ctx.db.execute(
                "UPDATE project_budgets SET spent_micro_usd = spent_micro_usd + ? WHERE project_id=?",
                (cost, project_id),
            )
        if status == "ok":
            await plat.ingest(
                TracePayload(
                    trace_id=trace_id,
                    ts=datetime.now(timezone.utc),
                    app_id=app_id,
                    agent_id=str(body.get("agent_id") or "api"),
                    prompt=prompt,
                    response=response_text,
                    model=body.get("model"),
                    tools=list(body.get("tools", [])),
                    env=dict(body.get("env", {})),
                )
            )
        code = 200 if status == "ok" else 402
        return web.json_response(
            {"ok": status == "ok", "trace_id": trace_id, "cost_micro_usd": cost, "error": error},
            status=code,
        )

    app.add_routes(
        [
            web.get("/admin/users", admin_users),
            web.post("/admin/users/role", admin_set_role),
            web.post("/admin/users/toggle", admin_toggle_active),
            web.post("/admin/impersonate", admin_impersonate),
            web.get("/admin/audit", admin_audit),
            web.get("/admin/purge-demo", admin_purge_demo_page),
            web.post("/admin/purge-demo", admin_purge_demo),
            web.get("/admin/agents", admin_agents_page),
            web.get("/admin/serving", admin_serving_page),
            web.post("/admin/serving/prefix", admin_serving_prefix),
            web.post("/admin/agents/delete", admin_agent_delete),
            web.get("/admin/agents/{name}/test", admin_agent_test),
            web.get("/agents", agents_page),
            web.post("/agents/register", agent_register),
            web.post("/agents/toggle", agent_toggle),
            web.get("/agents/{name}/test", agent_test),
            web.post("/api/agents/register", agent_self_register),
            web.post("/api/agents/{name}/heartbeat", agent_heartbeat),
            web.get("/api/agents", api_agents),
            web.get("/projects", projects_page),
            web.post("/projects/create", project_create),
            web.post("/projects/select", project_select),
            web.post("/projects/clear", project_clear),
            web.post("/projects/api-key", project_api_key),
            web.post("/api/ingest/run", api_ingest_run),
        ]
    )
