"""Auth flows: login/logout/register/forgot/reset
(reference: services/dashboard/app.py:2481-2672)."""

from __future__ import annotations

import os
import re
import time

from aiohttp import web

from kakveda_tpu.core.runtime import get_runtime_config
from kakveda_tpu.dashboard import auth as auth_lib
from kakveda_tpu.dashboard import email as email_lib
from kakveda_tpu.dashboard.core import COOKIE_NAME, CTX_KEY, RATE_LIMITER, VIEW_AS_COOKIE
from kakveda_tpu.dashboard.routes_main import off_loop

_EMAIL_RE = re.compile(r"^[^@\s]+@[^@\s]+\.[^@\s]+$|^[^@\s]+@local$")

# Reference password policy: ≥8 chars with letters and digits
# (reference: services/dashboard/app.py:521-533).
def _password_ok(pw: str) -> bool:
    return len(pw) >= 8 and any(c.isalpha() for c in pw) and any(c.isdigit() for c in pw)


def _client_key(request: web.Request, bucket: str) -> str:
    peer = request.remote or "unknown"
    return f"{bucket}:{peer}"


def setup(app: web.Application) -> None:
    ctx = app[CTX_KEY]

    async def login_page(request):
        return ctx.render(request, "login.html", error=None, next=request.query.get("next", "/"))

    async def login(request):
        if not await RATE_LIMITER.allow_async(_client_key(request, "login"), limit=20):
            return ctx.render(request, "login.html", error="Too many attempts; slow down.", next="/")
        form = await request.post()
        email = str(form.get("email", "")).strip().lower()
        password = str(form.get("password", ""))
        row = ctx.db.user_by_email(email)
        # pbkdf2 is ~100 ms of CPU; keep it off the event loop that serves
        # the /warn micro-batcher.
        pw_ok = row is not None and await off_loop(
            auth_lib.verify_password, password, row["password_hash"]
        )
        if row is None or not row["is_active"] or not pw_ok:
            ctx.db.audit(email, "login.failed")
            return ctx.render(request, "login.html", error="Invalid credentials", next=form.get("next", "/"))
        roles = ctx.db.user_roles(row["id"])
        token = auth_lib.create_access_token(email=email, roles=roles, secret=ctx.jwt_secret)
        nxt = str(form.get("next") or "/")
        # Local-path redirects only: "//evil.com" is protocol-relative and
        # "/\evil.com" gets browser-normalized to it, so backslashes are
        # rejected outright.
        if not nxt.startswith("/") or nxt.startswith("//") or "\\" in nxt:
            nxt = "/"
        resp = web.HTTPFound(nxt)
        resp.set_cookie(COOKIE_NAME, token, httponly=True, samesite="Lax")
        ctx.db.audit(email, "login.ok")
        raise resp

    async def logout(request):
        user = request.get("user")
        # Revoke the token itself (reference:
        # services/dashboard/app.py:2507-2524 + redis_helpers.py:26-59) —
        # deleting the cookie alone leaves a stolen copy valid until expiry.
        token = request.cookies.get(COOKIE_NAME)
        if token:
            claims = auth_lib.decode_token(token, secret=ctx.jwt_secret)
            if claims and claims.get("jti"):
                ctx.revocations.revoke(claims["jti"], float(claims.get("exp", 0)))
        resp = web.HTTPFound("/login")
        resp.del_cookie(COOKIE_NAME)
        resp.del_cookie(VIEW_AS_COOKIE)
        if user:
            ctx.db.audit(user.email, "logout")
        raise resp

    async def register_page(request):
        return ctx.render(request, "register.html", error=None)

    async def register(request):
        if not await RATE_LIMITER.allow_async(_client_key(request, "register"), limit=10):
            return ctx.render(request, "register.html", error="Too many attempts; slow down.")
        form = await request.post()
        email = str(form.get("email", "")).strip().lower()
        password = str(form.get("password", ""))
        name = str(form.get("display_name", "")).strip() or email
        if not _EMAIL_RE.match(email):
            return ctx.render(request, "register.html", error="Invalid email address")
        if not _password_ok(password):
            return ctx.render(
                request, "register.html", error="Password needs ≥8 chars with letters and digits"
            )
        if ctx.db.user_by_email(email) is not None:
            return ctx.render(request, "register.html", error="Account already exists")
        pw_hash = await off_loop(auth_lib.hash_password, password)
        uid = ctx.db.execute(
            "INSERT INTO users (email, password_hash, display_name, is_active, created_at)"
            " VALUES (?,?,?,1,?)",
            (email, pw_hash, name, time.time()),
        )
        rid = ctx.db.one("SELECT id FROM roles WHERE name='viewer'")["id"]
        ctx.db.execute("INSERT OR IGNORE INTO user_roles (user_id, role_id) VALUES (?,?)", (uid, rid))
        ctx.db.audit(email, "register")
        raise web.HTTPFound("/login")

    async def forgot_page(request):
        return ctx.render(request, "forgot.html", sent=False, reset_link=None)

    async def forgot(request):
        if not await RATE_LIMITER.allow_async(_client_key(request, "forgot"), limit=5):
            return ctx.render(request, "forgot.html", sent=True, reset_link=None)
        form = await request.post()
        email = str(form.get("email", "")).strip().lower()
        row = ctx.db.user_by_email(email)
        reset_link = None
        if row is not None:
            token = auth_lib.mint_reset_token()
            ctx.db.execute(
                "INSERT INTO password_reset_tokens (token, user_id, expires_at) VALUES (?,?,?)",
                (token, row["id"], time.time() + 3600),
            )
            # SMTP delivery when configured (reference:
            # services/dashboard/app.py:2585-2642); otherwise demo mode shows
            # the link inline — but never in production, where that would
            # hand any account's reset token to an anonymous requester.
            # Mail clients need an absolute URL. Only DASHBOARD_BASE_URL is
            # trusted in production — deriving the base from request.host
            # would let an attacker poison the emailed link via the Host
            # header and harvest the victim's live reset token. Outside
            # production the request origin is a convenience fallback.
            base = os.environ.get("DASHBOARD_BASE_URL", "").rstrip("/")
            if not base and get_runtime_config(service_name="dashboard").env != "production":
                base = f"{request.scheme}://{request.host}"
            link = f"{base}/reset?token={token}"
            sent = False
            if email_lib.smtp_configured():
                sent = await off_loop(
                    email_lib.send_email,
                    email,
                    "Password reset",
                    f"Reset your password: {link}\nThis link expires in 1 hour.",
                )
            if not sent and get_runtime_config(service_name="dashboard").env != "production":
                reset_link = link
            ctx.db.audit(email, "forgot.requested", {"emailed": sent})
        return ctx.render(request, "forgot.html", sent=True, reset_link=reset_link)

    async def reset_page(request):
        return ctx.render(request, "reset.html", token=request.query.get("token", ""), error=None)

    async def reset(request):
        form = await request.post()
        token = str(form.get("token", ""))
        password = str(form.get("password", ""))
        row = ctx.db.one(
            "SELECT * FROM password_reset_tokens WHERE token=? AND used=0 AND expires_at>?",
            (token, time.time()),
        )
        if row is None:
            return ctx.render(request, "reset.html", token=token, error="Invalid or expired token")
        if not _password_ok(password):
            return ctx.render(
                request, "reset.html", token=token, error="Password needs ≥8 chars with letters and digits"
            )
        pw_hash = await off_loop(auth_lib.hash_password, password)
        ctx.db.execute(
            "UPDATE users SET password_hash=? WHERE id=?",
            (pw_hash, row["user_id"]),
        )
        ctx.db.execute("UPDATE password_reset_tokens SET used=1 WHERE token=?", (token,))
        ctx.db.audit(None, "password.reset", {"user_id": row["user_id"]})
        raise web.HTTPFound("/login")

    app.add_routes(
        [
            web.get("/login", login_page),
            web.post("/login", login),
            web.get("/logout", logout),
            web.post("/logout", logout),
            web.get("/register", register_page),
            web.post("/register", register),
            web.get("/forgot", forgot_page),
            web.post("/forgot", forgot),
            web.get("/reset", reset_page),
            web.post("/reset", reset),
        ]
    )
