"""Datasets/evaluations, prompt library, experiments
(reference: services/dashboard/app.py:2229-2478, 3302-3532, 3554-3648)."""

from __future__ import annotations

import time
from datetime import datetime, timezone
from typing import List

from aiohttp import web

from kakveda_tpu.core.fingerprint import detect_citation_markers, prompt_intent_tags
from kakveda_tpu.core.schemas import TracePayload, WarningRequest
from kakveda_tpu.dashboard.core import CTX_KEY, require_login, require_roles
from kakveda_tpu.dashboard.db import new_trace_id
from kakveda_tpu.dashboard.routes_main import estimate_cost_micro_usd, estimate_tokens


def _p50_p95(values: List[int]) -> tuple[float, float]:
    if not values:
        return 0.0, 0.0
    vs = sorted(values)
    return (
        float(vs[len(vs) // 2]),
        float(vs[min(len(vs) - 1, int(len(vs) * 0.95))]),
    )


def citation_check_passes(prompt: str, response: str) -> bool:
    """Deterministic eval check: a citation-demanding prompt must NOT get a
    fabricated-citation response (reference: services/dashboard/app.py:2306-2312)."""
    wants = "intent:citations_required" in prompt_intent_tags(prompt)
    has_markers = detect_citation_markers(response).has_citation_markers
    return not (wants and has_markers)


def setup(app: web.Application) -> None:
    ctx = app[CTX_KEY]
    plat = ctx.platform

    # ------------------------------------------------------------------
    # datasets
    # ------------------------------------------------------------------

    @require_login
    async def datasets_page(request):
        datasets = ctx.db.query(
            "SELECT d.*, COUNT(e.id) AS n_examples FROM datasets d"
            " LEFT JOIN dataset_examples e ON e.dataset_id=d.id GROUP BY d.id ORDER BY d.created_at DESC"
        )
        return ctx.render(request, "datasets.html", datasets=datasets)

    @require_roles("admin", "operator")
    async def dataset_create(request):
        form = await request.post()
        name = str(form.get("name") or "").strip()
        if not name:
            raise web.HTTPBadRequest(text="name required")
        ctx.db.execute(
            "INSERT OR IGNORE INTO datasets (name, description, created_at) VALUES (?,?,?)",
            (name, str(form.get("description") or ""), time.time()),
        )
        raise web.HTTPFound("/datasets")

    @require_login
    async def dataset_detail(request):
        ds_id = int(request.match_info["ds_id"])
        ds = ctx.db.one("SELECT * FROM datasets WHERE id=?", (ds_id,))
        if ds is None:
            raise web.HTTPNotFound(text="dataset not found")
        examples = ctx.db.query("SELECT * FROM dataset_examples WHERE dataset_id=?", (ds_id,))
        evals = ctx.db.query(
            "SELECT * FROM evaluation_runs WHERE dataset_id=? ORDER BY ts DESC", (ds_id,)
        )
        return ctx.render(request, "dataset_detail.html", ds=ds, examples=examples, evals=evals)

    @require_roles("admin", "operator")
    async def example_add(request):
        ds_id = int(request.match_info["ds_id"])
        form = await request.post()
        prompt = str(form.get("prompt") or "").strip()
        if not prompt:
            raise web.HTTPBadRequest(text="prompt required")
        ctx.db.execute(
            "INSERT INTO dataset_examples (dataset_id, app_id, prompt, expected) VALUES (?,?,?,?)",
            (ds_id, str(form.get("app_id") or "eval-app"), prompt, str(form.get("expected") or "")),
        )
        raise web.HTTPFound(f"/datasets/{ds_id}")

    def _persist_trace(ex: dict, gen, trace_id: str, ts: float) -> TracePayload:
        """Rich trace_runs row + the TracePayload to ingest, shared by the
        single-example and batched-eval paths so the 13-column insert can't
        drift between them. The row goes in BEFORE plat.ingest — the
        trace.ingested subscriber writes a sparse fallback row and
        INSERT OR IGNORE is first-wins."""
        tin, tout = estimate_tokens(ex["prompt"]), estimate_tokens(gen.text)
        ctx.db.execute(
            "INSERT OR IGNORE INTO trace_runs (trace_id, ts, app_id, agent_id, prompt, response,"
            " provider, model, latency_ms, tokens_in, tokens_out, cost_micro_usd, status)"
            " VALUES (?,?,?,?,?,?,?,?,?,?,?,?,'ok')",
            (
                trace_id,
                ts,
                ex["app_id"],
                "eval",
                ex["prompt"],
                gen.text,
                gen.meta.get("provider"),
                gen.meta.get("model"),
                gen.meta.get("latency_ms"),
                tin,
                tout,
                estimate_cost_micro_usd(tin, tout),
            ),
        )
        return TracePayload(
            trace_id=trace_id,
            ts=datetime.now(timezone.utc),
            app_id=ex["app_id"],
            agent_id="eval",
            prompt=ex["prompt"],
            response=gen.text,
            model=gen.meta.get("model"),
            tools=[],
            env={},
        )

    async def _run_one_example(ex: dict, prewarned: bool = False) -> dict:
        """warn → generate → deterministic check → trace persist.
        ``prewarned=True`` when the caller already warned the whole dataset
        in one batched device call."""
        trace_id = new_trace_id()
        t0 = time.time()
        from kakveda_tpu.dashboard.routes_main import off_loop

        if not prewarned:
            await off_loop(
                plat.warn,
                WarningRequest(
                    app_id=ex["app_id"], agent_id="eval", prompt=ex["prompt"], tools=[], env={}
                ),
            )
        gen = await off_loop(ctx.model.generate, ex["prompt"])
        passed = citation_check_passes(ex["prompt"], gen.text)
        await plat.ingest(_persist_trace(ex, gen, trace_id, t0))
        return {
            "trace_id": trace_id,
            "passed": passed,
            "latency_ms": gen.meta.get("latency_ms", 0),
            "provider": gen.meta.get("provider"),
        }

    @require_roles("admin", "operator")
    async def example_run_now(request):
        ds_id = int(request.match_info["ds_id"])
        ex_id = int(request.match_info["ex_id"])
        ex = ctx.db.one(
            "SELECT * FROM dataset_examples WHERE id=? AND dataset_id=?", (ex_id, ds_id)
        )
        if ex is None:
            raise web.HTTPNotFound(text="example not found")
        res = await _run_one_example(ex)
        raise web.HTTPFound(f"/runs/{res['trace_id']}")

    # ------------------------------------------------------------------
    # evaluations
    # ------------------------------------------------------------------

    @require_roles("admin", "operator")
    async def eval_run(request):
        ds_id = int(request.match_info["ds_id"])
        examples = ctx.db.query("SELECT * FROM dataset_examples WHERE dataset_id=?", (ds_id,))
        if not examples:
            raise web.HTTPBadRequest(text="dataset has no examples")
        run_id = ctx.db.execute(
            "INSERT INTO evaluation_runs (dataset_id, ts, user_email, total, passed, status)"
            " VALUES (?,?,?,?,0,'running')",
            (ds_id, time.time(), request["user"].email, len(examples)),
        )
        # The whole dataset runs as THREE batched calls — one warn_batch
        # (single compiled matmul+top-k), one generate_batch (single padded
        # decode stream on the TPU runtime), one ingest_batch (single
        # classify+embed+insert) — where the reference loops
        # warn→generate→ingest one example at a time
        # (reference: services/dashboard/app.py:2315-2393, SURVEY §3.4's
        # "obvious batch-parallel target"). Per-example results are
        # unchanged: generate_batch is exact left-padded batching.
        from kakveda_tpu.dashboard.routes_main import off_loop
        from kakveda_tpu.models.runtime import generate_batch

        await off_loop(
            plat.warn_batch,
            [
                WarningRequest(
                    app_id=ex["app_id"], agent_id="eval", prompt=ex["prompt"], tools=[], env={}
                )
                for ex in examples
            ],
        )
        t0 = time.time()
        gens = await off_loop(generate_batch, ctx.model, [ex["prompt"] for ex in examples])
        passed = 0
        traces = []
        for ex, gen in zip(examples, gens):
            trace_id = new_trace_id()
            ok = citation_check_passes(ex["prompt"], gen.text)
            passed += int(ok)
            traces.append(_persist_trace(ex, gen, trace_id, t0))
            ctx.db.execute(
                "INSERT INTO evaluation_results (eval_run_id, example_id, trace_id, passed,"
                " detail, latency_ms, provider) VALUES (?,?,?,?,?,?,?)",
                (
                    run_id,
                    ex["id"],
                    trace_id,
                    int(ok),
                    None if ok else "citation hallucination detected",
                    gen.meta.get("latency_ms", 0),
                    gen.meta.get("provider"),
                ),
            )
        await plat.ingest_batch(traces)
        ctx.db.execute(
            "UPDATE evaluation_runs SET passed=?, status='done' WHERE id=?", (passed, run_id)
        )
        ctx.db.audit(request["user"].email, "eval.run", {"dataset_id": ds_id, "run_id": run_id})
        raise web.HTTPFound(f"/eval/{run_id}")

    @require_login
    async def evals_page(request):
        """All evaluation runs across datasets, newest first."""
        runs = ctx.db.query(
            "SELECT e.*, d.name AS dataset_name FROM evaluation_runs e"
            " LEFT JOIN datasets d ON d.id=e.dataset_id ORDER BY e.ts DESC LIMIT 200"
        )
        return ctx.render(request, "evals.html", runs=runs)

    @require_login
    async def eval_detail(request):
        """Pass-rate + p50/p95 latency + provider split
        (reference: services/dashboard/app.py:2396-2478)."""
        run_id = int(request.match_info["run_id"])
        run = ctx.db.one("SELECT * FROM evaluation_runs WHERE id=?", (run_id,))
        if run is None:
            raise web.HTTPNotFound(text="eval run not found")
        results = ctx.db.query("SELECT * FROM evaluation_results WHERE eval_run_id=?", (run_id,))
        lat = [r["latency_ms"] or 0 for r in results]
        p50, p95 = _p50_p95(lat)
        providers: dict = {}
        for r in results:
            providers[r["provider"]] = providers.get(r["provider"], 0) + 1
        return ctx.render(
            request,
            "eval_detail.html",
            run=run,
            results=results,
            p50=p50,
            p95=p95,
            providers=providers,
            pass_rate=(100.0 * run["passed"] / run["total"]) if run["total"] else 0.0,
        )

    # ------------------------------------------------------------------
    # prompt library
    # ------------------------------------------------------------------

    @require_login
    async def prompts_page(request):
        prompts = ctx.db.query(
            "SELECT p.*, MAX(v.version) AS latest FROM prompt_library p"
            " LEFT JOIN prompt_versions v ON v.prompt_id=p.id GROUP BY p.id ORDER BY p.name"
        )
        return ctx.render(request, "prompts.html", prompts=prompts)

    @require_roles("admin", "operator")
    async def prompt_save(request):
        """Create or add an auto-incrementing version
        (reference: services/dashboard/app.py:3302-3417)."""
        form = await request.post()
        name = str(form.get("name") or "").strip()
        text = str(form.get("text") or "").strip()
        if not name or not text:
            raise web.HTTPBadRequest(text="name and text required")
        p = ctx.db.one("SELECT id FROM prompt_library WHERE name=?", (name,))
        pid = (
            p["id"]
            if p
            else ctx.db.execute(
                "INSERT INTO prompt_library (name, description, created_at) VALUES (?,?,?)",
                (name, str(form.get("description") or ""), time.time()),
            )
        )
        latest = ctx.db.one(
            "SELECT COALESCE(MAX(version),0) AS v FROM prompt_versions WHERE prompt_id=?", (pid,)
        )["v"]
        ctx.db.execute(
            "INSERT INTO prompt_versions (prompt_id, version, text, created_at) VALUES (?,?,?,?)",
            (pid, latest + 1, text, time.time()),
        )
        raise web.HTTPFound(f"/prompts/{pid}")

    @require_login
    async def prompt_detail(request):
        pid = int(request.match_info["pid"])
        p = ctx.db.one("SELECT * FROM prompt_library WHERE id=?", (pid,))
        if p is None:
            raise web.HTTPNotFound(text="prompt not found")
        versions = ctx.db.query(
            "SELECT * FROM prompt_versions WHERE prompt_id=? ORDER BY version DESC", (pid,)
        )
        return ctx.render(request, "prompt_detail.html", prompt=p, versions=versions)

    # ------------------------------------------------------------------
    # experiments
    # ------------------------------------------------------------------

    @require_login
    async def experiments_page(request):
        exps = ctx.db.query(
            "SELECT e.*, COUNT(r.trace_id) AS n_runs FROM experiments e"
            " LEFT JOIN experiment_runs r ON r.experiment_id=e.id GROUP BY e.id ORDER BY e.created_at DESC"
        )
        return ctx.render(request, "experiments.html", experiments=exps)

    @require_roles("admin", "operator")
    async def experiment_create(request):
        form = await request.post()
        name = str(form.get("name") or "").strip()
        if not name:
            raise web.HTTPBadRequest(text="name required")
        ctx.db.execute(
            "INSERT OR IGNORE INTO experiments (name, description, created_at) VALUES (?,?,?)",
            (name, str(form.get("description") or ""), time.time()),
        )
        raise web.HTTPFound("/experiments")

    @require_login
    async def experiment_detail(request):
        """Run links + p50/p95 scorecard (reference: app.py:3420-3532)."""
        eid = int(request.match_info["eid"])
        exp = ctx.db.one("SELECT * FROM experiments WHERE id=?", (eid,))
        if exp is None:
            raise web.HTTPNotFound(text="experiment not found")
        runs = ctx.db.query(
            "SELECT t.* FROM trace_runs t JOIN experiment_runs r ON r.trace_id=t.trace_id"
            " WHERE r.experiment_id=? ORDER BY t.ts DESC",
            (eid,),
        )
        p50, p95 = _p50_p95([r["latency_ms"] or 0 for r in runs])
        return ctx.render(
            request, "experiment_detail.html", exp=exp, runs=runs, p50=p50, p95=p95
        )

    app.add_routes(
        [
            web.get("/datasets", datasets_page),
            web.post("/datasets/create", dataset_create),
            web.get("/datasets/{ds_id}", dataset_detail),
            web.post("/datasets/{ds_id}/examples", example_add),
            web.post("/datasets/{ds_id}/examples/{ex_id}/run", example_run_now),
            web.post("/datasets/{ds_id}/eval", eval_run),
            web.get("/evals", evals_page),
            web.get("/eval/{run_id}", eval_detail),
            web.get("/prompts", prompts_page),
            web.post("/prompts/save", prompt_save),
            web.get("/prompts/{pid}", prompt_detail),
            web.get("/experiments", experiments_page),
            web.post("/experiments/create", experiment_create),
            web.get("/experiments/{eid}", experiment_detail),
        ]
    )
