"""Core dashboard pages: home, health, failures, scenarios, warnings, runs,
playground (reference: services/dashboard/app.py §2.1-2.8 areas)."""

from __future__ import annotations

import json
import os
import time
from collections import defaultdict
from datetime import datetime, timezone
from typing import Any, Dict, List, Optional

from aiohttp import web

import asyncio

from kakveda_tpu.core import admission as _admission
from kakveda_tpu.core.admission import DeviceUnavailableError, OverloadError
from kakveda_tpu.core.schemas import TracePayload, WarningRequest
from kakveda_tpu.dashboard.core import CTX_KEY, require_login, require_roles
from kakveda_tpu.dashboard.db import new_trace_id
from kakveda_tpu.models.runtime import UnknownModelError


async def off_loop(fn, *args, **kwargs):
    """Run a blocking call (model generate, sync HTTP) in the executor so it
    can't stall the shared event loop serving /warn and /healthz."""
    loop = asyncio.get_running_loop()
    return await loop.run_in_executor(None, lambda: fn(*args, **kwargs))


# Optional per-client token bucket on the playground routes
# (KAKVEDA_RATELIMIT_RPS, same shape as the service /ingest limiter:
# 429 + Retry-After). Lazy module-level singleton — the env is read once,
# like every other serving lever.
_PLAYGROUND_BUCKET = None
_PLAYGROUND_BUCKET_INIT = False


def _playground_ratelimit(request) -> None:
    global _PLAYGROUND_BUCKET, _PLAYGROUND_BUCKET_INIT
    if not _PLAYGROUND_BUCKET_INIT:
        _PLAYGROUND_BUCKET_INIT = True
        rps = float(os.environ.get("KAKVEDA_RATELIMIT_RPS", "0") or 0)
        if rps > 0:
            from kakveda_tpu.core.ratelimit import TokenBucket

            burst = os.environ.get("KAKVEDA_RATELIMIT_BURST")
            _PLAYGROUND_BUCKET = TokenBucket(rps, float(burst) if burst else None)
    if _PLAYGROUND_BUCKET is None:
        return
    ok, ra = _PLAYGROUND_BUCKET.allow(request.remote or "anon")
    if not ok:
        _admission.get_admission().note_shed("interactive", "ratelimit", retry_after=ra)
        raise OverloadError(
            "per-client rate limit exceeded", retry_after=ra,
            klass="interactive", reason="ratelimit",
        )


def _retry_after_http(e) -> "web.HTTPException":
    """Map a typed shed/degraded error to the playground's HTTP answer:
    429 (overload) or 503 (device loss), both with Retry-After."""
    headers = {"Retry-After": str(max(1, int(round(e.retry_after))))}
    if isinstance(e, OverloadError):
        return web.HTTPTooManyRequests(text=str(e), headers=headers)
    return web.HTTPServiceUnavailable(text=str(e), headers=headers)

TOKEN_PRICE_MICRO_USD_IN = 15  # per 1k tokens — env-tunable in the runtime config
TOKEN_PRICE_MICRO_USD_OUT = 75


def estimate_tokens(text: str) -> int:
    """len/4 heuristic (reference: services/dashboard/app.py:139-147)."""
    return max(1, len(text or "") // 4)


def estimate_cost_micro_usd(tokens_in: int, tokens_out: int) -> int:
    return (tokens_in * TOKEN_PRICE_MICRO_USD_IN + tokens_out * TOKEN_PRICE_MICRO_USD_OUT) // 1000


def parse_advanced_query(q: str) -> Dict[str, Any]:
    """Runs-explorer mini query language: free text plus ``provider:x``,
    ``model:x``, ``project:x``, ``tag:x`` / ``label:x`` (repeatable —
    a run matches ANY of the listed values), ``thumb:up``,
    ``latency_ms>N`` / ``latency_ms<N``, ``has:error``
    (reference: services/dashboard/app.py:173-221)."""
    out: Dict[str, Any] = {"text": [], "filters": {}}
    f = out["filters"]
    for tok in (q or "").split():
        if tok.startswith(("provider:", "model:", "thumb:", "project:")):
            k, _, v = tok.partition(":")
            f[k] = v
        elif tok.startswith(("tag:", "label:")):
            k, _, v = tok.partition(":")
            f.setdefault(k + "s", []).append(v)
        elif tok.startswith("latency_ms") and (">" in tok or "<" in tok):
            op = ">" if ">" in tok else "<"
            try:
                f["latency_gt" if op == ">" else "latency_lt"] = int(tok.split(op, 1)[1])
            except ValueError:
                pass
        elif tok == "has:error":
            f["has_error"] = True
        else:
            out["text"].append(tok)
    out["text"] = " ".join(out["text"])
    return out


def setup(app: web.Application) -> None:
    ctx = app[CTX_KEY]
    plat = ctx.platform

    # ------------------------------------------------------------------
    # home
    # ------------------------------------------------------------------

    @require_login
    async def home(request):
        # Paged + incrementally-maintained accessors: the home view costs
        # O(page), not O(all records), at 1M-row GFKBs.
        failures = plat.failures_page(limit=15)
        patterns = plat.patterns_list()
        apps = plat.apps()
        health = {a: plat.health_history(a, limit=1) for a in apps}
        recent_warnings = ctx.db.query(
            "SELECT * FROM warning_events ORDER BY ts DESC LIMIT 10"
        )
        return ctx.render(
            request,
            "home.html",
            failures=failures,
            patterns=patterns,
            health={a: (pts[-1] if pts else None) for a, pts in health.items()},
            recent_warnings=recent_warnings,
            gfkb_count=plat.gfkb.count,
        )

    # ------------------------------------------------------------------
    # health + failure detail
    # ------------------------------------------------------------------

    @require_login
    async def health_page(request):
        app_id = request.query.get("app_id", "")
        apps = plat.apps()
        points = plat.health_history(app_id, limit=100) if app_id else []
        return ctx.render(request, "health.html", apps=apps, app_id=app_id, points=points)

    @require_roles("admin")
    async def health_test(request):
        """Admin fault injection: publish a synthetic failure.detected
        (reference: services/dashboard/app.py:1762-1819)."""
        form = await request.post()
        app_id = str(form.get("app_id") or "test-app")
        severity = str(form.get("severity") or "medium")
        ftype = str(form.get("failure_type") or "SYNTHETIC_TEST")
        event = {
            "trace_id": new_trace_id(),
            "ts": datetime.now(timezone.utc).isoformat(),
            "app_id": app_id,
            "failure_type": ftype,
            "severity": severity,
            "context_signature": {"injected": True},
        }
        await plat.bus.publish("failure.detected", event)
        ctx.db.audit(request["user"].email, "health.test", event)
        raise web.HTTPFound(f"/health-page?app_id={app_id}")

    @require_login
    async def failure_detail(request):
        fid = request.match_info["failure_id"]
        # Version-aware lookup: F-0001v3 pins a version, plain id = latest
        # (reference: services/dashboard/app.py:1822-1909).
        want_version = None
        if "v" in fid[2:]:
            base, _, v = fid.rpartition("v")
            if v.isdigit():
                fid, want_version = base, int(v)
        rec = plat.get_failure(fid)
        if rec is None:
            raise web.HTTPNotFound(text=f"failure {fid} not found")
        history = []
        if plat.gfkb.failures_path.exists():
            # The failures log grows unbounded — read it off the event loop
            # so a big GFKB doesn't stall every other request.
            raw = await asyncio.get_running_loop().run_in_executor(
                None, lambda: plat.gfkb.failures_path.read_text(encoding="utf-8")
            )
            for line in raw.splitlines():
                if not line.strip():
                    continue
                row = json.loads(line)
                if row.get("failure_id") == fid:
                    history.append(row)
        shown = rec.model_dump(mode="json")
        if want_version is not None:
            pinned = next((h for h in history if h.get("version") == want_version), None)
            if pinned:
                shown = pinned
        return ctx.render(
            request, "failure_detail.html", failure=shown, history=history, latest=rec
        )

    # ------------------------------------------------------------------
    # scenario runner
    # ------------------------------------------------------------------

    @require_login
    async def scenarios_page(request):
        recent = ctx.db.query("SELECT * FROM scenario_runs ORDER BY ts DESC LIMIT 20")
        return ctx.render(request, "scenarios.html", recent=recent)

    @require_roles("admin", "operator")
    async def run_scenario(request):
        """The canonical end-to-end path: warn → generate → ingest, with
        span capture (reference: services/dashboard/app.py:2094-2226)."""
        form = await request.post()
        app_id = str(form.get("app_id") or "app-A")
        prompt = str(form.get("prompt") or "")
        if not prompt:
            raise web.HTTPBadRequest(text="prompt required")
        user = request["user"]
        trace_id = new_trace_id()
        t_start = time.time()

        w_t0 = time.time()
        warning = await off_loop(
            plat.warn,
            WarningRequest(app_id=app_id, agent_id="dashboard", prompt=prompt, tools=[], env={}),
        )
        w_t1 = time.time()

        g_t0 = time.time()
        gen = await off_loop(ctx.model.generate, prompt)
        g_t1 = time.time()

        # Rich trace row BEFORE plat.ingest: the dashboard's trace.ingested
        # subscriber inserts a sparse fallback row for externally-ingested
        # traces, and INSERT OR IGNORE means whichever lands first wins.
        tokens_in = estimate_tokens(prompt)
        tokens_out = estimate_tokens(gen.text)
        ctx.db.execute(
            "INSERT OR IGNORE INTO trace_runs (trace_id, ts, app_id, agent_id, prompt, response,"
            " provider, model, latency_ms, tokens_in, tokens_out, cost_micro_usd, status)"
            " VALUES (?,?,?,?,?,?,?,?,?,?,?,?,'ok')",
            (
                trace_id,
                t_start,
                app_id,
                "dashboard",
                prompt,
                gen.text,
                gen.meta.get("provider"),
                gen.meta.get("model"),
                gen.meta.get("latency_ms"),
                tokens_in,
                tokens_out,
                estimate_cost_micro_usd(tokens_in, tokens_out),
            ),
        )

        i_t0 = time.time()
        trace = TracePayload(
            trace_id=trace_id,
            ts=datetime.now(timezone.utc),
            app_id=app_id,
            agent_id="dashboard",
            prompt=prompt,
            response=gen.text,
            model=gen.meta.get("model"),
            tools=[],
            env={},
        )
        await plat.ingest(trace)
        i_t1 = time.time()
        ctx.db.execute(
            "INSERT INTO scenario_runs (ts, user_email, app_id, prompt, response, warning_action,"
            " warning_confidence, provider, model, latency_ms, trace_id) VALUES (?,?,?,?,?,?,?,?,?,?,?)",
            (
                t_start,
                user.email,
                app_id,
                prompt,
                gen.text,
                warning.action,
                warning.confidence,
                gen.meta.get("provider"),
                gen.meta.get("model"),
                gen.meta.get("latency_ms"),
                trace_id,
            ),
        )
        best = warning.references[0] if warning.references else None
        wid = ctx.db.execute(
            "INSERT INTO warning_events (ts, app_id, action, confidence, pattern_id, failure_id,"
            " failure_type, message, source) VALUES (?,?,?,?,?,?,?,?, 'scenario')",
            (
                t_start,
                app_id,
                warning.action,
                warning.confidence,
                warning.pattern_id,
                best.failure_id if best else None,
                best.failure_type if best else None,
                warning.message,
            ),
        )
        parent = ctx.db.add_span(trace_id, "scenario.run", t_start, i_t1)
        ctx.db.add_span(trace_id, "warn_policy.call", w_t0, w_t1, parent, {"action": warning.action})
        ctx.db.add_span(trace_id, "model.generate", g_t0, g_t1, parent, gen.meta)
        ctx.db.add_span(trace_id, "ingestion.ingest", i_t0, i_t1, parent)
        ctx.db.audit(user.email, "scenario.run", {"app_id": app_id, "trace_id": trace_id})
        raise web.HTTPFound(f"/warnings#w-{wid}")

    # ------------------------------------------------------------------
    # warnings + analytics
    # ------------------------------------------------------------------

    @require_login
    async def warnings_page(request):
        """Warning list + interactive analytics (reference:
        services/dashboard/app.py:1912-2041, templates/warnings.html):
        stat tiles, a 30-day daily-count chart with every day present,
        per-app / per-pattern / cost breakdowns — plus the raw 90-day rows
        shipped as JSON so the 30d/90d window and app filter re-aggregate
        CLIENT-side with no round trip."""
        now = time.time()
        d30 = now - 30 * 86400
        app_filter = (request.query.get("app_id") or "").strip()
        sql = "SELECT * FROM warning_events WHERE ts>?"
        params: List[Any] = [now - 90 * 86400]
        if app_filter:
            sql += " AND app_id=?"
            params.append(app_filter)
        events = ctx.db.query(sql + " ORDER BY ts DESC LIMIT 500", tuple(params))
        # Aggregates run over the FULL 30d window in SQL — the event list
        # is capped at the 500 newest, and deriving the tiles/chart from
        # it would silently undercount busy deployments.
        def agg(col_expr: str, since: float):
            q = (
                f"SELECT {col_expr} AS k, COUNT(*) AS n FROM warning_events "
                "WHERE ts>?" + (" AND app_id=?" if app_filter else "") + " GROUP BY k"
            )
            p = [since] + ([app_filter] if app_filter else [])
            return ctx.db.query(q, tuple(p))

        by_day: Dict[int, int] = {
            int(r["k"]): r["n"] for r in agg("CAST(ts/86400 AS INTEGER)", d30)
        }
        by_app = [
            (r["k"], r["n"])
            for r in sorted(agg("app_id", d30), key=lambda r: -r["n"])
        ]
        by_pattern = [
            (r["k"], r["n"])
            for r in sorted(agg("pattern_id", d30), key=lambda r: -r["n"])
            if r["k"]
        ]
        # Every day present (zero-filled) so the chart reads as a time
        # series, not a sparse list of whichever days had warnings. Keys
        # run from the cutoff's UTC day through TODAY inclusive — the
        # cutoff day holds real events (SQL keeps ts > d30 within it),
        # and anything past today would be a phantom empty bucket.
        day0, day_last = int(d30 // 86400), int(now // 86400)
        by_day_filled = [
            (
                datetime.fromtimestamp(d * 86400, tz=timezone.utc).strftime("%Y-%m-%d"),
                by_day.get(d, 0),
            )
            for d in range(day0, day_last + 1)
        ]
        cost_sql = "SELECT app_id, SUM(cost_micro_usd) AS cost FROM trace_runs WHERE ts>?"
        cost_params: List[Any] = [d30]
        if app_filter:
            cost_sql += " AND app_id=?"
            cost_params.append(app_filter)
        cost_rows = ctx.db.query(cost_sql + " GROUP BY app_id", tuple(cost_params))
        total_cost = sum((c["cost"] or 0) for c in cost_rows) / 1e6
        n30 = sum(n for _, n in by_day_filled)
        # Raw rows for instant client-side re-aggregation (the newest 500
        # of the 90d window; `truncated` tells the client its re-derived
        # numbers are a view, not the full count). "<" is escaped so a
        # hostile app_id cannot terminate the <script> block (stored XSS).
        rows_json = json.dumps(
            {
                "truncated": len(events) >= 500,
                "rows": [
                    {
                        "ts": e["ts"],
                        "app_id": e["app_id"],
                        "action": e["action"],
                        "pattern_id": e["pattern_id"],
                        "confidence": e["confidence"],
                    }
                    for e in events
                ],
            }
        ).replace("<", "\\u003c")
        # Full-window SQL aggregates for the INITIAL render: the client
        # script only re-aggregates from the truncated rows_json once a
        # filter changes (with a visible "view" badge) — deriving the
        # first paint from 500 rows would silently undercount busy
        # deployments (the very thing the SQL aggregation exists for).
        server_agg_json = json.dumps(
            {
                "by_day": by_day_filled,
                "by_app": by_app[:12],
                "by_pattern": by_pattern[:12],
                "n_events": len(events),
            }
        ).replace("<", "\\u003c")
        return ctx.render(
            request,
            "warnings.html",
            events=events,
            server_agg_json=server_agg_json,
            cost_by_app=cost_rows,
            total_warnings_30d=n30,
            apps_active_30d=len(by_app),
            total_cost_usd_30d=total_cost,
            rows_json=rows_json,
            app_filter=app_filter,
        )

    # ------------------------------------------------------------------
    # runs explorer + detail + feedback
    # ------------------------------------------------------------------

    @require_login
    async def runs_page(request):
        q = request.query.get("q", "")
        parsed = parse_advanced_query(q)
        sql = "SELECT * FROM trace_runs"
        clauses: List[str] = []
        params: List[Any] = []
        f = parsed["filters"]
        if f.get("provider"):
            clauses.append("provider=?")
            params.append(f["provider"])
        if f.get("model"):
            clauses.append("model=?")
            params.append(f["model"])
        if f.get("latency_gt") is not None:
            clauses.append("latency_ms>?")
            params.append(f["latency_gt"])
        if f.get("latency_lt") is not None:
            clauses.append("latency_ms<?")
            params.append(f["latency_lt"])
        if f.get("has_error"):
            clauses.append("(status='error' OR error IS NOT NULL)")
        if f.get("project"):
            # project:<name> (or a raw numeric id) scopes to one project.
            proj = ctx.db.one("SELECT id FROM projects WHERE name=?", (f["project"],))
            if proj is not None:
                clauses.append("project_id=?")
                params.append(proj["id"])
            elif f["project"].isdigit():
                clauses.append("project_id=?")
                params.append(int(f["project"]))
            else:
                clauses.append("1=0")  # unknown project: empty result, not all runs
        if f.get("tags"):
            # Repeatable tag: — a run matches ANY of the listed tags
            # (reference IN-subquery semantics, app.py:2831-2837).
            clauses.append("(" + " OR ".join(["tags_json LIKE ?"] * len(f["tags"])) + ")")
            params.extend(f"%{t}%" for t in f["tags"])
        if parsed["text"]:
            clauses.append("(prompt LIKE ? OR response LIKE ? OR app_id LIKE ?)")
            like = f"%{parsed['text']}%"
            params.extend([like, like, like])
        if f.get("thumb") or f.get("labels"):
            sub = "SELECT trace_id FROM run_feedback WHERE 1=1"
            if f.get("thumb"):
                sub += " AND thumb=?"
                params.append(f["thumb"])
            if f.get("labels"):
                sub += " AND label IN (" + ",".join("?" * len(f["labels"])) + ")"
                params.extend(f["labels"])
            clauses.append(f"trace_id IN ({sub})")
        if clauses:
            sql += " WHERE " + " AND ".join(clauses)
        sql += " ORDER BY ts DESC LIMIT 100"
        runs = ctx.db.query(sql, params)
        return ctx.render(request, "runs.html", runs=runs, q=q)

    @require_login
    async def run_detail(request):
        trace_id = request.match_info["trace_id"]
        run = ctx.db.one("SELECT * FROM trace_runs WHERE trace_id=?", (trace_id,))
        if run is None:
            raise web.HTTPNotFound(text="run not found")
        spans = ctx.db.query(
            "SELECT * FROM trace_spans WHERE trace_id=? ORDER BY start_ts", (trace_id,)
        )
        # Waterfall layout: a real span TREE (parent walk, depth-indented,
        # children under their parent in start order) with pct offsets
        # relative to the full window (reference:
        # services/dashboard/app.py:2927-2970).
        total_ms = 1
        if spans:
            t0 = min(s["start_ts"] for s in spans)
            t1 = max(s["end_ts"] for s in spans)
            total = max(t1 - t0, 1e-6)
            total_ms = int(total * 1000)
            by_parent: Dict[Optional[int], List[Dict]] = defaultdict(list)
            for s in spans:
                s["pct_left"] = round(100.0 * (s["start_ts"] - t0) / total, 2)
                s["pct_width"] = round(max(0.5, 100.0 * (s["end_ts"] - s["start_ts"]) / total), 2)
                s["start_off_ms"] = int((s["start_ts"] - t0) * 1000)
                s["duration_ms"] = int((s["end_ts"] - s["start_ts"]) * 1000)
                s["meta"] = json.loads(s["meta_json"] or "{}")
                by_parent[s["parent_id"]].append(s)
            for kids in by_parent.values():
                kids.sort(key=lambda s: s["start_ts"])
            ordered: List[Dict] = []
            seen: set = set()

            def walk(parent_id, depth):
                for s in by_parent.get(parent_id, []):
                    if s["id"] in seen:  # parent cycle from corrupted ingestion
                        continue
                    seen.add(s["id"])
                    s["depth"] = depth
                    s["has_children"] = bool(by_parent.get(s["id"]))
                    ordered.append(s)
                    walk(s["id"], depth + 1)

            walk(None, 0)
            # Orphan subtrees (parent_id points at a span not in this
            # trace — partial ingestion, pruned parent): walk them as
            # extra roots rather than silently dropping them from the
            # waterfall.
            span_ids = {s["id"] for s in spans}
            for s in sorted(spans, key=lambda s: s["start_ts"]):
                if s["id"] not in seen and s["parent_id"] not in span_ids:
                    seen.add(s["id"])
                    s["depth"] = 0
                    s["has_children"] = bool(by_parent.get(s["id"]))
                    ordered.append(s)
                    walk(s["id"], 1)
            # Last resort: spans whose parent chain never reaches a root —
            # a parent cycle or self-parenting row. Surface them as extra
            # depth-0 rows (the seen-guard in walk() breaks the cycle)
            # instead of vanishing them from the waterfall.
            for s in sorted(spans, key=lambda s: s["start_ts"]):
                if s["id"] not in seen:
                    seen.add(s["id"])
                    s["depth"] = 0
                    s["has_children"] = bool(by_parent.get(s["id"]))
                    ordered.append(s)
                    walk(s["id"], 1)
            spans = ordered
        feedback = ctx.db.query("SELECT * FROM run_feedback WHERE trace_id=?", (trace_id,))
        return ctx.render(
            request, "run_detail.html", run=run, spans=spans, feedback=feedback,
            total_ms=total_ms,
        )

    @require_login
    async def run_feedback(request):
        trace_id = request.match_info["trace_id"]
        form = await request.post()
        thumb = str(form.get("thumb") or "")
        label = str(form.get("label") or "") or None
        note = str(form.get("note") or "") or None
        if thumb not in ("up", "down"):
            raise web.HTTPBadRequest(text="thumb must be up|down")
        ctx.db.execute(
            "INSERT OR IGNORE INTO run_feedback (trace_id, user_email, thumb, label, note, ts)"
            " VALUES (?,?,?,?,?,?)",
            (trace_id, request["user"].email, thumb, label, note, time.time()),
        )
        raise web.HTTPFound(f"/runs/{trace_id}")

    # ------------------------------------------------------------------
    # playground
    # ------------------------------------------------------------------

    # Model listing may hit the network (Ollama /api/tags, 3 s timeout);
    # cache it so page loads and run re-renders don't pay that per request.
    _models_cache: dict = {"ts": 0.0, "models": None}
    _MODELS_TTL_S = 60.0

    async def _get_models() -> list:
        now = time.time()
        if _models_cache["models"] is None or now - _models_cache["ts"] > _MODELS_TTL_S:
            from kakveda_tpu.models.runtime import list_models

            _models_cache["models"] = await off_loop(list_models, ctx.model)
            _models_cache["ts"] = now
        return _models_cache["models"]

    @require_login
    async def playground_page(request):
        agents = ctx.db.query("SELECT * FROM agent_registry WHERE enabled=1")
        prompts = ctx.db.query(
            "SELECT p.name, v.text, v.version FROM prompt_library p JOIN prompt_versions v"
            " ON v.prompt_id=p.id ORDER BY p.name, v.version DESC"
        )
        experiments = ctx.db.query("SELECT * FROM experiments ORDER BY created_at DESC")
        return ctx.render(
            request,
            "playground.html",
            agents=agents,
            prompts=prompts,
            experiments=experiments,
            models=await _get_models(),
            result=None,
        )

    def record_playground_run(trace_id, t0, t1, prompt, text, provider, model, latency_ms, span, meta):
        """One trace_runs row + span for a playground invocation — shared by
        the blocking and streaming endpoints (same table shape, same cost
        accounting)."""
        tokens_in, tokens_out = estimate_tokens(prompt), estimate_tokens(text)
        ctx.db.execute(
            "INSERT OR IGNORE INTO trace_runs (trace_id, ts, app_id, agent_id, prompt,"
            " response, provider, model, latency_ms, tokens_in, tokens_out,"
            " cost_micro_usd, status) VALUES (?,?,?,?,?,?,?,?,?,?,?,?,'ok')",
            (
                trace_id, t0, "playground", provider, prompt, text, provider,
                model, latency_ms, tokens_in, tokens_out,
                estimate_cost_micro_usd(tokens_in, tokens_out),
            ),
        )
        ctx.db.add_span(trace_id, span, t0, t1, meta=meta)

    @require_roles("admin", "operator")
    async def playground_stream(request):
        """Server-sent-events streaming generation: text deltas reach the
        client per decode chunk instead of after the full response — the
        reference's playground blocks on one whole Ollama reply
        (services/dashboard/app.py:3127-3299). Runtimes without a
        generate_stream (the Ollama client) fall back to a single delta
        event; the stub streams word-by-word. The run is recorded to
        trace_runs exactly like /playground/run."""
        form = await request.post()
        prompt = str(form.get("prompt") or "")
        if not prompt:
            raise web.HTTPBadRequest(text="prompt required")
        _playground_ratelimit(request)
        chosen_target = str(form.get("target") or "model")
        chosen = (
            chosen_target.split(":", 1)[1] if chosen_target.startswith("model:") else None
        )
        resp = web.StreamResponse(
            headers={
                "Content-Type": "text/event-stream",
                "Cache-Control": "no-cache",
                "X-Accel-Buffering": "no",
            }
        )
        await resp.prepare(request)
        loop = asyncio.get_running_loop()
        ch: asyncio.Queue = asyncio.Queue()
        t0 = time.time()

        import threading

        cancelled = threading.Event()

        def pump():
            # Blocking generator runs in the executor; deltas hop to the
            # event loop thread-safely. The sentinel carries the outcome.
            # On client disconnect the handler sets `cancelled`; closing
            # the generator cancels the engine request (slot frees instead
            # of decoding for nobody).
            try:
                stream_fn = getattr(ctx.model, "generate_stream", None)
                parts: list = []
                if callable(stream_fn):
                    try:
                        gen = stream_fn(prompt, model=chosen, cancel=cancelled)
                    except TypeError:  # runtime without cancel support
                        gen = stream_fn(prompt, model=chosen)
                    try:
                        for d in gen:
                            parts.append(d)
                            loop.call_soon_threadsafe(ch.put_nowait, ("delta", d))
                            if cancelled.is_set():
                                break
                    finally:
                        gen.close()
                else:
                    gen = ctx.model.generate(prompt, model=chosen)
                    parts.append(gen.text)
                    loop.call_soon_threadsafe(ch.put_nowait, ("delta", gen.text))
                loop.call_soon_threadsafe(ch.put_nowait, ("done", "".join(parts)))
            except (OverloadError, DeviceUnavailableError) as e:
                # Shed/brownout/degraded rejection: the terminal error
                # frame carries the RETRY HINT so an EventSource client
                # can back off and resubmit instead of guessing.
                loop.call_soon_threadsafe(
                    ch.put_nowait,
                    ("error", {
                        "error": f"{type(e).__name__}: {e}",
                        "retry_after": round(e.retry_after, 2),
                        "retryable": True,
                    }),
                )
            except Exception as e:  # noqa: BLE001 — surface in-stream, not a 500 mid-SSE
                loop.call_soon_threadsafe(ch.put_nowait, ("error", f"{type(e).__name__}: {e}"))

        task = loop.run_in_executor(None, pump)
        text = ""
        # Idle streams emit SSE comment keepalives so buffering/idle-timeout
        # proxies don't sever the connection while a request waits for a
        # slot or a slow chunk (comment lines are invisible to clients).
        keepalive_s = float(os.environ.get("KAKVEDA_SSE_KEEPALIVE", "15"))
        last_write = time.monotonic()
        try:
            while True:
                try:
                    kind, payload = await asyncio.wait_for(ch.get(), timeout=0.5)
                except asyncio.TimeoutError:
                    # No delta yet (queued behind a full pool, or a slow
                    # model): a write has never failed, so poll the
                    # transport — a gone client must cancel the engine
                    # request instead of holding a slot for nobody.
                    tr = request.transport
                    if tr is None or tr.is_closing():
                        cancelled.set()
                        break
                    if keepalive_s > 0 and time.monotonic() - last_write >= keepalive_s:
                        await resp.write(b": keepalive\n\n")
                        last_write = time.monotonic()
                    continue
                last_write = time.monotonic()
                if kind == "delta":
                    await resp.write(
                        b"data: " + json.dumps({"delta": payload}).encode() + b"\n\n"
                    )
                elif kind == "error":
                    # Terminal error frame (engine died mid-stream, model
                    # raised, request shed by admission/brownout): a typed
                    # `event: error` so EventSource clients get an
                    # addressable event, plus the error in the data
                    # payload for raw line parsers — then the stream
                    # CLOSES instead of going silent until the client
                    # times out. Shed payloads arrive as dicts carrying
                    # the retry_after hint; plain failures as strings.
                    body = payload if isinstance(payload, dict) else {"error": payload}
                    await resp.write(
                        b"event: error\ndata: " + json.dumps(body).encode() + b"\n\n"
                    )
                    break
                else:
                    text = payload
                    latency_ms = int((time.time() - t0) * 1000)
                    await resp.write(
                        b"data: "
                        + json.dumps({"done": True, "latency_ms": latency_ms}).encode()
                        + b"\n\n"
                    )
                    break
        except (ConnectionResetError, ConnectionError):
            cancelled.set()  # client went away: stop generating for nobody
        finally:
            cancelled.set()
            await task
        if text:
            t1 = time.time()
            # Provider/model from the runtime, not a hardcoded "tpu": the
            # stream yields text only (no meta), but the blocking
            # endpoint records meta["provider"], and a stub/Ollama-backed
            # stream must attribute the same way or provider: queries and
            # the runs table mislabel streamed traffic.
            provider = getattr(ctx.model, "name", None) or "tpu"
            model_used = (
                chosen
                or getattr(ctx.model, "model_label", None)
                or getattr(ctx.model, "model", None)
            )
            if model_used is None:
                try:
                    model_used = (ctx.model.list_models() or [None])[0]
                except Exception:  # noqa: BLE001 — attribution must not fail the stream
                    model_used = None
            record_playground_run(
                new_trace_id(), t0, t1, prompt, text, provider, model_used,
                int((t1 - t0) * 1000), "playground.stream", {"streamed": True},
            )
        await resp.write_eof()
        return resp

    @require_roles("admin", "operator")
    async def playground_run(request):
        """Direct model or external-agent invocation with span + cost capture
        (reference: services/dashboard/app.py:3127-3299)."""
        form = await request.post()
        prompt = str(form.get("prompt") or "")
        target = str(form.get("target") or "model")
        experiment = str(form.get("experiment") or "")
        if not prompt:
            raise web.HTTPBadRequest(text="prompt required")
        _playground_ratelimit(request)
        trace_id = new_trace_id()
        t0 = time.time()
        if target.startswith("agent:"):
            name = target.split(":", 1)[1]
            agent = ctx.db.one("SELECT * FROM agent_registry WHERE name=? AND enabled=1", (name,))
            if agent is None:
                raise web.HTTPBadRequest(text=f"unknown agent {name}")
            import httpx

            try:
                r = await off_loop(
                    httpx.post,
                    f"{agent['base_url']}/invoke",
                    json={"event_type": "ask", "payload": {"prompt": prompt}},
                    timeout=10.0,
                )
                r.raise_for_status()
                body = r.json()
                text = json.dumps(body.get("events", []), indent=1)
                meta = {"provider": f"agent:{name}", "model": name}
            except Exception as e:  # noqa: BLE001 — surface agent errors in UI
                text = f"agent error: {type(e).__name__}: {e}"
                meta = {"provider": f"agent:{name}", "model": name, "error": str(e)}
        else:
            # target "model" (runtime default) or "model:<name>" (explicit
            # model — reference's per-model variant, app.py:1226-1258).
            chosen = target.split(":", 1)[1] if target.startswith("model:") else None
            try:
                gen = await off_loop(lambda: ctx.model.generate(prompt, model=chosen))
                text, meta = gen.text, gen.meta
            except (OverloadError, DeviceUnavailableError) as e:
                # Shed by admission/brownout (429) or device-loss degraded
                # mode (503): retryable by contract, Retry-After attached
                # — never rendered as a fake model answer.
                raise _retry_after_http(e)
            except UnknownModelError as e:
                # Stale/hand-crafted model label (multi-model runtimes
                # reject unknown labels): surface in the UI, not a 500.
                # ONLY the label rejection — other ValueErrors ('no decode
                # room', prompt too long) are real serving faults and must
                # reach the error middleware.
                text = f"model error: {e}"
                meta = {"provider": "error", "model": chosen, "error": str(e)}
        t1 = time.time()
        # Engine-backed generations carry their serving timeline (queue
        # wait, prefill, TTFT, tokens/s, engine request id) in meta; hang
        # it on this request's OTel span so traces correlate with /metrics
        # and the flight recorder by request id. No-op without otel.
        from kakveda_tpu.core import otel as _otel

        _otel.add_span_events("serving.timeline", meta.get("serve"))
        record_playground_run(
            trace_id, t0, t1, prompt, text, meta.get("provider"), meta.get("model"),
            meta.get("latency_ms", int((t1 - t0) * 1000)), "playground.run", meta,
        )
        if experiment:
            exp = ctx.db.one("SELECT id FROM experiments WHERE name=?", (experiment,))
            if exp:
                ctx.db.execute(
                    "INSERT OR IGNORE INTO experiment_runs (experiment_id, trace_id) VALUES (?,?)",
                    (exp["id"], trace_id),
                )
        agents = ctx.db.query("SELECT * FROM agent_registry WHERE enabled=1")
        return ctx.render(
            request,
            "playground.html",
            agents=agents,
            prompts=[],
            experiments=ctx.db.query("SELECT * FROM experiments"),
            models=await _get_models(),
            result={"text": text, "meta": meta, "trace_id": trace_id},
        )

    app.add_routes(
        [
            web.get("/", home),
            web.get("/health-page", health_page),
            web.post("/health/test", health_test),
            web.get("/failures/{failure_id}", failure_detail),
            web.get("/scenarios", scenarios_page),
            web.post("/scenarios/run", run_scenario),
            web.get("/warnings", warnings_page),
            web.get("/runs", runs_page),
            web.get("/runs/{trace_id}", run_detail),
            web.post("/runs/{trace_id}/feedback", run_feedback),
            web.get("/playground", playground_page),
            web.post("/playground/run", playground_run),
            web.post("/playground/stream", playground_stream),
        ]
    )
