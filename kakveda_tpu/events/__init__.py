"""Host-side event plane."""

from kakveda_tpu.events.bus import EventBus, TOPIC_TRACE_INGESTED, TOPIC_FAILURE_DETECTED, TOPIC_CHILD_SAFETY  # noqa: F401
