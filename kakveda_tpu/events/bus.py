"""In-process asyncio event bus with the reference's pub/sub contract.

The reference runs a dedicated event-bus container doing HTTP fan-out with
volatile in-memory subscriptions — best-effort, at-most-once, exceptions
dropped on the floor, subscriptions lost on restart
(reference: services/event_bus/app.py:25-54). Here the intelligence pipeline
is in-process, so delivery to local subscribers is a function call with
structured error accounting; remote integrations (external agents,
dashboards in other processes) subscribe with a callback URL and get the
same HTTP POST contract the reference speaks. Device-side propagation
(index shard updates) rides XLA collectives, not this bus — see
kakveda_tpu.parallel.

Improvements over the reference, deliberate: delivery results are reported
(not silently swallowed), and local handlers are awaited with a timeout so
one stuck consumer can't wedge the fan-out.
"""

from __future__ import annotations

import asyncio
import logging
from typing import Any, Awaitable, Callable, Dict, List, Union

log = logging.getLogger("kakveda.events")

TOPIC_TRACE_INGESTED = "trace.ingested"
TOPIC_FAILURE_DETECTED = "failure.detected"
TOPIC_CHILD_SAFETY = "child_safety_alert"

Handler = Callable[[dict], Union[Awaitable[Any], Any]]


class EventBus:
    """Topic → subscriber fan-out. Subscribers are async/sync callables or
    HTTP callback URLs (the reference's external contract)."""

    def __init__(self, delivery_timeout: float = 3.0):
        self._subs: Dict[str, List[Union[Handler, str]]] = {}
        self.delivery_timeout = delivery_timeout

    def subscribe(self, topic: str, handler: Union[Handler, str]) -> int:
        subs = self._subs.setdefault(topic, [])
        if handler not in subs:
            subs.append(handler)
        return len(subs)

    def unsubscribe(self, topic: str, handler: Union[Handler, str]) -> None:
        subs = self._subs.get(topic, [])
        if handler in subs:
            subs.remove(handler)

    def topics(self) -> Dict[str, int]:
        return {k: len(v) for k, v in self._subs.items()}

    async def _deliver(self, sub: Union[Handler, str], event: dict) -> bool:
        try:
            if isinstance(sub, str):
                import httpx

                async with httpx.AsyncClient(timeout=self.delivery_timeout) as client:
                    await client.post(sub, json=event)
                return True
            if asyncio.iscoroutinefunction(sub):
                await asyncio.wait_for(sub(event), timeout=self.delivery_timeout)
            else:
                # Sync handlers run in the executor so a blocking consumer
                # can't wedge the loop, with the same delivery timeout.
                loop = asyncio.get_running_loop()
                result = await asyncio.wait_for(
                    loop.run_in_executor(None, sub, event), timeout=self.delivery_timeout
                )
                if asyncio.iscoroutine(result):  # sync factory returning a coroutine
                    await asyncio.wait_for(result, timeout=self.delivery_timeout)
            return True
        except Exception as e:  # noqa: BLE001 — fan-out must not break on one subscriber
            log.warning("event delivery failed: %s -> %r: %s", type(e).__name__, sub, e)
            return False

    async def publish(self, topic: str, event: dict) -> int:
        """Fan out to all subscribers concurrently; returns delivered count."""
        subs = list(self._subs.get(topic, []))
        if not subs:
            return 0
        results = await asyncio.gather(*[self._deliver(s, event) for s in subs])
        return sum(results)

    def publish_sync(self, topic: str, event: dict) -> int:
        """Publish from synchronous code (spins a private loop)."""
        return asyncio.run(self.publish(topic, event))
