"""In-process asyncio event bus with the reference's pub/sub contract.

The reference runs a dedicated event-bus container doing HTTP fan-out with
volatile in-memory subscriptions — best-effort, at-most-once, exceptions
dropped on the floor, subscriptions lost on restart
(reference: services/event_bus/app.py:25-54). Here the intelligence pipeline
is in-process, so delivery to local subscribers is a function call with
structured error accounting; remote integrations (external agents,
dashboards in other processes) subscribe with a callback URL and get the
same HTTP POST contract the reference speaks. Device-side propagation
(index shard updates) rides XLA collectives, not this bus — see
kakveda_tpu.parallel.

Improvements over the reference, deliberate: delivery results are reported
(not silently swallowed), local handlers are awaited with a timeout so one
stuck consumer can't wedge the fan-out, and HTTP subscriptions are durable
— the reference loses every subscription when its bus container restarts
(in-memory dict, event_bus/app.py:25; flagged as an ordering hazard at
startup), whereas here URL subscriptions append to a JSONL log and are
replayed on construction.
"""

from __future__ import annotations

import asyncio
import json
import logging
from pathlib import Path
from typing import Any, Awaitable, Callable, Collection, Dict, List, Optional, Union

from kakveda_tpu.core import metrics as _metrics

log = logging.getLogger("kakveda.events")

TOPIC_TRACE_INGESTED = "trace.ingested"
TOPIC_FAILURE_DETECTED = "failure.detected"
TOPIC_CHILD_SAFETY = "child_safety_alert"

Handler = Callable[[dict], Union[Awaitable[Any], Any]]


class EventBus:
    """Topic → subscriber fan-out. Subscribers are async/sync callables or
    HTTP callback URLs (the reference's external contract)."""

    def __init__(
        self,
        delivery_timeout: float = 3.0,
        persist_path: Optional[str | Path] = None,
    ):
        self._subs: Dict[str, List[Union[Handler, str]]] = {}
        self.delivery_timeout = delivery_timeout
        self._persist_path = Path(persist_path) if persist_path else None
        if self._persist_path is not None:
            self._replay_subscriptions()
        reg = _metrics.get_registry()
        self._m_published = reg.counter(
            "kakveda_bus_events_published_total",
            "Events published on the in-process bus", ("topic",),
        )
        self._m_deliveries = reg.counter(
            "kakveda_bus_deliveries_total", "Bus deliveries by result", ("result",),
        )
        self._m_ok = self._m_deliveries.labels(result="ok")
        self._m_err = self._m_deliveries.labels(result="error")
        # Fan-out backpressure gauge: how many deliveries are in flight
        # right now (bounded by MAX_CONCURRENT_DELIVERIES per publish).
        self._m_inflight = reg.gauge(
            "kakveda_bus_inflight_deliveries", "Bus deliveries currently in flight",
        )

    # --- durable URL subscriptions -------------------------------------

    def _replay_subscriptions(self) -> None:
        path = self._persist_path
        if path is None or not path.exists():
            return
        for line in path.read_text().splitlines():
            if not line.strip():
                continue
            try:
                rec = json.loads(line)
            except ValueError:
                continue  # torn tail write from a crashed process
            topic, url = rec.get("topic"), rec.get("url")
            if not topic or not url:
                continue
            subs = self._subs.setdefault(topic, [])
            if rec.get("action") == "unsubscribe":
                if url in subs:
                    subs.remove(url)
            elif url not in subs:
                subs.append(url)

    def _persist(self, action: str, topic: str, url: str) -> None:
        if self._persist_path is None:
            return
        try:
            self._persist_path.parent.mkdir(parents=True, exist_ok=True)
            with self._persist_path.open("a") as f:
                f.write(json.dumps({"action": action, "topic": topic, "url": url}) + "\n")
        except OSError as e:
            log.warning("subscription persist failed: %s", e)

    def subscribe(self, topic: str, handler: Union[Handler, str]) -> int:
        subs = self._subs.setdefault(topic, [])
        if handler not in subs:
            subs.append(handler)
            if isinstance(handler, str):
                self._persist("subscribe", topic, handler)
        return len(subs)

    def unsubscribe(self, topic: str, handler: Union[Handler, str]) -> None:
        subs = self._subs.get(topic, [])
        if handler in subs:
            subs.remove(handler)
            if isinstance(handler, str):
                self._persist("unsubscribe", topic, handler)

    def topics(self) -> Dict[str, int]:
        return {k: len(v) for k, v in self._subs.items()}

    def has_subscribers(self, topic: str, exclude: Collection[Handler] = ()) -> bool:
        return any(s not in exclude for s in self._subs.get(topic, []))

    async def _deliver(self, sub: Union[Handler, str], event: dict, client=None) -> bool:
        try:
            if isinstance(sub, str):
                if client is not None:
                    await client.post(sub, json=event)
                else:
                    import httpx

                    async with httpx.AsyncClient(timeout=self.delivery_timeout) as c:
                        await c.post(sub, json=event)
                return True
            if asyncio.iscoroutinefunction(sub):
                await asyncio.wait_for(sub(event), timeout=self.delivery_timeout)
            else:
                # Sync handlers run in the executor so a blocking consumer
                # can't wedge the loop, with the same delivery timeout.
                loop = asyncio.get_running_loop()
                result = await asyncio.wait_for(
                    loop.run_in_executor(None, sub, event), timeout=self.delivery_timeout
                )
                if asyncio.iscoroutine(result):  # sync factory returning a coroutine
                    await asyncio.wait_for(result, timeout=self.delivery_timeout)
            return True
        except Exception as e:  # noqa: BLE001 — fan-out must not break on one subscriber
            log.warning("event delivery failed: %s -> %r: %s", type(e).__name__, sub, e)
            return False

    # Cap on simultaneous in-flight deliveries per publish call: a 512-trace
    # ingest batch with a URL subscriber must not open hundreds of TCP
    # connections in one gather (fd exhaustion surfaces as silently-dropped
    # events).
    MAX_CONCURRENT_DELIVERIES = 32

    async def _fan_out(self, pairs: List[tuple]) -> int:
        """Deliver (subscriber, event) pairs with bounded concurrency and one
        shared pooled HTTP client for all URL deliveries."""
        sem = asyncio.Semaphore(self.MAX_CONCURRENT_DELIVERIES)
        needs_http = any(isinstance(s, str) for s, _ in pairs)
        client = None
        if needs_http:
            import httpx

            client = httpx.AsyncClient(
                timeout=self.delivery_timeout,
                limits=httpx.Limits(max_connections=self.MAX_CONCURRENT_DELIVERIES),
            )

        async def one(sub, event) -> bool:
            async with sem:
                self._m_inflight.inc()
                try:
                    return await self._deliver(sub, event, client=client)
                finally:
                    self._m_inflight.dec()

        try:
            results = await asyncio.gather(*[one(s, e) for s, e in pairs])
        finally:
            if client is not None:
                await client.aclose()
        ok = sum(results)
        self._m_ok.inc(ok)
        if ok < len(results):
            self._m_err.inc(len(results) - ok)
        return ok

    async def publish(self, topic: str, event: dict, exclude: Collection[Handler] = ()) -> int:
        """Fan out to all subscribers concurrently; returns delivered count.

        ``exclude`` skips specific subscribers — used by the platform's
        batched ingest, which invokes its internal reactors once per batch
        directly and must not have them re-triggered per event.
        """
        self._m_published.labels(topic=topic).inc()
        subs = [s for s in self._subs.get(topic, []) if s not in exclude]
        if not subs:
            return 0
        return await self._fan_out([(s, event) for s in subs])

    async def publish_many(
        self, topic: str, events: List[dict], exclude: Collection[Handler] = ()
    ) -> int:
        """Publish a batch of events concurrently (bounded-concurrency
        fan-out over all event×subscriber deliveries)."""
        self._m_published.labels(topic=topic).inc(len(events))
        subs = [s for s in self._subs.get(topic, []) if s not in exclude]
        if not subs or not events:
            return 0
        return await self._fan_out([(s, e) for e in events for s in subs])

    def publish_sync(self, topic: str, event: dict) -> int:
        """Publish from synchronous code (spins a private loop)."""
        return asyncio.run(self.publish(topic, event))
