"""In-process asyncio event bus with the reference's pub/sub contract.

The reference runs a dedicated event-bus container doing HTTP fan-out with
volatile in-memory subscriptions — best-effort, at-most-once, exceptions
dropped on the floor, subscriptions lost on restart
(reference: services/event_bus/app.py:25-54). Here the intelligence pipeline
is in-process, so delivery to local subscribers is a function call with
structured error accounting; remote integrations (external agents,
dashboards in other processes) subscribe with a callback URL and get the
same HTTP POST contract the reference speaks. Device-side propagation
(index shard updates) rides XLA collectives, not this bus — see
kakveda_tpu.parallel.

Improvements over the reference, deliberate: delivery results are reported
(not silently swallowed), local handlers are awaited with a timeout so one
stuck consumer can't wedge the fan-out, and HTTP subscriptions are durable
— the reference loses every subscription when its bus container restarts
(in-memory dict, event_bus/app.py:25; flagged as an ordering hazard at
startup), whereas here URL subscriptions append to a JSONL log and are
replayed on construction.

Delivery to URL subscribers is **at-least-once** (docs/robustness.md):

* each failed POST retries with exponential backoff + jitter
  (``KAKVEDA_BUS_RETRIES`` attempts, ``KAKVEDA_BUS_RETRY_BASE`` seconds);
* a per-URL **circuit breaker** opens after
  ``KAKVEDA_BUS_BREAKER_THRESHOLD`` consecutive event failures — while
  open, deliveries short-circuit straight to the dead-letter queue instead
  of burning the fan-out on a dead endpoint; after
  ``KAKVEDA_BUS_BREAKER_COOLDOWN`` seconds one half-open probe delivery is
  allowed through (success closes the breaker, failure reopens it);
* events that exhaust retries (or short-circuit) append to a **dead-letter
  JSONL** (``dlq.jsonl`` beside the subscription log) with the error and
  attempt count; ``kakveda-tpu dlq replay`` — or :meth:`EventBus.replay_dlq`
  in-process — re-delivers them and rewrites the file with what still fails.

Local (callable) subscribers keep single-attempt semantics: they are
in-process reactors whose failures are code bugs, not transient transport.
"""

from __future__ import annotations

import asyncio
import json
import logging
import os
import random
import threading
import time
from pathlib import Path
from typing import Any, Awaitable, Callable, Collection, Dict, List, Optional, Union

from kakveda_tpu.core import faults as _faults
from kakveda_tpu.core import metrics as _metrics
from kakveda_tpu.core import sanitize

log = logging.getLogger("kakveda.events")

TOPIC_TRACE_INGESTED = "trace.ingested"
TOPIC_FAILURE_DETECTED = "failure.detected"
TOPIC_CHILD_SAFETY = "child_safety_alert"
# Fleet topics (docs/scale-out.md): ``gfkb.replicate`` is the ingest
# replication log — classified rows accepted by any replica, applied
# idempotently by event id on every peer (at-least-once + DLQ replay IS
# the convergence mechanism). ``fleet.control`` is the gossiped control
# state (occupancy / brownout rung / DEGRADED latch) — EPHEMERAL by
# convention: every sample is superseded by the next tick, so deliveries
# are single-attempt and never dead-lettered (mark_ephemeral).
TOPIC_GFKB_REPLICATE = "gfkb.replicate"
TOPIC_FLEET_CONTROL = "fleet.control"
# Range-scoped replication (KAKVEDA_FLEET_OWNERSHIP=1, fleet/ownership.py):
# each peer gets its OWN replicate topic, carrying only the rows whose
# ownership holder set includes it. One URL subscriber per topic keeps
# the whole at-least-once machinery — retry/backoff, per-URL breaker,
# DLQ + `dlq replay` — per destination, so one slow peer's backpressure
# never couples to the others.
TOPIC_GFKB_REPLICATE_PREFIX = TOPIC_GFKB_REPLICATE + ".to."


def replicate_topic(replica_id: str) -> str:
    """The per-peer range-scoped replication topic for one replica."""
    return TOPIC_GFKB_REPLICATE_PREFIX + replica_id

Handler = Callable[[dict], Union[Awaitable[Any], Any]]


def new_event_id() -> str:
    """Mint a bus event id (hex uuid4). Events that must be applied
    idempotently under at-least-once delivery (``gfkb.replicate``) carry
    one in their ``id`` field; subscribers dedup on it."""
    import uuid

    return uuid.uuid4().hex


class EventBus:
    """Topic → subscriber fan-out. Subscribers are async/sync callables or
    HTTP callback URLs (the reference's external contract)."""

    def __init__(
        self,
        delivery_timeout: float = 3.0,
        persist_path: Optional[str | Path] = None,
        dlq_path: Optional[str | Path] = None,
    ):
        self._subs: Dict[str, List[Union[Handler, str]]] = {}
        self.delivery_timeout = delivery_timeout
        self._persist_path = Path(persist_path) if persist_path else None
        # Dead-letter log: defaults beside the subscription log so a
        # persistent bus is dead-letter-capable without extra wiring; an
        # in-memory bus (both None) counts drops on the metrics plane only.
        if dlq_path is not None:
            self._dlq_path: Optional[Path] = Path(dlq_path)
        elif self._persist_path is not None:
            self._dlq_path = self._persist_path.parent / "dlq.jsonl"
        else:
            self._dlq_path = None
        self._dlq_lock = sanitize.named_lock("EventBus._dlq_lock")
        # At-least-once knobs, read once at construction.
        self._retries = max(1, int(os.environ.get("KAKVEDA_BUS_RETRIES", "3")))
        self._retry_base = float(os.environ.get("KAKVEDA_BUS_RETRY_BASE", "0.05"))
        self._breaker_threshold = max(
            1, int(os.environ.get("KAKVEDA_BUS_BREAKER_THRESHOLD", "5"))
        )
        self._breaker_cooldown = float(
            os.environ.get("KAKVEDA_BUS_BREAKER_COOLDOWN", "30")
        )
        # DLQ auto-replay (KAKVEDA_DLQ_AUTO_S > 0): when a URL's breaker
        # RE-closes (open/half_open -> closed — the peer demonstrably
        # healed), re-deliver the dead-letter queue after that many
        # seconds, unprompted. Safe because replay is idempotent for
        # subscribers by contract (gfkb.replicate dedups by event id;
        # docs/robustness.md). 0 = off: `dlq replay` stays manual.
        self._dlq_auto_s = float(os.environ.get("KAKVEDA_DLQ_AUTO_S", "0"))
        self._dlq_auto_pending = False  # guarded by _breaker_lock (coalesce)
        # Pending auto-replay timer + shutdown latch (guarded by
        # _breaker_lock): close() cancels the timer and stops re-arming.
        self._dlq_auto_timer: Optional[threading.Timer] = None
        self._closed = False
        # Per-URL breaker state: {"state": closed|open|half_open,
        # "fails": consecutive failed events, "opened_at": monotonic ts}.
        # A threading lock, not asyncio: publish_sync spins private loops,
        # so two event loops can touch this dict from different threads.
        self._breakers: Dict[str, dict] = {}
        self._breaker_lock = sanitize.named_lock("EventBus._breaker_lock")
        # Ephemeral topics (fleet gossip): single-attempt URL delivery, no
        # dead-lettering — each event is superseded by the next tick, so
        # retrying or replaying a stale one is pure waste. The breaker
        # still applies (a dead peer must not cost a timeout per tick).
        self._ephemeral_topics: set = set()
        if self._persist_path is not None:
            self._replay_subscriptions()
        self._fault_deliver = _faults.site("bus.deliver")
        reg = _metrics.get_registry()
        self._m_published = reg.counter(
            "kakveda_bus_events_published_total",
            "Events published on the in-process bus", ("topic",),
        )
        self._m_deliveries = reg.counter(
            "kakveda_bus_deliveries_total", "Bus deliveries by result", ("result",),
        )
        self._m_ok = self._m_deliveries.labels(result="ok")
        self._m_err = self._m_deliveries.labels(result="error")
        attempts = reg.counter(
            "kakveda_bus_delivery_attempts_total",
            "URL delivery attempts by result (ok|retry|failed|short_circuit)",
            ("result",),
        )
        self._m_att_ok = attempts.labels(result="ok")
        self._m_att_retry = attempts.labels(result="retry")
        self._m_att_failed = attempts.labels(result="failed")
        self._m_att_short = attempts.labels(result="short_circuit")
        self._m_breaker_trans = reg.counter(
            "kakveda_bus_breaker_transitions_total",
            "Bus circuit-breaker state transitions", ("to",),
        )
        self._m_breaker_open = reg.gauge(
            "kakveda_bus_breaker_open",
            "URL subscribers whose circuit breaker is currently open",
        )
        self._m_dlq = reg.counter(
            "kakveda_bus_dlq_total",
            "Events dead-lettered after retries were exhausted or the "
            "breaker short-circuited",
        )
        self._m_dlq_auto = reg.counter(
            "kakveda_bus_dlq_auto_total",
            "Automatic DLQ replays triggered by a breaker re-close "
            "(KAKVEDA_DLQ_AUTO_S), by result", ("result",),
        )
        # Fan-out backpressure gauge: how many deliveries are in flight
        # right now (bounded by MAX_CONCURRENT_DELIVERIES per publish).
        self._m_inflight = reg.gauge(
            "kakveda_bus_inflight_deliveries", "Bus deliveries currently in flight",
        )

    # --- durable URL subscriptions -------------------------------------

    def _replay_subscriptions(self) -> None:
        path = self._persist_path
        if path is None or not path.exists():
            return
        for lineno, line in enumerate(path.read_text().splitlines(), start=1):
            if not line.strip():
                continue
            # Skip-with-warning per line: one malformed record (torn tail
            # from a crashed process, a non-dict JSON value, hand edits)
            # must not take down service startup — the remaining
            # subscriptions still replay.
            try:
                rec = json.loads(line)
                topic, url = rec.get("topic"), rec.get("url")
                action = rec.get("action")
            except Exception as e:  # noqa: BLE001 — any bad record, not just bad JSON
                log.warning(
                    "skipping malformed subscription record %s:%d (%s: %s)",
                    path, lineno, type(e).__name__, e,
                )
                continue
            if not topic or not url:
                continue
            subs = self._subs.setdefault(topic, [])
            if action == "unsubscribe":
                if url in subs:
                    subs.remove(url)
            elif url not in subs:
                subs.append(url)

    def _persist(self, action: str, topic: str, url: str) -> None:
        if self._persist_path is None:
            return
        try:
            self._persist_path.parent.mkdir(parents=True, exist_ok=True)
            with self._persist_path.open("a") as f:
                f.write(json.dumps({"action": action, "topic": topic, "url": url}) + "\n")
        except OSError as e:
            log.warning("subscription persist failed: %s", e)

    def subscribe(self, topic: str, handler: Union[Handler, str]) -> int:
        subs = self._subs.setdefault(topic, [])
        if handler not in subs:
            subs.append(handler)
            if isinstance(handler, str):
                self._persist("subscribe", topic, handler)
        return len(subs)

    def unsubscribe(self, topic: str, handler: Union[Handler, str]) -> None:
        subs = self._subs.get(topic, [])
        if handler in subs:
            subs.remove(handler)
            if isinstance(handler, str):
                self._persist("unsubscribe", topic, handler)

    def topics(self) -> Dict[str, int]:
        return {k: len(v) for k, v in self._subs.items()}

    def url_subscribers(self, topic: str) -> List[str]:
        """The URL (external) subscribers of a topic — fleet startup uses
        this to prune stale peer subscriptions without reaching into the
        subscription table."""
        return [s for s in self._subs.get(topic, []) if isinstance(s, str)]

    def mark_ephemeral(self, topic: str) -> None:
        """Opt a topic out of the at-least-once policy: URL deliveries are
        single-attempt and never dead-lettered (gossip semantics — the next
        tick supersedes this one). Local handlers are unaffected."""
        self._ephemeral_topics.add(topic)

    def has_subscribers(self, topic: str, exclude: Collection[Handler] = ()) -> bool:
        return any(s not in exclude for s in self._subs.get(topic, []))

    # --- circuit breaker (URL subscribers) -----------------------------

    def _breaker_state(self, url: str) -> dict:
        return self._breakers.setdefault(
            url, {"state": "closed", "fails": 0, "opened_at": 0.0, "probing": False}
        )

    def _set_breaker(self, br: dict, to: str) -> None:
        """ONE definition of a breaker transition: state, transition
        counter and open-breaker gauge move together. Caller holds
        ``_breaker_lock``."""
        if br["state"] == to:
            return
        br["state"] = to
        n_open = sum(1 for b in self._breakers.values() if b["state"] == "open")
        self._m_breaker_trans.labels(to=to).inc()
        self._m_breaker_open.set(n_open)
        log.warning("bus breaker -> %s (%d open)", to, n_open)

    def _breaker_allow(self, url: str) -> bool:
        """May a delivery to ``url`` proceed? Open breakers short-circuit
        until the cooldown elapses, then admit exactly ONE half-open probe
        at a time (success closes, failure reopens)."""
        with self._breaker_lock:
            br = self._breaker_state(url)
            if br["state"] == "closed":
                return True
            if br["state"] == "open":
                if time.monotonic() - br["opened_at"] < self._breaker_cooldown:
                    return False
                self._set_breaker(br, "half_open")
                br["probing"] = True
                return True
            if not br["probing"]:  # half_open, probe slot free
                br["probing"] = True
                return True
            return False

    def _breaker_result(self, url: str, ok: bool) -> None:
        with self._breaker_lock:
            br = self._breaker_state(url)
            br["probing"] = False
            if ok:
                br["fails"] = 0
                # A RE-close (open/half_open -> closed) means the peer
                # healed: the events its outage dead-lettered are now
                # deliverable, so schedule the auto-replay. A plain ok on
                # an already-closed breaker is just steady state.
                reclosed = br["state"] != "closed"
                self._set_breaker(br, "closed")
                if reclosed:
                    self._schedule_dlq_auto_locked()
                return
            if br["state"] == "half_open":
                br["opened_at"] = time.monotonic()
                self._set_breaker(br, "open")
                return
            br["fails"] += 1
            if br["fails"] >= self._breaker_threshold and br["state"] == "closed":
                br["opened_at"] = time.monotonic()
                self._set_breaker(br, "open")

    def breaker_states(self) -> Dict[str, str]:
        """url -> breaker state, for /topics-style introspection and tests."""
        with self._breaker_lock:
            return {u: b["state"] for u, b in self._breakers.items()}

    # --- dead-letter queue ---------------------------------------------

    def _dead_letter(
        self, topic: str, url: str, event: dict, error: str, attempts: int
    ) -> None:
        self._m_dlq.inc()
        if self._dlq_path is None:
            return
        rec = {
            "ts": time.time(), "topic": topic, "url": url, "event": event,
            "error": error, "attempts": attempts,
        }
        try:
            with self._dlq_lock:
                self._dlq_path.parent.mkdir(parents=True, exist_ok=True)
                with self._dlq_path.open("a", encoding="utf-8") as f:
                    f.write(json.dumps(rec, ensure_ascii=False) + "\n")
        except OSError as e:
            log.error("dead-letter append failed (event dropped): %s", e)

    def replay_dlq(self, timeout: Optional[float] = None) -> dict:
        """Re-deliver every dead-lettered event (sync POSTs) and rewrite the
        DLQ with what still fails. URLs that accepted a replay get their
        breaker closed — a successful replay is the operator's evidence the
        endpoint recovered, no need to wait out the cooldown."""
        if self._dlq_path is None:
            return {"replayed": 0, "failed": 0, "path": None}
        with self._dlq_lock:
            out = replay_dlq_file(
                self._dlq_path, timeout=timeout or self.delivery_timeout
            )
        with self._breaker_lock:
            for url in out.get("replayed_urls", ()):
                br = self._breakers.get(url)
                if br is not None:
                    br["fails"] = 0
                    br["probing"] = False
                    self._set_breaker(br, "closed")
        return out

    def _schedule_dlq_auto_locked(self) -> None:
        """Arm ONE delayed auto-replay after a breaker re-close (caller
        holds ``_breaker_lock``). A timer thread, not a loop task: breaker
        results arrive from publish_sync's short-lived private loops too,
        and a callback parked on a dead loop would never fire. Re-closes
        while a replay is pending coalesce — the single replay drains the
        whole DLQ anyway."""
        if self._dlq_auto_s <= 0 or self._dlq_path is None:
            return
        if self._dlq_auto_pending or self._closed:
            return
        self._dlq_auto_pending = True
        self._m_dlq_auto.labels(result="scheduled").inc()
        timer = threading.Timer(self._dlq_auto_s, self._run_dlq_auto)
        timer.daemon = True
        timer.start()
        # Retain the handle so close() can cancel a pending replay instead
        # of letting it fire against a torn-down platform (unjoined-thread
        # lifecycle: daemonized AND cancelled on the close path).
        self._dlq_auto_timer = timer

    def close(self) -> None:
        """Shut down the bus's background work: cancel a pending DLQ
        auto-replay timer and stop new ones from arming. Idempotent; the
        bus stays usable for synchronous delivery afterwards (teardown
        ordering elsewhere may still publish a final event)."""
        with self._breaker_lock:
            self._closed = True
            timer, self._dlq_auto_timer = self._dlq_auto_timer, None
            self._dlq_auto_pending = False
        if timer is not None:
            timer.cancel()

    def _run_dlq_auto(self) -> None:
        with self._breaker_lock:
            if self._closed:
                return
            self._dlq_auto_pending = False
            self._dlq_auto_timer = None
        try:
            out = self.replay_dlq()
        except Exception as e:  # noqa: BLE001 — auto-replay must never kill the timer path
            log.warning("DLQ auto-replay failed: %s: %s", type(e).__name__, e)
            self._m_dlq_auto.labels(result="failed").inc()
            return
        result = "replayed" if out.get("replayed") else (
            "failed" if out.get("failed") else "empty"
        )
        self._m_dlq_auto.labels(result=result).inc()
        if out.get("replayed") or out.get("failed"):
            log.info(
                "DLQ auto-replay after breaker re-close: %d replayed, %d still failing",
                out.get("replayed", 0), out.get("failed", 0),
            )

    # --- delivery -------------------------------------------------------

    async def _deliver_url(self, topic: str, url: str, event: dict, client=None) -> bool:
        """At-least-once URL delivery: breaker gate, bounded retries with
        exponential backoff + jitter, dead-letter on exhaustion. Ephemeral
        topics (mark_ephemeral) keep the breaker gate but drop the retries
        and the DLQ — the next sample supersedes this one."""
        ephemeral = topic in self._ephemeral_topics
        if not self._breaker_allow(url):
            self._m_att_short.inc()
            if not ephemeral:
                self._dead_letter(topic, url, event, "circuit breaker open", 0)
            return False
        retries = 1 if ephemeral else self._retries
        for attempt in range(retries):
            ok = await self._deliver(url, event, client=client)
            if ok:
                self._m_att_ok.inc()
                self._breaker_result(url, True)
                return True
            if attempt + 1 < retries:
                self._m_att_retry.inc()
                await asyncio.sleep(
                    self._retry_base * (2 ** attempt) * (0.5 + random.random())
                )
        self._m_att_failed.inc()
        self._breaker_result(url, False)
        if not ephemeral:
            self._dead_letter(
                topic, url, event,
                f"delivery failed after {retries} attempt(s)", retries,
            )
        return False

    async def _deliver(self, sub: Union[Handler, str], event: dict, client=None) -> bool:
        try:
            self._fault_deliver.fire()
            if isinstance(sub, str):
                # A non-2xx answer IS a failed delivery: the subscriber did
                # not accept the event (crashed handler, 429 shed, …), so
                # the at-least-once policy must retry/dead-letter it — a
                # fire-and-forget POST that ignores the status would count
                # a peer's 500 as delivered and silently lose the event
                # (the fleet replication log rides this path).
                if client is not None:
                    r = await client.post(sub, json=event)
                else:
                    import httpx

                    async with httpx.AsyncClient(timeout=self.delivery_timeout) as c:
                        r = await c.post(sub, json=event)
                r.raise_for_status()
                return True
            if asyncio.iscoroutinefunction(sub):
                await asyncio.wait_for(sub(event), timeout=self.delivery_timeout)
            else:
                # Sync handlers run in the executor so a blocking consumer
                # can't wedge the loop, with the same delivery timeout.
                loop = asyncio.get_running_loop()
                result = await asyncio.wait_for(
                    loop.run_in_executor(None, sub, event), timeout=self.delivery_timeout
                )
                if asyncio.iscoroutine(result):  # sync factory returning a coroutine
                    await asyncio.wait_for(result, timeout=self.delivery_timeout)
            return True
        except Exception as e:  # noqa: BLE001 — fan-out must not break on one subscriber
            log.warning("event delivery failed: %s -> %r: %s", type(e).__name__, sub, e)
            return False

    # Cap on simultaneous in-flight deliveries per publish call: a 512-trace
    # ingest batch with a URL subscriber must not open hundreds of TCP
    # connections in one gather (fd exhaustion surfaces as silently-dropped
    # events).
    MAX_CONCURRENT_DELIVERIES = 32

    async def _fan_out(self, topic: str, pairs: List[tuple]) -> int:
        """Deliver (subscriber, event) pairs with bounded concurrency and one
        shared pooled HTTP client for all URL deliveries. URL subscribers go
        through the at-least-once policy (retry → breaker → DLQ, which needs
        the topic for the dead-letter record); local handlers stay
        single-attempt."""
        sem = asyncio.Semaphore(self.MAX_CONCURRENT_DELIVERIES)
        needs_http = any(isinstance(s, str) for s, _ in pairs)
        client = None
        if needs_http:
            import httpx

            client = httpx.AsyncClient(
                timeout=self.delivery_timeout,
                limits=httpx.Limits(max_connections=self.MAX_CONCURRENT_DELIVERIES),
            )

        async def one(sub, event) -> bool:
            async with sem:
                self._m_inflight.inc()
                try:
                    if isinstance(sub, str):
                        return await self._deliver_url(topic, sub, event, client=client)
                    return await self._deliver(sub, event, client=client)
                finally:
                    self._m_inflight.dec()

        try:
            results = await asyncio.gather(*[one(s, e) for s, e in pairs])
        finally:
            if client is not None:
                await client.aclose()
        ok = sum(results)
        self._m_ok.inc(ok)
        if ok < len(results):
            self._m_err.inc(len(results) - ok)
        return ok

    async def publish(self, topic: str, event: dict, exclude: Collection[Handler] = ()) -> int:
        """Fan out to all subscribers concurrently; returns delivered count.

        ``exclude`` skips specific subscribers — used by the platform's
        batched ingest, which invokes its internal reactors once per batch
        directly and must not have them re-triggered per event.
        """
        self._m_published.labels(topic=topic).inc()
        subs = [s for s in self._subs.get(topic, []) if s not in exclude]
        if not subs:
            return 0
        return await self._fan_out(topic, [(s, event) for s in subs])

    async def publish_many(
        self, topic: str, events: List[dict], exclude: Collection[Handler] = ()
    ) -> int:
        """Publish a batch of events concurrently (bounded-concurrency
        fan-out over all event×subscriber deliveries)."""
        self._m_published.labels(topic=topic).inc(len(events))
        subs = [s for s in self._subs.get(topic, []) if s not in exclude]
        if not subs or not events:
            return 0
        return await self._fan_out(topic, [(s, e) for e in events for s in subs])

    def publish_sync(self, topic: str, event: dict) -> int:
        """Publish from synchronous code (spins a private loop)."""
        return asyncio.run(self.publish(topic, event))


def replay_dlq_file(path: str | Path, timeout: float = 5.0) -> dict:
    """Re-deliver every event in a dead-letter JSONL (one sync POST each,
    the same HTTP contract the fan-out speaks) and atomically rewrite the
    file with what still fails — the ``kakveda-tpu dlq replay`` verb and
    :meth:`EventBus.replay_dlq` both land here. Malformed lines are kept
    in place (skip-with-warning), never silently dropped."""
    import httpx

    path = Path(path)
    if not path.exists():
        return {"replayed": 0, "failed": 0, "path": str(path), "replayed_urls": []}
    remaining: List[str] = []
    replayed = 0
    replayed_urls: set = set()
    for lineno, line in enumerate(path.read_text(encoding="utf-8").splitlines(), 1):
        if not line.strip():
            continue
        try:
            rec = json.loads(line)
            url, event = rec["url"], rec["event"]
        except Exception as e:  # noqa: BLE001 — keep the record for a human
            log.warning(
                "dlq replay: keeping malformed record %s:%d (%s)", path, lineno, e
            )
            remaining.append(line)
            continue
        try:
            r = httpx.post(url, json=event, timeout=timeout)
            r.raise_for_status()
            replayed += 1
            replayed_urls.add(url)
        except Exception as e:  # noqa: BLE001 — still undeliverable, keep it
            rec["error"] = f"{type(e).__name__}: {e}"
            rec["attempts"] = int(rec.get("attempts", 0)) + 1
            remaining.append(json.dumps(rec, ensure_ascii=False))
    tmp = path.with_name(path.name + ".tmp")
    tmp.write_text(
        "".join(ln + "\n" for ln in remaining), encoding="utf-8"
    )
    os.replace(tmp, path)
    return {
        "replayed": replayed,
        "failed": len(remaining),
        "path": str(path),
        "replayed_urls": sorted(replayed_urls),
    }
