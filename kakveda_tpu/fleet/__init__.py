"""Replica fleet — horizontal scale-out of the serving tier (ROADMAP
item 3, docs/scale-out.md).

The single-process platform becomes an N-replica deployment on one box:

* :mod:`kakveda_tpu.fleet.hashring` — deterministic consistent hashing
  (warn traffic shards by app key; losing a replica remaps ~1/N of keys).
* :mod:`kakveda_tpu.fleet.router` — the front router app: forwards by
  ring assignment, probes replica health, ejects on consecutive
  transport failures, retries idempotent warn reads on the next replica.
* :mod:`kakveda_tpu.fleet.gossip` — control-state gossip over the bus
  (``fleet.control``): every replica publishes occupancy / brownout rung
  / DEGRADED latch and folds the fleet view back into its OWN admission
  controller as a pressure input (never writing gate state directly).
* :mod:`kakveda_tpu.fleet.supervisor` — spawn / supervise / tear down
  replica processes (``cli up --replicas N``; per-replica pid/log files
  beside the single-process server.pid/server.log convention).

GFKB ingest fan-in rides the existing at-least-once bus
(``gfkb.replicate`` topic): the accepting replica publishes classified
rows as the replication log, every peer applies them idempotently by
event id through the tiered insert path, and DLQ replay converges
stragglers after an outage.
"""

from kakveda_tpu.fleet.hashring import HashRing

__all__ = ["HashRing"]
