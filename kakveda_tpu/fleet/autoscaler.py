"""Closed-loop elastic fleet — autoscaling with lossless drain + replacement.

The policy loop runs in the ROUTER process (it owns the FleetSupervisor,
the probe state, and epoch writership) and consumes the same gossip
vocabulary the replicas publish: the router folds one gossip-shaped
sample per successful probe into its own :class:`~kakveda_tpu.fleet.
gossip.FleetView` (occupancy, brownout rung, DEGRADED latch — the
occupancy export already folds the replica's TTL'd pressure floor), so
the autoscaler sees exactly what the fleet gossips, with the same seq/TTL
freshness discipline.

Three actions, all through existing seams:

* **scale-up** — sustained pressure ``>= KAKVEDA_SCALE_UP_OCC`` for the
  dwell window (enter/exit discipline mirrors the brownout ladder):
  spawn a replica (``FleetSupervisor.add_replica``), wait for /readyz,
  then ``Router.rebalance_to`` ships it its ranges and flips the epoch
  — the router stays the SINGLE epoch writer; the autoscaler requests,
  the router's probe loop re-affirms residual pushes.
* **lossless scale-down** — sustained idle ``<= KAKVEDA_SCALE_DOWN_OCC``:
  pick the least-loaded live replica, run the range-migration protocol
  (export → ship → flip → drain the watermark delta), remove it from the
  ring, THEN stop the process. Never stop-then-migrate. Bounded below by
  ``KAKVEDA_SCALE_MIN``; any :class:`MigrationError` aborts with the
  replica still serving.
* **replacement** — a replica dead/ejected past ``KAKVEDA_SCALE_REPLACE_S``
  is declared dead: the same index restarts (same id/url → same ring
  position), a fresh probe re-admits it, and its GFKB gap heals by
  snapshot-shipping its held arcs back from the surviving holders through
  the migration protocol (row-idempotent signature upserts) — plus the
  origins' DLQ auto-replay (``KAKVEDA_DLQ_AUTO_S`` / ``cli dlq replay``)
  for the replication events dead-lettered while it was down. An
  expo-backoff budget (``KAKVEDA_SCALE_REPLACE_BACKOFF_S`` doubling, at
  most ``KAKVEDA_SCALE_REPLACE_MAX`` attempts per replica) keeps a
  crash-looping binary from flapping the ring.

``decide`` is a PURE function of (snapshot, policy state, knobs, now) —
``policy_selftest()`` runs a canned decision table over it with no
processes (scripts/verify_static.sh stage 4). Every transition of the
scale state machine goes through ONE ``_set_scale_state`` helper (gauge
vector + transition counter + flight recorder together — the same
single-writer invariant as the brownout ladder, machine-enforced by
scripts/lint_invariants.py), and every decision lands as one typed
:class:`ScaleDecision` line in ``data/scale_log.jsonl``.

Knob table + state machine: docs/scale-out.md § Elastic fleet.
"""

from __future__ import annotations

import asyncio
import json
import logging
import os
import time
from dataclasses import dataclass, field
from pathlib import Path
from typing import Dict, List, Optional

from kakveda_tpu.core import faults as _faults
from kakveda_tpu.core import trace as _trace
from kakveda_tpu.core import metrics as _metrics
from kakveda_tpu.core import sanitize

log = logging.getLogger("kakveda.fleet")

__all__ = [
    "SCALE_STATES",
    "ScaleKnobs",
    "PolicyState",
    "ScaleDecision",
    "decide",
    "commit",
    "Autoscaler",
    "policy_selftest",
]

# Chaos seams (resolved once at import, no-ops unarmed — the fault-site
# rule; cataloged in docs/robustness.md). scale_spawn fires BEFORE any
# process is created or epoch touched: a faulted spawn retries next tick
# and never flips the epoch early. scale_drain fires BEFORE the drain
# migration starts: a faulted drain aborts with the replica still serving.
_FAULT_SPAWN = _faults.site("fleet.scale_spawn")
_FAULT_DRAIN = _faults.site("fleet.scale_drain")

# The scale state machine (gauge vector over these; transitions only via
# _set_scale_state): steady -> scale_up|drain|replace while an action
# executes -> cooldown on success -> steady when the cooldown expires.
SCALE_STATES = ("steady", "scale_up", "drain", "replace", "cooldown")


def _env_f(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, "") or default)
    except ValueError:
        return default


def _env_i(name: str, default: int) -> int:
    try:
        return int(os.environ.get(name, "") or default)
    except ValueError:
        return default


@dataclass(frozen=True)
class ScaleKnobs:
    """Policy constants — env-resolved once at mount (KAKVEDA_SCALE_*)."""

    up_occ: float = 0.8
    down_occ: float = 0.3
    dwell_s: float = 5.0
    cooldown_s: float = 15.0
    min_replicas: int = 1
    max_replicas: int = 8
    replace_s: float = 10.0
    replace_backoff_s: float = 5.0
    replace_max: int = 3
    tick_s: float = 1.0
    ready_s: float = 240.0

    @classmethod
    def from_env(
        cls,
        min_replicas: Optional[int] = None,
        max_replicas: Optional[int] = None,
    ) -> "ScaleKnobs":
        return cls(
            up_occ=_env_f("KAKVEDA_SCALE_UP_OCC", 0.8),
            down_occ=_env_f("KAKVEDA_SCALE_DOWN_OCC", 0.3),
            dwell_s=_env_f("KAKVEDA_SCALE_DWELL_S", 5.0),
            cooldown_s=_env_f("KAKVEDA_SCALE_COOLDOWN_S", 15.0),
            min_replicas=(
                _env_i("KAKVEDA_SCALE_MIN", 1)
                if min_replicas is None else int(min_replicas)
            ),
            max_replicas=(
                _env_i("KAKVEDA_SCALE_MAX", 8)
                if max_replicas is None else int(max_replicas)
            ),
            replace_s=_env_f("KAKVEDA_SCALE_REPLACE_S", 10.0),
            replace_backoff_s=_env_f("KAKVEDA_SCALE_REPLACE_BACKOFF_S", 5.0),
            replace_max=_env_i("KAKVEDA_SCALE_REPLACE_MAX", 3),
            tick_s=max(0.05, _env_f("KAKVEDA_SCALE_TICK_S", 1.0)),
            ready_s=_env_f("KAKVEDA_SCALE_READY_S", 240.0),
        )


@dataclass
class PolicyState:
    """Mutable hysteresis state ``decide``/``commit`` evolve. Dwell
    tracking lives here (not in the FleetView) so the policy stays a pure
    function of (snapshot, state, knobs, now)."""

    high_since: Optional[float] = None
    low_since: Optional[float] = None
    cooldown_until: float = 0.0
    # Per-replica replacement bookkeeping: first-seen-dead stamp, attempt
    # count against the budget, and the expo-backoff next-eligible stamp.
    dead_since: Dict[str, float] = field(default_factory=dict)
    replace_counts: Dict[str, int] = field(default_factory=dict)
    replace_next_ok: Dict[str, float] = field(default_factory=dict)


@dataclass
class ScaleDecision:
    """One typed decision record — the scale_log.jsonl line format
    (docs/scale-out.md). ``outcome`` is stamped by the executor:
    ``ok`` | ``fault`` (armed chaos site; retried next tick) | ``error``
    | ``aborted`` (drain MigrationError — replica still serving) |
    ``noop`` for action "none"."""

    action: str  # none | scale_up | scale_down | replace
    reason: str
    pressure: float
    n: int  # live replica count at decision time
    target: Optional[str] = None
    outcome: str = "pending"
    ts: float = 0.0
    detail: str = ""

    def to_dict(self) -> dict:
        out = {
            "ts": round(self.ts, 3),
            "action": self.action,
            "outcome": self.outcome,
            "reason": self.reason,
            "pressure": round(self.pressure, 4),
            "n": self.n,
        }
        if self.target:
            out["target"] = self.target
        if self.detail:
            out["detail"] = self.detail
        return out


def _replica_index(rid: str) -> int:
    """Supervisor index from the fleet id convention (``r<i>``)."""
    try:
        return int(rid.lstrip("r"))
    except ValueError:
        return 1 << 30


def decide(
    snapshot: dict, state: PolicyState, knobs: ScaleKnobs, now: float
) -> ScaleDecision:
    """ONE policy evaluation — pure in (snapshot, state, knobs, now).

    ``snapshot`` is ``{"replicas": {rid: {"live", "occupancy",
    "dead_for_s"}}, "pressure": float}``. Mutates only the dwell stamps in
    ``state`` (deterministically); side effects belong to the executor.

    Ordering is deliberate: replacement first (healing a dead owner beats
    elasticity and ignores the scale cooldown — a hole in the ring is a
    correctness problem, not a capacity one), then the dwell+cooldown
    hysteresis for scale-up/down, one action per tick.
    """
    reps: Dict[str, dict] = snapshot.get("replicas", {})
    live = [r for r, s in reps.items() if s.get("live", True)]
    n = len(live)
    pressure = float(snapshot.get("pressure", 0.0))

    # 1) replacement — dead past the threshold, inside budget and backoff.
    for rid in sorted(reps, key=_replica_index):
        s = reps[rid]
        if s.get("live", True):
            continue
        dead_for = float(s.get("dead_for_s", 0.0))
        if dead_for < knobs.replace_s:
            continue
        if state.replace_counts.get(rid, 0) >= knobs.replace_max:
            continue  # budget exhausted: stop flapping the ring
        if now < state.replace_next_ok.get(rid, 0.0):
            continue  # expo backoff window still open
        return ScaleDecision(
            "replace",
            f"dead {dead_for:.1f}s >= replace_s {knobs.replace_s:g}s",
            pressure, n, target=rid,
        )

    # 2) dwell bookkeeping — the brownout ladder's enter/exit discipline:
    # a band crossing starts the clock, leaving the band resets it.
    if pressure >= knobs.up_occ:
        if state.high_since is None:
            state.high_since = now
        state.low_since = None
    elif pressure <= knobs.down_occ:
        if state.low_since is None:
            state.low_since = now
        state.high_since = None
    else:
        state.high_since = None
        state.low_since = None

    if now < state.cooldown_until:
        return ScaleDecision(
            "none", f"cooldown {state.cooldown_until - now:.1f}s left",
            pressure, n, outcome="noop",
        )

    if state.high_since is not None and now - state.high_since >= knobs.dwell_s:
        if n >= knobs.max_replicas:
            return ScaleDecision(
                "none", f"pressure high but at max ({knobs.max_replicas})",
                pressure, n, outcome="noop",
            )
        return ScaleDecision(
            "scale_up",
            f"pressure {pressure:.2f} >= {knobs.up_occ:g} "
            f"for {knobs.dwell_s:g}s",
            pressure, n,
        )

    if state.low_since is not None and now - state.low_since >= knobs.dwell_s:
        if n <= knobs.min_replicas:
            return ScaleDecision(
                "none", f"idle but at min ({knobs.min_replicas})",
                pressure, n, outcome="noop",
            )
        # Least-loaded live victim; ties break to the HIGHEST index (the
        # newest replica) so drained indices recycle last-in-first-out.
        victim = min(
            live,
            key=lambda r: (
                float(reps[r].get("occupancy", 0.0)),
                -_replica_index(r),
            ),
        )
        return ScaleDecision(
            "scale_down",
            f"pressure {pressure:.2f} <= {knobs.down_occ:g} "
            f"for {knobs.dwell_s:g}s",
            pressure, n, target=victim,
        )

    return ScaleDecision("none", "steady", pressure, n, outcome="noop")


def commit(
    state: PolicyState, dec: ScaleDecision, knobs: ScaleKnobs, now: float
) -> None:
    """Fold an EXECUTED decision back into the policy state.

    Only a terminal outcome arms the cooldown and resets the dwell
    clocks; a ``fault`` outcome (armed chaos site, nothing happened)
    leaves both so the very next tick retries — the contract behind the
    fleet.scale_spawn/scale_drain sites. A replacement bumps the
    per-replica attempt count and doubles its backoff window whatever the
    outcome: a target that keeps failing to come back IS the crash-loop
    the budget exists for.
    """
    if dec.action == "none":
        return
    if dec.action == "replace" and dec.target:
        cnt = state.replace_counts.get(dec.target, 0) + 1
        state.replace_counts[dec.target] = cnt
        state.replace_next_ok[dec.target] = (
            now + knobs.replace_backoff_s * (2 ** (cnt - 1))
        )
        if dec.outcome == "ok":
            state.dead_since.pop(dec.target, None)
    if dec.outcome == "fault":
        return  # retry next tick: dwell preserved, no cooldown
    state.high_since = None
    state.low_since = None
    if dec.outcome == "ok":
        state.cooldown_until = now + knobs.cooldown_s


class Autoscaler:
    """The policy loop: snapshot the router's fleet view, ``decide``,
    execute through the supervisor/router seams, ledger the outcome."""

    def __init__(
        self,
        router,
        supervisor,
        *,
        min_replicas: Optional[int] = None,
        max_replicas: Optional[int] = None,
        knobs: Optional[ScaleKnobs] = None,
        scale_log: Optional[str | Path] = None,
    ):
        self.router = router
        self.supervisor = supervisor
        self.knobs = knobs if knobs is not None else ScaleKnobs.from_env(
            min_replicas, max_replicas)
        self.state = PolicyState()
        self._lock = sanitize.named_lock("Autoscaler._lock", kind="rlock")
        self._scale_state = "steady"
        self._entered_at = time.monotonic()
        self._flaps = 0
        self._last_dir: Optional[str] = None
        self._counts: Dict[str, int] = {}
        self._recent: List[dict] = []
        self._log_path = (
            Path(scale_log) if scale_log is not None
            else Path(supervisor.root) / "data" / "scale_log.jsonl"
        )
        self.recorder = _metrics.FlightRecorder("fleet-scale")
        reg = _metrics.get_registry()
        self._m_state = reg.gauge(
            "kakveda_fleet_scale_state",
            "Scale state machine position (one-hot over "
            "steady|scale_up|drain|replace|cooldown)", ("state",),
        )
        for s in SCALE_STATES:
            self._m_state.labels(state=s).set(1.0 if s == "steady" else 0.0)
        self._m_transitions = reg.counter(
            "kakveda_fleet_scale_transitions_total",
            "Scale state transitions", ("from", "to"),
        )
        self._m_decisions = reg.counter(
            "kakveda_fleet_scale_decisions_total",
            "Executed scale decisions by action and outcome",
            ("action", "outcome"),
        )
        self._m_replicas = reg.gauge(
            "kakveda_fleet_scale_replicas",
            "Live replica count as seen by the autoscaler",
        )
        self._m_flaps = reg.counter(
            "kakveda_fleet_scale_flaps_total",
            "Scale direction reversals (up->down or down->up)",
        )

    # -- single-writer transition helper ---------------------------------

    def _set_scale_state(self, new_state: str, pressure: float,
                         detail: str = "") -> None:
        """THE one place the scale state machine moves: gauge vector +
        transition counter + flight-recorder event + log line together
        (single-writer invariant, scripts/lint_invariants.py). Caller
        holds ``_lock``."""
        old = self._scale_state
        if new_state == old:
            return
        self._scale_state = new_state
        self._entered_at = time.monotonic()
        self._m_state.labels(state=old).set(0.0)
        self._m_state.labels(state=new_state).set(1.0)
        self._m_transitions.labels(**{"from": old, "to": new_state}).inc()
        self.recorder.record(
            "scale", **{"from": old, "to": new_state,
                        "pressure": round(pressure, 3), "detail": detail})
        log.warning("fleet scale %s -> %s (pressure %.2f)%s",
                    old, new_state, pressure,
                    f" [{detail}]" if detail else "")

    # -- observation ------------------------------------------------------

    def snapshot(self, now: Optional[float] = None) -> dict:
        """The policy input, from the router's probe-fed FleetView +
        liveness verdicts + the supervisor's process poll (a SIGKILLed
        child shows up here a probe interval before the ring notices)."""
        if now is None:
            now = time.monotonic()
        view = getattr(self.router, "fleet_view", None)
        occ = view.occupancies() if view is not None else {}
        pressure = view.fleet_pressure() if view is not None else 0.0
        liveness = self.router.liveness()
        dead_procs = {
            self.supervisor.replica_id(i)
            for i in self.supervisor.poll_dead()
        }
        replicas: Dict[str, dict] = {}
        for rid, alive in liveness.items():
            alive = bool(alive) and rid not in dead_procs
            if alive:
                self.state.dead_since.pop(rid, None)
                dead_for = 0.0
            else:
                first = self.state.dead_since.setdefault(rid, now)
                dead_for = now - first
            replicas[rid] = {
                "live": alive,
                "occupancy": float(occ.get(rid, 0.0)),
                "dead_for_s": dead_for,
            }
        return {"replicas": replicas, "pressure": pressure}

    # -- the loop ----------------------------------------------------------

    async def run(self) -> None:
        while True:
            try:
                await self.tick()
            except asyncio.CancelledError:
                raise
            except Exception as e:  # noqa: BLE001 — the loop must survive
                log.warning("autoscale tick failed: %s: %s",
                            type(e).__name__, e)
            await asyncio.sleep(self.knobs.tick_s)

    async def tick(self) -> ScaleDecision:
        now = time.monotonic()
        snap = self.snapshot(now)
        with self._lock:
            dec = decide(snap, self.state, self.knobs, now)
            self._m_replicas.set(float(dec.n))
            if dec.action == "none":
                if (self._scale_state == "cooldown"
                        and now >= self.state.cooldown_until):
                    self._set_scale_state("steady", dec.pressure)
                return dec
            self._set_scale_state(
                {"scale_up": "scale_up", "scale_down": "drain",
                 "replace": "replace"}[dec.action],
                dec.pressure, dec.target or "")
        try:
            if dec.action == "scale_up":
                await self._do_scale_up(dec)
            elif dec.action == "scale_down":
                await self._do_scale_down(dec)
            else:
                await self._do_replace(dec)
            dec.outcome = "ok"
        except _faults.FaultInjected as e:
            dec.outcome = "fault"
            dec.detail = str(e)
            log.warning("scale %s faulted (%s); retrying next tick",
                        dec.action, e)
        except Exception as e:  # noqa: BLE001 — ledger it, keep looping
            from kakveda_tpu.fleet.ownership import MigrationError

            if dec.action == "scale_down" and isinstance(e, MigrationError):
                dec.outcome = "aborted"  # replica still serving
            else:
                dec.outcome = "error"
            dec.detail = f"{type(e).__name__}: {e}"
            log.warning("scale %s failed: %s", dec.action, dec.detail)
        with self._lock:
            commit(self.state, dec, self.knobs, time.monotonic())
            if dec.outcome == "ok" and dec.action in ("scale_up", "scale_down"):
                d = "up" if dec.action == "scale_up" else "down"
                if self._last_dir is not None and self._last_dir != d:
                    self._flaps += 1
                    self._m_flaps.inc()
                self._last_dir = d
            self._set_scale_state(
                "cooldown" if dec.outcome == "ok" else "steady",
                dec.pressure, dec.outcome)
        self._ledger(dec)
        return dec

    # -- executors ---------------------------------------------------------

    async def _do_scale_up(self, dec: ScaleDecision) -> None:
        """Spawn -> ready -> ring admission. The fault fires FIRST: a
        faulted spawn creates no process and never touches the epoch."""
        _FAULT_SPAWN.fire()
        loop = asyncio.get_running_loop()
        idx = await loop.run_in_executor(None, self.supervisor.add_replica)
        rid = self.supervisor.replica_id(idx)
        dec.target = rid
        # Wait on JUST the newcomer: an unrelated peer dying mid-spawn
        # must not fail this scale-up (replacement handles the peer).
        await loop.run_in_executor(
            None,
            lambda: self.supervisor.wait_ready(self.knobs.ready_s, only=(idx,)))
        if self.router.ownership is not None:
            members = dict(self.router.ownership.members)
            members[rid] = self.supervisor.url(idx)
            await self.router.rebalance_to(members)
        else:
            self.router.add_backend(rid, self.supervisor.url(idx))
        await self.router.probe_replica(rid)

    async def _do_scale_down(self, dec: ScaleDecision) -> None:
        """Migrate-then-stop, never the reverse: ship the victim's arcs
        (export -> ship -> epoch flip -> watermark-delta drain), drop it
        from the ring, THEN SIGTERM. The fault fires before the drain
        starts; any MigrationError aborts with the replica serving."""
        rid = dec.target or ""
        idx = _replica_index(rid)
        _FAULT_DRAIN.fire()
        if self.router.ownership is not None:
            members = {
                r: u for r, u in self.router.ownership.members.items()
                if r != rid
            }
            if not members:
                raise RuntimeError("refusing to drain the last owner")
            await self.router.rebalance_to(members)
        self.router.remove_backend(rid)
        loop = asyncio.get_running_loop()
        await loop.run_in_executor(None, lambda: self.supervisor.stop(idx))
        self.supervisor.retire(idx)

    async def _do_replace(self, dec: ScaleDecision) -> None:
        """Reap -> respawn at the SAME index (same id/url/ring position)
        -> probe re-admission -> heal: snapshot-ship its held arcs back
        from the surviving holders (run_rebalance over view-without-it ->
        full-view@epoch+1; signature-keyed upserts make the re-ship
        row-idempotent), while the origins' DLQ replay covers replication
        events dead-lettered at them during the outage."""
        rid = dec.target or ""
        idx = _replica_index(rid)
        _FAULT_SPAWN.fire()
        loop = asyncio.get_running_loop()
        # Short grace: the process is already presumed dead; the stop
        # escalation policy (supervisor.stop) still refuses SIGKILL on a
        # lease-marked replica.
        await loop.run_in_executor(
            None, lambda: self.supervisor.stop(idx, timeout_s=5.0))
        await loop.run_in_executor(None, self.supervisor.start, idx)
        await loop.run_in_executor(
            None,
            lambda: self.supervisor.wait_ready(self.knobs.ready_s, only=(idx,)))
        await self.router.probe_replica(rid)
        await self.router.resync_member(rid)

    # -- ledger / introspection -------------------------------------------

    def _ledger(self, dec: ScaleDecision) -> None:
        dec.ts = time.time()
        rec = dec.to_dict()
        self._m_decisions.labels(action=dec.action, outcome=dec.outcome).inc()
        # Scale decisions trace against the ownership epoch that fenced
        # them — a mid-migration warn anomaly joins its scale event by
        # trace ring, not log archaeology. tick() skips _ledger for
        # action "none", so the ring only carries real actions.
        attrs = dict(
            action=dec.action, target=dec.target or "",
            decision=dec.outcome, pressure=round(dec.pressure, 4),
        )
        epoch = getattr(getattr(self.router, "ownership", None), "epoch", None)
        if epoch is not None:
            attrs["epoch"] = epoch
        _trace.get_tracer().record_completed(
            "fleet.scale", ts=dec.ts,
            outcome="ok" if dec.outcome in ("ok", "noop") else "error",
            **attrs)
        with self._lock:
            key = f"{dec.action}:{dec.outcome}"
            self._counts[key] = self._counts.get(key, 0) + 1
            self._recent.append(rec)
            del self._recent[:-32]
        try:
            self._log_path.parent.mkdir(parents=True, exist_ok=True)
            with open(self._log_path, "a") as f:
                f.write(json.dumps(rec) + "\n")
        except OSError as e:
            log.warning("scale_log append failed: %s", e)

    def flap_count(self) -> int:
        with self._lock:
            return self._flaps

    def decision_counts(self) -> Dict[str, int]:
        """{"action:outcome": n} — the scale_events chaos action and the
        elastic bench read these."""
        with self._lock:
            return dict(self._counts)

    def info(self) -> dict:
        """Status block for router /readyz -> cli status/doctor."""
        now = time.monotonic()
        with self._lock:
            return {
                "state": self._scale_state,
                "min": self.knobs.min_replicas,
                "max": self.knobs.max_replicas,
                "flaps": self._flaps,
                "cooldown_left_s": round(
                    max(0.0, self.state.cooldown_until - now), 2),
                "counts": dict(self._counts),
                "last_decisions": list(self._recent[-8:]),
            }


def policy_selftest() -> int:
    """Canned decision table over the pure policy — no processes, no
    router, <1s. Raises AssertionError on the first divergence; returns
    the number of checks. Wired as scripts/verify_static.sh stage 4 and a
    tier-1 unit test, so a policy regression fails pre-commit."""
    k = ScaleKnobs(
        up_occ=0.8, down_occ=0.3, dwell_s=5.0, cooldown_s=15.0,
        min_replicas=2, max_replicas=4, replace_s=10.0,
        replace_backoff_s=5.0, replace_max=2,
    )
    st = PolicyState()

    def snap(occs: Dict[str, float], dead: Dict[str, float] = {}):
        reps = {
            r: {"live": r not in dead, "occupancy": o,
                "dead_for_s": dead.get(r, 0.0)}
            for r, o in occs.items()
        }
        live = [o for r, o in occs.items() if r not in dead]
        return {"replicas": reps, "pressure": max(live, default=0.0)}

    checks = 0

    def expect(t, s, action, target=None, outcome=None):
        nonlocal checks
        d = decide(s, st, k, t)
        assert d.action == action, (
            f"t={t}: expected {action}, got {d.action} ({d.reason})")
        if target is not None:
            assert d.target == target, (
                f"t={t}: expected target {target}, got {d.target}")
        if outcome is not None:
            d.outcome = outcome
            commit(st, d, k, t)
        checks += 1
        return d

    # high pressure: dwell blocks the first evaluations...
    expect(0.0, snap({"r0": 0.9, "r1": 0.85}), "none")
    expect(3.0, snap({"r0": 0.9, "r1": 0.85}), "none")
    # ...a dip resets the dwell clock...
    expect(4.0, snap({"r0": 0.5, "r1": 0.4}), "none")
    expect(5.0, snap({"r0": 0.9, "r1": 0.9}), "none")
    # ...and sustained pressure past dwell_s scales up.
    expect(10.5, snap({"r0": 0.9, "r1": 0.9}), "scale_up", outcome="ok")
    # cooldown gates the next one even at full dwell (the dwell clock
    # keeps running — pressure sustained THROUGH the cooldown counts)...
    expect(20.0, snap({"r0": 0.95, "r1": 0.95, "r2": 0.9}), "none")
    # ...so the second scale-up fires as soon as the cooldown expires...
    expect(26.0, snap({"r0": 0.95, "r1": 0.95, "r2": 0.9}),
           "scale_up", outcome="ok")
    # ...but max_replicas clamps at 4.
    expect(52.0, snap({"r0": 0.95, "r1": 0.95, "r2": 0.9, "r3": 0.9}),
           "none")
    expect(58.0, snap({"r0": 0.95, "r1": 0.95, "r2": 0.9, "r3": 0.9}),
           "none")
    # idle: least-loaded live replica drains (tie -> highest index)...
    expect(70.0, snap({"r0": 0.1, "r1": 0.05, "r2": 0.05, "r3": 0.2}),
           "none")
    expect(75.5, snap({"r0": 0.1, "r1": 0.05, "r2": 0.05, "r3": 0.2}),
           "scale_down", target="r2", outcome="ok")
    # ...cooldown gates again, then min_replicas floors the fleet at 2.
    expect(80.0, snap({"r0": 0.1, "r1": 0.1, "r3": 0.05}), "none")
    expect(97.0, snap({"r0": 0.1, "r1": 0.1, "r3": 0.05}),
           "scale_down", target="r3", outcome="ok")
    expect(120.0, snap({"r0": 0.0, "r1": 0.0}), "none")
    expect(126.0, snap({"r0": 0.0, "r1": 0.0}), "none")
    # replacement: fires past replace_s, beats elasticity, ignores
    # cooldown; a mid-pressure snapshot still replaces first.
    st2 = PolicyState()
    s_dead = snap({"r0": 0.9, "r1": 0.9}, dead={"r1": 12.0})
    d = decide(s_dead, st2, k, 200.0)
    assert d.action == "replace" and d.target == "r1", d
    d.outcome = "fault"
    commit(st2, d, k, 200.0)
    checks += 1
    # a faulted replace still burns budget + backoff (crash-loop damping):
    # next attempt blocked until 200 + 5s...
    d = decide(s_dead, st2, k, 203.0)
    assert d.action != "replace", d
    checks += 1
    # ...allowed at 206, and the SECOND attempt doubles the window.
    d = decide(s_dead, st2, k, 206.0)
    assert d.action == "replace", d
    d.outcome = "ok"
    commit(st2, d, k, 206.0)
    assert st2.replace_next_ok["r1"] == 206.0 + 10.0, st2.replace_next_ok
    checks += 1
    # budget exhausted (replace_max=2): never again.
    s_dead2 = snap({"r0": 0.9, "r1": 0.9}, dead={"r1": 500.0})
    d = decide(s_dead2, st2, k, 1000.0)
    assert d.action != "replace", d
    checks += 1
    # a faulted scale-up preserves the dwell clock: retry is immediate.
    st3 = PolicyState()
    hot = snap({"r0": 0.9, "r1": 0.9})
    decide(hot, st3, k, 0.0)
    d = decide(hot, st3, k, 6.0)
    assert d.action == "scale_up", d
    d.outcome = "fault"
    commit(st3, d, k, 6.0)
    d = decide(hot, st3, k, 6.5)
    assert d.action == "scale_up", f"faulted spawn must retry next tick: {d}"
    checks += 1
    return checks
