"""Gossiped control state — fleet-wide brownout from per-replica inputs.

Each replica publishes one control sample per tick on the bus topic
``fleet.control`` (delivered to every peer's ``POST /fleet/gossip``):

    {"replica": "r0", "seq": 17, "ts": …, "occupancy": 0.42,
     "brownout": "normal", "brownout_step": 0, "degraded": false}

and folds the samples it receives into a :class:`FleetView`. The folded
view feeds the replica's OWN admission controller through
:meth:`AdmissionController.note_fleet_pressure` — a pressure *input*, so
the brownout ladder degrades fleet-wide (one saturated replica steps
every replica down) while every actual transition still goes through the
single-writer ``_set_brownout_state`` helper. The gossip path never
touches gate state directly, and a replica's DEGRADED latch stays local
(peer device loss is reported in the view, not latched here).

Freshness discipline (what makes DLQ replay and at-least-once redelivery
safe for this topic even though it is marked ephemeral): a sample is
folded only when its ``seq`` advances the sender's last-seen sequence AND
its ``ts`` is within the view TTL — replayed or reordered samples are
counted and dropped. Samples older than the TTL expire out of the view,
so a dead peer stops contributing pressure ~one TTL after it dies.

Knobs (docs/scale-out.md): ``KAKVEDA_FLEET_GOSSIP_S`` publish interval,
``KAKVEDA_FLEET_GOSSIP_TTL_S`` view/pressure TTL.
"""

from __future__ import annotations

import asyncio
import logging
import threading
import time
from typing import Dict, Optional

from kakveda_tpu.core import metrics as _metrics
from kakveda_tpu.events.bus import TOPIC_FLEET_CONTROL, EventBus
from kakveda_tpu.core import sanitize

log = logging.getLogger("kakveda.fleet")


class FleetView:
    """Peer control samples, folded with seq/TTL freshness discipline.

    Thread-safe: folds arrive on the event loop, readers include the
    gossip tick and /readyz."""

    def __init__(self, ttl_s: float = 5.0):
        self.ttl_s = float(ttl_s)
        self._lock = sanitize.named_lock("FleetView._lock")
        # replica id -> (sample dict, folded-at monotonic ts)
        self._samples: Dict[str, tuple] = {}
        reg = _metrics.get_registry()
        self._m_gossip = reg.counter(
            "kakveda_fleet_gossip_total",
            "Gossip samples by result (sent|folded|stale)", ("result",),
        )
        self._m_sent = self._m_gossip.labels(result="sent")
        self._m_folded = self._m_gossip.labels(result="folded")
        self._m_stale = self._m_gossip.labels(result="stale")

    def note_sent(self) -> None:
        self._m_sent.inc()

    def fold(self, sample: dict) -> bool:
        """Fold one received sample; returns False (and counts ``stale``)
        for replays, reordering, or samples past the TTL."""
        replica = sample.get("replica")
        seq = sample.get("seq")
        ts = sample.get("ts")
        if not isinstance(replica, str) or not isinstance(seq, (int, float)):
            self._m_stale.inc()
            return False
        if isinstance(ts, (int, float)) and time.time() - ts > self.ttl_s:
            self._m_stale.inc()  # DLQ replay / long-delayed redelivery
            return False
        with self._lock:
            prev = self._samples.get(replica)
            if prev is not None and prev[0].get("seq", -1) >= seq:
                self._m_stale.inc()
                return False
            self._samples[replica] = (dict(sample), time.monotonic())
        self._m_folded.inc()
        return True

    def _live_locked(self) -> Dict[str, dict]:
        now = time.monotonic()
        return {
            r: s for r, (s, at) in self._samples.items() if now - at <= self.ttl_s
        }

    def peers(self) -> Dict[str, dict]:
        """Live (unexpired) samples with their age — the /readyz view."""
        with self._lock:
            now = time.monotonic()
            return {
                r: {**s, "age_s": round(now - at, 2)}
                for r, (s, at) in self._samples.items()
                if now - at <= self.ttl_s
            }

    # The router folds its probe/ejection liveness into the same view:
    # one synthetic sample per broadcast under this sender id, carrying
    # {"probe_verdicts": {rid: bool}}. One liveness world-view — the
    # pressure floor and the router's ejection decisions stop disagreeing.
    ROUTER_SENDER = "__router__"

    def probe_verdicts(self) -> Dict[str, bool]:
        """The router's latest per-replica liveness verdicts, {} when no
        fresh router sample has arrived (standalone replicas, old routers)."""
        with self._lock:
            live = self._live_locked()
        s = live.get(self.ROUTER_SENDER)
        v = s.get("probe_verdicts") if isinstance(s, dict) else None
        return {str(k): bool(b) for k, b in v.items()} if isinstance(v, dict) else {}

    def ownership_epochs(self) -> Dict[str, int]:
        """Per-peer ownership epochs from live samples — stale-ring-view
        detection (doctor flags disagreement; fleet/ownership.py)."""
        with self._lock:
            live = self._live_locked()
        out: Dict[str, int] = {}
        for r, s in live.items():
            e = s.get("ownership_epoch")
            if isinstance(e, int):
                out[r] = e
        return out

    def fleet_pressure(self) -> float:
        """Max peer occupancy among live samples — the ladder input the
        local admission controller folds in (note_fleet_pressure).

        Peers the router's probe verdict marks dead are skipped: a peer
        that died seconds after gossiping 0.9 occupancy would otherwise
        pin every survivor's brownout floor for a full TTL while the
        router already routes around it. No verdict (no router, or none
        yet) keeps the pure-TTL behavior."""
        verdicts = self.probe_verdicts()
        with self._lock:
            live = self._live_locked()
        return max(
            (
                float(s.get("occupancy", 0.0))
                for r, s in live.items()
                if r != self.ROUTER_SENDER and verdicts.get(r, True)
            ),
            default=0.0,
        )

    def occupancies(self) -> Dict[str, float]:
        """Per-replica occupancy from live samples (router sender
        excluded) — the autoscaler's least-loaded victim selection."""
        with self._lock:
            live = self._live_locked()
        return {
            r: float(s.get("occupancy", 0.0))
            for r, s in live.items()
            if r != self.ROUTER_SENDER
        }

    def any_degraded(self) -> bool:
        with self._lock:
            live = self._live_locked()
        return any(bool(s.get("degraded")) for s in live.values())

    def worst_brownout(self) -> Dict[str, object]:
        """The most-degraded live peer's ladder position (fleet mode for
        /readyz and doctor)."""
        with self._lock:
            live = self._live_locked()
        worst = {"state": "normal", "step": 0}
        for s in live.values():
            step = int(s.get("brownout_step", 0) or 0)
            if step > int(worst["step"]):
                worst = {"state": str(s.get("brownout", "?")), "step": step}
        return worst


def sample_from_ready(rid: str, seq: int, ready: dict) -> dict:
    """Synthesize a gossip-shaped control sample from a /readyz body.

    The router folds one per successful probe into its OWN FleetView, so
    the autoscaler consumes the same (occupancy, brownout rung, DEGRADED)
    vocabulary — with the same seq/TTL freshness discipline — whether the
    signal travelled by bus gossip or by probe. The replica's exported
    ``admission.occupancy`` is its LOCAL load only (local_pressure) —
    never the folded fleet floor, which would echo pressure rumors back
    into the view."""
    adm = ready.get("admission") or {}
    occ = adm.get("occupancy")
    if not isinstance(occ, (int, float)):
        # Older replicas without the export: approximate the LOCAL load
        # from per-class in-flight counts (never the gossiped floor —
        # folding it back in re-creates the echo the export avoids).
        classes = adm.get("classes") or {}
        loads = [
            c.get("inflight", 0) / c["limit"]
            for c in classes.values()
            if isinstance(c, dict) and c.get("limit")
        ]
        occ = max(loads, default=0.0)
    dev = ready.get("device") or {}
    out = {
        "replica": rid,
        "seq": int(seq),
        "ts": time.time(),
        "occupancy": round(float(occ), 4),
        "brownout": adm.get("brownout", "normal"),
        "brownout_step": int(adm.get("brownout_step", 0) or 0),
        "degraded": bool(dev.get("degraded")),
    }
    own = ready.get("ownership") or {}
    if isinstance(own.get("epoch"), int):
        out["ownership_epoch"] = own["epoch"]
    return out


class GossipPublisher:
    """The per-replica gossip tick: sample own admission/health state,
    publish on ``fleet.control``, and re-feed the folded fleet pressure
    into the local controller (so the ladder also re-evaluates — and can
    step back down — while the replica is idle)."""

    def __init__(
        self,
        bus: EventBus,
        admission,
        health,
        replica_id: str,
        view: FleetView,
        interval_s: float = 1.0,
        ownership=None,
    ):
        self.bus = bus
        self.admission = admission
        self.health = health
        self.replica_id = replica_id
        self.view = view
        self.interval_s = max(0.05, float(interval_s))
        # fleet.ownership.OwnershipState when KAKVEDA_FLEET_OWNERSHIP=1:
        # samples then carry the replica's acknowledged ownership epoch,
        # so peers (and doctor) detect stale ring views fleet-wide.
        self.ownership = ownership
        self._seq = 0
        self._m_pressure = _metrics.get_registry().gauge(
            "kakveda_fleet_pressure",
            "Folded fleet pressure input (max live peer occupancy) fed to "
            "the local admission controller",
        )

    def sample(self) -> dict:
        self._seq += 1
        brown = self.admission.brownout
        out = {
            "replica": self.replica_id,
            "seq": self._seq,
            "ts": time.time(),
            # LOCAL load only: publishing the combined pressure() would
            # echo a peer's gossiped floor back out as our own occupancy
            # and two replicas then refresh each other's floor forever —
            # the floor is an input (tick_inputs), never an output.
            "occupancy": round(self.admission.local_pressure(), 4),
            "brownout": brown.state,
            "brownout_step": brown.step,
            "degraded": bool(self.health.degraded),
        }
        if self.ownership is not None:
            out["ownership_epoch"] = self.ownership.view.epoch
        return out

    def tick_inputs(self) -> None:
        """Fold the current fleet view into the local controller — the
        ONLY admission-facing effect of the gossip path (an input; gate
        state moves solely through the controller's own helpers)."""
        p = self.view.fleet_pressure()
        self._m_pressure.set(p)
        self.admission.note_fleet_pressure(p, ttl_s=self.view.ttl_s)

    async def run(self) -> None:
        while True:
            try:
                await self.bus.publish(TOPIC_FLEET_CONTROL, self.sample())
                self.view.note_sent()
                self.tick_inputs()
            except asyncio.CancelledError:
                raise
            except Exception as e:  # noqa: BLE001 — gossip must never kill the app
                log.warning("gossip tick failed: %s: %s", type(e).__name__, e)
            await asyncio.sleep(self.interval_s)
