"""Deterministic consistent-hash ring for warn-shard routing.

Why a ring and not ``hash(key) % N``: replica loss must remap only the
keys the dead replica owned (~1/N of traffic), never reshuffle the whole
key space — the warn path's per-replica match caches and incremental
mining reuse (``index/gfkb.py`` match cache) are keyed by signature, and
a global reshuffle would cold-start every one of them at once.

Why :func:`hashlib.blake2b` and not Python's ``hash()``: ``hash()`` is
salted per process (PYTHONHASHSEED), so a restarted router would assign
every key differently — assignment must be a pure function of
(key, membership) so routers can restart, and replicas can be probed
back in, without a remap storm.  Tested properties
(tests/test_fleet.py): identical assignment across independent ring
instances, and remap fraction on single-node loss ≲ 1/N + slack.
"""

from __future__ import annotations

import hashlib
from bisect import bisect_right
from typing import Iterable, List, Optional, Sequence, Tuple


def _point(key: str) -> int:
    """64-bit ring position — stable across processes and Python builds."""
    return int.from_bytes(
        hashlib.blake2b(key.encode("utf-8"), digest_size=8).digest(), "big"
    )


class HashRing:
    """Consistent-hash ring with virtual nodes.

    ``vnodes`` spreads each node over the ring so load stays balanced even
    at small N (64 vnodes keeps the max/mean shard ratio ≲ 1.3 at N=4).
    """

    def __init__(self, nodes: Iterable[str], vnodes: int = 64):
        self.vnodes = max(1, int(vnodes))
        # Insertion order preserved, duplicates dropped (node ids are the
        # routing identity — two vnode sets for one id would double-weight it).
        self._nodes: List[str] = list(dict.fromkeys(nodes))
        ring: List[Tuple[int, str]] = []
        for n in self._nodes:
            for v in range(self.vnodes):
                ring.append((_point(f"{n}#{v}"), n))
        ring.sort()
        self._ring = ring
        self._points = [p for p, _ in ring]

    @property
    def nodes(self) -> List[str]:
        return list(self._nodes)

    def preference(self, key: str, limit: Optional[int] = None) -> List[str]:
        """Distinct nodes in clockwise ring order from ``key``'s position —
        element 0 is the owner, the rest are the stable failover order
        (retry-on-next-replica walks this list)."""
        if not self._ring:
            return []
        limit = len(self._nodes) if limit is None else min(limit, len(self._nodes))
        out: List[str] = []
        start = bisect_right(self._points, _point(key))
        for i in range(len(self._ring)):
            node = self._ring[(start + i) % len(self._ring)][1]
            if node not in out:
                out.append(node)
                if len(out) >= limit:
                    break
        return out

    def arc_preferences(self, limit: Optional[int] = None) -> List[Tuple[str, ...]]:
        """Per-vnode-arc holder walks: for every arc of the ring (keys
        hashing into it start their clockwise walk at that arc's vnode),
        the distinct-node preference tuple of length ≤ ``limit``.

        This enumerates every assignment outcome the ring can produce —
        sharded ownership (fleet/ownership.py) uses it to count a
        replica's owned/standby ranges and to detect coverage holes
        exactly, instead of sampling keys."""
        if not self._ring:
            return []
        limit = len(self._nodes) if limit is None else min(limit, len(self._nodes))
        n = len(self._ring)
        out: List[Tuple[str, ...]] = []
        for start in range(n):
            pref: List[str] = []
            for i in range(n):
                node = self._ring[(start + i) % n][1]
                if node not in pref:
                    pref.append(node)
                    if len(pref) >= limit:
                        break
            out.append(tuple(pref))
        return out

    def assign(self, key: str, exclude: Sequence[str] = ()) -> Optional[str]:
        """The owning node for ``key``, skipping ``exclude`` (ejected
        replicas). Membership does NOT change on ejection — the ring stays
        stable and excluded keys spill to their failover successor, so a
        probe-recovered replica gets its exact old keys back."""
        for node in self.preference(key):
            if node not in exclude:
                return node
        return None
