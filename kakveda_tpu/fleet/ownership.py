"""Sharded ownership over the fleet hash ring — ranges, epochs, migration.

With ``KAKVEDA_FLEET_OWNERSHIP=1`` the blake2b ring stops being a pure
routing hint and becomes the fleet's data-placement authority: every key
(the ingest ``app_id``) has exactly R **holders** — the ring-preference
walk ``[owner, standby_1, …, standby_{R-1}]`` (``KAKVEDA_FLEET_REPLICATION``,
default 2) — and

* ingest replication is **range-scoped**: an origin publishes accepted rows
  only to the holders of each row's key, on per-peer bus topics
  (:func:`kakveda_tpu.events.bus.replicate_topic`), keeping the existing
  at-least-once retry → breaker → DLQ machinery and the idempotent
  event-id apply;
* warn becomes a router-side **scatter-gather top-k merge** across live
  shards (fleet/router.py) with a typed partial-result contract;
* the **ownership epoch** (one fleet-wide int, the router is the single
  writer) fences stale ring views: every scoped replicate event carries the
  publisher's epoch, and a receiver that is no longer a holder of the
  rows' keys drops an OLDER-epoch event cleanly instead of resurrecting a
  migrated range (service/app.py ``/replicate``).

``KAKVEDA_FLEET_OWNERSHIP=0`` (the default) leaves the full-replication
fleet bit-for-bit untouched — this module is then never consulted.

Range migration (scale-out/in) is :func:`run_rebalance`: for a membership
change ``old → new`` it (1) snapshot-ships, from each responsible source,
the rows whose NEW holder set gained a member (deterministic event ids, so
re-runs and DLQ replay stay idempotent), (2) flips ownership atomically
per replica by pushing the new epoch'd view to every member and the
router, then (3) drains the delta — rows appended at the sources since the
export mark. Movement is bounded: only rows whose holder set changed ship.
An armed ``fleet.range_migrate`` fault aborts a ship batch cleanly BEFORE
the flip (ownership unchanged, no lost rows); a drain failure after the
flip is healed by re-running the rebalance (same ids → dedup) or DLQ
replay. Sources keep rows they no longer hold (copy-based migration; the
GFKB log is append-only) — residency bounds are enforced by ingest-time
scoping, and foreign rows age out on re-seed.

State machine + failure contract: docs/scale-out.md.
"""

from __future__ import annotations

import json
import logging
import os
import time
from pathlib import Path
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

from kakveda_tpu.core import faults as _faults
from kakveda_tpu.fleet.hashring import HashRing

log = logging.getLogger("kakveda.fleet")

# Chaos site (docs/robustness.md): armed, a migration ship batch fails —
# the rebalance aborts cleanly before the ownership flip (pre-flip) or
# leaves a re-runnable drain gap (post-flip); never a lost or
# double-counted row.
_FAULT_MIGRATE = _faults.site("fleet.range_migrate")


class MigrationError(RuntimeError):
    """A range migration failed mid-protocol. ``flipped`` says whether the
    ownership flip already happened: False → nothing changed, safe to
    retry from scratch; True → re-run the same rebalance (deterministic
    event ids dedup the re-ship) to close the drain gap."""

    def __init__(self, message: str, *, flipped: bool):
        super().__init__(message)
        self.flipped = flipped


def parse_members(spec: str) -> Dict[str, str]:
    """``"r0=http://h:p,r1=http://h:q"`` → ``{rid: url}`` (the
    ``KAKVEDA_FLEET_MEMBERS`` env format written by the supervisor)."""
    out: Dict[str, str] = {}
    for part in (spec or "").split(","):
        part = part.strip()
        if not part or "=" not in part:
            continue
        rid, url = part.split("=", 1)
        if rid.strip() and url.strip():
            out[rid.strip()] = url.strip().rstrip("/")
    return out


def shard_key_of_row(row: dict) -> str:
    """The ownership key of one replication/ingest row dict — the app that
    produced it, falling back to the signature for app-less rows. Must
    agree with :meth:`GFKB.shard_key_counts` so residency accounting and
    placement see the same key."""
    k = row.get("app_id")
    if isinstance(k, str) and k:
        return k
    sig = row.get("signature_text")
    return sig if isinstance(sig, str) else ""


class OwnershipView:
    """One immutable (members, replication, epoch) placement snapshot.

    Holders of a key are the ring-preference walk limited to R — element 0
    is the owner, the rest the warm standbys. "Ranges" are the ring's
    vnode arcs: coverage accounting (partial-result contract, doctor's
    coverage-hole check) enumerates every arc's holder tuple rather than
    sampling keys, so a range with zero live holders is detected exactly.
    """

    def __init__(
        self,
        members: Dict[str, str],
        *,
        replication: int = 2,
        epoch: int = 1,
        vnodes: int = 64,
    ):
        if not members:
            raise ValueError("ownership view needs at least one member")
        self.members: Dict[str, str] = {
            rid: url.rstrip("/") for rid, url in sorted(members.items())
        }
        self.replication = max(1, min(int(replication), len(self.members)))
        self.epoch = int(epoch)
        self.vnodes = int(vnodes)
        self.ring = HashRing(list(self.members), vnodes=self.vnodes)
        # Every arc's distinct-holder walk, computed once: 64·N tuples.
        self._arcs: List[Tuple[str, ...]] = self.ring.arc_preferences(
            limit=self.replication
        )

    # -- placement -------------------------------------------------------

    def holders(self, key: str) -> List[str]:
        """``[owner, standby_1, …]`` for ``key`` — R distinct members."""
        return self.ring.preference(key, limit=self.replication)

    def owner(self, key: str) -> str:
        return self.holders(key)[0]

    def is_holder(self, rid: str, key: str) -> bool:
        return rid in self.holders(key)

    def role(self, rid: str, key: str) -> Optional[str]:
        h = self.holders(key)
        if not h or rid not in h:
            return None
        return "owner" if h[0] == rid else "standby"

    # -- range (arc) accounting -----------------------------------------

    def arcs(self) -> List[Tuple[str, ...]]:
        """Per-vnode-arc holder tuples (element 0 owns the arc)."""
        return list(self._arcs)

    def arc_counts(self, rid: str) -> Tuple[int, int]:
        """(owned arcs, standby arcs) for one member."""
        owned = sum(1 for a in self._arcs if a and a[0] == rid)
        standby = sum(1 for a in self._arcs if rid in a[1:])
        return owned, standby

    def coverage_holes(self, live: Iterable[str]) -> int:
        """Arcs whose ENTIRE holder set is outside ``live`` — key ranges
        no reachable replica can answer for. Zero in a healthy fleet; any
        positive count is a doctor error and flips ``partial=true`` on a
        scatter-gather verdict."""
        alive = set(live)
        return sum(1 for a in self._arcs if not (set(a) & alive))

    # -- (de)serialization ----------------------------------------------

    def to_dict(self) -> dict:
        return {
            "epoch": self.epoch,
            "replication": self.replication,
            "vnodes": self.vnodes,
            "members": dict(self.members),
        }

    @classmethod
    def from_dict(cls, obj: dict) -> "OwnershipView":
        return cls(
            dict(obj["members"]),
            replication=int(obj.get("replication", 2)),
            epoch=int(obj.get("epoch", 1)),
            vnodes=int(obj.get("vnodes", 64)),
        )

    def with_members(
        self, members: Dict[str, str], *, epoch: Optional[int] = None
    ) -> "OwnershipView":
        return OwnershipView(
            members,
            replication=self.replication,
            epoch=self.epoch + 1 if epoch is None else epoch,
            vnodes=self.vnodes,
        )

    def with_epoch(self, epoch: int) -> "OwnershipView":
        return OwnershipView(
            self.members,
            replication=self.replication,
            epoch=epoch,
            vnodes=self.vnodes,
        )

    def save(self, path: Path) -> None:
        """Atomic persist — a replica restarted mid-topology-change must
        come back with the epoch it had acknowledged, not its spawn env."""
        tmp = path.with_suffix(".tmp")
        tmp.write_text(json.dumps(self.to_dict(), indent=2))
        os.replace(tmp, path)

    @classmethod
    def load(cls, path: Path) -> Optional["OwnershipView"]:
        try:
            return cls.from_dict(json.loads(path.read_text()))
        except (OSError, ValueError, KeyError):
            return None


class OwnershipState:
    """The mutable per-process handle over the immutable view — platform
    publish, the /replicate fence and gossip all read ``state.view``, and
    the /fleet/ownership push swaps it atomically (one reference write)."""

    def __init__(self, view: OwnershipView, self_id: str):
        self.view = view
        self.self_id = self_id


def responsible_source(
    key: str, old: OwnershipView, sources: Sequence[str]
) -> Optional[str]:
    """Exactly ONE source ships each key during a rebalance: the first
    member of the OLD holder walk that is actually exportable (``sources``
    — scale-in removes dead members, which cannot export). R-way
    replication means any surviving holder has the rows."""
    for rid in old.holders(key):
        if rid in sources:
            return rid
    return None


def plan_targets(
    key: str, old: OwnershipView, new: OwnershipView
) -> List[str]:
    """Members that GAIN ``key`` under the new view — the bounded movement
    set (holders whose membership did not change never receive a copy)."""
    before = set(old.holders(key))
    return [rid for rid in new.holders(key) if rid not in before]


def run_rebalance(
    old: OwnershipView,
    new: OwnershipView,
    *,
    timeout_s: float = 30.0,
    batch: int = 256,
) -> dict:
    """Drive one membership change ``old → new`` over live replicas.

    Synchronous by design (runs in an executor from the router's
    /fleet/rebalance, or inline from bench/tests): export → ship →
    flip → drain, with deterministic event ids throughout so any retry —
    including a full re-run after a post-flip failure — applies
    idempotently. Returns movement stats; raises :class:`MigrationError`
    with ``flipped`` telling the caller whether ownership changed."""
    import httpx

    if new.epoch <= old.epoch:
        raise ValueError(
            f"new view epoch {new.epoch} must exceed old epoch {old.epoch}"
        )
    t0 = time.monotonic()
    moved = 0
    batches = 0
    sources = sorted(rid for rid in old.members if rid in new.members)
    if not sources:
        raise MigrationError(
            "no surviving member can export (old ∩ new is empty)", flipped=False
        )
    flipped = False

    def _ship(client, src: str, grouped: Dict[str, List[dict]], tag: str) -> None:
        nonlocal moved, batches
        for tgt in sorted(grouped):
            rows = grouped[tgt]
            url = new.members[tgt] + "/replicate"
            for bi in range(0, len(rows), batch):
                chunk = rows[bi : bi + batch]
                event_id = f"mig-{new.epoch}-{src}-{tgt}-{tag}-{bi // batch}"
                _FAULT_MIGRATE.fire()
                r = client.post(
                    url,
                    json={
                        "id": event_id,
                        "origin": src,
                        "ts": time.time(),
                        "epoch": new.epoch,
                        "migration": True,
                        "rows": chunk,
                    },
                )
                r.raise_for_status()
                moved += len(chunk)
                batches += 1

    def _export(client, src: str, since: int) -> Tuple[Dict[str, List[dict]], int]:
        r = client.post(
            old.members[src] + "/fleet/export",
            json={
                "old": old.to_dict(),
                "new": new.to_dict(),
                "sources": sources,
                "since": since,
            },
        )
        r.raise_for_status()
        body = r.json()
        grouped = {
            str(t): list(rows)
            for t, rows in (body.get("rows") or {}).items()
            if rows
        }
        return grouped, int(body.get("count", 0))

    try:
        with httpx.Client(timeout=timeout_s) as client:
            # 1) snapshot-ship each responsible source's gained ranges.
            marks: Dict[str, int] = {}
            for src in sources:
                grouped, marks[src] = _export(client, src, 0)
                _ship(client, src, grouped, "snap")
            # 2) atomic flip: push the epoch'd view to every member (old
            # AND new — a scale-in survivor must learn it lost ranges).
            urls = {**old.members, **new.members}
            for rid in sorted(urls):
                r = client.post(urls[rid] + "/fleet/ownership", json=new.to_dict())
                r.raise_for_status()
            flipped = True
            # 3) drain the delta log: rows appended since the export mark.
            for src in sources:
                grouped, _ = _export(client, src, marks[src])
                _ship(client, src, grouped, "drain")
    except (httpx.HTTPError, _faults.FaultInjected) as e:
        raise MigrationError(
            f"rebalance {old.epoch}->{new.epoch} failed "
            f"({'post' if flipped else 'pre'}-flip): {type(e).__name__}: {e}",
            flipped=flipped,
        ) from e
    return {
        "epoch": new.epoch,
        "rows_moved": moved,
        "batches": batches,
        "sources": sources,
        "wall_s": round(time.monotonic() - t0, 3),
    }
