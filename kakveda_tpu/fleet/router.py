"""The fleet front router — one ingress port over N service replicas.

``cli up --replicas N`` mounts this app on the public port; replicas
listen on ``port_base + i``. Routing policy (docs/scale-out.md):

* **Warn traffic shards by app key** (``app_id``, falling back to
  ``signature_text``) over a deterministic consistent-hash ring
  (:mod:`kakveda_tpu.fleet.hashring`) — affinity keeps each replica's
  match cache and incremental-mining reuse hot for its share of apps.
* **Health probes + ejection**: a background probe hits every replica's
  ``/readyz``; ``KAKVEDA_ROUTER_EJECT_FAILS`` consecutive transport
  failures eject a replica from selection (ring membership is untouched,
  so recovery restores its exact key range); a successful probe un-ejects.
* **Retry-on-next-replica** for idempotent reads (warn, match, GETs):
  a transport failure or 5xx walks the key's stable failover order —
  the kill-one-replica drill's zero-lost-warns contract. Ingest retries
  ONLY on connect errors (the request never left), and admin mutations
  are single-attempt.
* 429/503 from a replica are passed through untouched: those are
  admission/degraded verdicts, not router failures — shedding stays
  end-to-end typed (core/admission.py).

The router is deliberately stateless beyond health/breaker bookkeeping:
all durable state lives in the replicas, so a router restart only needs
the backend list to resume identical routing (hashring determinism).

Metrics: the ``kakveda_fleet_*`` family (docs/observability.md) —
per-replica forwards/ejections/health, reroute counter, router overhead
histogram and a hot-key skew gauge (max single-key share of routed warn
traffic).
"""

from __future__ import annotations

import asyncio
import json
import logging
import os
import time
from typing import Dict, List, Mapping, Optional

from aiohttp import web

from kakveda_tpu.core import faults as _faults
from kakveda_tpu.core import metrics as _metrics
from kakveda_tpu.core import trace as _trace
from kakveda_tpu.core.runtime import ensure_request_id
from kakveda_tpu.fleet.gossip import FleetView, sample_from_ready
from kakveda_tpu.fleet.hashring import HashRing

log = logging.getLogger("kakveda.fleet")

# Chaos site (docs/robustness.md): an armed router.forward fault fails a
# forward attempt exactly like a transport error — proving the
# retry-on-next-replica path without killing a process.
_FAULT_FORWARD = _faults.site("router.forward")
# Sharded-ownership chaos sites (docs/robustness.md, resolve-once):
# an armed gfkb.scatter_gather fault fails ONE shard sub-request of a
# scatter-gather warn exactly like a transport error — the merged verdict
# must degrade to partial=true with shard provenance, never hang, never
# silently shrink coverage. An armed fleet.promote fault fails the
# ownership-epoch push after an ejection — routing has already failed
# over (candidates skip the ejected owner); the push stays dirty and
# retries next probe tick.
_FAULT_SCATTER = _faults.site("gfkb.scatter_gather")
_FAULT_PROMOTE = _faults.site("fleet.promote")

ROUTER_KEY: web.AppKey["Router"] = web.AppKey("fleet_router", object)  # type: ignore[type-var]
_PROBE_TASK_KEY: web.AppKey[object] = web.AppKey("fleet_probe_task", object)
_SUPERVISE_TASK_KEY: web.AppKey[object] = web.AppKey("fleet_supervise_task", object)
AUTOSCALER_KEY: web.AppKey[object] = web.AppKey("fleet_autoscaler", object)
_AUTOSCALE_TASK_KEY: web.AppKey[object] = web.AppKey("fleet_autoscale_task", object)

# Bounded hot-key accounting: enough keys to see real skew, cheap enough
# to keep on the forward hot path.
_HOT_KEYS_MAX = 4096


def _env_int(name: str, default: int) -> int:
    try:
        return int(os.environ.get(name, str(default)))
    except ValueError:
        return default


def _env_float(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, str(default)))
    except ValueError:
        return default


class Router:
    """Routing + health state over a fixed backend map {replica_id: url}."""

    def __init__(
        self,
        backends: Dict[str, str],
        *,
        vnodes: Optional[int] = None,
        probe_interval_s: Optional[float] = None,
        eject_fails: Optional[int] = None,
        retries: Optional[int] = None,
        timeout_s: Optional[float] = None,
        ownership=None,
    ):
        if not backends:
            raise ValueError("router needs at least one backend replica")
        self.backends = dict(backends)
        # Sharded ownership (fleet/ownership.py OwnershipView, or None =
        # legacy full replication). The router is the epoch's single
        # writer: ejections and re-admissions mark the view dirty, the
        # probe loop bumps the epoch once per change batch and pushes the
        # view to every live replica (standby promotion is routing-side
        # instant via candidates(); the push is what fences stale views).
        self.ownership = ownership
        self._own_dirty = False
        self._verdict_seq = 0
        from kakveda_tpu.core.runtime import get_runtime_config

        # Resolved once (hot forwards must not re-read config): the header
        # the service tier echoes/logs — propagated per hop so replica
        # logs join router logs by request id (and by trace id).
        self._rid_header = get_runtime_config(
            service_name="kakveda-router"
        ).request_id_header
        self.ring = HashRing(
            list(self.backends),
            vnodes=_env_int("KAKVEDA_FLEET_VNODES", 64) if vnodes is None else vnodes,
        )
        self.probe_interval_s = (
            _env_float("KAKVEDA_ROUTER_PROBE_S", 1.0)
            if probe_interval_s is None else probe_interval_s
        )
        self.eject_fails = (
            _env_int("KAKVEDA_ROUTER_EJECT_FAILS", 3)
            if eject_fails is None else eject_fails
        )
        # Extra attempts after the owner for idempotent reads.
        self.retries = (
            min(_env_int("KAKVEDA_ROUTER_RETRIES", 2), len(self.backends) - 1)
            if retries is None else retries
        )
        self.timeout_s = (
            _env_float("KAKVEDA_ROUTER_TIMEOUT_S", 15.0)
            if timeout_s is None else timeout_s
        )
        self._state = {
            rid: {"fails": 0, "ejected": False, "healthy": None, "ready": None}
            for rid in self.backends
        }
        # The router's own fold of the fleet's control vocabulary: one
        # gossip-shaped sample per successful probe (gossip.
        # sample_from_ready) under the SAME seq/TTL freshness discipline
        # the replicas use on the bus — the autoscaler's policy input.
        self.fleet_view = FleetView(
            ttl_s=_env_float("KAKVEDA_FLEET_GOSSIP_TTL_S", 5.0)
        )
        self._probe_fold_seq = 0
        # Mounted by make_router_app(autoscale=…); report() exposes it.
        self.autoscaler = None
        self._client = None  # httpx.AsyncClient, bound at app startup
        self._hot_keys: Dict[str, int] = {}
        self._hot_total = 0
        reg = _metrics.get_registry()
        fwd = reg.counter(
            "kakveda_fleet_forwards_total",
            "Router forwards by replica and outcome (ok|error|passthrough)",
            ("replica", "outcome"),
        )
        self._m_fwd = {
            rid: {o: fwd.labels(replica=rid, outcome=o)
                  for o in ("ok", "error", "passthrough")}
            for rid in self.backends
        }
        self._m_reroutes = reg.counter(
            "kakveda_fleet_reroutes_total",
            "Requests retried on the next replica after a forward failure",
        )
        ej = reg.counter(
            "kakveda_fleet_ejections_total",
            "Replica ejections after consecutive forward/probe failures",
            ("replica",),
        )
        self._m_eject = {rid: ej.labels(replica=rid) for rid in self.backends}
        g_healthy = reg.gauge(
            "kakveda_fleet_replica_healthy",
            "1 while a replica answers probes and is not ejected", ("replica",),
        )
        self._m_healthy = {rid: g_healthy.labels(replica=rid) for rid in self.backends}
        load = reg.counter(
            "kakveda_fleet_shard_load_total",
            "Key-routed requests per replica (shard balance)", ("replica",),
        )
        self._m_load = {rid: load.labels(replica=rid) for rid in self.backends}
        self._m_overhead = reg.histogram(
            "kakveda_fleet_router_overhead_seconds",
            "Wall time the router spends forwarding one request (includes "
            "the replica's own service time)",
        )
        self._m_hot_share = reg.gauge(
            "kakveda_fleet_hot_key_share",
            "Share of routed keyed traffic going to the single hottest key "
            "(hot-key skew indicator)",
        )
        self._m_scatter = reg.counter(
            "kakveda_fleet_scatter_total",
            "Scatter-gather merges by outcome (ok|partial|shed|unreachable)",
            ("outcome",),
        )
        self._m_promote = reg.counter(
            "kakveda_fleet_promotions_total",
            "Ownership-epoch bumps pushed after ejection/re-admission/"
            "membership change",
        )
        self._m_epoch = reg.gauge(
            "kakveda_fleet_ownership_epoch",
            "The router's current ownership epoch (0 = ownership off)",
        )
        if self.ownership is not None:
            self._m_epoch.set(float(self.ownership.epoch))

    # -- selection -------------------------------------------------------

    def ejected(self) -> List[str]:
        return [rid for rid, st in self._state.items() if st["ejected"]]

    def liveness(self) -> Dict[str, bool]:
        """Per-replica routability (healthy AND not ejected) — the same
        verdict broadcast_verdicts gossips; the autoscaler's dead-replica
        detection input."""
        return {
            rid: bool(st["healthy"]) and not st["ejected"]
            for rid, st in self._state.items()
        }

    def candidates(self, key: str, attempts: int) -> List[str]:
        """The owner + failover order for ``key``, ejected replicas
        skipped — unless that empties the list (all ejected), in which
        case trying beats failing outright.

        Under sharded ownership a keyed request may ONLY land on the
        key's holders — any other replica simply does not store the
        range — so the walk is the holder list, not the full ring.
        Ejected-owner fallback within it IS standby promotion for the
        data plane (the standby holds the range by R-way replication)."""
        if self.ownership is not None and key:
            pref = self.ownership.holders(key)[: max(1, attempts)]
        else:
            pref = self.ring.preference(key, limit=attempts)
        ejected = set(self.ejected())
        live = [r for r in pref if r not in ejected]
        return live or pref

    def note_key(self, key: str) -> None:
        if len(self._hot_keys) >= _HOT_KEYS_MAX and key not in self._hot_keys:
            return  # bounded: skew among the first 4096 keys is plenty
        self._hot_keys[key] = self._hot_keys.get(key, 0) + 1
        self._hot_total += 1
        self._m_hot_share.set(max(self._hot_keys.values()) / self._hot_total)

    # -- failure accounting ---------------------------------------------

    def note_result(self, rid: str, ok: bool) -> None:
        st = self._state.get(rid)
        if st is None:
            return  # removed by a concurrent scale-down mid-flight
        if ok:
            st["fails"] = 0
            return
        st["fails"] += 1
        if st["fails"] >= self.eject_fails and not st["ejected"]:
            st["ejected"] = True
            self._m_eject[rid].inc()
            self._m_healthy[rid].set(0.0)
            log.warning(
                "replica %s ejected after %d consecutive failures", rid, st["fails"]
            )
            if self.ownership is not None:
                # Standby promotion: the data plane flipped the moment the
                # owner left candidates(); the epoch bump + view push (next
                # probe tick) is what fences stale ring views fleet-wide.
                self._own_dirty = True

    # -- forwarding ------------------------------------------------------

    def _hop_headers(
        self, body: Optional[bytes], incoming: Optional[Mapping[str, str]]
    ) -> Dict[str, str]:
        """Base outgoing headers for one forward/scatter: Content-Type
        for bodies plus the PROPAGATED incoming request id — without it,
        replica logs cannot be joined to router logs even by request id."""
        out: Dict[str, str] = {}
        if body:
            out["Content-Type"] = "application/json"
        if incoming:
            rid = incoming.get(self._rid_header)
            if rid:
                out[self._rid_header] = rid
        return out

    def _with_hop_context(
        self,
        base: Dict[str, str],
        hop,
        incoming: Optional[Mapping[str, str]],
    ) -> Optional[Dict[str, str]]:
        """Stamp one attempt's trace context: the hop span's traceparent
        (the replica's server span parents under THIS attempt), falling
        back to the raw incoming header when tracing is inert."""
        hdrs = dict(base)
        tp = hop.traceparent() or (
            incoming.get(_trace.TRACEPARENT_HEADER, "") if incoming else ""
        )
        if tp:
            hdrs[_trace.TRACEPARENT_HEADER] = tp
        return hdrs or None

    async def forward(
        self,
        method: str,
        path: str,
        body: Optional[bytes],
        key: str,
        *,
        idempotent: bool,
        retry_connect_only: bool = False,
        headers: Optional[Mapping[str, str]] = None,
    ) -> web.Response:
        """Forward one request along ``key``'s candidate list. Transport
        failures (and 5xx on idempotent routes) walk to the next replica;
        HTTP verdicts — including 429 shed and 503 degraded — pass through
        untouched. The forward client is aiohttp (the platform's native
        HTTP stack): on a shared-core box its per-request cost is roughly
        half httpx's, which directly bounds router overhead."""
        import aiohttp

        attempts = 1 + (self.retries if (idempotent or retry_connect_only) else 0)
        cands = self.candidates(key, attempts)
        base_headers = self._hop_headers(body, headers)
        t0 = time.perf_counter()
        last_err: Optional[str] = None
        for i, rid in enumerate(cands):
            if i > 0:
                self._m_reroutes.inc()
            base = self.backends.get(rid)
            if base is None:
                # Removed by a concurrent scale-down between candidate
                # selection and dispatch — walk on, don't 500.
                last_err = f"{rid} removed"
                continue
            url = base + path
            # Each attempt is its own child span (replica + outcome
            # provenance); the hop's traceparent rides the sub-request so
            # the replica's server span parents under THIS attempt, not
            # under a retry that never reached it.
            hop = _trace.get_tracer().start_span(
                "router.forward", replica=rid, attempt=i, path=path
            )
            hdrs = self._with_hop_context(base_headers, hop, headers)
            try:
                _FAULT_FORWARD.fire()
                async with self._client.request(
                    method, url, data=body, headers=hdrs,
                ) as r:
                    content = await r.read()
                    status = r.status
                    ctype = r.headers.get("Content-Type", "application/json")
                    retry_after = r.headers.get("Retry-After")
            except (aiohttp.ClientError, asyncio.TimeoutError,
                    _faults.FaultInjected) as e:
                self.note_result(rid, False)
                self._m_fwd[rid]["error"].inc()
                last_err = f"{type(e).__name__}: {e}"
                hop.end("error", error=type(e).__name__)
                continue
            if status >= 500 and idempotent and i + 1 < len(cands):
                # A dying replica can serve 500s before its socket closes;
                # an idempotent read is safe to answer from the next one.
                self.note_result(rid, False)
                self._m_fwd[rid]["error"].inc()
                last_err = f"HTTP {status}"
                hop.end("error", status=status)
                continue
            hop.end(_hop_outcome(status), status=status)
            self.note_result(rid, status < 500)
            self._m_fwd[rid]["ok" if status < 500 else "passthrough"].inc()
            if key:
                self._m_load[rid].inc()
            self._m_overhead.observe(time.perf_counter() - t0)
            headers = {}
            if retry_after is not None:
                headers["Retry-After"] = retry_after
            return web.Response(
                body=content,
                status=status,
                content_type=ctype.split(";")[0],
                headers=headers,
            )
        self._m_overhead.observe(time.perf_counter() - t0)
        return web.json_response(
            {"ok": False, "error": f"no replica reachable ({last_err})"},
            status=502,
        )

    # -- scatter-gather (sharded ownership) ------------------------------

    async def scatter(
        self,
        path: str,
        body: Optional[bytes],
        merge,
        headers: Optional[Mapping[str, str]] = None,
    ) -> web.Response:
        """Fan one request out to every live shard and merge — the warn /
        match data plane under sharded ownership (each replica holds only
        its owned + standby ranges, so no single forward sees the corpus).

        Partial-result contract: a shard that is unreachable (or chaos:
        gfkb.scatter_gather) is recorded in ``shards`` provenance; the
        merged verdict carries ``partial=true`` IFF some ownership range
        has NO holder among the answering shards (exact arc accounting,
        fleet/ownership.py) — coverage is never silently dropped, and the
        gather is bounded by the per-request client timeout, never hangs.
        All-shed verdicts pass through typed as 429 + Retry-After."""
        import aiohttp

        view = self.ownership
        ejected = set(self.ejected())
        targets = [
            rid for rid in view.members
            if rid in self.backends and rid not in ejected
        ] or [rid for rid in view.members if rid in self.backends]
        base_headers = self._hop_headers(body, headers)
        t0 = time.perf_counter()

        async def one(rid: str):
            # One child span per shard sub-request — the assembled tree
            # shows every shard's replica + outcome, including the ones
            # the merge never used.
            hop = _trace.get_tracer().start_span(
                "router.scatter", replica=rid, path=path
            )
            hdrs = self._with_hop_context(base_headers, hop, headers)
            try:
                _FAULT_SCATTER.fire()
                async with self._client.request(
                    "POST", self.backends[rid] + path, data=body, headers=hdrs
                ) as r:
                    content = await r.read()
                    hop.end(_hop_outcome(r.status), status=r.status)
                    return rid, r.status, content, r.headers.get("Retry-After")
            except (aiohttp.ClientError, asyncio.TimeoutError,
                    _faults.FaultInjected) as e:
                hop.end("error", error=type(e).__name__)
                return rid, None, None, None

        results = await asyncio.gather(*(one(rid) for rid in targets))
        answered: Dict[str, dict] = {}
        shards: Dict[str, str] = {}
        sheds: List[Optional[str]] = []
        for rid, status, content, retry_after in results:
            if status is None:
                self.note_result(rid, False)
                self._m_fwd[rid]["error"].inc()
                shards[rid] = "unreachable"
                continue
            self.note_result(rid, status < 500)
            if status == 200:
                try:
                    parsed = json.loads(content)
                except ValueError:
                    self._m_fwd[rid]["error"].inc()
                    shards[rid] = "bad_body"
                    continue
                self._m_fwd[rid]["ok"].inc()
                answered[rid] = parsed
                shards[rid] = "ok"
            elif status in (429, 503):
                self._m_fwd[rid]["passthrough"].inc()
                shards[rid] = "shed" if status == 429 else "degraded_unavailable"
                sheds.append(retry_after)
            else:
                self._m_fwd[rid]["error"].inc()
                shards[rid] = f"http_{status}"
        self._m_overhead.observe(time.perf_counter() - t0)
        if not answered:
            if sheds:
                # Uniform backpressure: keep the shed typed end-to-end.
                self._m_scatter.labels(outcome="shed").inc()
                ra = max((int(float(x)) for x in sheds if x), default=1)
                return web.json_response(
                    {"ok": False, "error": "all shards shed or unreachable",
                     "shards": shards, "retry_after": ra},
                    status=429, headers={"Retry-After": str(max(1, ra))},
                )
            self._m_scatter.labels(outcome="unreachable").inc()
            return web.json_response(
                {"ok": False, "error": "no shard reachable", "shards": shards},
                status=502,
            )
        holes = view.coverage_holes(answered.keys())
        merged = merge(answered)
        merged["shards"] = shards
        merged["partial"] = holes > 0
        if holes:
            merged["uncovered_ranges"] = holes
        self._m_scatter.labels(outcome="partial" if holes else "ok").inc()
        return web.json_response(merged)

    # -- ownership epoch (promotion / rebalance) -------------------------

    def set_ownership(self, view) -> None:
        """Swap in a new ownership view (rebalance flip) — one reference
        write; in-flight scatters finish on the view they captured."""
        self.ownership = view
        self._m_epoch.set(float(view.epoch))

    async def push_ownership(self, *, bump: bool = True) -> bool:
        """Bump the epoch (promotion: ejection / re-admission changed who
        serves which ranges) and push the view to every live member.
        Failure — including chaos fleet.promote — leaves the dirty flag
        set; the probe loop retries next tick. Routing never waits for
        this: candidates() already fails over, the push only fences."""
        import aiohttp

        if self.ownership is None:
            return True
        try:
            _FAULT_PROMOTE.fire()
        except _faults.FaultInjected as e:
            log.warning("ownership push deferred (chaos): %s", e)
            return False
        if bump:
            self.set_ownership(self.ownership.with_epoch(self.ownership.epoch + 1))
            self._m_promote.inc()
        body = json.dumps(self.ownership.to_dict()).encode("utf-8")
        ok = True
        for rid in list(self.ownership.members):
            st = self._state.get(rid)
            if st is None or st["ejected"]:
                continue  # re-admission push happens on probe recovery
            try:
                async with self._client.post(
                    self.backends[rid] + "/fleet/ownership", data=body,
                    headers={"Content-Type": "application/json"},
                ) as r:
                    if r.status >= 500:
                        ok = False
            except (aiohttp.ClientError, asyncio.TimeoutError):
                ok = False
        if ok:
            self._own_dirty = False
        return ok

    async def rebalance_to(self, members: Dict[str, str]) -> dict:
        """Drive the range-migration protocol to an explicit target
        membership — THE membership-change epoch write path. Both the
        POST /fleet/rebalance handler and the autoscaler go through
        here, so the router stays the single epoch writer (the
        autoscaler requests; run_rebalance's flip push commits, and any
        residual promotion retries ride the probe loop's dirty flag).
        Raises :class:`~kakveda_tpu.fleet.ownership.MigrationError` with
        ``flipped`` provenance; flipped=False means the old view still
        rules everywhere and a full retry is safe."""
        from kakveda_tpu.fleet import ownership as _own

        if self.ownership is None:
            raise RuntimeError("ownership disabled")
        old = self.ownership
        new = old.with_members(dict(members))
        # Migration traces against the epochs that fence it: a failed
        # migration's span (error outcome, flipped provenance in the
        # raised MigrationError) correlates with every replicate_apply
        # span fenced at epoch_to.
        with _trace.get_tracer().start_span(
            "fleet.rebalance", epoch_from=old.epoch, epoch_to=new.epoch,
            members=len(new.members),
        ):
            summary = await asyncio.get_running_loop().run_in_executor(
                None, lambda: _own.run_rebalance(old, new)
            )
            for rid, url in new.members.items():
                self.add_backend(rid, url)
            for rid in [r for r in self.backends if r not in new.members]:
                self.remove_backend(rid)
            self.set_ownership(new)
            self._m_promote.inc()
            return summary

    async def resync_member(self, rid: str) -> dict:
        """Heal a replaced member's GFKB gap: snapshot-ship its held
        (owned + standby) arcs back from the surviving holders through
        the SAME migration protocol — ``run_rebalance`` from the view
        WITHOUT the member (same epoch, export basis only; never pushed)
        to the full view at epoch+1 ships exactly the arcs whose holder
        set regains the member, then drains the watermark delta.
        Row-idempotent by construction: deterministic ``mig-*`` event ids
        plus signature-keyed upserts mean re-shipped rows the member
        already holds update in place, never duplicate."""
        from kakveda_tpu.fleet import ownership as _own

        view = self.ownership
        if view is None or rid not in view.members:
            return {}
        donors = {r: u for r, u in view.members.items() if r != rid}
        if not donors:
            return {}
        old = view.with_members(donors, epoch=view.epoch)
        new = view.with_epoch(view.epoch + 1)
        with _trace.get_tracer().start_span(
            "fleet.resync", replica=rid,
            epoch_from=view.epoch, epoch_to=new.epoch,
        ):
            summary = await asyncio.get_running_loop().run_in_executor(
                None, lambda: _own.run_rebalance(old, new)
            )
            self.set_ownership(new)
            self._m_promote.inc()
            return summary

    def add_backend(self, rid: str, url: str) -> None:
        """Grow the routable fleet at runtime (scale-out): extend the
        backend map + ring and mint the per-replica metric children the
        constructor resolves once. The probe loop picks the newcomer up on
        its next pass (the due map self-heals)."""
        url = url.rstrip("/")
        if rid in self.backends:
            self.backends[rid] = url
            return
        self.backends[rid] = url
        self.ring = HashRing(list(self.backends), vnodes=self.ring.vnodes)
        self._state[rid] = {
            "fails": 0, "ejected": False, "healthy": None, "ready": None
        }
        reg = _metrics.get_registry()
        fwd = reg.counter(
            "kakveda_fleet_forwards_total",
            "Router forwards by replica and outcome (ok|error|passthrough)",
            ("replica", "outcome"),
        )
        self._m_fwd[rid] = {
            o: fwd.labels(replica=rid, outcome=o)
            for o in ("ok", "error", "passthrough")
        }
        ej = reg.counter(
            "kakveda_fleet_ejections_total",
            "Replica ejections after consecutive forward/probe failures",
            ("replica",),
        )
        self._m_eject[rid] = ej.labels(replica=rid)
        g_healthy = reg.gauge(
            "kakveda_fleet_replica_healthy",
            "1 while a replica answers probes and is not ejected", ("replica",),
        )
        self._m_healthy[rid] = g_healthy.labels(replica=rid)
        load = reg.counter(
            "kakveda_fleet_shard_load_total",
            "Key-routed requests per replica (shard balance)", ("replica",),
        )
        self._m_load[rid] = load.labels(replica=rid)

    def remove_backend(self, rid: str) -> None:
        """Shrink the routable fleet at runtime (lossless scale-down
        epilogue — the victim's arcs were already migrated away). A
        DELIBERATE membership change, unlike ejection, which never
        touches ring membership. Metric children stay minted (their
        counters keep their history); the probe loop prunes its due map."""
        if rid not in self.backends:
            return
        del self.backends[rid]
        self._state.pop(rid, None)
        self.ring = HashRing(list(self.backends), vnodes=self.ring.vnodes)
        m = self._m_healthy.get(rid)
        if m is not None:
            m.set(0.0)

    # -- probe-verdict broadcast (one liveness world-view) ---------------

    async def broadcast_verdicts(self) -> None:
        """Fold the router's probe/ejection liveness into every replica's
        FleetView as a synthetic gossip sample (sender ``__router__``).
        Ejection and the gossip pressure floor then share ONE liveness
        opinion: a peer the router marks dead stops pinning survivors'
        brownout ladders before its stale sample's TTL runs out.
        Best-effort — the TTL discipline covers missed broadcasts."""
        import aiohttp

        self._verdict_seq += 1
        sample = {
            "replica": "__router__",
            "seq": self._verdict_seq,
            "ts": time.time(),
            "occupancy": 0.0,
            "probe_verdicts": self.liveness(),
        }
        # The router's own view folds the verdicts too, so its
        # fleet_pressure() skips dead peers exactly like a replica's.
        self.fleet_view.fold(sample)
        body = json.dumps(sample).encode("utf-8")
        for rid, st in list(self._state.items()):
            if not st["healthy"]:
                continue
            try:
                async with self._client.post(
                    self.backends[rid] + "/fleet/gossip", data=body,
                    headers={"Content-Type": "application/json"},
                    timeout=aiohttp.ClientTimeout(total=min(2.0, self.timeout_s)),
                ) as r:
                    await r.read()
            except (aiohttp.ClientError, asyncio.TimeoutError):
                pass

    # -- probing ---------------------------------------------------------

    async def probe_replica(self, rid: str) -> None:
        import aiohttp

        url = self.backends[rid]
        st = self._state[rid]
        try:
            async with self._client.get(
                url + "/readyz",
                timeout=aiohttp.ClientTimeout(total=min(2.0, self.timeout_s)),
            ) as r:
                if r.status != 200:
                    raise ValueError(f"readyz HTTP {r.status}")
                st["ready"] = await r.json()
            self._probe_fold_seq += 1
            self.fleet_view.fold(
                sample_from_ready(rid, self._probe_fold_seq, st["ready"])
            )
            st["healthy"] = True
            st["fails"] = 0
            if st["ejected"]:
                st["ejected"] = False
                log.warning("replica %s re-admitted (probe ok)", rid)
                if self.ownership is not None:
                    self._own_dirty = True  # owner takes its ranges back
            self._m_healthy[rid].set(1.0)
        except (aiohttp.ClientError, asyncio.TimeoutError, ValueError) as e:
            st["healthy"] = False
            self._m_healthy[rid].set(0.0)
            self.note_result(rid, False)
            st["ready"] = None
            log.debug("probe %s failed: %s", rid, e)

    async def probe_once(self) -> None:
        """Probe every replica back-to-back — startup (the router must not
        route before it knows who is alive) and tests. The steady-state
        loop never does this: see probe_loop."""
        for rid in self.backends:
            await self.probe_replica(rid)

    def probe_phase(self, rid: str) -> float:
        """Deterministic per-replica probe phase in [0, interval): blake2b
        of the replica id, the hash ring's derivation discipline (never
        salted ``hash()``), so the stagger is stable across router
        restarts and identical on every router instance."""
        import hashlib

        h = int.from_bytes(
            hashlib.blake2b(rid.encode(), digest_size=4).digest(), "big"
        )
        return self.probe_interval_s * ((h % 9973) / 9973.0)

    async def probe_loop(self) -> None:
        """Phase-jittered health probing: every replica is still probed
        once per ``probe_interval_s``, but on its own deterministic phase
        offset instead of one synchronized tick. Back-to-back probing
        meant N /readyz bursts landing on the fleet simultaneously every
        interval — at small intervals the burst itself becomes load, and a
        transient stall (GC pause, snapshot fsync) hitting the shared tick
        could fail several replicas' probes at once and eject half the
        ring in one beat. Staggered, each replica's probe samples a
        different instant."""
        due = {
            rid: time.monotonic() + self.probe_phase(rid)
            for rid in self.backends
        }
        last_broadcast = 0.0
        while True:
            for rid in self.backends:  # add_backend: newcomers self-heal in
                due.setdefault(rid, time.monotonic() + self.probe_phase(rid))
            for rid in [r for r in due if r not in self.backends]:
                del due[rid]  # remove_backend (scale-down) prunes out
            rid = min(due, key=due.get)
            delay = due[rid] - time.monotonic()
            if delay > 0:
                await asyncio.sleep(delay)
            try:
                await self.probe_replica(rid)
                now = time.monotonic()
                if now - last_broadcast >= self.probe_interval_s:
                    last_broadcast = now
                    await self.broadcast_verdicts()
                if self.ownership is not None and self._own_dirty:
                    await self.push_ownership()
            except asyncio.CancelledError:
                raise
            except Exception as e:  # noqa: BLE001 — probe must never die
                log.warning("probe loop error: %s: %s", type(e).__name__, e)
            due[rid] = time.monotonic() + self.probe_interval_s

    # -- fleet report ----------------------------------------------------

    def report(self) -> dict:
        """Per-replica health + fleet admission mode — the router /readyz
        body (and what `cli doctor` prints for a running fleet)."""
        replicas = {}
        worst = {"state": "normal", "step": 0}
        degraded_any = False
        for rid, st in self._state.items():
            ready = st["ready"] or {}
            adm = ready.get("admission") or {}
            step = int(adm.get("brownout_step", 0) or 0)
            if st["healthy"] and step > worst["step"]:
                worst = {"state": adm.get("brownout", "?"), "step": step}
            dev = ready.get("device") or {}
            degraded_any = degraded_any or bool(dev.get("degraded"))
            replicas[rid] = {
                "url": self.backends[rid],
                "healthy": st["healthy"],
                "ejected": st["ejected"],
                "gfkb_count": ready.get("gfkb_count"),
                "brownout": adm.get("brownout"),
                "degraded": bool(dev.get("degraded")),
            }
        healthy = [r for r in replicas.values() if r["healthy"]]
        out = {
            "ok": bool(healthy),
            "replicas": replicas,
            "fleet": {
                "size": len(replicas),
                "healthy": len(healthy),
                "brownout": worst["state"],
                "degraded_any": degraded_any,
            },
        }
        if self.ownership is not None:
            view = self.ownership
            live = [
                rid for rid, st in self._state.items()
                if st["healthy"] and not st["ejected"]
            ]
            out["ownership"] = {
                "enabled": True,
                "epoch": view.epoch,
                "replication": view.replication,
                "members": list(view.members),
                "coverage_holes": view.coverage_holes(live),
            }
        if self.autoscaler is not None:
            out["autoscale"] = self.autoscaler.info()
        return out


def _merge_warn(answered: Dict[str, dict]) -> dict:
    """Top-k merge of per-shard /warn verdicts. Each shard answered from
    its owned+standby slice of the corpus; the global top-k is exactly the
    k best of the union of per-shard top-ks (scores are absolute cosine
    similarities — shard-independent), so the merge preserves single-node
    parity for every rank the shards cover. References gain ``shard``
    provenance; the winning verdict body comes from the shard holding the
    best merged reference (its policy decision saw that evidence)."""
    refs = []
    for rid, body in answered.items():
        for ref in body.get("references") or []:
            if isinstance(ref, dict):
                refs.append({**ref, "shard": rid})
    refs.sort(key=lambda r: -float(r.get("score", 0.0)))
    k = max((len(b.get("references") or []) for b in answered.values()), default=0)
    top = refs[: max(k, 1)] if refs else []
    if top:
        win = answered[top[0]["shard"]]
    else:  # no shard matched anything: keep the most confident verdict
        win = max(
            answered.values(),
            key=lambda b: float(b.get("confidence", 0.0) or 0.0),
        )
    out = dict(win)
    out["references"] = top
    out["degraded"] = any(bool(b.get("degraded")) for b in answered.values())
    return out


def _merge_matches(answered: Dict[str, dict]) -> dict:
    """Top-k merge of per-shard /failures/match candidate lists (same
    absolute-score argument as :func:`_merge_warn`)."""
    matches = []
    for rid, body in answered.items():
        for m in body.get("matches") or []:
            if isinstance(m, dict):
                matches.append({**m, "shard": rid})
    matches.sort(key=lambda m: -float(m.get("score", 0.0)))
    k = max((len(b.get("matches") or []) for b in answered.values()), default=0)
    out = dict(next(iter(answered.values())))
    out["matches"] = matches[: max(k, 1)] if matches else []
    return out


def _hop_outcome(status: Optional[int]) -> str:
    """Span outcome for one hop's HTTP verdict — mirrors the admission
    taxonomy: 429 is a shed, 503 a degraded verdict, other 5xx an error."""
    if status is None or status >= 500 and status != 503:
        return "error"
    if status == 429:
        return "shed"
    if status == 503:
        return "degraded"
    return "ok"


def _route_key(path: str, body: Optional[bytes]) -> str:
    """The shard key for a request: app_id when the body carries one,
    signature_text for raw match calls, first trace's app for batches.
    Unparseable bodies route by empty key (stable arbitrary owner)."""
    if not body:
        return ""
    try:
        obj = json.loads(body)
    except ValueError:
        return ""
    if not isinstance(obj, dict):
        return ""
    if isinstance(obj.get("app_id"), str):
        return obj["app_id"]
    tr = obj.get("trace")
    if isinstance(tr, dict) and isinstance(tr.get("app_id"), str):
        return tr["app_id"]
    trs = obj.get("traces")
    if isinstance(trs, list) and trs and isinstance(trs[0], dict):
        aid = trs[0].get("app_id")
        if isinstance(aid, str):
            return aid
    sig = obj.get("signature_text")
    if isinstance(sig, str):
        return sig
    return ""


def make_router_app(
    backends: Dict[str, str],
    *,
    supervisor=None,
    autoscale=None,
    **router_kw,
) -> web.Application:
    """Build the front-router app over ``{replica_id: base_url}``.

    ``supervisor`` (optional, a :class:`fleet.supervisor.FleetSupervisor`)
    enables the supervise loop: dead replica processes are restarted up to
    ``KAKVEDA_FLEET_RESTARTS`` times each (default 0 — route around only).

    ``autoscale=(min, max)`` (requires ``supervisor``) mounts the elastic
    :class:`fleet.autoscaler.Autoscaler` policy loop instead — replacement
    of dead replicas subsumes the supervise loop's restart duty, so the
    two are never mounted together (a double-start race on the same
    replica index otherwise).

    ``KAKVEDA_FLEET_OWNERSHIP=1`` (or an ``ownership=`` OwnershipView kw)
    turns on sharded ownership: warn/match become scatter-gather merges,
    ejection/re-admission drive epoch-bumped ownership pushes, and
    ``POST /fleet/rebalance`` runs the range-migration protocol."""
    if "ownership" not in router_kw and os.environ.get(
        "KAKVEDA_FLEET_OWNERSHIP", "0"
    ) == "1":
        from kakveda_tpu.fleet.ownership import OwnershipView

        router_kw["ownership"] = OwnershipView(
            dict(backends),
            replication=_env_int("KAKVEDA_FLEET_REPLICATION", 2),
            vnodes=_env_int("KAKVEDA_FLEET_VNODES", 64),
        )
    router = Router(backends, **router_kw)

    @web.middleware
    async def _trace_mw(request: web.Request, handler):
        """Router-side trace root: extract the caller's W3C context or
        start a new trace folding the request id (same discipline as the
        service middleware, service/app.py) — hop spans under it carry
        per-replica, per-attempt outcome provenance."""
        rid = ensure_request_id(request.headers.get(router._rid_header))
        span = _trace.get_tracer().start_span(
            "router.request",
            traceparent=request.headers.get(_trace.TRACEPARENT_HEADER),
            trace_id=rid, path=request.path, method=request.method, rid=rid,
        )
        span.activate()
        try:
            response = await handler(request)
        except web.HTTPException as e:
            span.deactivate()
            span.end(_hop_outcome(e.status), status=e.status)
            e.headers.setdefault(router._rid_header, rid)
            raise
        except BaseException:
            span.deactivate()
            span.end("error")
            raise
        span.deactivate()
        span.end(_hop_outcome(response.status), status=response.status)
        response.headers.setdefault(router._rid_header, rid)
        return response

    app = web.Application(middlewares=[_trace_mw])
    app[ROUTER_KEY] = router

    async def _startup(app):
        import aiohttp

        router._client = aiohttp.ClientSession(
            timeout=aiohttp.ClientTimeout(total=router.timeout_s),
            connector=aiohttp.TCPConnector(limit=256),
        )
        await router.probe_once()
        app[_PROBE_TASK_KEY] = asyncio.get_running_loop().create_task(
            router.probe_loop()
        )
        if autoscale is not None and supervisor is not None:
            from kakveda_tpu.fleet.autoscaler import Autoscaler

            mn, mx = autoscale
            scaler = Autoscaler(
                router, supervisor, min_replicas=int(mn), max_replicas=int(mx)
            )
            router.autoscaler = scaler
            app[AUTOSCALER_KEY] = scaler
            app[_AUTOSCALE_TASK_KEY] = asyncio.get_running_loop().create_task(
                scaler.run()
            )
        elif supervisor is not None:
            app[_SUPERVISE_TASK_KEY] = asyncio.get_running_loop().create_task(
                _supervise_loop(router, supervisor)
            )

    async def _cleanup(app):
        for key in (_PROBE_TASK_KEY, _SUPERVISE_TASK_KEY, _AUTOSCALE_TASK_KEY):
            t = app.get(key)
            if t is not None:
                t.cancel()
                try:
                    await t
                except asyncio.CancelledError:
                    pass
        if router._client is not None:
            await router._client.close()

    app.on_startup.append(_startup)
    app.on_cleanup.append(_cleanup)

    def _keyed(idempotent: bool, retry_connect_only: bool = False):
        async def handler(request: web.Request):
            body = await request.read()
            key = _route_key(request.path, body)
            if key:
                router.note_key(key)
            return await router.forward(
                request.method, request.path, body or None, key,
                idempotent=idempotent, retry_connect_only=retry_connect_only,
                headers=request.headers,
            )

        return handler

    async def healthz(request):
        return web.json_response({"ok": True, "role": "router"})

    async def readyz(request):
        rep = router.report()
        return web.json_response(rep, status=200 if rep["ok"] else 503)

    async def metrics_ep(request):
        return web.Response(
            body=_metrics.get_registry().render().encode("utf-8"),
            headers={"Content-Type": _metrics.PROMETHEUS_CONTENT_TYPE},
        )

    async def metrics_fleet(request):
        """GET /metrics/fleet — ONE scrape for the whole fleet: every
        replica's exposition plus the router's own, counters/histograms
        summed, gauges tagged per replica (core/metrics.py
        federate_renders). A replica that cannot answer is skipped — a
        partial fleet scrape beats a failed one."""
        import aiohttp

        texts = {"__router__": _metrics.get_registry().render()}

        async def pull(rid: str, base: str):
            try:
                async with router._client.get(base + "/metrics") as r:
                    if r.status == 200:
                        texts[rid] = (await r.read()).decode("utf-8", "replace")
            except (aiohttp.ClientError, asyncio.TimeoutError):
                pass

        await asyncio.gather(
            *(pull(rid, base) for rid, base in list(router.backends.items()))
        )
        return web.Response(
            body=_metrics.federate_renders(texts).encode("utf-8"),
            headers={"Content-Type": _metrics.PROMETHEUS_CONTENT_TYPE},
        )

    async def trace_ring(request):
        tr = _trace.get_tracer()
        try:
            limit = int(request.query["n"]) if "n" in request.query else None
        except ValueError:
            limit = None
        return web.json_response(
            {"plane": tr.plane(), "spans": tr.dump(limit=limit)}
        )

    async def trace_collect(request):
        """GET /trace/{id} — the cross-process collector: the router's
        own ring plus every replica's ``/trace/{id}``, deduped by span id
        and scatter-assembled into one rendered tree. Per-source span
        counts ride along (-1 = replica unreachable) so a hole in the
        tree is attributable."""
        import aiohttp

        tid = request.match_info["trace_id"]
        spans = {s["span_id"]: s for s in _trace.get_tracer().dump(tid)}
        sources = {"__router__": len(spans)}

        async def pull(rid: str, base: str):
            try:
                async with router._client.get(base + "/trace/" + tid) as r:
                    if r.status != 200:
                        sources[rid] = -1
                        return
                    body = json.loads(await r.read())
            except (aiohttp.ClientError, asyncio.TimeoutError, ValueError):
                sources[rid] = -1
                return
            n = 0
            for s in body.get("spans") or []:
                sid = s.get("span_id")
                if sid and sid not in spans:
                    spans[sid] = s
                    n += 1
            sources[rid] = n

        await asyncio.gather(
            *(pull(rid, base) for rid, base in list(router.backends.items()))
        )
        ordered = sorted(
            spans.values(), key=lambda s: (s.get("ts") or 0.0, s.get("span_id"))
        )
        return web.json_response({
            "trace_id": tid,
            "spans": ordered,
            "sources": sources,
            "tree": _trace.render_trace(ordered) if ordered else "",
        })

    warm = _keyed(idempotent=True)
    ingest = _keyed(idempotent=False, retry_connect_only=True)
    admin = _keyed(idempotent=False)
    reads = _keyed(idempotent=True)

    def _scattered(merge):
        """Ownership on: warn/match must see every owned range, so they
        fan out and merge instead of forwarding to one replica (which
        only holds its own slice of the corpus)."""
        async def handler(request: web.Request):
            body = await request.read()
            key = _route_key(request.path, body)
            if key:
                router.note_key(key)
            return await router.scatter(
                request.path, body or None, merge, headers=request.headers
            )

        return handler

    if router.ownership is not None:
        warn_handler = _scattered(_merge_warn)
        match_handler = _scattered(_merge_matches)
    else:
        warn_handler = warm
        match_handler = warm

    async def rebalance(request: web.Request):
        """POST /fleet/rebalance — the range-migration protocol driver
        (fleet/ownership.py run_rebalance): snapshot-ship → flip → drain.
        Body: {"add": {"id": rid, "url": url}} to scale out by one, or
        {"members": {rid: url, ...}} for an explicit target membership
        (scale-in drops replicas). 409 with ``flipped`` provenance on a
        failed migration — flipped=false means the old view still rules
        everywhere and a retry is safe."""
        if router.ownership is None:
            return web.json_response(
                {"ok": False, "error": "ownership disabled"}, status=409
            )
        try:
            obj = json.loads(await request.read())
            if not isinstance(obj, dict):
                raise ValueError("body must be an object")
            members = dict(router.ownership.members)
            if isinstance(obj.get("members"), dict):
                members = {str(k): str(v) for k, v in obj["members"].items()}
            add = obj.get("add")
            if isinstance(add, dict):
                members[str(add["id"])] = str(add["url"])
            if not members:
                raise ValueError("empty membership")
        except (ValueError, KeyError, TypeError) as e:
            return web.json_response({"ok": False, "error": str(e)}, status=422)
        from kakveda_tpu.fleet import ownership as _own

        try:
            summary = await router.rebalance_to(members)
        except _own.MigrationError as e:
            return web.json_response(
                {"ok": False, "error": str(e), "flipped": e.flipped}, status=409
            )
        return web.json_response({"ok": True, **summary})

    app.add_routes(
        [
            web.get("/healthz", healthz),
            web.get("/readyz", readyz),
            web.get("/metrics", metrics_ep),
            web.get("/metrics/fleet", metrics_fleet),
            web.get("/trace", trace_ring),
            web.get("/trace/{trace_id}", trace_collect),
            web.post("/fleet/rebalance", rebalance),
            # Sharded, idempotent: retry-on-next-replica. Under ownership
            # these scatter-gather across owning shards instead.
            web.post("/warn", warn_handler),
            web.post("/failures/match", match_handler),
            # Sharded ingest: retried only when the connect itself failed.
            web.post("/ingest", ingest),
            web.post("/ingest/batch", ingest),
            # Reads: any healthy replica (replicated GFKB), retryable.
            web.get("/failures", reads),
            web.get("/patterns", reads),
            web.get("/topics", reads),
            web.get("/health/{app_id}", reads),
            # Admin mutations: single attempt, owner-routed.
            web.post("/failures/upsert", admin),
            web.post("/patterns/upsert", admin),
            web.post("/patterns/mine", admin),
            web.post("/snapshot", admin),
            web.post("/subscribe", admin),
            web.post("/unsubscribe", admin),
            web.post("/publish", admin),
        ]
    )
    return app


async def _supervise_loop(router: Router, supervisor) -> None:
    """Restart dead replica processes within the KAKVEDA_FLEET_RESTARTS
    budget (per replica). Routing already survives the gap (ejection +
    retry-on-next); this closes the loop for unattended fleets."""
    budget = _env_int("KAKVEDA_FLEET_RESTARTS", 0)
    restarts: Dict[int, int] = {}
    while True:
        await asyncio.sleep(max(0.5, router.probe_interval_s))
        try:
            for idx in supervisor.poll_dead():
                used = restarts.get(idx, 0)
                if used >= budget:
                    continue
                restarts[idx] = used + 1
                log.warning(
                    "replica %d died; restarting (%d/%d)", idx, used + 1, budget
                )
                await asyncio.get_running_loop().run_in_executor(
                    None, supervisor.start, idx
                )
        except asyncio.CancelledError:
            raise
        except Exception as e:  # noqa: BLE001 — supervision must never die
            log.warning("supervise loop error: %s: %s", type(e).__name__, e)
