"""Replica process lifecycle — spawn, watch, tear down.

``cli up --replicas N --port-base P`` (and the fleet bench / chaos drill)
drive fleets through this one class so the file conventions stay uniform
with the single-process server (cli/main.py):

    <root>/replica-<i>.pid     child pid (written by the child itself,
                               like server.pid)
    <root>/replica-<i>.log     child stdout/stderr
    <root>/data/replica-<i>/   the child's private data_dir (per-host
                               GFKB data-dir invariant — replicas must
                               never share an append log)
    <root>/fleet.json          manifest {router_port, replicas:[{id,url,…}]}
                               read by `cli doctor` / `cli status`

Each child is a plain single-process server (``cli up --replica-index i``)
with its fleet identity in env: ``KAKVEDA_REPLICA_ID``,
``KAKVEDA_FLEET_SELF``, ``KAKVEDA_FLEET_PEERS`` — the service app wires
gossip + replication from those (service/app.py).

Teardown is SIGTERM + bounded wait, THEN a bounded SIGKILL escalation
(``KAKVEDA_FLEET_STOP_KILL_S``) — but never on a replica that may hold a
real TPU lease (CLAUDE.md: a killed lease holder wedges the device for
hours). Lease detection is conservative: ``KAKVEDA_FLEET_TPU_LEASE=1``
forces the marker on, and absent an explicit non-TPU platform pin in the
child env the lease is ASSUMED — only cpu-pinned children (bench/test
fleets) are safe to escalate.
"""

from __future__ import annotations

import json
import logging
import os
import signal
import socket
import subprocess
import sys
import time
from pathlib import Path
from typing import Dict, Iterable, List, Optional

log = logging.getLogger("kakveda.fleet")


def pick_port_base(n: int, host: str = "127.0.0.1") -> int:
    """Find a base port with ``n`` consecutive free ports — bench/tests
    allocate fleets on ephemeral ranges without clashing."""
    for _ in range(64):
        with socket.socket() as s:
            s.bind((host, 0))
            base = s.getsockname()[1]
        if base + n >= 65535:
            continue
        ok = True
        for p in range(base, base + n):
            with socket.socket() as s:
                try:
                    s.bind((host, p))
                except OSError:
                    ok = False
                    break
        if ok:
            return base
    raise RuntimeError("could not find a free consecutive port range")


class FleetSupervisor:
    """Spawn/supervise/tear down N replica processes under one root."""

    def __init__(
        self,
        root: str | Path,
        *,
        host: str = "127.0.0.1",
        port_base: int,
        replicas: int,
        env: Optional[Dict[str, str]] = None,
        router_port: Optional[int] = None,
    ):
        self.root = Path(root)
        self.host = host
        self.port_base = int(port_base)
        self.n = int(replicas)
        self.extra_env = dict(env or {})
        self.router_port = router_port
        self.procs: Dict[int, subprocess.Popen] = {}
        # Indices drained away by the autoscaler: excluded from the
        # active fleet (backend_map/poll/manifest) and recycled first by
        # add_replica so ports and ring positions stay bounded.
        self.retired: set = set()
        # (min, max) when the fleet runs under an autoscaler — stamped
        # into the manifest so status/doctor know to report scale state.
        self.autoscale: Optional[tuple] = None
        self.root.mkdir(parents=True, exist_ok=True)

    # -- identity --------------------------------------------------------

    def replica_id(self, i: int) -> str:
        return f"r{i}"

    def url(self, i: int) -> str:
        return f"http://{self.host}:{self.port_base + i}"

    def active_indices(self) -> List[int]:
        """Spawned-slot indices minus the retired ones — the fleet."""
        return [i for i in range(self.n) if i not in self.retired]

    def urls(self) -> List[str]:
        return [self.url(i) for i in self.active_indices()]

    def backend_map(self) -> Dict[str, str]:
        """{replica_id: url} — what make_router_app consumes."""
        return {self.replica_id(i): self.url(i) for i in self.active_indices()}

    def pid_file(self, i: int) -> Path:
        return self.root / f"replica-{i}.pid"

    def log_file(self, i: int) -> Path:
        return self.root / f"replica-{i}.log"

    def data_dir(self, i: int) -> Path:
        return self.root / "data" / f"replica-{i}"

    # -- spawn -----------------------------------------------------------

    def _child_env(self, i: int) -> Dict[str, str]:
        env = dict(os.environ)
        # Never override PYTHONPATH bare (CLAUDE.md): prepend the repo root
        # this package was imported from, keep whatever else is there.
        import kakveda_tpu

        repo = str(Path(kakveda_tpu.__file__).resolve().parents[1])
        parts = [p for p in env.get("PYTHONPATH", "").split(os.pathsep) if p]
        if repo not in parts:
            parts.append(repo)
        env["PYTHONPATH"] = os.pathsep.join(parts)
        active = self.active_indices()
        peers = [self.url(j) for j in active if j != i]
        env.update(
            KAKVEDA_REPLICA_ID=self.replica_id(i),
            KAKVEDA_FLEET_SELF=self.url(i),
            KAKVEDA_FLEET_PEERS=",".join(peers),
            # Seed membership for sharded ownership (fleet/ownership.py);
            # inert unless the child also gets KAKVEDA_FLEET_OWNERSHIP=1
            # (usually via extra_env below). Children spawned later by
            # add_replica see the grown membership; earlier children learn
            # it from the epoch'd /fleet/ownership push instead.
            KAKVEDA_FLEET_MEMBERS=",".join(
                f"{self.replica_id(j)}={self.url(j)}" for j in active
            ),
        )
        env.update(self.extra_env)
        return env

    def start(self, i: int) -> subprocess.Popen:
        """Spawn replica ``i`` detached-ish (own session so a router
        SIGINT doesn't tear the fleet down un-supervised)."""
        cmd = [
            sys.executable, "-m", "kakveda_tpu.cli", "up",
            "--dir", str(self.root),
            "--host", self.host,
            "--port", str(self.port_base + i),
            "--dashboard-port", "0",
            "--replica-index", str(i),
        ]
        self.data_dir(i).mkdir(parents=True, exist_ok=True)
        logf = open(self.log_file(i), "ab")
        proc = subprocess.Popen(
            cmd, stdout=logf, stderr=subprocess.STDOUT,
            env=self._child_env(i), start_new_session=True,
        )
        logf.close()
        self.procs[i] = proc
        return proc

    def start_all(self) -> None:
        for i in self.active_indices():
            self.start(i)
        self.write_manifest()

    def add_replica(self) -> int:
        """Scale out by one: recycle the lowest retired slot (its port
        and ring position come back) or spawn replica ``n`` on the next
        port, then refresh the manifest. The caller (router
        /fleet/rebalance, autoscaler, bench, drill) still owns the range
        migration — this only creates the process. Returns the index."""
        if self.retired:
            i = min(self.retired)
            self.retired.discard(i)
        else:
            i = self.n
            self.n = i + 1
        self.start(i)
        self.write_manifest()
        return i

    def retire(self, i: int) -> None:
        """Drop a (stopped) replica from the active fleet — the
        autoscaler's scale-down epilogue. The slot recycles via
        add_replica; the data dir stays (its rows were migrated away,
        logs keep their forensic value)."""
        self.retired.add(i)
        self.procs.pop(i, None)
        self.pid_file(i).unlink(missing_ok=True)
        self.write_manifest()

    # -- watch -----------------------------------------------------------

    def alive(self, i: int) -> bool:
        p = self.procs.get(i)
        return p is not None and p.poll() is None

    def poll_dead(self) -> List[int]:
        return [
            i for i in self.active_indices()
            if i in self.procs and not self.alive(i)
        ]

    def wait_ready(self, timeout_s: float = 180.0,
                   only: Optional[Iterable[int]] = None) -> None:
        """Block until every replica's /readyz answers — replica startup
        (jax import + platform build) dominates fleet bring-up. ``only``
        narrows the wait to those indices: the autoscaler waits on JUST
        the replica it spawned, so an unrelated peer dying mid-spawn (the
        flash-crowd crash drill) cannot fail the scale-up."""
        import httpx

        deadline = time.monotonic() + timeout_s
        pending = set(self.active_indices() if only is None else only)
        while pending:
            for i in sorted(pending):
                if not self.alive(i):
                    tail = ""
                    try:
                        tail = self.log_file(i).read_text(errors="replace")[-2000:]
                    except OSError:
                        pass
                    raise RuntimeError(
                        f"replica {i} exited during startup; log tail:\n{tail}"
                    )
                try:
                    r = httpx.get(self.url(i) + "/readyz", timeout=2.0)
                    if r.status_code == 200:
                        pending.discard(i)
                except httpx.HTTPError:
                    pass
            if pending:
                if time.monotonic() > deadline:
                    raise TimeoutError(
                        f"replicas {sorted(pending)} not ready within {timeout_s}s"
                    )
                time.sleep(0.25)

    # -- teardown --------------------------------------------------------

    def may_hold_device_lease(self, i: int) -> bool:
        """Conservative TPU-lease marker for the SIGKILL escalation below
        (CLAUDE.md gotcha: killing a lease holder wedges the device for
        hours). ``KAKVEDA_FLEET_TPU_LEASE=1`` forces it on; otherwise a
        lease is ASSUMED unless the child env pins jax to a leaseless
        platform (``JAX_PLATFORMS`` set and TPU-free — the cpu-pinned
        bench/test fleets)."""
        env = {**os.environ, **self.extra_env}
        if env.get("KAKVEDA_FLEET_TPU_LEASE") == "1":
            return True
        plats = env.get("JAX_PLATFORMS", "").strip().lower()
        if not plats:
            return True  # default backend may be the remote TPU
        return any(p.strip() in ("tpu", "axon") for p in plats.split(","))

    def stop(self, i: int, timeout_s: float = 20.0, sig: int = signal.SIGTERM) -> None:
        """Signal + bounded wait, then a bounded SIGKILL escalation so a
        wedged replica cannot hang `down`/scale-down forever — except on
        a replica that may hold the device lease, which is left alone
        (warned) by design."""
        p = self.procs.get(i)
        if p is None or p.poll() is not None:
            return
        try:
            p.send_signal(sig)
        except ProcessLookupError:
            return
        try:
            p.wait(timeout=timeout_s)
            return
        except subprocess.TimeoutExpired:
            pass
        if self.may_hold_device_lease(i):
            log.warning("replica %d did not exit within %.0fs; leaving it "
                        "(never SIGKILL a process that may hold a device "
                        "lease)", i, timeout_s)
            return
        grace = 5.0
        try:
            grace = float(os.environ.get("KAKVEDA_FLEET_STOP_KILL_S", "") or 5.0)
        except ValueError:
            pass
        log.warning("replica %d did not exit within %.0fs; escalating to "
                    "SIGKILL (no device-lease marker; reap grace %.0fs)",
                    i, timeout_s, grace)
        try:
            p.kill()
            p.wait(timeout=max(0.1, grace))
        except ProcessLookupError:
            return
        except subprocess.TimeoutExpired:
            log.warning("replica %d still not reaped %.0fs after SIGKILL",
                        i, grace)

    def stop_all(self, timeout_s: float = 20.0) -> None:
        for i in list(self.procs):
            self.stop(i, timeout_s=timeout_s)
        for i in list(self.procs):
            self.pid_file(i).unlink(missing_ok=True)
        (self.root / "fleet.json").unlink(missing_ok=True)

    # -- manifest --------------------------------------------------------

    def write_manifest(self) -> None:
        manifest = {
            "router_port": self.router_port,
            "host": self.host,
            "port_base": self.port_base,
            "ownership": {
                "enabled": self.extra_env.get("KAKVEDA_FLEET_OWNERSHIP")
                == "1"
                or os.environ.get("KAKVEDA_FLEET_OWNERSHIP") == "1",
                "replication": int(
                    self.extra_env.get("KAKVEDA_FLEET_REPLICATION")
                    or os.environ.get("KAKVEDA_FLEET_REPLICATION", "2")
                    or 2
                ),
            },
            "replicas": [
                {
                    "id": self.replica_id(i),
                    "url": self.url(i),
                    "pid_file": str(self.pid_file(i)),
                    "log_file": str(self.log_file(i)),
                    "data_dir": str(self.data_dir(i)),
                }
                for i in self.active_indices()
            ],
        }
        if self.autoscale is not None:
            manifest["autoscale"] = {
                "min": int(self.autoscale[0]),
                "max": int(self.autoscale[1]),
                "scale_log": str(self.root / "data" / "scale_log.jsonl"),
            }
        (self.root / "fleet.json").write_text(json.dumps(manifest, indent=2))


def read_manifest(root: str | Path) -> Optional[dict]:
    """The fleet manifest written at spawn, or None (single-process)."""
    p = Path(root) / "fleet.json"
    try:
        return json.loads(p.read_text())
    except (OSError, ValueError):
        return None
