"""Global Failure Knowledge Base — device-hot index + tiered host storage."""

from kakveda_tpu.index.gfkb import GFKB  # noqa: F401
from kakveda_tpu.index.tiers import TierConfig, TieredIndex  # noqa: F401
