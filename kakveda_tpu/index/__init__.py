"""Device-resident Global Failure Knowledge Base."""

from kakveda_tpu.index.gfkb import GFKB  # noqa: F401
