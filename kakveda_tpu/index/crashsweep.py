"""Crash-point recovery certification for the GFKB lifecycle.

"Truncation is the contract" and "a crash at ANY byte leaves the old or
the new log fully live" are prose invariants until something kills a real
writer at every durable write seam and checks what a restart recovers.
This module is that something.

Mechanics
---------
The sweep runs a fixed, deterministic lifecycle cycle — row aging, an
organic resurrection, fresh upserts, then a failures-log compaction —
in a CHILD process per kill point, with one crash point armed via
``KAKVEDA_FAULTS_CRASH=site:nth`` (core/faults.py): the n-th pass through
that fault site hard-kills the child with ``os._exit(137)`` — no
exception, no ``finally``, no buffered-write flush. Power-cut semantics,
not exception semantics. The parent then opens the crashed store in a
fresh VERIFY child and certifies the recovered state:

* every pre-existing record survives, and every recovered record's
  ``(version, occurrences)`` equals its pre-cycle or post-cycle value —
  never a hybrid, never a parse error;
* the recovered tombstone set is a subset of pre ∪ post tombstones
  (each individual transition is durable-before-visible, so a crash
  mid-aging yields a clean prefix, not a torn record);
* top-1 warn parity on a held-out stable query set (rows the cycle never
  touches): the recovered store answers exactly like the pre/post oracle.

A child that exits 0 means the armed site was never reached ``nth``
times — the site is exhausted and the sweep moves to the next one, so
the sweep self-discovers every kill offset instead of hard-coding them.

Everything child-side forces ``jax_platforms=cpu`` BEFORE importing the
index stack: sweep children must never touch (or wedge) the real TPU
lease — see CLAUDE.md's environment gotchas.

Entry points: :func:`run_sweep` (tests, bench recovery row) and
``python -m kakveda_tpu.index.crashsweep`` (standalone summary JSON).
"""

from __future__ import annotations

import argparse
import json
import os
import shutil
import subprocess
import sys
import tempfile
import time
from pathlib import Path
from typing import Dict, List, Optional, Sequence

__all__ = ["run_sweep", "DEFAULT_SITES", "CRASH_RC"]

CRASH_RC = 137

# Every durable write seam of the aging/compaction cycle, in the order
# the cycle reaches them.  gfkb.append covers the shared JSONL append
# seam (failures + tombstone + applied logs), gfkb.tombstone the
# per-transition tombstone writes, gfkb.snapshot the checkpoint write,
# and the three compact_* sites bracket the fenced swap.
DEFAULT_SITES = (
    "gfkb.tombstone",
    "gfkb.append",
    "gfkb.snapshot",
    "gfkb.compact_delta",
    "gfkb.compact_fence",
    "gfkb.compact_swap",
)


def _sig(i: int) -> str:
    return f"crashsweep failure signature {i} stack frame worker pool"


def _ftype(i: int) -> str:
    return "oom" if i % 2 else "timeout"


def _child_env(data_dir: Path, crash: str = "") -> Dict[str, str]:
    """Clean child environment: inherit the interpreter setup, strip every
    KAKVEDA_* knob (the sweep's cycle must not inherit auto-compaction or
    ambient chaos arming from the parent), arm exactly one crash spec."""
    env = {k: v for k, v in os.environ.items() if not k.startswith("KAKVEDA_")}
    if crash:
        env["KAKVEDA_FAULTS_CRASH"] = crash
    env["KAKVEDA_CRASHSWEEP_CHILD"] = "1"
    return env


def _spawn(
    mode: str,
    data_dir: Path,
    *,
    capacity: int,
    dim: int,
    rows: int,
    aged: int,
    crash: str = "",
    extra: Sequence[str] = (),
    timeout: float = 300.0,
) -> subprocess.CompletedProcess:
    cmd = [
        sys.executable,
        "-m",
        "kakveda_tpu.index.crashsweep",
        "--mode",
        mode,
        "--data-dir",
        str(data_dir),
        "--capacity",
        str(capacity),
        "--dim",
        str(dim),
        "--rows",
        str(rows),
        "--aged",
        str(aged),
        *extra,
    ]
    return subprocess.run(
        cmd,
        env=_child_env(data_dir, crash),
        capture_output=True,
        text=True,
        timeout=timeout,
    )


def _check(proc: subprocess.CompletedProcess, what: str) -> dict:
    if proc.returncode != 0:
        raise RuntimeError(
            f"crashsweep {what} child failed rc={proc.returncode}:\n"
            f"{proc.stderr[-2000:]}"
        )
    return json.loads(proc.stdout.strip().splitlines()[-1])


# ----------------------------------------------------------------------
# child modes (run under a CPU-pinned interpreter; may be hard-killed)
# ----------------------------------------------------------------------


def _force_cpu() -> None:
    # The image's sitecustomize pins jax at the remote TPU; only the
    # in-process config update reliably overrides it (CLAUDE.md).
    import jax

    jax.config.update("jax_platforms", "cpu")


def _open_store(args):
    from kakveda_tpu.index.gfkb import GFKB

    return GFKB(data_dir=Path(args.data_dir), capacity=args.capacity, dim=args.dim)


def _child_seed(args) -> None:
    """Build the pre-cycle store: two row cohorts with a real wall-clock
    gap between them so the cycle's TTL boundary can age the old cohort
    and keep the young one. Prints the cohort boundary timestamps."""
    kb = _open_store(args)
    for i in range(args.aged):
        kb.upsert_failure(
            failure_type=_ftype(i),
            signature_text=_sig(i),
            app_id=f"app-{i % 3}",
            impact_severity="high",
        )
    t_old = time.time()
    time.sleep(args.gap)
    t_new = time.time()
    for i in range(args.aged, args.rows):
        kb.upsert_failure(
            failure_type=_ftype(i),
            signature_text=_sig(i),
            app_id=f"app-{i % 3}",
            impact_severity="high",
        )
    kb.close()
    print(json.dumps({"t_old": t_old, "t_new": t_new}))


def _child_cycle(args) -> None:
    """One deterministic lifecycle cycle; the armed crash point (if any)
    kills us somewhere inside. Every mutation is a plain public call —
    the cycle exercises the production write path, not a test double."""
    kb = _open_store(args)
    kb.age_rows(ttl_s=args.ttl, now=args.now)
    if args.phase == "aging":
        kb.close()
        print(json.dumps({"cycle": "aging"}))
        return
    # Organic resurrection of aged row 0 (replication would be fenced;
    # a real recurrence must come back).
    kb.upsert_failure(
        failure_type=_ftype(0),
        signature_text=_sig(0),
        app_id="app-res",
        impact_severity="high",
    )
    for i in (args.rows, args.rows + 1):
        kb.upsert_failure(
            failure_type=_ftype(i),
            signature_text=_sig(i),
            app_id=f"app-{i % 3}",
            impact_severity="high",
        )
    kb.compact()
    kb.close()
    print(json.dumps({"cycle": "complete"}))


def _child_verify(args) -> None:
    """Open the (possibly crash-recovered) store and print its canonical
    state: per-record (version, occurrences), net tombstones, top-1 warn
    answer per sweep signature, compaction generation."""
    kb = _open_store(args)
    with kb._lock:
        records = {
            str(r.failure_id): [r.version, r.occurrences] for r in kb._records
        }
        tombs = {
            str(kb._records[s].failure_id): reason
            for s, reason in kb._tombstoned.items()
        }
    queries = [_sig(i) for i in range(args.rows + 2)]
    top1: Dict[str, Optional[str]] = {}
    for q, matches in zip(queries, kb.match_batch(queries)):
        top1[q] = str(matches[0].failure_id) if matches else None
    out = {
        "records": records,
        "tombstones": tombs,
        "top1": top1,
        "generation": kb.lifecycle_info()["compact_generation"],
    }
    kb.close()
    print(json.dumps(out))


# ----------------------------------------------------------------------
# parent sweep
# ----------------------------------------------------------------------


def run_sweep(
    *,
    rows: int = 10,
    aged: int = 5,
    sites: Sequence[str] = DEFAULT_SITES,
    max_nth: int = 60,
    capacity: int = 64,
    dim: int = 256,
    gap: float = 1.2,
    keep_dirs: bool = False,
) -> dict:
    """Sweep every kill offset of one lifecycle cycle; certify recovery.

    Returns ``{"kill_points": n, "corrupt_recoveries": n, "failures":
    [...], "sites": {site: points}}``. A non-empty ``failures`` list (and
    ``corrupt_recoveries > 0``) means a crash offset from which restart
    replay produced a state that is neither pre- nor post-cycle — the
    bench recovery row raises on it.
    """
    root = Path(tempfile.mkdtemp(prefix="kakveda-crashsweep-"))
    common = dict(capacity=capacity, dim=dim, rows=rows, aged=aged)
    try:
        seed_dir = root / "seed"
        seed_dir.mkdir()
        seed = _check(
            _spawn("seed", seed_dir, **common, extra=["--gap", str(gap)]),
            "seed",
        )
        # TTL boundary between the cohorts; injected clock = real clock
        # (the gap is real wall time, no month-compression needed here).
        now = time.time()
        ttl = now - (seed["t_old"] + seed["t_new"]) / 2.0
        cyc = ["--ttl", str(ttl), "--now", str(now)]

        pre = _check(_spawn("verify", seed_dir, **common), "verify-pre")

        # MID oracle: aging only. A crash between a row's aging and its
        # later resurrection recovers to this intermediate — every
        # individual transition is durable-before-visible, so a clean
        # prefix of the cycle is a legal recovery target, not corruption.
        mid_dir = root / "mid"
        shutil.copytree(seed_dir, mid_dir)
        _check(
            _spawn(
                "cycle", mid_dir, **common, extra=[*cyc, "--phase", "aging"]
            ),
            "cycle-mid",
        )
        mid = _check(_spawn("verify", mid_dir, **common), "verify-mid")

        post_dir = root / "post"
        shutil.copytree(seed_dir, post_dir)
        _check(_spawn("cycle", post_dir, **common, extra=cyc), "cycle-post")
        post = _check(_spawn("verify", post_dir, **common), "verify-post")

        # Queries the cycle never touches: stable top-1 across all oracles.
        stable = [
            _sig(i)
            for i in range(aged, rows)
            if pre["top1"].get(_sig(i))
            == mid["top1"].get(_sig(i))
            == post["top1"].get(_sig(i))
        ]

        results: Dict[str, int] = {}
        failures: List[dict] = []
        kill_points = 0
        for site in sites:
            points = 0
            for nth in range(1, max_nth + 1):
                work = root / f"{site.replace('.', '_')}-{nth}"
                shutil.copytree(seed_dir, work)
                proc = _spawn(
                    "cycle", work, **common, extra=cyc, crash=f"{site}:{nth}"
                )
                if proc.returncode == 0:
                    shutil.rmtree(work, ignore_errors=True)
                    break  # site exhausted: the cycle has < nth passes
                if proc.returncode != CRASH_RC:
                    failures.append(
                        {
                            "site": site,
                            "nth": nth,
                            "kind": "bad_exit",
                            "rc": proc.returncode,
                            "stderr": proc.stderr[-1000:],
                        }
                    )
                    shutil.rmtree(work, ignore_errors=True)
                    continue
                points += 1
                kill_points += 1
                try:
                    rec = _check(_spawn("verify", work, **common), "verify")
                    errs = _certify(rec, pre, mid, post, stable)
                except Exception as e:  # noqa: BLE001 — a recovery crash IS the finding
                    errs = [f"recovery raised: {type(e).__name__}: {e}"]
                if errs:
                    failures.append({"site": site, "nth": nth, "errors": errs})
                if not keep_dirs:
                    shutil.rmtree(work, ignore_errors=True)
            else:
                failures.append(
                    {"site": site, "kind": "not_exhausted", "max_nth": max_nth}
                )
            results[site] = points
        return {
            "kill_points": kill_points,
            "corrupt_recoveries": len(failures),
            "failures": failures,
            "sites": results,
            "stable_queries": len(stable),
            "root": str(root) if keep_dirs else None,
        }
    finally:
        if not keep_dirs:
            shutil.rmtree(root, ignore_errors=True)


def _certify(
    rec: dict, pre: dict, mid: dict, post: dict, stable: Sequence[str]
) -> List[str]:
    """The recovery contract, as checks over canonical verify output."""
    errs: List[str] = []
    for fid, vo in pre["records"].items():
        if fid not in rec["records"]:
            errs.append(f"committed record {fid} lost")
    for fid, vo in rec["records"].items():
        ok = vo == pre["records"].get(fid) or vo == post["records"].get(fid)
        if not ok:
            errs.append(
                f"record {fid} hybrid state {vo} "
                f"(pre {pre['records'].get(fid)}, post {post['records'].get(fid)})"
            )
    allowed = (
        set(pre["tombstones"]) | set(mid["tombstones"]) | set(post["tombstones"])
    )
    for fid in rec["tombstones"]:
        if fid not in allowed:
            errs.append(f"unexpected tombstone {fid}")
    for q in stable:
        want = pre["top1"].get(q)
        got = rec["top1"].get(q)
        if got != want:
            errs.append(f"top-1 parity broke for {q!r}: {got} != {want}")
        if got is not None and got in rec["tombstones"]:
            errs.append(f"top-1 for {q!r} is tombstoned row {got}")
    return errs


def main(argv: Optional[Sequence[str]] = None) -> int:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument(
        "--mode", choices=("seed", "cycle", "verify", "sweep"), default="sweep"
    )
    p.add_argument("--data-dir", default="")
    p.add_argument("--capacity", type=int, default=64)
    p.add_argument("--dim", type=int, default=256)
    p.add_argument("--rows", type=int, default=10)
    p.add_argument("--aged", type=int, default=5)
    p.add_argument("--gap", type=float, default=1.2)
    p.add_argument("--ttl", type=float, default=0.0)
    p.add_argument("--now", type=float, default=0.0)
    p.add_argument("--phase", choices=("full", "aging"), default="full")
    p.add_argument("--max-nth", type=int, default=60)
    args = p.parse_args(argv)
    if args.mode == "sweep":
        out = run_sweep(
            rows=args.rows,
            aged=args.aged,
            capacity=args.capacity,
            dim=args.dim,
            max_nth=args.max_nth,
        )
        print(json.dumps(out, indent=2))
        return 1 if out["corrupt_recoveries"] else 0
    if not args.data_dir:
        p.error("--data-dir is required for child modes")
    _force_cpu()
    {"seed": _child_seed, "cycle": _child_cycle, "verify": _child_verify}[
        args.mode
    ](args)
    return 0


if __name__ == "__main__":
    sys.exit(main())
