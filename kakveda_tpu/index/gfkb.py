"""Global Failure Knowledge Base — the framework's center of gravity.

Capability parity with the reference GFKB service
(reference: services/gfkb/app.py:23-198): append-only JSONL persistence with
versioning-by-append, ``F-%04d``/``FP-%04d`` id minting, top-k similarity
match, and pattern upsert with identity-by-name. Re-designed TPU-first:

  * every canonical failure's ``signature_text`` is embedded once at upsert
    time (hashed n-grams, kakveda_tpu.ops.featurizer) and lives in an
    HBM-resident [capacity, dim] matrix sharded over the mesh's ``data``
    axis — instead of the reference's read-the-whole-file + TF-IDF-refit per
    match request (reference: services/gfkb/app.py:54-56,81-89);
  * a match is one compiled matmul + sharded top-k (kakveda_tpu.ops.knn),
    batched across concurrent queries;
  * the index is fully replayable from ``failures.jsonl`` (checkpoint =
    the append log, mirroring the reference's durability-by-append design).

Deliberate deviations from the reference, both documented here:
  * id minting counts *canonical* failures, not JSONL rows — the reference
    mints ``F-{len(rows)+1}`` so version appends create id gaps
    (reference: services/gfkb/app.py:117); here ids are dense.
  * the reference applies the ``failure_type`` filter *after* truncating to
    top-5 so a type-filtered query can return fewer (or zero) matches even
    when matching failures exist (reference: services/gfkb/app.py:89-91).
    ``type_filter="post"`` (default) preserves that observable behavior;
    ``type_filter="pre"`` fixes it with a device-side pre-selection mask
    (per-slot type ids AND-ed into the valid mask before top-k).
"""

from __future__ import annotations

import json
import logging
import os
import threading
import time
from collections import OrderedDict, deque
from pathlib import Path
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np
from jax.sharding import Mesh

from kakveda_tpu import native
from kakveda_tpu.core import faults as _faults
from kakveda_tpu.core import ledger as _ledger
from kakveda_tpu.core import metrics as _metrics
from kakveda_tpu.core import profiling

log = logging.getLogger("kakveda.gfkb")
from kakveda_tpu.core.schemas import (
    CanonicalFailureRecord,
    FailureMatch,
    PatternEntity,
    Severity,
    utcnow,
)
from kakveda_tpu.index.tiers import TierConfig, TieredIndex
from kakveda_tpu.ops.featurizer import HashedNGramFeaturizer, dense_rows_to_sparse
from kakveda_tpu.ops.knn import ShardedKnn, batch_bucket
from kakveda_tpu.parallel.mesh import create_mesh
from kakveda_tpu.core import sanitize


class SnapshotError(RuntimeError):
    """Snapshot unavailable or aborted (persist=False, concurrent reload) —
    a caller-side condition, distinct from device/runtime failures."""


class HostFallbackDisabled(RuntimeError):
    """Degraded-mode matching requested but the host tiers are disabled
    (KAKVEDA_HOST_FALLBACK=0) — a configuration condition, typed so the
    warn path never confuses it with a device failure."""


def _iso(ts: str):
    """Parse our own model_dump_json timestamps. Pydantic writes tz-aware
    UTC as '…Z', which datetime.fromisoformat only learned in Python 3.11
    — on 3.10 the bare call raised and the blanket corruption-fallback in
    _restore_snapshot silently degraded EVERY restore to a full log
    replay (the snapshot fast path never actually ran)."""
    from datetime import datetime

    if ts.endswith("Z"):
        ts = ts[:-1] + "+00:00"
    return datetime.fromisoformat(ts)


def _record_from_snapshot(obj: dict) -> dict:
    """Snapshot rows are our own model_dump_json output: re-hydrate the two
    non-JSON-native field types for model_construct (which skips the
    validators that would otherwise do this)."""
    obj["created_at"] = _iso(obj["created_at"])
    obj["updated_at"] = _iso(obj["updated_at"])
    obj["impact_severity"] = Severity(obj["impact_severity"])
    return obj


class GFKB:
    """Failure + pattern store with a device-resident similarity index."""

    def __init__(
        self,
        data_dir: str | Path = "data",
        mesh: Optional[Mesh] = None,
        capacity: int = 1 << 14,
        dim: int = 2048,
        top_k: int = 5,
        featurizer: Optional[HashedNGramFeaturizer] = None,
        persist: bool = True,
        tier_config: Optional[TierConfig] = None,
    ):
        self.data_dir = Path(data_dir)
        self.persist = persist
        if persist:
            self.data_dir.mkdir(parents=True, exist_ok=True)
        self.failures_path = self.data_dir / "failures.jsonl"
        self.patterns_path = self.data_dir / "patterns.jsonl"
        # Replication idempotency (fleet ingest fan-in, docs/scale-out.md):
        # bus events applied through upsert_failures_batch(event_id=…) are
        # dedup'd on this set, persisted as their own append log so DLQ
        # replay and at-least-once redelivery stay dedup-safe ACROSS
        # restarts. Bounded (KAKVEDA_GFKB_APPLIED_MAX, FIFO eviction) —
        # far larger than any plausible redelivery window.
        self.applied_path = self.data_dir / "applied_events.jsonl"
        self._applied_events: "OrderedDict[str, bool]" = OrderedDict()
        self._applied_max = int(os.environ.get("KAKVEDA_GFKB_APPLIED_MAX", "65536"))
        # Lifecycle side-log (docs/robustness.md § failure-memory
        # lifecycle): row aging and duplicate collapse append
        # {"op": "tomb"|"live", "id", "reason", "ts"} lines here instead
        # of touching the record schema — a KAKVEDA_GFKB_COMPACT=0 store
        # stays byte-identical to the pre-lifecycle format. Tombstoned
        # slots keep their records, ids and (type, signature) keys: slot
        # stability is load-bearing for dense id minting, replay
        # latest-wins and replication cursors. They are filtered out of
        # every match assembly host-side, zeroed on device (so they never
        # consume top-k candidates), fence replicated re-inserts (2xx
        # drop), and resurrect in place on an ORGANIC upsert.
        self.tombstones_path = self.data_dir / "tombstones.jsonl"
        self._tombstoned: Dict[int, str] = {}  # slot -> reason
        # Compaction posture: generation/ts live in the snapshot manifest's
        # "compact" section; the age auto-trigger counts from process start
        # when the store has never compacted.
        self._opened_ts = time.time()
        self._last_compact_ts = 0.0
        self._compact_generation = 0
        self._compact_inflight = False
        self._compact_bytes = int(os.environ.get("KAKVEDA_GFKB_COMPACT_BYTES", "0"))
        self._compact_age_s = float(os.environ.get("KAKVEDA_GFKB_COMPACT_AGE_S", "0"))

        self.mesh = mesh if mesh is not None else create_mesh("data:-1")
        self.featurizer = featurizer or HashedNGramFeaturizer(dim=dim)
        self.top_k = top_k
        self._knn = ShardedKnn(self.mesh, capacity, dim, k=top_k)
        self._emb, self._valid = self._knn.alloc()
        # Per-slot failure-type ids (device int32 side-table) for the
        # device-side type pre-filter; host mapping type name -> id.
        self._types = self._knn.alloc_i32()
        self._type_ids: Dict[str, int] = {}

        # Host-side metadata: one entry per canonical failure, slot-aligned.
        self._records: List[CanonicalFailureRecord] = []
        self._slot_by_key: Dict[Tuple[str, str], int] = {}
        self._slot_by_id: Dict[str, int] = {}
        # Pattern store: set-backed mutable state per name. The log is
        # DELTA-append — each line carries only the failure_ids/apps new in
        # that upsert, and replay unions lines — because re-appending the
        # full membership per upsert (the reference's model,
        # services/gfkb/app.py:168-198) makes both the log and the per-batch
        # serialize cost O(N²) over a failure stream. Full-record lines from
        # older logs replay identically (union of growing prefixes).
        self._pattern_state: Dict[str, dict] = {}  # name -> mutable state
        # Reentrant: compact() snapshots and swaps the log under ONE
        # critical section (a snapshot racing in between would pin a log
        # offset the swap is about to invalidate).
        self._snapshot_write_lock = sanitize.named_lock(
            "GFKB._snapshot_write_lock", kind="rlock"
        )
        # Bumped by reload(); snapshot() aborts if it changed mid-write so a
        # purge (external log rewrite + reload) can't race a snapshot into
        # resurrecting pre-purge records.
        self._generation = 0
        # Per-type aggregates maintained incrementally at upsert so pattern
        # detection reads them O(1) instead of rescanning every record per
        # batch (O(N²) over a failure stream).
        self._ids_by_type: Dict[str, List[str]] = {}
        self._apps_by_type: Dict[str, set] = {}
        self._lock = sanitize.named_lock("GFKB._lock")
        # Upserts append records under the lock but embed AFTER releasing it
        # (_embed_new_slots). Consumers of (records, embeddings) pairs —
        # snapshot(), records_and_embeddings() — must not observe appended
        # records whose rows are still zero: they drain this in-flight
        # counter first (snapshots would otherwise persist zero vectors
        # permanently, since restore never re-embeds).
        self._pending_embeds = 0
        self._embeds_cv = threading.Condition(self._lock)
        # Group-commit append logs (C++ writer when available): records are
        # buffered and flushed after each upsert batch instead of paying an
        # open+write+close per record (the reference's pattern,
        # services/gfkb/app.py:49-51).
        self._logs: Dict[Path, "native.AppendLog"] = {}
        # Crash-safe replay: a torn FINAL line (a crash mid-append) is
        # tolerated at startup — replay warns, remembers the offset here,
        # and the next append truncates the file back to it before
        # writing, so the torn bytes never corrupt a later record.
        # Mid-file corruption still raises (that is data loss, not a torn
        # tail, and must not be silently truncated away).
        self._truncate_pending: Dict[Path, int] = {}
        # Chaos-harness sites (core/faults.py), resolved once.
        self._fault_append = _faults.site("gfkb.append")
        self._fault_snapshot = _faults.site("gfkb.snapshot")
        self._fault_mine = _faults.site("gfkb.mine_state")
        # Durable-write seams of the compaction fence + the tombstone
        # append — the crash-point sweep (index/crashsweep.py) arms these
        # one at a time and certifies recovery at every kill offset.
        self._fault_compact_delta = _faults.site("gfkb.compact_delta")
        self._fault_compact_fence = _faults.site("gfkb.compact_fence")
        self._fault_compact_swap = _faults.site("gfkb.compact_swap")
        self._fault_tombstone = _faults.site("gfkb.tombstone")
        # Device-loss drill site, SHARED with the device-health probe
        # (core/admission.py): armed, every match dispatch fails exactly
        # like a wedged backend — and the probe keeps failing until it is
        # disarmed, which is what un-latches degraded mode.
        self._fault_device = _faults.site("device.unavailable")

        # Tiered storage hierarchy (index/tiers.py): the host-warm tier
        # mirrors every row's sparse (idx, val) embedding slot-aligned —
        # degraded-mode matching, overflow past the device hot-row budget
        # and snapshot restore ALL serve through it (one abstraction, not
        # the PR-5 parallel mirror) — and the disk-cold tier pages rows
        # past the warm budget in from memmap shards on demand. Routing
        # is IVF-style over coarse centroids maintained per ingest batch.
        # KAKVEDA_HOST_FALLBACK=0 opts out of the host tiers entirely (no
        # mirror, no fallback, no hot cap — degraded warn then errors).
        self._host_fallback = os.environ.get("KAKVEDA_HOST_FALLBACK", "1") != "0"
        self._tiers: Optional[TieredIndex] = None
        if self._host_fallback:
            self._tiers = TieredIndex(
                self.featurizer.dim,
                tier_config or TierConfig(),
                self.data_dir if persist else None,
            )
        self._m_warn_fallback = _metrics.get_registry().counter(
            "kakveda_warn_fallback_total",
            "Warn verdicts served by the host-side fallback index while "
            "the backend is degraded",
        )
        _rep = _metrics.get_registry().counter(
            "kakveda_gfkb_replicate_apply_total",
            "Bus-replicated ingest events applied to this GFKB by outcome "
            "(applied|dedup; fenced counts individual tombstoned ROWS "
            "dropped by the lifecycle fence)", ("outcome",),
        )
        self._m_rep_applied = _rep.labels(outcome="applied")
        self._m_rep_dedup = _rep.labels(outcome="dedup")
        self._m_rep_fenced = _rep.labels(outcome="fenced")
        # Lifecycle metrics — children resolved here, BEFORE _replay():
        # the startup applied-log compaction already counts into the
        # shared kakveda_gfkb_compact_total family.
        _reg0 = _metrics.get_registry()
        _cmp = _reg0.counter(
            "kakveda_gfkb_compact_total",
            "Durable-log compactions by store and outcome (ok|skipped|"
            "error|stale_tmp; stale_tmp = leftover temp file from a "
            "crashed rewrite, removed before the next attempt)",
            ("store", "outcome"),
        )
        self._m_compact = {
            (st, oc): _cmp.labels(store=st, outcome=oc)
            for st in ("failures", "applied", "tombstones")
            for oc in ("ok", "skipped", "error", "stale_tmp")
        }
        _tmb = _reg0.counter(
            "kakveda_gfkb_tombstone_total",
            "Row lifecycle transitions by reason (aged = TTL demotion, "
            "collapsed = near-duplicate fold, resurrected = organic "
            "re-upsert of a tombstoned signature)",
            ("reason",),
        )
        self._m_tombstone = {
            r: _tmb.labels(reason=r) for r in ("aged", "collapsed", "resurrected")
        }
        self._g_tombstoned = _reg0.gauge(
            "kakveda_gfkb_tombstoned_rows",
            "Currently tombstoned (resident but never matched) GFKB rows",
        )

        # Incremental mining state (KAKVEDA_MINE_INCREMENTAL=0 restores
        # the full-sweep-only behavior bit-for-bit: no state, no cache, no
        # extra device dispatches). The union-find + aggregates live on
        # host; each ingest batch gets ONE delta top-k dispatch against
        # the resident index (ops/incremental.py) whose packed result is
        # drained lazily — or zero dispatches when a recent warn match for
        # the same signature already fetched the neighbors.
        self._mine_enabled = os.environ.get("KAKVEDA_MINE_INCREMENTAL", "1") != "0"
        self._mine = None
        # pending delta results: (knn, slots np.int32, packed, generation)
        self._mine_pending: deque = deque()
        self._mine_pending_max = int(os.environ.get("KAKVEDA_MINE_PENDING_MAX", "256"))
        # signature_text -> (scores, slots, generation): the warn path's
        # already-fetched neighbors, reused for free attachment at ingest.
        self._match_cache: "OrderedDict[str, tuple]" = OrderedDict()
        self._match_cache_max = int(os.environ.get("KAKVEDA_MINE_MATCH_CACHE", "4096"))
        self.mine_delta_dispatches = 0  # observability + reuse tests
        self._mine_merges_seen = 0
        if self._mine_enabled:
            from kakveda_tpu.ops.incremental import ClusterState

            self._mine = ClusterState(
                threshold=float(os.environ.get("KAKVEDA_MINE_THRESHOLD", "0.6")),
                k=int(os.environ.get("KAKVEDA_MINE_K", "32")),
            )
        reg = _metrics.get_registry()
        self._m_mine_update = reg.histogram(
            "kakveda_mine_update_seconds",
            "Incremental cluster-state update wall per drained delta batch",
        )
        self._m_mine_clusters = reg.gauge(
            "kakveda_mine_clusters",
            "Live clusters in the incremental mining state",
        )
        _attach = reg.counter(
            "kakveda_mine_attach_total",
            "Rows attached to the incremental cluster state by neighbor source",
            ("source",),
        )
        self._m_mine_attach = {
            s: _attach.labels(source=s) for s in ("delta", "reused", "tier")
        }
        self._m_mine_merges = reg.counter(
            "kakveda_mine_merges_total",
            "Cluster merges performed by incremental attachment",
        )
        # Published immutable view for lock-free matching: a tuple swap is
        # atomic under the GIL, so match_batch never takes the data lock —
        # see match_batch for the consistency argument.
        self._view = (self._knn, self._emb, self._valid, self._types, self._records)

        if persist:
            self._replay()
        self._mine_after_replay()
        self._publish()

    # ------------------------------------------------------------------
    # persistence
    # ------------------------------------------------------------------

    def _append_jsonl(self, path: Path, obj: dict) -> None:
        """Buffer one record; callers group-commit with :meth:`_flush_logs`
        at the end of each public mutation (read-your-writes for external
        readers of the JSONL files, one syscall per batch instead of an
        open+write+close per record)."""
        self._append_line(path, json.dumps(obj, ensure_ascii=False))

    def _append_line(self, path: Path, line: str) -> None:
        """Raw pre-serialized variant: the streaming path serializes with
        pydantic's C serializer (model_dump_json) and skips the Python json
        encoder entirely."""
        if not self.persist:
            return
        self._fault_append.fire()
        pend = self._truncate_pending.pop(path, None)
        if pend is not None:
            # First append since a torn tail was tolerated at replay:
            # truncate the file back to the last complete record before
            # anything new lands after the torn bytes.
            lg = self._logs.pop(path, None)
            if lg is not None:
                lg.close()
            try:
                os.truncate(path, pend)
                log.warning("truncated torn tail of %s to %d bytes", path, pend)
            except OSError as e:
                log.error("could not truncate torn tail of %s: %s", path, e)
                self._truncate_pending[path] = pend
                raise
        alog = self._logs.get(path)
        if alog is None:
            alog = self._logs[path] = native.AppendLog(path)
        alog.append((line + "\n").encode("utf-8"))

    def _flush_logs(self) -> None:
        for log in self._logs.values():
            log.flush()

    def close(self) -> None:
        """Flush and close the append logs (safe to call repeatedly)."""
        with self._lock:
            self._close_locked()

    def _close_locked(self) -> None:
        """Caller holds ``_lock`` (reload() closes mid-rebuild while
        already inside its locked section)."""
        for log in self._logs.values():
            log.close()
        self._logs.clear()

    def _iter_log_lines(self, path: Path, offset: int, parse):
        """Yield ``parse(line)`` for each JSONL line of ``path`` from byte
        ``offset``, tolerating exactly one torn FINAL line: a record that
        fails to decode/parse with nothing but whitespace after it is a
        crash mid-append — warn, schedule truncate-on-next-append
        (``_truncate_pending``) and stop. A bad record with more data
        after it is mid-file corruption and raises."""
        with path.open("rb") as f:
            if offset:
                f.seek(offset)
            pos = f.tell()
            for raw in f:
                line_start = pos
                pos += len(raw)
                try:
                    text = raw.decode("utf-8").strip()
                    if not text:
                        continue
                    parsed = parse(text)
                except Exception as e:  # noqa: BLE001 — decode OR parse failure
                    rest = f.read()
                    if rest.strip():
                        raise ValueError(
                            f"corrupt record mid-file in {path} at byte "
                            f"{line_start} ({type(e).__name__}: {e}); refusing "
                            "to replay past it"
                        ) from e
                    log.warning(
                        "tolerating torn final line of %s at byte %d (%s); "
                        "will truncate on next append",
                        path, line_start, type(e).__name__,
                    )
                    self._truncate_pending[path] = line_start
                    return
                yield parsed

    def _replay(self) -> None:
        """Rebuild host metadata + device index from the append logs,
        fast-forwarding through a snapshot when one is valid (startup at
        1M rows is dominated by re-embedding + re-parsing otherwise).
        Both logs tolerate one torn final line (see _iter_log_lines)."""
        if self.failures_path.exists():
            tail_offset = self._restore_snapshot()
            latest: Dict[Tuple[str, str], CanonicalFailureRecord] = {}
            order: List[Tuple[str, str]] = []
            for rec in self._iter_log_lines(
                self.failures_path, tail_offset,
                lambda t: CanonicalFailureRecord.model_validate(json.loads(t)),
            ):
                key = (rec.failure_type, rec.signature_text)
                if key in self._slot_by_key:  # snapshot row updated in tail
                    self._records[self._slot_by_key[key]] = rec
                    self._apps_by_type.setdefault(rec.failure_type, set()).update(
                        rec.affected_apps
                    )
                    if self._mine is not None:
                        # Membership is unchanged by a version update, but
                        # the cluster's app span may have widened.
                        self._mine.note_apps(
                            self._slot_by_key[key], rec.affected_apps
                        )
                    continue
                if key not in latest:
                    order.append(key)
                latest[key] = rec
            if order:
                base = len(self._records)
                self._records.extend(latest[k] for k in order)
                for i, k in enumerate(order):
                    self._slot_by_key[k] = base + i
                    self._slot_by_id[latest[k].failure_id] = base + i
                for k in order:
                    rec = latest[k]
                    self._ids_by_type.setdefault(rec.failure_type, []).append(rec.failure_id)
                    self._apps_by_type.setdefault(rec.failure_type, set()).update(
                        rec.affected_apps
                    )
                self._ensure_capacity(len(self._records))
                tids = np.asarray(
                    [self._type_id(latest[k].failure_type) for k in order], np.int32
                )
                self._insert_texts_chunked(
                    [latest[k].signature_text for k in order],
                    np.arange(base, base + len(order), dtype=np.int32),
                    tids,
                )

        if self.patterns_path.exists():
            for p in self._iter_log_lines(
                self.patterns_path, 0,
                lambda t: PatternEntity.model_validate(json.loads(t)),
            ):
                self._merge_pattern_line(p)

        if self.applied_path.exists():
            # Replication dedup set: replayed whole (the log is ids only,
            # ~40 bytes/event) with the same torn-tail tolerance. A torn
            # final id means that event's rows may replay once more — an
            # occurrence bump, never a duplicate record (upserts key on
            # (failure_type, signature_text)).
            n_lines = 0
            for rec in self._iter_log_lines(self.applied_path, 0, json.loads):
                n_lines += 1
                eid = rec.get("id") if isinstance(rec, dict) else None
                if isinstance(eid, str):
                    self._applied_note_locked(eid)
            self._compact_applied_log(n_lines)

        if self.tombstones_path.exists():
            # Lifecycle side-log: net tombstone state replays from byte 0
            # (tiny — one op line per transition; compact() rewrites it to
            # net state). Unknown ids skip-with-warning — the failures log
            # can be independently rewritten (purge) or truncated.
            for rec in self._iter_log_lines(self.tombstones_path, 0, json.loads):
                if not isinstance(rec, dict):
                    log.warning("non-object tombstone line skipped")
                    continue
                fid = rec.get("id")
                slot = self._slot_by_id.get(fid) if isinstance(fid, str) else None
                if slot is None:
                    log.warning("tombstone line for unknown id %r skipped", fid)
                    continue
                if rec.get("op") == "tomb":
                    self._tombstoned[slot] = str(rec.get("reason", "aged"))
                else:
                    self._tombstoned.pop(slot, None)
            if self._tombstoned:
                # The replay above re-embedded every row; re-zero the
                # tombstoned ones so they never consume top-k candidates.
                self._zero_device_rows_locked(sorted(self._tombstoned))
            self._g_tombstoned.set(len(self._tombstoned))

    def _compact_applied_log(self, n_lines: int) -> None:
        """Rewrite ``applied_events.jsonl`` to the retained dedup tail.

        The in-memory set is bounded (KAKVEDA_GFKB_APPLIED_MAX, FIFO) but
        the on-disk log only ever appended — a long-lived replica replayed
        an unbounded file every restart just to discard most of it here.
        Startup is the one safe moment to rewrite (single-threaded, no
        append handle open yet); the swap is write-tmp + atomic replace so
        a crash mid-compaction leaves the old log intact. Ids evicted from
        the bounded set were unreplayable as dedup evidence anyway — their
        events re-apply as occurrence bumps, the documented FIFO contract.
        ``KAKVEDA_GFKB_APPLIED_COMPACT=0`` opts out (docs/scale-out.md)."""
        if not self.persist:
            return
        tmp = self.applied_path.with_suffix(".tmp")
        if tmp.exists():
            # A crash between the tmp write and os.replace strands the
            # temp file — it is never valid input (the real log is still
            # live), so remove it before any early return can leak it.
            try:
                tmp.unlink()
                self._m_compact[("applied", "stale_tmp")].inc()
            except OSError as e:
                log.warning("stale %s could not be removed: %s", tmp, e)
        if n_lines <= len(self._applied_events):
            return
        if os.environ.get("KAKVEDA_GFKB_APPLIED_COMPACT", "1") == "0":
            self._m_compact[("applied", "skipped")].inc()
            return
        # A pending torn-tail truncation is handled by the rewrite itself
        # (only fully parsed ids survive), so drop the schedule.
        self._truncate_pending.pop(self.applied_path, None)
        try:
            with tmp.open("w", encoding="utf-8") as f:
                for eid in self._applied_events:
                    f.write(json.dumps({"id": eid}) + "\n")
            os.replace(tmp, self.applied_path)
            self._m_compact[("applied", "ok")].inc()
            log.info(
                "compacted %s: %d -> %d ids",
                self.applied_path, n_lines, len(self._applied_events),
            )
        except OSError as e:  # disk trouble: keep the uncompacted log
            log.warning("applied-log compaction skipped: %s", e)
            self._m_compact[("applied", "error")].inc()
            tmp.unlink(missing_ok=True)

    # --- snapshot / restore --------------------------------------------

    # v2: embeddings persist as sparse (idx, val) pairs (~16× smaller,
    # no re-sparsify on restore). v3 adds a content checksum over the
    # snapshot payload to the manifest, so a corrupted snapshot (bad disk,
    # partial copy) degrades to full replay instead of restoring garbage
    # vectors. v4 adds the incremental-mining cluster labels
    # (clusters.npy) with their OWN manifest checksum: a bad cluster file
    # degrades to one full re-mine (state marked stale), never to full
    # log replay and never to restoring unverified labels. v5 adds the
    # tiered-index state — centroids.npy + tier_assign.npy (the IVF
    # router) with their own checksum, and a "tiers" manifest section
    # recording the hot boundary; overflow rows persist through the same
    # sparse payload (sourced from the host tiers instead of the device).
    # A bad/missing tier file degrades to one router rebuild from the
    # restored rows, never to full log replay. Older snapshots fall back
    # to full replay — acceptable one-time cost, no migration path needed.
    _SNAPSHOT_VERSION = 5
    _TAIL_HASH_BYTES = 4096
    _SNAPSHOT_PAYLOAD = ("sparse_idx.npy", "sparse_val.npy", "records.jsonl")

    @classmethod
    def _snapshot_checksum(cls, sd: Path) -> str:
        """sha256 over the snapshot payload files, in manifest order — THE
        content checksum both snapshot() and _restore_snapshot() compute."""
        import hashlib

        h = hashlib.sha256()
        for name in cls._SNAPSHOT_PAYLOAD:
            with (sd / name).open("rb") as f:
                for chunk in iter(lambda: f.read(1 << 20), b""):
                    h.update(chunk)
            h.update(b"\x00")
        return h.hexdigest()

    def _snapshot_dir(self) -> Path:
        return self.data_dir / "snapshot"

    def _log_prefix_hash(self, offset: int) -> str:
        """sha256 of the last ≤4KB of failures.jsonl before ``offset`` —
        cheap integrity check that the log the snapshot covered is still
        the same log (purge-demo rewrites it, for instance)."""
        import hashlib

        start = max(0, offset - self._TAIL_HASH_BYTES)
        with self.failures_path.open("rb") as f:
            f.seek(start)
            return hashlib.sha256(f.read(offset - start)).hexdigest()

    def snapshot(self) -> Path:
        """Write an atomic point-in-time snapshot: slot-ordered embedding
        rows (no re-embed on restore) + pre-serialized records (no pydantic
        re-validate) + a manifest pinning the covered failures.jsonl byte
        range. Restore replays only the log tail written after it."""
        import shutil
        import tempfile

        # Capture a consistent view under the data lock: records list copy
        # (records are replaced, never mutated) + a device-side HBM copy of
        # the embedding buffer (fast). The slow parts — the multi-GB host
        # transfer and the disk write — run WITHOUT the data lock so a live
        # service's warn/ingest path doesn't stall. A separate snapshot lock
        # serializes concurrent snapshot() calls (endpoint + shutdown).
        if not self.persist:
            raise SnapshotError("snapshot requires a persistent GFKB (persist=True)")
        # Multi-host discipline: under multi-controller JAX, snapshot() is a
        # COLLECTIVE — the slot gather over the globally-sharded buffer
        # needs every process to run the same program, so every process
        # must call snapshot(), and every process writes to ITS OWN
        # data_dir. Symmetric writes are load-bearing, not redundancy: a
        # host that restored from a snapshot runs different insert programs
        # at startup than a host that full-replayed, which desynchronizes
        # the SPMD lockstep (observed as gloo size-mismatch aborts). The
        # deployment contract is per-host data dirs — a shared data_dir
        # across processes is already invalid (every host would
        # double-append the same log lines).
        with self._snapshot_write_lock:
            with self._lock:
                self._drain_pending_embeds()
                # Fold every pending delta attach into the union-find so
                # the persisted labels cover exactly the persisted rows —
                # a pending-at-snapshot edge would otherwise be lost on
                # restore (desynced labels, the thing v4 must never do).
                self._mine_drain_locked()
                self._flush_logs()
                records = list(self._records)
                n = len(records)
                mine_labels = None
                mine_threshold = None
                if (
                    self._mine is not None
                    and not self._mine.stale
                    and self._mine.n_rows == n
                ):
                    mine_labels = self._mine.labels()
                    mine_threshold = self._mine.threshold
                offset = self.failures_path.stat().st_size if self.failures_path.exists() else 0
                # Capture the knn alongside the buffer: a concurrent growth
                # re-shard swaps self._knn and would decode emb_copy's
                # layout with the wrong rows_per_shard.
                knn = self._knn
                emb_copy = knn.device_copy(self._emb)
                log_hash = self._log_prefix_hash(offset) if offset else ""
                generation = self._generation
                hot_n = min(n, self._hot_cap())
                router_state = (
                    self._tiers.export_router_state()
                    if self._tiers is not None else None
                )

            vecs = knn.gather_slots(emb_copy, np.arange(hot_n, dtype=np.int32))
            del emb_copy
            # Persist SPARSE (idx, val) pairs, not the dense matrix:
            # hashed-ngram rows are ~98% zeros, so the snapshot shrinks
            # ~16× (0.5 GB vs 8 GB at 1M×2048) — at 1M rows the dense
            # write/read dominated restore AND its writeback stalled the
            # first post-snapshot restore on slow disks (measured r5:
            # 253 s restore right after a dense snapshot vs 120 s
            # isolated). Restore feeds these pairs straight to the device
            # scatter with no re-sparsify pass.
            sp_idx, sp_val = dense_rows_to_sparse(vecs, knn.dim)
            del vecs
            if n > hot_n:
                # Overflow rows never touched the device: their sparse
                # pairs come straight from the host tiers (warm RAM or
                # cold shards), padded to a common row width.
                o_idx, o_val = self._tiers._rows_block(
                    np.arange(hot_n, n, dtype=np.int64)
                )
                kk = max(sp_idx.shape[1], o_idx.shape[1])

                def _pad(a, fill, dtype):
                    out = np.full((a.shape[0], kk), fill, dtype)
                    out[:, : a.shape[1]] = a
                    return out

                sp_idx = np.concatenate(
                    [_pad(sp_idx, knn.dim, np.int32), _pad(o_idx, knn.dim, np.int32)]
                )
                sp_val = np.concatenate(
                    [_pad(sp_val, 0.0, np.float32), _pad(o_val, 0.0, np.float32)]
                )
            sd = self._snapshot_dir()
            tmp = Path(tempfile.mkdtemp(dir=self.data_dir, prefix=".snapshot-"))
            old = self.data_dir / f".snapshot-old-{os.getpid()}-{id(tmp)}"
            try:
                np.save(tmp / "sparse_idx.npy", sp_idx)
                np.save(tmp / "sparse_val.npy", sp_val)
                with (tmp / "records.jsonl").open("w", encoding="utf-8") as f:
                    f.writelines(r.model_dump_json() + "\n" for r in records)
                # Chaos site: a snapshot-write failure here exercises the
                # except path below — tmp is removed and the previous
                # snapshot (if any) stays installed.
                self._fault_snapshot.fire()
                manifest = {
                    "version": self._SNAPSHOT_VERSION,
                    "n": n,
                    "dim": knn.dim,
                    "log_offset": offset,
                    "log_hash": log_hash,
                    # Content checksum: restore verifies it and
                    # degrades to full replay on any mismatch.
                    "checksum": self._snapshot_checksum(tmp),
                    # Compaction posture survives snapshot rewrites — the
                    # generation fence (compact()) bumps it via its own
                    # manifest rewrite.
                    "compact": {
                        "generation": self._compact_generation,
                        "ts": self._last_compact_ts,
                    },
                }
                if mine_labels is not None:
                    import hashlib

                    np.save(tmp / "clusters.npy", mine_labels.astype(np.int32))
                    manifest["mine"] = {
                        "n": n,
                        "threshold": mine_threshold,
                        # Own checksum (not part of the main payload
                        # tuple): a rotted cluster file costs one full
                        # re-mine, not a full log replay.
                        "checksum": hashlib.sha256(
                            (tmp / "clusters.npy").read_bytes()
                        ).hexdigest(),
                    }
                if router_state is not None:
                    import hashlib

                    cent, assign = router_state
                    np.save(tmp / "centroids.npy", cent.astype(np.float32))
                    np.save(tmp / "tier_assign.npy", assign.astype(np.int32))
                    h = hashlib.sha256((tmp / "centroids.npy").read_bytes())
                    h.update((tmp / "tier_assign.npy").read_bytes())
                    manifest["tiers"] = {
                        "n": n,
                        "hot": hot_n,
                        # Own checksum: a rotted router file costs one
                        # router rebuild from the restored rows, not a
                        # full log replay (routing is derived state).
                        "checksum": h.hexdigest(),
                    }
                (tmp / "manifest.json").write_text(json.dumps(manifest))
                # Swap via renames under the data lock: serialized with
                # reload(), and a crash mid-swap leaves at worst no snapshot
                # (full replay fallback), never a half-written one.
                with self._lock:
                    if self._generation != generation:
                        raise SnapshotError(
                            "GFKB was reloaded during snapshot; snapshot aborted — retry"
                        )
                    if sd.exists():
                        sd.rename(old)
                    tmp.rename(sd)
                shutil.rmtree(old, ignore_errors=True)
            except BaseException:
                shutil.rmtree(tmp, ignore_errors=True)
                if old.exists() and not sd.exists():
                    old.rename(sd)  # restore the previous snapshot
                raise
            return sd

    def _restore_snapshot(self) -> int:
        """Load a valid snapshot; returns the failures.jsonl byte offset to
        replay from (0 = no usable snapshot, full replay)."""
        sd = self._snapshot_dir()
        manifest_path = sd / "manifest.json"
        if not manifest_path.exists():
            return 0
        try:
            manifest = json.loads(manifest_path.read_text())
            if manifest.get("version") != self._SNAPSHOT_VERSION:
                return 0
            if manifest.get("dim") != self._knn.dim:
                return 0
            offset = int(manifest.get("log_offset", 0))
            size = self.failures_path.stat().st_size if self.failures_path.exists() else 0
            if size < offset:
                return 0  # log truncated/rewritten since the snapshot
            if offset and self._log_prefix_hash(offset) != manifest.get("log_hash"):
                return 0  # log rewritten in place (e.g. purge) — full replay
            if self._snapshot_checksum(sd) != manifest.get("checksum"):
                # Payload doesn't match what snapshot() wrote (bit rot,
                # partial copy, hand edits): restoring would install
                # garbage vectors the warn path then trusts — degrade to
                # full replay from the append log instead.
                log.warning(
                    "snapshot at %s fails its content checksum; ignoring it "
                    "and replaying the full log", sd,
                )
                return 0
            cm = manifest.get("compact") or {}
            self._compact_generation = int(cm.get("generation", 0))
            self._last_compact_ts = float(cm.get("ts", 0.0) or 0.0)
            n = int(manifest["n"])
            records = []
            with (sd / "records.jsonl").open("r", encoding="utf-8") as f:
                for line in f:
                    if line.strip():
                        # our own snapshot — construct without re-validation
                        records.append(
                            CanonicalFailureRecord.model_construct(
                                **_record_from_snapshot(json.loads(line))
                            )
                        )
            if len(records) != n:
                return 0
            sp_idx = np.load(sd / "sparse_idx.npy")
            sp_val = np.load(sd / "sparse_val.npy")
            if (
                sp_idx.shape != sp_val.shape
                or sp_idx.shape[0] != n
                or sp_idx.dtype != np.int32
                or sp_val.dtype != np.float32
            ):
                return 0
        except Exception:  # noqa: BLE001 — any corruption ⇒ full replay
            return 0
        # Grow the index BEFORE installing the records: _ensure_capacity
        # re-embeds from self._records on growth, which would re-do exactly
        # the work the snapshot vectors exist to skip.
        self._ensure_capacity(n)
        self._records = records
        self._slot_by_key = {
            (r.failure_type, r.signature_text): i for i, r in enumerate(records)
        }
        self._slot_by_id = {r.failure_id: i for i, r in enumerate(records)}
        for r in records:
            self._ids_by_type.setdefault(r.failure_type, []).append(r.failure_id)
            self._apps_by_type.setdefault(r.failure_type, set()).update(r.affected_apps)
        if n:
            tids = np.asarray([self._type_id(r.failure_type) for r in records], np.int32)
            # route=False: the router's persisted partition (or a rebuild)
            # installs after the rows, instead of re-assigning online.
            self._bulk_insert_chunked(
                lambda i, j: (sp_idx[i:j], sp_val[i:j]),
                np.arange(n, dtype=np.int32),
                tids,
                route=False,
            )
        self._mine_restore(sd, manifest)
        self._restore_tiers(sd, manifest)
        return offset

    def _restore_tiers(self, sd: Path, manifest: dict) -> None:
        """Install the snapshot's IVF router state. NEVER trusts an
        unverified partition: a missing section, checksum mismatch or
        shape error degrades to ONE router rebuild from the restored rows
        — routing is derived state; it must not force a full log replay
        and must not silently misroute."""
        t = self._tiers
        if t is None or t.router is None:
            return
        try:
            mf = manifest.get("tiers")
            if not mf:
                raise ValueError("snapshot carries no tier state")
            import hashlib

            h = hashlib.sha256((sd / "centroids.npy").read_bytes())
            h.update((sd / "tier_assign.npy").read_bytes())
            if h.hexdigest() != mf.get("checksum"):
                raise ValueError("tier-state checksum mismatch")
            cent = np.load(sd / "centroids.npy")
            assign = np.load(sd / "tier_assign.npy")
            if len(assign) != len(self._records) or int(mf.get("n", -1)) != len(assign):
                raise ValueError("tier-state shape mismatch")
            t.restore_router_state(cent, assign)
        except Exception as e:  # noqa: BLE001 — degrade, never desync
            log.warning(
                "tier-router restore failed (%s: %s); rebuilding the "
                "coarse partition from the restored rows",
                type(e).__name__, e,
            )
            t.rebuild_router()

    def _mine_restore(self, sd: Path, manifest: dict) -> None:
        """Seed the incremental cluster state from a snapshot's labels.
        NEVER installs unverified labels: any missing/mismatched field,
        checksum failure or injected fault leaves the state stale, which
        costs exactly one full re-mine on the next mine_patterns call."""
        m = self._mine
        if m is None:
            return
        try:
            self._fault_mine.fire()
            mf = manifest.get("mine")
            if not mf:
                m.mark_stale("snapshot carries no cluster state")
                return
            import hashlib

            raw = (sd / "clusters.npy").read_bytes()
            if hashlib.sha256(raw).hexdigest() != mf.get("checksum"):
                raise ValueError("cluster-state checksum mismatch")
            import io

            labels = np.load(io.BytesIO(raw))
            if (
                labels.shape != (len(self._records),)
                or labels.dtype != np.int32
                or int(mf.get("n", -1)) != len(self._records)
            ):
                raise ValueError("cluster-state shape mismatch")
            if float(mf.get("threshold", -1.0)) != m.threshold:
                # Config changed since the snapshot: labels were built for
                # a different graph — full re-mine, don't reinterpret.
                m.mark_stale("snapshot threshold differs from configured")
                return
            m.seed(
                labels,
                [(r.failure_type, r.failure_id, r.affected_apps) for r in self._records],
            )
        except Exception as e:  # noqa: BLE001 — degrade, never desync
            log.warning(
                "cluster-state restore failed (%s: %s); first mine will run "
                "a full sweep", type(e).__name__, e,
            )
            m.mark_stale(f"restore failed: {type(e).__name__}")

    def _bulk_insert_chunked(
        self, sparsify, slots: np.ndarray, tids: np.ndarray, route: bool = True
    ) -> None:
        """Bulk insert in bounded 64k chunks: insert inputs are replicated
        on every device, so a million-row restore in one call would put the
        whole matrix on each chip. ``sparsify(i, j)`` yields the (idx, val)
        pair for rows [i, j) — rows always ship sparse (hashed-ngram
        embeddings are ~98% zeros; at 1M rows that is ~250 MB over the wire
        instead of 8 GB). Slots past the hot cap land in the host tiers
        only — the device never grows past its row budget."""
        chunk = 1 << 16
        hot = self._hot_cap()
        for i in range(0, len(slots), chunk):
            j = min(i + chunk, len(slots))
            sp_i, sp_v = sparsify(i, j)
            self._store_tier_rows(slots[i:j], sp_i, sp_v, route=route)
            dev = slots[i:j] < hot
            if dev.any():
                self._emb, self._valid, self._types = self._knn.insert_sparse(
                    self._emb, self._valid, self._types,
                    sp_i[dev], sp_v[dev], slots[i:j][dev], tids[i:j][dev],
                )

    def _insert_texts_chunked(self, texts: List[str], slots: np.ndarray, tids: np.ndarray) -> None:
        """Signature texts (replay/rebuild): encode sparse per chunk — no
        dense host matrix ever materializes."""
        self._bulk_insert_chunked(
            lambda i, j: self.featurizer.encode_batch_sparse(texts[i:j]), slots, tids
        )

    def reload(self) -> None:
        """Drop all in-memory/device state and replay the append logs.

        Required after any external rewrite of the JSONL files (e.g. the
        dashboard's purge-demo flow) so the device index, id minting and
        host metadata stay consistent with the log. Any existing snapshot
        describes the pre-rewrite state and is deleted; an in-flight
        snapshot sees the generation bump at its swap step and aborts
        (reload deliberately does NOT take the snapshot-write lock — a
        purge must not stall behind a multi-GB snapshot disk write).
        """
        import shutil

        with self._lock:
            self._generation += 1
            shutil.rmtree(self._snapshot_dir(), ignore_errors=True)
            # Reopen the append logs: an external rewrite may have replaced
            # the files (new inode), and a held fd would append to the old
            # one. _lock is already held here — close() would deadlock.
            self._close_locked()
            self._emb, self._valid = self._knn.alloc()
            self._types = self._knn.alloc_i32()
            self._type_ids = {}
            self._records = []
            self._slot_by_key = {}
            self._slot_by_id = {}
            self._pattern_state = {}
            self._ids_by_type = {}
            self._apps_by_type = {}
            self._tombstoned = {}
            # The rewrite replaced the files; any torn-tail truncation
            # scheduled against the OLD files must not fire on the new ones.
            self._truncate_pending = {}
            # Host tiers describe pre-rewrite slots (including any cold
            # shards on disk) — drop them with everything else.
            if self._tiers is not None:
                self._tiers.reset()
            if self._mine is not None:
                from kakveda_tpu.ops.incremental import ClusterState

                self._mine = ClusterState(
                    threshold=self._mine.threshold, k=self._mine.k
                )
            self._mine_pending.clear()
            self._match_cache.clear()
            self._mine_merges_seen = 0
            if self.persist:
                self._replay()
            self._mine_after_replay()
            self._publish()

    def _mine_after_replay(self) -> None:
        """Post-replay invariant: the cluster state must cover exactly the
        replayed rows or be stale. A snapshot restore seeds it; a full log
        replay (or a log tail with rows the snapshot never saw) leaves a
        gap that only a full re-mine can close."""
        m = self._mine
        if m is None:
            return
        if len(self._records) and m.n_rows != len(self._records):
            m.mark_stale("replayed rows not covered by restored cluster state")
        nc = m.n_clusters_cached()
        if nc is not None:
            self._m_mine_clusters.set(nc)

    # ------------------------------------------------------------------
    # failures
    # ------------------------------------------------------------------

    @property
    def count(self) -> int:
        return len(self._records)

    def list_failures(self) -> List[CanonicalFailureRecord]:
        with self._lock:
            return list(self._records)

    def list_failures_page(
        self, offset: int = 0, limit: int = 50, newest_first: bool = True
    ) -> List[CanonicalFailureRecord]:
        """A page of records without copying the whole list — dashboard
        views at 1M records must not pay O(N) per page render."""
        with self._lock:
            n = len(self._records)
            if newest_first:
                hi = max(0, n - offset)
                lo = max(0, hi - limit)
                return self._records[lo:hi][::-1]
            return self._records[offset : offset + limit]

    def get_failure(self, failure_id: str) -> Optional[CanonicalFailureRecord]:
        """O(1) id lookup via the maintained id→slot map."""
        with self._lock:
            slot = self._slot_by_id.get(failure_id)
            return self._records[slot] if slot is not None else None

    def all_apps(self) -> List[str]:
        """Sorted union of affected apps — maintained incrementally so the
        dashboard's app dropdowns never scan the record list."""
        with self._lock:
            out: set = set()
            for apps in self._apps_by_type.values():
                out |= apps
            return sorted(out)

    def records_and_embeddings(self) -> Tuple[List[CanonicalFailureRecord], np.ndarray]:
        """Consistent (records, slot-aligned embedding rows) pair — captured
        atomically so a concurrent reload() (purge) can't misalign row i
        with records[i]. The slow host transfer happens after the lock via a
        device-side buffer copy."""
        with self._lock:
            self._drain_pending_embeds()
            records = list(self._records)
            knn = self._knn  # growth re-shard swaps the knn; pair it with the buffer
            emb_copy = knn.device_copy(self._emb)
            hot_n = min(len(records), self._hot_cap())
        vecs = knn.gather_slots(emb_copy, np.arange(hot_n, dtype=np.int32))
        if len(records) > hot_n:
            # Overflow rows densify from the host tiers (the device never
            # held them). Callers of this API (full-sweep mining, audits)
            # already accept O(N·dim) host memory.
            o_idx, o_val = self._tiers._rows_block(
                np.arange(hot_n, len(records), dtype=np.int64)
            )
            dense = np.zeros((len(records) - hot_n, knn.dim + 1), np.float32)
            rows = np.broadcast_to(
                np.arange(dense.shape[0])[:, None], o_idx.shape
            )
            np.add.at(dense, (rows, np.minimum(o_idx, knn.dim)), o_val)
            vecs = np.concatenate([vecs, dense[:, : knn.dim]])
        return records, vecs

    def type_aggregate(self, failure_type: str) -> Tuple[List[str], List[str]]:
        """(failure_ids in insertion order, sorted affected apps) for a type
        — maintained incrementally so per-batch pattern detection never
        rescans the record list."""
        with self._lock:
            return (
                list(self._ids_by_type.get(failure_type, [])),
                sorted(self._apps_by_type.get(failure_type, set())),
            )

    def _publish(self) -> None:
        """Swap the lock-free read view (call with the data lock held, or
        single-threaded during init)."""
        self._view = (self._knn, self._emb, self._valid, self._types, self._records)

    def _type_id(self, failure_type: str) -> int:
        """Dense id for a failure type (assigns on first sight; callers hold
        the data lock when creating records)."""
        tid = self._type_ids.get(failure_type)
        if tid is None:
            tid = self._type_ids[failure_type] = len(self._type_ids)
        return tid

    def _build_index(self, new_cap: int, records: Sequence[CanonicalFailureRecord]):
        """Allocate a capacity-``new_cap`` index populated with ``records``
        (re-embed + type scatter). Pure construction — no shared state."""
        knn = ShardedKnn(self.mesh, new_cap, self._knn.dim, k=self.top_k)
        emb, valid = knn.alloc()
        types = knn.alloc_i32()
        if records:
            chunk = 1 << 16
            # _type_id MINTS unseen ids — replay reaches here before any
            # upsert has registered the types (raw dict access crashed a
            # reopen whose log had outgrown the configured capacity).
            tids = np.asarray([self._type_id(r.failure_type) for r in records], np.int32)
            for i in range(0, len(records), chunk):
                batch = records[i : i + chunk]
                sp_i, sp_v = self.featurizer.encode_batch_sparse(
                    [r.signature_text for r in batch]
                )
                slots = np.arange(i, i + len(batch), dtype=np.int32)
                emb, valid, types = knn.insert_sparse(
                    emb, valid, types, sp_i, sp_v, slots, tids[i : i + chunk]
                )
        return knn, emb, valid, types

    def _ensure_capacity(self, needed: int) -> None:
        """Init-time growth (replay/restore run single-threaded). The
        device only ever grows to the hot cap; overflow is the tiers'."""
        needed = min(needed, self._hot_cap())
        if needed <= self._knn.capacity:
            return
        new_cap = self._knn.capacity
        while new_cap < needed:
            new_cap *= 2
        self._knn, self._emb, self._valid, self._types = self._build_index(
            new_cap, self._records[:needed]
        )
        self._publish()

    def _grow_and_reembed(self) -> None:
        """Runtime growth: an explicit re-shard event. The expensive work —
        re-embedding every record and building the doubled index — runs
        WITHOUT the data lock so concurrent matches and ingests aren't
        stalled behind it; the swap re-checks under the lock and retries if
        a reload or competing growth won the race. Rows appended while the
        rebuild ran are delta-scattered at swap time. Growth stops at the
        hot cap — rows past it are host-tier only, by design."""
        while True:
            with self._lock:
                hot = self._hot_cap()
                needed = min(len(self._records), hot)
                if needed <= self._knn.capacity:
                    return
                records = list(self._records[:hot])
                old_knn = self._knn
                gen = self._generation
            new_cap = old_knn.capacity
            while new_cap < len(records):
                new_cap *= 2
            knn, emb, valid, types = self._build_index(new_cap, records)
            with self._lock:
                if self._generation != gen or self._knn is not old_knn:
                    continue  # reload or another growth swapped first; re-check
                hot_now = min(len(self._records), hot)
                if hot_now > new_cap:
                    continue  # appends outran the doubling; rebuild bigger
                if hot_now > len(records):
                    delta = self._records[len(records) : hot_now]
                    d_i, d_v = self.featurizer.encode_batch_sparse(
                        [r.signature_text for r in delta]
                    )
                    dslots = np.arange(len(records), hot_now, dtype=np.int32)
                    dtids = np.asarray(
                        [self._type_id(r.failure_type) for r in delta], np.int32
                    )
                    emb, valid, types = knn.insert_sparse(
                        emb, valid, types, d_i, d_v, dslots, dtids
                    )
                self._knn, self._emb, self._valid, self._types = knn, emb, valid, types
                self._publish()
                return

    def upsert_failure(
        self,
        *,
        failure_type: str,
        signature_text: str,
        app_id: str,
        impact_severity: Severity,
        context_signature: Optional[dict] = None,
        root_cause: Optional[str] = None,
        resolution: Optional[str] = None,
    ) -> Tuple[CanonicalFailureRecord, bool]:
        """Versioned upsert; returns (record, created).

        Identity is (failure_type, signature_text) — same as the reference's
        reverse scan (reference: services/gfkb/app.py:108-113). Updates bump
        version/occurrences, merge affected apps, and let root cause /
        resolution evolve; every write re-appends to the JSONL log.
        """
        with self._lock:
            key = (failure_type, signature_text)
            slot = self._slot_by_key.get(key)
            now = utcnow()
            gen = self._generation
            revived = False
            if slot is None:
                created = True
                rec = CanonicalFailureRecord(
                    failure_id=f"F-{len(self._records) + 1:04d}",
                    version=1,
                    created_at=now,
                    updated_at=now,
                    failure_type=failure_type,
                    root_cause=root_cause,
                    context_signature=context_signature or {},
                    impact_severity=impact_severity,
                    resolution=resolution,
                    occurrences=1,
                    affected_apps=[app_id],
                    signature_text=signature_text,
                )
                slot = len(self._records)
                tid = self._type_id(failure_type)
                self._records.append(rec)
                self._slot_by_key[key] = slot
                self._slot_by_id[rec.failure_id] = slot
                self._ids_by_type.setdefault(failure_type, []).append(rec.failure_id)
                self._apps_by_type.setdefault(failure_type, set()).add(app_id)
                if self._mine is not None and not self._mine.stale:
                    self._mine.add_row(slot, failure_type, rec.failure_id, [app_id])
            else:
                created = False
                old = self._records[slot]
                rec = old.model_copy(deep=True)
                rec.version += 1
                rec.updated_at = now
                rec.occurrences += 1
                if app_id not in rec.affected_apps:
                    rec.affected_apps.append(app_id)
                self._apps_by_type.setdefault(failure_type, set()).add(app_id)
                if self._mine is not None:
                    self._mine.note_apps(slot, [app_id])
                rec.root_cause = root_cause or rec.root_cause
                rec.resolution = resolution or rec.resolution
                rec.context_signature = context_signature or rec.context_signature
                self._records[slot] = rec
                if slot in self._tombstoned:
                    # Organic resurrection: the signature is live traffic
                    # again. Durable "live" line, then re-embed below —
                    # the device row was zeroed at tombstone time.
                    self._resurrect_locked(slot, rec)
                    tid = self._type_id(failure_type)
                    revived = True
                # Same signature text => identical embedding; an un-tombstoned
                # update needs no device write.
            need_embed = created or revived
            self._append_jsonl(self.failures_path, rec.model_dump(mode="json"))
            self._flush_logs()
            if need_embed:
                self._pending_embeds += 1
        if need_embed:
            self._embed_new_slots([slot], [signature_text], [tid], gen)
        return rec, created

    def _applied_note_locked(self, event_id: str) -> None:
        """Record an applied replication event id in the bounded dedup set
        (caller holds ``_lock``, or is single-threaded construction)."""
        self._applied_events[event_id] = True
        self._applied_events.move_to_end(event_id)
        while len(self._applied_events) > self._applied_max:
            self._applied_events.popitem(last=False)

    def apply_replication(self, rows: Sequence[dict], event_id: str) -> int:
        """Apply one bus-replicated ingest event (fleet fan-in) through the
        normal tiered insert path, idempotently by event id: at-least-once
        redelivery and DLQ replay of an already-applied event are no-ops.
        Returns the number of rows applied (0 on dedup)."""
        out = self.upsert_failures_batch(rows, event_id=event_id)
        if out:
            self._m_rep_applied.inc()
        else:
            self._m_rep_dedup.inc()
        return len(out)

    @staticmethod
    def shard_key_of(rec: CanonicalFailureRecord) -> str:
        """The ownership shard key of one record — the app that created it
        (``affected_apps[0]``, insertion-ordered), signature as fallback.
        Must agree with fleet.ownership.shard_key_of_row (placement and
        residency accounting read the same key)."""
        return rec.affected_apps[0] if rec.affected_apps else rec.signature_text

    def shard_key_counts(self) -> Dict[str, int]:
        """Resident rows per shard key — the per-range row counts behind
        /readyz's ownership section and `cli status`. O(N) on demand; at
        readiness-probe cadence that is noise next to a device match."""
        out: Dict[str, int] = {}
        with self._lock:
            for slot, rec in enumerate(self._records):
                if slot in self._tombstoned:
                    continue  # retired rows are not placement-relevant residency
                k = self.shard_key_of(rec)
                out[k] = out.get(k, 0) + 1
        return out

    def export_rows(self, since: int = 0) -> Tuple[List[dict], int]:
        """Snapshot the record range ``[since, count)`` as replication-shaped
        row dicts, plus the count watermark at export time.

        This is the range-migration export surface (fleet/ownership.py):
        the first call ships the snapshot, a second call with the returned
        watermark drains the delta appended during the ship. Rows carry the
        full ``affected_apps`` so the receiving upsert reconstructs the
        record's app span, and re-encode their signature on apply — the
        hashed-ngram featurizer is deterministic, so the receiver's vectors
        are identical to the source's. Slots only ever append (updates stay
        in place), so a slot range IS a consistent delta cursor.
        Tombstoned rows are excluded — a migration must not re-materialize
        a row the lifecycle retired (the receiver would serve it)."""
        with self._lock:
            recs = [
                r
                for i, r in enumerate(self._records[since:], start=since)
                if i not in self._tombstoned
            ]
            count = len(self._records)
        rows = [
            {
                "failure_type": rec.failure_type,
                "root_cause": rec.root_cause,
                "context_signature": dict(rec.context_signature or {}),
                "impact_severity": rec.impact_severity.value
                if hasattr(rec.impact_severity, "value") else rec.impact_severity,
                "resolution": rec.resolution,
                "signature_text": rec.signature_text,
                "app_id": self.shard_key_of(rec),
                "affected_apps": list(rec.affected_apps),
            }
            for rec in recs
        ]
        return rows, count

    def upsert_failures_batch(
        self, items: Sequence[dict], event_id: Optional[str] = None
    ) -> List[Tuple[CanonicalFailureRecord, bool]]:
        """Batched upsert for the streaming-ingest path.

        New signatures are embedded in one ``encode_batch`` and written to the
        device in one scatter — the 10k traces/sec path.

        ``event_id`` (replication apply): when set and already applied, the
        whole batch is a dedup no-op; otherwise the id is appended to its
        own log AFTER the row lines, so a crash between the two replays the
        rows on redelivery (an occurrence bump) rather than losing them.
        """
        # Ledger attribution: embed/scatter compiles and uploads land on
        # the ingest entry/phase.
        with _ledger.entry("ingest"), _ledger.phase("ingest"):
            out = self._upsert_failures_batch(items, event_id)
        # Size/age compaction trigger rides the ingest cadence (background
        # thread — the batch never waits on a checkpoint write).
        self._maybe_auto_compact()
        return out

    def _upsert_failures_batch(
        self, items: Sequence[dict], event_id: Optional[str] = None
    ) -> List[Tuple[CanonicalFailureRecord, bool]]:
        out: List[Tuple[CanonicalFailureRecord, bool]] = []
        new_slots: List[int] = []
        new_texts: List[str] = []
        new_tids: List[int] = []
        with self._lock:
            if event_id is not None and event_id in self._applied_events:
                return []
            gen = self._generation
            now = utcnow()
            for item in items:
                key = (item["failure_type"], item["signature_text"])
                slot = self._slot_by_key.get(key)
                if slot is None:
                    # model_construct: inputs are classifier-built and typed;
                    # skipping validation keeps batch inserts off the pydantic
                    # hot loop (single-record upsert_failure keeps validating).
                    rec = CanonicalFailureRecord.model_construct(
                        failure_id=f"F-{len(self._records) + 1:04d}",
                        version=1,
                        created_at=now,
                        updated_at=now,
                        failure_type=item["failure_type"],
                        root_cause=item.get("root_cause"),
                        context_signature=item.get("context_signature") or {},
                        impact_severity=Severity(item["impact_severity"]),
                        resolution=item.get("resolution"),
                        occurrences=1,
                        # Migration-shipped rows carry the source record's
                        # full app list; ingest rows just their own app.
                        affected_apps=list(item.get("affected_apps") or [item["app_id"]]),
                        signature_text=item["signature_text"],
                    )
                    slot = len(self._records)
                    self._records.append(rec)
                    self._slot_by_key[key] = slot
                    self._slot_by_id[rec.failure_id] = slot
                    self._ids_by_type.setdefault(rec.failure_type, []).append(rec.failure_id)
                    self._apps_by_type.setdefault(rec.failure_type, set()).add(item["app_id"])
                    if self._mine is not None and not self._mine.stale:
                        self._mine.add_row(
                            slot, rec.failure_type, rec.failure_id,
                            list(rec.affected_apps),
                        )
                    new_slots.append(slot)
                    new_texts.append(rec.signature_text)
                    new_tids.append(self._type_id(rec.failure_type))
                    out.append((rec, True))
                else:
                    if event_id is not None and slot in self._tombstoned:
                        # Lifecycle fence: a replicated event (at-least-once
                        # redelivery, DLQ replay) re-carrying a tombstoned
                        # row drops it cleanly — same 2xx-drop shape as the
                        # stale-epoch ownership fence (docs/scale-out.md).
                        # Only an ORGANIC upsert resurrects.
                        self._m_rep_fenced.inc()
                        continue
                    old = self._records[slot]
                    rec = old.model_copy(deep=True)
                    rec.version += 1
                    rec.updated_at = now
                    rec.occurrences += 1
                    for app in item.get("affected_apps") or [item["app_id"]]:
                        if app not in rec.affected_apps:
                            rec.affected_apps.append(app)
                        self._apps_by_type.setdefault(rec.failure_type, set()).add(app)
                    if self._mine is not None:
                        self._mine.note_apps(
                            slot, item.get("affected_apps") or [item["app_id"]]
                        )
                    rec.root_cause = item.get("root_cause") or rec.root_cause
                    rec.resolution = item.get("resolution") or rec.resolution
                    rec.context_signature = item.get("context_signature") or rec.context_signature
                    self._records[slot] = rec
                    if slot in self._tombstoned:
                        # Organic resurrection: re-embed via the new-slot
                        # scatter below (the device row was zeroed).
                        self._resurrect_locked(slot, rec)
                        new_slots.append(slot)
                        new_texts.append(rec.signature_text)
                        new_tids.append(self._type_id(rec.failure_type))
                    out.append((rec, False))
                self._append_line(self.failures_path, rec.model_dump_json())
            if event_id is not None:
                self._applied_note_locked(event_id)
                self._append_line(self.applied_path, json.dumps({"id": event_id}))
            self._flush_logs()
            if new_slots:
                self._pending_embeds += 1
        if new_slots:
            self._embed_new_slots(new_slots, new_texts, new_tids, gen)
        return out

    def _embed_new_slots(
        self, slots: List[int], texts: List[str], tids: List[int], gen: int
    ) -> None:
        """Embed freshly appended records and scatter them into the index.

        Runs AFTER the metadata lock is released: the (expensive) host-side
        embedding never blocks matches or other ingests. Correctness under
        concurrency: slots are disjoint per caller, scatters are idempotent,
        and a growth that raced us re-embeds every record it captured plus a
        delta — so whichever order the swaps land, every slot ends up
        written. A reload (generation bump) makes the slots meaningless;
        replay already re-embedded everything from the log, so we skip.
        Callers incremented _pending_embeds under the append lock; the
        finally block releases snapshot()/records_and_embeddings() waiters."""
        try:
            # Sparse path: hashed-ngram rows are ~98% zeros; shipping (idx,
            # val) pairs instead of dense [B, dim] keeps streaming ingest off
            # the host→device wire bottleneck (the dense transfer dominated
            # the whole pipeline at 10k traces/sec rates).
            sp_idx, sp_val = self.featurizer.encode_batch_sparse(texts)
            arr_slots = np.asarray(slots, dtype=np.int32)
            arr_tids = np.asarray(tids, dtype=np.int32)
            with self._lock:
                if self._generation != gen:
                    return  # reloaded since append; replay covered these rows
                # Host tiers first: a device scatter that dies on a wedged
                # backend must still leave degraded-mode matching complete —
                # and slots past the hot cap live ONLY here.
                self._store_tier_rows(arr_slots, sp_idx, sp_val)
                hot = self._hot_cap()
                dev = arr_slots < hot
                need_growth = min(len(self._records), hot) > self._knn.capacity
                if not need_growth and dev.any():
                    with profiling.annotate("gfkb.insert"):
                        self._emb, self._valid, self._types = self._knn.insert_sparse(
                            self._emb, self._valid, self._types,
                            sp_idx[dev], sp_val[dev], arr_slots[dev], arr_tids[dev],
                        )
                    self._publish()
            if need_growth:
                # The rebuild re-embeds every hot record, these included.
                self._grow_and_reembed()
            self._mine_attach_new(slots, texts, sp_idx, sp_val, gen)
        finally:
            with self._lock:
                self._pending_embeds -= 1
                self._embeds_cv.notify_all()

    # ------------------------------------------------------------------
    # incremental mining state
    # ------------------------------------------------------------------

    def _mine_attach_new(self, slots, texts, sp_idx, sp_val, gen) -> None:
        """Queue attach-neighbors for freshly inserted rows.

        Rows whose signature a recent warn match already scored reuse
        those neighbors outright (zero device work); the rest share ONE
        delta top-k dispatch against the resident index — O(ΔN·N) per
        batch. The packed result's host copy starts immediately but is
        consumed lazily (mine_patterns drains it), so the ingest path
        never pays a device→host fetch RTT here. Any failure degrades the
        state to stale (one full re-mine) — mining is derived state and
        must never fail an ingest."""
        m = self._mine
        if m is None or m.stale:
            return
        try:
            self._fault_mine.fire()
            reused = []  # (slot, neigh_slots, sims)
            tier_attach = []  # overflow rows: neighbors from the host tiers
            delta_rows: List[int] = []
            hot = self._hot_cap()
            with self._lock:
                if self._generation != gen:
                    return
                for i, (s, t) in enumerate(zip(slots, texts)):
                    hit = self._match_cache.get(t)
                    if hit is not None and hit[2] == gen:
                        reused.append((s, hit[1], hit[0]))
                    else:
                        delta_rows.append(i)
                if delta_rows:
                    if sp_idx is None:
                        sub_texts = [texts[i] for i in delta_rows]
                        d_idx, d_val = self.featurizer.encode_batch_sparse(sub_texts)
                    else:
                        d_idx = sp_idx[delta_rows]
                        d_val = sp_val[delta_rows]
                    # Overflow rows aren't in the device index: their
                    # neighbors come from the host tiers' (routed) top-k
                    # instead of a device dispatch — same attach contract.
                    ovf = [
                        j for j, i in enumerate(delta_rows) if int(slots[i]) >= hot
                    ]
                    if ovf and self._tiers is not None:
                        # One batched host match for every overflow row —
                        # the candidate gather and (native) scoring run
                        # once per ingest batch, not once per row.
                        batch = self._tiers.match_host_batch(
                            d_idx[ovf], d_val[ovf], m.k + 1
                        )
                        for j, (nscores, nslots, _mode) in zip(ovf, batch):
                            tier_attach.append(
                                (int(slots[delta_rows[j]]), nslots, nscores)
                            )
                        keep = [j for j in range(len(delta_rows)) if j not in set(ovf)]
                        delta_rows = [delta_rows[j] for j in keep]
                        d_idx, d_val = d_idx[keep], d_val[keep]
                if delta_rows:
                    from kakveda_tpu.ops.incremental import delta_topk_sparse

                    # Dispatch under the data lock (PJRT buffer-hold rule,
                    # same as match_batch); +1 neighbor: each row's top-1
                    # against the post-insert index is itself.
                    with profiling.annotate("gfkb.mine.delta"):
                        packed = delta_topk_sparse(
                            self._emb, self._valid, d_idx, d_val, m.k + 1
                        )
                    self.mine_delta_dispatches += 1
                    self._mine_pending.append(
                        (
                            self._knn,
                            np.asarray([slots[i] for i in delta_rows], np.int32),
                            packed,
                            gen,
                        )
                    )
            for s, nslots, nsims in reused:
                m.attach(int(s), nslots, nsims)
                self._m_mine_attach["reused"].inc()
            for s, nslots, nsims in tier_attach:
                keep = np.isfinite(nsims) & (nsims >= m.threshold)
                m.attach(int(s), nslots[keep], nsims[keep])
                self._m_mine_attach["tier"].inc()
            if len(self._mine_pending) > self._mine_pending_max:
                with self._lock:
                    self._mine_drain_locked()
        except Exception as e:  # noqa: BLE001 — degrade, never fail ingest
            log.warning(
                "incremental mining attach failed (%s: %s); state marked "
                "stale — next mine_patterns runs a full sweep",
                type(e).__name__, e,
            )
            m.mark_stale(f"attach failed: {type(e).__name__}")
            with self._lock:
                self._mine_pending.clear()

    def _mine_drain_locked(self) -> int:
        """Fold every pending delta top-k result into the union-find
        (call with the data lock held). Packed buffers started their host
        copy at dispatch, so the fetch here is normally a no-wait read."""
        m = self._mine
        if m is None:
            return 0
        drained = 0
        while self._mine_pending:
            knn, d_slots, packed, gen = self._mine_pending.popleft()
            if gen != self._generation or m.stale:
                continue
            t0 = time.perf_counter()
            try:
                self._fault_mine.fire()
                from kakveda_tpu.ops.incremental import unpack_topk
                from kakveda_tpu.ops.knn import physical_to_slot

                sims, phys = unpack_topk(packed, len(d_slots))
                for row in range(len(d_slots)):
                    keep = np.isfinite(sims[row]) & (sims[row] >= m.threshold)
                    keep &= phys[row] < knn.capacity
                    p = phys[row][keep]
                    sl = (
                        p
                        if knn.single_device
                        else physical_to_slot(p, knn.n_shards, knn.rows_per_shard)
                    )
                    m.attach(int(d_slots[row]), sl, sims[row][keep])
                    self._m_mine_attach["delta"].inc()
                drained += len(d_slots)
            except Exception as e:  # noqa: BLE001 — degrade, never desync
                log.warning(
                    "incremental mining drain failed (%s: %s); state marked "
                    "stale — next mine_patterns runs a full sweep",
                    type(e).__name__, e,
                )
                m.mark_stale(f"drain failed: {type(e).__name__}")
                self._mine_pending.clear()
                break
            self._m_mine_update.observe(time.perf_counter() - t0)
        nc = m.n_clusters_cached()
        if nc is not None:
            self._m_mine_clusters.set(nc)
        return drained

    def mine_drain(self) -> int:
        """Public drain: apply pending incremental deltas, return the
        number of rows attached."""
        with self._lock:
            return self._mine_drain_locked()

    def mine_state_info(self) -> dict:
        """Freshness view of the incremental state (service/mine endpoint
        + tests): enabled flag, row/cluster/dirty counts, staleness and
        the pending (not yet drained) delta batches."""
        with self._lock:
            if self._mine is None:
                return {"enabled": False}
            info = self._mine.info()
            info.update(
                enabled=True,
                pending=len(self._mine_pending),
                covers_all_rows=self._mine.n_rows == len(self._records),
                delta_dispatches=self.mine_delta_dispatches,
            )
            return info

    def mine_pop_dirty(self) -> List[dict]:
        """Aggregate snapshots of clusters touched since the last call
        (drains pending deltas first so 'dirty' is current)."""
        with self._lock:
            self._mine_drain_locked()
            m = self._mine
            if m is None or m.stale:
                return []
            out = m.pop_dirty()
            self._m_mine_merges.inc(m.merges - self._mine_merges_seen)
            self._mine_merges_seen = m.merges
            nc = m.n_clusters_cached()
            if nc is not None:
                self._m_mine_clusters.set(nc)
            return out

    def mine_usable(self, threshold: float) -> bool:
        """Can mine_patterns serve this call incrementally? Requires the
        state to be enabled, non-stale, covering every record, and built
        for the same threshold (a different threshold is a different
        graph — full sweep)."""
        with self._lock:
            m = self._mine
            return (
                m is not None
                and not m.stale
                and m.n_rows == len(self._records)
                and m.threshold == float(threshold)
            )

    def mine_reseed(self, labels: np.ndarray, threshold: float, n_records: int) -> bool:
        """Install a full-sweep result as the new incremental baseline.
        ``n_records`` is the record count the sweep covered; rows appended
        during the sweep leave the state stale (the next sweep catches
        them) rather than silently uncovered."""
        with self._lock:
            m = self._mine
            if m is None:
                return False
            self._mine_pending.clear()
            if n_records != len(self._records) or len(labels) != n_records:
                m.mark_stale("records changed during the full sweep")
                return False
            m.seed(
                labels,
                [(r.failure_type, r.failure_id, r.affected_apps) for r in self._records],
                threshold=threshold,
            )
            nc = m.n_clusters_cached()
            if nc is not None:
                self._m_mine_clusters.set(nc)
            if self._tiers is not None:
                # A fresh full-sweep partition is the best coarse structure
                # available — re-seed the IVF router's centroids from it
                # (ops/incremental.py centroid export; failure keeps the
                # online partition, routing is derived state).
                self._tiers.reseed_router(labels)
            return True

    def _drain_pending_embeds(self) -> None:
        """Wait (holding the lock via the condition) until no appended
        record is still awaiting its embedding scatter. Call with the data
        lock held; may release and re-acquire it."""
        while self._pending_embeds > 0:
            self._embeds_cv.wait(timeout=30.0)

    # ------------------------------------------------------------------
    # lifecycle: row aging, duplicate collapse, log compaction
    # ------------------------------------------------------------------

    @staticmethod
    def _fsync_dir(path: Path) -> None:
        """fsync a directory so a just-completed rename is durable, not
        merely ordered — best-effort (not every platform supports it)."""
        try:
            fd = os.open(path, os.O_RDONLY)
            try:
                os.fsync(fd)
            finally:
                os.close(fd)
        except OSError:
            pass

    def _zero_device_rows_locked(self, slots) -> None:
        """Overwrite device rows with zeros (pad-only sparse rows — the
        scatter's SET semantics make that a clean row wipe) and un-type
        them (tid -1 matches no real type id), so a tombstoned row can
        neither score nor pass the type pre-filter. Warm/cold tier rows
        stay in place: the warm inverted index keeps postings for
        overwritten slots by design, so every host-path assembly filters
        tombstoned slots explicitly instead. Caller holds ``_lock`` (or
        is single-threaded init replay)."""
        arr = np.asarray(sorted(int(s) for s in slots), np.int32)
        arr = arr[arr < min(self._hot_cap(), self._knn.capacity)]
        if not len(arr):
            return
        sp_idx = np.full((len(arr), 1), self._knn.dim, np.int32)
        sp_val = np.zeros((len(arr), 1), np.float32)
        self._emb, self._valid, self._types = self._knn.insert_sparse(
            self._emb, self._valid, self._types,
            sp_idx, sp_val, arr, np.full(len(arr), -1, np.int32),
        )
        self._publish()

    def _tombstone_rows_locked(
        self, slots, reason: str, now: Optional[float] = None
    ) -> List[int]:
        """Durable "tomb" op line first, then the state flip, per slot —
        a crash between rows leaves every completed transition replayable
        and the rest simply not taken. Returns the slots actually
        tombstoned (already-tombstoned slots are skipped). Caller holds
        ``_lock`` and zeroes the device rows afterwards."""
        wrote: List[int] = []
        ts = now if now is not None else time.time()
        for slot in slots:
            slot = int(slot)
            if slot in self._tombstoned or not 0 <= slot < len(self._records):
                continue
            try:
                self._fault_tombstone.fire()
                self._append_jsonl(
                    self.tombstones_path,
                    {
                        "op": "tomb",
                        "id": self._records[slot].failure_id,
                        "reason": reason,
                        "ts": ts,
                    },
                )
            except (OSError, _faults.FaultInjected) as e:
                # Durable-before-visible: a transition that never hit disk
                # never happened — the row STAYS LIVE and the pass stops
                # (IO trouble is file-wide, not per-row). Aging/collapse
                # report fewer rows; nothing is half-tombstoned.
                log.warning(
                    "tombstone write failed after %d rows (%s: %s)",
                    len(wrote), type(e).__name__, e,
                )
                break
            self._tombstoned[slot] = reason
            self._m_tombstone[reason].inc()
            wrote.append(slot)
        if wrote:
            self._flush_logs()
            self._g_tombstoned.set(len(self._tombstoned))
        return wrote

    def _resurrect_locked(self, slot: int, rec: CanonicalFailureRecord) -> None:
        """Organic upsert over a tombstoned slot brings it back: durable
        "live" op line, state flip, metrics. Caller holds ``_lock`` and
        re-embeds the slot (its device row was zeroed at tombstone
        time)."""
        self._append_jsonl(
            self.tombstones_path,
            {"op": "live", "id": rec.failure_id, "ts": time.time()},
        )
        self._tombstoned.pop(slot, None)
        self._m_tombstone["resurrected"].inc()
        self._g_tombstoned.set(len(self._tombstoned))

    def age_rows(
        self, ttl_s: Optional[float] = None, now: Optional[float] = None
    ) -> dict:
        """TTL demotion — the terminal hop of hot→warm→cold→tombstone:
        retire every row whose last version write predates ``now - ttl_s``,
        EXCEPT slots in the cold tier's promote-LRU (recently paged in by
        live queries — touch evidence the record timestamps don't carry,
        index/tiers.py ``recently_promoted_slots``). Tombstoning is
        terminal-but-resident: slots, ids and keys stay stable (dense id
        minting, replay latest-wins and replication cursors depend on
        that); reclaiming LOG bytes is :meth:`compact`'s job. ``now`` is
        injectable so the month-compressed aging scenario and the recovery
        bench run without waiting out a real TTL."""
        if ttl_s is None:
            ttl_s = float(os.environ.get("KAKVEDA_GFKB_AGE_TTL_S", "0"))
        if ttl_s <= 0:
            return {"tombstoned": 0, "ttl_s": ttl_s}
        ts = now if now is not None else time.time()
        with self._lock:
            exempt = (
                self._tiers.recently_promoted_slots()
                if self._tiers is not None
                else set()
            )
            victims = [
                slot
                for slot, rec in enumerate(self._records)
                if slot not in self._tombstoned
                and slot not in exempt
                and ts - rec.updated_at.timestamp() > ttl_s
            ]
            wrote = self._tombstone_rows_locked(victims, "aged", now=ts)
            if wrote:
                self._zero_device_rows_locked(wrote)
        return {"tombstoned": len(wrote), "ttl_s": ttl_s, "exempt": len(exempt)}

    def collapse_duplicates(self, min_cluster: Optional[int] = None) -> dict:
        """Near-duplicate collapse over the incremental mining clusters:
        every cluster with ≥ ``min_cluster`` live members keeps ONE
        exemplar (the min live slot — the labels' own min-member
        convention), folds the victims' occurrence counts and app spans
        into it via a normal version-bump log line (replayable, no new
        record shape), and tombstones the victims. Mining is derived
        state: a stale or behind state means NO collapse this round —
        never collapse on unverified labels."""
        if min_cluster is None:
            min_cluster = int(os.environ.get("KAKVEDA_GFKB_DUP_COLLAPSE", "0"))
        out = {"collapsed": 0, "clusters": 0, "min_cluster": min_cluster}
        if min_cluster <= 1:
            return out
        from kakveda_tpu.ops.incremental import collapse_groups

        with self._lock:
            m = self._mine
            if m is None:
                out["reason"] = "incremental mining disabled"
                return out
            self._mine_drain_locked()
            if m.stale or m.n_rows != len(self._records):
                out["reason"] = "mine state stale or behind"
                return out
            now = utcnow()
            for exemplar, victims in collapse_groups(
                m.labels(), min_cluster, exclude=self._tombstoned
            ):
                ex = self._records[exemplar].model_copy(deep=True)
                ex.version += 1
                ex.updated_at = now
                for v in victims:
                    vr = self._records[v]
                    ex.occurrences += vr.occurrences
                    for app in vr.affected_apps:
                        if app not in ex.affected_apps:
                            ex.affected_apps.append(app)
                self._apps_by_type.setdefault(ex.failure_type, set()).update(
                    ex.affected_apps
                )
                m.note_apps(exemplar, list(ex.affected_apps))
                self._records[exemplar] = ex
                self._append_line(self.failures_path, ex.model_dump_json())
                wrote = self._tombstone_rows_locked(victims, "collapsed")
                if wrote:
                    self._zero_device_rows_locked(wrote)
                out["collapsed"] += len(wrote)
                out["clusters"] += 1
            self._flush_logs()
        return out

    def compact(self) -> dict:
        """Checkpoint+delta rewrite of the failures log.

        Takes a fresh snapshot (the checkpoint), rewrites failures.jsonl
        down to ONLY the bytes appended after it, and rewrites the
        tombstone side-log to net state — restart replay then parses the
        delta instead of the full version-append history. The swap is
        FENCED by the snapshot manifest: the manifest (log_offset=0,
        generation bump) swaps via temp+fsync+rename BEFORE the log does,
        so a crash at ANY byte leaves a (manifest, log) pair that replays
        to the pre- or post-compaction state, never a hybrid:

          * before the manifest swap — the old manifest still covers the
            old log at its recorded offset (pre-state);
          * between the two swaps — offset 0 replays the FULL old log
            over the snapshot; versioned upserts replay latest-wins in
            place, converging to the same records (post-state);
          * after the log swap — offset 0 replays exactly the delta
            (post-state).

        The patterns log is untouched (delta-append is already compact —
        lines carry only new members). ``KAKVEDA_GFKB_COMPACT=0`` refuses
        outright — the bit-for-bit append-only opt-out. A concurrent
        reload aborts via the snapshot generation check. Auto-trigger:
        ``KAKVEDA_GFKB_COMPACT_BYTES`` / ``KAKVEDA_GFKB_COMPACT_AGE_S``
        (checked post-ingest-batch, default off)."""
        if not self.persist:
            raise SnapshotError("compaction requires a persistent GFKB (persist=True)")
        if os.environ.get("KAKVEDA_GFKB_COMPACT", "1") == "0":
            self._m_compact[("failures", "skipped")].inc()
            return {"compacted": False, "reason": "KAKVEDA_GFKB_COMPACT=0"}
        stale = self.failures_path.with_suffix(".compact-tmp")
        if stale.exists():
            # A crash between the delta write and the log swap strands the
            # temp file; it is never valid input (whichever log is live at
            # failures.jsonl wins) — remove it before this attempt.
            try:
                stale.unlink()
                self._m_compact[("failures", "stale_tmp")].inc()
            except OSError as e:
                log.warning("stale %s could not be removed: %s", stale, e)
        with self._snapshot_write_lock:
            try:
                self.snapshot()
                out = self._compact_swap_locked()
            except SnapshotError:
                self._m_compact[("failures", "skipped")].inc()
                raise
            except (OSError, _faults.FaultInjected) as e:
                self._m_compact[("failures", "error")].inc()
                log.error("failures-log compaction failed: %s", e)
                raise
        self._m_compact[("failures", "ok")].inc()
        log.info(
            "compacted %s: %d -> %d bytes (generation %d)",
            self.failures_path, out["bytes_before"], out["bytes_after"],
            out["generation"],
        )
        return out

    def _compact_swap_locked(self) -> dict:
        """The fenced swap — caller holds the snapshot-write lock with the
        just-written snapshot installed; takes ``_lock`` for the swap so
        no append lands between the tail read and the log replace. Every
        file move is temp+fsync+rename inside data_dir."""
        with self._lock:
            sd = self._snapshot_dir()
            manifest_path = sd / "manifest.json"
            manifest = json.loads(manifest_path.read_text())
            offset = int(manifest.get("log_offset", 0))
            size = (
                self.failures_path.stat().st_size
                if self.failures_path.exists()
                else 0
            )
            with self.failures_path.open("rb") as f:
                f.seek(offset)
                tail = f.read()
            # A torn final line the last replay tolerated must not survive
            # into the new log (truncation is the contract, never leniency).
            pend = self._truncate_pending.get(self.failures_path)
            if pend is not None and pend >= offset:
                tail = tail[: pend - offset]
            tmp = self.failures_path.with_suffix(".compact-tmp")
            with tmp.open("wb") as f:
                f.write(tail)
                f.flush()
                os.fsync(f.fileno())
            self._fault_compact_delta.fire()
            gen = self._compact_generation + 1
            manifest["log_offset"] = 0
            manifest["log_hash"] = ""
            manifest["compact"] = {"generation": gen, "ts": time.time()}
            mtmp = sd / "manifest.json.tmp"
            with mtmp.open("w", encoding="utf-8") as f:
                f.write(json.dumps(manifest))
                f.flush()
                os.fsync(f.fileno())
            os.replace(mtmp, manifest_path)
            self._fsync_dir(sd)
            # THE FENCE: from here, replay starts at byte 0 of whichever
            # file is live at failures.jsonl — the full old log (latest-
            # wins convergence) or the delta below; both reach post-state.
            self._fault_compact_fence.fire()
            os.replace(tmp, self.failures_path)
            self._fsync_dir(self.data_dir)
            self._fault_compact_swap.fire()
            # The append handle points at the replaced inode — reopen; and
            # a torn-tail truncation scheduled against the old file must
            # not fire on the new one (the rewrite dropped the torn bytes).
            self._close_locked()
            self._truncate_pending.pop(self.failures_path, None)
            self._compact_generation = gen
            self._last_compact_ts = time.time()
            n_tomb = self._compact_tombstones_locked()
        return {
            "compacted": True,
            "generation": gen,
            "bytes_before": size,
            "bytes_after": len(tail),
            "checkpoint_rows": int(manifest.get("n", 0)),
            "tombstone_lines": n_tomb,
        }

    def _compact_tombstones_locked(self) -> int:
        """Rewrite the tombstone side-log to net state (one "tomb" line
        per currently tombstoned slot) through the same temp+fsync+rename
        seam. A crash mid-rewrite keeps the old log, which replays to the
        same net state. Returns the lines written."""
        if not self._tombstoned and not self.tombstones_path.exists():
            return 0
        lg = self._logs.pop(self.tombstones_path, None)
        if lg is not None:
            lg.close()
        tmp = self.tombstones_path.with_suffix(".compact-tmp")
        try:
            with tmp.open("w", encoding="utf-8") as f:
                for slot in sorted(self._tombstoned):
                    f.write(
                        json.dumps(
                            {
                                "op": "tomb",
                                "id": self._records[slot].failure_id,
                                "reason": self._tombstoned[slot],
                                "ts": self._last_compact_ts,
                            }
                        )
                        + "\n"
                    )
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, self.tombstones_path)
            self._truncate_pending.pop(self.tombstones_path, None)
            self._m_compact[("tombstones", "ok")].inc()
        except OSError as e:
            log.warning("tombstone-log compaction skipped: %s", e)
            tmp.unlink(missing_ok=True)
            self._m_compact[("tombstones", "error")].inc()
        return len(self._tombstoned)

    def _maybe_auto_compact(self) -> None:
        """Size/age compaction trigger (KAKVEDA_GFKB_COMPACT_BYTES /
        _AGE_S, 0 = off), checked after each ingest batch. The compaction
        runs on a daemon thread — ingest never waits on a checkpoint
        write; one inflight flag keeps it single-flight."""
        if not self.persist or self._compact_inflight:
            return
        if self._compact_bytes <= 0 and self._compact_age_s <= 0:
            return
        if os.environ.get("KAKVEDA_GFKB_COMPACT", "1") == "0":
            return
        try:
            size = self.failures_path.stat().st_size
        except OSError:
            return
        due = self._compact_bytes > 0 and size >= self._compact_bytes
        if not due and self._compact_age_s > 0 and size > 0:
            last = self._last_compact_ts or self._opened_ts
            due = (time.time() - last) >= self._compact_age_s
        if not due:
            return
        with self._lock:
            if self._compact_inflight:
                return
            self._compact_inflight = True

        def _run() -> None:
            try:
                self.compact()
            except Exception as e:  # noqa: BLE001 — never fail/abort ingest
                log.warning("auto-compaction failed (%s: %s)", type(e).__name__, e)
            finally:
                self._compact_inflight = False

        threading.Thread(
            target=_run, name="kakveda-gfkb-compact", daemon=True
        ).start()

    def lifecycle_info(self) -> dict:
        """Durability/lifecycle posture (cli status, tests): tombstone
        counts by reason, compaction generation/timestamp, current
        failures-log byte size."""
        with self._lock:
            by_reason: Dict[str, int] = {}
            for r in self._tombstoned.values():
                by_reason[r] = by_reason.get(r, 0) + 1
            size = 0
            if self.persist:
                try:
                    size = self.failures_path.stat().st_size
                except OSError:
                    size = 0
            return {
                "tombstoned": len(self._tombstoned),
                "by_reason": by_reason,
                "compact_generation": self._compact_generation,
                "last_compact_ts": self._last_compact_ts,
                "failures_log_bytes": size,
            }

    # ------------------------------------------------------------------
    # host tiers (degraded mode, overflow, restore — one hierarchy)
    # ------------------------------------------------------------------

    def _hot_cap(self) -> int:
        """Logical slots the device-hot tier may hold. Unbounded without
        the host tiers (KAKVEDA_HOST_FALLBACK=0 — nothing could absorb an
        overflow) or with tiering off (pre-tiered growth semantics)."""
        if self._tiers is None:
            return 1 << 62
        return self._tiers.cfg.hot_rows

    def _store_tier_rows(
        self, slots, sp_idx: np.ndarray, sp_val: np.ndarray, route: bool = True
    ) -> None:
        """Land freshly embedded rows in the host tiers (warm RAM, or the
        cold memmap past the warm budget) and feed the router's per-batch
        delta update. Rows land BEFORE the device scatter, so a scatter
        that dies on a wedged backend still leaves degraded-mode matching
        complete. ``route=False`` skips the router assignment (snapshot
        restore installs the persisted router state instead)."""
        if self._tiers is None:
            return
        self._tiers.insert(np.asarray(slots, np.int64), sp_idx, sp_val, route=route)

    def tiers_info(self) -> dict:
        """Tier residency/routing view (readyz + tests)."""
        if self._tiers is None:
            return {"enabled": False}
        info = self._tiers.info()
        info["enabled"] = True
        return info

    def match_batch_fallback(
        self,
        signature_texts: Sequence[str],
        failure_type: Optional[str] = None,
    ) -> Tuple[List[List[FailureMatch]], dict]:
        """Device-free top-k from the host tiers — the degraded-mode path
        (and the code overflow matching shares). Small corpora take the
        exact inverted-index walk (bit-for-bit the PR-5 fallback scores);
        past the routing floor the IVF router narrows each query to
        ``nprobe`` candidate lists with exact scoring over candidates. A
        routing fault degrades that query to the exact scan — slower,
        never wrong-but-confident. Returns ``(matches, info)`` where
        ``info`` carries the serving ``tier``/``nprobe`` for verdicts.
        ``failure_type`` keeps :meth:`match_batch`'s default
        post-truncation filter semantics."""
        if self._tiers is None:
            raise HostFallbackDisabled(
                "host fallback disabled (KAKVEDA_HOST_FALLBACK=0)"
            )
        q_idx, q_val = self.featurizer.encode_batch_sparse(list(signature_texts))
        with self._lock:
            records = list(self._records)
            tomb = set(self._tombstoned)
        n = len(records)
        if n == 0:
            return [[] for _ in signature_texts], {"tier": "warm", "nprobe": None}
        out: List[List[FailureMatch]] = []
        k = self.top_k
        routed = False
        # One batched host match: candidate dedup + the cold tier's
        # coalesced read plan + (native) scoring run once per warn batch.
        batch = self._tiers.match_host_batch(q_idx, q_val, max(k, 1))
        for scores, slots, mode in batch:
            routed = routed or mode == "routed"
            row: List[FailureMatch] = []
            for s, slot in zip(scores.tolist(), slots.tolist()):
                if s <= 0.0 or slot >= n or slot in tomb:
                    continue  # padding / tombstoned rows never surface
                rec = records[slot]
                if failure_type and rec.failure_type != failure_type:
                    continue
                row.append(
                    FailureMatch(
                        failure_id=rec.failure_id,
                        version=rec.version,
                        score=min(1.0, max(-1.0, float(s))),
                        failure_type=rec.failure_type,
                        suggested_mitigation=rec.resolution,
                    )
                )
            out.append(row)
        self._m_warn_fallback.inc(len(signature_texts))
        info = {
            "tier": "warm_routed" if routed else "warm",
            "nprobe": self._tiers.cfg.nprobe if routed else None,
        }
        return out, info

    # ------------------------------------------------------------------
    # match
    # ------------------------------------------------------------------

    def match(
        self,
        signature_text: str,
        failure_type: Optional[str] = None,
        type_filter: str = "post",
    ) -> List[FailureMatch]:
        return self.match_batch([signature_text], failure_type, type_filter)[0]

    def match_batch(
        self,
        signature_texts: Sequence[str],
        failure_type: Optional[str] = None,
        type_filter: str = "post",
    ) -> List[List[FailureMatch]]:
        return self.match_batch_info(signature_texts, failure_type, type_filter)[0]

    def match_batch_info(
        self,
        signature_texts: Sequence[str],
        failure_type: Optional[str] = None,
        type_filter: str = "post",
    ) -> Tuple[List[List[FailureMatch]], dict]:
        """Top-k similarity matches for a batch of queries (one device call),
        plus serving provenance (``tier``/``nprobe``) for verdicts.

        Slots within the hot cap are answered by the exact device scan;
        when the corpus has overflowed onto the host tiers, each query
        additionally gathers a routed (or exact-degraded) host top-k over
        the overflow slots and the two are merged by score — the device
        stays exact over what it holds, the tiers make the rest
        representable.

        ``type_filter``:
          * ``"post"`` (default) — reference-compatible: the type filter
            applies AFTER top-k truncation, so a filtered query can return
            < k matches even when more of that type exist (the reference's
            observable behavior, services/gfkb/app.py:89-91).
          * ``"pre"`` — device-side pre-selection: the per-slot type id is
            AND-ed into the valid mask BEFORE top-k, so the query returns k
            hits whenever ≥ k failures of that type exist.

        Concurrency design: the query embedding (host work) runs before the
        lock and the result fetch (one wire RTT on remote-attached TPUs —
        the dominant cost) runs after it; the lock covers only the async
        DISPATCH of the top-k (microseconds). Dispatches must be serialized
        with mutators because inserts donate the index buffers and PJRT's
        buffer-hold bookkeeping is not safe against a concurrent reader
        dispatch; once dispatched, execution ordering protects the read.
        Warn latency therefore no longer serializes behind ingest's
        embedding work, capacity-growth re-embeds (both off-lock now), or
        other matches' result fetches.
        """
        # Ledger attribution: any compile or transfer below lands on the
        # warn entry/phase (lambda jits inherit the ambient entry).
        with _ledger.entry("warn"), _ledger.phase("warn"):
            return self._match_batch_info(signature_texts, failure_type, type_filter)

    def _match_batch_info(
        self,
        signature_texts: Sequence[str],
        failure_type: Optional[str] = None,
        type_filter: str = "post",
    ) -> Tuple[List[List[FailureMatch]], dict]:
        # Sparse query form: (idx, val) pairs ship ~60× fewer bytes per
        # pre-flight check than dense rows; the device densifies before the
        # same top-k (identical scores). topk_async_sparse buckets ragged
        # batches internally.
        q_idx, q_val = self.featurizer.encode_batch_sparse(list(signature_texts))
        b = q_idx.shape[0]

        with self._lock:
            knn, emb, valid, types, records = self._view
            # Tombstone filter set: device rows are zeroed (score 0, never
            # outrank a real match) but can still occupy candidate
            # positions — the assembly drop below is what guarantees a
            # retired row never surfaces in a verdict.
            tomb = set(self._tombstoned) if self._tombstoned else ()
            n = len(records)
            if n == 0:
                return [[] for _ in signature_texts], {"tier": "hot", "nprobe": None}
            tid = None
            if type_filter == "pre" and failure_type is not None:
                tid = self._type_ids.get(failure_type)
                if tid is None:
                    return [[] for _ in signature_texts], {"tier": "hot", "nprobe": None}
            with profiling.annotate("gfkb.match.dispatch"):
                # Device-loss drill point: armed, the dispatch dies the way
                # a wedged backend does, and the warn path's degraded-mode
                # fallback (WarningPolicy → match_batch_fallback) takes over.
                self._fault_device.fire()
                if tid is not None:
                    valid = knn.mask_valid(valid, types, tid)
                packed = knn.topk_async_sparse(emb, valid, q_idx, q_val)
        with profiling.annotate("gfkb.match.fetch"):
            scores, slots = knn.topk_result(packed)

        info = {"tier": "hot", "nprobe": None}
        hot = self._hot_cap()
        if n > hot and self._tiers is not None:
            # Overflow: merge the device's exact hot top-k with the host
            # tiers' (routed) top-k over slots the device doesn't hold.
            modes: set = set()
            m_scores, m_slots = [], []
            k = scores.shape[1]
            overflow = self._tiers.match_host_batch(q_idx, q_val, k, min_slot=hot)
            for i in range(b):
                o_s, o_sl, mode = overflow[i]
                modes.add(mode)
                if tid is not None and len(o_sl):
                    keep = np.asarray(
                        [records[int(s)].failure_type == failure_type for s in o_sl]
                    )
                    o_s, o_sl = o_s[keep], o_sl[keep]
                cs = np.concatenate([scores[i], o_s])
                csl = np.concatenate([slots[i], o_sl])
                order = np.argsort(-cs)[:k]
                m_scores.append(cs[order])
                m_slots.append(csl[order])
            scores = np.stack(m_scores)
            slots = np.stack(m_slots)
            if "fault_exact" in modes:
                info = {"tier": "tiered_fault", "nprobe": None}
            elif modes == {"routed"}:
                info = {"tier": "tiered", "nprobe": self._tiers.cfg.nprobe}
            else:
                info = {"tier": "tiered_exact", "nprobe": None}

        if self._mine is not None and self._match_cache_max > 0 and failure_type is None:
            # Remember the fetched neighbors per signature: a pre-flight
            # warn is usually followed by the SAME signature being
            # ingested when the trace fails, and these rows make its
            # cluster attachment free (no extra device dispatch).
            with self._lock:
                gen_now = self._generation
                for i in range(b):
                    self._match_cache[signature_texts[i]] = (
                        scores[i], slots[i], gen_now
                    )
                    self._match_cache.move_to_end(signature_texts[i])
                while len(self._match_cache) > self._match_cache_max:
                    self._match_cache.popitem(last=False)

        out: List[List[FailureMatch]] = []
        for i in range(b):
            row: List[FailureMatch] = []
            for s, slot in zip(scores[i], slots[i]):
                if s <= -1.0 or slot >= n or int(slot) in tomb:
                    continue  # padding / invalid / tombstoned rows
                rec = records[int(slot)]
                if failure_type and rec.failure_type != failure_type:
                    continue
                row.append(
                    FailureMatch(
                        failure_id=rec.failure_id,
                        version=rec.version,
                        # f32 accumulation can nudge an exact self-match a hair
                        # past 1.0; cosine is bounded, so clamp.
                        score=min(1.0, max(-1.0, float(s))),
                        failure_type=rec.failure_type,
                        suggested_mitigation=rec.resolution,
                    )
                )
            out.append(row)
        return out, info

    # ------------------------------------------------------------------
    # patterns
    # ------------------------------------------------------------------

    def _merge_pattern_line(self, p: PatternEntity) -> None:
        """Union one log line into the in-memory state (replay path). Works
        for both delta lines and legacy full-membership lines."""
        st = self._pattern_state.get(p.name)
        if st is None:
            self._pattern_state[p.name] = {
                "pattern_id": p.pattern_id,
                "name": p.name,
                "created_at": p.created_at,
                "fid_list": list(dict.fromkeys(p.failure_ids)),
                "fid_set": set(p.failure_ids),
                "app_list": list(dict.fromkeys(p.affected_apps)),
                "app_set": set(p.affected_apps),
                "description": p.description,
            }
            return
        for f in p.failure_ids:
            if f not in st["fid_set"]:
                st["fid_set"].add(f)
                st["fid_list"].append(f)
        for a in p.affected_apps:
            if a not in st["app_set"]:
                st["app_set"].add(a)
                st["app_list"].append(a)
        if p.description:
            st["description"] = p.description

    def _pattern_view(self, st: dict) -> PatternEntity:
        """Materialized read view. Lists are copied so callers can't mutate
        live state; membership order is insertion order (first-seen), not
        lexicographic — sorting N ids per upsert is exactly the O(N log N)
        per-batch cost the delta design removes."""
        return PatternEntity.model_construct(
            pattern_id=st["pattern_id"],
            name=st["name"],
            created_at=st["created_at"],
            failure_ids=list(st["fid_list"]),
            affected_apps=list(st["app_list"]),
            description=st["description"],
        )

    def list_patterns(self) -> List[PatternEntity]:
        """Latest state per pattern (dedup-for-presentation, like the
        reference's GET /patterns, services/gfkb/app.py:150-157)."""
        with self._lock:
            return [self._pattern_view(st) for st in self._pattern_state.values()]

    def upsert_pattern(
        self,
        *,
        name: str,
        failure_ids: Sequence[str],
        affected_apps: Sequence[str],
        description: Optional[str] = None,
    ) -> Tuple[PatternEntity, bool]:
        """Identity-by-name pattern upsert with set-union merge
        (reference: services/gfkb/app.py:168-198).

        Streaming-safe: the in-memory union is set-backed (O(delta) per
        call), only the *new* members are appended to the log, and a no-op
        upsert (nothing new) skips the append entirely."""
        with self._lock:
            st = self._pattern_state.get(name)
            created = st is None
            if created:
                st = {
                    "pattern_id": f"FP-{len(self._pattern_state) + 1:04d}",
                    "name": name,
                    "created_at": utcnow(),
                    "fid_list": [],
                    "fid_set": set(),
                    "app_list": [],
                    "app_set": set(),
                    "description": description,
                }
                self._pattern_state[name] = st
            new_f = [f for f in dict.fromkeys(failure_ids) if f not in st["fid_set"]]
            new_a = [a for a in dict.fromkeys(affected_apps) if a not in st["app_set"]]
            desc_changed = bool(description) and description != st["description"]
            if not created and not new_f and not new_a and not desc_changed:
                return self._pattern_view(st), False
            st["fid_list"].extend(new_f)
            st["fid_set"].update(new_f)
            st["app_list"].extend(new_a)
            st["app_set"].update(new_a)
            if description:
                st["description"] = description
            delta = PatternEntity.model_construct(
                pattern_id=st["pattern_id"],
                name=name,
                created_at=st["created_at"],
                failure_ids=new_f,
                affected_apps=new_a,
                description=st["description"],
            )
            self._append_line(self.patterns_path, delta.model_dump_json())
            self._flush_logs()
            return self._pattern_view(st), created
